#!/usr/bin/env python
"""Trace-driven fleet soak + QoS drill (ISSUE 11) — the first direct
evidence for the million-user north star.

Two phases against a real `ServingRouter` fleet of tiny-model engines
on ONE shared virtual clock (`paddle_tpu.loadgen`):

1. **Capacity.** Binary-search the open-loop arrival rate for the
   fleet's max sustainable QPS: the highest rate at which nothing is
   refused and the interactive lane's p95 TTFT meets the stated
   objective, on a seeded replayable trace (diurnal + burst arrivals,
   heavy-tailed lengths, tenant/lane mix).
2. **Overload.** Soak at `--overload` x that rate with the QoS
   admission controller ON (`serving/admission.py`): interactive vs
   batch priority lanes, sliding-window tenant budgets (the `free`
   tenant gets a deliberately tight one), and SLO-arbitrated shedding
   — the burn-rate engine decides WHEN to shed, lane/tenant ordering
   decides WHO.

The drill then GRADES the run (non-zero exit on failure):

* interactive p95 TTFT stays under the objective at overload,
* sheds are confined to the batch lane / over-budget tenants — an
  in-budget interactive session is never QoS-shed,
* `pdt_admission_*` counters reconcile EXACTLY with the router's
  terminal counters (committed admissions == terminal requests, with
  backpressure refusals booked separately; sheds == qos_shed
  rejections),
* the trace replays: the same seed regenerates the identical arrival
  sequence.

A third leg then kills the CONTROL PLANE (docs/serving.md
"Durability"): a write-ahead-journaled fleet takes the front half of
a sustainable-rate trace, the router dies mid-decode (SIGKILL-shaped
teardown), `ServingRouter.recover()` rehydrates a fresh incarnation
from the journal, the remaining arrivals land on it, and the drill
grades ZERO lost soak sessions + outputs identical to an unkilled
fleet, printing the `pdt_journal_*` Prometheus dump.

`--autoscale` adds a fourth leg (ISSUE 16, docs/serving.md
"Autoscaling"): the same diurnal trace replays twice — once against a
static peak-provisioned fleet, once against a journaled fleet scaled
from a 1-replica floor by `FleetAutoscaler` — and the drill grades
zero lost sessions, autoscaled p95 TTFT within the objective,
replica-step (chip-time) savings > 0, at least one grow AND one
shrink, and burst reaction time <= 2 virtual seconds.

`--multimodel` adds the consolidation leg (ISSUE 17, docs/serving.md
"Multi-model serving"): a per-tenant model mix (two LoRA fine-tunes
over the shared base) soaks ONE `model_affinity` fleet behind a
`FleetModelStore`, then each model's arrivals replay against a
DEDICATED single-model fleet of the same size. The drill grades zero
ADMITTED sessions lost (backpressure refusals are visible and
reconciled — the mix rides a different arrival realization than the
one phase 1 certified), mixed-fleet interactive p95 TTFT meeting the
same objective the dedicated baselines meet (latency parity at 1/N
the chips), and EXACT per-model terminal-counter reconciliation
(driver-side per-model outcomes == `fleet_info()["models"]` ==
`num_terminal_by_model`).

    python recipes/fleet_soak.py                   # search + 2x soak
    python recipes/fleet_soak.py --qps 6 --overload 3
    python recipes/fleet_soak.py --duration 120 --replicas 4  # heavier
`--profile` prints the performance-attribution report after the soak
(ISSUE 20, docs/observability.md "Performance attribution"):
decode-round waterfall, compile-cache table, memory ledger.

    python recipes/fleet_soak.py                   # search + 2x soak
    python recipes/fleet_soak.py --qps 6 --overload 3
    python recipes/fleet_soak.py --duration 120 --replicas 4  # heavier
    python recipes/fleet_soak.py --autoscale       # + the elastic leg
    python recipes/fleet_soak.py --multimodel      # + the model-mix leg
    python recipes/fleet_soak.py --profile         # + attribution
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Open-loop fleet soak + QoS admission drill")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--duration", type=float, default=30.0,
                   help="virtual seconds of trace per soak run")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--slots", type=int, default=2,
                   help="engine max_batch_size per replica")
    p.add_argument("--step-dt", type=float, default=0.05,
                   help="virtual wall seconds charged per fleet step")
    p.add_argument("--qps", type=float, default=0.0,
                   help="sustainable QPS to assume (0 = binary search)")
    p.add_argument("--overload", type=float, default=2.0,
                   help="overload factor over max sustainable QPS")
    p.add_argument("--ttft-objective", type=float, default=0.5,
                   help="interactive p95 TTFT objective, virtual s")
    p.add_argument("--free-budget", type=int, default=400,
                   help="sliding-window token budget for the 'free' "
                        "tenant (deliberately tight)")
    p.add_argument("--autoscale", action="store_true",
                   help="run the elastic-fleet leg: the same diurnal "
                        "trace against a STATIC peak-size fleet and an "
                        "AUTOSCALED one (journal-attached, min 1 .. max "
                        "--replicas), grading p95 TTFT parity, "
                        "replica-step savings, burst reaction time, "
                        "and zero lost sessions")
    p.add_argument("--multimodel", action="store_true",
                   help="run the multi-model leg: a per-tenant LoRA "
                        "model mix against ONE model_affinity fleet vs "
                        "per-model DEDICATED fleets, grading TTFT "
                        "parity and exact per-model terminal-counter "
                        "reconciliation")
    p.add_argument("--quant", action="store_true",
                   help="serve the whole fleet quantized (int8 weights"
                        " + int8 KV pages, QuantServingConfig) — the "
                        "soak grades the same objectives against the "
                        "half-width-page engine")
    p.add_argument("--harvest-every", type=int, default=1,
                   help="pipelined decode: every engine defers its "
                        "D2H token harvest to one batched pull per K "
                        "steps (docs/serving.md 'Pipelined decode'); "
                        "1 = the synchronous loop. The soak grades "
                        "the SAME objectives — chaos, recovery, and "
                        "SLOs must hold at any window size")
    p.add_argument("--profile", action="store_true",
                   help="print the performance-attribution report "
                        "(decode-round waterfall, compile-cache table, "
                        "memory ledger — docs/observability.md "
                        "'Performance attribution') after the soak")
    args = p.parse_args(argv)

    import paddle_tpu as paddle
    import paddle_tpu.observability as telemetry
    from paddle_tpu.loadgen import (SoakDriver, TraceConfig,
                                    VirtualClock, binary_search_qps,
                                    generate_trace)
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.serving import ContinuousBatchingEngine
    from paddle_tpu.observability import render_fleet_status
    from paddle_tpu.observability.slo import (SloMonitor, SloObjective,
                                              format_slo_report)
    from paddle_tpu.serving import QosAdmission, ServingRouter

    telemetry.enable()
    paddle.seed(0)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    model.eval()

    page = 16
    out_max, prompt_max = 12, 32
    objective = args.ttft_objective

    def trace_cfg(qps):
        return TraceConfig(
            seed=args.seed, duration_s=args.duration, base_qps=qps,
            diurnal_amplitude=0.3, diurnal_period_s=args.duration,
            burst_start_prob=0.02, burst_mean_s=1.5,
            burst_multiplier=2.5,
            prompt_len_median=10.0, prompt_len_max=prompt_max,
            output_len_median=6.0, output_len_max=out_max,
            tenants=(("acme", 3.0), ("bidco", 2.0), ("free", 1.0)),
            # the drill must be physically winnable: shedding batch
            # frees capacity for interactive only if the interactive
            # slice alone fits the fleet — keep
            # interactive_fraction * overload < 1
            interactive_fraction=min(0.4, 0.8 / args.overload),
            num_system_prompts=4,
            system_prompt_len=page, shared_prefix_prob=0.4,
            vocab_size=cfg.vocab_size)

    def build_fleet(with_qos, journal=None, recover_from=None):
        clock = VirtualClock()
        # a SHORT window makes the burn responsive: shedding starts
        # within seconds of the first breach-shaped samples and backs
        # off as soon as the recent window recovers
        window = min(10.0, args.duration / 3)
        mon = SloMonitor(
            [SloObjective("interactive_ttft_p95", "ttft.interactive",
                          "latency", objective, quantile=0.95,
                          window_s=window),
             SloObjective("ttft_p95", "ttft", "latency", objective,
                          quantile=0.95, window_s=window)],
            clock=clock)
        qos = None
        if with_qos:
            qos = QosAdmission(
                slo_monitor=mon,
                shed_objective="interactive_ttft_p95", shed_burn=0.5,
                budgets={"free": args.free_budget},
                tenant_window_s=max(10.0, args.duration / 3),
                clock=clock)
        # --quant: every replica serves int8 weights + int8 KV pages
        # (fleets must be quant-homogeneous — cross-mode migration is
        # a typed refusal); the soak's grading is unchanged, which is
        # the point: the quantized fleet must hold the same objectives
        quant_cfg = None
        if args.quant:
            from paddle_tpu.models.serving import QuantServingConfig
            quant_cfg = QuantServingConfig(weights="int8", kv="int8")

        def engine(i):
            return ContinuousBatchingEngine(
                model, max_batch_size=args.slots, page_size=page,
                max_seq_len=prompt_max + page + out_max + 2 * page,
                clock=clock, quant=quant_cfg,
                harvest_every=args.harvest_every)

        kw = dict(
            num_replicas=args.replicas, policy="least_outstanding",
            page_size=page, max_replica_outstanding=4 * args.slots,
            clock=clock, sleep=clock.advance, slo_monitor=mon,
            admission=qos)
        if recover_from is not None:
            # a fresh incarnation rehydrated from a dead router's
            # write-ahead journal (docs/serving.md "Durability")
            router = ServingRouter.recover(recover_from, engine, **kw)
        else:
            router = ServingRouter(engine, journal=journal, **kw)
        return router, clock, mon

    def soak(qps, with_qos):
        telemetry.reset()
        router, clock, mon = build_fleet(with_qos)
        driver = SoakDriver(router, generate_trace(trace_cfg(qps)),
                            clock=clock, step_dt=args.step_dt,
                            max_wall_s=1800)
        result = driver.run()
        return result, router, mon

    if args.quant:
        print("mode: QUANTIZED fleet (weights=int8, kv=int8 — "
              "half-width KV pages, fused dequant matmuls)")
    if args.harvest_every > 1:
        print(f"mode: PIPELINED decode (harvest_every="
              f"{args.harvest_every} — one batched D2H harvest per "
              f"window, bounded-staleness durability)")

    # -- phase 1: capacity ---------------------------------------------
    if args.qps > 0:
        max_qps = args.qps
        print(f"capacity: assuming max sustainable QPS {max_qps:g} "
              "(--qps)")
    else:
        def sustainable(qps):
            s = soak(qps, with_qos=False)[0].summary()
            # sustainable = every session FINISHED (refusals and
            # admitted-then-lost preemptions/timeouts both disqualify
            # — a lost session leaves no TTFT sample to grade) under
            # the interactive p95 objective
            lost = s["sessions"] - s["outcomes"].get("finished", 0)
            p95 = s["lanes"].get("interactive", {}).get("ttft_p95_s")
            ok = lost == 0 and (p95 is None or p95 <= objective)
            print(f"  probe {qps:6.2f} qps: lost={lost} "
                  f"interactive p95 TTFT="
                  f"{'-' if p95 is None else f'{p95:.3f}'}s -> "
                  f"{'sustainable' if ok else 'UNSUSTAINABLE'}")
            return ok

        print("capacity: binary search for max sustainable QPS "
              f"(objective: interactive p95 TTFT <= {objective:g}s)")
        max_qps = binary_search_qps(sustainable, 0.5, 4.0, iters=5)
        print(f"capacity: max sustainable ~{max_qps:.2f} qps")

    # -- phase 2: overload with QoS -------------------------------------
    rate = max_qps * args.overload
    print(f"\noverload: soaking at {rate:.2f} qps "
          f"({args.overload:g}x) with QoS admission ON")
    result, router, mon = soak(rate, with_qos=True)
    summary = result.summary()
    print(json.dumps(summary, indent=1))
    print()
    print(render_fleet_status(router.fleet_info()))
    print()
    print(format_slo_report(mon.evaluate(export=False)))

    # -- grading --------------------------------------------------------
    failures = []
    inter = summary["lanes"].get("interactive", {})
    p95 = inter.get("ttft_p95_s")
    if p95 is None:
        failures.append("no interactive TTFT samples at overload")
    elif p95 > objective:
        failures.append(
            f"interactive p95 TTFT {p95:.3f}s exceeds the "
            f"{objective:g}s objective at {args.overload:g}x overload")

    # sheds confined to the batch lane / over-budget tenants
    stray = [s for s in result.sessions
             if s.outcome == "shed" and s.lane == "interactive"
             and s.shed_reason != "tenant_budget"]
    if stray:
        failures.append(
            f"{len(stray)} in-budget interactive sessions were shed "
            f"(e.g. {stray[0].request_id})")
    sheds = sum(1 for s in result.sessions if s.outcome == "shed")
    if sheds == 0:
        failures.append(
            f"no sheds at {args.overload:g}x overload — the drill "
            "proved nothing; raise --overload")

    # exact counter reconciliation (one telemetry snapshot)
    snap = telemetry.snapshot()["counters"]

    def total(name, **labels):
        series = snap.get(name, {})
        want = [f'{k}="{v}"' for k, v in labels.items()]
        return int(sum(v for key, v in series.items()
                       if all(w in key for w in want)))

    admits = total("pdt_admission_decisions_total", decision="admit")
    terminals = total("pdt_router_requests_terminal_total")
    fleet_full = total("pdt_router_rejections_total",
                       reason="fleet_full")
    # admissions are counted at COMMIT (after the fleet accepted), so
    # the identity is exact: every committed admission reaches exactly
    # one terminal state once the fleet drains
    if admits != terminals:
        failures.append(
            f"admission/terminal mismatch: {admits} committed "
            f"admissions != {terminals} terminals "
            f"({fleet_full} fleet_full refusals booked separately)")
    shed_counter = total("pdt_admission_shed_total")
    qos_rejects = total("pdt_router_rejections_total",
                        reason="qos_shed")
    if not (shed_counter == qos_rejects == sheds):
        failures.append(
            f"shed reconciliation failed: pdt_admission_shed_total="
            f"{shed_counter}, qos_shed rejections={qos_rejects}, "
            f"driver-side sheds={sheds}")

    # replayability: the same seed regenerates the same arrivals
    replay = generate_trace(trace_cfg(rate))
    original = generate_trace(trace_cfg(rate))
    if replay != original:
        failures.append("trace replay diverged for the same seed")

    # -- phase 3: kill the control plane mid-run ------------------------
    # everything the soak graded above survives REPLICA death; this leg
    # kills the ROUTER. A journaled fleet takes the front half of a
    # sustainable-rate trace, dies mid-decode (SIGKILL-shaped teardown:
    # nothing of the incarnation survives but its write-ahead journal),
    # `ServingRouter.recover()` rehydrates a fresh incarnation, the
    # remaining arrivals land on IT, and the drill grades zero lost
    # sessions + outputs identical to an unkilled fleet on the same
    # submissions (docs/serving.md "Durability").
    print(f"\nrestart: kill-the-router drill at {max_qps:.2f} qps")
    import shutil
    import tempfile
    from paddle_tpu.serving import RouterJournal

    # enough sessions to straddle the kill, few enough that open-loop
    # submission stays inside the fleet's backpressure bound
    drill_events = generate_trace(trace_cfg(max_qps))[
        :3 * args.replicas * args.slots]

    def drill_submit(router, events):
        return [router.submit(list(ev.prompt), ev.max_new_tokens,
                              request_id=ev.request_id, lane=ev.lane,
                              tenant=ev.tenant) for ev in events]

    ref_router, _, _ = build_fleet(with_qos=False)
    ref_ids = drill_submit(ref_router, drill_events)
    ref_out = ref_router.run()                   # the unkilled oracle

    wal_root = tempfile.mkdtemp(prefix="fleet_soak_wal_")
    try:
        wal = os.path.join(wal_root, "wal")
        router, _, _ = build_fleet(
            with_qos=False,
            journal=RouterJournal(wal, fsync="terminal"))
        half = len(drill_events) // 2
        drill_submit(router, drill_events[:half])
        finished_before = []
        while not finished_before:               # kill mid-decode,
            finished_before += router.step()     # some work finished
        del router                               # SIGKILL-shaped
        recovered, _, _ = build_fleet(
            with_qos=False,
            recover_from=RouterJournal(wal, fsync="terminal"))
        drill_submit(recovered, drill_events[half:])
        got_out = recovered.run()
        n_rec = int(telemetry.value(
            "pdt_journal_replay_recovered_total"))
        n_dedup = int(telemetry.value(
            "pdt_journal_replay_deduped_total"))
        lost = [i for i in ref_ids if i not in got_out]
        if lost:
            failures.append(
                f"router restart lost {len(lost)} soak session(s) "
                f"(e.g. {lost[0]})")
        mismatched = [i for i in ref_ids
                      if got_out.get(i) != ref_out[i]]
        if mismatched:
            failures.append(
                f"router restart changed {len(mismatched)} output "
                f"stream(s) (e.g. {mismatched[0]})")
        print(f"restart: killed the router with {half} sessions in "
              f"flight ({len(finished_before)} already finished) -> "
              f"recover() rehydrated {n_rec} live, restored {n_dedup} "
              f"finished without re-execution; "
              f"{len(drill_events) - half} post-restart arrivals "
              "served by the recovered incarnation; "
              f"{len(drill_events) - len(lost)}/{len(drill_events)} "
              "sessions finished")
        print("--- journal telemetry (Prometheus text exposition) ---")
        print("\n".join(line for line in telemetry.to_prometheus()
                        .splitlines() if "pdt_journal" in line))
        print("--- end journal telemetry ---")
    finally:
        shutil.rmtree(wal_root, ignore_errors=True)

    # -- phase 4 (--autoscale): the elastic fleet ------------------------
    # the same pronounced-diurnal trace twice: a STATIC fleet pinned at
    # peak size, then an AUTOSCALED one (journal-attached so every
    # resize is a two-phase INTENT/COMMIT transaction) starting at one
    # replica under a FleetAutoscaler. Grades: zero lost sessions,
    # interactive p95 TTFT holds the objective, measurably fewer
    # replica-steps (the chip-time proxy), bounded burst reaction, and
    # at least one journaled grow + shrink (docs/serving.md
    # "Autoscaling").
    if args.autoscale:
        from paddle_tpu.loadgen import TraceConfig as _TC
        from paddle_tpu.serving import (AutoscalePolicy, FleetAutoscaler,
                                        RouterJournal)

        def diurnal_cfg():
            base = max_qps * 0.6
            return _TC(
                seed=args.seed + 1, duration_s=2 * args.duration,
                base_qps=base,
                # one pronounced cycle: the trough needs ~a third of
                # the peak's capacity — exactly the gap elasticity
                # harvests
                diurnal_amplitude=0.6,
                diurnal_period_s=2 * args.duration,
                burst_start_prob=0.0, burst_mean_s=1.0,
                burst_multiplier=1.0,
                prompt_len_median=10.0, prompt_len_max=prompt_max,
                output_len_median=6.0, output_len_max=out_max,
                tenants=(("acme", 3.0), ("bidco", 2.0), ("free", 1.0)),
                interactive_fraction=0.4, num_system_prompts=4,
                system_prompt_len=page, shared_prefix_prob=0.4,
                vocab_size=cfg.vocab_size)

        def elastic_soak(autoscaled, journal=None):
            telemetry.reset()
            router, clock, mon = build_fleet(with_qos=False,
                                             journal=journal)
            scaler = None
            if autoscaled:
                # shrink to one replica first — the drill starts at
                # the trough-shaped fleet the policy would converge to
                while len(router.replicas) > 1:
                    router.resize(
                        num_replicas=len(router.replicas) - 1,
                        reason="autoscale-drill-floor")
                scaler = FleetAutoscaler(
                    router,
                    AutoscalePolicy(
                        min_replicas=1, max_replicas=args.replicas,
                        scale_up_depth=2.0 * args.slots,
                        scale_down_depth=0.75,
                        # the capacity model: phase 1 measured the
                        # peak fleet's sustainable rate, so one
                        # replica's share is the per-replica capacity
                        replica_qps=max_qps / args.replicas,
                        up_ticks=2, down_ticks=6,
                        cooldown_s=2.0, max_step=1),
                    interval_s=1.0, clock=clock)
            driver = SoakDriver(router, generate_trace(diurnal_cfg()),
                                clock=clock, step_dt=args.step_dt,
                                max_wall_s=1800, autoscaler=scaler)
            return driver.run(), router, scaler

        print(f"\nautoscale: diurnal drill at {max_qps * 0.6:.2f} qps "
              f"base (static peak fleet = {args.replicas} replicas "
              "vs autoscaled 1.." f"{args.replicas})")
        static_res, _, _ = elastic_soak(autoscaled=False)
        static_sum = static_res.summary()
        wal_root2 = tempfile.mkdtemp(prefix="fleet_soak_autoscale_")
        try:
            auto_res, auto_router, scaler = elastic_soak(
                autoscaled=True,
                journal=RouterJournal(os.path.join(wal_root2, "wal"),
                                      fsync="off"))
            auto_sum = auto_res.summary()
            journaled_resizes = auto_router.fleet_info()["resizes"]
        finally:
            shutil.rmtree(wal_root2, ignore_errors=True)

        lost_auto = auto_sum["sessions"] \
            - auto_sum["outcomes"].get("finished", 0)
        p95_static = static_sum["lanes"].get(
            "interactive", {}).get("ttft_p95_s")
        p95_auto = auto_sum["lanes"].get(
            "interactive", {}).get("ttft_p95_s")
        savings_pct = 100.0 * (1.0 - auto_res.replica_steps
                               / max(1, static_res.replica_steps))
        grows = sum(1 for a in scaler.actions if a["action"] == "grow")
        shrinks = sum(1 for a in scaler.actions
                      if a["action"] == "shrink")
        reaction = max(scaler.reactions, default=None)
        autoscale_metrics = {
            "ttft_p95_static_s": p95_static,
            "ttft_p95_autoscaled_s": p95_auto,
            "replica_steps_static": static_res.replica_steps,
            "replica_steps_autoscaled": auto_res.replica_steps,
            "replica_step_savings_pct": round(savings_pct, 2),
            "burst_reaction_s": reaction,
            "grows": grows, "shrinks": shrinks,
            "journaled_resizes": journaled_resizes,
            "lost_sessions": lost_auto,
        }
        print(json.dumps({"autoscale": autoscale_metrics}, indent=1))
        if lost_auto:
            failures.append(
                f"autoscaled soak lost {lost_auto} session(s) — "
                "elasticity must never cost work")
        if p95_auto is None:
            failures.append("autoscaled soak produced no interactive "
                            "TTFT samples")
        elif p95_auto > objective:
            failures.append(
                f"autoscaled interactive p95 TTFT {p95_auto:.3f}s "
                f"exceeds the {objective:g}s objective (static peak "
                f"fleet held {p95_static})")
        if savings_pct <= 0:
            failures.append(
                f"autoscaling saved no replica-steps "
                f"({auto_res.replica_steps} vs "
                f"{static_res.replica_steps} static)")
        if grows < 1 or shrinks < 1:
            failures.append(
                f"diurnal cycle should force both directions: "
                f"{grows} grows, {shrinks} shrinks")
        if reaction is not None and reaction > 2.0:
            failures.append(
                f"burst reaction {reaction:.2f}s exceeds the 2.0s "
                "bound (hysteresis + cooldown mistuned)")

    # -- phase 5 (--multimodel): the consolidation leg --------------------
    # a per-tenant model mix (two LoRA fine-tunes over the shared base)
    # soaks ONE model_affinity fleet behind a FleetModelStore, then
    # each model's arrivals replay against a DEDICATED single-model
    # fleet of the SAME size — the baseline a consolidation must match
    # while using 1/N the chips (docs/serving.md "Multi-model serving").
    if args.multimodel:
        import dataclasses

        import numpy as np
        from paddle_tpu.serving import FleetModelStore

        # small LoRA deltas over two of the tiny model's matmuls; the
        # shapes come from the live state dict so the recipe tracks
        # the config
        sd = {k: v for k, v in model.state_dict().items()}
        targets = ("model.layers.0.self_attn.q_proj.weight",
                   "model.layers.1.mlp.gate_proj.weight")
        drng = np.random.default_rng(args.seed)

        def lora_deltas():
            out = {}
            for nm in targets:
                K, N = sd[nm].shape
                out[nm] = (
                    drng.normal(size=(K, 4)).astype(np.float32) * 0.05,
                    drng.normal(size=(4, N)).astype(np.float32) * 0.05)
            return out

        def fresh_store():
            # fresh per fleet (resident sets are per-router state);
            # re-seeding regenerates identical deltas, so every fleet
            # hosts the same artifacts
            nonlocal drng
            drng = np.random.default_rng(args.seed)
            store = FleetModelStore(base_model="base", max_rank=8)
            mids = [store.register_adapter("tuna", lora_deltas()),
                    store.register_adapter("salmon", lora_deltas())]
            return store, mids

        def build_mm_fleet(store):
            clock = VirtualClock()
            mon = SloMonitor(
                [SloObjective("interactive_ttft_p95",
                              "ttft.interactive", "latency", objective,
                              quantile=0.95,
                              window_s=min(10.0, args.duration / 3))],
                clock=clock)

            def engine(i):
                return ContinuousBatchingEngine(
                    model, max_batch_size=args.slots, page_size=page,
                    max_seq_len=prompt_max + page + out_max + 2 * page,
                    clock=clock)

            router = ServingRouter(
                engine, num_replicas=args.replicas,
                policy="model_affinity", page_size=page,
                max_replica_outstanding=4 * args.slots,
                clock=clock, sleep=clock.advance, slo_monitor=mon,
                model_store=store)
            return router, clock

        store, (m_tuna, m_salmon) = fresh_store()
        mm_rate = max_qps
        mm_cfg = dataclasses.replace(
            trace_cfg(mm_rate),
            seed=args.seed + 2,
            request_id_prefix="mm",
            model_mix=(("acme", ((m_tuna, 3.0), ("base", 1.0))),
                       ("bidco", ((m_salmon, 1.0),)),
                       ("free", (("base", 1.0),))))
        mm_events = generate_trace(mm_cfg)
        mix_counts = {}
        for ev in mm_events:
            mix_counts[ev.model] = mix_counts.get(ev.model, 0) + 1
        print(f"\nmultimodel: {len(mm_events)} arrivals at "
              f"{mm_rate:.2f} qps, mix {mix_counts} -> one "
              f"{args.replicas}-replica model_affinity fleet vs "
              "dedicated per-model fleets")

        telemetry.reset()
        mm_router, mm_clock = build_mm_fleet(store)
        mm_res = SoakDriver(mm_router, mm_events, clock=mm_clock,
                            step_dt=args.step_dt, max_wall_s=1800).run()
        mm_sum = mm_res.summary()
        mm_info = mm_router.fleet_info()
        # snapshot NOW: the dedicated baselines below tick the same
        # process-wide counters
        mm_terminals_total = int(sum(
            telemetry.snapshot()["counters"]
            .get("pdt_router_requests_terminal_total", {}).values()))
        # phase 1 certified max_qps on a DIFFERENT arrival realization
        # (the model draws shift the trace RNG stream), so backpressure
        # refusals are legitimate here — visible and reconciled below.
        # What may NEVER happen is an ADMITTED session going missing.
        refused_mm = sum(mm_sum["outcomes"].get(o, 0)
                         for o in ("shed", "overloaded", "invalid"))
        lost_mm = mm_sum["sessions"] - refused_mm \
            - mm_sum["outcomes"].get("finished", 0)
        p95_mm = mm_sum["lanes"].get("interactive", {}) \
            .get("ttft_p95_s")

        # the dedicated baseline: each model's arrivals alone against a
        # fleet of the same size hosting only that model
        dedicated_p95 = {}
        for mid in sorted(mix_counts):
            d_store, _ = fresh_store()
            d_router, d_clock = build_mm_fleet(d_store)
            d_events = [ev for ev in mm_events if ev.model == mid]
            d_res = SoakDriver(d_router, d_events, clock=d_clock,
                               step_dt=args.step_dt,
                               max_wall_s=1800).run()
            d_sum = d_res.summary()
            d_lost = d_sum["sessions"] \
                - sum(d_sum["outcomes"].get(o, 0)
                      for o in ("shed", "overloaded", "invalid")) \
                - d_sum["outcomes"].get("finished", 0)
            if d_lost:
                failures.append(f"dedicated {mid} fleet lost "
                                f"{d_lost} admitted session(s)")
            dedicated_p95[mid] = d_sum["lanes"].get(
                "interactive", {}).get("ttft_p95_s")

        # exact per-model terminal reconciliation, three ways: the
        # driver's per-session ledger, the router's python-side
        # num_terminal_by_model, and fleet_info()["models"]
        driver_by_model = {}
        for s in mm_res.sessions:
            if s.outcome in ("shed", "overloaded", "invalid"):
                continue
            mid = s.model if s.model is not None else "base"
            d = driver_by_model.setdefault(mid, {})
            d[s.outcome] = d.get(s.outcome, 0) + 1
        router_by_model = {
            mid: dict(c)
            for mid, c in mm_router.num_terminal_by_model.items()}
        info_by_model = {
            mid: dict(rec["terminal"])
            for mid, rec in mm_info["models"].items()
            if rec["terminal"]}
        if not (driver_by_model == router_by_model == info_by_model):
            failures.append(
                "per-model terminal reconciliation failed: "
                f"driver={driver_by_model} "
                f"router={router_by_model} fleet_info={info_by_model}")
        by_model_sum = sum(sum(c.values())
                           for c in router_by_model.values())
        if mm_terminals_total != by_model_sum:
            failures.append(
                f"per-model terminals {by_model_sum} != fleet total "
                f"{mm_terminals_total}")

        mm_metrics = {
            "arrivals": len(mm_events), "mix": mix_counts,
            "ttft_p95_mixed_s": p95_mm,
            "ttft_p95_dedicated_s": dedicated_p95,
            "cold_installs": dict(mm_router.num_cold_installs_by_model),
            "model_store": mm_info["model_store"],
            "refusals": refused_mm,
            "lost_admitted_sessions": lost_mm,
            "replicas_mixed": args.replicas,
            "replicas_dedicated_total":
                args.replicas * len(mix_counts),
        }
        print(json.dumps({"multimodel": mm_metrics}, indent=1))
        if lost_mm:
            failures.append(f"multi-model soak lost {lost_mm} "
                            "admitted session(s)")
        if p95_mm is None:
            failures.append("multi-model soak produced no interactive "
                            "TTFT samples")
        elif p95_mm > objective:
            failures.append(
                f"mixed-model interactive p95 TTFT {p95_mm:.3f}s "
                f"exceeds the {objective:g}s objective "
                f"(dedicated baselines: {dedicated_p95}) — "
                "consolidation broke latency parity")
        for mid, p in dedicated_p95.items():
            if p is not None and p > objective:
                failures.append(
                    f"dedicated {mid} baseline p95 TTFT {p:.3f}s "
                    f"missed the {objective:g}s objective — the "
                    "parity grade has no valid baseline")

    if args.profile:
        # where the soak's decode rounds went + what compiled; the
        # fleet_info/render_fleet_status calls above already refreshed
        # the pdt_mem_bytes ledger from the live fleet
        from paddle_tpu.observability import profile as _profile
        print()
        print(_profile.snapshot_report())

    print()
    if failures:
        for f in failures:
            print(f"FAIL: {f}")
        return 1
    print(f"PASS: interactive p95 TTFT {p95:.3f}s <= {objective:g}s "
          f"at {args.overload:g}x overload; {sheds} sheds, all "
          "batch-lane or over-budget; admission counters reconcile "
          f"exactly ({admits} committed admissions = {terminals} "
          f"terminals; {fleet_full} backpressure refusals booked "
          "separately); trace replays bit-identically")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
