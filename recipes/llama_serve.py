#!/usr/bin/env python
"""Llama serving demo — the full L10 inference stack in one script.

≙ the reference's serving deployment recipe (PaddleNLP llm serving /
`AnalysisPredictor` flows, SURVEY.md §1 L10): load or build a model,
then drive every decode surface the framework ships —

  * `generate()` greedy / sampling / beam search (+ repetition penalty),
  * the continuous-batching engine on the paged KV cache,
  * automatic prefix caching across requests sharing a system prompt,
  * resilient serving: bounded-queue backpressure, per-request
    deadlines, and a chaos drill (injected prefill fault + forced
    pool exhaustion -> preemption) proving failure isolation,
  * the multi-replica fleet (`--replicas N`): prefix-affinity dispatch
    over N engines plus a kill-a-replica failover drill — SIGKILL one
    replica mid-decode, prove zero loss (outputs identical to an
    unkilled fleet), and print the `pdt_router_*` Prometheus dump,
  * disaggregated prefill/decode (`--roles prefill:N,decode:M`): the
    role-split fleet vs a colocated oracle on the same jobs, with a
    kill-a-prefill-replica-mid-migration drill — the transfer dies at
    the `transfer.serialize` fault site, the source is SIGKILLed, and
    outputs are still identical (KV page transfer plane + fleet-wide
    prefix store stats printed),
  * the crash-durable control plane (docs/serving.md "Durability"):
    a write-ahead-journaled fleet loses its ROUTER mid-decode
    (SIGKILL-shaped teardown), `ServingRouter.recover()` rehydrates a
    fresh incarnation from the journal — finished requests restored
    without re-execution, live ones re-prefilled with folded tokens —
    outputs identical to an unkilled fleet, `pdt_journal_*` dump
    printed,
  * the operator surface (docs/observability.md): an `SloMonitor`
    grades the drill's TTFT/availability objectives (SLO report +
    fleet status printed), and the failover timeline is written as a
    Perfetto/Chrome trace (`--trace-out`) for visual inspection,
  * speculative decoding with a draft model (lossless vs greedy),

and print per-path outputs + engine cache/occupancy stats.

    python recipes/llama_serve.py                    # tiny synthetic model
    python recipes/llama_serve.py --hf path/to/llama # converted HF weights
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser(description="Llama serving demo")
    p.add_argument("--hf", default=None,
                   help="path to a HuggingFace Llama checkpoint "
                        "(default: tiny synthetic model)")
    p.add_argument("--max-new-tokens", type=int, default=24)
    p.add_argument("--num-beams", type=int, default=4)
    p.add_argument("--draft-layers", type=int, default=1)
    p.add_argument("--speculate", type=int, default=0, metavar="K",
                   help="run the kill-a-replica fleet drill with "
                        "ENGINE speculative decoding (spec_decode="
                        "SpecConfig(draft, K)): the killed fleet "
                        "drafts K tokens per slot per round while the "
                        "unkilled reference fleet stays plain, so the "
                        "outputs-identical assert proves losslessness "
                        "through SIGKILL failover; prints acceptance "
                        "rate + effective tokens/sec (0 = off)")
    p.add_argument("--attention-impl", default="ragged",
                   choices=("ragged", "legacy"),
                   help="serving attention path: the fused ragged "
                        "paged-attention kernel (default) or the "
                        "legacy per-bucket prefill + q=1 decode paths "
                        "(greedy outputs are bit-identical)")
    p.add_argument("--replicas", type=int, default=3,
                   help="fleet size for the router failover drill")
    p.add_argument("--roles", default="prefill:2,decode:2",
                   help="role split for the disaggregation drill "
                        "(prefill:N,decode:M[,colocated:K]); the "
                        "drill proves outputs identical to a "
                        "colocated fleet through a SIGKILL of a "
                        "prefill replica mid-migration")
    p.add_argument("--tp", type=int, default=0, metavar="N",
                   help="run the kill-a-SUBMESH drill: a fleet of "
                        "tensor-parallel replicas (one replica = one "
                        "N-device GSPMD submesh, serving/submesh.py), "
                        "SIGKILL one TP replica mid-decode, assert "
                        "outputs identical to an unkilled tp=1 fleet, "
                        "and print the pdt_tp/transfer Prometheus "
                        "dump (0 = off)")
    p.add_argument("--corrupt-drill", action="store_true",
                   help="run the GRAY-FAILURE drill (docs/serving.md "
                        "\"Gray failures\"): arm a seeded KV bit-flip "
                        "corrupt-mode fault on one replica of a "
                        "sentried fleet — the replica keeps answering "
                        "but answers WRONG — prove the canary probe "
                        "quarantines it and every stream re-serves "
                        "bit-identical to a clean fleet, then print "
                        "the pdt_sentry quarantine/canary Prometheus "
                        "dump")
    p.add_argument("--trace-out", default=None,
                   help="write the failover drill's Perfetto/Chrome "
                        "trace here (default: a temp file)")
    p.add_argument("--lint-gate", action="store_true",
                   help="run paddle-tpu-lint against the committed "
                        "baseline FIRST and refuse to serve a dirty "
                        "tree (the serving invariants the lint "
                        "encodes are the ones this recipe's drills "
                        "rely on — docs/static_analysis.md)")
    args = p.parse_args(argv)

    if args.lint_gate:
        # fail fast, before any model build: a tree that violates the
        # serving invariants (or drifted from the baseline) must not
        # demo green
        from paddle_tpu.analysis.__main__ import main as lint_main
        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        rc = lint_main([os.path.join(root, "paddle_tpu"),
                        "--root", root])
        if rc != 0:
            print("lint gate: tree is dirty vs the pdt-lint baseline "
                  "— fix or suppress (with a reason) before serving")
            return rc
        print("lint gate: clean vs baseline")

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.observability as telemetry
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.serving import ContinuousBatchingEngine
    from paddle_tpu.models.speculative import speculative_generate

    # live demo of the metric catalog: every path below records, and the
    # recipe ends with the Prometheus dump a scraper would see
    telemetry.enable()

    if args.hf:
        # transformers loads the checkpoint; the converter copies weights
        # into our model (q/k rope-permutation handled inside)
        from transformers import AutoConfig, AutoModelForCausalLM
        from paddle_tpu.models.hf_convert import load_llama_from_hf
        hc = AutoConfig.from_pretrained(args.hf)
        cfg = LlamaConfig(
            vocab_size=hc.vocab_size, hidden_size=hc.hidden_size,
            intermediate_size=hc.intermediate_size,
            num_hidden_layers=hc.num_hidden_layers,
            num_attention_heads=hc.num_attention_heads,
            num_key_value_heads=hc.num_key_value_heads,
            max_position_embeddings=hc.max_position_embeddings,
            rope_theta=getattr(hc, "rope_theta", 10000.0),
            rms_norm_eps=hc.rms_norm_eps)
        model = LlamaForCausalLM(cfg)
        # torch_dtype="auto": load at the checkpoint's stored dtype (bf16
        # for modern Llamas) instead of materializing fp32 host copies
        load_llama_from_hf(
            model, AutoModelForCausalLM.from_pretrained(
                args.hf, torch_dtype="auto").state_dict())
    else:
        paddle.seed(0)
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
    model.eval()
    n = args.max_new_tokens
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, cfg.vocab_size, 12).astype(np.int32)

    # 1) generate(): one compiled program per strategy
    ids = paddle.to_tensor(prompt[None])
    for strat, kw in (("greedy_search", {}),
                      ("sampling", dict(temperature=0.8, top_p=0.95)),
                      ("beam_search", dict(num_beams=args.num_beams,
                                           length_penalty=0.6))):
        t0 = time.perf_counter()
        toks, score = model.generate(ids, max_new_tokens=n,
                                     decode_strategy=strat,
                                     repetition_penalty=1.1, **kw)
        dt = time.perf_counter() - t0
        print(f"{strat:>14}: {np.asarray(toks._value)[0, :8].tolist()}... "
              f"({dt:.2f}s incl. compile)")

    # 2) continuous batching on the paged cache + prefix caching
    system = rng.integers(1, cfg.vocab_size, 32).tolist()
    eng = ContinuousBatchingEngine(model, max_batch_size=4,
                                   max_seq_len=min(
                                       256, cfg.max_position_embeddings),
                                   enable_prefix_caching=True,
                                   attention_impl=args.attention_impl)
    print(f"engine attention_impl: {eng.attn_impl}")
    rids = [eng.add_request(
        system + rng.integers(1, cfg.vocab_size,
                              int(rng.integers(4, 10))).tolist(), n)
        for _ in range(6)]
    t0 = time.perf_counter()
    results = eng.run()
    dt = time.perf_counter() - t0
    info = eng.cache_memory_info()
    print(f"engine: {len(results)} requests, "
          f"{sum(len(v) for v in results.values())} tokens in {dt:.2f}s; "
          f"prefix hits {eng.prefix_hits} "
          f"({eng.prefix_tokens_reused} tokens reused), "
          f"pages in use {info['pages_in_use']}/{info['total_pages']}")
    assert sorted(results) == sorted(rids)

    # 3) resilient serving: backpressure + deadlines + chaos drill
    from paddle_tpu.models.serving import EngineOverloaded, RequestStatus
    from paddle_tpu.utils.faults import FaultInjector
    eng = ContinuousBatchingEngine(
        model, max_batch_size=2,
        max_seq_len=min(256, cfg.max_position_embeddings),
        max_waiting=3, attention_impl=args.attention_impl)
    for _ in range(3):
        eng.add_request(rng.integers(1, cfg.vocab_size, 6).tolist(), 8)
    try:
        eng.add_request([1, 2, 3], 8)
        raise AssertionError("queue bound not enforced")
    except EngineOverloaded:
        shed = True                      # ≙ a front end's 429
    reqs = {}
    with FaultInjector(seed=0) as fi:
        fi.arm("serving.prefill", nth=1)  # first prefill dies
        while True:
            for r in eng.step():
                reqs[r.rid] = r
            li = eng.lifecycle_info()
            if not li["waiting"] and not li["running"]:
                break
    statuses = sorted(r.status for r in reqs.values())
    assert statuses.count(RequestStatus.FAILED) == 1     # isolated
    assert statuses.count(RequestStatus.FINISHED) == 2   # others fine
    li = eng.lifecycle_info()
    print(f"robustness: shed_on_overload={shed}, "
          f"failures={li['failures']} (isolated), "
          f"finished={statuses.count(RequestStatus.FINISHED)}, "
          f"pages_in_use="
          f"{eng.cache_memory_info()['pages_in_use']}")

    # 3b) telemetry: the serving + chaos drill above populated the
    # metric catalog — dump the text exposition a Prometheus scraper
    # would collect, and prove it reconciles with what we observed
    snap = telemetry.snapshot()
    term = snap["counters"]["pdt_serving_requests_terminal_total"]
    assert term['status="failed"'] == statuses.count(RequestStatus.FAILED)
    assert telemetry.value("pdt_faults_fired_total",
                           site="serving.prefill") == 1
    print("--- telemetry (Prometheus text exposition) ---")
    print(telemetry.to_prometheus(), end="")
    print("--- end telemetry ---")

    # 3c) the serving fleet: prefix-affinity dispatch over --replicas
    # engines, then the failover drill — SIGKILL a replica mid-decode
    # and prove zero loss against an unkilled fleet's outputs
    from paddle_tpu.serving import ServingRouter
    from paddle_tpu.observability.slo import (SloMonitor,
                                              default_serving_objectives)

    # the draft model (shared by the --speculate fleet drill and the
    # standalone speculative_generate demo below)
    from paddle_tpu.models.serving import SpecConfig
    d_cfg = LlamaConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size // 2,
        intermediate_size=cfg.intermediate_size // 2,
        num_hidden_layers=args.draft_layers,
        num_attention_heads=max(1, cfg.num_attention_heads // 2),
        num_key_value_heads=max(1, cfg.num_key_value_heads // 2),
        max_position_embeddings=cfg.max_position_embeddings)
    paddle.seed(1)
    draft = LlamaForCausalLM(d_cfg)
    draft.eval()

    def fleet(mon=None, speculate=0):
        return ServingRouter(
            lambda i: ContinuousBatchingEngine(
                model, max_batch_size=2,
                max_seq_len=min(256, cfg.max_position_embeddings),
                enable_prefix_caching=True,
                attention_impl=args.attention_impl,
                spec_decode=SpecConfig(draft, k=speculate)
                if speculate else None),
            num_replicas=args.replicas, policy="prefix_affinity",
            page_size=16, slo_monitor=mon)

    fleet_jobs = [system + rng.integers(
        1, cfg.vocab_size, int(rng.integers(4, 10))).tolist()
        for _ in range(2 * args.replicas)]
    ref_router = fleet()
    ref_ids = [ref_router.submit(pr, n) for pr in fleet_jobs]
    want_out = ref_router.run()                  # the unkilled oracle

    # the killed fleet runs with the operator surface attached: an SLO
    # monitor grading the drill (generous bounds — tiny-model CPU
    # prefills span compiles) and a cleared trace ring so the exported
    # Perfetto timeline shows exactly the failover drill
    telemetry.clear_events()
    slo_mon = SloMonitor(default_serving_objectives(
        ttft_p95=120.0, tpot_p95=30.0, max_error_rate=0.01,
        min_availability=0.99, window_s=3600.0))
    router = fleet(mon=slo_mon, speculate=args.speculate)
    ids_f = [router.submit(pr, n) for pr in fleet_jobs]
    router.step()
    router.step()                                # mid-decode everywhere
    victim = router.requests[ids_f[0]].replica
    router.kill_replica(victim)                  # SIGKILL-shaped
    t0 = time.perf_counter()
    got_out = router.run()
    drill_wall = time.perf_counter() - t0
    assert [got_out[i] for i in ids_f] \
        == [want_out[i] for i in ref_ids], "failover changed outputs"
    info = router.fleet_info()
    print(f"fleet: {args.replicas} replicas, killed replica {victim} "
          f"mid-decode -> {info['failovers']} failover(s), "
          f"{info['pending']} lost, outputs identical; "
          f"prefix hits {info['prefix_hits']} "
          f"({info['prefix_tokens_reused']} tokens reused), "
          f"affinity hit rate "
          f"{telemetry.value('pdt_router_affinity_hit_rate'):.2f}")
    assert info["failovers"] >= 1 and info["pending"] == 0
    if args.speculate:
        # the killed fleet ran ENGINE speculation against a PLAIN
        # reference fleet — the assert above just proved losslessness
        # through the SIGKILL (the survivor's rebuilt draft cache
        # included)
        sp = info["speculation"]
        toks = sum(len(v) for v in got_out.values())
        print(f"speculation: k={args.speculate}, acceptance "
              f"{sp['acceptance_rate']:.2f} ({sp['accepted']}/"
              f"{sp['proposed']} over {sp['rounds']} rounds, "
              f"{sp['degraded']} degraded), effective "
              f"{toks / drill_wall:.0f} tok/s through the kill drill")
        assert sp["rounds"] >= 1
    print("--- router telemetry (Prometheus text exposition) ---")
    print("\n".join(line for line in telemetry.to_prometheus()
                    .splitlines() if "pdt_router" in line))
    print("--- end router telemetry ---")

    # 3d) operator surface: SLO verdicts, the fleet status report, and
    # the drill's failover timeline as a Perfetto/Chrome trace
    slo_report = slo_mon.evaluate()
    assert all(st.ok for st in slo_report.values()), slo_report
    print(slo_mon.report())
    print(telemetry.render_fleet_status(info))
    killed_rid = ids_f[0]
    tree = telemetry.request_tree(killed_rid)
    assert tree is not None and tree["children"], \
        "killed request left no span tree"
    import tempfile
    trace_out = args.trace_out or os.path.join(
        tempfile.gettempdir(), "llama_serve_failover_trace.json")
    telemetry.export_chrome_trace(path=trace_out)
    print(f"failover drill trace -> {trace_out} "
          "(load in chrome://tracing or https://ui.perfetto.dev; "
          "pid=replica, tid=request)")

    # 3f) performance attribution (docs/observability.md "Performance
    # attribution"): where did the drill's decode rounds go, and what
    # compiled — the waterfall + compile-cache table from the live
    # registry, same report `paddle-tpu-obs profile` renders offline
    # (fleet_info above already refreshed the pdt_mem_bytes ledger)
    from paddle_tpu.observability import profile as _profile
    print(_profile.snapshot_report())

    # 3e) disaggregated prefill/decode (docs/serving.md
    # "Disaggregation"): the same jobs through a colocated fleet (the
    # oracle) and a role-split fleet, with a kill-a-prefill-replica-
    # mid-migration drill — the first migration attempt dies at the
    # transfer.serialize fault site, the source replica is SIGKILLed
    # with the transfer un-done, and failover re-prefills on survivors:
    # outputs must still be identical to the unkilled colocated fleet
    from paddle_tpu.serving import parse_roles
    role_list = parse_roles(args.roles)
    n_roles = len(role_list)
    disagg_jobs = [system + rng.integers(
        1, cfg.vocab_size, int(rng.integers(4, 10))).tolist()
        for _ in range(2 * n_roles)]

    def role_fleet(roles):
        return ServingRouter(
            lambda i: ContinuousBatchingEngine(
                model, max_batch_size=2,
                max_seq_len=min(256, cfg.max_position_embeddings),
                enable_prefix_caching=True,
                attention_impl=args.attention_impl),
            num_replicas=n_roles, policy="prefix_affinity",
            page_size=16, roles=roles)

    colo = role_fleet(None)
    colo_ids = [colo.submit(pr, n) for pr in disagg_jobs]
    colo_out = colo.run()                        # the colocated oracle

    disagg = role_fleet(args.roles)
    d_ids = [disagg.submit(pr, n) for pr in disagg_jobs]
    victim = next(i for i, h in enumerate(disagg.replicas)
                  if h.role == "prefill")
    with FaultInjector(seed=0) as fi:
        fi.arm("transfer.serialize", nth=1)      # first migration dies
        disagg.step()                            # ... mid-transfer
    disagg.kill_replica(victim)                  # SIGKILL the source
    d_out = disagg.run()
    assert [d_out[i] for i in d_ids] == [colo_out[i] for i in colo_ids], \
        "disaggregation changed outputs"
    info = disagg.fleet_info()
    assert info["migrations"] >= 1 and info["pending"] == 0
    store = info["prefix_store"]
    print(f"disaggregation: roles {args.roles}, killed prefill replica "
          f"{victim} mid-migration -> {info['failovers']} failover(s), "
          f"{info['migrations']} migration(s), outputs identical to the "
          f"colocated fleet; prefix store {store['chains']} chains "
          f"({store['spilled_chains']} spilled), hit rate "
          f"{store['hit_rate']}")
    print(telemetry.render_fleet_status(info))
    print("--- transfer telemetry (Prometheus text exposition) ---")
    print("\n".join(line for line in telemetry.to_prometheus()
                    .splitlines()
                    if "pdt_transfer" in line or "pdt_prefix_store"
                    in line))
    print("--- end transfer telemetry ---")

    # 3f) tensor parallelism (docs/serving.md "Tensor parallelism"):
    # the kill-a-submesh drill — a fleet where each replica is one
    # --tp-device GSPMD submesh (weights column/row-sharded, KV pages
    # sharded on the head axis), SIGKILL one TP replica mid-decode,
    # and prove outputs identical to an unkilled tp=1 fleet; then one
    # roles migration so the per-shard transfer fragments are
    # exercised and metered
    if args.tp:
        import jax as _jax
        from paddle_tpu.serving import TpConfig
        n_dev = len(_jax.devices())
        tp_replicas = min(2, n_dev // args.tp)
        if tp_replicas < 2:
            raise SystemExit(
                f"--tp {args.tp} needs >= {2 * args.tp} devices for a "
                f"2-replica drill, have {n_dev}")
        tp_jobs = [system + rng.integers(
            1, cfg.vocab_size, int(rng.integers(4, 10))).tolist()
            for _ in range(2 * tp_replicas)]

        def tp_fleet(tp):
            if tp is None:
                return ServingRouter(
                    lambda i: ContinuousBatchingEngine(
                        model, max_batch_size=2,
                        max_seq_len=min(256,
                                        cfg.max_position_embeddings),
                        enable_prefix_caching=True),
                    num_replicas=tp_replicas)
            return ServingRouter(
                lambda i, sm: ContinuousBatchingEngine(
                    model, max_batch_size=2,
                    max_seq_len=min(256, cfg.max_position_embeddings),
                    enable_prefix_caching=True, submesh=sm),
                num_replicas=tp_replicas, tp=TpConfig(tp=tp))

        ref = tp_fleet(None)                     # the tp=1 oracle
        ref_ids = [ref.submit(pr, n) for pr in tp_jobs]
        tp_want = ref.run()
        fleet_tp = tp_fleet(args.tp)
        tp_ids = [fleet_tp.submit(pr, n) for pr in tp_jobs]
        fleet_tp.step()
        fleet_tp.step()                          # mid-decode
        victim = fleet_tp.requests[tp_ids[0]].replica
        fleet_tp.kill_replica(victim)            # SIGKILL the submesh
        tp_got = fleet_tp.run()
        assert [tp_got[i] for i in tp_ids] \
            == [tp_want[i] for i in ref_ids], \
            "tensor parallelism changed outputs"
        info = fleet_tp.fleet_info()
        print(f"tensor parallelism: {tp_replicas} replicas x "
              f"tp={args.tp}, killed replica {victim} (submesh "
              f"{info['replicas'][victim]['submesh']['devices']}) "
              f"mid-decode -> {info['failovers']} failover(s), "
              "outputs identical to the tp=1 fleet")
        assert info["failovers"] >= 1 and info["pending"] == 0
        # one migration between TP replicas: per-shard payload bytes
        disagg_tp = ServingRouter(
            lambda i, sm: ContinuousBatchingEngine(
                model, max_batch_size=2,
                max_seq_len=min(256, cfg.max_position_embeddings),
                enable_prefix_caching=True, submesh=sm),
            roles="prefill:1,decode:1", tp=args.tp, page_size=16)
        d_ids = [disagg_tp.submit(pr, n) for pr in tp_jobs]
        d_got = disagg_tp.run()
        assert [d_got[i] for i in d_ids] \
            == [tp_want[i] for i in ref_ids], \
            "TP migration changed outputs"
        assert disagg_tp.fleet_info()["migrations"] >= 1
        print(telemetry.render_fleet_status(info))
        print("--- tp telemetry (Prometheus text exposition) ---")
        print("\n".join(line for line in telemetry.to_prometheus()
                        .splitlines()
                        if "pdt_tp" in line or "pdt_transfer" in line))
        print("--- end tp telemetry ---")

    # 3g) crash-durable control plane (docs/serving.md "Durability"):
    # every drill above killed things BELOW the router; this one kills
    # the ROUTER. A journaled fleet dies mid-decode (abandoned,
    # SIGKILL-shaped — nothing of the incarnation survives but the
    # write-ahead journal directory), `ServingRouter.recover()`
    # rehydrates a fresh incarnation: requests that finished before
    # the kill restore WITHOUT re-execution (idempotent per
    # request_id), live ones re-prefill with their journaled tokens
    # folded in, and outputs must be identical to an unkilled fleet
    import shutil
    import tempfile
    from paddle_tpu.serving import RouterJournal

    def dur_engine(i):
        return ContinuousBatchingEngine(
            model, max_batch_size=2,
            max_seq_len=min(256, cfg.max_position_embeddings),
            enable_prefix_caching=True,
            attention_impl=args.attention_impl)

    dur_kwargs = dict(num_replicas=args.replicas,
                      policy="prefix_affinity", page_size=16)

    dur_jobs = [system + rng.integers(
        1, cfg.vocab_size, int(rng.integers(4, 10))).tolist()
        for _ in range(2 * args.replicas)]
    # staggered budgets: some requests must FINISH before the kill
    # (exercising the restore-without-re-execution path) while others
    # are still mid-decode (the folded re-prefill path)
    dur_budgets = [n if i % 2 == 0 else max(2, n // 4)
                   for i in range(len(dur_jobs))]
    dur_ref = ServingRouter(dur_engine, **dur_kwargs)
    dur_ref_ids = [dur_ref.submit(pr, b)
                   for pr, b in zip(dur_jobs, dur_budgets)]
    dur_want = dur_ref.run()                     # the unkilled oracle

    wal_root = tempfile.mkdtemp(prefix="llama_serve_wal_")
    try:
        wal = os.path.join(wal_root, "wal")
        router = ServingRouter(
            dur_engine, journal=RouterJournal(wal, fsync="terminal"),
            **dur_kwargs)
        dur_ids = [router.submit(pr, b)
                   for pr, b in zip(dur_jobs, dur_budgets)]
        finished_before = []
        while not finished_before:               # someone must finish
            finished_before += [r.request_id for r in router.step()]
        assert any(not router.requests[i].done for i in dur_ids)
        del router                               # SIGKILL-shaped
        recovered = ServingRouter.recover(
            RouterJournal(wal, fsync="terminal"), dur_engine,
            **dur_kwargs)
        for rid in finished_before:              # restored, not re-run
            assert recovered.requests[rid].done
            assert recovered.requests[rid].dispatches == 0
        dur_out = recovered.run()
        assert [dur_out[i] for i in dur_ids] \
            == [dur_want[i] for i in dur_ref_ids], \
            "router restart changed outputs"
        n_rec = telemetry.value("pdt_journal_replay_recovered_total")
        n_dedup = telemetry.value("pdt_journal_replay_deduped_total")
        print(f"durability: killed the ROUTER mid-decode -> recover() "
              f"rehydrated {n_rec:.0f} live request(s) and restored "
              f"{n_dedup:.0f} finished one(s) without re-execution; "
              "outputs identical to the unkilled fleet")
        assert n_rec >= 1 and n_dedup >= len(finished_before)
        print("--- journal telemetry (Prometheus text exposition) ---")
        print("\n".join(line for line in telemetry.to_prometheus()
                        .splitlines() if "pdt_journal" in line))
        print("--- end journal telemetry ---")
    finally:
        shutil.rmtree(wal_root, ignore_errors=True)

    # 3h) gray-failure drill (docs/serving.md "Gray failures"): every
    # drill above is FAIL-STOP — this one is fail-WRONG. One replica
    # of a sentried fleet gets a seeded always-firing KV bit-flip
    # (corrupt-mode fault, pinned by tag= like one sick chip); its
    # streams go silently wrong, the scheduled canary replays the
    # golden prompt THROUGH the corrupt engine and mismatches, the
    # replica quarantines, tainted token suffixes are dropped, and
    # every request re-serves bit-identically to a clean fleet
    if args.corrupt_drill:
        from paddle_tpu.serving import CanaryConfig, SentryConfig

        def gray_fleet(sentried):
            return ServingRouter(
                lambda i: ContinuousBatchingEngine(
                    model, max_batch_size=3,
                    max_seq_len=min(256, cfg.max_position_embeddings),
                    attention_impl=args.attention_impl),
                num_replicas=args.replicas, policy="round_robin",
                page_size=16,
                sentry=SentryConfig(scan_every=8) if sentried
                else None,
                canary=CanaryConfig(interval=0.05, max_new_tokens=8)
                if sentried else None,
                restart_backoff_base=0.2, restart_backoff_max=0.5)

        gray_jobs = [rng.integers(
            1, cfg.vocab_size, int(rng.integers(5, 11))).tolist()
            for _ in range(2 * args.replicas)]
        clean = gray_fleet(False)
        clean_ids = [clean.submit(pr, n) for pr in gray_jobs]
        clean_out = clean.run()                  # the uncorrupted oracle

        gray = gray_fleet(True)
        g_ids = [gray.submit(pr, n) for pr in gray_jobs]
        gray.step()
        victim = gray.requests[g_ids[0]].replica
        with FaultInjector(seed=0) as fi:
            # the sick chip: every KV commit on the victim flips one
            # seeded byte of a LIVE page — requests AND the canary
            # replay decode through the damage
            fi.arm_corrupt("serving.kv_page", mode="bitflip",
                           always=True, tag=str(victim))
            g_out = gray.run()
        assert [g_out[i] for i in g_ids] \
            == [clean_out[i] for i in clean_ids], \
            "gray failure leaked tainted tokens into a finished stream"
        info = gray.fleet_info()
        sn = info["sentry"]
        assert sn["quarantines"] >= 1, "corrupt replica never caught"
        print(f"gray failure: replica {victim} served a seeded KV "
              f"bit-flip -> canary caught it ({sn['canary_runs']} "
              f"probe(s), {sn['canary_failures']} failure(s)), "
              f"{sn['quarantines']} quarantine(s), "
              f"{sn['tainted_tokens_dropped']} tainted token(s) "
              "dropped and re-served; outputs identical to a clean "
              "fleet")
        print("--- sentry telemetry (Prometheus text exposition) ---")
        print("\n".join(line for line in telemetry.to_prometheus()
                        .splitlines() if "pdt_sentry" in line))
        print("--- end sentry telemetry ---")

    # 4) standalone speculative decoding (same draft as the fleet
    # drill's engine-mode speculation)
    want, _ = model.generate(ids, max_new_tokens=n)
    got, acc = speculative_generate(model, draft, ids, max_new_tokens=n,
                                    num_draft_tokens=4)
    ok = np.array_equal(np.asarray(got._value), np.asarray(want._value))
    print(f"speculative: lossless={ok}, draft acceptance "
          f"{float(acc):.2f}")
    assert ok
    print("SERVING DEMO OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
