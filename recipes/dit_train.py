#!/usr/bin/env python
"""DiT diffusion training — north-star config #4 (DiT/SD3 style,
BASELINE.json configs[3] / SURVEY.md §6): conv(patchify) + attention
through the Pallas flash kernel on TPU, diffusion loss + DDIM sampling
as single compiled XLA programs.

    python recipes/dit_train.py --steps 10                 # synthetic
    python recipes/dit_train.py --mesh dp=4,mp=2 --steps 5 # 8-dev CPU
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from recipes.common import RecipeResult, run_train, std_parser  # noqa: E402
from recipes.llama_pretrain import parse_mesh  # noqa: E402


def main(argv=None):
    p = std_parser("DiT diffusion training")
    p.add_argument("--size", choices=["tiny", "s"], default="tiny")
    p.add_argument("--mesh", type=str, default=None, help="e.g. dp=4,mp=2")
    p.add_argument("--sample-after", action="store_true",
                   help="run a 10-step DDIM sample at the end")
    args = p.parse_args(argv)

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models.dit import (DiT, DiTConfig, GaussianDiffusion,
                                       synthetic_dit_batch)
    from paddle_tpu.optimizer import AdamW

    cfg = DiTConfig.tiny() if args.size == "tiny" else DiTConfig(
        input_size=32, patch_size=4, hidden_size=384, num_hidden_layers=12,
        num_attention_heads=6, num_classes=1000)
    paddle.seed(args.seed)
    model = DiT(cfg)
    diffusion = GaussianDiffusion()

    mesh = dist.create_mesh(**parse_mesh(args.mesh)) if args.mesh else None

    def build_step():
        opt = AdamW(learning_rate=args.lr,
                    parameters=model.parameters(), weight_decay=0.0)
        return paddle.jit.TrainStep(
            model, opt,
            loss_fn=lambda m, x, t, y: diffusion.training_loss(m, x, t, y),
            accumulate_steps=args.accumulate_steps)

    def batches():
        i = 0
        while True:
            yield synthetic_dit_batch(args.batch_size, cfg,
                                      seed=args.seed + i)
            i += 1

    gen = batches()

    if mesh is not None:
        with dist.use_mesh(mesh):
            # DP-shard the batch; model params replicated (DiT-tiny fits) —
            # 'mp' shards the attention/MLP weights when divisible
            from paddle_tpu.distributed.mesh import (Replicate, Shard,
                                                     shard_tensor)
            names = mesh.dim_names
            for lname, prm in model.named_parameters():
                placements = [Replicate() for _ in names]
                if prm._value.ndim == 2 and "mp" in names and \
                        mesh.get_dim_size("mp") > 1 and \
                        prm._value.shape[1] % mesh.get_dim_size("mp") == 0:
                    placements[names.index("mp")] = Shard(1)
                sh = shard_tensor(prm, mesh, placements)
                prm._value = sh._value
                prm.dist_attr = sh.dist_attr
            step = build_step()
            pl = [dist.Replicate() for _ in names]
            if "dp" in names:
                pl[names.index("dp")] = dist.Shard(0)

            def sharded_step(x, t, y):
                x = dist.shard_tensor(x, mesh, pl)
                t = dist.shard_tensor(t, mesh, pl)
                y = dist.shard_tensor(y, mesh, pl)
                return step(x, t, y)

            loss = run_train(sharded_step,
                             (next(gen) for _ in iter(int, 1)),
                             args.steps, args.log_every)
    else:
        step = build_step()
        loss = run_train(lambda *b: step(*b),
                         (next(gen) for _ in iter(int, 1)),
                         args.steps, args.log_every)

    if args.sample_after:
        y = paddle.to_tensor(np.arange(min(2, cfg.num_classes),
                                       dtype=np.int32))
        img = diffusion.ddim_sample(model, batch_size=y.shape[0], y=y,
                                    num_steps=10, seed=args.seed)
        print(f"sampled {tuple(img.shape)}", flush=True)

    if args.save:
        paddle.save(model.state_dict(), args.save)
    print(f"final loss: {loss:.4f}", flush=True)
    return RecipeResult(final_loss=loss, steps=args.steps)


if __name__ == "__main__":
    main()
