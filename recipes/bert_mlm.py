#!/usr/bin/env python
"""BERT MLM fine-tune — north-star config #1 (single chip).

≙ BASELINE.json configs[0] / SURVEY.md §6 + §7 step 4: tokenized data →
DataLoader → BertForMaskedLM → AdamW → one compiled TrainStep per batch.

    python recipes/bert_mlm.py --steps 50                 # synthetic
    python recipes/bert_mlm.py --data corpus.txt --size base
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from recipes.common import RecipeResult, run_train, std_parser, \
    token_source  # noqa: E402


def main(argv=None):
    p = std_parser("BERT MLM fine-tune (single chip)")
    p.add_argument("--size", choices=["tiny", "base"], default="base")
    args = p.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.io import DataLoader
    from paddle_tpu.models.bert import BertConfig, BertForMaskedLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.optimizer.lr import LinearWarmup
    from paddle_tpu.text import ByteTokenizer, MLMBlockDataset

    cfg = BertConfig.base() if args.size == "base" else BertConfig.tiny()
    paddle.seed(args.seed)
    model = BertForMaskedLM(cfg)

    tok = ByteTokenizer()
    src = token_source(args, min(cfg.vocab_size, tok.vocab_size))
    ds = MLMBlockDataset(src, args.seq_len, mask_id=tok.mask_id,
                         vocab_size=min(cfg.vocab_size, tok.vocab_size),
                         seed=args.seed)
    loader = DataLoader(ds, batch_size=args.batch_size, shuffle=True,
                        drop_last=True)

    sched = LinearWarmup(args.lr, warmup_steps=min(10, args.steps),
                         start_lr=0.0, end_lr=args.lr)
    opt = AdamW(learning_rate=sched, parameters=model.parameters(),
                weight_decay=0.01)
    step = paddle.jit.TrainStep(
        model, opt, loss_fn=lambda m, x, y: m(x, labels=y)[0],
        accumulate_steps=args.accumulate_steps)

    def step_and_sched(x, y):
        loss = step(paddle.to_tensor(x), paddle.to_tensor(y))
        sched.step()
        return loss

    final = run_train(step_and_sched, loader, args.steps, args.log_every)
    if args.save:
        paddle.save(model.state_dict(), args.save)
        print(f"saved {args.save}")
    return RecipeResult(final, args.steps)


if __name__ == "__main__":
    r = main()
    print(f"final loss {r.final_loss:.4f}")
