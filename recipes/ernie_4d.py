#!/usr/bin/env python
"""ERNIE-4.5-style pretraining on a 4D hybrid mesh — north-star config #3
(BASELINE.json configs[2] / SURVEY.md §6): dp x mp x sharding (x sep)
expressed as ONE GSPMD mesh over `shard_ernie` placements.

    python recipes/ernie_4d.py --steps 10                    # synthetic, 1 dev
    python recipes/ernie_4d.py --mesh dp=2,mp=2,sharding=2   # 8-dev CPU mesh
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from recipes.common import RecipeResult, run_train, std_parser, \
    token_source  # noqa: E402
from recipes.llama_pretrain import parse_mesh  # noqa: E402


def main(argv=None):
    p = std_parser("ERNIE pretraining (MLM + SOP) on a 4D hybrid mesh")
    p.add_argument("--size", choices=["tiny", "base"], default="tiny")
    p.add_argument("--mesh", type=str, default=None,
                   help="e.g. dp=2,mp=2,sharding=2")
    args = p.parse_args(argv)

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models.ernie import (ErnieConfig, ErnieForPretraining,
                                         shard_ernie,
                                         synthetic_ernie_batch)
    from paddle_tpu.optimizer import AdamW

    cfg = ErnieConfig.tiny() if args.size == "tiny" else ErnieConfig.base()
    paddle.seed(args.seed)
    model = ErnieForPretraining(cfg)

    mesh = dist.create_mesh(**parse_mesh(args.mesh)) if args.mesh else None

    def build_step():
        opt = AdamW(learning_rate=args.lr,
                    parameters=model.parameters(), weight_decay=0.01)
        return paddle.jit.TrainStep(
            model, opt,
            loss_fn=lambda m, ids, lbl, sop: m(ids, labels=lbl,
                                               sop_labels=sop)[0],
            accumulate_steps=args.accumulate_steps)

    def batches():
        i = 0
        while True:
            yield synthetic_ernie_batch(args.batch_size, args.seq_len,
                                        cfg.vocab_size,
                                        seed=args.seed + i)
            i += 1

    gen = batches()

    if mesh is not None:
        with dist.use_mesh(mesh):
            shard_ernie(model, mesh)
            step = build_step()
            pl = [dist.Replicate() for _ in mesh.dim_names]
            if "dp" in mesh.dim_names:
                pl[mesh.dim_names.index("dp")] = dist.Shard(0)

            def sharded_step(ids, lbl, sop):
                ids = dist.shard_tensor(ids, mesh, pl)
                lbl = dist.shard_tensor(lbl, mesh, pl)
                sop = dist.shard_tensor(sop, mesh, pl)
                return step(ids, lbl, sop)

            loss = run_train(sharded_step,
                             (next(gen) for _ in iter(int, 1)),
                             args.steps, args.log_every)
    else:
        step = build_step()
        loss = run_train(lambda *b: step(*b),
                         (next(gen) for _ in iter(int, 1)),
                         args.steps, args.log_every)

    if args.save:
        paddle.save(model.state_dict(), args.save)
    print(f"final loss: {loss:.4f}", flush=True)
    return RecipeResult(final_loss=loss, steps=args.steps)


if __name__ == "__main__":
    main()
