#!/usr/bin/env python
"""Llama causal-LM pretraining — north-star config #2 (single chip → DP,
the bench.py shape). ≙ BASELINE.json configs[1] / SURVEY.md §6.

    python recipes/llama_pretrain.py --steps 20                # synthetic
    python recipes/llama_pretrain.py --size bench --recompute \
        --accumulate-steps 4
    python recipes/llama_pretrain.py --mesh dp=2,sharding=4    # 8-dev CPU
    python recipes/llama_pretrain.py --steps 2 --size tiny --resume-drill

`--mesh` shards the step over a device mesh (GSPMD; batch on dp,
ZeRO on sharding, Megatron placements on mp). `--resume-drill` runs the
durable-checkpoint save->corrupt->resume drill (docs/checkpointing.md)
and prints its telemetry snapshot.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from recipes.common import RecipeResult, run_train, std_parser, \
    token_source  # noqa: E402


def run_resume_drill(model, optimizer, ckpt_dir):
    """Save -> corrupt -> resume drill (docs/checkpointing.md): commit
    two checkpoints through the atomic protocol, verify both, flip
    bytes in the newest one's shards, and prove `ElasticManager.resume`
    quarantines it and falls back — then print the telemetry a real
    incident would leave behind (as llama_serve.py does for serving)."""
    import paddle_tpu.observability as telemetry
    from paddle_tpu.distributed.checkpoint import verify_checkpoint
    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    from paddle_tpu.utils.faults import flip_ocdbt_shards

    telemetry.enable()
    print("--- durable-checkpoint resume drill ---")
    em = ElasticManager(ckpt_dir, save_interval_steps=1,
                        sleep=lambda _: None)
    em.save(0, model, optimizer)
    em.save(1, model, optimizer)
    for step in (0, 1):
        res = verify_checkpoint(os.path.join(ckpt_dir, f"step_{step}"),
                                rehash=True)
        print(f"verify step_{step}: ok={res.ok} "
              f"({res.arrays_checked} arrays re-hashed)")
    # flip one byte in every OCDBT data file of the newest checkpoint's
    # model group — a silent disk corruption, .done marker still valid
    n = flip_ocdbt_shards(os.path.join(ckpt_dir, "step_1"))
    print(f"corrupted step_1 (flipped bytes in {n} model shards)")
    start = em.resume(model, optimizer)
    quarantined = sorted(n for n in os.listdir(ckpt_dir)
                         if n.endswith(".corrupt"))
    print(f"resume fell back to start step {start} "
          f"(quarantined: {quarantined})")
    assert start == 1 and quarantined == ["step_1.corrupt"], (
        start, quarantined)
    print("--- checkpoint telemetry (Prometheus text exposition) ---")
    for line in telemetry.to_prometheus().splitlines():
        if "pdt_checkpoint" in line:
            print(line)
    print("--- end drill ---")


def parse_mesh(spec: str):
    axes = {}
    for part in spec.split(","):
        k, v = part.split("=")
        axes[k.strip()] = int(v)
    return axes


def main(argv=None):
    p = std_parser("Llama causal-LM pretraining")
    p.add_argument("--size", choices=["tiny", "small", "bench"],
                   default="small")
    p.add_argument("--recompute", action="store_true")
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--mesh", type=str, default=None,
                   help="e.g. dp=2,sharding=2,mp=2")
    p.add_argument("--resume-drill", action="store_true",
                   help="after training, run the save->corrupt->resume "
                        "durability drill and print its telemetry")
    args = p.parse_args(argv)

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.io import DataLoader
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, \
        shard_llama
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.text import LMBlockDataset

    if args.size == "bench":
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_hidden_layers=16,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=2048)
    elif args.size == "small":
        cfg = LlamaConfig.small()
    else:
        cfg = LlamaConfig.tiny()
    cfg.recompute = args.recompute

    paddle.seed(args.seed)
    model = LlamaForCausalLM(cfg)
    if args.bf16:
        model.to(dtype="bfloat16")

    src = token_source(args, cfg.vocab_size)
    ds = LMBlockDataset(src, args.seq_len)
    loader = DataLoader(ds, batch_size=args.batch_size, shuffle=True,
                        drop_last=True)

    mesh = None
    if args.mesh:
        mesh = dist.create_mesh(**parse_mesh(args.mesh))

    def build_step():
        opt = AdamW(learning_rate=args.lr,
                    parameters=model.parameters(), weight_decay=0.01,
                    multi_precision=args.bf16)
        return paddle.jit.TrainStep(
            model, opt, loss_fn=lambda m, x, y: m(x, labels=y)[0],
            accumulate_steps=args.accumulate_steps)

    if mesh is not None:
        with dist.use_mesh(mesh):
            shard_llama(model, mesh)
            step = build_step()
            pl = [dist.Shard(0)] + [dist.Replicate()] * (
                len(mesh.dim_names) - 1)

            def step_fn(x, y):
                return step(
                    dist.shard_tensor(paddle.to_tensor(x), mesh, pl),
                    dist.shard_tensor(paddle.to_tensor(y), mesh, pl))
            final = run_train(step_fn, loader, args.steps, args.log_every)
    else:
        step = build_step()

        def step_fn(x, y):
            return step(paddle.to_tensor(x), paddle.to_tensor(y))
        final = run_train(step_fn, loader, args.steps, args.log_every)

    if args.save:
        paddle.save(model.state_dict(), args.save)
        print(f"saved {args.save}")
    if args.resume_drill:
        import tempfile
        opt = AdamW(learning_rate=args.lr,
                    parameters=model.parameters(), weight_decay=0.01)
        with tempfile.TemporaryDirectory(prefix="pdt_ckpt_drill_") as d:
            run_resume_drill(model, opt, d)
    return RecipeResult(final, args.steps)


if __name__ == "__main__":
    r = main()
    print(f"final loss {r.final_loss:.4f}")
