"""Shared recipe plumbing: argument parsing, data sources, train loop.

≙ the reference's runnable configs (BASELINE.json north-star workloads,
SURVEY.md §6): each recipe is `config dataclass + main()` over
TrainStep/hapi, runnable in one command with synthetic data by default
(offline image) or `--data file.txt|file.bin` for real tokens.
"""
from __future__ import annotations

import argparse
import time
from dataclasses import dataclass

import numpy as np


def std_parser(desc: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=desc)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--data", type=str, default=None,
                   help=".txt or .bin token file; default = synthetic")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=5)
    p.add_argument("--accumulate-steps", type=int, default=1)
    p.add_argument("--save", type=str, default=None,
                   help="checkpoint path to save at the end")
    return p


def token_source(args, vocab_size: int):
    from paddle_tpu.text import FileTokens, SyntheticTokens
    if args.data:
        src = FileTokens(args.data)
        if src.vocab_size > vocab_size:
            raise ValueError(
                f"data has ids up to {src.vocab_size}, model vocab is "
                f"{vocab_size}")
        return src
    need = args.batch_size * (args.seq_len + 1) * max(args.steps, 4)
    return SyntheticTokens(vocab_size, need, seed=args.seed)


def run_train(step_fn, loader, steps: int, log_every: int) -> float:
    """Drive `steps` train steps from an (endlessly cycled) loader;
    returns the final loss."""
    import itertools
    it = itertools.cycle(loader)
    loss = float("nan")
    t0 = time.perf_counter()
    for i in range(steps):
        batch = next(it)
        loss = float(step_fn(*batch))
        if log_every and (i % log_every == 0 or i == steps - 1):
            dt = time.perf_counter() - t0
            print(f"step {i:4d}  loss {loss:.4f}  "
                  f"({dt / (i + 1):.3f}s/step)", flush=True)
    return loss


@dataclass
class RecipeResult:
    final_loss: float
    steps: int
