#!/usr/bin/env python
"""MoE causal-LM training — north-star config #5 (DeepSeekMoE/Qwen2-MoE
style expert parallelism). ≙ BASELINE.json configs[4] / SURVEY.md §6.

    python recipes/moe_train.py --steps 10                    # synthetic
    python recipes/moe_train.py --mesh dp=2,ep=4 --dropless   # 8-dev CPU
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from recipes.common import RecipeResult, run_train, std_parser, \
    token_source  # noqa: E402
from recipes.llama_pretrain import parse_mesh  # noqa: E402


def main(argv=None):
    p = std_parser("MoE causal-LM training (expert parallel)")
    p.add_argument("--size", choices=["tiny", "small"], default="tiny")
    p.add_argument("--dropless", action="store_true")
    p.add_argument("--mesh", type=str, default=None,
                   help="e.g. dp=2,ep=4")
    args = p.parse_args(argv)

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.io import DataLoader
    from paddle_tpu.models.moe import (MoEConfig, MoEForCausalLM,
                                       shard_moe_model)
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.text import LMBlockDataset

    cfg = MoEConfig.tiny() if args.size == "tiny" else MoEConfig.small()
    cfg.dropless = args.dropless
    paddle.seed(args.seed)
    model = MoEForCausalLM(cfg)

    src = token_source(args, cfg.vocab_size)
    ds = LMBlockDataset(src, args.seq_len)
    loader = DataLoader(ds, batch_size=args.batch_size, shuffle=True,
                        drop_last=True)

    mesh = dist.create_mesh(**parse_mesh(args.mesh)) if args.mesh else None

    def build_step():
        opt = AdamW(learning_rate=args.lr,
                    parameters=model.parameters(), weight_decay=0.01)
        return paddle.jit.TrainStep(
            model, opt, loss_fn=lambda m, x, y: m(x, labels=y)[0],
            accumulate_steps=args.accumulate_steps)

    if mesh is not None:
        with dist.use_mesh(mesh):
            shard_moe_model(model, mesh)
            step = build_step()
            pl = [dist.Shard(0)] + [dist.Replicate()] * (
                len(mesh.dim_names) - 1)

            def step_fn(x, y):
                return step(
                    dist.shard_tensor(paddle.to_tensor(x), mesh, pl),
                    dist.shard_tensor(paddle.to_tensor(y), mesh, pl))
            final = run_train(step_fn, loader, args.steps, args.log_every)
    else:
        step = build_step()

        def step_fn(x, y):
            return step(paddle.to_tensor(x), paddle.to_tensor(y))
        final = run_train(step_fn, loader, args.steps, args.log_every)

    if args.save:
        paddle.save(model.state_dict(), args.save)
        print(f"saved {args.save}")
    return RecipeResult(final, args.steps)


if __name__ == "__main__":
    r = main()
    print(f"final loss {r.final_loss:.4f}")
