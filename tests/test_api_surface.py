"""API-surface regression guard: the documented namespaces must keep
exporting their key names (a rename or dropped import fails here, not in
a user's script). ≙ the reference's API-signature CI check
(«tools/check_api_compatible.py» [U])."""
import importlib

import pytest

import paddle_tpu as paddle

# (module, required names) — spot anchors per namespace, not exhaustive
SURFACE = {
    "paddle_tpu": [
        "to_tensor", "arange", "matmul", "einsum", "where", "concat",
        "grad", "no_grad", "save", "load", "seed", "jit", "flops",
        "summary", "block_diag", "vecdot", "gammainc", "isposinf",
        "diagonal_scatter", "select_scatter", "slice_scatter",
        "cartesian_prod", "float_power", "cumulative_trapezoid",
    ],
    "paddle_tpu.nn": [
        "Layer", "Linear", "Conv2D", "LSTM", "MultiHeadAttention",
        "Transformer", "RMSNorm", "MaxUnPool2D", "FractionalMaxPool2D",
        "AdaptiveLogSoftmaxWithLoss", "Unflatten",
    ],
    "paddle_tpu.nn.functional": [
        "cross_entropy", "scaled_dot_product_attention", "flash_attention",
        "flash_attn_unpadded", "flash_attn_qkvpacked", "max_unpool2d",
        "fractional_max_pool2d", "rms_norm", "masked_multihead_attention",
    ],
    "paddle_tpu.optimizer": [
        "SGD", "AdamW", "Lamb", "NAdam", "RAdam", "Rprop", "ASGD",
        "LBFGS",
    ],
    "paddle_tpu.optimizer.lr": [
        "LRScheduler", "CosineAnnealingDecay", "OneCycleLR", "CyclicLR",
        "ReduceOnPlateau",
    ],
    "paddle_tpu.distribution": [
        "Normal", "Categorical", "MultivariateNormal", "StudentT",
        "Cauchy", "Binomial", "Independent", "TransformedDistribution",
        "ChainTransform", "StackTransform", "kl_divergence",
    ],
    "paddle_tpu.distributed": [
        "all_reduce", "all_gather", "reduce_scatter", "alltoall",
        "shard_tensor", "reshard", "create_mesh", "spawn",
        "init_parallel_env", "DataParallel",
    ],
    "paddle_tpu.distributed.fleet": [
        "init", "distributed_model", "distributed_optimizer",
        "HybridCommunicateGroup", "DataParallel", "PipelineParallel",
    ],
    "paddle_tpu.geometric": [
        "segment_sum", "segment_mean", "send_u_recv", "send_ue_recv",
        "sample_neighbors", "reindex_graph",
    ],
    "paddle_tpu.vision": [
        "resnet50", "vgg16", "mobilenet_v2", "densenet121", "googlenet",
        "shufflenet_v2_x1_0", "LeNet",
    ],
    "paddle_tpu.vision.ops": [
        "nms", "roi_align", "roi_pool", "deform_conv2d", "box_iou",
        "DeformConv2D",
    ],
    "paddle_tpu.vision.transforms": [
        "Compose", "Resize", "ColorJitter", "RandomResizedCrop",
        "RandomErasing", "adjust_brightness",
    ],
    "paddle_tpu.static": [
        "Program", "program_guard", "data", "Executor",
        "default_main_program", "default_startup_program", "nn",
        "save_inference_model",
    ],
    "paddle_tpu.text": [
        "BPETokenizer", "ByteTokenizer", "viterbi_decode",
        "ViterbiDecoder", "LMBlockDataset",
    ],
    "paddle_tpu.incubate.nn": [
        "FusedLinear", "FusedMultiHeadAttention",
        "FusedTransformerEncoderLayer", "FusedRMSNorm",
    ],
    "paddle_tpu.incubate.nn.functional": [
        "swiglu", "fused_linear", "fused_rms_norm", "paged_attention",
        "flash_attention_varlen", "fused_rotary_position_embedding",
    ],
    "paddle_tpu.incubate.autograd": [
        "vjp", "jvp", "jacobian", "hessian", "grad",
    ],
    "paddle_tpu.amp": ["auto_cast", "GradScaler", "decorate"],
    "paddle_tpu.amp.debugging": [
        "check_numerics", "collect_operator_stats", "TensorCheckerConfig",
    ],
    "paddle_tpu.utils": ["dlpack", "unique_name", "require_version",
                         "get_flags", "set_flags"],
    "paddle_tpu.sparse": ["sparse_coo_tensor", "sparse_csr_tensor",
                          "matmul", "masked_matmul", "mv", "addmm",
                          "coalesce", "sin", "tanh", "cast", "nn"],
    "paddle_tpu.sparse.nn": ["ReLU", "Softmax", "Conv3D", "SubmConv3D",
                             "BatchNorm", "MaxPool3D", "functional"],
    "paddle_tpu.linalg": ["svd", "qr", "lu", "lu_solve", "ormqr",
                          "cholesky_inverse", "matrix_transpose"],
    "paddle_tpu.metric": ["Accuracy", "Precision", "Recall", "Auc"],
    "paddle_tpu.profiler": ["Profiler", "RecordEvent", "make_scheduler"],
    "paddle_tpu.callbacks": ["EarlyStopping", "ModelCheckpoint",
                             "VisualDL"],
}


@pytest.mark.parametrize("module,names", SURFACE.items(),
                         ids=list(SURFACE))
def test_surface(module, names):
    mod = importlib.import_module(module)
    missing = [n for n in names if not hasattr(mod, n)]
    assert not missing, f"{module} missing: {missing}"


def test_tensor_method_surface():
    t = paddle.to_tensor([1.0, 2.0])
    for m in ("reshape", "matmul", "sum", "backward", "numpy", "item",
              "astype", "detach", "clone", "dim", "nelement",
              "element_size", "register_hook", "isposinf", "vecdot"):
        assert hasattr(t, m), m


class TestTensorMethodAudit:
    """Round-4 Tensor-method audit: the 211 commonly-probed methods must
    all exist (the 15 that were missing are attached + tested)."""

    def test_round4_method_closers(self):
        import numpy as np
        t = paddle.to_tensor(np.eye(3, dtype=np.float32))
        for m in ["arccos", "arcsin", "arctan", "arccosh", "arcsinh",
                  "arctanh", "fill_diagonal_", "inverse", "is_tensor",
                  "logit", "lu", "multinomial", "reverse", "slice",
                  "softmax", "stack", "tensordot", "shard_index",
                  "pin_memory"]:
            assert hasattr(t, m), m
        # aliases agree with their canonical spellings
        x = paddle.to_tensor(np.array([0.5], np.float32))
        np.testing.assert_allclose(np.asarray(x.arccos()._value),
                                   np.asarray(x.acos()._value))
        np.testing.assert_allclose(
            np.asarray(t.reverse(axis=0)._value),
            np.asarray(t._value)[::-1])
        # fill_diagonal_ with offsets (review: OOB-drop accident fixed)
        d = np.asarray(paddle.to_tensor(
            np.zeros((4, 4), np.float32)).fill_diagonal_(
                2.0, offset=-2)._value)
        np.testing.assert_allclose(d, np.diag([2.0] * 2, -2))
        # non-square + wrap + N-D (round-4 review)
        ns = np.asarray(paddle.to_tensor(
            np.zeros((3, 5), np.float32)).fill_diagonal_(
                1.0, offset=2)._value)
        ref = np.zeros((3, 5), np.float32)
        np.fill_diagonal(ref[:, 2:], 1.0)
        np.testing.assert_allclose(ns, ref)
        w = np.asarray(paddle.to_tensor(
            np.zeros((6, 2), np.float32)).fill_diagonal_(
                1.0, wrap=True)._value)
        refw = np.zeros((6, 2), np.float32)
        np.fill_diagonal(refw, 1.0, wrap=True)
        np.testing.assert_allclose(w, refw)
        nd = np.asarray(paddle.to_tensor(
            np.zeros((3, 3, 3), np.float32)).fill_diagonal_(1.0)._value)
        assert nd.sum() == 3 and nd[2, 2, 2] == 1
        # logit eps clamps (reference contract)
        lg = np.asarray(paddle.to_tensor(
            np.array([0.0], np.float32)).logit(eps=1e-6)._value)
        assert np.isfinite(lg).all()
        # softmax method == functional
        sm = np.asarray(t.softmax(-1)._value)
        np.testing.assert_allclose(sm.sum(-1), 1.0, rtol=1e-6)
