"""API-surface regression guard: the documented namespaces must keep
exporting their key names (a rename or dropped import fails here, not in
a user's script). ≙ the reference's API-signature CI check
(«tools/check_api_compatible.py» [U])."""
import importlib

import pytest

import paddle_tpu as paddle

# (module, required names) — spot anchors per namespace, not exhaustive
SURFACE = {
    "paddle_tpu": [
        "to_tensor", "arange", "matmul", "einsum", "where", "concat",
        "grad", "no_grad", "save", "load", "seed", "jit", "flops",
        "summary", "block_diag", "vecdot", "gammainc", "isposinf",
        "diagonal_scatter", "select_scatter", "slice_scatter",
        "cartesian_prod", "float_power", "cumulative_trapezoid",
    ],
    "paddle_tpu.nn": [
        "Layer", "Linear", "Conv2D", "LSTM", "MultiHeadAttention",
        "Transformer", "RMSNorm", "MaxUnPool2D", "FractionalMaxPool2D",
        "AdaptiveLogSoftmaxWithLoss", "Unflatten",
    ],
    "paddle_tpu.nn.functional": [
        "cross_entropy", "scaled_dot_product_attention", "flash_attention",
        "flash_attn_unpadded", "flash_attn_qkvpacked", "max_unpool2d",
        "fractional_max_pool2d", "rms_norm", "masked_multihead_attention",
    ],
    "paddle_tpu.optimizer": [
        "SGD", "AdamW", "Lamb", "NAdam", "RAdam", "Rprop", "ASGD",
        "LBFGS",
    ],
    "paddle_tpu.optimizer.lr": [
        "LRScheduler", "CosineAnnealingDecay", "OneCycleLR", "CyclicLR",
        "ReduceOnPlateau",
    ],
    "paddle_tpu.distribution": [
        "Normal", "Categorical", "MultivariateNormal", "StudentT",
        "Cauchy", "Binomial", "Independent", "TransformedDistribution",
        "ChainTransform", "StackTransform", "kl_divergence",
    ],
    "paddle_tpu.distributed": [
        "all_reduce", "all_gather", "reduce_scatter", "alltoall",
        "shard_tensor", "reshard", "create_mesh", "spawn",
        "init_parallel_env", "DataParallel",
    ],
    "paddle_tpu.distributed.fleet": [
        "init", "distributed_model", "distributed_optimizer",
        "HybridCommunicateGroup", "DataParallel", "PipelineParallel",
    ],
    "paddle_tpu.geometric": [
        "segment_sum", "segment_mean", "send_u_recv", "send_ue_recv",
        "sample_neighbors", "reindex_graph",
    ],
    "paddle_tpu.vision": [
        "resnet50", "vgg16", "mobilenet_v2", "densenet121", "googlenet",
        "shufflenet_v2_x1_0", "LeNet",
    ],
    "paddle_tpu.vision.ops": [
        "nms", "roi_align", "roi_pool", "deform_conv2d", "box_iou",
        "DeformConv2D",
    ],
    "paddle_tpu.vision.transforms": [
        "Compose", "Resize", "ColorJitter", "RandomResizedCrop",
        "RandomErasing", "adjust_brightness",
    ],
    "paddle_tpu.static": [
        "Program", "program_guard", "data", "Executor",
        "default_main_program", "default_startup_program", "nn",
        "save_inference_model",
    ],
    "paddle_tpu.text": [
        "BPETokenizer", "ByteTokenizer", "viterbi_decode",
        "ViterbiDecoder", "LMBlockDataset",
    ],
    "paddle_tpu.incubate.nn": [
        "FusedLinear", "FusedMultiHeadAttention",
        "FusedTransformerEncoderLayer", "FusedRMSNorm",
    ],
    "paddle_tpu.incubate.nn.functional": [
        "swiglu", "fused_linear", "fused_rms_norm", "paged_attention",
        "flash_attention_varlen", "fused_rotary_position_embedding",
    ],
    "paddle_tpu.incubate.autograd": [
        "vjp", "jvp", "jacobian", "hessian", "grad",
    ],
    "paddle_tpu.amp": ["auto_cast", "GradScaler", "decorate"],
    "paddle_tpu.amp.debugging": [
        "check_numerics", "collect_operator_stats", "TensorCheckerConfig",
    ],
    "paddle_tpu.utils": ["dlpack", "unique_name", "require_version",
                         "get_flags", "set_flags"],
    "paddle_tpu.sparse": ["sparse_coo_tensor", "sparse_csr_tensor",
                          "matmul", "masked_matmul", "mv", "addmm",
                          "coalesce", "sin", "tanh", "cast", "nn"],
    "paddle_tpu.sparse.nn": ["ReLU", "Softmax", "Conv3D", "SubmConv3D",
                             "BatchNorm", "MaxPool3D", "functional"],
    "paddle_tpu.linalg": ["svd", "qr", "lu", "lu_solve", "ormqr",
                          "cholesky_inverse", "matrix_transpose"],
    "paddle_tpu.metric": ["Accuracy", "Precision", "Recall", "Auc"],
    "paddle_tpu.profiler": ["Profiler", "RecordEvent", "make_scheduler"],
    "paddle_tpu.callbacks": ["EarlyStopping", "ModelCheckpoint",
                             "VisualDL"],
}


@pytest.mark.parametrize("module,names", SURFACE.items(),
                         ids=list(SURFACE))
def test_surface(module, names):
    mod = importlib.import_module(module)
    missing = [n for n in names if not hasattr(mod, n)]
    assert not missing, f"{module} missing: {missing}"


def test_tensor_method_surface():
    t = paddle.to_tensor([1.0, 2.0])
    for m in ("reshape", "matmul", "sum", "backward", "numpy", "item",
              "astype", "detach", "clone", "dim", "nelement",
              "element_size", "register_hook", "isposinf", "vecdot"):
        assert hasattr(t, m), m
