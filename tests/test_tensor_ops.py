"""Op forward/grad parity vs NumPy (OpTest style, SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_forward, check_grad

rng = np.random.default_rng(42)


class TestElementwise:
    def test_add(self):
        a, b = rng.normal(size=(3, 4)), rng.normal(size=(3, 4))
        check_forward(paddle.add, np.add, [a, b])
        check_grad(paddle.add, np.add, [a, b])

    def test_add_broadcast(self):
        a, b = rng.normal(size=(3, 4)), rng.normal(size=(4,))
        check_forward(paddle.add, np.add, [a, b])

    def test_sub_mul_div(self):
        a = rng.normal(size=(2, 5))
        b = rng.normal(size=(2, 5)) + 3.0
        check_forward(paddle.subtract, np.subtract, [a, b])
        check_forward(paddle.multiply, np.multiply, [a, b])
        check_forward(paddle.divide, np.divide, [a, b])
        check_grad(paddle.multiply, np.multiply, [a, b])
        check_grad(paddle.divide, np.divide, [a, b])

    def test_scalar_ops(self):
        x = paddle.to_tensor([1.0, 2.0])
        assert np.allclose((x + 1.5).numpy(), [2.5, 3.5])
        assert np.allclose((2.0 * x).numpy(), [2.0, 4.0])
        assert np.allclose((1.0 / x).numpy(), [1.0, 0.5])
        assert (x + 1).dtype == np.float32  # no promotion from python scalar

    def test_unary(self):
        x = rng.uniform(0.1, 2.0, size=(3, 3))
        for name in ["exp", "log", "sqrt", "tanh", "sin", "cos", "abs",
                     "sigmoid", "square", "rsqrt", "log1p", "floor", "ceil"]:
            np_fn = {"sigmoid": lambda v: 1 / (1 + np.exp(-v)),
                     "square": np.square,
                     "rsqrt": lambda v: 1 / np.sqrt(v)}.get(
                name, getattr(np, name, None))
            check_forward(getattr(paddle, name), np_fn, [x])

    def test_unary_grads(self):
        x = rng.uniform(0.5, 1.5, size=(2, 3))
        check_grad(paddle.exp, np.exp, [x])
        check_grad(paddle.tanh, np.tanh, [x])
        check_grad(paddle.sqrt, np.sqrt, [x])

    def test_pow_maximum_minimum(self):
        a = rng.uniform(0.5, 2, (3, 3))
        b = rng.uniform(0.5, 2, (3, 3))
        check_forward(paddle.pow, np.power, [a, b])
        check_forward(paddle.maximum, np.maximum, [a, b])
        check_forward(paddle.minimum, np.minimum, [a, b])

    def test_clip(self):
        x = rng.normal(size=(4, 4))
        check_forward(lambda t: paddle.clip(t, -0.5, 0.5),
                      lambda v: np.clip(v, -0.5, 0.5), [x])


class TestReduce:
    def test_sum_mean(self):
        x = rng.normal(size=(3, 4, 5))
        check_forward(paddle.sum, np.sum, [x])
        check_forward(lambda t: paddle.sum(t, axis=1),
                      lambda v: np.sum(v, axis=1), [x])
        check_forward(lambda t: paddle.mean(t, axis=[0, 2], keepdim=True),
                      lambda v: np.mean(v, axis=(0, 2), keepdims=True), [x])
        check_grad(paddle.sum, np.sum, [x])
        check_grad(lambda t: paddle.mean(t, axis=1),
                   lambda v: np.mean(v, axis=1), [x])

    def test_max_min_prod(self):
        x = rng.normal(size=(3, 4))
        check_forward(lambda t: paddle.max(t, axis=1),
                      lambda v: np.max(v, axis=1), [x])
        check_forward(lambda t: paddle.min(t, axis=0),
                      lambda v: np.min(v, axis=0), [x])
        check_forward(lambda t: paddle.prod(t, axis=1),
                      lambda v: np.prod(v, axis=1), [x])

    def test_logsumexp_std_var(self):
        x = rng.normal(size=(3, 4))
        from scipy.special import logsumexp as np_lse
        check_forward(lambda t: paddle.logsumexp(t, axis=1),
                      lambda v: np_lse(v, axis=1), [x])
        check_forward(lambda t: paddle.std(t, axis=1),
                      lambda v: np.std(v, axis=1, ddof=1), [x])
        check_forward(lambda t: paddle.var(t, axis=1, unbiased=False),
                      lambda v: np.var(v, axis=1), [x])

    def test_cumsum_cumprod(self):
        x = rng.normal(size=(3, 4))
        check_forward(lambda t: paddle.cumsum(t, axis=1),
                      lambda v: np.cumsum(v, axis=1), [x])
        check_forward(lambda t: paddle.cumprod(t, dim=0),
                      lambda v: np.cumprod(v, axis=0), [x])


class TestMatmul:
    def test_matmul(self):
        a, b = rng.normal(size=(3, 4)), rng.normal(size=(4, 5))
        check_forward(paddle.matmul, np.matmul, [a, b])
        check_grad(paddle.matmul, np.matmul, [a, b])

    def test_matmul_transpose(self):
        a, b = rng.normal(size=(4, 3)), rng.normal(size=(5, 4))
        check_forward(
            lambda x, y: paddle.matmul(x, y, transpose_x=True,
                                       transpose_y=True),
            lambda x, y: x.T @ y.T, [a, b])

    def test_bmm_einsum(self):
        a, b = rng.normal(size=(2, 3, 4)), rng.normal(size=(2, 4, 5))
        check_forward(paddle.bmm, np.matmul, [a, b])
        check_forward(lambda x, y: paddle.einsum("bij,bjk->bik", x, y),
                      np.matmul, [a, b])

    def test_dot_outer(self):
        a, b = rng.normal(size=(5,)), rng.normal(size=(5,))
        check_forward(paddle.dot, lambda x, y: np.sum(x * y), [a, b])
        check_forward(paddle.outer, np.outer, [a, b])


class TestManipulation:
    def test_reshape_transpose(self):
        x = rng.normal(size=(2, 3, 4))
        check_forward(lambda t: paddle.reshape(t, [6, 4]),
                      lambda v: v.reshape(6, 4), [x])
        check_forward(lambda t: paddle.transpose(t, [2, 0, 1]),
                      lambda v: v.transpose(2, 0, 1), [x])
        check_grad(lambda t: paddle.reshape(t, [24]),
                   lambda v: v.reshape(24), [x])

    def test_concat_stack_split(self):
        a, b = rng.normal(size=(2, 3)), rng.normal(size=(2, 3))
        check_forward(lambda x, y: paddle.concat([x, y], axis=0),
                      lambda x, y: np.concatenate([x, y], 0), [a, b])
        check_forward(lambda x, y: paddle.stack([x, y], axis=1),
                      lambda x, y: np.stack([x, y], 1), [a, b])
        x = rng.normal(size=(6, 4))
        outs = paddle.split(paddle.to_tensor(np.float32(x)), 3, axis=0)
        assert len(outs) == 3
        np.testing.assert_allclose(outs[1].numpy(), x[2:4], rtol=1e-6)
        outs = paddle.split(paddle.to_tensor(np.float32(x)), [1, 2, -1],
                            axis=0)
        assert outs[2].shape == [3, 4]

    def test_squeeze_unsqueeze_tile(self):
        x = rng.normal(size=(1, 3, 1, 4))
        check_forward(lambda t: paddle.squeeze(t, axis=0),
                      lambda v: np.squeeze(v, 0), [x])
        check_forward(lambda t: paddle.unsqueeze(t, axis=[0, 2]),
                      lambda v: np.expand_dims(np.expand_dims(v, 0), 2), [x])
        y = rng.normal(size=(2, 3))
        check_forward(lambda t: paddle.tile(t, [2, 2]),
                      lambda v: np.tile(v, (2, 2)), [y])

    def test_gather_scatter(self):
        x = rng.normal(size=(5, 3))
        idx = np.array([0, 2, 4])
        check_forward(lambda t: paddle.gather(t, paddle.to_tensor(idx)),
                      lambda v: v[idx], [x])
        upd = np.float32(rng.normal(size=(3, 3)))
        out = paddle.scatter(paddle.to_tensor(np.float32(x)),
                             paddle.to_tensor(idx),
                             paddle.to_tensor(upd))
        want = x.astype(np.float32).copy()
        want[idx] = upd
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-6)

    def test_getitem_setitem(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
        np.testing.assert_allclose(x[1].numpy(), [4, 5, 6, 7])
        np.testing.assert_allclose(x[:, 1:3].numpy(),
                                   [[1, 2], [5, 6], [9, 10]])
        x[0] = 0.0
        np.testing.assert_allclose(x[0].numpy(), [0, 0, 0, 0])

    def test_getitem_grad(self):
        x = paddle.to_tensor(np.ones((3, 4), np.float32),
                             stop_gradient=False)
        y = x[1].sum()
        y.backward()
        want = np.zeros((3, 4))
        want[1] = 1
        np.testing.assert_allclose(x.grad.numpy(), want)

    def test_where_masked_fill(self):
        x = rng.normal(size=(3, 3))
        cond = x > 0
        out = paddle.where(paddle.to_tensor(cond),
                           paddle.to_tensor(np.float32(x)),
                           paddle.to_tensor(np.zeros((3, 3), np.float32)))
        np.testing.assert_allclose(out.numpy(), np.where(cond, x, 0),
                                   rtol=1e-6)

    def test_flip_roll_pad(self):
        x = rng.normal(size=(3, 4))
        check_forward(lambda t: paddle.flip(t, axis=[1]),
                      lambda v: np.flip(v, 1), [x])
        check_forward(lambda t: paddle.roll(t, 1, axis=0),
                      lambda v: np.roll(v, 1, 0), [x])


class TestSearchSort:
    def test_argmax_argsort_topk(self):
        x = rng.normal(size=(4, 6))
        check_forward(lambda t: paddle.argmax(t, axis=1),
                      lambda v: np.argmax(v, 1), [x])
        check_forward(lambda t: paddle.argsort(t, axis=1),
                      lambda v: np.argsort(v, 1, kind="stable"), [x])
        vals, idx = paddle.topk(paddle.to_tensor(np.float32(x)), 3, axis=1)
        want = np.sort(x.astype(np.float32), 1)[:, ::-1][:, :3]
        np.testing.assert_allclose(vals.numpy(), want, rtol=1e-6)

    def test_sort_unique(self):
        x = np.array([3.0, 1.0, 2.0, 1.0])
        check_forward(paddle.sort, np.sort, [x])
        out = paddle.unique(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy(), [1.0, 2.0, 3.0])

    def test_nonzero_masked_select(self):
        x = np.array([[1.0, 0.0], [0.0, 2.0]])
        nz = paddle.nonzero(paddle.to_tensor(x))
        np.testing.assert_array_equal(nz.numpy(), [[0, 0], [1, 1]])
        ms = paddle.masked_select(paddle.to_tensor(x),
                                  paddle.to_tensor(x > 0))
        np.testing.assert_allclose(ms.numpy(), [1.0, 2.0])


class TestLinalg:
    def test_norm_det_inv(self):
        x = rng.normal(size=(3, 3)) + 3 * np.eye(3)
        check_forward(paddle.linalg.det, np.linalg.det, [x], rtol=1e-4)
        check_forward(paddle.linalg.inv, np.linalg.inv, [x], rtol=1e-4)
        check_forward(lambda t: paddle.norm(t),
                      lambda v: np.sqrt((v * v).sum()), [x])

    def test_svd_qr_cholesky(self):
        a = rng.normal(size=(4, 3))
        s_got = paddle.linalg.svdvals(paddle.to_tensor(np.float32(a)))
        s_want = np.linalg.svd(a, compute_uv=False)
        np.testing.assert_allclose(s_got.numpy(), s_want, rtol=1e-4)
        spd = a.T @ a + 3 * np.eye(3)
        l = paddle.linalg.cholesky(paddle.to_tensor(np.float32(spd)))
        np.testing.assert_allclose(l.numpy() @ l.numpy().T, spd, rtol=1e-3)

    def test_solve_eigh(self):
        a = rng.normal(size=(3, 3)) + 3 * np.eye(3)
        b = rng.normal(size=(3, 2))
        check_forward(paddle.linalg.solve, np.linalg.solve, [a, b], rtol=1e-4)
        sym = (a + a.T) / 2
        w, v = paddle.linalg.eigh(paddle.to_tensor(np.float32(sym)))
        w_want = np.linalg.eigvalsh(sym)
        np.testing.assert_allclose(np.sort(w.numpy()), np.sort(w_want),
                                   rtol=1e-4)


class TestCreation:
    def test_creation_ops(self):
        assert paddle.zeros([2, 3]).shape == [2, 3]
        # int64 canonicalizes to int32 on TPU (x64 off) — documented deviation
        assert paddle.ones([2], "int64").dtype in (np.int32, np.int64)
        assert np.allclose(paddle.full([2, 2], 7.0).numpy(), 7.0)
        assert np.allclose(paddle.arange(5).numpy(), np.arange(5))
        assert np.allclose(paddle.linspace(0, 1, 5).numpy(),
                           np.linspace(0, 1, 5))
        assert np.allclose(paddle.eye(3).numpy(), np.eye(3))
        x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert np.allclose(paddle.zeros_like(x).numpy(), 0)
        assert np.allclose(paddle.tril(x).numpy(), np.tril(x.numpy()))

    def test_dtype_semantics(self):
        assert paddle.to_tensor([1.0, 2.0]).dtype == np.float32
        assert paddle.to_tensor([1, 2]).dtype in (np.int32, np.int64)
        assert paddle.to_tensor(np.float64([1.0])).dtype == np.float32
        x32 = paddle.ones([2], "float32")
        x16 = paddle.ones([2], "bfloat16")
        assert (x32 + x16).dtype == np.float32  # promotion

    def test_one_hot(self):
        x = paddle.to_tensor([0, 2, 1])
        oh = paddle.one_hot(x, 3)
        np.testing.assert_allclose(oh.numpy(), np.eye(3)[[0, 2, 1]])


class TestRandom:
    def test_seed_reproducible(self):
        paddle.seed(123)
        a = paddle.randn([4, 4]).numpy()
        paddle.seed(123)
        b = paddle.randn([4, 4]).numpy()
        np.testing.assert_array_equal(a, b)

    def test_distributions(self):
        paddle.seed(0)
        u = paddle.uniform([1000], min=0.0, max=1.0).numpy()
        assert 0.4 < u.mean() < 0.6
        n = paddle.normal(0.0, 1.0, [1000]).numpy()
        assert abs(n.mean()) < 0.2
        r = paddle.randint(0, 10, [100]).numpy()
        assert r.min() >= 0 and r.max() < 10
        p = paddle.randperm(10).numpy()
        assert sorted(p.tolist()) == list(range(10))


class TestCheckNanInf:
    """FLAGS_check_nan_inf op-level blame (SURVEY.md §5 race/NaN row;
    VERDICT r2 'no per-op NaN blame')."""

    def test_nan_blamed_with_op_name(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor(np.array([0.0, 1.0], np.float32))
            with pytest.raises(RuntimeError, match="op 'divide'"):
                _ = x / x
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_inf_blamed(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            a = paddle.to_tensor(np.array([1.0], np.float32))
            b = paddle.to_tensor(np.array([0.0], np.float32))
            with pytest.raises(RuntimeError, match="Inf"):
                _ = a / b
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_off_by_default_no_raise(self):
        x = paddle.to_tensor(np.array([0.0], np.float32))
        y = x / x
        assert np.isnan(np.asarray(y._value)).all()
