"""Memory observability (VERDICT r3 missing #7 / next #8, weak #5/#6).

compiled_memory_stats is the CI-side source of truth (XLA buffer
assignment, backend-independent); the tests use it to PROVE the memory
claims: recompute shrinks activation residency, ZeRO placement shrinks
per-device parameter bytes, and group_sharded reports what it skipped.
"""
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.utils import memory as M


class TestCompiledMemoryStats:
    def test_basic_keys(self):
        st = M.compiled_memory_stats(
            lambda a, b: a @ b,
            jnp.zeros((64, 64), jnp.float32),
            jnp.zeros((64, 64), jnp.float32))
        if not st["available"]:
            pytest.skip("memory_analysis unavailable")
        assert st["argument_bytes"] >= 2 * 64 * 64 * 4
        assert st["output_bytes"] >= 64 * 64 * 4
        assert st["total_bytes"] > 0

    def test_recompute_reduces_activation_residency(self):
        """Per-layer jax.checkpoint inside a lax.scan over layers with
        WIDE internal activations (the transformer FFN geometry): the
        plain backward stacks every wide intermediate into the scan
        residuals, the rematerialized one stacks only the narrow layer
        inputs — compiled temp high-water must drop.

        (Deliberately scan-based: in Python-loop form XLA:CPU strips
        the optimization_barrier and CSE undoes the recompute, so loop
        -form remat shows no CPU-tier memory change; scan-form remat is
        structural in the jaxpr and backend-independent.)"""
        def layer(x, ws):
            w1, w2 = ws
            return x + jnp.tanh(x @ w1) @ w2, None  # 256->1024->256

        def chain(wstack, x, remat):
            body = jax.checkpoint(layer) if remat else layer
            out, _ = jax.lax.scan(body, x, wstack)
            return jnp.sum(out ** 2)

        ws = (jnp.zeros((8, 256, 1024), jnp.float32),
              jnp.zeros((8, 1024, 256), jnp.float32))
        x = jnp.zeros((512, 256), jnp.float32)
        plain = M.compiled_memory_stats(
            jax.grad(lambda w, v: chain(w, v, False)), ws, x)
        remat = M.compiled_memory_stats(
            jax.grad(lambda w, v: chain(w, v, True)), ws, x)
        if not plain["available"]:
            pytest.skip("memory_analysis unavailable")
        print(f"\ngrad temp bytes: plain {plain['temp_bytes']}, "
              f"remat {remat['temp_bytes']}")
        assert remat["temp_bytes"] < 0.7 * plain["temp_bytes"], (
            remat["temp_bytes"], plain["temp_bytes"])

    @pytest.mark.slow
    def test_llama_recompute_flag_reduces_memory(self):
        """The model-level recompute toggle (≙ PaddleNLP recipe
        `recompute`) measurably shrinks the train-step temp memory —
        proven on the scan-over-layers llama (LlamaForCausalLMPipe's
        no-pp path), where remat restructures the scan residuals."""
        from paddle_tpu.models.llama import (LlamaConfig,
                                             synthetic_lm_batch)
        from paddle_tpu.models.llama_pipe import LlamaForCausalLMPipe
        from paddle_tpu.optimizer import SGD

        sizes = {}
        for remat in (False, True):
            paddle.seed(0)
            cfg = LlamaConfig(vocab_size=256, hidden_size=128,
                              intermediate_size=512, num_hidden_layers=6,
                              num_attention_heads=4, num_key_value_heads=2,
                              max_position_embeddings=256)
            cfg.recompute = remat
            m = LlamaForCausalLMPipe(cfg)
            opt = SGD(learning_rate=0.1, parameters=m.parameters())
            ids, labels = synthetic_lm_batch(2, 256, cfg.vocab_size)
            step = paddle.jit.TrainStep(
                m, opt, loss_fn=lambda mm, x, y: mm(x, labels=y)[0])
            st = step.memory_analysis(ids, labels)
            if not st["available"]:
                pytest.skip("memory_analysis unavailable")
            sizes[remat] = st["temp_bytes"]
        print(f"\ntrain-step temp bytes: no-remat {sizes[False]}, "
              f"remat {sizes[True]}")
        assert sizes[True] < sizes[False], sizes


class TestShardedParamBytes:
    def test_group_sharded_shrinks_per_device_bytes(self):
        from paddle_tpu import nn
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        from paddle_tpu.optimizer import AdamW

        mesh = dist.create_mesh(sharding=8)
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(256, 512), nn.ReLU(),
                              nn.Linear(512, 256))
        before = M.sharded_param_bytes(model.parameters())
        with dist.use_mesh(mesh):
            opt = AdamW(learning_rate=1e-3,
                        parameters=model.parameters())
            group_sharded_parallel(model, opt)
        after = M.sharded_param_bytes(model.parameters())
        assert after["global_bytes"] == before["global_bytes"]
        # weight matrices shard 8-way; small biases may replicate —
        # per-device residency must still drop by at least 4x
        assert after["max_per_device"] < before["max_per_device"] / 4, (
            before, after)

    def test_skipped_params_are_reported(self):
        from paddle_tpu import nn
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        from paddle_tpu.optimizer import SGD

        mesh = dist.create_mesh(sharding=8)

        class Odd(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(256, 256)
                # 7x5: no dim divisible by 8
                self.odd = self.create_parameter((7, 5))

            def forward(self, x):
                return self.lin(x)

        paddle.seed(0)
        model = Odd()
        with dist.use_mesh(mesh):
            opt = SGD(learning_rate=0.1, parameters=model.parameters())
            with warnings.catch_warnings(record=True) as rec:
                warnings.simplefilter("always")
                group_sharded_parallel(model, opt)
        assert any("stayed replicated" in str(r.message) for r in rec)
        skipped = model._group_sharded_skipped
        assert any(sh == (7, 5) for _, sh, _ in skipped), skipped
        # the divisible Linear weight must NOT be in the skip list
        assert not any(sh == (256, 256) for _, sh, _ in skipped)


class TestTrainStepMemoryAnalysis:
    def test_keys_and_magnitude(self):
        from paddle_tpu import nn
        from paddle_tpu.optimizer import AdamW
        paddle.seed(0)
        model = nn.Linear(64, 64)
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = paddle.jit.TrainStep(
            model, opt,
            loss_fn=lambda m, x, y: ((m(x) - y) ** 2).mean())
        x = paddle.to_tensor(np.zeros((8, 64), np.float32))
        st = step.memory_analysis(x, x)
        if not st["available"]:
            pytest.skip("memory_analysis unavailable")
        # params + AdamW moments ride as arguments
        assert st["argument_bytes"] > 3 * 64 * 64 * 4
        assert st["total_bytes"] > 0


class TestProfilerMemoryColumn:
    def test_summary_has_memory_line(self):
        from paddle_tpu import profiler as prof
        p = prof.Profiler(timer_only=True, profile_memory=True)
        p.start()
        for _ in range(3):
            _ = jnp.ones((16, 16)).sum()
            p.step()
        p.stop()
        out = p.summary()
        assert "device memory" in out
