"""Deterministic chaos tests (fast tier, `chaos` marker): force every
failure branch of the serving engine on the CPU mesh via
`utils.faults.FaultInjector` — pool exhaustion mid-decode (preempt ->
requeue -> identical tokens), injected prefill failure (request fails,
engine keeps serving), deadline / queue-time expiry, transient decode
faults, interrupted checkpoint saves, and fleet-level drills against
the multi-replica `ServingRouter` (SIGKILL a replica mid-decode: every
in-flight request completes on survivors with bit-identical greedy
output, all four terminal fates reconcile exactly across the
`pdt_router_*` / `pdt_serving_*` counters, and the dead replica
restarts with backoff and resumes taking traffic). conftest enables
PDT_CHECK_INVARIANTS=1 for this file, so page accounting is re-proved
after every engine step of every test."""
import json
import random

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.serving import (ContinuousBatchingEngine,
                                       EngineInvariantError,
                                       EngineOverloaded, PoolExhausted,
                                       RequestStatus)
from paddle_tpu.serving import (CanaryConfig, ReplicaState,
                                SentryConfig, ServingRouter)
from paddle_tpu.utils.faults import FaultError, FaultInjector, fault_point

pytestmark = pytest.mark.chaos


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=1, max_position_embeddings=64)
    paddle.seed(7)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _engine(model, **kw):
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", 4)
    return ContinuousBatchingEngine(model, **kw)


def _drain(eng):
    """run(), but keep the Request objects (status/error/preemptions)."""
    reqs = {}
    while eng._queue or any(r is not None for r in eng._slot_req):
        for r in eng.step():
            reqs[r.rid] = r
    return reqs


class TestFaultInjector:
    def test_nth_fires_once_deterministically(self):
        with FaultInjector() as fi:
            fi.arm("x", nth=3)
            for i in range(1, 6):
                if i == 3:
                    with pytest.raises(FaultError) as e:
                        fault_point("x")
                    assert e.value.site == "x"
                else:
                    fault_point("x")
            assert fi.calls("x") == 5 and fi.trips("x") == 1
        fault_point("x")      # scope exited: disarmed, no raise

    def test_probability_reproducible_with_seed(self):
        def run(seed):
            fired = []
            with FaultInjector(seed=seed) as fi:
                fi.arm("p", probability=0.5)
                for _ in range(24):
                    try:
                        fault_point("p")
                        fired.append(False)
                    except FaultError:
                        fired.append(True)
            return fired

        a, b, c = run(1), run(1), run(2)
        assert a == b                 # seeded: bit-identical
        assert a != c                 # and seed-sensitive
        assert any(a) and not all(a)

    def test_always_with_times_cap_and_custom_exc(self):
        with FaultInjector() as fi:
            fi.arm("a", always=True, times=2, exc=PoolExhausted)
            for _ in range(2):
                with pytest.raises(PoolExhausted):
                    fault_point("a")
            fault_point("a")          # cap reached: no more firings
            assert fi.stats()["a"] == {"calls": 3, "trips": 2}

    def test_nested_scopes_inner_wins_and_unwinds(self):
        with FaultInjector() as outer:
            outer.arm("s", nth=1)
            with FaultInjector() as inner:
                inner.arm("s", always=True, exc=ValueError)
                with pytest.raises(ValueError):
                    fault_point("s")  # innermost injector consulted first
            with pytest.raises(FaultError):
                fault_point("s")      # outer's nth=1 still pending
            fault_point("s")

    def test_inner_scope_shadows_even_when_declining(self):
        with FaultInjector() as outer:
            outer.arm("s", always=True)
            with FaultInjector() as inner:
                inner.arm("s", nth=5)
                fault_point("s")      # inner declines AND shadows outer
                assert inner.calls("s") == 1 and outer.calls("s") == 0
            with pytest.raises(FaultError):
                fault_point("s")      # outer visible again

    def test_arm_validation(self):
        fi = FaultInjector()
        with pytest.raises(ValueError):
            fi.arm("x")
        with pytest.raises(ValueError):
            fi.arm("x", nth=1, always=True)
        with pytest.raises(ValueError):
            fi.arm("x", nth=0)
        with pytest.raises(ValueError):
            fi.arm("x", probability=1.5)


class TestEngineChaos:
    def _ref(self, model, jobs, **kw):
        eng = _engine(model, **kw)
        rids = [eng.add_request(p, n) for p, n in jobs]
        res = eng.run()
        return [res[r] for r in rids]

    def test_pool_exhaustion_preempts_and_recovers(self, model):
        """Forced exhaustion mid-decode: the youngest request is
        preempted, requeued, re-prefilled — and the final token streams
        are IDENTICAL to an unfaulted run."""
        jobs = [([5, 4, 3, 2, 6, 7], 8), ([9, 1, 2], 6)]
        ref = self._ref(model, jobs)
        eng = _engine(model)
        rids = [eng.add_request(p, n) for p, n in jobs]
        with FaultInjector() as fi:
            # admission allocates pages 1-3 (prompts of 6 and 3 tokens
            # at page_size 4); visit #4 is the first decode-time lazy
            # growth -> exhaustion mid-decode
            fi.arm("serving.alloc_page", nth=4, exc=PoolExhausted)
            reqs = _drain(eng)
        assert [reqs[r].output for r in rids] == ref
        assert eng.num_preemptions == 1
        assert all(reqs[r].status == RequestStatus.FINISHED
                   for r in rids)
        assert reqs[rids[1]].preemptions == 1   # youngest took the hit
        assert eng.cache_memory_info()["pages_in_use"] == 0
        # chaos runs are assertable via telemetry, not just side effects
        assert telemetry.value("pdt_faults_fired_total",
                               site="serving.alloc_page") \
            == fi.trips("serving.alloc_page") == 1
        assert telemetry.value("pdt_serving_preemptions_total") == 1

    def test_self_preemption_resumes_and_matches(self, model):
        """Single slot: the faulting slot IS the youngest. It must
        release itself, requeue prompt+generated, and still emit the
        unfaulted greedy stream."""
        job = ([5, 4, 3, 2, 6, 7], 8)
        ref = self._ref(model, [job], max_batch_size=1)
        eng = _engine(model, max_batch_size=1)
        rid = eng.add_request(*job)
        with FaultInjector() as fi:
            fi.arm("serving.alloc_page", nth=3, exc=PoolExhausted)
            reqs = _drain(eng)
        assert reqs[rid].output == ref[0]
        assert reqs[rid].status == RequestStatus.FINISHED
        assert reqs[rid].preemptions == 1
        assert eng.cache_memory_info()["pages_in_use"] == 0

    def test_admission_alloc_exhaustion_requeues(self, model):
        """Pool exhaustion during ADMISSION-time allocation must not
        fail the request: it backs out, requeues, and admits cleanly
        on a later step — outputs identical to an unfaulted run."""
        jobs = [([5, 4, 3, 2, 6, 7], 4), ([9, 1, 2], 6)]
        ref = self._ref(model, jobs, max_batch_size=1)
        eng = _engine(model, max_batch_size=1)
        rids = [eng.add_request(p, n) for p, n in jobs]
        with FaultInjector() as fi:
            # visits 1-3: request 0 (2 admission allocs + 1 growth);
            # visit 4 lands in request 1's admission _reserve_and_alloc
            fi.arm("serving.alloc_page", nth=4, exc=PoolExhausted)
            reqs = _drain(eng)
        assert [reqs[r].output for r in rids] == ref
        assert all(reqs[r].status == RequestStatus.FINISHED
                   for r in rids)
        assert eng.num_preemptions == 1 and eng.num_failures == 0
        assert reqs[rids[1]].preemptions == 1
        assert eng.cache_memory_info()["pages_in_use"] == 0

    def test_preemption_starvation_guard(self, model):
        eng = _engine(model, max_batch_size=1, max_preemptions=0)
        rid = eng.add_request([5, 4, 3, 2, 6, 7], 8)
        with FaultInjector() as fi:
            fi.arm("serving.alloc_page", nth=3, exc=PoolExhausted)
            reqs = _drain(eng)
        assert reqs[rid].status == RequestStatus.PREEMPTED
        assert reqs[rid].done and eng.num_preemptions == 1
        assert "starvation" in reqs[rid].error
        assert len(reqs[rid].output) > 0        # partial output kept
        assert eng.cache_memory_info()["pages_in_use"] == 0

    def test_prefill_failure_isolates_request(self, model):
        jobs = [([5, 4, 3, 2, 6, 7], 8), ([9, 1, 2], 6)]
        ref = self._ref(model, jobs)
        eng = _engine(model)
        a, b = [eng.add_request(p, n) for p, n in jobs]
        with FaultInjector() as fi:
            fi.arm("serving.prefill", nth=1)
            reqs = _drain(eng)
        assert reqs[a].status == RequestStatus.FAILED
        assert reqs[a].output == []
        assert "FaultError" in reqs[a].error
        assert reqs[b].status == RequestStatus.FINISHED
        assert reqs[b].output == ref[1]         # untouched by the fault
        assert eng.num_failures == 1
        snap = telemetry.snapshot()
        assert snap["counters"]["pdt_faults_fired_total"][
            'site="serving.prefill"'] == 1
        assert snap["counters"]["pdt_serving_requests_terminal_total"][
            'status="failed"'] == 1
        assert any(e["name"] == "fault.fire"
                   and e["attrs"]["site"] == "serving.prefill"
                   for e in telemetry.events())
        # the engine keeps serving after the failure
        c = eng.add_request(jobs[0][0], 8)
        assert eng.run()[c] == ref[0]
        assert eng.cache_memory_info()["pages_in_use"] == 0

    def test_deadline_expiry_mid_decode(self, model):
        clk = FakeClock()
        eng = _engine(model, clock=clk)
        rid = eng.add_request([5, 4, 3, 2, 6, 7], 32, deadline=10.0)
        assert eng.step() == []                 # admit + first decode
        clk.advance(11.0)
        done = eng.step()
        assert [r.rid for r in done] == [rid]
        assert done[0].status == RequestStatus.TIMEOUT
        assert 0 < len(done[0].output) < 32     # partial output retained
        assert eng.num_timeouts == 1
        assert eng.cache_memory_info()["pages_in_use"] == 0

    def test_max_queue_time_expires_waiting_request(self, model):
        clk = FakeClock()
        eng = _engine(model, max_batch_size=1, clock=clk)
        a = eng.add_request([5, 4, 3], 24)
        b = eng.add_request([9, 1, 2], 8, max_queue_time=5.0)
        assert eng.step() == []                 # a holds the only slot
        clk.advance(6.0)
        done = {r.rid: r for r in eng.step()}
        assert done[b].status == RequestStatus.TIMEOUT
        assert done[b].output == []             # expired before running
        reqs = _drain(eng)                      # a is unaffected
        assert reqs[a].status == RequestStatus.FINISHED
        assert len(reqs[a].output) == 24

    def test_backpressure_and_admission_policy(self, model):
        eng = _engine(model, max_waiting=2)
        eng.add_request([1, 2], 2)
        eng.add_request([3, 4], 2)
        with pytest.raises(EngineOverloaded, match="queue full"):
            eng.add_request([5, 6], 2)
        eng.run()                               # drained: queue reopens
        eng.add_request([7, 8], 2)
        eng.run()
        eng = _engine(
            model, admission_policy=lambda e, r: len(r.prompt) <= 4)
        eng.add_request([1, 2, 3, 4], 2)
        with pytest.raises(EngineOverloaded, match="policy"):
            eng.add_request([1, 2, 3, 4, 5], 2)
        eng.run()

    def test_decode_fault_retries_transparently(self, model):
        ref = self._ref(model, [([5, 4, 3], 6)])
        eng = _engine(model)
        rid = eng.add_request([5, 4, 3], 6)
        with FaultInjector() as fi:
            fi.arm("serving.decode", nth=2)
            reqs = _drain(eng)
        assert reqs[rid].output == ref[0]       # retry is lossless
        assert eng.num_decode_retries == 1

    def test_decode_fault_persistent_raises_after_cap(self, model):
        eng = _engine(model, max_decode_retries=2)
        eng.add_request([5, 4, 3], 6)
        with FaultInjector() as fi:
            fi.arm("serving.decode", always=True)
            with pytest.raises(FaultError):
                eng.run()
        assert eng.num_decode_retries == 3      # 2 retries + the raiser

    def test_starvation_finalize_survives_decode_fault(self, model):
        """A request finalized by the starvation guard inside _decode
        must still be returned by step() when the SAME decode dispatch
        then faults — terminal requests must never be silently lost."""
        eng = _engine(model, max_preemptions=0)
        a = eng.add_request([5, 4, 3, 2, 6, 7], 8)
        b = eng.add_request([9, 1, 2], 6)
        with FaultInjector() as fi:
            fi.arm("serving.alloc_page", nth=4, exc=PoolExhausted)
            fi.arm("serving.decode", nth=2)   # same step as the guard
            reqs = _drain(eng)
        assert reqs[b].status == RequestStatus.PREEMPTED   # not lost
        assert reqs[a].status == RequestStatus.FINISHED
        assert eng.num_decode_retries == 1

    def test_finished_backlog_survives_retry_cap_raise(self, model):
        """When the decode-retry cap forces step() to re-raise, requests
        already finalized in that same step are delivered by the next
        step() instead of being silently dropped with the exception."""
        eng = _engine(model, max_preemptions=0, max_decode_retries=0)
        a = eng.add_request([5, 4, 3, 2, 6, 7], 8)
        b = eng.add_request([9, 1, 2], 6)
        with FaultInjector() as fi:
            fi.arm("serving.alloc_page", nth=4, exc=PoolExhausted)
            fi.arm("serving.decode", nth=2)   # same step, cap 0 -> raise
            with pytest.raises(FaultError):
                eng.run()
        reqs = _drain(eng)                    # fault cleared: continue
        assert reqs[b].status == RequestStatus.PREEMPTED  # delivered
        assert reqs[a].status == RequestStatus.FINISHED
        assert eng.cache_memory_info()["pages_in_use"] == 0

    def test_invariant_checker_catches_corruption(self, model):
        eng = _engine(model)
        eng.check_invariants()                  # clean engine passes
        leaked = eng._free.pop()                # rc==0 page off the list
        with pytest.raises(EngineInvariantError, match="LEAKED"):
            eng.check_invariants()
        eng._free.append(leaked)
        eng.check_invariants()
        eng._free.append(leaked)                # duplicate free entry
        with pytest.raises(EngineInvariantError, match="duplicates"):
            eng.check_invariants()


class TestSpeculativeChaos:
    """ISSUE 10 fault sites: an armed `speculative.draft` /
    `speculative.verify` site degrades THAT round to plain decode —
    the request never fails, the stream stays bit-identical, and the
    degradation is visible (pdt_spec_degraded_total{site=} +
    serving.spec_degraded event + the fault counter chaos runs
    reconcile against)."""

    JOBS = [([5, 4, 3, 2, 6, 7], 8), ([9, 1, 2], 6)]

    @pytest.fixture(scope="class")
    def draft(self):
        cfg = LlamaConfig(vocab_size=64, hidden_size=16,
                          intermediate_size=32, num_hidden_layers=1,
                          num_attention_heads=2, num_key_value_heads=1,
                          max_position_embeddings=64)
        paddle.seed(8)
        d = LlamaForCausalLM(cfg)
        d.eval()
        return d

    def _run(self, model, draft=None, fault=None, k=4):
        from paddle_tpu.models.serving import SpecConfig
        eng = _engine(model, spec_decode=None if draft is None
                      else SpecConfig(draft, k=k))
        rids = [eng.add_request(p, n) for p, n in self.JOBS]
        if fault is None:
            reqs = _drain(eng)
        else:
            with FaultInjector() as fi:
                fi.arm(fault[0], **fault[1])
                reqs = _drain(eng)
        return eng, [reqs[r].output for r in rids], \
            [reqs[r].status for r in rids]

    def test_draft_fault_degrades_round_not_request(self, model, draft):
        _, want, _ = self._run(model)               # plain reference
        telemetry.reset()
        telemetry.clear_events()
        eng, got, statuses = self._run(
            model, draft, fault=("speculative.draft", dict(nth=2)))
        assert got == want                          # still lossless
        assert all(s == RequestStatus.FINISHED for s in statuses)
        assert eng.num_spec_degraded == 1
        assert eng.num_spec_rounds >= 1             # other rounds spec'd
        snap = telemetry.snapshot()["counters"]
        assert snap["pdt_spec_degraded_total"]['site="draft"'] == 1
        assert snap["pdt_faults_fired_total"][
            'site="speculative.draft"'] == 1
        ev = [e for e in telemetry.events()
              if e["name"] == "serving.spec_degraded"]
        assert len(ev) == 1 and ev[0]["attrs"]["site"] == "draft"
        # the degraded round served through the PLAIN decode dispatch
        assert any(e["name"] == "serving.decode_step"
                   for e in telemetry.events())

    def test_verify_fault_storm_never_fails_requests(self, model,
                                                     draft):
        """speculative.verify armed ALWAYS: every round degrades (the
        draft pass runs, then verify dies pre-dispatch), the engine
        serves every request to completion through plain decode, and
        zero spec rounds commit."""
        _, want, _ = self._run(model)
        telemetry.reset()
        eng, got, statuses = self._run(
            model, draft, fault=("speculative.verify",
                                 dict(always=True)))
        assert got == want
        assert all(s == RequestStatus.FINISHED for s in statuses)
        assert eng.num_spec_rounds == 0
        assert eng.num_spec_degraded >= 1
        assert telemetry.value("pdt_spec_degraded_total",
                               site="verify") \
            == eng.num_spec_degraded
        assert telemetry.value("pdt_faults_fired_total",
                               site="speculative.verify") \
            == eng.num_spec_degraded


class TestCheckpointChaos:
    def test_injected_save_failure_leaves_no_partial_checkpoint(
            self, tmp_path):
        from paddle_tpu import nn
        from paddle_tpu.distributed.fleet.elastic import (
            ElasticManager, latest_checkpoint)
        paddle.seed(0)
        net = nn.Linear(4, 4)
        em = ElasticManager(str(tmp_path), save_interval_steps=1)
        em.save(0, net)
        assert latest_checkpoint(str(tmp_path)).endswith("step_0")
        with FaultInjector() as fi:
            fi.arm("checkpoint.save", always=True)
            with pytest.raises(FaultError):
                em.save(1, net)
        # the interrupted save wrote no .done marker: resume discovery
        # still picks the last COMPLETE checkpoint
        assert latest_checkpoint(str(tmp_path)).endswith("step_0")
        em.save(2, net)                          # heals once fault clears
        assert latest_checkpoint(str(tmp_path)).endswith("step_2")

    def test_kill_mid_save_resumes_previous_and_quarantines(
            self, tmp_path):
        """The acceptance drill (ISSUE 3): torn tmp from a write fault,
        a .done-marked dir with no loadable data, and a bit-flipped
        newest checkpoint — resume() must land on the last good step,
        quarantine the bad ones, and the telemetry must reconcile
        exactly with the injected damage."""
        import os

        from paddle_tpu import nn
        from paddle_tpu.distributed.checkpoint import (verify_checkpoint,
                                                       write_done)
        from paddle_tpu.distributed.fleet.elastic import (
            ElasticManager, latest_checkpoint)
        from paddle_tpu.optimizer import Adam

        paddle.seed(0)
        net = nn.Linear(4, 4)
        opt = Adam(learning_rate=1e-2, parameters=net.parameters())
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        loss = (net(x) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        em = ElasticManager(str(tmp_path), save_interval_steps=1,
                            save_retries=1, sleep=lambda _: None)
        em.save(0, net, opt)
        w0 = net.weight.numpy().copy()
        em.save(1, net, opt)

        # damage 1: kill mid-save of step 2 — write fault with no
        # retries budget leaves a torn step_2.tmp (model group written,
        # no manifest, never renamed)
        with FaultInjector() as fi:
            fi.arm("checkpoint.write", nth=1)
            with pytest.raises(FaultError):
                em.save(2, net, opt)
        assert (tmp_path / "step_2.tmp").exists()
        # damage 2: a committed-looking dir whose data never landed
        (tmp_path / "step_3").mkdir()
        write_done(str(tmp_path / "step_3"), step=3)
        # damage 3: silent bit flips in the newest real checkpoint
        from paddle_tpu.utils.faults import flip_ocdbt_shards
        flip_ocdbt_shards(tmp_path / "step_1")
        assert latest_checkpoint(str(tmp_path)).endswith("step_3")

        paddle.seed(1)
        net2 = nn.Linear(4, 4)
        opt2 = Adam(learning_rate=1e-2, parameters=net2.parameters())
        start = em.resume(net2, opt2)

        # landed on the previous complete step, with its exact weights
        assert start == 1
        np.testing.assert_array_equal(net2.weight.numpy(), w0)
        names = {p.name for p in tmp_path.iterdir()}
        assert "step_3.corrupt" in names     # missing data: load fail
        assert "step_1.corrupt" in names     # flipped bytes: verify
        assert "step_2.tmp" in names         # ignored, never trusted
        assert "step_0" in names
        # telemetry reconciles 1:1 with the injected damage
        assert telemetry.value("pdt_faults_fired_total",
                               site="checkpoint.write") == 1
        assert telemetry.value("pdt_checkpoint_corrupt_total",
                               reason="load") == 1
        assert telemetry.value("pdt_checkpoint_corrupt_total",
                               reason="verify") == 1
        assert telemetry.value(
            "pdt_checkpoint_resume_fallbacks_total") == 2
        assert telemetry.value(
            "pdt_checkpoint_resume_fallback_depth") == 2
        # the survivor still verifies clean, checksums and all
        assert verify_checkpoint(str(tmp_path / "step_0"),
                                 rehash=True).ok

    def test_interrupted_then_retried_save_verifies_clean(
            self, tmp_path):
        """Acceptance: a save interrupted at EVERY protocol stage and
        then retried (in-place via backoff, or by a fresh call) must
        commit a checkpoint that verifies clean — no half-written state
        leaks across attempts."""
        from paddle_tpu import nn
        from paddle_tpu.distributed.checkpoint import verify_checkpoint
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        paddle.seed(0)
        net = nn.Linear(4, 4)
        em = ElasticManager(str(tmp_path), save_interval_steps=1,
                            save_retries=3, keep_last=3,
                            sleep=lambda _: None)
        with FaultInjector() as fi:
            fi.arm("checkpoint.write", nth=1)     # retried in place
            em.save(0, net)
        with FaultInjector() as fi:
            fi.arm("checkpoint.finalize", nth=1)  # retried in place
            em.save(1, net)
        # exhaust retries entirely, then heal with a fresh save() call
        em2 = ElasticManager(str(tmp_path), save_interval_steps=1,
                             save_retries=1, keep_last=3,
                             sleep=lambda _: None)
        with FaultInjector() as fi:
            fi.arm("checkpoint.write", always=True)
            with pytest.raises(FaultError):
                em2.save(2, net)
        em2.save(2, net)
        for step in (0, 1, 2):
            res = verify_checkpoint(
                str(tmp_path / f"step_{step}"), rehash=True)
            assert res.ok, (step, res.errors)
        assert telemetry.value(
            "pdt_checkpoint_save_retries_total") == 2

    def test_load_fault_site_forces_fallback(self, tmp_path):
        """checkpoint.load is armable: a PERSISTENT restore failure on
        the newest checkpoint (every one of its `load_retries` attempts
        fails) quarantines it and falls back instead of crash-looping."""
        from paddle_tpu import nn
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        paddle.seed(0)
        net = nn.Linear(4, 4)
        em = ElasticManager(str(tmp_path), save_interval_steps=1,
                            sleep=lambda _: None)
        em.save(0, net)
        em.save(1, net)
        with FaultInjector() as fi:
            # step_1 gets load_retries=2 attempts, both fail; the cap
            # then lets step_0's load through clean
            fi.arm("checkpoint.load", always=True, times=2)
            assert em.resume(net) == 1
        assert fi.trips("checkpoint.load") == 2
        assert (tmp_path / "step_1.corrupt").exists()
        assert telemetry.value("pdt_checkpoint_load_retries_total") == 1

    def test_transient_load_fault_is_retried_not_quarantined(self, tmp_path):
        """One flaky I/O error must not cost a save interval: the load
        is retried in place and the newest checkpoint stays trusted."""
        from paddle_tpu import nn
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        paddle.seed(0)
        net = nn.Linear(4, 4)
        em = ElasticManager(str(tmp_path), save_interval_steps=1,
                            sleep=lambda _: None)
        em.save(0, net)
        em.save(1, net)
        with FaultInjector() as fi:
            fi.arm("checkpoint.load", nth=1)     # first attempt only
            assert em.resume(net) == 2           # newest step restored
        assert fi.trips("checkpoint.load") == 1
        assert not (tmp_path / "step_1.corrupt").exists()
        assert telemetry.value("pdt_checkpoint_load_retries_total") == 1
        assert telemetry.value(
            "pdt_checkpoint_resume_fallbacks_total") == 0


class TestRouterFleetChaos:
    """Fleet-level drills over `paddle_tpu.serving.ServingRouter`:
    deterministic SIGKILL of a replica mid-decode (the acceptance drill
    for the multi-replica subsystem) plus fault-site storms against the
    `router.*` sites. Same FakeClock discipline as the engine tests —
    the router, the engines, and every deadline share one injectable
    clock, so every transition is forced, never awaited."""

    def _fleet(self, model, n=3, clock=None, engine_kw=None, **kw):
        clock = clock if clock is not None else FakeClock()
        ekw = dict(max_batch_size=2, max_seq_len=64, page_size=4)
        ekw.update(engine_kw or {})
        kw.setdefault("page_size", 4)
        kw.setdefault("sleep", clock.advance)
        router = ServingRouter(
            lambda i: ContinuousBatchingEngine(model, clock=clock, **ekw),
            num_replicas=n, policy="round_robin", clock=clock, **kw)
        return router, clock

    def _ref(self, model, jobs, **kw):
        kw.setdefault("max_batch_size", 2)
        kw.setdefault("max_seq_len", 64)
        kw.setdefault("page_size", 4)
        eng = ContinuousBatchingEngine(model, **kw)
        rids = [eng.add_request(p, m) for p, m in jobs]
        res = eng.run()
        return [res[r] for r in rids]

    def test_replica_kill_four_fates_reconcile(self, model):
        """The ISSUE-4 acceptance drill. One fleet run produces every
        terminal fate — PREEMPTED (starvation guard under forced pool
        exhaustion), FAILED (injected prefill fault, replica stays
        healthy), TIMEOUT (deadline expires mid-decode), FINISHED
        (including one request SIGKILLed off its replica mid-decode and
        re-prefilled on a survivor) — and the fleet-level
        `pdt_router_requests_terminal_total` reconciles EXACTLY, per
        status, with the engines' `pdt_serving_requests_terminal_total`.
        Then the dead replica restarts with backoff and demonstrably
        takes traffic again."""
        jobs = [([5, 4, 3, 2, 6, 7], 8), ([9, 1, 2], 6), ([7, 7, 1, 2], 5)]
        ref = self._ref(model, jobs)
        # the oracle engine above ticked the GLOBAL pdt_serving_*
        # counters; baseline them so the reconciliation below measures
        # the fleet run alone
        statuses = (RequestStatus.FINISHED, RequestStatus.FAILED,
                    RequestStatus.TIMEOUT, RequestStatus.PREEMPTED)
        eng_base = {s: telemetry.value(
            "pdt_serving_requests_terminal_total", status=s)
            for s in statuses}
        adm_base = telemetry.value("pdt_serving_admissions_total")
        router, clock = self._fleet(
            model, n=3, restart_backoff_base=3.0, restart_backoff_max=3.0,
            engine_kw=dict(max_preemptions=0))

        # fate 1 — PREEMPTED: replica 0 is the only busy engine, so the
        # alloc-visit counting is single-engine deterministic (admission
        # takes visits 1-2 for the 6-token prompt, visit 3 is the first
        # lazy growth mid-decode); max_preemptions=0 turns the preempt
        # into the starvation-guard terminal
        d = router.submit([5, 4, 3, 2, 6, 7], 8)        # round robin: r0
        with FaultInjector() as fi:
            fi.arm("serving.alloc_page", nth=3, exc=PoolExhausted)
            while not router.requests[d].done:
                router.step()
        rec_d = router.requests[d]
        assert rec_d.status == RequestStatus.PREEMPTED
        assert len(rec_d.tokens) > 0            # partial output retained
        assert router.replicas[0].state == ReplicaState.HEALTHY

        # fate 2 — FAILED: an injected prefill fault is a REQUEST
        # failure, isolated by the engine — not a replica health event
        c = router.submit([9, 1, 2], 6)                 # round robin: r1
        with FaultInjector() as fi:
            fi.arm("serving.prefill", nth=1)
            while not router.requests[c].done:
                router.step()
        assert router.requests[c].status == RequestStatus.FAILED
        assert router.replicas[1].state == ReplicaState.HEALTHY

        # fates 3+4 — TIMEOUT and FINISHED-after-failover: three normal
        # requests and one doomed deadline, placements fixed by round
        # robin (a1->r2, a2->r0, a3->r1, b->r2)
        a1, a2, a3 = [router.submit(p, m) for p, m in jobs]
        b = router.submit([1, 2, 3], 40, deadline=5.0)
        router.step()
        router.step()                           # mid-decode everywhere
        assert not router.requests[a2].done
        router.kill_replica(0)                  # SIGKILL: a2 stranded
        clock.advance(6.0)                      # past b's deadline AND
        out = router.run()                      # past r0's backoff
        assert [out[i] for i in (a1, a2, a3)] == ref   # zero loss,
        #                                          bit-identical greedy
        assert router.requests[a2].failovers == 1
        assert router.requests[b].status == RequestStatus.TIMEOUT

        # the dead replica restarted with backoff and takes traffic:
        # three more submissions necessarily cover every replica index
        assert router.replicas[0].state == ReplicaState.HEALTHY
        assert router.replicas[0].restarts == 1
        extra = [router.submit(p, m) for p, m in jobs]
        assert {router.requests[i].replica for i in extra} == {0, 1, 2}
        out = router.run()
        assert [out[i] for i in extra] == ref

        # exact reconciliation, fleet vs engines, per status: every
        # request reaches an ENGINE terminal exactly once (the request
        # killed mid-decode produced no terminal on the dead engine),
        # and the router mirrors each one
        fates = {RequestStatus.FINISHED: 6, RequestStatus.FAILED: 1,
                 RequestStatus.TIMEOUT: 1, RequestStatus.PREEMPTED: 1}
        for status, want in fates.items():
            assert telemetry.value("pdt_router_requests_terminal_total",
                                   status=status) == want, status
            assert telemetry.value("pdt_serving_requests_terminal_total",
                                   status=status) \
                - eng_base[status] == want, status
        assert sum(fates.values()) == len(router.requests)
        assert telemetry.value("pdt_router_failovers_total") == 1 \
            == router.num_failovers
        # every dispatch that PREFILLED is an engine admission:
        # originals + the one failover, minus the prefill-faulted
        # request (admissions count successful prefills only)
        assert telemetry.value("pdt_serving_admissions_total") - adm_base \
            == len(router.requests) + router.num_failovers - 1
        assert telemetry.value("pdt_router_replica_restarts_total",
                               replica="0") == 1
        # the failover event stream carries the stable request_id
        moved = [e for e in telemetry.events()
                 if e["name"] == "router.failover"]
        assert [e["attrs"]["request_id"] for e in moved] == [a2]

    def test_step_fault_storm_kills_and_recovers_zero_loss(self, model):
        """A persistent `router.step` fault storm (the wedged-process
        shape) rides a replica down HEALTHY -> DEGRADED -> DEAD; its
        work re-prefills on survivors with identical output, and the
        storm's end lets the backoff restart bring it back."""
        jobs = [([5, 4, 3, 2, 6, 7], 8), ([9, 1, 2], 6)]
        ref = self._ref(model, jobs)
        router, clock = self._fleet(
            model, n=2, degraded_after=1, dead_after=2,
            restart_backoff_base=2.0, restart_backoff_max=2.0)
        a = router.submit(*jobs[0])             # round robin: replica 0
        with FaultInjector() as fi:
            # idle replicas do not consume router.step visits, so the
            # storm lands entirely on replica 0 — the only busy one
            fi.arm("router.step", always=True, times=2)
            router.step()
            assert router.replicas[0].state == ReplicaState.DEGRADED
            router.step()
            assert router.replicas[0].state == ReplicaState.DEAD
            assert fi.trips("router.step") == 2
        b = router.submit(*jobs[1])             # survivor takes it
        out = router.run()                      # failover completes all
        assert [out[i] for i in (a, b)] == ref
        assert router.requests[a].failovers == 1
        clock.advance(2.5)
        router.step()
        assert router.replicas[0].state == ReplicaState.HEALTHY


class TestObservabilityChaos:
    """ISSUE-5 acceptance drills: one request traced end to end through
    a 4-replica kill drill must yield a single CONNECTED span tree
    whose Chrome export validates against the trace-event schema, and
    an attached SloMonitor must flag a deliberately induced TTFT breach
    while grading the unfaulted run pass."""

    def _fleet(self, model, n=4, clock=None, engine_kw=None, **kw):
        clock = clock if clock is not None else FakeClock()
        ekw = dict(max_batch_size=2, max_seq_len=64, page_size=4)
        ekw.update(engine_kw or {})
        kw.setdefault("page_size", 4)
        kw.setdefault("sleep", clock.advance)
        router = ServingRouter(
            lambda i: ContinuousBatchingEngine(model, clock=clock, **ekw),
            num_replicas=n, policy="round_robin", clock=clock, **kw)
        return router, clock

    JOBS = [([5, 4, 3, 2, 6, 7], 8), ([9, 1, 2], 6),
            ([7, 7, 1, 2], 5), ([1, 2, 3, 4], 6)]

    def test_kill_drill_yields_one_connected_span_tree(self, model):
        from paddle_tpu.observability import trace as trace_mod
        router, clock = self._fleet(model, n=4, restart_backoff_base=9.0,
                                    restart_backoff_max=9.0)
        rids = [router.submit(p, m) for p, m in self.JOBS]
        router.step()
        router.step()                           # mid-decode everywhere
        x = rids[0]
        victim = router.requests[x].replica
        assert not router.requests[x].done
        router.kill_replica(victim)             # SIGKILL: x stranded
        router.run()                            # survivors finish all
        assert router.requests[x].failovers == 1
        assert router.requests[x].status == RequestStatus.FINISHED

        # ONE tree: router.submit root -> dispatch on the victim ->
        # prefill -> decode steps -> failover -> re-dispatch on a
        # survivor -> re-prefill -> terminal
        evts = telemetry.events()
        tree = trace_mod.request_tree(x, evts)
        assert tree is not None
        assert tree["event"]["name"] == "router.submit"

        def flatten(node):
            out = [node["event"]]
            for c in node["children"]:
                out += flatten(c)
            return out

        flat = flatten(tree)
        names = [e["name"] for e in flat]
        assert names.count("router.dispatch") == 2     # orig + failover
        assert names.count("serving.prefill") == 2     # prefill twice
        assert "router.failover" in names
        assert "router.terminal" in names
        assert "serving.first_token" in names
        assert "serving.decode_step" in names          # batched fan-in
        # CONNECTED: the tree contains every ring event of this trace
        # plus every batched decode step that served the request
        tid = tree["event"]["trace"]
        in_trace = [e for e in evts if e.get("trace") == tid]
        fanin = [e for e in evts if e.get("trace") != tid
                 and x in (e["attrs"].get("rids") or ())]
        assert len(flat) == len(in_trace) + len(fanin)
        assert {e["seq"] for e in flat} \
            == {e["seq"] for e in in_trace + fanin}
        # the failover is visible as two distinct dispatch replicas
        dispatch_replicas = [e["attrs"]["replica"] for e in flat
                             if e["name"] == "router.dispatch"]
        assert dispatch_replicas[0] == victim
        assert dispatch_replicas[1] != victim
        # timestamps all on one clock base: parents start no later
        # than their children (duration reconstruction holds)
        by_seq = {e["seq"]: e for e in flat}
        for e in flat:
            p = by_seq.get(e.get("parent"))
            if p is not None:
                assert p["ts_mono"] <= e["ts_mono"] + 1e-9

        # the Chrome export validates against the trace-event schema
        doc = telemetry.export_chrome_trace(evts)
        assert set(doc) == {"traceEvents", "displayTimeUnit"}
        for e in doc["traceEvents"]:
            assert isinstance(e["name"], str)
            assert isinstance(e["pid"], int)
            assert isinstance(e["tid"], int)
            assert e["ph"] in ("X", "i", "M"), e
            if e["ph"] == "X":
                assert e["dur"] >= 0.0 and e["ts"] >= 0.0
            elif e["ph"] == "i":
                assert e["s"] in ("t", "p", "g") and e["ts"] >= 0.0
        procs = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert f"replica {victim}" in procs        # pid = replica
        assert len(procs) >= 3                     # victim + survivor(s)
        threads = {e["args"]["name"] for e in doc["traceEvents"]
                   if e["ph"] == "M" and e["name"] == "thread_name"}
        assert set(rids) <= threads                # tid = request
        json.dumps(doc)                            # serializable

    def test_slo_monitor_flags_deliberate_ttft_breach(self, model):
        from paddle_tpu.observability.slo import SloMonitor, SloObjective

        def objectives():
            return [SloObjective("ttft_p95", "ttft", "latency", 0.5,
                                 quantile=0.95, window_s=1e6),
                    SloObjective("availability", "outcome",
                                 "availability", 0.99, window_s=1e6)]

        # unfaulted run: on the fake clock TTFT is 0.0s -> pass
        clock = FakeClock()
        mon = SloMonitor(objectives(), clock=clock, warn_burn=0.5)
        router, clock = self._fleet(model, n=2, clock=clock,
                                    slo_monitor=mon)
        for p, m in self.JOBS:
            router.submit(p, m)
        router.run()
        rep = mon.evaluate()
        assert rep["ttft_p95"].state == "pass"
        assert rep["availability"].state == "pass"
        assert rep["ttft_p95"].samples == len(self.JOBS)
        info = router.fleet_info()
        assert info["slo"]["ttft_p95"]["state"] == "pass"
        # per-replica SLO rides fleet_info next to health
        graded = [r["slo"] for r in info["replicas"]
                  if r["slo"] is not None]
        assert graded and all(s == "pass" for s in graded)
        assert telemetry.value("pdt_slo_state",
                               objective="ttft_p95") == 0

        # deliberate breach: the fleet sits on its queue for 1.2s of
        # fake time before the first step, so every first token lands
        # 1.2s after arrival — p95 TTFT 1.2s >> the 0.5s objective
        clock2 = FakeClock()
        mon2 = SloMonitor(objectives(), clock=clock2, warn_burn=0.5)
        router2, clock2 = self._fleet(model, n=2, clock=clock2,
                                      slo_monitor=mon2)
        for p, m in self.JOBS:
            router2.submit(p, m)
        clock2.advance(1.2)
        router2.run()
        st = mon2.evaluate()["ttft_p95"]
        assert st.state == "breach"
        assert st.value == pytest.approx(1.2)
        assert st.burn_rate > 1.0
        assert mon2.evaluate()["availability"].state == "pass"
        assert telemetry.value("pdt_slo_state",
                               objective="ttft_p95") == 2
        info2 = router2.fleet_info()
        assert info2["slo"]["ttft_p95"]["state"] == "breach"
        assert "breach" in {r["slo"] for r in info2["replicas"]}

    def test_slo_ttft_spans_failover_on_router_clock(self, model):
        """Time a request spends on a replica that dies before
        producing a token is time the CLIENT waited: the monitor's
        TTFT sample must span submit -> first mirrored token on the
        router clock, not restart from the failover re-dispatch (the
        survivor engine's arrival_time resets there)."""
        from paddle_tpu.observability.slo import SloMonitor, SloObjective
        clock = FakeClock()
        mon = SloMonitor([SloObjective("ttft_p95", "ttft", "latency",
                                       0.5, quantile=0.95,
                                       window_s=1e6)], clock=clock)
        router, clock = self._fleet(model, n=2, clock=clock,
                                    slo_monitor=mon)
        rid = router.submit([5, 4, 3], 4)
        router.kill_replica(router.requests[rid].replica)
        clock.advance(2.0)              # dead time the client sat out
        router.run()
        assert router.requests[rid].status == RequestStatus.FINISHED
        st = mon.evaluate()["ttft_p95"]
        assert st.samples == 1
        assert st.value == pytest.approx(2.0)   # not 0.0-from-survivor
        assert st.state == "breach"

class TestDisaggChaos:
    """Four-fates drill under DISAGGREGATED roles (ISSUE 8): a
    prefill:1,decode:1 fleet lands every terminal fate — PREEMPTED
    (forced pool exhaustion on the DECODE engine, post-migration),
    FAILED (injected prefill fault on the prefill replica), TIMEOUT (a
    deadline that dies with its replica), FINISHED (including requests
    whose migration was killed mid-transfer by a SIGKILL of the
    prefill endpoint) — with greedy outputs bit-identical to a
    colocated engine and exact fleet-vs-engine counter reconciliation.
    Same FakeClock discipline as every fleet drill."""

    def _fleet(self, model, clock=None, engine_kw=None, **kw):
        clock = clock if clock is not None else FakeClock()
        ekw = dict(max_batch_size=2, max_seq_len=64, page_size=4)
        ekw.update(engine_kw or {})
        kw.setdefault("page_size", 4)
        kw.setdefault("sleep", clock.advance)
        router = ServingRouter(
            lambda i: ContinuousBatchingEngine(model, clock=clock,
                                               **ekw),
            roles="prefill:1,decode:1", policy="round_robin",
            clock=clock, **kw)
        return router, clock

    def test_disagg_four_fates_reconcile(self, model):
        jobs = [([5, 4, 3, 2, 6, 7], 8), ([9, 1, 2], 6),
                ([7, 7, 1, 2], 5)]
        eng = ContinuousBatchingEngine(model, max_batch_size=2,
                                       max_seq_len=64, page_size=4)
        rids = [eng.add_request(p, m) for p, m in jobs]
        res = eng.run()
        ref = [res[r] for r in rids]
        statuses = (RequestStatus.FINISHED, RequestStatus.FAILED,
                    RequestStatus.TIMEOUT, RequestStatus.PREEMPTED)
        eng_base = {s: telemetry.value(
            "pdt_serving_requests_terminal_total", status=s)
            for s in statuses}
        adm_base = telemetry.value("pdt_serving_admissions_total")
        router, clock = self._fleet(
            model, restart_backoff_base=3.0, restart_backoff_max=3.0,
            engine_kw=dict(max_preemptions=0))

        # fate 1 — PREEMPTED, on the DECODE engine after migration.
        # alloc-page visits are deterministic: admission on the prefill
        # engine takes 1-2 (6-token prompt, page 4), the migration
        # install on the decode engine takes 3-4 (ctx 7 -> 2 pages),
        # and visit 5 is the decode engine's first lazy growth — so
        # nth=5 forces pool exhaustion exactly there; max_preemptions=0
        # turns the preempt into the starvation-guard terminal
        d = router.submit([5, 4, 3, 2, 6, 7], 8)
        with FaultInjector() as fi:
            fi.arm("serving.alloc_page", nth=5, exc=PoolExhausted)
            while not router.requests[d].done:
                router.step()
        rec_d = router.requests[d]
        assert rec_d.status == RequestStatus.PREEMPTED
        assert len(rec_d.tokens) > 0            # partial output retained
        assert router.requests[d].replica == 1  # it died a decode-side
        assert router.num_migrations == 1       # resident, post-transfer
        assert all(h.state == ReplicaState.HEALTHY
                   for h in router.replicas)

        # fate 2 — FAILED: an injected prefill fault on the prefill
        # replica is a REQUEST failure, isolated by the engine
        c = router.submit([9, 1, 2], 6)
        with FaultInjector() as fi:
            fi.arm("serving.prefill", nth=1)
            while not router.requests[c].done:
                router.step()
        assert router.requests[c].status == RequestStatus.FAILED
        assert router.replicas[0].state == ReplicaState.HEALTHY

        # fates 3+4 — TIMEOUT and FINISHED-after-SIGKILL-mid-migration:
        # three normal requests and one doomed deadline all land on the
        # only prefill replica; every migration attempt this step dies
        # mid-transfer (the serialize fault), then the prefill endpoint
        # is SIGKILLed with the transfers un-done
        a1, a2, a3 = [router.submit(p, m) for p, m in jobs]
        b = router.submit([1, 2, 3], 40, deadline=5.0)
        with FaultInjector() as fi:
            fi.arm("transfer.serialize", always=True)
            router.step()                       # prefills; transfers die
            assert fi.trips("transfer.serialize") == 2
        router.kill_replica(0)                  # SIGKILL the source
        clock.advance(6.0)                      # past b's deadline AND
        out = router.run()                      # past r0's backoff
        assert [out[i] for i in (a1, a2, a3)] == ref   # zero loss
        assert router.requests[b].status == RequestStatus.TIMEOUT
        assert "failover" in (router.requests[b].error or "")

        # the restarted prefill replica takes fresh traffic again and
        # hands it to the decode replica through the transfer plane
        assert router.replicas[0].state == ReplicaState.HEALTHY
        assert router.replicas[0].restarts == 1
        extra = [router.submit(p, m) for p, m in jobs[:2]]
        assert all(router.requests[i].replica == 0 for i in extra)
        out = router.run()
        assert [out[i] for i in extra] == ref[:2]

        # exact reconciliation, fleet vs engines, per status. The one
        # asymmetry is STRUCTURAL: b timed out while dead-stranded, so
        # the router finalized it honestly and no engine ever saw it —
        # fleet timeout=1, engine timeout=0.
        fates = {RequestStatus.FINISHED: 5, RequestStatus.FAILED: 1,
                 RequestStatus.TIMEOUT: 1, RequestStatus.PREEMPTED: 1}
        for status, want in fates.items():
            assert telemetry.value("pdt_router_requests_terminal_total",
                                   status=status) == want, status
        for status, want in ((RequestStatus.FINISHED, 5),
                             (RequestStatus.FAILED, 1),
                             (RequestStatus.TIMEOUT, 0),
                             (RequestStatus.PREEMPTED, 1)):
            assert telemetry.value("pdt_serving_requests_terminal_total",
                                   status=status) \
                - eng_base[status] == want, status
        assert sum(fates.values()) == len(router.requests)
        # admissions = successful PREFILLS only: d (1), a1+a2 before
        # the kill (2), a1+a2+a3 re-prefilled after it (3), extras (2).
        # c's prefill faulted and b never left the queue; migration
        # installs deliberately do NOT count as admissions.
        assert telemetry.value("pdt_serving_admissions_total") \
            - adm_base == 8
        # migrations: d, the three re-prefilled a's, both extras — the
        # two killed-mid-transfer attempts retried after failover
        assert router.num_migrations == 6
        assert telemetry.value("pdt_transfer_migrations_total") == 6
        assert telemetry.value("pdt_transfer_failures_total",
                               stage="serialize") == 2
        info = router.fleet_info()
        assert info["roles"]["prefill"]["migrations"] == 6
        assert info["roles"]["decode"]["migrations"] == 6


class TestGrayFailureChaos:
    """ISSUE-14 acceptance drills: the fleet versus a replica that
    keeps answering but answers WRONG. (a) a seeded KV bit-flip
    corrupt-mode fault on one replica of a 4-replica fleet is caught
    by the canary probe, the replica quarantines, every in-flight
    request finishes bit-identical to an uncorrupted fleet, and zero
    tainted tokens reach a finished stream; (c) a corrupt-mode fault
    on a migration payload is refused by the PR-13 sha256 verify gate,
    with sentry and payload-verify counters accounted separately."""

    JOBS = [([5, 4, 3, 2, 6, 7], 10), ([9, 1, 2], 10),
            ([7, 7, 1, 2], 10), ([3, 3, 9], 10)]

    def _fleet(self, model, clock, n=4, **kw):
        ekw = dict(max_batch_size=3, max_seq_len=64, page_size=4)
        kw.setdefault("page_size", 4)
        kw.setdefault("sleep", clock.advance)
        return ServingRouter(
            lambda i: ContinuousBatchingEngine(model, clock=clock,
                                               **ekw),
            num_replicas=n, policy="round_robin", clock=clock, **kw)

    def test_kv_bitflip_quarantine_drill(self, model):
        """Drill (a), tp=1 (tests/test_sentry.py carries the tp=2
        variant): arm a seeded always-firing KV bit-flip pinned to
        replica 1 (tag= — one sick chip in a healthy fleet). Its
        streams go silently wrong; the scheduled canary replays the
        golden prompt THROUGH the corrupt engine, mismatches, and
        quarantines; the tainted suffixes are dropped and re-generated
        on survivors. Greedy outputs land bit-identical to an
        uncorrupted fleet — fast wrong answers never ship."""
        ref = self._fleet(model, FakeClock())
        ref_ids = [ref.submit(p, m) for p, m in self.JOBS]
        want = ref.run()
        clock = FakeClock()
        router = self._fleet(
            model, clock,
            sentry=SentryConfig(scan_every=4),
            canary=CanaryConfig(interval=5.0, max_new_tokens=6),
            restart_backoff_base=3.0, restart_backoff_max=3.0)
        # the reference fleet and the canary golden's SCRATCH engine
        # both ticked the global counters: baseline AFTER construction
        # so reconciliation covers the drill alone
        eng_fin_base = telemetry.value(
            "pdt_serving_requests_terminal_total",
            status=RequestStatus.FINISHED)
        rtr_fin_base = telemetry.value(
            "pdt_router_requests_terminal_total",
            status=RequestStatus.FINISHED)
        ids = [router.submit(p, m) for p, m in self.JOBS]
        with FaultInjector(seed=0) as fi:
            fi.arm_corrupt("serving.kv_page", mode="bitflip",
                           always=True, tag="1")
            router.step()
            router.step()              # corruption flowing on r1
            assert fi.trips("serving.kv_page") >= 1
            clock.advance(6.0)         # canary schedule due
            for _ in range(60):
                router.step()
                if router.replicas[1].state \
                        == ReplicaState.QUARANTINED:
                    break
            assert router.replicas[1].state \
                == ReplicaState.QUARANTINED
            clock.advance(4.0)
            out = router.run()         # fault still armed: r1 cycles
            #                            probation->fail->quarantine,
            #                            survivors finish everything
        assert [out[i] for i in ids] == [want[r] for r in ref_ids]
        # the corrupt replica HAD streamed wrong tokens — they were
        # dropped at quarantine, not delivered (bit-identity above is
        # the zero-tainted-tokens proof; the counter shows the drop
        # actually happened rather than nothing having been at risk)
        assert router.num_tainted_tokens >= 1
        assert telemetry.value("pdt_sentry_tainted_tokens_total") \
            == router.num_tainted_tokens
        assert router.num_quarantines >= 1
        ev = [e for e in telemetry.events()
              if e["name"] == "replica.quarantine"]
        assert ev and ev[0]["attrs"]["reason"] == "canary_mismatch"
        assert ev[0]["attrs"]["replica"] == 1
        # every job reached exactly one ROUTER terminal, all finished
        assert telemetry.value("pdt_router_requests_terminal_total",
                               status=RequestStatus.FINISHED) \
            - rtr_fin_base == len(self.JOBS)
        # engine-side finished terminals reconcile EXACTLY once canary
        # probes are accounted: jobs + completed canary probes (pass/
        # dirty/fail verdicts each came from an engine-FINISHED probe;
        # aborted ones finalize under other statuses)
        canary_fin = sum(
            telemetry.value("pdt_sentry_canary_runs_total", result=r)
            for r in ("pass", "dirty", "fail"))
        assert telemetry.value("pdt_serving_requests_terminal_total",
                               status=RequestStatus.FINISHED) \
            - eng_fin_base == len(self.JOBS) + canary_fin
        info = router.fleet_info()
        assert info["sentry"]["quarantines"] \
            == router.num_quarantines
        assert info["pending"] == 0

    def test_corrupt_migration_payload_refused_by_verify(self, model):
        """Drill (c): under disaggregated roles, a corrupt-mode
        `transfer.payload` fault flips serialized KV bytes in flight —
        the PR-13 sha256 manifest refuses the install at
        stage="verify", the request keeps decoding on its consistent
        source, the NEXT tick's clean retry migrates it, and outputs
        stay identical to a colocated fleet. Sentry and payload-verify
        ledgers stay separate."""
        jobs = [([5, 4, 3, 2, 6, 7], 8), ([9, 1, 2], 6)]
        eng = ContinuousBatchingEngine(model, max_batch_size=2,
                                       max_seq_len=64, page_size=4)
        rids = [eng.add_request(p, m) for p, m in jobs]
        res = eng.run()
        ref = [res[r] for r in rids]
        clock = FakeClock()
        router = ServingRouter(
            lambda i: ContinuousBatchingEngine(
                model, clock=clock, max_batch_size=2, max_seq_len=64,
                page_size=4),
            roles="prefill:1,decode:1", policy="round_robin",
            page_size=4, clock=clock, sleep=clock.advance)
        ids = [router.submit(p, m) for p, m in jobs]
        verify_base = telemetry.value("pdt_transfer_failures_total",
                                      stage="verify")
        with FaultInjector(seed=0) as fi:
            fi.arm_corrupt("transfer.payload", nth=1)
            out = router.run()
            assert fi.trips("transfer.payload") == 1
        assert [out[i] for i in ids] == ref
        assert telemetry.value("pdt_transfer_failures_total",
                               stage="verify") - verify_base == 1
        assert telemetry.value("pdt_faults_fired_total",
                               site="transfer.payload") == 1
        # the refused attempt was retried clean: both requests still
        # migrated to the decode replica
        assert router.num_migrations == 2
        # payload-verify and sentry are SEPARATE ledgers: no sentry
        # instrument moved for a transfer-plane refusal
        snap = telemetry.snapshot()["counters"]
        assert "pdt_sentry_trips_total" not in snap
        assert "pdt_sentry_tainted_tokens_total" not in snap


class TestQuantChaos:
    """Quantized-serving four-fates drill (ISSUE 15): the PR-4
    acceptance drill re-run with every engine in
    ``quant=QuantServingConfig(weights="int8", kv="int8")`` mode. One
    quantized fleet run lands PREEMPTED / FAILED / TIMEOUT / FINISHED
    (including a request SIGKILLed off its replica mid-decode), the
    fleet-vs-engine terminal counters reconcile exactly, and every
    surviving stream is BIT-IDENTICAL to an uninterrupted quantized
    engine — determinism through chaos is preserved inside quantized
    mode even though values legitimately differ from bf16 (per-row
    page quantization is commit-order invariant, so a failover's
    re-prefilled pages hold the same int8 bytes the dead replica's
    did)."""

    def _quant(self):
        from paddle_tpu.models.serving import QuantServingConfig
        return QuantServingConfig(weights="int8", kv="int8")

    def _fleet(self, model, n=3, clock=None, engine_kw=None, **kw):
        clock = clock if clock is not None else FakeClock()
        ekw = dict(max_batch_size=2, max_seq_len=64, page_size=4,
                   quant=self._quant())
        ekw.update(engine_kw or {})
        kw.setdefault("page_size", 4)
        kw.setdefault("sleep", clock.advance)
        router = ServingRouter(
            lambda i: ContinuousBatchingEngine(model, clock=clock,
                                               **ekw),
            num_replicas=n, policy="round_robin", clock=clock, **kw)
        return router, clock

    def _ref(self, model, jobs):
        eng = ContinuousBatchingEngine(
            model, max_batch_size=2, max_seq_len=64, page_size=4,
            quant=self._quant())
        rids = [eng.add_request(p, m) for p, m in jobs]
        res = eng.run()
        return [res[r] for r in rids]

    def test_quant_four_fates_reconcile(self, model):
        jobs = [([5, 4, 3, 2, 6, 7], 8), ([9, 1, 2], 6),
                ([7, 7, 1, 2], 5)]
        ref = self._ref(model, jobs)
        statuses = (RequestStatus.FINISHED, RequestStatus.FAILED,
                    RequestStatus.TIMEOUT, RequestStatus.PREEMPTED)
        eng_base = {s: telemetry.value(
            "pdt_serving_requests_terminal_total", status=s)
            for s in statuses}
        router, clock = self._fleet(
            model, n=3, restart_backoff_base=3.0,
            restart_backoff_max=3.0,
            engine_kw=dict(max_preemptions=0))

        # fate 1 — PREEMPTED (starvation guard under forced pool
        # exhaustion; same alloc-visit arithmetic as the full-width
        # drill — the quantized allocator is the SAME allocator)
        d = router.submit([5, 4, 3, 2, 6, 7], 8)        # round robin: r0
        with FaultInjector() as fi:
            fi.arm("serving.alloc_page", nth=3, exc=PoolExhausted)
            while not router.requests[d].done:
                router.step()
        assert router.requests[d].status == RequestStatus.PREEMPTED
        assert router.replicas[0].state == ReplicaState.HEALTHY

        # fate 2 — FAILED (injected prefill fault, request-isolated)
        c = router.submit([9, 1, 2], 6)                 # round robin: r1
        with FaultInjector() as fi:
            fi.arm("serving.prefill", nth=1)
            while not router.requests[c].done:
                router.step()
        assert router.requests[c].status == RequestStatus.FAILED
        assert router.replicas[1].state == ReplicaState.HEALTHY

        # fates 3+4 — TIMEOUT and FINISHED-after-SIGKILL-failover
        a1, a2, a3 = [router.submit(p, m) for p, m in jobs]
        b = router.submit([1, 2, 3], 40, deadline=5.0)
        router.step()
        router.step()                           # mid-decode everywhere
        assert not router.requests[a2].done
        router.kill_replica(0)                  # SIGKILL: a2 stranded
        clock.advance(6.0)
        out = router.run()
        assert [out[i] for i in (a1, a2, a3)] == ref
        assert router.requests[a2].failovers == 1
        assert router.requests[b].status == RequestStatus.TIMEOUT
        assert router.replicas[0].restarts == 1

        fates = {RequestStatus.FINISHED: 3, RequestStatus.FAILED: 1,
                 RequestStatus.TIMEOUT: 1, RequestStatus.PREEMPTED: 1}
        for status, want in fates.items():
            assert telemetry.value("pdt_router_requests_terminal_total",
                                   status=status) == want, status
            assert telemetry.value("pdt_serving_requests_terminal_total",
                                   status=status) \
                - eng_base[status] == want, status
        assert telemetry.value("pdt_router_failovers_total") == 1
