"""nn.Layer system + layers + functional tests. ≙ reference «test/nn/» [U]."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

rng = np.random.default_rng(0)


class TestLayerSystem:
    def test_parameters_and_naming(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        names = [n for n, _ in m.named_parameters()]
        assert names == ["0.weight", "0.bias", "2.weight", "2.bias"]
        assert len(m.parameters()) == 4

    def test_state_dict_roundtrip(self):
        m1 = nn.Linear(4, 4)
        m2 = nn.Linear(4, 4)
        m2.set_state_dict(m1.state_dict())
        x = paddle.randn([2, 4])
        np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy(), rtol=1e-6)

    def test_buffers(self):
        bn = nn.BatchNorm1D(4)
        sd = bn.state_dict()
        assert "_mean" in sd and "_variance" in sd

    def test_train_eval(self):
        m = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
        m.eval()
        assert not m[1].training
        x = paddle.ones([8, 4])
        np.testing.assert_allclose(m[1](x).numpy(), 1.0)
        m.train()
        assert m[1].training

    def test_hooks(self):
        m = nn.Linear(2, 2)
        calls = []
        m.register_forward_pre_hook(lambda l, inp: calls.append("pre"))
        m.register_forward_post_hook(lambda l, inp, out: calls.append("post"))
        m(paddle.ones([1, 2]))
        assert calls == ["pre", "post"]

    def test_apply_and_to(self):
        m = nn.Linear(2, 2)
        m.to(dtype="bfloat16")
        assert m.weight.dtype == paddle.bfloat16

    def test_sublayers_containers(self):
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(ll) == 3
        ll.append(nn.Linear(2, 2))
        assert len(list(ll.parameters())) == 8
        ld = nn.LayerDict({"a": nn.Linear(2, 2)})
        assert "a" in ld


class TestLayers:
    def test_linear(self):
        m = nn.Linear(3, 5)
        x = paddle.randn([4, 3])
        out = m(x)
        assert out.shape == [4, 5]
        want = x.numpy() @ m.weight.numpy() + m.bias.numpy()
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-5)

    def test_embedding(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        out = emb(paddle.to_tensor([[1, 0, 3]]))
        assert out.shape == [1, 3, 4]
        np.testing.assert_allclose(out.numpy()[0, 1], 0.0)

    def test_layernorm_matches_numpy(self):
        ln = nn.LayerNorm(8)
        x = rng.normal(size=(2, 8)).astype(np.float32)
        out = ln(paddle.to_tensor(x)).numpy()
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        want = (x - mu) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    def test_rmsnorm(self):
        m = nn.RMSNorm(8)
        x = rng.normal(size=(2, 8)).astype(np.float32)
        out = m(paddle.to_tensor(x)).numpy()
        want = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    def test_batchnorm_train_and_eval(self):
        bn = nn.BatchNorm1D(4)
        x = paddle.to_tensor(rng.normal(size=(16, 4)).astype(np.float32))
        out = bn(x)
        assert abs(out.numpy().mean()) < 1e-5
        # running stats moved
        assert not np.allclose(bn._mean.numpy(), 0.0)
        bn.eval()
        out2 = bn(x)
        assert out2.shape == [16, 4]

    def test_conv2d_shape_and_value(self):
        conv = nn.Conv2D(3, 8, 3, padding=1)
        x = paddle.randn([2, 3, 16, 16])
        assert conv(x).shape == [2, 8, 16, 16]
        # value check vs manual correlation for 1x1 kernel
        c1 = nn.Conv2D(2, 3, 1, bias_attr=False)
        xi = rng.normal(size=(1, 2, 4, 4)).astype(np.float32)
        out = c1(paddle.to_tensor(xi)).numpy()
        w = c1.weight.numpy()  # (3,2,1,1)
        want = np.einsum("nchw,oc->nohw", xi, w[:, :, 0, 0])
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    def test_conv_transpose_shape(self):
        m = nn.Conv2DTranspose(4, 2, 3, stride=2, padding=1)
        x = paddle.randn([1, 4, 8, 8])
        assert m(x).shape == [1, 2, 15, 15]

    def test_pool(self):
        x = paddle.to_tensor(
            np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        mp = nn.MaxPool2D(2, 2)(x)
        np.testing.assert_allclose(mp.numpy()[0, 0],
                                   [[5, 7], [13, 15]])
        ap = nn.AvgPool2D(2, 2)(x)
        np.testing.assert_allclose(ap.numpy()[0, 0],
                                   [[2.5, 4.5], [10.5, 12.5]])
        aap = nn.AdaptiveAvgPool2D(1)(x)
        np.testing.assert_allclose(aap.numpy()[0, 0, 0, 0], 7.5)

    def test_activations(self):
        x = paddle.to_tensor([-1.0, 0.0, 1.0])
        np.testing.assert_allclose(nn.ReLU()(x).numpy(), [0, 0, 1])
        assert nn.GELU()(x).shape == [3]
        assert nn.Softmax()(x).numpy().sum() == pytest.approx(1.0, rel=1e-5)

    def test_dropout_scaling(self):
        paddle.seed(7)
        x = paddle.ones([1000])
        d = nn.Dropout(0.5)
        out = d(x)
        kept = out.numpy()[out.numpy() > 0]
        np.testing.assert_allclose(kept, 2.0)  # upscale_in_train

    def test_multihead_attention(self):
        m = nn.MultiHeadAttention(16, 4)
        x = paddle.randn([2, 5, 16])
        out = m(x)
        assert out.shape == [2, 5, 16]

    def test_transformer_encoder(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        x = paddle.randn([2, 6, 16])
        assert enc(x).shape == [2, 6, 16]

    def test_lstm_gru(self):
        lstm = nn.LSTM(4, 8, num_layers=1)
        x = paddle.randn([2, 5, 4])
        out, (h, c) = lstm(x)
        assert out.shape == [2, 5, 8]
        assert h.shape == [1, 2, 8]
        gru = nn.GRU(4, 8, direction="bidirect")
        out2, h2 = gru(x)
        assert out2.shape == [2, 5, 16]

    def test_grad_flows_through_layer(self):
        m = nn.Linear(3, 2)
        x = paddle.randn([4, 3])
        loss = m(x).sum()
        loss.backward()
        assert m.weight.grad is not None
        assert m.weight.grad.shape == [3, 2]


class TestFunctionalLoss:
    def test_cross_entropy_vs_numpy(self):
        logits = rng.normal(size=(8, 5)).astype(np.float32)
        labels = rng.integers(0, 5, 8)
        loss = F.cross_entropy(paddle.to_tensor(logits),
                               paddle.to_tensor(labels))
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        want = -np.log(p[np.arange(8), labels]).mean()
        assert float(loss) == pytest.approx(want, rel=1e-4)

    def test_cross_entropy_ignore_index(self):
        logits = rng.normal(size=(4, 3)).astype(np.float32)
        labels = np.array([0, -100, 2, -100])
        loss = F.cross_entropy(paddle.to_tensor(logits),
                               paddle.to_tensor(labels), ignore_index=-100)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        want = -np.log(p[[0, 2], [0, 2]]).mean()
        assert float(loss) == pytest.approx(want, rel=1e-4)

    def test_soft_label_and_smoothing(self):
        logits = rng.normal(size=(4, 3)).astype(np.float32)
        soft = np.float32(np.eye(3)[[0, 1, 2, 0]])
        l1 = F.cross_entropy(paddle.to_tensor(logits),
                             paddle.to_tensor(soft), soft_label=True)
        l2 = F.cross_entropy(paddle.to_tensor(logits),
                             paddle.to_tensor(np.array([0, 1, 2, 0])))
        assert float(l1) == pytest.approx(float(l2), rel=1e-4)

    def test_mse_l1(self):
        a = rng.normal(size=(3, 3)).astype(np.float32)
        b = rng.normal(size=(3, 3)).astype(np.float32)
        assert float(F.mse_loss(paddle.to_tensor(a), paddle.to_tensor(b))) \
            == pytest.approx(((a - b) ** 2).mean(), rel=1e-5)
        assert float(F.l1_loss(paddle.to_tensor(a), paddle.to_tensor(b))) \
            == pytest.approx(np.abs(a - b).mean(), rel=1e-5)

    def test_bce_with_logits(self):
        z = rng.normal(size=(6,)).astype(np.float32)
        y = (rng.random(6) > 0.5).astype(np.float32)
        got = float(F.binary_cross_entropy_with_logits(
            paddle.to_tensor(z), paddle.to_tensor(y)))
        p = 1 / (1 + np.exp(-z))
        want = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
        assert got == pytest.approx(want, rel=1e-4)

    def test_kl_div(self):
        logp = np.log(np.float32([[0.3, 0.7], [0.5, 0.5]]))
        t = np.float32([[0.4, 0.6], [0.2, 0.8]])
        got = float(F.kl_div(paddle.to_tensor(logp), paddle.to_tensor(t),
                             reduction="sum"))
        want = (t * (np.log(t) - logp)).sum()
        assert got == pytest.approx(want, rel=1e-4)


class TestAttentionFunctional:
    def test_sdpa_matches_naive(self):
        q = rng.normal(size=(2, 4, 2, 8)).astype(np.float32)
        k = rng.normal(size=(2, 4, 2, 8)).astype(np.float32)
        v = rng.normal(size=(2, 4, 2, 8)).astype(np.float32)
        out = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v))
        # naive reference
        qb = q.transpose(0, 2, 1, 3)
        kb = k.transpose(0, 2, 1, 3)
        vb = v.transpose(0, 2, 1, 3)
        logits = qb @ kb.transpose(0, 1, 3, 2) / np.sqrt(8)
        w = np.exp(logits - logits.max(-1, keepdims=True))
        w = w / w.sum(-1, keepdims=True)
        want = (w @ vb).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-4, atol=1e-5)

    def test_sdpa_causal(self):
        q = rng.normal(size=(1, 4, 1, 4)).astype(np.float32)
        out = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            is_causal=True)
        assert out.shape == [1, 4, 1, 4]

    def test_softmax_logsoftmax(self):
        x = rng.normal(size=(3, 5)).astype(np.float32)
        s = F.softmax(paddle.to_tensor(x), axis=-1).numpy()
        np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)
        ls = F.log_softmax(paddle.to_tensor(x), axis=-1).numpy()
        np.testing.assert_allclose(np.exp(ls), s, rtol=1e-4, atol=1e-6)


class TestDropoutModes:
    def test_downscale_in_infer_scales_at_inference(self):
        """paddle semantics: downscale_in_infer multiplies by (1-p) at
        inference (was silently identity before round 3)."""
        from paddle_tpu.nn import functional as F
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        out = F.dropout(x, p=0.25, training=False,
                        mode="downscale_in_infer")
        np.testing.assert_allclose(np.asarray(out._value), 0.75)
        # upscale_in_train is identity at inference
        out2 = F.dropout(x, p=0.25, training=False)
        np.testing.assert_allclose(np.asarray(out2._value), 1.0)
        # train-mode downscale keeps raw values (no 1/(1-p))
        paddle.seed(0)
        out3 = np.asarray(F.dropout(x, p=0.5, training=True,
                                    mode="downscale_in_infer")._value)
        assert set(np.unique(out3)).issubset({0.0, 1.0})


class TestWeightNorm:
    """nn.utils weight/spectral norm hooks (round 3; formerly no-op shims,
    VERDICT r2 padded-files list)."""

    def test_weight_norm_reconstructs_and_trains(self):
        paddle.seed(0)
        l = nn.Linear(4, 3)
        w0 = np.asarray(l.weight._value).copy()
        nn.utils.weight_norm(l, "weight", dim=1)
        assert "weight_g" in l._parameters and "weight_v" in l._parameters
        np.testing.assert_allclose(np.asarray(l.weight._value), w0,
                                   rtol=1e-5)
        x = paddle.to_tensor(np.random.default_rng(0)
                             .normal(size=(2, 4)).astype(np.float32))
        out = l(x)
        out.sum().backward()
        assert l.weight_g.grad is not None
        assert l.weight_v.grad is not None

    def test_remove_weight_norm(self):
        paddle.seed(0)
        l = nn.Linear(4, 3)
        w0 = np.asarray(l.weight._value).copy()
        nn.utils.weight_norm(l, "weight")
        nn.utils.remove_weight_norm(l, "weight")
        assert sorted(l._parameters.keys()) == ["bias", "weight"]
        np.testing.assert_allclose(np.asarray(l.weight._value), w0,
                                   rtol=1e-5)

    def test_spectral_norm_unit_sigma(self):
        paddle.seed(1)
        l = nn.Linear(4, 4)
        nn.utils.spectral_norm(l, "weight", n_power_iterations=8)
        x = paddle.to_tensor(np.random.default_rng(1)
                             .normal(size=(2, 4)).astype(np.float32))
        out = l(x)
        sv = np.linalg.svd(np.asarray(l.weight._value),
                           compute_uv=False)
        assert abs(float(sv[0]) - 1.0) < 1e-3
        out.sum().backward()
        assert l.weight_orig.grad is not None


class TestMaxUnpool:
    def test_unpool2d_roundtrip_matches_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.default_rng(5).normal(size=(2, 3, 8, 8)) \
            .astype(np.float32)
        out, mask = F.max_pool2d(paddle.to_tensor(x), 2, 2,
                                 return_mask=True)
        rec = F.max_unpool2d(out, mask, 2, 2)
        tout, tidx = torch.nn.functional.max_pool2d(
            torch.tensor(x), 2, 2, return_indices=True)
        tref = torch.nn.functional.max_unpool2d(tout, tidx, 2, 2).numpy()
        np.testing.assert_allclose(np.asarray(rec._value), tref,
                                   rtol=1e-6)

    def test_unpool1d_and_layer(self):
        x = np.random.default_rng(6).normal(size=(1, 2, 8)) \
            .astype(np.float32)
        out, mask = F.max_pool1d(paddle.to_tensor(x), 2, 2,
                                 return_mask=True)
        up = nn.MaxUnPool1D(2, 2)(out, mask)
        assert tuple(up.shape) == (1, 2, 8)
        # every pooled max lands back at its original position
        rec = np.asarray(up._value)
        src = np.asarray(out._value)
        assert np.isin(src, rec).all()

    def test_unpool_grad_flows(self):
        x = paddle.to_tensor(np.random.default_rng(7)
                             .normal(size=(1, 1, 4, 4)).astype(np.float32),
                             stop_gradient=False)
        out, mask = F.max_pool2d(x, 2, 2, return_mask=True)
        up = F.max_unpool2d(out, mask, 2, 2)
        up.sum().backward()
        g = np.asarray(x.grad)
        assert g.sum() == 4.0  # one max per window passes gradient 1


class TestFractionalPool:
    def test_matches_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.default_rng(8).normal(size=(1, 2, 9, 9)) \
            .astype(np.float32)
        out = F.fractional_max_pool2d(paddle.to_tensor(x), output_size=3,
                                      random_u=0.5)
        # same u drives torch's _random_samples per (N, C, 2)
        t = torch.nn.functional.fractional_max_pool2d(
            torch.tensor(x), kernel_size=3, output_size=3,
            _random_samples=torch.full((1, 2, 2), 0.5))
        assert tuple(out.shape) == (1, 2, 3, 3)
        # boundary conventions differ slightly; check max-coverage property
        # instead: every output value must exist in the input and the
        # global max must survive pooling
        ov = np.asarray(out._value)
        assert np.isin(ov, x).all()
        assert x.max() == ov.max()

    def test_3d_and_layer(self):
        x = np.random.default_rng(9).normal(size=(1, 1, 8, 8, 8)) \
            .astype(np.float32)
        out = nn.FractionalMaxPool3D(output_size=2)(paddle.to_tensor(x))
        assert tuple(out.shape) == (1, 1, 2, 2, 2)
        assert np.asarray(out._value).max() == x.max()

    def test_grad(self):
        x = paddle.to_tensor(np.random.default_rng(10)
                             .normal(size=(1, 1, 8, 8)).astype(np.float32),
                             stop_gradient=False)
        out = F.fractional_max_pool2d(x, output_size=4, random_u=0.3)
        out.sum().backward()
        g = np.asarray(x.grad)
        assert g.sum() == 16.0  # one max per bin

    def test_return_mask_indices(self):
        # ADVICE r3: return_mask must return real flat argmax indices
        # (max_pool convention), not None
        x = np.random.default_rng(11).normal(size=(2, 3, 9, 9)) \
            .astype(np.float32)
        out, mask = F.fractional_max_pool2d(paddle.to_tensor(x),
                                            output_size=3, random_u=0.4,
                                            return_mask=True)
        ov, mv = np.asarray(out._value), np.asarray(mask._value)
        assert mv.dtype == np.int32 and mv.shape == ov.shape
        # gathering the input at the mask indices recovers the outputs
        flat = x.reshape(2, 3, -1)
        for b in range(2):
            for c in range(3):
                np.testing.assert_array_equal(
                    flat[b, c][mv[b, c].ravel()], ov[b, c].ravel())
        # and the mask round-trips through max_unpool2d: unpooled map has
        # exactly the pooled values at the mask positions
        up = F.max_unpool2d(out, mask, kernel_size=3, output_size=[9, 9])
        uv = np.asarray(up._value)
        assert uv.shape == x.shape
        for b in range(2):
            for c in range(3):
                np.testing.assert_array_equal(
                    uv[b, c].ravel()[mv[b, c].ravel()], ov[b, c].ravel())
        assert (uv != 0).sum() <= 2 * 3 * 9  # sparse elsewhere

    def test_return_mask_3d(self):
        x = np.random.default_rng(12).normal(size=(1, 2, 6, 6, 6)) \
            .astype(np.float32)
        out, mask = F.fractional_max_pool3d(paddle.to_tensor(x),
                                            output_size=2, random_u=0.7,
                                            return_mask=True)
        ov, mv = np.asarray(out._value), np.asarray(mask._value)
        flat = x.reshape(1, 2, -1)
        for c in range(2):
            np.testing.assert_array_equal(
                flat[0, c][mv[0, c].ravel()], ov[0, c].ravel())


class TestAmpDebugging:
    def test_check_numerics_and_stats(self):
        from paddle_tpu.amp import debugging as dbg
        t = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        n_nan, n_inf, n_zero = dbg.check_numerics(t)
        assert int(n_zero._value) == 1
        bad = paddle.to_tensor(np.array([np.nan], np.float32))
        with pytest.raises(RuntimeError, match="NaN"):
            dbg.check_numerics(bad, "op", "x")
        dbg.enable_operator_stats_collection()
        _ = t + t
        _ = t + t
        _ = t * t
        stats = dbg.disable_operator_stats_collection()
        assert stats["add:float32"] == 2
        assert stats["multiply:float32"] == 1
        from paddle_tpu.core import tensor as ct
        assert ct._op_observer is None

    def test_unflatten_layer(self):
        u = nn.Unflatten(1, [2, 3])
        x = paddle.to_tensor(np.zeros((4, 6), np.float32))
        assert tuple(u(x).shape) == (4, 2, 3)


class TestAdaptiveLogSoftmax:
    def test_forward_parity_with_full_logprob(self):
        paddle.seed(0)
        m = nn.AdaptiveLogSoftmaxWithLoss(16, 100, cutoffs=[10, 40])
        x = paddle.to_tensor(np.random.default_rng(0)
                             .normal(size=(8, 16)).astype(np.float32))
        y = paddle.to_tensor(np.array([1, 5, 12, 45, 99, 0, 33, 77],
                                      np.int64))
        out, loss = m(x, y)
        lp = m.log_prob(x)
        ref = np.take_along_axis(np.asarray(lp._value),
                                 np.asarray(y._value)[:, None], 1)[:, 0]
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.exp(np.asarray(lp._value)).sum(-1),
                                   1.0, rtol=1e-4)
        assert float(loss) == pytest.approx(-float(out.mean()), rel=1e-6)

    def test_matches_torch(self):
        torch = pytest.importorskip("torch")
        paddle.seed(0)
        m = nn.AdaptiveLogSoftmaxWithLoss(8, 20, cutoffs=[4, 10],
                                          div_value=2.0)
        tm = torch.nn.AdaptiveLogSoftmaxWithLoss(8, 20, cutoffs=[4, 10],
                                                 div_value=2.0,
                                                 head_bias=False)
        # copy our params into torch (head + tails)
        with torch.no_grad():
            tm.head.weight.copy_(torch.tensor(
                np.asarray(m.head.weight._value).T))
            for i in range(2):
                tm.tail[i][0].weight.copy_(torch.tensor(
                    np.asarray(m.tail[i]._sub_layers['0'].weight._value).T))
                tm.tail[i][1].weight.copy_(torch.tensor(
                    np.asarray(m.tail[i]._sub_layers['1'].weight._value).T))
        x = np.random.default_rng(1).normal(size=(6, 8)).astype(np.float32)
        y = np.array([0, 3, 5, 9, 12, 19], np.int64)
        out, loss = m(paddle.to_tensor(x), paddle.to_tensor(y))
        t_out, t_loss = tm(torch.tensor(x), torch.tensor(y))
        np.testing.assert_allclose(np.asarray(out._value),
                                   t_out.detach().numpy(), rtol=1e-4,
                                   atol=1e-5)
        assert float(loss) == pytest.approx(float(t_loss), rel=1e-4)

    def test_grad_and_predict(self):
        paddle.seed(0)
        m = nn.AdaptiveLogSoftmaxWithLoss(8, 20, cutoffs=[5])
        x = paddle.to_tensor(np.random.default_rng(2)
                             .normal(size=(4, 8)).astype(np.float32),
                             stop_gradient=False)
        y = paddle.to_tensor(np.array([0, 6, 19, 2], np.int64))
        _, loss = m(x, y)
        loss.backward()
        assert x.grad is not None
        pred = m.predict(x)
        assert tuple(pred.shape) == (4,)
