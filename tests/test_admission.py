"""QoS-tiered admission (serving/admission.py + the router/engine
wiring, ISSUE 11): lane-aware queue ordering, sliding-window tenant
budgets, burn-arbitrated shed ordering, the unified retry_after
semantics, and the admission.decide fail-OPEN chaos discipline.
conftest runs this file with PDT_TELEMETRY=1 and
PDT_CHECK_INVARIANTS=1."""
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.serving import (ContinuousBatchingEngine,
                                       EngineOverloaded)
from paddle_tpu.observability.slo import SloMonitor, SloObjective
from paddle_tpu.serving import (FleetOverloaded, Lane, QosAdmission,
                                QosShed, ServingRouter, TenantBudget,
                                derive_retry_after)
from paddle_tpu.utils.faults import FaultError, FaultInjector

pytestmark = pytest.mark.chaos


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=1, max_position_embeddings=64)
    paddle.seed(7)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _engine(model, clock=None, **kw):
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", 4)
    return ContinuousBatchingEngine(model, clock=clock, **kw)


def _monitor(clock, threshold=0.1, window=60.0):
    return SloMonitor(
        [SloObjective("interactive_ttft_p95", "ttft.interactive",
                      "latency", threshold, quantile=0.95,
                      window_s=window)],
        clock=clock)


def _burn(mon, n=20, value=1.0):
    """Feed `n` breach-shaped interactive TTFT samples (all past the
    0.1s objective -> burn = 1/0.05 = 20)."""
    for _ in range(n):
        mon.observe("ttft.interactive", value)


class TestDeriveRetryAfter:
    def test_floor_is_base(self):
        assert derive_retry_after(0.05) == 0.05

    def test_queue_drain_term(self):
        assert derive_retry_after(0.05, queue_depth=10) == \
            pytest.approx(0.5)

    def test_burn_term(self):
        assert derive_retry_after(0.05, burn_rate=20.0) == \
            pytest.approx(1.0)

    def test_restart_wait_term(self):
        assert derive_retry_after(0.05, restart_wait=3.0) == 3.0

    def test_strongest_wins(self):
        assert derive_retry_after(0.1, queue_depth=4, burn_rate=2.0,
                                  restart_wait=0.3) == \
            pytest.approx(0.4)

    def test_cap(self):
        assert derive_retry_after(0.05, burn_rate=1e12, cap=60.0) == 60.0

    # -- property sweeps (ISSUE 16 satellite): the hint is a sane
    # backoff function over its whole input range, not just the
    # point cases above
    _DEPTHS = (0, 1, 2, 5, 17, 100, 10_000)
    _BURNS = (0.0, 0.3, 1.0, 2.5, 20.0, 1e6)
    _BASES = (0.01, 0.05, 1.0)

    def test_monotone_in_queue_depth(self):
        for base in self._BASES:
            for burn in self._BURNS:
                hints = [derive_retry_after(base, queue_depth=d,
                                            burn_rate=burn)
                         for d in self._DEPTHS]
                assert hints == sorted(hints), \
                    f"depth-monotonicity broke at base={base} " \
                    f"burn={burn}: {hints}"

    def test_monotone_in_burn_rate(self):
        for base in self._BASES:
            for depth in self._DEPTHS:
                hints = [derive_retry_after(base, queue_depth=depth,
                                            burn_rate=b)
                         for b in self._BURNS]
                assert hints == sorted(hints), \
                    f"burn-monotonicity broke at base={base} " \
                    f"depth={depth}: {hints}"

    def test_floored_at_base_capped_at_cap_everywhere(self):
        for base in self._BASES:
            for depth in self._DEPTHS:
                for burn in self._BURNS:
                    h = derive_retry_after(base, queue_depth=depth,
                                           burn_rate=burn)
                    assert base <= h <= 60.0
                    assert derive_retry_after(
                        base, queue_depth=depth, burn_rate=burn,
                        cap=2.0) <= 2.0

    def test_negative_burn_never_undercuts_the_floor(self):
        assert derive_retry_after(0.05, burn_rate=-5.0) == 0.05

    def test_autoscaler_cooldown_never_undercuts_retry_after(
            self, model):
        """The flapping-guard invariant (serving/autoscaler.py
        `cooldown_for`): whatever retry-after hint the fleet handed
        its shed clients under some (depth, burn) pressure, the
        autoscaler's post-action cooldown under the SAME pressure is
        at least as long — capacity cannot flap away before the
        clients it turned away were told to come back."""
        from paddle_tpu.serving import (AutoscaleObservation,
                                        AutoscalePolicy,
                                        FleetAutoscaler)
        clock = FakeClock()
        router = _qos_router(model, clock, None, None)
        scaler = FleetAutoscaler(
            router, AutoscalePolicy(cooldown_s=0.0), clock=clock)
        for depth in self._DEPTHS:
            for burn in self._BURNS:
                obs = AutoscaleObservation(
                    t=clock(), arrival_qps=0.0, queue_depth=depth,
                    queue_min=depth, burn=burn, replicas=2,
                    serving=2, quarantined=0, journal_failing=False)
                hint = derive_retry_after(router._retry_cost,
                                          queue_depth=depth,
                                          burn_rate=burn)
                assert scaler.cooldown_for(obs) >= hint
        # and the policy floor still rules when it is the larger term
        slow = FleetAutoscaler(
            router, AutoscalePolicy(cooldown_s=45.0), clock=clock)
        assert slow.cooldown_for(AutoscaleObservation(
            t=0.0, arrival_qps=0.0, queue_depth=0, queue_min=0,
            burn=0.0, replicas=2, serving=2, quarantined=0,
            journal_failing=False)) == 45.0


class TestTenantBudget:
    def test_sliding_window_refill(self):
        clock = FakeClock()
        b = TenantBudget(100, window_s=10.0, clock=clock)
        b.charge(80)
        assert b.used() == 80 and not b.over()
        clock.advance(5.0)
        b.charge(40)
        assert b.used() == 120 and b.over()
        clock.advance(5.5)               # first charge expired
        assert b.used() == 40 and not b.over()
        clock.advance(5.0)               # second charge expired too
        assert b.used() == 0

    def test_validation(self):
        clock = FakeClock()
        with pytest.raises(ValueError):
            TenantBudget(0, 10.0, clock)
        with pytest.raises(ValueError):
            TenantBudget(10, 0.0, clock)


class TestLaneOrdering:
    def test_lane_constants_match_trace_module(self):
        # trace.py is stdlib-only by design and duplicates the lane
        # literals; this is the drift pin
        from paddle_tpu.loadgen import trace
        assert trace.LANE_INTERACTIVE == Lane.INTERACTIVE
        assert trace.LANE_BATCH == Lane.BATCH
        assert Lane.PRIORITY[Lane.INTERACTIVE] \
            < Lane.PRIORITY[Lane.BATCH]

    def test_queue_orders_by_priority_fifo_within(self, model):
        eng = _engine(model, max_batch_size=1)
        eng.add_request([1, 2], 2, priority=1, request_id="b0")
        eng.add_request([3, 4], 2, priority=0, request_id="i0")
        eng.add_request([5, 6], 2, priority=1, request_id="b1")
        eng.add_request([7, 8], 2, priority=0, request_id="i1")
        assert [r.request_id for r in eng._queue] == \
            ["i0", "i1", "b0", "b1"]

    def test_interactive_claims_slot_before_queued_batch(self, model):
        eng = _engine(model, max_batch_size=1)
        eng.add_request([1, 2, 3], 6, priority=1, request_id="batch")
        eng.add_request([4, 5, 6], 6, priority=0,
                        request_id="interactive")
        eng.step()
        running = [r for r in eng._slot_req if r is not None]
        assert [r.request_id for r in running] == ["interactive"]

    def test_requeue_reenters_head_of_own_class(self, model):
        eng = _engine(model)
        eng.add_request([1, 2], 4, priority=0, request_id="i0")
        eng.add_request([3, 4], 4, priority=1, request_id="b0")
        victim = eng._queue[1]
        eng._queue.remove(victim)
        eng._requeue_or_starve(victim, [])
        # ahead of nothing batch-side, but never ahead of interactive
        assert [r.request_id for r in eng._queue] == ["i0", "b0"]
        eng.run()

    def test_priority_survives_migration_payload(self, model):
        src = _engine(model, max_batch_size=1)
        dst = _engine(model, max_batch_size=1)
        rid = src.add_request([5, 4, 3, 2], 6, priority=1)
        src.step()                       # prefill: now RUNNING
        payload = src.export_pages(rid)
        assert payload["priority"] == 1
        req = dst.import_pages(payload)
        assert req.priority == 1
        src.evict_request(rid)


class TestQosDecide:
    def test_no_monitor_admits_everything(self):
        clock = FakeClock()
        qos = QosAdmission(clock=clock)
        for lane in (Lane.INTERACTIVE, Lane.BATCH):
            d = qos.decide(prompt_tokens=4, max_new_tokens=4,
                           lane=lane)
            assert d.admit and d.reason == "ok"
            qos.commit(d)
        # the ledger moves at COMMIT, not at the verdict
        assert qos.stats()["lanes"][Lane.BATCH]["admitted"] == 1
        d = qos.decide(prompt_tokens=4, max_new_tokens=4,
                       lane=Lane.BATCH)
        assert d.admit                   # verdict without commit:
        assert qos.stats()["lanes"][Lane.BATCH]["admitted"] == 1

    def test_burn_sheds_batch_not_interactive(self):
        clock = FakeClock()
        mon = _monitor(clock)
        _burn(mon)
        qos = QosAdmission(slo_monitor=mon,
                           shed_objective="interactive_ttft_p95",
                           shed_burn=1.0, clock=clock)
        assert qos.current_burn() > 1.0
        shed = qos.decide(prompt_tokens=4, max_new_tokens=4,
                          lane=Lane.BATCH)
        assert not shed.admit and shed.reason == "burn"
        assert shed.retry_after > 0
        ok = qos.decide(prompt_tokens=4, max_new_tokens=4,
                        lane=Lane.INTERACTIVE)
        assert ok.admit
        snap = telemetry.snapshot()["counters"]
        assert snap["pdt_admission_shed_total"][
            'lane="batch",reason="burn"'] == 1

    def test_over_budget_tenant_sheds_first_any_lane(self):
        clock = FakeClock()
        mon = _monitor(clock)
        _burn(mon)
        qos = QosAdmission(slo_monitor=mon,
                           shed_objective="interactive_ttft_p95",
                           shed_burn=1.0, budgets={"hog": 10},
                           clock=clock)
        d = qos.decide(prompt_tokens=8, max_new_tokens=8,
                       lane=Lane.INTERACTIVE, tenant="hog")
        assert d.admit                   # in budget so far
        qos.commit(d)                    # 16 tokens charged: now over
        d2 = qos.decide(prompt_tokens=8, max_new_tokens=8,
                        lane=Lane.INTERACTIVE, tenant="hog")
        assert not d2.admit and d2.reason == "tenant_budget"
        # a different, in-budget tenant still admits interactively
        d3 = qos.decide(prompt_tokens=8, max_new_tokens=8,
                        lane=Lane.INTERACTIVE, tenant="polite")
        assert d3.admit

    def test_budgets_idle_without_burn_by_default(self):
        clock = FakeClock()
        qos = QosAdmission(budgets={"hog": 10}, clock=clock)
        d = qos.decide(prompt_tokens=50, max_new_tokens=50,
                       lane=Lane.BATCH, tenant="hog")
        qos.commit(d)
        assert qos.over_budget("hog")
        # burn is 0 (no monitor): under_burn enforcement stays open
        assert qos.decide(prompt_tokens=4, max_new_tokens=4,
                          lane=Lane.BATCH, tenant="hog").admit

    def test_enforce_budgets_always(self):
        clock = FakeClock()
        qos = QosAdmission(budgets={"hog": 10},
                           enforce_budgets="always", clock=clock)
        qos.commit(qos.decide(prompt_tokens=20, max_new_tokens=20,
                              lane=Lane.BATCH, tenant="hog"))
        d = qos.decide(prompt_tokens=4, max_new_tokens=4,
                       lane=Lane.BATCH, tenant="hog")
        assert not d.admit and d.reason == "tenant_budget"

    def test_commit_not_decide_charges_the_budget(self):
        clock = FakeClock()
        qos = QosAdmission(tenant_budget_tokens=100, clock=clock)
        d = qos.decide(prompt_tokens=30, max_new_tokens=30,
                       lane=Lane.BATCH, tenant="t")
        assert qos.budget_for("t").used() == 0
        qos.commit(d)
        assert qos.budget_for("t").used() == 60

    def test_budget_map_bounded_by_live_charges(self):
        # shed verdicts / unseen tenants never allocate, and drained
        # default-budget tenants prune — the map tracks tenants with
        # LIVE charges, not tenants ever seen
        clock = FakeClock()
        qos = QosAdmission(tenant_budget_tokens=100,
                           tenant_window_s=5.0, clock=clock)
        for i in range(50):
            qos.decide(prompt_tokens=4, max_new_tokens=4,
                       lane=Lane.BATCH, tenant=f"drive-by-{i}")
        assert len(qos._budgets) == 0       # verdicts alone: no entry
        qos.commit(qos.decide(prompt_tokens=4, max_new_tokens=4,
                              lane=Lane.BATCH, tenant="t0"))
        assert len(qos._budgets) == 1
        clock.advance(6.0)                  # window drained
        assert not qos.over_budget("t0")
        assert len(qos._budgets) == 0       # pruned
        # override-configured budgets are permanent
        qos2 = QosAdmission(budgets={"vip": 10}, clock=clock)
        assert not qos2.over_budget("vip")
        assert "vip" in qos2._budgets

    def test_over_budget_gauge_fresh_from_decide_path(self):
        clock = FakeClock()
        qos = QosAdmission(budgets={"hog": 10},
                           reeval_interval_s=0.25, clock=clock)
        qos.commit(qos.decide(prompt_tokens=20, max_new_tokens=20,
                              lane=Lane.BATCH, tenant="hog"))
        clock.advance(0.3)                  # past the refresh cadence
        qos.decide(prompt_tokens=1, max_new_tokens=1,
                   lane=Lane.BATCH, tenant="other")
        assert telemetry.value(
            "pdt_admission_tenants_over_budget") == 1

    def test_burn_reevaluation_is_cached(self):
        clock = FakeClock()
        mon = _monitor(clock)
        qos = QosAdmission(slo_monitor=mon,
                           shed_objective="interactive_ttft_p95",
                           reeval_interval_s=1.0, clock=clock)
        assert qos.current_burn() == 0.0
        _burn(mon)
        assert qos.current_burn() == 0.0     # cached verdict
        clock.advance(1.0)
        assert qos.current_burn() > 1.0      # re-evaluated

    def test_unknown_lane_and_bad_config(self):
        clock = FakeClock()
        qos = QosAdmission(clock=clock)
        with pytest.raises(ValueError):
            qos.decide(prompt_tokens=1, max_new_tokens=1, lane="vip")
        with pytest.raises(ValueError):
            QosAdmission(enforce_budgets="sometimes")
        with pytest.raises(ValueError):
            QosAdmission(shed_burn=0.0)
        with pytest.raises(ValueError):
            # must fail at construction, never inside a post-dispatch
            # commit
            QosAdmission(tenant_budget_tokens=0)


def _qos_router(model, clock, qos, mon, **kw):
    kw.setdefault("page_size", 4)
    kw.setdefault("sleep", clock.advance)

    def factory(index):
        return _engine(model, clock=clock)

    return ServingRouter(factory, num_replicas=2,
                         policy="least_outstanding", clock=clock,
                         slo_monitor=mon, admission=qos, **kw)


class TestRouterQos:
    def test_shed_is_429_shaped_with_retry_after(self, model):
        clock = FakeClock()
        mon = _monitor(clock)
        _burn(mon)
        qos = QosAdmission(slo_monitor=mon,
                           shed_objective="interactive_ttft_p95",
                           shed_burn=1.0, clock=clock)
        router = _qos_router(model, clock, qos, mon)
        with pytest.raises(QosShed) as e:
            router.submit([1, 2, 3], 4, lane=Lane.BATCH,
                          tenant="acme")
        assert isinstance(e.value, FleetOverloaded)
        assert isinstance(e.value, EngineOverloaded)   # 429
        assert e.value.retry_after > 0
        assert e.value.reason == "burn"
        assert telemetry.value("pdt_router_rejections_total",
                               reason="qos_shed") == 1
        # the protected lane still admits, with its queue priority
        rid = router.submit([1, 2, 3], 4, lane=Lane.INTERACTIVE)
        assert router.requests[rid].priority == 0
        assert router.requests[rid].engine_req.priority == 0
        router.run()

    def test_admits_reconcile_with_terminals(self, model):
        clock = FakeClock()
        mon = _monitor(clock, threshold=10.0)   # never burns
        qos = QosAdmission(slo_monitor=mon,
                           shed_objective="interactive_ttft_p95",
                           clock=clock)
        router = _qos_router(model, clock, qos, mon)
        for i in range(4):
            router.submit([5, 4, 3 + i], 4,
                          lane=Lane.BATCH if i % 2 else
                          Lane.INTERACTIVE, tenant=f"t{i % 2}")
        router.run()
        admits = telemetry.value("pdt_admission_decisions_total",
                                 lane="interactive",
                                 decision="admit") + \
            telemetry.value("pdt_admission_decisions_total",
                            lane="batch", decision="admit")
        terminals = sum(
            v for v in telemetry.snapshot()["counters"]
            ["pdt_router_requests_terminal_total"].values())
        assert admits == terminals == 4
        info = router.fleet_info()
        assert info["admission"]["lanes"]["interactive"][
            "admitted"] == 2

    def test_unknown_lane_rejected_before_admission(self, model):
        clock = FakeClock()
        router = _qos_router(model, clock, None, None)
        with pytest.raises(ValueError):
            router.submit([1, 2], 2, lane="vip")

    def test_backpressure_retry_after_includes_burn(self, model):
        clock = FakeClock()
        mon = _monitor(clock)
        _burn(mon)                       # burn = 20
        qos = QosAdmission(slo_monitor=mon,
                           shed_objective="interactive_ttft_p95",
                           shed_burn=1e9,     # never QoS-shed here
                           clock=clock)
        router = _qos_router(model, clock, qos, mon,
                             max_replica_outstanding=1)
        router.submit([5, 4, 3], 4)
        router.submit([9, 1, 2], 4)
        with pytest.raises(FleetOverloaded) as e:
            router.submit([7, 7, 1], 4)
        # unified semantics: the burn term (0.05 * 20 = 1.0) dominates
        # the depth term here
        assert e.value.retry_after == pytest.approx(
            derive_retry_after(0.05, queue_depth=1,
                               burn_rate=qos.current_burn()))
        router.run()


class TestFailOpen:
    def test_router_submits_survive_admission_fault(self, model):
        clock = FakeClock()
        mon = _monitor(clock)
        _burn(mon)                       # shedding SHOULD be active
        qos = QosAdmission(slo_monitor=mon,
                           shed_objective="interactive_ttft_p95",
                           shed_burn=1.0, clock=clock)
        router = _qos_router(model, clock, qos, mon)
        with FaultInjector(seed=0) as fi:
            fi.arm("admission.decide", always=True)
            # a dead admission brain degrades to FIFO: even the batch
            # lane admits — degrade, never wedge
            rid = router.submit([1, 2, 3], 4, lane=Lane.BATCH)
            assert router.requests[rid].engine_req is not None
            assert fi.trips("admission.decide") == 1
        assert telemetry.value("pdt_admission_failopen_total") == 1
        assert telemetry.value("pdt_faults_fired_total",
                               site="admission.decide") == 1
        # disarmed: the burn arbitration is back
        with pytest.raises(QosShed):
            router.submit([4, 5, 6], 4, lane=Lane.BATCH)
        router.run()

    def test_engine_policy_hook_sheds_and_fails_open(self, model):
        clock = FakeClock()
        mon = _monitor(clock)
        _burn(mon)
        qos = QosAdmission(slo_monitor=mon,
                           shed_objective="interactive_ttft_p95",
                           shed_burn=1.0, clock=clock)
        eng = _engine(model, clock=clock,
                      admission_policy=qos.engine_policy())
        with pytest.raises(EngineOverloaded):
            eng.add_request([1, 2, 3], 4, priority=1)   # batch: shed
        eng.add_request([1, 2, 3], 4, priority=0)       # protected
        with FaultInjector(seed=0) as fi:
            fi.arm("admission.decide", always=True)
            eng.add_request([4, 5, 6], 4, priority=1)   # fail open
            assert fi.trips("admission.decide") == 1
        assert telemetry.value("pdt_admission_failopen_total") == 1
        eng.run()

    def test_broken_commit_never_loses_a_dispatched_request(self,
                                                            model):
        # commit runs AFTER dispatch: a failure there must lose only
        # the bookkeeping, never the in-flight request
        clock = FakeClock()

        class BrokenCommit(QosAdmission):
            def commit(self, decision, now=None):
                raise RuntimeError("ledger on fire")

        qos = BrokenCommit(clock=clock)
        router = _qos_router(model, clock, qos, None)
        rid = router.submit([1, 2, 3], 4, lane=Lane.BATCH)
        assert rid in router.requests
        assert router.requests[rid].engine_req is not None
        assert telemetry.value("pdt_admission_failopen_total") == 1
        out = router.run()
        assert len(out[rid]) == 4        # served to completion

    def test_broken_monitor_never_wedges_submits(self, model):
        clock = FakeClock()

        class BrokenMonitor:
            def evaluate(self, export=True):
                raise RuntimeError("monitor on fire")

            def observe(self, *a, **k):
                pass

            def observe_outcome(self, *a, **k):
                pass

        qos = QosAdmission(slo_monitor=BrokenMonitor(), clock=clock)
        router = _qos_router(model, clock, qos, None)
        rid = router.submit([1, 2, 3], 4, lane=Lane.BATCH)
        assert rid in router.requests
        assert telemetry.value("pdt_admission_failopen_total") >= 1
        router.run()
