"""Distributed tests on the 8-virtual-CPU-device mesh (SURVEY.md §4:
fake-backend strategy replacing the reference's custom_cpu plugin tests;
convergence-parity oracle ≙ test_dist_base.TestDistBase)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

import jax


def _need8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")


class TestMesh:
    def test_process_mesh(self):
        _need8()
        mesh = dist.ProcessMesh(shape=(2, 4), dim_names=("dp", "mp"))
        assert mesh.shape == [2, 4]
        assert mesh.get_dim_size("mp") == 4
        assert len(mesh.process_ids) == 8

    def test_shard_tensor_placements(self):
        _need8()
        mesh = dist.create_mesh(dp=2, mp=4)
        x = paddle.randn([8, 16])
        xs = dist.shard_tensor(x, mesh, [dist.Shard(0), dist.Shard(1)])
        spec = xs._value.sharding.spec
        assert tuple(spec) == ("dp", "mp")
        np.testing.assert_allclose(xs.numpy(), x.numpy())
        # replicated
        xr = dist.shard_tensor(x, mesh, [dist.Replicate(), dist.Replicate()])
        assert tuple(xr._value.sharding.spec) == ()

    def test_reshard(self):
        _need8()
        mesh = dist.create_mesh(dp=2, mp=4)
        x = dist.shard_tensor(paddle.randn([8, 16]), mesh,
                              [dist.Shard(0), dist.Replicate()])
        y = dist.reshard(x, mesh, [dist.Replicate(), dist.Shard(1)])
        assert tuple(y._value.sharding.spec) == (None, "mp")
        np.testing.assert_allclose(x.numpy(), y.numpy())

    def test_hybrid_mesh_cpu_factoring(self):
        """VERDICT r4 #7: hybrid ICI x DCN mesh. On the CPU platform the
        dcn axes factor the flat list slowest-varying — contiguous device
        ids form each virtual slice."""
        _need8()
        mesh = dist.create_hybrid_mesh(dcn_axes={"dp": 2},
                                       ici_axes={"mp": 4})
        assert mesh.dim_names == ["dp", "mp"] and mesh.shape == [2, 4]
        dev = mesh.jax_mesh.devices
        # each dcn row is one virtual slice: contiguous ids
        ids = np.array([[d.id for d in row] for row in dev])
        assert ids[0].tolist() == sorted(ids[0].tolist())
        assert set(ids[0]) & set(ids[1]) == set()
        # a sharded matmul runs over it: dp batch-sharded, mp col-sharded
        x = dist.shard_tensor(paddle.randn([4, 16]), mesh,
                              [dist.Shard(0), dist.Replicate()])
        w = dist.shard_tensor(paddle.randn([16, 8]), mesh,
                              [dist.Replicate(), dist.Shard(1)])
        y = paddle.matmul(x, w)
        np.testing.assert_allclose(
            y.numpy(), x.numpy() @ w.numpy(), rtol=2e-5, atol=2e-5)

    def test_hybrid_mesh_validation(self):
        _need8()
        with pytest.raises(ValueError, match="both dcn_axes and ici_axes"):
            dist.create_hybrid_mesh(dcn_axes={"dp": 2})
        with pytest.raises(ValueError, match="duplicate axis"):
            dist.create_hybrid_mesh(dcn_axes={"dp": 2},
                                    ici_axes={"dp": 4})
        with pytest.raises(ValueError, match="devices"):
            dist.create_hybrid_mesh(dcn_axes={"dp": 64},
                                    ici_axes={"mp": 64})

    def test_spec_roundtrip(self):
        _need8()
        from paddle_tpu.distributed.mesh import (placements_to_spec,
                                                 spec_to_placements)
        mesh = dist.create_mesh(dp=2, mp=4)
        pl = [dist.Shard(1), dist.Replicate()]
        spec = placements_to_spec(pl, mesh)
        back = spec_to_placements(spec, mesh, 2)
        assert back == pl


class TestCollectives:
    def test_all_reduce_sum_max(self):
        _need8()
        g = dist.new_group(list(range(8)))
        t = g.stack([paddle.to_tensor([float(i), 1.0]) for i in range(8)])
        dist.all_reduce(t, group=g)
        np.testing.assert_allclose(t.numpy()[0], [28.0, 8.0])
        t2 = g.stack([paddle.to_tensor([float(i)]) for i in range(8)])
        dist.all_reduce(t2, op=dist.ReduceOp.MAX, group=g)
        np.testing.assert_allclose(t2.numpy()[3], [7.0])

    def test_all_gather(self):
        _need8()
        g = dist.new_group(list(range(8)))
        out = []
        dist.all_gather(out, g.stack(
            [paddle.to_tensor([float(i) * 2]) for i in range(8)]), group=g)
        assert len(out) == 8
        np.testing.assert_allclose([float(t) for t in out],
                                   [0, 2, 4, 6, 8, 10, 12, 14])

    def test_broadcast(self):
        _need8()
        g = dist.new_group(list(range(8)))
        t = g.stack([paddle.to_tensor([float(i)]) for i in range(8)])
        dist.broadcast(t, src=3, group=g)
        np.testing.assert_allclose(t.numpy().ravel(), 3.0)

    def test_reduce_scatter(self):
        _need8()
        g = dist.new_group(list(range(8)))
        # each rank holds vector of length 8; result rank i = sum slice i
        rows = [paddle.to_tensor(np.full(8, float(i), np.float32))
                for i in range(8)]
        out = dist.reduce_scatter(g.stack(rows), group=g)
        np.testing.assert_allclose(out.numpy().ravel(), 28.0)

    def test_alltoall(self):
        _need8()
        g = dist.new_group(list(range(8)))
        rows = [paddle.to_tensor(np.arange(8, dtype=np.float32) + 10 * i)
                for i in range(8)]
        out = []
        dist.alltoall(out, rows, group=g)
        # out[i][j] == in[j][i]
        np.testing.assert_allclose(out[2].numpy(),
                                   [2.0, 12.0, 22.0, 32.0, 42.0, 52.0,
                                    62.0, 72.0])


class TestFleet:
    def _init(self, **degrees):
        import paddle_tpu.distributed.fleet as fleet
        s = fleet.DistributedStrategy()
        base = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                "sharding_degree": 1, "sep_degree": 1}
        base.update(degrees)
        s.hybrid_configs = base
        fleet.init(strategy=s)
        return fleet

    def test_hcg_axes(self):
        _need8()
        fleet = self._init(dp_degree=2, mp_degree=4)
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_model_parallel_world_size() == 4
        assert hcg.get_data_parallel_world_size() == 2

    def test_tp_layers_match_plain(self):
        _need8()
        fleet = self._init(mp_degree=4)
        paddle.seed(3)
        col = fleet.meta_parallel.ColumnParallelLinear(16, 32,
                                                       gather_output=True)
        row = fleet.meta_parallel.RowParallelLinear(32, 16)
        x = paddle.randn([4, 16])
        out = row(col(x))
        # same math as unsharded matmuls
        want = ((x.numpy() @ col.weight.numpy() + col.bias.numpy())
                @ row.weight.numpy() + row.bias.numpy())
        np.testing.assert_allclose(out.numpy(), want, rtol=2e-4, atol=1e-5)
        assert tuple(col.weight._value.sharding.spec) == (None, "mp")
        assert tuple(row.weight._value.sharding.spec)[0] == "mp"

    def test_vocab_parallel_embedding(self):
        _need8()
        fleet = self._init(mp_degree=4)
        emb = fleet.meta_parallel.VocabParallelEmbedding(64, 16)
        out = emb(paddle.to_tensor([[1, 5, 63]]))
        assert out.shape == [1, 3, 16]
        np.testing.assert_allclose(out.numpy()[0, 0],
                                   emb.weight.numpy()[1], rtol=1e-6)

    def test_distributed_model_shards_params(self):
        _need8()
        fleet = self._init(sharding_degree=8)
        m = nn.Linear(16, 8)
        fleet.distributed_model(m)
        assert tuple(m.weight._value.sharding.spec)[0] == "sharding"

    def test_dp_convergence_parity(self):
        """Convergence oracle: single-device loss curve == dp-sharded curve
        (≙ reference TestDistBase, SURVEY.md §4)."""
        _need8()
        from paddle_tpu.optimizer import SGD

        def run(shard_batch):
            paddle.seed(11)
            m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
            opt = SGD(learning_rate=0.1, parameters=m.parameters())
            rngx = np.random.default_rng(0)
            losses = []
            mesh = dist.create_mesh(dp=8)
            for i in range(5):
                xb = rngx.normal(size=(16, 8)).astype(np.float32)
                yb = xb.sum(-1, keepdims=True).astype(np.float32)
                x, y = paddle.to_tensor(xb), paddle.to_tensor(yb)
                if shard_batch:
                    x = dist.shard_tensor(x, mesh, [dist.Shard(0)])
                    y = dist.shard_tensor(y, mesh, [dist.Shard(0)])
                loss = F.mse_loss(m(x), y)
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss))
            return losses

        single = run(False)
        sharded = run(True)
        np.testing.assert_allclose(single, sharded, rtol=1e-4, atol=1e-6)
        assert single[-1] < single[0]


@pytest.mark.slow
class TestGraftEntry:
    def test_dryrun_multichip(self):
        _need8()
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "graft_entry", os.path.join(os.path.dirname(__file__), "..",
                                        "__graft_entry__.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.dryrun_multichip(8)
