"""paddle.static compatibility surface (VERDICT r3 missing #2 / next #7).

The migration oracle: reference-style static training scripts run
verbatim against the op-replay Program + jitted Executor, and converge
like their eager equivalents. Graph-break detection gets its own tier:
data-dependent Python control flow inside a compiled region must raise
the pointed GraphBreakError, not a cryptic tracer leak.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import GraphBreakError


@pytest.fixture(autouse=True)
def _leave_dynamic():
    yield
    paddle.disable_static()


class TestStaticMigrationScript:
    def test_reference_style_regression_script(self):
        """The canonical paddle 2.x static linear-regression script."""
        paddle.enable_static()
        assert not paddle.in_dynamic_mode()

        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data(name="x", shape=[None, 13],
                                   dtype="float32")
            y = paddle.static.data(name="y", shape=[None, 1],
                                   dtype="float32")
            hidden = paddle.static.nn.fc(x, size=32, activation="relu")
            pred = paddle.static.nn.fc(hidden, size=1)
            loss = paddle.mean(
                paddle.nn.functional.square_error_cost(pred, y))
            opt = paddle.optimizer.SGD(learning_rate=0.05)
            opt.minimize(loss)

        exe = paddle.static.Executor(paddle.CPUPlace())
        exe.run(startup)

        rng = np.random.default_rng(0)
        w_true = rng.normal(size=(13, 1)).astype(np.float32)
        losses = []
        for i in range(30):
            xb = rng.normal(size=(16, 13)).astype(np.float32)
            yb = xb @ w_true
            (lv,) = exe.run(main, feed={"x": xb, "y": yb},
                            fetch_list=[loss])
            losses.append(float(lv))
        assert losses[-1] < 0.25 * losses[0], (losses[0], losses[-1])
        paddle.disable_static()
        assert paddle.in_dynamic_mode()

    def test_fetch_named_variable_by_string(self):
        """String fetch targets resolve against any NAMED variable
        recorded in the Program, not only feeds (advisor r4; ≙ the
        reference Executor's scope name lookup)."""
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data(name="x", shape=[None, 4],
                                   dtype="float32")
            mid = x * 3.0
            mid.name = "mid"
            out = mid + 1.0
        exe = paddle.static.Executor()
        xb = np.ones((2, 4), np.float32)
        mv, ov = exe.run(main, feed={"x": xb},
                         fetch_list=["mid", out])
        np.testing.assert_allclose(mv, np.full((2, 4), 3.0), rtol=1e-6)
        np.testing.assert_allclose(ov, np.full((2, 4), 4.0), rtol=1e-6)
        with pytest.raises(KeyError):
            exe.run(main, feed={"x": xb}, fetch_list=["nonexistent"])

    def test_variable_batch_size_replays(self):
        """shape=[None, d] placeholders: the same program serves any
        batch size (one compile per signature)."""
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data(name="x", shape=[None, 4],
                                   dtype="float32")
            out = (x * 2.0 + 1.0).sum(axis=1)
        exe = paddle.static.Executor()
        for b in (1, 3, 8):
            xb = np.ones((b, 4), np.float32)
            (ov,) = exe.run(main, feed={"x": xb}, fetch_list=[out])
            np.testing.assert_allclose(ov, np.full((b,), 12.0), rtol=1e-6)

    def test_startup_rerun_resets_parameters(self):
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data(name="x", shape=[None, 8],
                                   dtype="float32")
            y = paddle.static.data(name="y", shape=[None, 1],
                                   dtype="float32")
            pred = paddle.static.nn.fc(x, size=1)
            loss = paddle.mean((pred - y) ** 2)
            paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = paddle.static.Executor()
        exe.run(startup)
        w0 = [np.asarray(p._value).copy()
              for p in main.all_parameters()]
        rng = np.random.default_rng(1)
        for _ in range(5):
            xb = rng.normal(size=(8, 8)).astype(np.float32)
            exe.run(main, feed={"x": xb, "y": xb[:, :1]},
                    fetch_list=[loss])
        changed = any(
            not np.array_equal(np.asarray(p._value), w)
            for p, w in zip(main.all_parameters(), w0))
        assert changed
        exe.run(startup)                       # reset to init snapshot
        for p, w in zip(main.all_parameters(), w0):
            np.testing.assert_array_equal(np.asarray(p._value), w)

    def test_eager_layer_inside_program(self):
        """paddle.nn layers built inside program_guard record like
        static.nn helpers (the real migration path)."""
        from paddle_tpu import nn
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data(name="x", shape=[None, 6],
                                   dtype="float32")
            net = nn.Sequential(nn.Linear(6, 16), nn.ReLU(),
                                nn.Linear(16, 2))
            out = net(x)
        exe = paddle.static.Executor()
        xb = np.random.default_rng(2).normal(size=(4, 6)) \
            .astype(np.float32)
        (ov,) = exe.run(main, feed={"x": xb}, fetch_list=[out])
        net_eager = net(paddle.to_tensor(xb))
        np.testing.assert_allclose(ov, np.asarray(net_eager._value),
                                   rtol=1e-5, atol=1e-6)

    def test_save_load_roundtrip(self, tmp_path):
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data(name="x", shape=[None, 4],
                                   dtype="float32")
            out = paddle.static.nn.fc(x, size=3)
        p = str(tmp_path / "model")
        paddle.static.save(main, p)
        w_before = np.asarray(main.all_parameters()[0]._value).copy()
        main.all_parameters()[0]._value = \
            main.all_parameters()[0]._value * 0
        paddle.static.load(main, p)
        np.testing.assert_array_equal(
            np.asarray(main.all_parameters()[0]._value), w_before)


class TestExecutorGuards:
    def test_run_trained_program_without_feed_raises(self):
        """Never silently reset a trained program (round-4 review)."""
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data(name="x", shape=[None, 4],
                                   dtype="float32")
            _ = paddle.static.nn.fc(x, size=2)
        exe = paddle.static.Executor()
        with pytest.raises(ValueError, match="feed"):
            exe.run(main)

    def test_amp_casts_survive_replay(self):
        """Ops recorded under auto_cast must replay with the same casts
        (the recorded fn bakes the AMP decision in)."""
        from paddle_tpu import amp
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data(name="x", shape=[None, 8],
                                   dtype="float32")
            with amp.auto_cast(level="O1", dtype="bfloat16"):
                y = paddle.matmul(x, paddle.to_tensor(
                    np.eye(8, dtype=np.float32)))
        exe = paddle.static.Executor()
        xb = (np.arange(16, dtype=np.float32).reshape(2, 8)
              + 0.00390625 / 3)     # sub-bf16-precision offset
        (ov,) = exe.run(main, feed={"x": xb}, fetch_list=[y])
        # bf16 rounding must be visible in the replayed output
        import jax.numpy as jnp
        want = np.asarray(
            jnp.asarray(xb).astype(jnp.bfloat16).astype(jnp.float32))
        np.testing.assert_allclose(ov, want, rtol=1e-6)


class TestGraphBreakContract:
    def test_bool_on_traced_tensor_raises_pointed_error(self):
        import paddle_tpu.jit as jit

        def f(x):
            if x.sum() > 0:          # data-dependent Python branch
                return x * 2
            return x

        sf = jit.to_static(f, full_graph=True)
        with pytest.raises(GraphBreakError, match="graph break"):
            sf(paddle.to_tensor(np.ones(4, np.float32)))

    def test_float_int_item_on_traced_tensor(self):
        import paddle_tpu.jit as jit
        for coerce in (float, int, lambda t: t.item()):
            def f(x, c=coerce):
                _ = c(x.sum())
                return x

            with pytest.raises(GraphBreakError):
                jit.to_static(f, full_graph=True)(paddle.to_tensor(
                    np.ones(3, np.float32)))

    def test_eager_coercions_still_work(self):
        t = paddle.to_tensor(np.float32(2.5))
        assert float(t) == 2.5
        assert int(t) == 2
        assert bool(paddle.to_tensor(True))
        assert t.item() == 2.5

    def test_trainstep_graph_break_is_pointed(self):
        from paddle_tpu import nn
        from paddle_tpu.optimizer import SGD
        model = nn.Linear(4, 2)
        opt = SGD(learning_rate=0.1, parameters=model.parameters())

        def loss_fn(m, x, y):
            out = m(x)
            if out.mean() > 0:       # trace-burning branch
                return (out ** 2).mean()
            return out.mean()

        step = paddle.jit.TrainStep(model, opt, loss_fn=loss_fn)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        with pytest.raises(Exception) as ei:
            step(x, x)
        assert "graph break" in str(ei.value).lower() or \
            isinstance(ei.value, GraphBreakError)


class TestSOTLiteFallback:
    """VERDICT r4 #6: the reference SOT keeps running across a graph break
    (subgraph + eager resume, «python/paddle/jit/sot/»). SOT-lite contract:
    full_graph=False (default) logs the break and runs the function eagerly
    — numerics identical to eager, fallback decision cached."""

    def test_if_tensor_falls_back_with_matching_numerics(self):
        import paddle_tpu.jit as jit

        def f(x):
            if x.sum() > 0:          # reference-style migration code
                return x * 2
            return x - 1

        sf = jit.to_static(f)        # default full_graph=False
        xs = [np.ones(4, np.float32), -np.ones(4, np.float32)]
        with pytest.warns(UserWarning, match="graph break"):
            out = sf(paddle.to_tensor(xs[0]))
        np.testing.assert_allclose(out.numpy(), f(paddle.to_tensor(
            xs[0])).numpy())
        assert sf.graph_break_reason is not None
        # cached: both branches of the Python control flow now run
        out2 = sf(paddle.to_tensor(xs[1]))
        np.testing.assert_allclose(out2.numpy(), f(paddle.to_tensor(
            xs[1])).numpy())
        assert any("f" in name for name, _ in jit.sot_graph_breaks())

    def test_numpy_coercion_falls_back(self):
        """r5 review: .numpy() under trace must be a graph break (pointed
        error / SOT fallback), not a raw TracerArrayConversionError."""
        import paddle_tpu.jit as jit

        def f(x):
            return x * float(np.max(x.numpy()))

        sf = jit.to_static(f)
        x = paddle.to_tensor(np.array([1., 4., 2.], np.float32))
        with pytest.warns(UserWarning, match="graph break"):
            out = sf(x)
        np.testing.assert_allclose(out.numpy(), [4., 16., 8.])
        with pytest.raises(GraphBreakError, match="numpy"):
            jit.to_static(f, full_graph=True)(x)

    def test_clean_function_still_compiles_once(self):
        import paddle_tpu.jit as jit

        def g(x):
            return paddle.where(x > 0, x * 2, x - 1)   # tensor branch: no break

        sf = jit.to_static(g)
        out = sf(paddle.to_tensor(np.ones(4, np.float32)))
        assert sf.graph_break_reason is None
        np.testing.assert_allclose(out.numpy(), np.full(4, 2.0, np.float32))

    def test_layer_forward_falls_back(self):
        import paddle_tpu.jit as jit
        from paddle_tpu import nn

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                y = self.fc(x)
                if y.mean() > 1e9:   # data-dependent break, cold branch
                    return y * 0
                return y

        paddle.seed(0)
        m = M()
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        want = m.fc(x).numpy()
        jit.to_static(m)
        with pytest.warns(UserWarning, match="graph break"):
            got = m.forward(x).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)
