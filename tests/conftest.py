"""Test env: 8 virtual CPU devices (SURVEY.md §4 fake-backend strategy).

≙ the reference's fake custom_cpu device plugin («test/custom_runtime/»):
every parallelism test must pass on this fake 8-device mesh. Set
PDT_TEST_PLATFORM=tpu to run the suite natively on the attached chip
instead (distributed >1-device tests will skip there).

The axon sitecustomize imports jax at interpreter start, so env-var
platform selection is already too late here; jax.config.update after
import is the only override that sticks. XLA_FLAGS must still be set
before the (lazy) CPU client is created.
"""
import os

if os.environ.get("PDT_TEST_PLATFORM", "cpu") == "cpu":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_ENABLE_X64", "0")
    import jax

    jax.config.update("jax_platforms", "cpu")
else:
    import jax

# this jaxlib's CPU matmul defaults to fast (bf16-ish) passes; tests compare
# against NumPy, so force exact fp32 matmuls in the test env only
jax.config.update("jax_default_matmul_precision", "highest")
