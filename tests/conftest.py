"""Test env: 8 virtual CPU devices (SURVEY.md §4 fake-backend strategy).

≙ the reference's fake custom_cpu device plugin («test/custom_runtime/»):
every parallelism test must pass on this fake 8-device mesh. Set
PDT_TEST_PLATFORM=tpu to run the suite natively on the attached chip
instead (distributed >1-device tests will skip there).

The axon sitecustomize imports jax at interpreter start, so env-var
platform selection is already too late here; jax.config.update after
import is the only override that sticks. XLA_FLAGS must still be set
before the (lazy) CPU client is created.
"""
import os

if os.environ.get("PDT_TEST_PLATFORM", "cpu") == "cpu":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_ENABLE_X64", "0")
    import jax

    jax.config.update("jax_platforms", "cpu")
else:
    import jax

# this jaxlib's CPU matmul defaults to fast (bf16-ish) passes; tests compare
# against NumPy, so force exact fp32 matmuls in the test env only
jax.config.update("jax_default_matmul_precision", "highest")


# -- test tiers (SURVEY.md §4 CI plumbing; VERDICT r3 #9) --------------
# Default run = the FAST tier (target < 10 min on the 8-dev CPU mesh).
# Heavy tests carry @pytest.mark.slow (module-level pytestmark in the
# heavy files) and run only with PDT_RUN_SLOW=1 or `-m slow` /
# `--run-slow`. `pytest tests/` stays the quick regression gate;
# `PDT_RUN_SLOW=1 pytest tests/` is the full tier.
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption("--run-slow", action="store_true", default=False,
                     help="include the slow tier")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavy tier (HF parity, multi-process, "
        "e2e recipes) — run with --run-slow / PDT_RUN_SLOW=1")
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection tests "
        "(utils.faults) — CPU-mesh fast tier, runs in tier-1")
    config.addinivalue_line(
        "markers", "telemetry: observability-subsystem tests "
        "(paddle_tpu.observability) — CPU-mesh fast tier, runs in "
        "tier-1")


# serving/chaos/telemetry suites run with telemetry RECORDING on, each
# test from a zeroed registry/ring, so (a) the instrumentation paths are
# exercised by the whole engine suite for free and (b) a failing test's
# report carries a telemetry snapshot for post-mortem debugging
_TELEMETRY_FILES = ("test_serving.py", "test_chaos.py",
                    "test_telemetry.py", "test_elastic_robustness.py",
                    "test_router.py", "test_observability_slo.py",
                    "test_ragged_attention.py", "test_disagg.py",
                    "test_spec_decode.py", "test_admission.py",
                    "test_loadgen.py", "test_tp_serving.py",
                    "test_journal.py", "test_sentry.py",
                    "test_quant_serving.py", "test_autoscaler.py",
                    "test_multimodel.py", "test_async_pipeline.py",
                    "test_profile.py")

# failing fleet-drill tests additionally attach a Chrome-trace export
# of the telemetry ring: the failover timeline that produced the
# failure is then directly loadable in chrome://tracing / Perfetto
_CHROME_TRACE_FILES = ("test_chaos.py", "test_router.py")

# failing perf-sensitive tests additionally attach the performance-
# attribution report (decode-round decomposition + compile table +
# memory ledger): a hang or throughput collapse then arrives with its
# own waterfall instead of needing a rerun under a profiler
_PROFILE_REPORT_FILES = ("test_async_pipeline.py", "test_tp_serving.py",
                         "test_quant_serving.py", "test_profile.py")


@pytest.fixture(autouse=True)
def _telemetry_enabled(request, monkeypatch):
    if os.path.basename(str(request.fspath)) in _TELEMETRY_FILES:
        import paddle_tpu.observability as telemetry
        monkeypatch.setenv("PDT_TELEMETRY", "1")
        telemetry.reset()
        telemetry.clear_events()
    yield


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    rep = outcome.get_result()
    if rep.when == "call" and rep.failed:
        base = os.path.basename(str(item.fspath))
        if base in _TELEMETRY_FILES:
            try:
                import json
                import paddle_tpu.observability as telemetry
                rep.sections.append(
                    ("telemetry snapshot",
                     json.dumps(telemetry.snapshot(), indent=1,
                                sort_keys=True, default=str)))
            except Exception:
                pass    # a broken dump must never mask the real failure
        if base in _CHROME_TRACE_FILES:
            try:
                import json
                import paddle_tpu.observability as telemetry
                rep.sections.append(
                    ("chrome trace (save as .json, load in "
                     "chrome://tracing or ui.perfetto.dev)",
                     json.dumps(telemetry.export_chrome_trace(),
                                default=str)))
            except Exception:
                pass
        if base in _PROFILE_REPORT_FILES:
            try:
                from paddle_tpu.observability import profile
                rep.sections.append(
                    ("profile report", profile.snapshot_report()))
            except Exception:
                pass


@pytest.fixture(autouse=True)
def _serving_invariant_checks(request, monkeypatch):
    """Every serving/chaos test runs with the engine invariant checker
    on: page-accounting violations surface as EngineInvariantError in
    whatever test created them, for free."""
    if os.path.basename(str(request.fspath)) in (
            "test_serving.py", "test_chaos.py", "test_router.py",
            "test_ragged_attention.py", "test_disagg.py",
            "test_spec_decode.py", "test_admission.py",
            "test_loadgen.py", "test_tp_serving.py",
            "test_journal.py", "test_sentry.py",
            "test_quant_serving.py", "test_autoscaler.py",
            "test_multimodel.py", "test_async_pipeline.py",
            "test_profile.py"):
        monkeypatch.setenv("PDT_CHECK_INVARIANTS", "1")
    yield


def pytest_collection_modifyitems(config, items):
    if (config.getoption("--run-slow")
            or os.environ.get("PDT_RUN_SLOW") == "1"
            or "slow" in config.getoption("-m", "")):
        return
    skip = pytest.mark.skip(
        reason="slow tier: enable with --run-slow or PDT_RUN_SLOW=1")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
