"""Test env: force CPU with 8 virtual devices BEFORE jax import.

≙ the reference's fake custom_cpu device plugin strategy for testing the
whole device/comm path without accelerator hardware (SURVEY.md §4
«test/custom_runtime/»): every parallelism test must pass on this fake
8-device mesh."""
import os

# force CPU: the ambient env may pin JAX_PLATFORMS=axon (TPU tunnel), but
# the test tier always runs on the virtual 8-device CPU mesh (SURVEY.md §4)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
# this jaxlib's CPU matmul defaults to fast (bf16-ish) passes; tests compare
# against NumPy, so force exact fp32 matmuls in the test env only
import jax  # noqa: E402

jax.config.update("jax_default_matmul_precision", "highest")
