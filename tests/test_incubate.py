"""incubate.autograd (higher-order functional autodiff) and
fused_multi_head_attention. ≙ SURVEY.md §2.1 prim row + §2.2 incubate row;
VERDICT r2 items 6 (missing) and 10 (stub)."""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.incubate import autograd as iag

rng = np.random.default_rng(11)


class TestFunctionalAutograd:
    def test_vjp(self):
        x = paddle.to_tensor(np.asarray([2.0, 3.0], np.float32))
        out, g = iag.vjp(lambda t: (t * t).sum(), x)
        assert abs(float(out) - 13.0) < 1e-6
        np.testing.assert_allclose(np.asarray(g._value), [4.0, 6.0])

    def test_jvp(self):
        x = paddle.to_tensor(np.asarray([2.0, 3.0], np.float32))
        v = paddle.to_tensor(np.asarray([1.0, 0.0], np.float32))
        out, t = iag.jvp(lambda t: (t * t).sum(), x, v)
        assert abs(float(t) - 4.0) < 1e-6

    def test_jacobian(self):
        x = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
        j = iag.jacobian(lambda t: t * t, x)
        np.testing.assert_allclose(np.asarray(j._value),
                                   [[2.0, 0.0], [0.0, 4.0]])

    def test_hessian(self):
        x = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
        h = iag.hessian(lambda t: (t ** 3).sum(), x)
        np.testing.assert_allclose(np.asarray(h._value),
                                   [[6.0, 0.0], [0.0, 12.0]])

    def test_grad_composes_to_third_order(self):
        """The create_graph escape hatch: grad(grad(grad(f)))."""
        f = lambda t: (t ** 4).sum()
        d3 = iag.grad(iag.grad(iag.grad(f)))
        x = paddle.to_tensor(np.asarray(2.0, np.float32))
        # d^3/dx^3 x^4 = 24 x
        assert abs(float(d3(x)) - 48.0) < 1e-4

    def test_eager_create_graph_error_names_this_module(self):
        x = paddle.to_tensor(np.asarray([1.0], np.float32),
                             stop_gradient=False)
        y = (x * x).sum()
        with pytest.raises(NotImplementedError) as e:
            paddle.grad([y], [x], create_graph=True)
        assert "incubate.autograd" in str(e.value)


class TestFusedMHA:
    def _inputs(self, b=2, s=8, h=4, hd=8):
        rng = np.random.default_rng(0)
        e = h * hd
        x = rng.standard_normal((b, s, e)).astype(np.float32)
        qkv_w = rng.standard_normal((3, h, hd, e)).astype(np.float32) * 0.05
        lin_w = rng.standard_normal((e, e)).astype(np.float32) * 0.05
        return x, qkv_w, lin_w, h, hd, e

    def test_matches_unfused_composition(self):
        from paddle_tpu.incubate.nn.functional import \
            fused_multi_head_attention
        from paddle_tpu.nn import functional as F

        x, qkv_w, lin_w, h, hd, e = self._inputs()
        ln_scale = paddle.to_tensor(np.ones(e, np.float32))
        ln_bias = paddle.to_tensor(np.zeros(e, np.float32))
        out = fused_multi_head_attention(
            paddle.to_tensor(x), paddle.to_tensor(qkv_w),
            paddle.to_tensor(lin_w), ln_scale=ln_scale, ln_bias=ln_bias,
            dropout_rate=0.0, attn_dropout_rate=0.0)

        # hand composition
        b, s = x.shape[0], x.shape[1]
        w = qkv_w.reshape(3 * h * hd, e)
        qkv = (x @ w.T).reshape(b, s, 3, h, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v))
        ref = np.asarray(attn._value).reshape(b, s, e) @ lin_w + x
        mu = ref.mean(-1, keepdims=True)
        var = ref.var(-1, keepdims=True)
        ref = (ref - mu) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(out._value), ref, rtol=2e-3,
                                   atol=2e-3)

    def test_pre_layer_norm_and_grads(self):
        from paddle_tpu.incubate.nn.functional import \
            fused_multi_head_attention

        x, qkv_w, lin_w, h, hd, e = self._inputs()
        xt = paddle.to_tensor(x, stop_gradient=False)
        qw = paddle.to_tensor(qkv_w, stop_gradient=False)
        lw = paddle.to_tensor(lin_w, stop_gradient=False)
        scale = paddle.to_tensor(np.ones(e, np.float32))
        bias = paddle.to_tensor(np.zeros(e, np.float32))
        out = fused_multi_head_attention(
            xt, qw, lw, pre_layer_norm=True, pre_ln_scale=scale,
            pre_ln_bias=bias, dropout_rate=0.0, attn_dropout_rate=0.0)
        out.astype("float32").sum().backward()
        assert qw.grad is not None and lw.grad is not None
        assert np.isfinite(np.asarray(qw.grad._value)).all()

    def test_transpose_qkv_wb_layout(self):
        from paddle_tpu.incubate.nn.functional import \
            fused_multi_head_attention

        x, _, lin_w, h, hd, e = self._inputs()
        rng = np.random.default_rng(1)
        qkv_w2 = rng.standard_normal((e, 3 * e)).astype(np.float32) * 0.05
        out = fused_multi_head_attention(
            paddle.to_tensor(x), paddle.to_tensor(qkv_w2),
            paddle.to_tensor(lin_w), num_heads=h, transpose_qkv_wb=True,
            dropout_rate=0.0, attn_dropout_rate=0.0)
        assert out.shape == [2, 8, e]
        assert np.isfinite(np.asarray(out._value)).all()


class TestFusedBiasDropoutResidualLN:
    def test_matches_composition(self):
        from paddle_tpu.incubate.nn.functional import \
            fused_bias_dropout_residual_layer_norm
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 6, 16)).astype(np.float32)
        res = rng.standard_normal((2, 6, 16)).astype(np.float32)
        b = rng.standard_normal(16).astype(np.float32)
        out = fused_bias_dropout_residual_layer_norm(
            paddle.to_tensor(x), paddle.to_tensor(res),
            bias=paddle.to_tensor(b), dropout_rate=0.0)
        y = res + x + b
        mu = y.mean(-1, keepdims=True)
        var = y.var(-1, keepdims=True)
        ref = (y - mu) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=2e-4, atol=2e-4)

    def test_grads_flow(self):
        from paddle_tpu.incubate.nn.functional import \
            fused_bias_dropout_residual_layer_norm
        x = paddle.to_tensor(np.ones((2, 4, 8), np.float32),
                             stop_gradient=False)
        r = paddle.to_tensor(np.ones((2, 4, 8), np.float32) * 0.5,
                             stop_gradient=False)
        paddle.seed(0)
        out = fused_bias_dropout_residual_layer_norm(
            x, r, dropout_rate=0.3)
        out.astype("float32").sum().backward()
        assert x.grad is not None and r.grad is not None


class TestFusedLayers:
    """incubate.nn fused layer classes (round 3). ≙ reference
    «test/legacy_test/test_fused_attention_op.py» family [U]."""

    def test_fused_linear(self):
        import paddle_tpu.incubate.nn as inn
        paddle.seed(0)
        l = inn.FusedLinear(8, 16)
        x = paddle.to_tensor(rng.normal(size=(2, 8)).astype(np.float32))
        out = l(x)
        ref = np.asarray(x._value) @ np.asarray(l.weight._value) \
            + np.asarray(l.bias._value)
        np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-5)

    def test_fused_mha_matches_unfused(self):
        import paddle_tpu.incubate.nn as inn
        paddle.seed(0)
        E, H, B, S = 16, 4, 2, 6
        m = inn.FusedMultiHeadAttention(E, H, dropout_rate=0.0,
                                        attn_dropout_rate=0.0)
        m.eval()
        x = paddle.to_tensor(rng.normal(size=(B, S, E)).astype(np.float32))
        out = m(x)
        assert tuple(out.shape) == (B, S, E)
        assert np.isfinite(np.asarray(out._value)).all()

    def test_fused_encoder_layer_trains(self):
        import paddle_tpu.incubate.nn as inn
        paddle.seed(0)
        layer = inn.FusedTransformerEncoderLayer(
            16, 4, 32, dropout_rate=0.0)
        x = paddle.to_tensor(rng.normal(size=(2, 5, 16)).astype(np.float32),
                             stop_gradient=False)
        out = layer(x)
        out.mean().backward()
        assert x.grad is not None
        for p in layer.parameters():
            if p.grad is None:
                # ln params of unused branches may be skipped; at least the
                # qkv weight must have a grad
                continue
        assert layer.fused_attn.qkv_weight.grad is not None

    def test_fused_bias_dropout_residual_ln_layer(self):
        import paddle_tpu.incubate.nn as inn
        paddle.seed(0)
        l = inn.FusedBiasDropoutResidualLayerNorm(8, dropout_rate=0.0)
        l.eval()
        x = paddle.to_tensor(rng.normal(size=(2, 8)).astype(np.float32))
        r = paddle.to_tensor(rng.normal(size=(2, 8)).astype(np.float32))
        out = l(x, r)
        y = np.asarray(x._value) + np.asarray(l.linear_bias._value) \
            + np.asarray(r._value)
        mu = y.mean(-1, keepdims=True)
        var = y.var(-1, keepdims=True)
        ref = (y - mu) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=1e-4, atol=1e-4)

    def test_fused_rms_norm_layer(self):
        import paddle_tpu.incubate.nn as inn
        l = inn.FusedRMSNorm(8)
        x = paddle.to_tensor(rng.normal(size=(3, 8)).astype(np.float32))
        out = l(x)
        xv = np.asarray(x._value)
        ref = xv / np.sqrt((xv ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=1e-4, atol=1e-4)

    def test_fused_dropout_add_eval_is_add(self):
        import paddle_tpu.incubate.nn as inn
        l = inn.FusedDropoutAdd(p=0.9)
        l.eval()
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        y = paddle.to_tensor(np.full((2, 3), 2.0, np.float32))
        np.testing.assert_allclose(np.asarray(l(x, y)._value), 3.0)


class TestFusedFunctionals:
    def test_swiglu_both_forms(self):
        import paddle_tpu.incubate.nn.functional as IF
        import paddle_tpu.nn.functional as F
        x = paddle.to_tensor(rng.normal(size=(2, 8)).astype(np.float32))
        y = paddle.to_tensor(rng.normal(size=(2, 8)).astype(np.float32))
        both = IF.swiglu(x, y)
        ref = F.silu(x) * y
        np.testing.assert_allclose(np.asarray(both._value),
                                   np.asarray(ref._value), rtol=1e-6)
        split = IF.swiglu(paddle.concat([x, y], axis=-1))
        np.testing.assert_allclose(np.asarray(split._value),
                                   np.asarray(ref._value), rtol=1e-6)

    def test_fused_linear_activation(self):
        import paddle_tpu.incubate.nn.functional as IF
        x = paddle.to_tensor(rng.normal(size=(2, 4)).astype(np.float32))
        w = paddle.to_tensor(rng.normal(size=(4, 3)).astype(np.float32))
        b = paddle.to_tensor(rng.normal(size=(3,)).astype(np.float32))
        out = IF.fused_linear_activation(x, w, b, activation="relu")
        ref = np.maximum(np.asarray(x._value) @ np.asarray(w._value)
                         + np.asarray(b._value), 0)
        np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-5)
        lin = IF.fused_linear(x, w, b)
        np.testing.assert_allclose(
            np.asarray(lin._value),
            np.asarray(x._value) @ np.asarray(w._value)
            + np.asarray(b._value), rtol=1e-5)
