"""Distributed infra tests: launch CLI, ZeRO sharding API, auto_parallel
Engine, elastic checkpoint-restart. ≙ reference «test/collective/fleet/»
launch/elastic/sharding tiers (SURVEY.md §4)."""
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # heavy tier (VERDICT r3 #9)

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.optimizer import Adam

rng = np.random.default_rng(21)


class TestLaunchCLI:
    def test_runs_script_and_propagates_env(self, tmp_path):
        script = tmp_path / "train.py"
        script.write_text(
            "import os\n"
            "assert os.environ['PADDLE_TRAINER_ID'] == '0'\n"
            "assert os.environ['PADDLE_JOB_ID'] == 'jobx'\n"
            "print('TRAINED')\n")
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--job_id", "jobx", str(script)],
            capture_output=True, text=True,
            env={**{k: v for k, v in os.environ.items()
                    if k != "PALLAS_AXON_POOL_IPS"},
                 "PYTHONPATH": "/root/repo:"
                 + os.environ.get("PYTHONPATH", ""),
                 "JAX_PLATFORMS": "cpu"},
            timeout=120)
        assert out.returncode == 0, out.stderr
        assert "TRAINED" in out.stdout

    def test_elastic_restarts_on_failure(self, tmp_path):
        marker = tmp_path / "marker"
        script = tmp_path / "flaky.py"
        script.write_text(
            f"import os, sys\n"
            f"m = {str(marker)!r}\n"
            f"if not os.path.exists(m):\n"
            f"    open(m, 'w').write('x'); sys.exit(1)\n"
            f"print('RECOVERED')\n")
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--elastic_level", "1", str(script)],
            capture_output=True, text=True,
            env={**{k: v for k, v in os.environ.items()
                    if k != "PALLAS_AXON_POOL_IPS"},
                 "PYTHONPATH": "/root/repo:"
                 + os.environ.get("PYTHONPATH", ""),
                 "JAX_PLATFORMS": "cpu"},
            timeout=120)
        assert out.returncode == 0, out.stderr
        assert "RECOVERED" in out.stdout
        assert "restart 1/" in out.stderr


class TestGroupSharded:
    def test_params_get_sharding_placement(self):
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        mesh = dist.create_mesh(dp=2, sharding=4)
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                            nn.Linear(32, 8))
        opt = Adam(learning_rate=1e-3, parameters=net.parameters())
        with dist.use_mesh(mesh):
            net, opt, _ = group_sharded_parallel(net, opt, "p_g_os")
        w = net[0].weight
        assert any(ax == "sharding"
                   for ax in (w._value.sharding.spec or []) if ax), \
            w._value.sharding
        # training still works with sharded placements
        with dist.use_mesh(mesh):
            x = paddle.to_tensor(rng.normal(size=(4, 16)).astype(np.float32))
            loss = (net(x) ** 2).sum()
            loss.backward()
            opt.step()
        assert np.isfinite(float(loss))


class TestAutoParallelEngine:
    def test_engine_fit_loss_decreases(self):
        from paddle_tpu.distributed.auto_parallel import Engine, Strategy
        from paddle_tpu.io import Dataset

        class DS(Dataset):
            def __init__(self):
                self.x = rng.normal(size=(64, 8)).astype(np.float32)
                w = np.random.default_rng(1).normal(size=(8, 1))
                self.y = (self.x @ w).astype(np.float32)

            def __getitem__(self, i):
                return self.x[i], self.y[i]

            def __len__(self):
                return 64

        paddle.seed(0)
        net = nn.Linear(8, 1)
        eng = Engine(model=net, loss=nn.MSELoss(),
                     optimizer=Adam(learning_rate=0.05,
                                    parameters=net.parameters()),
                     strategy=Strategy())
        hist = eng.fit(DS(), epochs=5, batch_size=16, verbose=0)
        assert hist[-1] < hist[0] * 0.5, hist
        res = eng.evaluate(DS(), batch_size=16)
        assert res["loss"] < hist[0]



    def test_engine_fit_sharded_on_mesh(self):
        """Engine.fit under a mesh routes batches through shard_dataloader
        (Shard(0) over dp) — VERDICT r2 weak 9."""
        from paddle_tpu.distributed.auto_parallel import Engine, Strategy
        from paddle_tpu.io import Dataset

        class DS(Dataset):
            def __init__(self):
                self.x = rng.normal(size=(64, 8)).astype(np.float32)
                w = np.random.default_rng(2).normal(size=(8, 1))
                self.y = (self.x @ w).astype(np.float32)

            def __getitem__(self, i):
                return self.x[i], self.y[i]

            def __len__(self):
                return 64

        mesh = dist.create_mesh(dp=4, mp=2)
        paddle.seed(0)
        net = nn.Linear(8, 1)
        with dist.use_mesh(mesh):
            eng = Engine(model=net, loss=nn.MSELoss(),
                         optimizer=Adam(learning_rate=0.05,
                                        parameters=net.parameters()),
                         strategy=Strategy())
            hist = eng.fit(DS(), epochs=4, batch_size=16, verbose=0)
        assert hist[-1] < hist[0] * 0.5, hist


class TestElasticManager:
    def test_resume_roundtrip(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          latest_checkpoint)
        paddle.seed(0)
        net = nn.Linear(4, 4)
        opt = Adam(learning_rate=1e-2, parameters=net.parameters())
        em = ElasticManager(str(tmp_path), save_interval_steps=2,
                            keep_last=2)
        assert em.resume(net, opt) == 0
        x = paddle.to_tensor(rng.normal(size=(2, 4)).astype(np.float32))
        for step in range(6):
            loss = (net(x) ** 2).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
            em.maybe_save(step, net, opt)
        assert latest_checkpoint(str(tmp_path)).endswith("step_5")

        paddle.seed(1)
        net2 = nn.Linear(4, 4)
        opt2 = Adam(learning_rate=1e-2, parameters=net2.parameters())
        em2 = ElasticManager(str(tmp_path), save_interval_steps=2)
        start = em2.resume(net2, opt2)
        assert start == 6
        np.testing.assert_array_equal(net2.weight.numpy(),
                                      net.weight.numpy())
        # identical next step on both: lazily-created accumulators must
        # have consumed the restored moments (not restarted from zeros)
        for n_, o_ in ((net, opt), (net2, opt2)):
            loss = (n_(x) ** 2).sum()
            loss.backward()
            o_.step()
            o_.clear_grad()
        np.testing.assert_allclose(net2.weight.numpy(), net.weight.numpy(),
                                   rtol=1e-6, atol=1e-7)

    def test_gc_keeps_last(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager
        net = nn.Linear(2, 2)
        em = ElasticManager(str(tmp_path), save_interval_steps=1,
                            keep_last=2)
        for step in range(5):
            em.save(step, net)
        kept = sorted(n for n in os.listdir(tmp_path)
                      if n.startswith("step_"))
        assert kept == ["step_3", "step_4"], kept


class TestLaunchLogCapture:
    def test_log_capture_and_elastic_restart(self, tmp_path):
        """launch CLI captures per-rank logs and restarts on failure
        (≙ reference launcher log capture + elastic restart)."""
        from paddle_tpu.distributed.launch import launch

        script = tmp_path / "train.py"
        marker = tmp_path / "attempts"
        script.write_text(
            "import os, sys\n"
            f"p = {str(marker)!r}\n"
            "n = int(open(p).read()) if os.path.exists(p) else 0\n"
            "open(p, 'w').write(str(n + 1))\n"
            "print(f'attempt {n}', flush=True)\n"
            "sys.exit(0 if n >= 1 else 3)\n")

        class A:
            pass

        a = A()
        a.master = None
        a.nnodes = 1
        a.rank = 0
        a.job_id = "t"
        a.log_dir = str(tmp_path / "logs")
        a.elastic_level = 1
        a.max_restart = 2
        a.script = str(script)
        a.script_args = []
        rc = launch(a)
        assert rc == 0
        log = (tmp_path / "logs" / "t.rank0.log").read_text()
        assert "attempt 0" in log and "attempt 1" in log
        assert "restart 1/2" in log


class TestMultiHostRendezvous:
    def test_two_rank_launch_rendezvous_allgather(self, tmp_path):
        """Two `launch` invocations (simulating two hosts) rendezvous via
        jax.distributed using the env the launcher injects, then
        allgather across processes — the multi-host path SURVEY §2.1
        'Comm contexts + store' row maps to jax's coordinator service."""
        script = tmp_path / "worker.py"
        script.write_text(
            "import os\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "addr = os.environ['COORDINATOR_ADDRESS']\n"
            "n = int(os.environ['PADDLE_TRAINERS_NUM'])\n"
            "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
            "jax.distributed.initialize(coordinator_address=addr,\n"
            "                           num_processes=n, process_id=rank)\n"
            "assert jax.process_count() == 2\n"
            "import jax.numpy as jnp\n"
            "from jax.experimental import multihost_utils\n"
            "g = multihost_utils.process_allgather(\n"
            "    jnp.ones(2) * (rank + 1))\n"
            "assert g.tolist() == [[1.0, 1.0], [2.0, 2.0]], g\n"
            "print(f'rank {rank} rendezvous ok', flush=True)\n")

        import socket
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["JAX_PLATFORMS"] = "cpu"
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        procs = [subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--master", f"127.0.0.1:{port}", "--nnodes", "2",
             "--rank", str(i), "--log_dir", str(tmp_path / "logs"),
             "--job_id", "rdv", str(script)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True, cwd=str(tmp_path))
            for i in range(2)]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
            assert p.returncode == 0, out[-1500:]
        assert "rank 0 rendezvous ok" in outs[0] + outs[1]
        # per-rank logs captured by the launcher
        assert (tmp_path / "logs" / "rdv.rank0.log").exists()
        assert (tmp_path / "logs" / "rdv.rank1.log").exists()


class TestFaultInjection:
    """SIGKILL mid-training + elastic relaunch + checkpoint resume — the
    SURVEY.md §5 failure-detection oracle ('fault injection = test harness
    kills a host process'); VERDICT r2 'no fault-injection tests'."""

    def test_sigkill_midtrain_resumes_from_checkpoint(self, tmp_path):
        ckpt = tmp_path / "ckpt"
        script = tmp_path / "train.py"
        script.write_text(
            "import os, signal, sys\n"
            "import numpy as np\n"
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import paddle_tpu as paddle\n"
            "from paddle_tpu import nn\n"
            "from paddle_tpu.optimizer import Adam\n"
            "from paddle_tpu.distributed.fleet.elastic import "
            "ElasticManager\n"
            "paddle.seed(0)\n"
            "net = nn.Linear(4, 4)\n"
            "opt = Adam(learning_rate=1e-2, parameters=net.parameters())\n"
            f"em = ElasticManager({str(ckpt)!r}, save_interval_steps=2)\n"
            "start = em.resume(net, opt)\n"
            "print(f'RESUME_AT {start}', flush=True)\n"
            "x = paddle.to_tensor(np.ones((2, 4), np.float32))\n"
            "for step in range(start, 10):\n"
            "    loss = (net(x) ** 2).sum()\n"
            "    loss.backward(); opt.step(); opt.clear_grad()\n"
            "    em.maybe_save(step, net, opt)\n"
            "    if step == 4 and start == 0:\n"
            "        os.kill(os.getpid(), signal.SIGKILL)  # hard fault\n"
            "print('DONE', flush=True)\n")
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--elastic_level", "1", "--max_restart", "3", str(script)],
            capture_output=True, text=True,
            env={**{k: v for k, v in os.environ.items()
                    if k != "PALLAS_AXON_POOL_IPS"},
                 "PYTHONPATH": "/root/repo:"
                 + os.environ.get("PYTHONPATH", ""),
                 "JAX_PLATFORMS": "cpu"},
            timeout=240)
        assert out.returncode == 0, (out.stdout, out.stderr)
        assert "DONE" in out.stdout
        # first incarnation starts fresh, second resumes past the last
        # completed checkpoint (step 4 saved at interval 2 -> resume at 5)
        resumes = [int(l.split()[1]) for l in out.stdout.splitlines()
                   if l.startswith("RESUME_AT")]
        assert resumes[0] == 0 and len(resumes) >= 2, out.stdout
        assert resumes[1] >= 4, out.stdout


class TestSpawn:
    """paddle.distributed.spawn (reference «python/paddle/distributed/
    spawn.py» [U]): multi-process fork + jax.distributed rendezvous."""

    def test_two_rank_spawn_allgather(self, tmp_path):
        # run in a subprocess so the child interpreters start clean (the
        # test process already initialized a jax backend)
        script = tmp_path / "spawn_main.py"
        out_file = tmp_path / "out.txt"
        script.write_text(
            "import os\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "os.environ.pop('PALLAS_AXON_POOL_IPS', None)\n"
            "import paddle_tpu.distributed as dist\n\n"
            "def worker(out_path):\n"
            "    import jax\n"
            "    import jax.numpy as jnp\n"
            "    r = jax.process_index()\n"
            "    n = jax.process_count()\n"
            "    with open(f'{out_path}.{r}', 'w') as f:\n"
            "        f.write(f'{r}/{n}')\n\n"
            "if __name__ == '__main__':\n"
            "    import sys\n"
            f"    dist.spawn(worker, args=({str(out_file)!r},), nprocs=2)\n"
            "    print('SPAWN_OK')\n")
        out = subprocess.run(
            [sys.executable, str(script)], capture_output=True, text=True,
            env={**{k: v for k, v in os.environ.items()
                    if k != "PALLAS_AXON_POOL_IPS"},
                 "PYTHONPATH": "/root/repo:"
                 + os.environ.get("PYTHONPATH", ""),
                 "JAX_PLATFORMS": "cpu"},
            timeout=240)
        assert out.returncode == 0, (out.stdout, out.stderr)
        assert "SPAWN_OK" in out.stdout
        assert (tmp_path / "out.txt.0").read_text() == "0/2"
        assert (tmp_path / "out.txt.1").read_text() == "1/2"
