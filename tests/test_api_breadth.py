"""API-breadth tests: fft, signal, sparse, distribution, quantization.
≙ reference test tiers «test/fft/», «test/sparse/», «test/distribution/»,
«test/quantization/» [U] — NumPy/scipy-reference oracles (SURVEY.md §4)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn

rng = np.random.default_rng(13)


class TestFFT:
    def test_fft_roundtrip(self):
        x = rng.normal(size=(4, 32)).astype(np.float32)
        X = paddle.fft.fft(paddle.to_tensor(x))
        back = paddle.fft.ifft(X)
        np.testing.assert_allclose(back.numpy().real, x, atol=1e-5)
        np.testing.assert_allclose(X.numpy(), np.fft.fft(x), rtol=1e-4,
                                   atol=1e-4)

    def test_rfft_matches_numpy(self):
        x = rng.normal(size=(3, 16)).astype(np.float32)
        np.testing.assert_allclose(
            paddle.fft.rfft(paddle.to_tensor(x)).numpy(),
            np.fft.rfft(x), rtol=1e-4, atol=1e-4)

    def test_fft2_and_norms(self):
        x = rng.normal(size=(8, 8)).astype(np.float32)
        for norm in ("backward", "ortho", "forward"):
            np.testing.assert_allclose(
                paddle.fft.fft2(paddle.to_tensor(x), norm=norm).numpy(),
                np.fft.fft2(x, norm=norm), rtol=1e-4, atol=1e-4)
        with pytest.raises(ValueError):
            paddle.fft.fft(paddle.to_tensor(x), norm="bogus")

    def test_fftshift_freq(self):
        np.testing.assert_allclose(paddle.fft.fftfreq(8, d=0.5).numpy(),
                                   np.fft.fftfreq(8, d=0.5).astype(
                                       np.float32))
        x = rng.normal(size=(8,)).astype(np.float32)
        np.testing.assert_allclose(
            paddle.fft.fftshift(paddle.to_tensor(x)).numpy(),
            np.fft.fftshift(x))

    def test_fft_grad(self):
        x = paddle.to_tensor(rng.normal(size=(16,)).astype(np.float32),
                             stop_gradient=False)
        y = paddle.fft.rfft(x)
        (y.abs() ** 2).sum().backward()
        assert np.isfinite(x.grad.numpy()).all()


class TestSignal:
    def test_stft_istft_roundtrip(self):
        x = rng.normal(size=(2, 512)).astype(np.float32)
        win = np.hanning(128).astype(np.float32)
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=128,
                                  hop_length=32,
                                  window=paddle.to_tensor(win))
        back = paddle.signal.istft(spec, n_fft=128, hop_length=32,
                                   window=paddle.to_tensor(win),
                                   length=512)
        np.testing.assert_allclose(back.numpy(), x, atol=1e-4)

    def test_frame_shapes(self):
        x = paddle.to_tensor(rng.normal(size=(2, 100)).astype(np.float32))
        f = paddle.signal.frame(x, frame_length=20, hop_length=10)
        assert f.shape == [2, 20, 9]


class TestSparse:
    def test_coo_create_dense_roundtrip(self):
        idx = np.array([[0, 1, 2], [1, 2, 0]])
        vals = np.array([1.0, 2.0, 3.0], np.float32)
        s = paddle.sparse.sparse_coo_tensor(idx, vals, shape=[3, 3])
        d = s.to_dense().numpy()
        want = np.zeros((3, 3), np.float32)
        want[idx[0], idx[1]] = vals
        np.testing.assert_array_equal(d, want)
        assert s.nnz() == 3

    def test_csr_and_conversion(self):
        crows = np.array([0, 1, 3])
        cols = np.array([1, 0, 2])
        vals = np.array([5.0, 1.0, 2.0], np.float32)
        s = paddle.sparse.sparse_csr_tensor(crows, cols, vals, [2, 3])
        d = s.to_dense().numpy()
        assert d[0, 1] == 5.0 and d[1, 0] == 1.0 and d[1, 2] == 2.0
        coo = s.to_sparse_coo()
        np.testing.assert_array_equal(coo.to_dense().numpy(), d)

    def test_spmm_matches_dense(self):
        dense = (rng.random((4, 5)) * (rng.random((4, 5)) > 0.6)).astype(
            np.float32)
        idx = np.array(np.nonzero(dense))
        s = paddle.sparse.sparse_coo_tensor(idx, dense[tuple(idx)],
                                            shape=[4, 5])
        y = rng.normal(size=(5, 3)).astype(np.float32)
        out = paddle.sparse.matmul(s, paddle.to_tensor(y))
        np.testing.assert_allclose(out.numpy(), dense @ y, rtol=1e-5,
                                   atol=1e-5)

    def test_sparse_add_relu(self):
        a = np.diag([1.0, -2.0, 3.0]).astype(np.float32)
        idx = np.array(np.nonzero(a))
        s = paddle.sparse.sparse_coo_tensor(idx, a[tuple(idx)], [3, 3])
        r = paddle.sparse.relu(s)
        np.testing.assert_array_equal(
            r.to_dense().numpy(), np.maximum(a, 0))
        tot = paddle.sparse.add(s, s).to_dense().numpy()
        np.testing.assert_array_equal(tot, 2 * a)

    def test_masked_matmul(self):
        x = rng.normal(size=(3, 4)).astype(np.float32)
        y = rng.normal(size=(4, 3)).astype(np.float32)
        mask_d = np.eye(3, dtype=np.float32)
        idx = np.array(np.nonzero(mask_d))
        mask = paddle.sparse.sparse_coo_tensor(idx, mask_d[tuple(idx)],
                                               [3, 3])
        out = paddle.sparse.masked_matmul(paddle.to_tensor(x),
                                          paddle.to_tensor(y), mask)
        np.testing.assert_allclose(np.diag(out.to_dense().numpy()),
                                   np.diag(x @ y), rtol=1e-5)


class TestDistribution:
    def test_normal_moments_and_kl(self):
        from paddle_tpu.distribution import Normal, kl_divergence
        paddle.seed(0)
        p = Normal(0.0, 1.0)
        q = Normal(1.0, 2.0)
        s = p.sample((5000,))
        assert abs(float(s.numpy().mean())) < 0.1
        assert abs(float(s.numpy().std()) - 1.0) < 0.1
        kl = float(kl_divergence(p, q).numpy())
        want = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
        assert abs(kl - want) < 1e-5
        # log_prob vs scipy formula
        lp = float(p.log_prob(paddle.to_tensor(0.5)).numpy())
        assert abs(lp - (-0.5 * 0.25 - 0.5 * np.log(2 * np.pi))) < 1e-5

    def test_categorical(self):
        from paddle_tpu.distribution import Categorical, kl_divergence
        paddle.seed(0)
        c = Categorical(logits=np.log(np.array([0.2, 0.3, 0.5],
                                               np.float32)))
        s = c.sample((8000,)).numpy()
        freq = np.bincount(s.astype(int), minlength=3) / len(s)
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.03)
        ent = float(c.entropy().numpy())
        want = -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5))
        assert abs(ent - want) < 1e-5
        assert float(kl_divergence(c, c).numpy()) == pytest.approx(0.0,
                                                                   abs=1e-6)

    @pytest.mark.parametrize("cls,args,mean,var", [
        ("Bernoulli", (0.3,), 0.3, 0.21),
        ("Exponential", (2.0,), 0.5, 0.25),
        ("Laplace", (1.0, 2.0), 1.0, 8.0),
        ("Gamma", (3.0, 2.0), 1.5, 0.75),
        ("Beta", (2.0, 3.0), 0.4, 0.04),
        ("Geometric", (0.5,), 1.0, 2.0),
        ("Poisson", (4.0,), 4.0, 4.0),
    ])
    def test_moments(self, cls, args, mean, var):
        import paddle_tpu.distribution as D
        d = getattr(D, cls)(*args)
        assert float(d.mean.numpy()) == pytest.approx(mean, rel=1e-5)
        assert float(d.variance.numpy()) == pytest.approx(var, rel=1e-4)

    def test_sampling_statistics(self):
        import paddle_tpu.distribution as D
        paddle.seed(0)
        for d, m in [(D.Gamma(3.0, 2.0), 1.5), (D.Laplace(1.0, 2.0), 1.0),
                     (D.Gumbel(0.0, 1.0), float(np.euler_gamma))]:
            s = d.sample((4000,)).numpy()
            assert abs(s.mean() - m) < 0.15, (type(d).__name__, s.mean())

    def test_dirichlet_multinomial(self):
        import paddle_tpu.distribution as D
        paddle.seed(0)
        dd = D.Dirichlet(np.array([2.0, 3.0, 5.0], np.float32))
        s = dd.sample((2000,)).numpy()
        np.testing.assert_allclose(s.mean(0), [0.2, 0.3, 0.5], atol=0.03)
        mn = D.Multinomial(10, np.array([0.5, 0.5], np.float32))
        sm = mn.sample((500,)).numpy()
        assert sm.sum(-1).max() == 10
        np.testing.assert_allclose(sm.mean(0), [5, 5], atol=0.5)


class TestQuantization:
    def test_fake_quant_ste_grad(self):
        from paddle_tpu.quantization import fake_quant
        x = paddle.to_tensor(
            rng.uniform(-0.9, 0.9, size=(8,)).astype(np.float32),
            stop_gradient=False)
        y = fake_quant(x, 1.0, bit_length=8)
        # quantized values close to original at 8 bits
        np.testing.assert_allclose(y.numpy(), x.numpy(), atol=1 / 127 + 1e-6)
        y.sum().backward()
        # STE: unit gradient inside the clip range
        np.testing.assert_array_equal(x.grad.numpy(), np.ones(8,
                                                              np.float32))

    def test_qat_quantize_and_convert(self):
        from paddle_tpu.quantization import QAT, QuantedLinear
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        x = paddle.to_tensor(rng.normal(size=(2, 8)).astype(np.float32))
        ref = net(x).numpy()
        qat = QAT()
        qnet = qat.quantize(net)
        assert isinstance(qnet[0], QuantedLinear)
        out = qnet(x).numpy()
        np.testing.assert_allclose(out, ref, atol=0.15)  # 8-bit error
        # training still works through fake-quant (STE)
        loss = (qnet(x) ** 2).sum()
        loss.backward()
        assert qnet[0].linear.weight.grad is not None
        qat.convert(qnet)
        out2 = qnet(x).numpy()
        np.testing.assert_allclose(out2, ref, atol=0.15)

    def test_ptq_calibrate_convert(self):
        from paddle_tpu.quantization import PTQ
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 8))
        x = paddle.to_tensor(rng.normal(size=(4, 8)).astype(np.float32))
        ref = net(x).numpy()
        ptq = PTQ()
        onet = ptq.quantize(net)
        for _ in range(3):
            onet(x)  # calibration
        qnet = ptq.convert(onet)
        np.testing.assert_allclose(qnet(x).numpy(), ref, atol=0.1)


class TestRound3Ops:
    """Ops added in round 3 (op-surface growth, VERDICT r2 item 9):
    parity vs torch/numpy oracles."""

    def test_sgn_sinc_inverse_pdist(self):
        import torch.nn.functional as TF
        import torch
        a = np.random.default_rng(1).standard_normal((4, 4)).astype(
            np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.inverse(paddle.to_tensor(a))._value),
            np.linalg.inv(a), atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(paddle.sinc(paddle.to_tensor(a))._value),
            np.sinc(a), atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(paddle.sgn(paddle.to_tensor(a))._value),
            np.sign(a))
        pts = np.random.default_rng(2).standard_normal((5, 3)).astype(
            np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.pdist(paddle.to_tensor(pts))._value),
            TF.pdist(torch.tensor(pts)).numpy(), atol=1e-5)

    @pytest.mark.parametrize("align_corners", [True, False])
    @pytest.mark.parametrize("mode", ["bilinear", "nearest"])
    def test_grid_sample_affine_grid_vs_torch(self, align_corners, mode):
        import torch
        import torch.nn.functional as TF
        from paddle_tpu.nn import functional as F
        x = np.random.default_rng(0).standard_normal(
            (2, 3, 8, 8)).astype(np.float32)
        theta = np.asarray(
            [[[1.0, 0.1, 0.2], [0.0, 0.9, -0.1]]] * 2, np.float32)
        g_ref = TF.affine_grid(torch.tensor(theta), (2, 3, 6, 6),
                               align_corners=align_corners).numpy()
        g_got = np.asarray(F.affine_grid(
            paddle.to_tensor(theta), [2, 3, 6, 6],
            align_corners=align_corners)._value)
        np.testing.assert_allclose(g_got, g_ref, atol=1e-5)
        o_ref = TF.grid_sample(torch.tensor(x), torch.tensor(g_ref),
                               mode=mode, padding_mode="zeros",
                               align_corners=align_corners).numpy()
        o_got = np.asarray(F.grid_sample(
            paddle.to_tensor(x), paddle.to_tensor(g_ref), mode=mode,
            align_corners=align_corners)._value)
        np.testing.assert_allclose(o_got, o_ref, atol=1e-4)


class TestRound3Breadth:
    """Round-3 op additions: scatter variants, block_diag, special fns,
    linalg extensions. NumPy/scipy oracles (SURVEY.md §4 OpTest)."""

    def test_block_diag_and_cartesian_prod(self):
        a = rng.normal(size=(2, 3)).astype(np.float32)
        b = rng.normal(size=(1, 2)).astype(np.float32)
        out = paddle.block_diag([paddle.to_tensor(a), paddle.to_tensor(b)])
        import scipy.linalg as sl
        np.testing.assert_allclose(out.numpy(), sl.block_diag(a, b))

        u = np.array([1, 2], np.int32)
        v = np.array([3, 4, 5], np.int32)
        cp = paddle.cartesian_prod([paddle.to_tensor(u), paddle.to_tensor(v)])
        ref = np.array([[i, j] for i in u for j in v], np.int32)
        np.testing.assert_array_equal(cp.numpy(), ref)

    def test_scatter_variants(self):
        x = rng.normal(size=(4, 5)).astype(np.float32)
        d = rng.normal(size=(4,)).astype(np.float32)
        out = paddle.diagonal_scatter(paddle.to_tensor(x),
                                      paddle.to_tensor(d))
        ref = x.copy()
        np.fill_diagonal(ref, d)
        np.testing.assert_allclose(out.numpy(), ref)

        row = rng.normal(size=(5,)).astype(np.float32)
        out2 = paddle.select_scatter(paddle.to_tensor(x),
                                     paddle.to_tensor(row), axis=0, index=2)
        ref2 = x.copy()
        ref2[2] = row
        np.testing.assert_allclose(out2.numpy(), ref2)

        blk = rng.normal(size=(4, 2)).astype(np.float32)
        out3 = paddle.slice_scatter(paddle.to_tensor(x),
                                    paddle.to_tensor(blk), axes=[1],
                                    starts=[1], ends=[5], strides=[2])
        ref3 = x.copy()
        ref3[:, 1:5:2] = blk
        np.testing.assert_allclose(out3.numpy(), ref3)

    def test_special_functions(self):
        import scipy.special as sp
        a = rng.uniform(0.5, 3.0, (6,)).astype(np.float32)
        b = rng.uniform(0.5, 3.0, (6,)).astype(np.float32)
        np.testing.assert_allclose(
            paddle.gammainc(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
            sp.gammainc(a, b), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.gammaincc(paddle.to_tensor(a),
                             paddle.to_tensor(b)).numpy(),
            sp.gammaincc(a, b), rtol=1e-5)
        x = np.array([np.inf, -np.inf, 1.0], np.float32)
        assert paddle.isposinf(paddle.to_tensor(x)).numpy().tolist() == \
            [True, False, False]
        assert paddle.isneginf(paddle.to_tensor(x)).numpy().tolist() == \
            [False, True, False]
        np.testing.assert_allclose(
            paddle.float_power(paddle.to_tensor(np.array([2.0, 3.0])),
                               2).numpy(), [4.0, 9.0])

    def test_cumulative_trapezoid_and_vecdot(self):
        y = rng.normal(size=(3, 8)).astype(np.float32)
        x = np.sort(rng.normal(size=(3, 8)).astype(np.float32), axis=-1)
        out = paddle.cumulative_trapezoid(paddle.to_tensor(y),
                                          paddle.to_tensor(x))
        try:
            from scipy.integrate import cumulative_trapezoid as ct
            np.testing.assert_allclose(out.numpy(), ct(y, x, axis=-1),
                                       rtol=1e-4, atol=1e-5)
        except ImportError:
            assert out.shape == [3, 7]
        a = rng.normal(size=(4, 3)).astype(np.float32)
        b = rng.normal(size=(4, 3)).astype(np.float32)
        np.testing.assert_allclose(
            paddle.vecdot(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
            (a * b).sum(-1), rtol=1e-5)

    def test_linalg_extensions(self):
        a = rng.normal(size=(4, 4)).astype(np.float32)
        spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        l = np.linalg.cholesky(spd)
        inv = paddle.linalg.cholesky_inverse(paddle.to_tensor(l))
        np.testing.assert_allclose(inv.numpy(), np.linalg.inv(spd),
                                   rtol=1e-3, atol=1e-4)

        bvec = rng.normal(size=(4, 2)).astype(np.float32)
        lu_t, piv = paddle.linalg.lu(paddle.to_tensor(spd))
        x = paddle.linalg.lu_solve(paddle.to_tensor(bvec), lu_t, piv)
        np.testing.assert_allclose(spd @ x.numpy(), bvec, rtol=1e-3,
                                   atol=1e-3)

        m = rng.normal(size=(2, 3, 4)).astype(np.float32)
        np.testing.assert_allclose(
            paddle.linalg.matrix_transpose(paddle.to_tensor(m)).numpy(),
            m.swapaxes(-1, -2))

    def test_ormqr(self):
        import scipy.linalg as sl
        a = rng.normal(size=(5, 3)).astype(np.float32)
        c = rng.normal(size=(5, 2)).astype(np.float32)
        # LAPACK geqrf packed (qr, tau) from scipy; numpy's complete-mode Q
        # comes from the same reflectors (orgqr), so it is the exact oracle
        (qr_, tau), _r = sl.qr(a, mode="raw")
        out = paddle.linalg.ormqr(
            paddle.to_tensor(np.ascontiguousarray(qr_, np.float32)),
            paddle.to_tensor(np.ascontiguousarray(tau, np.float32)),
            paddle.to_tensor(c))
        q = np.linalg.qr(a, mode="complete")[0]
        np.testing.assert_allclose(out.numpy(), q @ c, rtol=1e-3,
                                   atol=1e-3)

    def test_histogram_bin_edges_and_misc(self):
        # local generator: the shared module rng makes this data depend on
        # test order, and an edge near 0 needs atol, not just rtol
        x = np.random.default_rng(42).normal(size=(50,)) \
            .astype(np.float32)
        e = paddle.histogram_bin_edges(paddle.to_tensor(x), bins=10)
        ref = np.histogram_bin_edges(x, bins=10)
        np.testing.assert_allclose(e.numpy(), ref, rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(
            paddle.bitwise_invert(
                paddle.to_tensor(np.array([0, 1], np.int32))).numpy(),
            [-1, -2])
        np.testing.assert_allclose(
            paddle.positive(paddle.to_tensor(np.array([-1.0, 2.0])))
            .numpy(), [-1.0, 2.0])


class TestRound3Distributions:
    """Cauchy/StudentT/MVN/Binomial/ContinuousBernoulli/Independent/
    Transformed (round-3). Oracles: scipy.stats + torch.distributions."""

    def test_cauchy(self):
        import scipy.stats as st
        from paddle_tpu.distribution import Cauchy
        d = Cauchy(loc=1.0, scale=2.0)
        x = np.array([-1.0, 0.5, 3.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(paddle.to_tensor(x)).numpy(),
            st.cauchy.logpdf(x, 1.0, 2.0), rtol=1e-5)
        np.testing.assert_allclose(
            d.cdf(paddle.to_tensor(x)).numpy(),
            st.cauchy.cdf(x, 1.0, 2.0), rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy().numpy()),
                                   st.cauchy.entropy(1.0, 2.0), rtol=1e-5)

    def test_student_t(self):
        import scipy.stats as st
        from paddle_tpu.distribution import StudentT
        d = StudentT(df=5.0, loc=1.0, scale=2.0)
        x = np.array([-1.0, 0.5, 3.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(paddle.to_tensor(x)).numpy(),
            st.t.logpdf(x, 5.0, 1.0, 2.0), rtol=1e-5)
        np.testing.assert_allclose(float(d.variance.numpy()),
                                   st.t.var(5.0, 1.0, 2.0), rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy().numpy()),
                                   st.t.entropy(5.0, 1.0, 2.0), rtol=1e-4)

    def test_multivariate_normal(self):
        import scipy.stats as st
        from paddle_tpu.distribution import (MultivariateNormal,
                                             kl_divergence)
        mu = np.array([1.0, -1.0], np.float32)
        cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
        d = MultivariateNormal(paddle.to_tensor(mu),
                               covariance_matrix=paddle.to_tensor(cov))
        x = np.array([[0.0, 0.0], [1.0, -1.0]], np.float32)
        np.testing.assert_allclose(
            d.log_prob(paddle.to_tensor(x)).numpy(),
            st.multivariate_normal.logpdf(x, mu, cov), rtol=1e-4)
        np.testing.assert_allclose(
            float(d.entropy().numpy()),
            st.multivariate_normal.entropy(mu, cov), rtol=1e-5)
        # KL vs itself = 0; vs shifted > 0
        assert abs(float(kl_divergence(d, d).numpy())) < 1e-5
        d2 = MultivariateNormal(paddle.to_tensor(mu + 1.0),
                                covariance_matrix=paddle.to_tensor(cov))
        assert float(kl_divergence(d, d2).numpy()) > 0.1
        s = d.sample((5000,))
        np.testing.assert_allclose(s.numpy().mean(0), mu, atol=0.1)

    def test_binomial(self):
        import scipy.stats as st
        from paddle_tpu.distribution import Binomial
        d = Binomial(total_count=10.0, probs=0.3)
        k = np.array([0.0, 3.0, 10.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(paddle.to_tensor(k)).numpy(),
            st.binom.logpmf(k, 10, 0.3), rtol=1e-4)
        np.testing.assert_allclose(float(d.entropy().numpy()),
                                   st.binom.entropy(10, 0.3), rtol=1e-4)

    def test_continuous_bernoulli_matches_torch(self):
        torch = pytest.importorskip("torch")
        from paddle_tpu.distribution import ContinuousBernoulli
        for p in (0.2, 0.5, 0.8):
            d = ContinuousBernoulli(probs=p)
            td = torch.distributions.ContinuousBernoulli(probs=p)
            x = np.array([0.1, 0.5, 0.9], np.float32)
            np.testing.assert_allclose(
                d.log_prob(paddle.to_tensor(x)).numpy(),
                td.log_prob(torch.tensor(x)).numpy(), rtol=1e-4,
                atol=1e-5)
            np.testing.assert_allclose(float(d.mean.numpy()),
                                       float(td.mean), rtol=1e-4)

    def test_independent_and_transformed(self):
        torch = pytest.importorskip("torch")
        from paddle_tpu.distribution import (Normal, Independent,
                                             TransformedDistribution,
                                             ExpTransform, AffineTransform)
        base = Normal(np.zeros(3, np.float32), np.ones(3, np.float32))
        ind = Independent(base, 1)
        assert ind.event_shape == (3,)
        x = np.array([0.5, -0.5, 1.0], np.float32)
        lp = float(ind.log_prob(paddle.to_tensor(x)).numpy())
        ref = float(torch.distributions.Independent(
            torch.distributions.Normal(torch.zeros(3), torch.ones(3)), 1)
            .log_prob(torch.tensor(x)))
        np.testing.assert_allclose(lp, ref, rtol=1e-5)

        # log-normal via TransformedDistribution == LogNormal
        td = TransformedDistribution(Normal(0.0, 1.0), [ExpTransform()])
        y = np.array([0.5, 1.0, 2.0], np.float32)
        import scipy.stats as st
        np.testing.assert_allclose(
            td.log_prob(paddle.to_tensor(y)).numpy(),
            st.lognorm.logpdf(y, 1.0), rtol=1e-4)
        # affine: y = 2x + 1 of standard normal == N(1, 2)
        ta = TransformedDistribution(Normal(0.0, 1.0),
                                     [AffineTransform(1.0, 2.0)])
        np.testing.assert_allclose(
            ta.log_prob(paddle.to_tensor(y)).numpy(),
            st.norm.logpdf(y, 1.0, 2.0), rtol=1e-4)

    def test_transform_roundtrip_and_ldj(self):
        from paddle_tpu.distribution import (SigmoidTransform,
                                             TanhTransform)
        x = np.array([-1.5, 0.0, 2.0], np.float32)
        for T in (SigmoidTransform, TanhTransform):
            t = T()
            y = t.forward(paddle.to_tensor(x))
            back = t.inverse(y)
            np.testing.assert_allclose(back.numpy(), x, rtol=1e-4,
                                       atol=1e-5)
            # fldj matches numeric d/dx log|f'(x)|
            eps = 1e-3
            num = np.log(np.abs(
                (t.forward(paddle.to_tensor(x + eps)).numpy()
                 - t.forward(paddle.to_tensor(x - eps)).numpy())
                / (2 * eps)))
            np.testing.assert_allclose(
                t.forward_log_det_jacobian(paddle.to_tensor(x)).numpy(),
                num, atol=1e-3)


class TestMoreTransforms:
    def test_chain_equals_lognormal_affine(self):
        import scipy.stats as st
        from paddle_tpu.distribution import (Normal, ChainTransform,
                                             ExpTransform, AffineTransform,
                                             TransformedDistribution)
        # y = 2 * exp(x) : chain [exp, affine(0, 2)] over standard normal
        td = TransformedDistribution(
            Normal(0.0, 1.0),
            [ChainTransform([ExpTransform(), AffineTransform(0.0, 2.0)])])
        y = np.array([0.5, 1.0, 3.0], np.float32)
        ref = st.lognorm.logpdf(y, 1.0, scale=2.0)
        np.testing.assert_allclose(
            td.log_prob(paddle.to_tensor(y)).numpy(), ref, rtol=1e-4)

    def test_power_and_abs(self):
        from paddle_tpu.distribution import PowerTransform, AbsTransform
        p = PowerTransform(2.0)
        x = np.array([1.5, 2.0], np.float32)
        np.testing.assert_allclose(
            p.forward(paddle.to_tensor(x)).numpy(), x ** 2, rtol=1e-6)
        np.testing.assert_allclose(
            p.inverse(p.forward(paddle.to_tensor(x))).numpy(), x,
            rtol=1e-5)
        eps = 1e-3
        num = np.log((((x + eps) ** 2) - ((x - eps) ** 2)) / (2 * eps))
        np.testing.assert_allclose(
            p.forward_log_det_jacobian(paddle.to_tensor(x)).numpy(), num,
            atol=1e-3)
        a = AbsTransform()
        np.testing.assert_allclose(
            a.forward(paddle.to_tensor(np.array([-2.0, 3.0]))).numpy(),
            [2.0, 3.0])

    def test_stack_transform(self):
        from paddle_tpu.distribution import (StackTransform, ExpTransform,
                                             AffineTransform)
        st_ = StackTransform([ExpTransform(), AffineTransform(1.0, 3.0)],
                             axis=0)
        x = np.array([[0.0, 1.0], [2.0, 3.0]], np.float32)
        out = st_.forward(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out[0], np.exp(x[0]), rtol=1e-6)
        np.testing.assert_allclose(out[1], 1.0 + 3.0 * x[1], rtol=1e-6)
        back = st_.inverse(paddle.to_tensor(out)).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-5, atol=1e-6)


class TestViterbi:
    def test_brute_force_parity(self):
        import itertools
        from paddle_tpu.text import viterbi_decode
        r = np.random.default_rng(4)
        B, T, N = 2, 5, 3
        pot = r.normal(size=(B, T, N)).astype(np.float32)
        trans = r.normal(size=(N, N)).astype(np.float32)
        scores, paths = viterbi_decode(paddle.to_tensor(pot),
                                       paddle.to_tensor(trans),
                                       include_bos_eos_tag=False)
        for b in range(B):
            best, bestp = -1e9, None
            for p in itertools.product(range(N), repeat=T):
                s = pot[b, 0, p[0]] + sum(
                    trans[p[i - 1], p[i]] + pot[b, i, p[i]]
                    for i in range(1, T))
                if s > best:
                    best, bestp = s, p
            np.testing.assert_allclose(float(scores.numpy()[b]), best,
                                       rtol=1e-5)
            np.testing.assert_array_equal(np.asarray(paths._value)[b],
                                          bestp)

    def test_bos_eos_brute_force_parity(self):
        # reference contract: potentials' tag dim == transitions dim N
        # (incl. BOS/EOS); start = trans[-1], stop = trans[:, -2]; decode
        # runs over the FULL tag space — BOS/EOS are discouraged only by
        # their transition scores, never hard-excluded (advisor r4).
        import itertools
        from paddle_tpu.text import ViterbiDecoder
        r = np.random.default_rng(5)
        B, T, N = 2, 4, 5          # 3 real labels + EOS + BOS
        pot = r.normal(size=(B, T, N)).astype(np.float32)
        trans = r.normal(size=(N, N)).astype(np.float32)
        dec = ViterbiDecoder(paddle.to_tensor(trans))
        scores, paths = dec(paddle.to_tensor(pot))
        assert tuple(paths.shape) == (B, T)
        for b in range(B):
            best, bestp = -1e9, None
            for p in itertools.product(range(N), repeat=T):
                s = trans[-1, p[0]] + pot[b, 0, p[0]] + sum(
                    trans[p[i - 1], p[i]] + pot[b, i, p[i]]
                    for i in range(1, T)) + trans[p[-1], -2]
                if s > best:
                    best, bestp = s, p
            np.testing.assert_allclose(float(scores.numpy()[b]), best,
                                       rtol=1e-5)
            np.testing.assert_array_equal(np.asarray(paths._value)[b],
                                          bestp)

    def test_bos_eos_discouraging_transitions_stay_on_real_labels(self):
        # when BOS/EOS carry strongly negative incoming transitions (the
        # trained-CRF shape), full-space decode picks only real labels
        from paddle_tpu.text import ViterbiDecoder
        r = np.random.default_rng(7)
        B, T, N = 2, 5, 6
        pot = r.normal(size=(B, T, N)).astype(np.float32)
        trans = r.normal(size=(N, N)).astype(np.float32)
        trans[:, -1] = -1e4        # nothing enters BOS
        trans[-2, :] = -1e4        # nothing leaves EOS
        trans[:, -2] -= 20.0       # EOS mid-sequence strongly penalized
        dec = ViterbiDecoder(paddle.to_tensor(trans))
        _, paths = dec(paddle.to_tensor(pot))
        assert int(np.asarray(paths._value).max()) < N - 2

    def test_lengths_and_bos_eos(self):
        from paddle_tpu.text import ViterbiDecoder
        r = np.random.default_rng(5)
        B, T, N = 2, 6, 6          # 4 real labels + EOS + BOS
        pot = r.normal(size=(B, T, N)).astype(np.float32)
        trans = r.normal(size=(N, N)).astype(np.float32)
        trans[:, -1] = -1e4        # trained-CRF shape: BOS/EOS never
        trans[:, -2] -= 20.0       # entered mid-sequence
        dec = ViterbiDecoder(paddle.to_tensor(trans))
        scores, paths = dec(paddle.to_tensor(pot),
                            paddle.to_tensor(np.array([6, 3], np.int32)))
        assert tuple(paths.shape) == (B, T)
        assert np.isfinite(scores.numpy()).all()
        assert int(np.asarray(paths._value).max()) < N - 2
        # shorter sequence: positions beyond length repeat the end tag
        p1 = np.asarray(paths._value)[1]
        assert (p1[2:] == p1[2]).all()

    def test_shape_mismatch_raises(self):
        from paddle_tpu.text import viterbi_decode
        pot = paddle.to_tensor(np.zeros((1, 3, 4), np.float32))
        bad = paddle.to_tensor(np.zeros((6, 6), np.float32))
        with pytest.raises(ValueError, match="tag dim"):
            viterbi_decode(pot, bad)


class TestRound4Breadth:
    """i0e/i1e/multigammaln/log_normal/Softmax2D/embedding_bag/
    margin_cross_entropy (round-4 breadth audit closers)."""

    def test_bessel_scaled_vs_scipy(self):
        import scipy.special as sp
        x = np.linspace(0.1, 5, 13).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.i0e(paddle.to_tensor(x))._value),
            sp.i0e(x), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(paddle.i1e(paddle.to_tensor(x))._value),
            sp.i1e(x), rtol=1e-5)

    def test_multigammaln_vs_scipy(self):
        import scipy.special as sp
        x = np.linspace(3, 8, 7).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.multigammaln(
                paddle.to_tensor(x), 3)._value),
            sp.multigammaln(x, 3), rtol=1e-5)

    def test_log_normal_moments(self):
        paddle.seed(3)
        s = np.asarray(paddle.log_normal(
            mean=0.0, std=0.25, shape=[20000])._value)
        assert (s > 0).all()
        np.testing.assert_allclose(np.log(s).mean(), 0.0, atol=0.02)
        np.testing.assert_allclose(np.log(s).std(), 0.25, atol=0.02)

    def test_softmax2d(self):
        from paddle_tpu import nn
        x = np.random.default_rng(0).normal(size=(2, 3, 4, 5)) \
            .astype(np.float32)
        out = np.asarray(nn.Softmax2D()(paddle.to_tensor(x))._value)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)
        with pytest.raises(ValueError):
            nn.Softmax2D()(paddle.to_tensor(np.zeros((2, 3), np.float32)))

    def test_embedding_bag_vs_torch(self):
        import torch
        import paddle_tpu.nn.functional as F
        r = np.random.default_rng(5)
        w = r.normal(size=(10, 4)).astype(np.float32)
        ids2d = r.integers(0, 10, (3, 5))
        for mode in ("sum", "mean", "max"):
            got = np.asarray(F.embedding_bag(
                paddle.to_tensor(ids2d.astype(np.int32)),
                paddle.to_tensor(w), mode=mode)._value)
            ref = torch.nn.functional.embedding_bag(
                torch.tensor(ids2d), torch.tensor(w),
                mode=mode).numpy()
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        # ragged 1-D + offsets
        ids1 = r.integers(0, 10, (7,))
        offs = np.array([0, 3, 5])
        got = np.asarray(F.embedding_bag(
            paddle.to_tensor(ids1.astype(np.int32)),
            paddle.to_tensor(w),
            offsets=paddle.to_tensor(offs.astype(np.int32)),
            mode="mean")._value)
        ref = torch.nn.functional.embedding_bag(
            torch.tensor(ids1), torch.tensor(w),
            offsets=torch.tensor(offs), mode="mean").numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_embedding_bag_per_sample_weights_grad_vs_torch(self):
        # grad must FLOW to per_sample_weights in mode='sum' (advisor
        # r4: it was closed over instead of passing through apply())
        import torch
        import paddle_tpu.nn.functional as F
        r = np.random.default_rng(9)
        w = r.normal(size=(10, 4)).astype(np.float32)
        ids2d = r.integers(0, 10, (3, 5))
        psw = r.normal(size=(3, 5)).astype(np.float32)

        pt = paddle.to_tensor(psw, stop_gradient=False)
        wt = paddle.to_tensor(w, stop_gradient=False)
        out = F.embedding_bag(paddle.to_tensor(ids2d.astype(np.int32)),
                              wt, mode="sum", per_sample_weights=pt)
        out.sum().backward()
        assert pt.grad is not None and wt.grad is not None

        tw = torch.tensor(w, requires_grad=True)
        tp = torch.tensor(psw, requires_grad=True)
        tout = torch.nn.functional.embedding_bag(
            torch.tensor(ids2d), tw, mode="sum", per_sample_weights=tp)
        tout.sum().backward()
        np.testing.assert_allclose(np.asarray(pt.grad._value),
                                   tp.grad.numpy(), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(wt.grad._value),
                                   tw.grad.numpy(), rtol=1e-5, atol=1e-6)
        # 1-D ragged path too
        ids1 = r.integers(0, 10, (6,))
        offs = np.array([0, 2, 5], np.int32)
        psw1 = r.normal(size=(6,)).astype(np.float32)
        p1 = paddle.to_tensor(psw1, stop_gradient=False)
        F.embedding_bag(paddle.to_tensor(ids1.astype(np.int32)),
                        paddle.to_tensor(w),
                        offsets=paddle.to_tensor(offs), mode="sum",
                        per_sample_weights=p1).sum().backward()
        tp1 = torch.tensor(psw1, requires_grad=True)
        torch.nn.functional.embedding_bag(
            torch.tensor(ids1), torch.tensor(w),
            offsets=torch.tensor(offs.astype(np.int64)), mode="sum",
            per_sample_weights=tp1).sum().backward()
        np.testing.assert_allclose(np.asarray(p1.grad._value),
                                   tp1.grad.numpy(), rtol=1e-5, atol=1e-6)

    def test_margin_cross_entropy_reduces_to_softmax_ce(self):
        import paddle_tpu.nn.functional as F
        r = np.random.default_rng(6)
        cos = np.clip(r.normal(scale=0.4, size=(8, 12)), -0.95,
                      0.95).astype(np.float32)
        lab = r.integers(0, 12, (8,)).astype(np.int64)
        # m1=1, m2=0, m3=0: identical to scaled softmax CE
        plain = F.margin_cross_entropy(
            paddle.to_tensor(cos), paddle.to_tensor(lab), margin1=1.0,
            margin2=0.0, margin3=0.0, scale=10.0)
        z = cos * 10.0
        lse = np.log(np.exp(z - z.max(-1, keepdims=True)).sum(-1)) \
            + z.max(-1)
        ref = (lse - z[np.arange(8), lab]).mean()
        np.testing.assert_allclose(float(plain), ref, rtol=1e-5)
        # arcface margin must INCREASE the loss (harder target)
        hard = F.margin_cross_entropy(
            paddle.to_tensor(cos), paddle.to_tensor(lab), margin2=0.5,
            scale=10.0)
        assert float(hard) > float(plain)
        # grads flow
        t = paddle.to_tensor(cos, stop_gradient=False)
        F.margin_cross_entropy(t, paddle.to_tensor(lab)).backward()
        assert t.grad is not None

    def test_embedding_bag_offsets_padding_mean_matches_torch(self):
        import torch
        import paddle_tpu.nn.functional as F
        w = np.arange(40, dtype=np.float32).reshape(10, 4)
        ids = np.array([0, 1, 2], np.int64)
        offs = np.array([0, 3], np.int64)
        got = np.asarray(F.embedding_bag(
            paddle.to_tensor(ids.astype(np.int32)), paddle.to_tensor(w),
            offsets=paddle.to_tensor(offs.astype(np.int32)),
            mode="mean", padding_idx=0)._value)
        ref = torch.nn.functional.embedding_bag(
            torch.tensor(ids), torch.tensor(w),
            offsets=torch.tensor(offs), mode="mean",
            padding_idx=0).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_margin_ce_bad_reduction_raises(self):
        import paddle_tpu.nn.functional as F
        with pytest.raises(ValueError, match="reduction"):
            F.margin_cross_entropy(
                paddle.to_tensor(np.zeros((2, 3), np.float32)),
                paddle.to_tensor(np.zeros(2, np.int64)),
                reduction="avg")

    def test_log_normal_int_and_tensor_shapes(self):
        paddle.seed(0)
        assert tuple(paddle.log_normal(shape=5).shape) == (5,)
        assert tuple(paddle.log_normal(
            shape=paddle.to_tensor(np.array([3], np.int32))).shape) == (3,)

    def test_i0e_preserves_dtype(self):
        import jax.numpy as jnp
        t = paddle.to_tensor(np.ones(4, np.float32)).astype("bfloat16")
        assert paddle.i0e(t)._value.dtype == jnp.bfloat16
