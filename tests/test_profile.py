"""Performance attribution plane (ISSUE 20,
paddle_tpu/observability/profile.py): decode-round decomposition,
the dispatch-gap sampler, compile-cache observability behind the
`_jit_lru`/`_jit_singleton` seam, the memory ledger, histogram
exemplars, and the `paddle-tpu-obs profile` CLI.

The two acceptance gates pinned here:

* the decomposition components sum to within 10% of the measured
  round wall on the CPU oracle (the attribution is honest — nothing
  big is missing and nothing is double-counted);
* 50 warm pipelined rounds record ZERO compiles (the steady-state
  claim every bench number rests on, finally verified).

conftest runs this file with PDT_TELEMETRY=1 and
PDT_CHECK_INVARIANTS=1 and attaches the profile report to failing
reports."""
import json
import time

import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.serving import ContinuousBatchingEngine
from paddle_tpu.observability import profile
from paddle_tpu.observability.__main__ import main as obs_main

pytestmark = pytest.mark.telemetry


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=1, max_position_embeddings=64)
    paddle.seed(7)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _engine(model, k=1, **kw):
    kw.setdefault("max_batch_size", 3)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", 4)
    return ContinuousBatchingEngine(model, harvest_every=k, **kw)


JOBS = [([1, 2, 3], 40), ([4, 5], 38), ([6, 7, 8, 9], 36)]


def _warm_engine(model, k=1, jobs=JOBS):
    eng = _engine(model, k)
    for p, n in jobs:
        eng.add_request(list(p), n)
    for _ in range(4):
        eng.step()
    return eng


def _compile_total(snap):
    return sum(snap.get("counters", {}).get(
        "pdt_jit_compiles_total", {}).values())


# -- no-op unless enabled ----------------------------------------------
class TestDisabledNoOp:
    def test_disabled_records_nothing(self, model, monkeypatch):
        monkeypatch.delenv("PDT_TELEMETRY", raising=False)
        telemetry.disable()
        telemetry.reset()
        try:
            profile.note_round("dispatch", 0.01)
            jit = profile.compile_timed(lambda: 7, "decode")
            assert jit() == 7
            profile.note_cache("prefill", 3, evicted=1)
            eng = _warm_engine(model)
            eng.step()
            snap = telemetry.snapshot()
        finally:
            telemetry.disable(clear_override=True)  # back to env-driven
        for section in ("counters", "gauges", "histograms"):
            assert not any(
                n.startswith(("pdt_profile_", "pdt_jit_", "pdt_mem_"))
                for n in snap.get(section, {})), snap[section]

    def test_fence_is_identity_when_unarmed(self):
        x = object()
        assert profile.fence("qkv", x) is x


# -- decode-round decomposition ----------------------------------------
class TestDecomposition:
    @pytest.mark.parametrize("k", [1, 4])
    def test_components_sum_close_to_round_wall(self, model, k):
        """THE honesty gate: sum of the per-component walls recorded
        across 20 warm steps lands within 10% of the outer wall of
        those same steps."""
        eng = _warm_engine(model, k)
        telemetry.reset()            # drop warm-phase observations
        t0 = time.perf_counter()
        for _ in range(20):
            eng.step()
        eng.quiesce()                # commit the tail window
        wall = time.perf_counter() - t0
        snap = telemetry.snapshot()
        series = snap["histograms"].get("pdt_profile_round_seconds", {})
        total = sum(v["sum"] for v in series.values())
        comps = {lbl.split('"')[1] for lbl in series}
        assert {"dispatch", "device", "harvest", "host"} <= comps
        assert 0.90 * wall <= total <= 1.10 * wall, (
            f"decomposition covers {total / wall:.1%} of the round "
            f"wall (components {sorted(comps)})")

    def test_components_are_catalogued_set(self):
        assert profile.COMPONENTS == ("dispatch", "device", "harvest",
                                      "journal", "sentry", "host")


# -- dispatch-gap sampler ----------------------------------------------
class TestGapSampler:
    def test_profile_round_table_and_determinism(self, model):
        """The sampled round is observation-only: interleaving
        profile_round() between steps must leave the greedy streams
        bit-identical to an undisturbed engine."""
        plain = _engine(model)
        sampled = _engine(model)
        for eng in (plain, sampled):
            for p, n in JOBS:
                eng.add_request(list(p), n)
            for _ in range(3):
                eng.step()
        tables = []
        for i in range(6):
            plain.step()
            sampled.step()
            if i % 2 == 0:
                tables.append(sampled.profile_round())
        out_p = {r.request_id: list(r.output)
                 for r in plain._slot_req if r is not None}
        out_s = {r.request_id: list(r.output)
                 for r in sampled._slot_req if r is not None}
        assert out_p == out_s
        # ranked table over the fenced op families of llama.py
        table = tables[-1]
        assert table, "sampled round produced no gap rows"
        pairs = [row["op_pair"] for row in table]
        gaps = [row["gap_s"] for row in table]
        assert gaps == sorted(gaps, reverse=True)
        fenced = {p for pair in pairs for p in pair.split("->")}
        assert fenced <= {"embed", "rmsnorm", "qkv", "rope",
                          "kv_scatter", "attention", "oproj", "mlp"}
        assert "qkv" in fenced and "attention" in fenced
        # and the ranked gauges are published
        gs = telemetry.snapshot()["gauges"].get(
            "pdt_profile_gap_seconds", {})
        assert len(gs) == len(table)

    def test_profile_round_requires_ragged_paged(self, model):
        eng = _engine(model, attention_impl="legacy")
        eng.add_request([1, 2], 8)
        eng.step()
        with pytest.raises(RuntimeError, match="paged\\+ragged"):
            eng.profile_round()

    def test_profile_round_requires_active_slot(self, model):
        eng = _engine(model)
        with pytest.raises(RuntimeError, match="active slot"):
            eng.profile_round()


# -- compile-cache observability ---------------------------------------
class TestCompileObservability:
    def test_fifty_warm_pipelined_rounds_zero_compiles(self, model):
        """THE steady-state gate (ISSUE 20 acceptance): 50 warm
        pipelined rounds on a shape-stable batch mint zero programs."""
        eng = _warm_engine(model, k=4,
                           jobs=[([1, 2, 3], 60), ([4, 5], 58),
                                 ([6, 7, 8, 9], 56)])
        telemetry.reset()
        for _ in range(50):
            eng.step()
        snap = telemetry.snapshot()
        assert _compile_total(snap) == 0, snap["counters"][
            "pdt_jit_compiles_total"]

    def test_compiles_metered_per_family(self, model):
        telemetry.reset()
        eng = _warm_engine(model)
        snap = telemetry.snapshot()
        compiles = snap["counters"]["pdt_jit_compiles_total"]
        fams = {lbl.split('"')[1] for lbl in compiles}
        # the paged+ragged admission/decode path mints exactly these:
        # one keyed ragged-prefill program + the decode singleton
        assert {"decode", "ragged"} <= fams
        hist = snap["histograms"]["pdt_jit_compile_seconds"]
        for lbl, n in compiles.items():
            assert hist[lbl]["count"] == n
        # the jit.compile span joined the trace ring
        assert any(e.get("name") == "jit.compile"
                   for e in telemetry.events())

    def test_lru_eviction_metered(self, model):
        telemetry.reset()
        from collections import OrderedDict
        eng = _engine(model)
        cache = OrderedDict()
        for key in ("a", "b", "c"):
            eng._jit_lru(cache, key, lambda: (lambda: None), cap=2,
                         family="suffix")
        snap = telemetry.snapshot()
        assert snap["counters"]["pdt_jit_cache_evictions_total"][
            'family="suffix"'] == 1.0
        assert snap["gauges"]["pdt_jit_cache_entries"][
            'family="suffix"'] == 2.0
        assert len(cache) == 2

    def test_retrace_storm_fires_on_churn_not_on_warm(self):
        clock = FakeClock()
        win = profile.configure_retrace(window_s=30.0, threshold=4,
                                        clock=clock)
        try:
            telemetry.reset()
            telemetry.clear_events()
            # warm path: ONE program invoked many times — no storm
            jit = profile.compile_timed(lambda: 0, "decode")
            for _ in range(20):
                jit()
                clock.advance(0.1)
            assert not any(e.get("name") == "profile.retrace_storm"
                           for e in telemetry.events())
            # churn: a fresh program every call (the program-key-churn
            # failure mode pow2 bucketing exists to prevent)
            for _ in range(4):
                profile.compile_timed(lambda: 0, "ragged")()
                clock.advance(0.1)
            evts = [e for e in telemetry.events()
                    if e.get("name") == "profile.retrace_storm"]
            assert len(evts) == 1
            assert telemetry.snapshot()["counters"][
                "pdt_jit_retrace_storms_total"][""] == 1.0
            # still inside the same saturated window: no re-fire
            profile.compile_timed(lambda: 0, "ragged")()
            assert sum(1 for e in telemetry.events()
                       if e.get("name") == "profile.retrace_storm") == 1
        finally:
            profile.configure_retrace(window_s=30.0, threshold=10,
                                      clock=time.monotonic)


# -- memory ledger ------------------------------------------------------
class TestMemoryLedger:
    def test_ledger_pools_from_live_engine(self, model):
        eng = _warm_engine(model)
        mem = profile.memory_ledger([eng])
        assert mem["kv_pool"] > 0
        assert 0 < mem["kv_in_use"] <= mem["kv_pool"]
        gs = telemetry.snapshot()["gauges"]["pdt_mem_bytes"]
        assert gs['pool="kv_pool"'] == mem["kv_pool"]

    def test_fleet_info_perf_section(self, model):
        from paddle_tpu.serving import ServingRouter
        router = ServingRouter(
            lambda i: _engine(model), num_replicas=1)
        router.submit([1, 2, 3], max_new_tokens=6)
        for _ in range(4):
            router.step()
        perf = router.fleet_info()["perf"]
        assert perf["mem_bytes"]["kv_pool"] > 0
        assert perf["jit"]["decode"]["compiles"] >= 1
        # and status.py renders it
        text = telemetry.render_fleet_status(router.fleet_info())
        assert "memory: " in text and "jit compiles: " in text


# -- exemplars ----------------------------------------------------------
class TestExemplars:
    def test_observe_exemplar_snapshot_and_roundtrip(self):
        telemetry.reset()
        h = telemetry.histogram("pdt_test_exemplar_seconds", "t",
                                buckets=(0.1, 1.0))
        h.observe(0.05, exemplar="req-1")
        h.observe(0.5, exemplar='we"ird\\id')
        h.observe(0.07)          # no exemplar: keeps req-1's bucket
        snap = telemetry.snapshot()
        ex = snap["histograms"]["pdt_test_exemplar_seconds"][""][
            "exemplars"]
        assert ex["0.1"] == {"trace_id": "req-1", "value": 0.05}
        assert ex["1"]["trace_id"] == 'we"ird\\id'
        text = telemetry.to_prometheus()
        assert '# {trace_id="req-1"} 0.05' in text
        parsed = telemetry.parse_prometheus(text)
        snap.pop("enabled", None)
        assert parsed == snap

    def test_ttft_exemplar_links_request(self, model):
        telemetry.reset()
        eng = _engine(model)
        rid = eng.add_request([1, 2, 3], 4)
        for _ in range(3):
            eng.step()
        ex = telemetry.snapshot()["histograms"][
            "pdt_serving_ttft_seconds"][""]["exemplars"]
        assert any(e["trace_id"] == str(rid) for e in ex.values())


# -- report + CLI -------------------------------------------------------
class TestReportAndCli:
    def _fleet_snapshot(self, model, tmp_path):
        telemetry.reset()
        eng = _warm_engine(model)
        for _ in range(4):
            eng.step()
        eng.profile_round()
        profile.memory_ledger([eng])
        path = tmp_path / "snap.json"
        path.write_text(json.dumps(telemetry.snapshot()))
        return path

    def test_cli_renders_ranked_report(self, model, tmp_path, capsys):
        path = self._fleet_snapshot(model, tmp_path)
        assert obs_main(["profile", "--from", str(path)]) == 0
        out = capsys.readouterr().out
        assert "decode-round decomposition" in out
        assert "top dispatch gaps" in out
        assert "compile cache" in out
        assert "memory ledger" in out
        # ranked: first gap row is the largest
        gap_lines = [ln for ln in out.splitlines()
                     if "->" in ln]
        assert gap_lines, out

    def test_cli_prom_text_input(self, model, tmp_path, capsys):
        json_path = self._fleet_snapshot(model, tmp_path)
        prom = tmp_path / "snap.prom"
        prom.write_text(telemetry.render_prometheus(
            json.loads(json_path.read_text())))
        assert obs_main(["profile", "--from", str(prom)]) == 0

    def test_cli_exit_one_on_empty_snapshot(self, tmp_path, capsys):
        p = tmp_path / "empty.json"
        p.write_text(json.dumps(
            {"counters": {}, "gauges": {}, "histograms": {}}))
        assert obs_main(["profile", "--from", str(p)]) == 1
        assert "no profile data" in capsys.readouterr().out
