"""Gray-failure defense (ISSUE 14): corrupt-mode fault injection,
numeric sentries, canary probes, SUSPECT -> QUARANTINED with
tainted-token re-serve, canary-gated restart probation, and the
transfer plane's per-stage deadlines.

The chaos drills here are the fail-WRONG siblings of test_chaos.py's
fail-stop drills: a replica keeps answering but answers incorrectly
(bit-flipped KV pages, NaN-poisoned logits, corrupted migration
payloads), and the fleet must NOTICE — sentry trip or canary mismatch
— then quarantine and re-serve tainted streams bit-identically to an
uncorrupted fleet. conftest enables PDT_TELEMETRY=1 and
PDT_CHECK_INVARIANTS=1 for this file."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.serving import ContinuousBatchingEngine
from paddle_tpu.serving import (CanaryConfig, NumericSentry,
                                ReplicaState, SentryConfig,
                                ServingRouter, TransferStageTimeout,
                                transfer)
from paddle_tpu.utils.faults import (FaultError, FaultInjector,
                                     fault_point, fault_value,
                                     value_armed)

pytestmark = pytest.mark.chaos


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=2, num_key_value_heads=1,
                      max_position_embeddings=64)
    paddle.seed(7)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


JOBS = [([5, 4, 3, 2, 6, 7], 10), ([9, 1, 2], 10), ([7, 7, 1, 2], 10),
        ([3, 3, 9], 10)]


def _fleet(model, n=4, clock=None, engine_kw=None, **kw):
    clock = clock if clock is not None else FakeClock()
    ekw = dict(max_batch_size=3, max_seq_len=64, page_size=4)
    ekw.update(engine_kw or {})
    kw.setdefault("page_size", 4)
    kw.setdefault("sleep", clock.advance)
    router = ServingRouter(
        lambda i: ContinuousBatchingEngine(model, clock=clock, **ekw),
        num_replicas=n, policy="round_robin", clock=clock, **kw)
    return router, clock


def _sentried(model, n=4, scan_every=2, interval=5.0, **kw):
    kw.setdefault("restart_backoff_base", 3.0)
    kw.setdefault("restart_backoff_max", 3.0)
    return _fleet(model, n=n,
                  sentry=SentryConfig(scan_every=scan_every),
                  canary=CanaryConfig(interval=interval,
                                      max_new_tokens=6), **kw)


def _reference(model, jobs, n=4):
    router, _ = _fleet(model, n=n)
    rids = [router.submit(p, m) for p, m in jobs]
    out = router.run()
    return [out[r] for r in rids]


# ---------------------------------------------------------------------
class TestCorruptFaultMode:
    """utils/faults.py CORRUPT arming: deterministic value mutation
    with the raise-mode trigger set, plus tag pinning."""

    def test_bitflip_nth_deterministic_and_identity(self):
        a = np.ones((4, 4), np.float32)
        with FaultInjector(seed=0) as fi:
            fi.arm_corrupt("serving.kv_page", nth=2)
            assert fault_value("serving.kv_page", a) is a   # visit 1
            b = fault_value("serving.kv_page", a)           # fires
            assert b is not a and not np.array_equal(b, a)
            assert (b != a).sum() == 1      # ONE element damaged
            assert fi.trips("serving.kv_page") == 1
            assert fi.calls("serving.kv_page") == 2
            # nth defaults times=1: no further damage
            assert fault_value("serving.kv_page", a) is a
        with FaultInjector(seed=0) as fi:   # same seed -> same damage
            fi.arm_corrupt("serving.kv_page", nth=2)
            fault_value("serving.kv_page", a)
            b2 = fault_value("serving.kv_page", a)
        assert np.array_equal(b, b2)

    def test_nan_and_scale_modes(self):
        a = np.ones(8, np.float32)
        with FaultInjector() as fi:
            fi.arm_corrupt("serving.logits", mode="nan", always=True)
            out = fault_value("serving.logits", a)
            assert np.isnan(out).sum() == 1
            ints = fault_value("serving.logits",
                               np.arange(5, dtype=np.int32))
            assert (ints < 0).sum() == 1    # int arrays: extreme value
        with FaultInjector() as fi:
            fi.arm_corrupt("transfer.payload", mode="scale",
                           always=True, factor=10.0)
            out = fault_value("transfer.payload", a)
            assert np.allclose(out, 10.0)   # scale hits the WHOLE array

    def test_tag_filter_pins_visits(self):
        a = np.ones(4, np.float32)
        with FaultInjector() as fi:
            fi.arm_corrupt("serving.kv_page", always=True, tag="1")
            assert not value_armed("serving.kv_page")        # no tag
            assert not value_armed("serving.kv_page", tag="0")
            assert value_armed("serving.kv_page", tag="1")
            assert fault_value("serving.kv_page", a, tag="0") is a
            assert fi.calls("serving.kv_page") == 0   # filtered: no
            #                                           visit consumed
            out = fault_value("serving.kv_page", a, tag="1")
            assert out is not a
            assert fi.calls("serving.kv_page") == 1

    def test_raise_rule_fires_at_value_site(self):
        """Every value site doubles as an exception site: arm() (not
        arm_corrupt) raises through fault_value."""
        with FaultInjector() as fi:
            fi.arm("serving.kv_page", always=True)
            with pytest.raises(FaultError) as ei:
                fault_value("serving.kv_page", np.ones(2))
            assert ei.value.site == "serving.kv_page"

    def test_corrupt_rule_at_fault_point_counts_only(self):
        """A corrupt rule visited through fault_point has no value to
        mutate: the visit counts, nothing raises, nothing trips."""
        with FaultInjector() as fi:
            fi.arm_corrupt("serving.kv_page", always=True)
            fault_point("serving.kv_page")
            assert fi.calls("serving.kv_page") == 1
            assert fi.trips("serving.kv_page") == 0

    def test_arm_corrupt_validation(self):
        fi = FaultInjector()
        with pytest.raises(ValueError, match="corrupt mode"):
            fi.arm_corrupt("x.y", mode="melt", always=True)
        with pytest.raises(ValueError, match="exactly one"):
            fi.arm_corrupt("x.y")
        with pytest.raises(ValueError, match="exactly one"):
            fi.arm_corrupt("x.y", nth=1, always=True)

    def test_corrupt_fire_counts_and_event(self):
        telemetry.reset()
        telemetry.clear_events()
        with FaultInjector() as fi:
            fi.arm_corrupt("serving.kv_page", always=True, times=1)
            fault_value("serving.kv_page", np.ones(2, np.float32))
            fault_value("serving.kv_page", np.ones(2, np.float32))
        assert telemetry.value("pdt_faults_fired_total",
                               site="serving.kv_page") == 1
        ev = [e for e in telemetry.events() if e["name"] == "fault.fire"]
        assert len(ev) == 1
        assert ev[0]["attrs"]["exc"] == "corrupt:bitflip"


# ---------------------------------------------------------------------
class TestNumericSentry:
    def test_token_oov_trips(self):
        telemetry.clear_events()
        s = NumericSentry(SentryConfig(), vocab_size=64, replica=3)
        s.observe_tokens(np.asarray([1, 5, 63]))
        assert s.trips == 0
        s.observe_tokens(np.asarray([1, 64]))
        s.observe_tokens(np.asarray([-7]))
        assert s.trips == 2
        assert s.last_trip["kind"] == "token_oov"
        assert telemetry.value("pdt_sentry_trips_total",
                               kind="token_oov") == 2
        ev = [e for e in telemetry.events()
              if e["name"] == "sentry.trip"]
        assert len(ev) == 2 and ev[0]["attrs"]["replica"] == 3

    def test_logit_scan_trips_nonfinite_and_absmax(self):
        s = NumericSentry(SentryConfig(logit_abs_max=100.0),
                          vocab_size=64)
        s.observe_logits(np.asarray([[1.0, -3.0], [2.0, 99.0]]))
        assert s.trips == 0
        s.observe_logits(np.asarray([[1.0, np.nan]]))
        assert s.trips == 1 \
            and s.last_trip["kind"] == "logit_nonfinite"
        s.observe_logits(np.asarray([[1.0, -101.0]]))
        assert s.trips == 2 and s.last_trip["kind"] == "logit_absmax"
        assert s.spent > 0.0

    def test_scan_cadence(self):
        s = NumericSentry(SentryConfig(scan_every=3), vocab_size=8)
        due = [s.step_tick() for _ in range(7)]
        assert due == [True, False, False, True, False, False, True]
        off = NumericSentry(SentryConfig(scan_every=0), vocab_size=8)
        assert not off.wants_logits
        assert [off.step_tick() for _ in range(3)] == [False] * 3

    def test_config_validation(self):
        with pytest.raises(ValueError, match="scan_every"):
            SentryConfig(scan_every=-1)
        with pytest.raises(ValueError, match="logit_abs_max"):
            SentryConfig(logit_abs_max=0)
        with pytest.raises(ValueError, match="non-empty"):
            CanaryConfig(prompt=())
        with pytest.raises(ValueError, match="interval"):
            CanaryConfig(interval=0.0)
        with pytest.raises(ValueError, match="max_suspect_rounds"):
            CanaryConfig(max_suspect_rounds=0)


# ---------------------------------------------------------------------
class TestEngineSentry:
    """Engine-level hooks: the sentry observes every harvest without
    perturbing the stream, and the `serving.logits` corrupt site
    poisons exactly what the scan inspects."""

    def _run(self, model, sentry=None, fault=None):
        eng = ContinuousBatchingEngine(model, max_batch_size=2,
                                       max_seq_len=64, page_size=4)
        if sentry is not None:
            eng.attach_sentry(sentry)
        rids = [eng.add_request(p, n) for p, n in JOBS[:2]]
        if fault is not None:
            with FaultInjector(seed=0) as fi:
                fi.arm_corrupt(fault[0], **fault[1])
                out = eng.run()
        else:
            out = eng.run()
        return [out[r] for r in rids]

    def test_sentry_on_stream_identical_and_scans(self, model):
        want = self._run(model)
        s = NumericSentry(SentryConfig(scan_every=2), vocab_size=64)
        got = self._run(model, sentry=s)
        assert got == want          # observation never perturbs
        assert s.scans >= 2 and s.steps >= 4 and s.trips == 0
        assert telemetry.value("pdt_sentry_checks_total",
                               kind="logit_scan") == s.scans

    def test_nan_poisoned_logits_caught_within_n_steps(self, model):
        """Drill (b), engine half: with the scan at every Nth step, a
        NaN poisoning of the logit harvest trips within N steps of
        arming — the amortization bound is the detection bound."""
        want = self._run(model)
        s = NumericSentry(SentryConfig(scan_every=2), vocab_size=64)
        got = self._run(model, sentry=s,
                        fault=("serving.logits",
                               dict(mode="nan", always=True)))
        assert s.trips >= 1
        assert s.last_trip["kind"] == "logit_nonfinite"
        # the first scan after arming caught it: trip step within
        # scan_every of the first scanned step
        assert got == want          # harvest poisoning never touches
        #                             the sampled stream itself

    def test_kv_corrupt_site_diverges_stream(self, model):
        """Sanity for drill (a): the `serving.kv_page` mutation lands
        in LIVE pages, so the greedy stream actually diverges — damage
        in free pages would drill nothing."""
        want = self._run(model)
        got = self._run(model, fault=("serving.kv_page",
                                      dict(always=True)))
        assert got != want

    def test_attach_sentry_rebuilds_decode_program(self, model):
        eng = ContinuousBatchingEngine(model, max_batch_size=2,
                                       max_seq_len=64, page_size=4)
        eng.add_request([5, 4, 3], 4)
        eng.run()
        assert eng._decode_jit is not None and not eng._decode_logits
        eng.attach_sentry(NumericSentry(SentryConfig(scan_every=1),
                                        vocab_size=64))
        assert eng._decode_jit is None      # rebuild pending
        eng.add_request([5, 4, 3], 4)
        eng.run()
        assert eng._decode_logits           # sentry variant built


# ---------------------------------------------------------------------
class TestCanaryFleet:
    def test_sentry_requires_canary(self, model):
        with pytest.raises(ValueError, match="requires canary"):
            _fleet(model, n=1, sentry=SentryConfig())

    def test_scheduled_canary_passes_on_healthy_fleet(self, model):
        want = _reference(model, JOBS[:2], n=2)
        router, clock = _sentried(model, n=2, interval=5.0)
        ids = [router.submit(p, m) for p, m in JOBS[:2]]
        clock.advance(6.0)          # schedule due on both replicas
        out = router.run()
        for _ in range(30):         # let in-flight canaries conclude
            if all(h.canary is None and h.canary_runs >= 1
                   for h in router.replicas):
                break
            router.step()
        assert [out[i] for i in ids] == want
        assert router.num_failovers == 0
        assert telemetry.value("pdt_sentry_canary_runs_total",
                               result="pass") >= 2
        assert all(h.state == ReplicaState.HEALTHY
                   for h in router.replicas)
        info = router.fleet_info()
        assert info["sentry"]["quarantines"] == 0
        assert info["sentry"]["canary_runs"] >= 2

    def test_false_positive_restores_with_zero_failovers(self, model):
        """Drill (d): ONE spurious sentry trip (a single NaN-poisoned
        logit harvest; the stream itself is untouched) marks the
        replica SUSPECT — its terminals PARK — then the immediate
        canary passes with a clean window and everything delivers
        exactly as an unfaulted fleet would: zero failovers, zero
        quarantines, zero tokens dropped."""
        want = _reference(model, JOBS[:2], n=2)
        # a LONG canary (24-token golden stream): the suspect
        # replica's in-flight request must reach its terminal while
        # the probe is still running, so the parking path is exercised
        router, clock = _fleet(
            model, n=2, sentry=SentryConfig(scan_every=1),
            canary=CanaryConfig(interval=1000.0, max_new_tokens=16),
            restart_backoff_base=3.0, restart_backoff_max=3.0)
        ids = [router.submit(p, m) for p, m in JOBS[:2]]
        with FaultInjector(seed=0) as fi:
            fi.arm_corrupt("serving.logits", mode="nan", nth=1,
                           tag="0")
            router.step()           # replica 0's first scan poisoned
            router.step()
        assert router.replicas[0].state == ReplicaState.SUSPECT
        # drive to completion: replica 0's request must finish PARKED
        # (not finalized) until the canary clears it
        parked_seen = False
        for _ in range(60):
            router.step()
            parked_seen = parked_seen or bool(router.replicas[0].parked)
            if all(router.requests[i].done for i in ids):
                break
        assert parked_seen, "suspect replica's terminal never parked"
        assert all(router.requests[i].done for i in ids)
        assert [router.requests[i].tokens for i in ids] == want
        assert router.replicas[0].state == ReplicaState.HEALTHY
        assert router.num_failovers == 0
        assert router.num_quarantines == 0
        assert router.num_tainted_tokens == 0
        assert telemetry.value("pdt_sentry_canary_runs_total",
                               result="pass") >= 1

    def test_nan_storm_quarantines_via_dirty_canaries(self, model):
        """Drill (b), fleet half: a PERSISTENT NaN poisoning of one
        replica's logit harvest trips the scan every step. The canary's
        tokens still match golden (harvest poisoning never alters the
        stream) but its windows are dirty — after max_suspect_rounds
        dirty passes the replica quarantines as persistently sick, and
        its streams re-serve bit-identically."""
        want = _reference(model, JOBS, n=4)
        router, clock = _sentried(model, n=4, scan_every=1,
                                  interval=1000.0)
        ids = [router.submit(p, m) for p, m in JOBS]
        with FaultInjector(seed=0) as fi:
            fi.arm_corrupt("serving.logits", mode="nan", always=True,
                           tag="1")
            for _ in range(80):
                router.step()
                if router.replicas[1].state \
                        == ReplicaState.QUARANTINED:
                    break
            assert router.replicas[1].state \
                == ReplicaState.QUARANTINED
            clock.advance(4.0)
            out = router.run()
        assert [out[i] for i in ids] == want
        ev = [e for e in telemetry.events()
              if e["name"] == "replica.quarantine"]
        assert ev and ev[0]["attrs"]["reason"] == "sentry_dirty"
        assert telemetry.value("pdt_sentry_canary_runs_total",
                               result="dirty") >= 2
        # the trips that EXPLAIN the quarantine survive the engine
        # discard it caused (retired-counter fold, like prefix/spec)
        info = router.fleet_info()
        assert info["sentry"]["sentry_trips"] >= 1
        assert router.replicas[1].sentry_trips() >= 1

    def test_journaled_quarantine_rewinds_tainted_tokens(
            self, model, tmp_path):
        """Journal x gray-failure composition: the quarantine journals
        a durable `rewind` record truncating the tainted stream, so a
        router SIGKILL between the quarantine and the request's
        terminal recovers the VERIFIED prefix only — tainted tokens
        cannot resurface through replay, and outputs stay
        bit-identical to an uncorrupted fleet."""
        from paddle_tpu.serving import RouterJournal
        want = _reference(model, JOBS, n=4)
        clock = FakeClock()
        jr_kw = dict(
            n=4, clock=clock,
            sentry=SentryConfig(scan_every=4),
            canary=CanaryConfig(interval=5.0, max_new_tokens=6),
            restart_backoff_base=3.0, restart_backoff_max=3.0)
        router, _ = _fleet(model,
                           journal=RouterJournal(tmp_path / "wal",
                                                 fsync="off"),
                           **jr_kw)
        ids = [router.submit(p, m) for p, m in JOBS]
        with FaultInjector(seed=0) as fi:
            fi.arm_corrupt("serving.kv_page", always=True, tag="1")
            router.step()
            router.step()
            clock.advance(6.0)
            for _ in range(60):
                router.step()
                if router.replicas[1].state \
                        == ReplicaState.QUARANTINED:
                    break
            assert router.replicas[1].state \
                == ReplicaState.QUARANTINED
        assert router.num_tainted_tokens >= 1
        assert telemetry.value("pdt_journal_records_total",
                               kind="rewind") >= 1
        live_left = [i for i in ids if not router.requests[i].done]
        assert live_left, "kill window missed: all requests terminal"
        del router                       # SIGKILL-shaped, PRE-terminal
        recovered = ServingRouter.recover(
            RouterJournal(tmp_path / "wal", fsync="off"),
            lambda i: ContinuousBatchingEngine(
                model, clock=clock, max_batch_size=3, max_seq_len=64,
                page_size=4),
            num_replicas=4, policy="round_robin", clock=clock,
            sleep=clock.advance, page_size=4,
            sentry=SentryConfig(scan_every=4),
            canary=CanaryConfig(interval=5.0, max_new_tokens=6),
            restart_backoff_base=3.0, restart_backoff_max=3.0)
        out = recovered.run()
        assert [out[i] for i in ids] == want

    def test_probation_gates_every_restart(self, model):
        """Satellite: EVERY restart re-enters through canary-gated
        PROBATION — no real traffic, and no restart-budget reset,
        until a canary passes. Closes the PR-4 hole where an idle
        restarted replica sat HEALTHY unproven."""
        router, clock = _sentried(model, n=2, interval=1000.0)
        a = router.submit(*JOBS[0])             # round robin: r0
        router.step()
        router.kill_replica(0)                  # plain fail-stop kill
        clock.advance(4.0)                      # past the backoff
        router.step()                           # restart lands...
        h = router.replicas[0]
        assert h.state == ReplicaState.PROBATION
        assert h.restart_attempt == 1           # budget NOT reset yet
        assert not h.can_accept()
        # new submits must avoid the probation replica entirely
        b = router.submit(*JOBS[1])
        assert router.requests[b].replica != 0
        for _ in range(40):                     # canary must clear it
            router.step()
            if h.state == ReplicaState.HEALTHY:
                break
        assert h.state == ReplicaState.HEALTHY
        assert h.restart_attempt == 0           # reset by the PASS
        assert h.last_canary_pass is not None
        out = router.run()
        assert len(out[a]) == JOBS[0][1] and len(out[b]) == JOBS[1][1]
        ev = [e for e in telemetry.events()
              if e["name"] == "router.replica_state"]
        assert any(e["attrs"]["state"] == "probation" for e in ev)
        assert any(e["attrs"]["reason"] == "probation_pass"
                   for e in ev)


# ---------------------------------------------------------------------
class TestGrayFailureTp2:
    """Acceptance drill (a), tp=2 variant: the corrupt replica is a
    whole GSPMD submesh — the sick-chip surface TP multiplies — and
    quarantine + re-serve still land bit-identical to an uncorrupted
    tp=1 fleet (8-simulated-device harness)."""

    def test_kv_bitflip_tp2_quarantine_bit_identical(self):
        paddle.seed(0)
        m = LlamaForCausalLM(LlamaConfig.tiny())
        m.eval()
        rng = np.random.default_rng(7)
        jobs = [rng.integers(1, 512, int(rng.integers(5, 10))).tolist()
                for _ in range(4)]
        clock = FakeClock()

        def tp_factory(i, sm):
            return ContinuousBatchingEngine(
                m, max_batch_size=2, max_seq_len=96, submesh=sm,
                clock=clock)

        ref = ServingRouter(
            lambda i: ContinuousBatchingEngine(m, max_batch_size=2,
                                               max_seq_len=96),
            num_replicas=4, policy="round_robin")
        rids = [ref.submit(p, 8) for p in jobs]
        want = ref.run()

        router = ServingRouter(
            tp_factory, num_replicas=4, policy="round_robin", tp=2,
            clock=clock, sleep=clock.advance,
            sentry=SentryConfig(scan_every=4),
            canary=CanaryConfig(interval=4.0, max_new_tokens=5),
            restart_backoff_base=3.0, restart_backoff_max=3.0)
        ids = [router.submit(p, 8) for p in jobs]
        with FaultInjector(seed=0) as fi:
            fi.arm_corrupt("serving.kv_page", always=True, tag="1")
            for _ in range(2):
                router.step()
            clock.advance(5.0)      # canary schedule fires
            for _ in range(60):
                router.step()
                if router.replicas[1].state \
                        == ReplicaState.QUARANTINED:
                    break
            assert router.replicas[1].state \
                == ReplicaState.QUARANTINED
            clock.advance(4.0)
            out = router.run()
        assert [out[i] for i in ids] == [want[r] for r in rids]
        assert router.num_quarantines >= 1
        assert telemetry.value("pdt_sentry_quarantines_total",
                               replica="1") >= 1


# ---------------------------------------------------------------------
class TestTransferStageDeadline:
    """Satellite: per-stage migration deadlines on the injectable
    clock — a stage that returns late is counted
    (`stage="timeout"`), the migration defers, and the SLOW endpoint
    is degraded; both engines stay consistent."""

    def _pair(self, model):
        e = dict(max_batch_size=2, max_seq_len=64, page_size=4)
        return (ContinuousBatchingEngine(model, **e),
                ContinuousBatchingEngine(model, **e))

    def test_slow_serialize_times_out_consistent(self, model):
        src, dst = self._pair(model)
        rid = src.add_request([5, 4, 3, 2, 6, 7], 6)
        src.step()
        clock = FakeClock()
        real_export = src.export_pages

        def slow_export(r):
            clock.advance(2.0)      # the stage "hangs" for 2 virtual s
            return real_export(r)

        src.export_pages = slow_export
        base = telemetry.value("pdt_transfer_failures_total",
                               stage="timeout")
        with pytest.raises(TransferStageTimeout) as ei:
            transfer.migrate_request(src, dst, rid, clock=clock,
                                     stage_deadline=1.0)
        assert ei.value.stage == "serialize"
        assert telemetry.value("pdt_transfer_failures_total",
                               stage="timeout") - base == 1
        # nothing moved: source still owns the request, target empty
        assert src.get_request(rid) is not None
        assert dst.lifecycle_info()["running"] == 0
        src.check_invariants()
        dst.check_invariants()

    def test_slow_install_backs_out_of_target(self, model):
        src, dst = self._pair(model)
        rid = src.add_request([5, 4, 3, 2, 6, 7], 6)
        src.step()
        clock = FakeClock()
        real_import = dst.import_pages

        def slow_import(payload, deadline=None):
            clock.advance(2.0)
            return real_import(payload, deadline=deadline)

        dst.import_pages = slow_import
        with pytest.raises(TransferStageTimeout) as ei:
            transfer.migrate_request(src, dst, rid, clock=clock,
                                     stage_deadline=1.0)
        assert ei.value.stage == "install"
        # the late install was BACKED OUT: source stays authoritative,
        # exactly one live copy (the transactional contract)
        assert src.get_request(rid) is not None
        assert dst.lifecycle_info()["running"] == 0
        src.check_invariants()
        dst.check_invariants()

    def test_router_defers_and_degrades_slow_endpoint(self, model):
        clock = FakeClock()
        ekw = dict(max_batch_size=2, max_seq_len=64, page_size=4)
        slow_engines = []

        def factory(i):
            eng = ContinuousBatchingEngine(model, clock=clock, **ekw)
            if i == 0:              # the prefill replica is slow
                real = eng.export_pages

                def slow_export(r):
                    clock.advance(2.0)
                    return real(r)
                eng.export_pages = slow_export
                slow_engines.append(eng)
            return eng

        router = ServingRouter(
            factory, roles="prefill:1,decode:1", policy="round_robin",
            page_size=4, clock=clock, sleep=clock.advance,
            degraded_after=1, dead_after=99,
            transfer_stage_deadline=1.0)
        rid = router.submit([5, 4, 3, 2, 6, 7], 8)
        out = router.run()
        assert len(out[rid]) == 8          # served despite deferrals
        assert router.num_migrations == 0  # every attempt deferred
        # the slow endpoint was charged a health failure per overrun
        # (successful steps between attempts recover it — the ladder
        # works; the EVENT stream proves the charge landed)
        ev = [e for e in telemetry.events()
              if e["name"] == "router.replica_state"
              and e["attrs"]["replica"] == 0
              and e["attrs"]["state"] == "degraded"]
        assert ev and "TransferStageTimeout" in ev[0]["attrs"]["reason"]
        assert telemetry.value("pdt_transfer_failures_total",
                               stage="timeout") >= 1

    def test_corrupt_payload_refused_by_sha256(self, model):
        """Drill (c), plane half: a corrupt-mode `transfer.payload`
        fault damages serialized KV bytes AFTER the manifest was
        attached — the PR-13 verify gate refuses the install at
        stage="verify", both engines consistent, and the sentry
        counters stay untouched (payload-verify and sentry are
        separate ledgers)."""
        src, dst = self._pair(model)
        rid = src.add_request([5, 4, 3, 2, 6, 7], 6)
        src.step()
        base_v = telemetry.value("pdt_transfer_failures_total",
                                 stage="verify")
        base_t = telemetry.value("pdt_sentry_trips_total",
                                 kind="token_oov")
        from paddle_tpu.models.serving import PayloadCorruption
        with FaultInjector(seed=0) as fi:
            fi.arm_corrupt("transfer.payload", nth=1)
            with pytest.raises(PayloadCorruption):
                transfer.migrate_request(src, dst, rid)
            assert fi.trips("transfer.payload") == 1
        assert telemetry.value("pdt_transfer_failures_total",
                               stage="verify") - base_v == 1
        assert telemetry.value("pdt_sentry_trips_total",
                               kind="token_oov") == base_t
        assert src.get_request(rid) is not None   # source untouched
        assert dst.lifecycle_info()["running"] == 0
        src.check_invariants()
        dst.check_invariants()

    def test_payload_corrupt_honors_source_tag(self, model):
        """A tag-pinned `transfer.payload` rule damages ONE replica's
        outbound payloads only — the serialize path threads the
        source engine's fault_tag through, same as the engine sites
        (a mismatched tag neither fires nor consumes visits, so a
        mis-pinned drill reads 0 trips instead of passing vacuously)."""
        src, dst = self._pair(model)
        src.fault_tag = "1"
        rid = src.add_request([5, 4, 3, 2, 6, 7], 6)
        src.step()
        with FaultInjector(seed=0) as fi:
            fi.arm_corrupt("transfer.payload", always=True, tag="0")
            req, _ = transfer.migrate_request(src, dst, rid)
            assert fi.calls("transfer.payload") == 0   # wrong replica
        assert req is not None                         # clean install
        dst.evict_request(req.rid)
        rid2 = dst.add_request([9, 1, 2], 6)
        dst.fault_tag = "0"
        dst.step()
        from paddle_tpu.models.serving import PayloadCorruption
        with FaultInjector(seed=0) as fi:
            fi.arm_corrupt("transfer.payload", always=True, tag="0")
            with pytest.raises(PayloadCorruption):
                transfer.migrate_request(dst, src, rid2)
            assert fi.trips("transfer.payload") == 1   # right replica
