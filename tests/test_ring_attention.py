"""Context-parallel attention tests on the 8-virtual-device CPU mesh.
≙ reference PaddleNLP ring_flash_attention tests + «test/collective/» tier
(SURVEY.md §4): every parallelism test must pass on the fake 8-device mesh."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # heavy tier (VERDICT r3 #9)

import jax
import jax.numpy as jnp

import paddle_tpu.distributed as dist
from paddle_tpu.distributed.ring_attention import (
    ring_attention_values, ulysses_attention_values)

rng = np.random.default_rng(11)


def _sdpa_ref(q, k, v, causal=False):
    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if h != hk:
        k = np.repeat(k, h // hk, axis=2)
        v = np.repeat(v, h // hk, axis=2)
    qb = q.transpose(0, 2, 1, 3).astype(np.float64)
    kb = k.transpose(0, 2, 1, 3).astype(np.float64)
    vb = v.transpose(0, 2, 1, 3).astype(np.float64)
    logits = qb @ kb.transpose(0, 1, 3, 2) / np.sqrt(d)
    if causal:
        mask = np.arange(sq)[:, None] + (sk - sq) >= np.arange(sk)[None, :]
        logits = np.where(mask, logits, -1e30)
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    return (w @ vb).transpose(0, 2, 1, 3).astype(np.float32)


@pytest.fixture(scope="module")
def sep_mesh():
    return dist.create_mesh(sep=4)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, sep_mesh, causal):
        q = rng.normal(size=(2, 64, 4, 16)).astype(np.float32)
        k = rng.normal(size=(2, 64, 4, 16)).astype(np.float32)
        v = rng.normal(size=(2, 64, 4, 16)).astype(np.float32)
        out = ring_attention_values(jnp.asarray(q), jnp.asarray(k),
                                    jnp.asarray(v), sep_mesh, "sep",
                                    causal=causal)
        np.testing.assert_allclose(np.asarray(out),
                                   _sdpa_ref(q, k, v, causal),
                                   rtol=2e-4, atol=2e-4)

    def test_gqa(self, sep_mesh):
        q = rng.normal(size=(1, 32, 4, 16)).astype(np.float32)
        k = rng.normal(size=(1, 32, 2, 16)).astype(np.float32)
        v = rng.normal(size=(1, 32, 2, 16)).astype(np.float32)
        out = ring_attention_values(jnp.asarray(q), jnp.asarray(k),
                                    jnp.asarray(v), sep_mesh, "sep",
                                    causal=True)
        np.testing.assert_allclose(np.asarray(out),
                                   _sdpa_ref(q, k, v, True),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grad_matches_reference(self, sep_mesh, causal):
        q = rng.normal(size=(1, 32, 2, 16)).astype(np.float32)
        k = rng.normal(size=(1, 32, 2, 16)).astype(np.float32)
        v = rng.normal(size=(1, 32, 2, 16)).astype(np.float32)

        def ring_loss(q_, k_, v_):
            return jnp.sum(ring_attention_values(
                q_, k_, v_, sep_mesh, "sep", causal=causal) ** 2)

        def ref_loss(q_, k_, v_):
            d = q_.shape[-1]
            qb = jnp.swapaxes(q_, 1, 2)
            kb = jnp.swapaxes(k_, 1, 2)
            vb = jnp.swapaxes(v_, 1, 2)
            logits = (qb @ jnp.swapaxes(kb, -1, -2)) / np.sqrt(d)
            if causal:
                s = logits.shape[-1]
                logits = jnp.where(jnp.tril(jnp.ones((s, s), bool)),
                                   logits, -1e30)
            w = jax.nn.softmax(logits, -1)
            return jnp.sum(jnp.swapaxes(w @ vb, 1, 2) ** 2)

        args = (jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        g_ring = jax.grad(ring_loss, (0, 1, 2))(*args)
        g_ref = jax.grad(ref_loss, (0, 1, 2))(*args)
        for gr, gx in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gx),
                                       rtol=5e-3, atol=1e-4)

    def test_jit_and_sharded_inputs(self, sep_mesh):
        """Ring attention under jit with sequence-sharded device inputs."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        q = rng.normal(size=(1, 64, 2, 16)).astype(np.float32)
        sh = NamedSharding(sep_mesh.jax_mesh, P(None, "sep", None, None))
        qd = jax.device_put(jnp.asarray(q), sh)

        @jax.jit
        def f(q_):
            return ring_attention_values(q_, q_, q_, sep_mesh, "sep",
                                         causal=True)

        out = f(qd)
        np.testing.assert_allclose(np.asarray(out),
                                   _sdpa_ref(q, q, q, True),
                                   rtol=2e-4, atol=2e-4)

    def test_no_axis_falls_back(self):
        q = rng.normal(size=(1, 32, 2, 16)).astype(np.float32)
        out = ring_attention_values(jnp.asarray(q), jnp.asarray(q),
                                    jnp.asarray(q), None, "sep", True)
        np.testing.assert_allclose(np.asarray(out), _sdpa_ref(q, q, q, True),
                                   rtol=2e-4, atol=2e-4)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, sep_mesh, causal):
        q = rng.normal(size=(2, 64, 4, 16)).astype(np.float32)
        k = rng.normal(size=(2, 64, 4, 16)).astype(np.float32)
        v = rng.normal(size=(2, 64, 4, 16)).astype(np.float32)
        out = ulysses_attention_values(jnp.asarray(q), jnp.asarray(k),
                                       jnp.asarray(v), sep_mesh, "sep",
                                       causal=causal)
        np.testing.assert_allclose(np.asarray(out),
                                   _sdpa_ref(q, k, v, causal),
                                   rtol=2e-4, atol=2e-4)

    def test_gqa_expand(self, sep_mesh):
        # hk=2 < sep=4: kv heads expand to full h before the alltoall
        q = rng.normal(size=(1, 32, 4, 16)).astype(np.float32)
        k = rng.normal(size=(1, 32, 2, 16)).astype(np.float32)
        v = rng.normal(size=(1, 32, 2, 16)).astype(np.float32)
        out = ulysses_attention_values(jnp.asarray(q), jnp.asarray(k),
                                       jnp.asarray(v), sep_mesh, "sep",
                                       causal=True)
        np.testing.assert_allclose(np.asarray(out),
                                   _sdpa_ref(q, k, v, True),
                                   rtol=2e-4, atol=2e-4)

    def test_indivisible_heads_raises(self, sep_mesh):
        q = rng.normal(size=(1, 32, 3, 16)).astype(np.float32)
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention_values(jnp.asarray(q), jnp.asarray(q),
                                     jnp.asarray(q), sep_mesh, "sep")

    def test_grad(self, sep_mesh):
        q = rng.normal(size=(1, 32, 4, 16)).astype(np.float32)

        def loss(q_):
            return jnp.sum(ulysses_attention_values(
                q_, q_, q_, sep_mesh, "sep", causal=True) ** 2)

        g = jax.grad(loss)(jnp.asarray(q))
        assert np.isfinite(np.asarray(g)).all()
        # compare against the single-device path's grad
        from paddle_tpu.ops.flash_attention import flash_attention_values

        def ref_loss(q_):
            return jnp.sum(flash_attention_values(q_, q_, q_,
                                                  causal=True) ** 2)

        g_ref = jax.grad(ref_loss)(jnp.asarray(q))
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=5e-3, atol=1e-4)


class TestZigzagRing:
    """Load-balanced causal ring (VERDICT r2 weak 4): every rank does ~2
    full sub-block attentions per tick instead of rank r idling n-r-1
    ticks."""

    def _qkv(self, b=2, s=64, h=4, hk=4, d=16, seed=0):
        r = np.random.default_rng(seed)
        return (r.standard_normal((b, s, h, d)).astype(np.float32),
                r.standard_normal((b, s, hk, d)).astype(np.float32),
                r.standard_normal((b, s, hk, d)).astype(np.float32))

    def test_matches_reference(self, sep_mesh):
        from paddle_tpu.nn.functional.attention import _sdpa_xla
        q, k, v = self._qkv()
        with dist.use_mesh(sep_mesh):
            out = ring_attention_values(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                sep_mesh, causal=True, balance="zigzag")
        ref = _sdpa_xla(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                        causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_gqa(self, sep_mesh):
        from paddle_tpu.nn.functional.attention import _sdpa_xla
        q, k, v = self._qkv(h=4, hk=2, seed=1)
        with dist.use_mesh(sep_mesh):
            out = ring_attention_values(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                sep_mesh, causal=True, balance="zigzag")
        ref = _sdpa_xla(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                        causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_grad_matches_contiguous_ring(self, sep_mesh):
        q, k, v = self._qkv(seed=2)

        def loss_zig(qq, kk, vv):
            with dist.use_mesh(sep_mesh):
                o = ring_attention_values(qq, kk, vv, sep_mesh,
                                          causal=True, balance="zigzag")
            return jnp.sum(o.astype(jnp.float32) ** 2)

        def loss_ring(qq, kk, vv):
            with dist.use_mesh(sep_mesh):
                o = ring_attention_values(qq, kk, vv, sep_mesh,
                                          causal=True)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        g1 = jax.grad(loss_zig, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        g2 = jax.grad(loss_ring, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-4, atol=3e-4)

    def test_noncausal_ignores_balance(self, sep_mesh):
        q, k, v = self._qkv(seed=3)
        with dist.use_mesh(sep_mesh):
            a = ring_attention_values(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), sep_mesh,
                                      causal=False, balance="zigzag")
            b = ring_attention_values(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), sep_mesh,
                                      causal=False)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
