"""OpTest harness: NumPy-reference forward check + numeric-vs-autograd
gradient check. ≙ reference «test/legacy_test/op_test.py» `OpTest` base class
(SURVEY.md §4): per op — run kernel, compare vs NumPy reference; gradient
check vs finite differences; dtype tolerance ladders."""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor

TOL = {
    "float32": dict(rtol=1e-4, atol=1e-5),
    "float64": dict(rtol=1e-7, atol=1e-9),
    "float16": dict(rtol=1e-2, atol=1e-3),
    "bfloat16": dict(rtol=2e-2, atol=2e-2),
}


def _np(x):
    if isinstance(x, Tensor):
        return np.asarray(x.numpy(), dtype=np.float64) \
            if x.dtype.name == "bfloat16" else x.numpy()
    return np.asarray(x)


def check_forward(op_fn, np_fn, inputs, dtype="float32", rtol=None, atol=None,
                  **op_kwargs):
    """Run op_fn on Tensors and np_fn on numpy arrays; assert allclose."""
    tol = dict(TOL[dtype])
    if rtol is not None:
        tol["rtol"] = rtol
    if atol is not None:
        tol["atol"] = atol
    t_in = [paddle.to_tensor(np.asarray(i, dtype)) for i in inputs]
    out = op_fn(*t_in, **op_kwargs)
    ref = np_fn(*[np.asarray(i, np.float64 if dtype != "float32"
                             else np.float32) for i in inputs])
    outs = out if isinstance(out, (tuple, list)) else [out]
    refs = ref if isinstance(ref, (tuple, list)) else [ref]
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(_np(o).astype(np.float64),
                                   np.asarray(r, np.float64), **tol)
    return outs


def numeric_grad(fn, inputs, idx, delta=1e-3):
    """Central finite differences of sum(fn(inputs)) w.r.t. inputs[idx]."""
    inputs = [np.asarray(i, np.float64) for i in inputs]
    x = inputs[idx]
    grad = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        orig = x[i]
        x[i] = orig + delta
        hi = float(np.sum(fn(*inputs)))
        x[i] = orig - delta
        lo = float(np.sum(fn(*inputs)))
        x[i] = orig
        grad[i] = (hi - lo) / (2 * delta)
        it.iternext()
    return grad


def check_grad(op_fn, np_fn, inputs, grad_inputs=None, dtype="float32",
               rtol=5e-3, atol=5e-4, delta=1e-3, **op_kwargs):
    """Autograd (tape) gradient vs numeric finite-difference gradient."""
    t_in = [paddle.to_tensor(np.asarray(i, dtype), stop_gradient=False)
            for i in inputs]
    out = op_fn(*t_in, **op_kwargs)
    loss = out.sum() if out.ndim > 0 else out
    loss.backward()
    check_idx = grad_inputs if grad_inputs is not None else range(len(inputs))
    for idx in check_idx:
        assert t_in[idx].grad is not None, f"no grad for input {idx}"
        got = _np(t_in[idx].grad).astype(np.float64)
        want = numeric_grad(np_fn, inputs, idx, delta)
        np.testing.assert_allclose(got, want, rtol=rtol, atol=atol,
                                   err_msg=f"grad mismatch for input {idx}")
