"""Elastic-infra robustness (fast tier): launch restart backoff with a
fake clock, HeartbeatMembership corrupt-beat tolerance, stale-beat
eviction, and scale_up/scale_down classification edge cases (including
a beat exactly at the timeout boundary)."""
import os
import random
import types

import pytest

from paddle_tpu.distributed.launch import launch, restart_backoff
from paddle_tpu.distributed.fleet.elastic import HeartbeatMembership


class TestRestartBackoff:
    def test_exponential_envelope_jitter_and_cap(self):
        rng = random.Random(0)
        delays = [restart_backoff(a, 1.0, 60.0, rng)
                  for a in range(1, 9)]
        for k, d in enumerate(delays, start=1):
            # +/-50% multiplicative jitter around the exponential,
            # clamped to the cap as a HARD ceiling
            assert min(0.5 * 2.0 ** (k - 1), 60.0) <= d <= 60.0, (k, d)
            assert d <= 1.5 * 2.0 ** (k - 1)
        assert delays[7] == 60.0          # 0.5 * 2^7 = 64 > cap: pinned
        # deterministic given the rng
        rng2 = random.Random(0)
        assert delays == [restart_backoff(a, 1.0, 60.0, rng2)
                          for a in range(1, 9)]
        assert restart_backoff(3, 0.0, 60.0, rng) == 0.0   # disabled

    def test_launch_backs_off_and_caps_restarts(self, tmp_path):
        script = tmp_path / "fail.py"
        script.write_text("import sys; sys.exit(7)\n")
        args = types.SimpleNamespace(
            master=None, nnodes=1, rank=0, job_id="bo", log_dir=None,
            elastic_level=1, max_restart=3, restart_backoff=2.0,
            restart_backoff_max=5.0, script=str(script), script_args=[])
        slept = []
        rc = launch(args, sleep=slept.append, rng=random.Random(42))
        assert rc == 7                    # max_restarts cap: rc propagated
        assert len(slept) == 3            # one backoff per restart
        rng = random.Random(42)
        assert slept == [restart_backoff(a, 2.0, 5.0, rng)
                         for a in (1, 2, 3)]
        assert all(d <= 5.0 for d in slept)    # hard cap

    def test_launch_args_without_backoff_fields_still_work(self, tmp_path):
        # duck-typed args objects predating the backoff knobs
        script = tmp_path / "ok.py"
        script.write_text("print('ok')\n")
        args = types.SimpleNamespace(
            master=None, nnodes=1, rank=0, job_id="t", log_dir=None,
            elastic_level=0, max_restart=1, script=str(script),
            script_args=[])
        assert launch(args, sleep=lambda _: None) == 0


class TestHeartbeatRobustness:
    def test_corrupt_beat_is_stale_not_fatal(self, tmp_path):
        hb = HeartbeatMembership(str(tmp_path), rank=0, timeout=5.0)
        hb.heartbeat()
        assert hb.alive() == {0}
        # a non-atomic writer observed mid-write: truncated / garbage
        with open(hb._beat_path(1), "w"):
            pass                              # empty file, fresh mtime
        with open(hb._beat_path(2), "w") as f:
            f.write("not-a-timestamp\x00")
        assert hb.alive() == {0}              # corrupt = stale, no raise
        assert hb.poll()["alive"] == {0}
        # the corrupt worker recovers on its next good beat
        HeartbeatMembership(str(tmp_path), rank=1).heartbeat()
        assert hb.alive() == {0, 1}

    def test_exactly_at_timeout_beat_is_alive(self, tmp_path):
        t0 = 1000.0
        hb = HeartbeatMembership(str(tmp_path), rank=0, timeout=5.0,
                                 clock=lambda: t0 + 5.0)
        hb.heartbeat()
        os.utime(hb._beat_path(0), (t0, t0))  # beat exactly timeout old
        assert hb.alive() == {0}              # boundary is inclusive
        hb._clock = lambda: t0 + 5.0 + 1e-3
        assert hb.alive() == set()            # a hair past: dead

    def test_stale_eviction_and_scale_classification(self, tmp_path):
        clk = {"t": 1000.0}
        watch = HeartbeatMembership(str(tmp_path), timeout=5.0,
                                    clock=lambda: clk["t"])

        def beat(rank):
            HeartbeatMembership(str(tmp_path), rank=rank).heartbeat()
            path = os.path.join(str(tmp_path), f"worker_{rank}.hb")
            os.utime(path, (clk["t"], clk["t"]))

        beat(0)
        beat(1)
        d = watch.poll()
        assert d["alive"] == {0, 1}
        assert d["event"] is None             # first sighting: no event
        beat(2)                               # join -> scale_up
        d = watch.poll()
        assert d["joined"] == {2} and d["event"] == "scale_up"
        # worker 0 goes silent past the timeout -> evicted, scale_down
        os.utime(os.path.join(str(tmp_path), "worker_0.hb"),
                 (clk["t"] - 6.0, clk["t"] - 6.0))
        d = watch.poll()
        assert d["dead"] == {0} and d["event"] == "scale_down"
        assert d["alive"] == {1, 2}
        # death + join in the same poll: scale_down wins (relaunch must
        # not be masked by a simultaneous join)
        os.utime(os.path.join(str(tmp_path), "worker_1.hb"),
                 (clk["t"] - 6.0, clk["t"] - 6.0))
        beat(3)
        d = watch.poll()
        assert d["dead"] == {1} and d["joined"] == {3}
        assert d["event"] == "scale_down"
        # everyone silent
        clk["t"] += 100.0
        d = watch.poll()
        assert d["alive"] == set() and d["event"] == "scale_down"


# ---------------------------------------------------------------------
# Durable checkpoints (docs/checkpointing.md): atomic commit protocol,
# integrity manifests, corruption-tolerant resume, GC safety. Fast
# tier: tiny Linear state dicts keep orbax writes cheap.

import json

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu import nn
from paddle_tpu.distributed.checkpoint import (MANIFEST_NAME, parse_done,
                                               save_state_dict,
                                               verify_checkpoint)
from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                  complete_checkpoints,
                                                  latest_checkpoint)
from paddle_tpu.utils.faults import FaultError, FaultInjector


def _net(seed=0):
    paddle.seed(seed)
    return nn.Linear(4, 4)


def _em(tmp_path, **kw):
    kw.setdefault("save_interval_steps", 1)
    kw.setdefault("sleep", lambda _: None)   # no real backoff waits
    return ElasticManager(str(tmp_path), **kw)


from paddle_tpu.utils.faults import \
    flip_ocdbt_shards as _flip_shards  # noqa: E402


class TestAtomicCommitProtocol:
    def test_save_commits_manifest_done_and_verifies(self, tmp_path):
        net = _net()
        _em(tmp_path).save(0, net)
        step = tmp_path / "step_0"
        # no droppings from the commit protocol
        assert sorted(p.name for p in tmp_path.iterdir()) == ["step_0"]
        manifest = json.loads((step / MANIFEST_NAME).read_text())
        assert manifest["format"] == "paddle-tpu-ckpt-manifest"
        assert manifest["step"] == 0
        assert manifest["wall_time"] > 0
        assert manifest["mesh"]["device_count"] >= 1
        arrays = manifest["groups"]["model"]
        assert set(arrays) == {"weight", "bias"}
        w = arrays["weight"]
        assert w["shape"] == [4, 4] and w["dtype"] == "float32"
        assert w["nbytes"] == 64
        assert w["checksum"].startswith("sha256:")
        # .done is a JSON payload committed atomically after the rename
        done = parse_done(str(step / ".done"))
        assert done["step"] == 0 and done["time"] > 0
        res = verify_checkpoint(str(step), rehash=True)
        assert res.ok and res.arrays_checked == 2 and res.step == 0

    def test_latest_checkpoint_rejects_unparsable_done(self, tmp_path):
        good = tmp_path / "step_1"
        good.mkdir()
        (good / ".done").write_text('{"step": 1, "time": 5.0}')
        for name, payload in (("step_2", ""),              # zero-byte
                              ("step_3", "not-a-time\x00"),
                              ("step_4", "[1, 2]"),        # wrong type
                              ("step_5", "true")):  # bool is NOT a time
            d = tmp_path / name
            d.mkdir()
            (d / ".done").write_text(payload)
        assert latest_checkpoint(str(tmp_path)).endswith("step_1")
        # legacy bare-float payloads stay accepted
        (tmp_path / "step_2" / ".done").write_text("1234.5")
        assert latest_checkpoint(str(tmp_path)).endswith("step_2")
        assert [s for s, _ in complete_checkpoints(str(tmp_path))] == \
            [2, 1]

    @pytest.mark.chaos
    def test_finalize_fault_retries_in_place(self, tmp_path):
        sleeps = []
        em = _em(tmp_path, save_retries=3, sleep=sleeps.append,
                 rng=random.Random(7))
        net = _net()
        with FaultInjector() as fi:
            fi.arm("checkpoint.finalize", nth=1)
            em.save(0, net)                     # succeeds on attempt 2
        assert fi.trips("checkpoint.finalize") == 1
        assert len(sleeps) == 1
        from paddle_tpu.distributed.launch import restart_backoff
        assert sleeps == [restart_backoff(1, em.retry_backoff,
                                          em.retry_backoff_max,
                                          random.Random(7))]
        assert telemetry.value("pdt_checkpoint_save_retries_total") == 1
        assert verify_checkpoint(str(tmp_path / "step_0"),
                                 rehash=True).ok
        assert not (tmp_path / "step_0.tmp").exists()

    @pytest.mark.chaos
    def test_write_fault_exhausts_retries_leaves_torn_tmp(self, tmp_path):
        em = _em(tmp_path, save_retries=2)
        net = _net()
        with FaultInjector() as fi:
            fi.arm("checkpoint.write", always=True)
            with pytest.raises(FaultError):
                em.save(0, net)
        assert fi.trips("checkpoint.write") == 2    # both attempts
        assert telemetry.value("pdt_checkpoint_save_retries_total") == 1
        # the kill-mid-save disk state: torn tmp, never a step_0
        assert not (tmp_path / "step_0").exists()
        assert (tmp_path / "step_0.tmp").exists()
        assert not (tmp_path / "step_0.tmp" / MANIFEST_NAME).exists()
        assert latest_checkpoint(str(tmp_path)) is None
        em.save(0, net)                   # fault cleared: tmp reclaimed
        assert verify_checkpoint(str(tmp_path / "step_0"),
                                 rehash=True).ok
        assert not (tmp_path / "step_0.tmp").exists()

    def test_resave_same_step_replaces_without_droppings(self, tmp_path):
        net = _net()
        em = _em(tmp_path)
        em.save(0, net)
        w0 = net.weight.numpy().copy()
        net.weight._value = paddle.to_tensor(w0 + 1.0)._value
        em.save(0, net)          # resumed job repeating the interval
        # fresh data won wholesale; the moved-aside old dir is gone
        assert sorted(p.name for p in tmp_path.iterdir()) == ["step_0"]
        assert verify_checkpoint(str(tmp_path / "step_0"),
                                 rehash=True).ok
        net2 = _net(seed=9)
        assert em.resume(net2) == 1
        np.testing.assert_array_equal(net2.weight.numpy(), w0 + 1.0)

    def test_crashed_resave_recovers_moved_aside_checkpoint(
            self, tmp_path):
        """Kill inside _commit's re-save window: the only complete copy
        of the step sits under step_N.old and a torn uncommitted
        step_N squats on the name. resume() must rename the complete
        copy back instead of returning 0 (and letting stale GC destroy
        the data)."""
        net = _net()
        em = _em(tmp_path)
        em.save(0, net)
        # the crash state: complete copy moved aside, fresh dir renamed
        # into place but killed before its .done landed
        os.replace(tmp_path / "step_0", tmp_path / "step_0.old")
        (tmp_path / "step_0").mkdir()
        (tmp_path / "step_0" / MANIFEST_NAME).write_text("{}")  # torn
        assert latest_checkpoint(str(tmp_path)) is None
        net2 = _net(seed=9)
        assert em.resume(net2) == 1
        np.testing.assert_array_equal(net2.weight.numpy(),
                                      net.weight.numpy())
        assert not (tmp_path / "step_0.old").exists()
        assert verify_checkpoint(str(tmp_path / "step_0"),
                                 rehash=True).ok

    def test_failed_recovery_rename_degrades_not_crashes(
            self, tmp_path, monkeypatch):
        """If the squatter's deletion partially fails and the recovery
        rename errors, resume() must skip recovery for this restart
        (keeping the .old for a later attempt) — not crash-loop."""
        net = _net()
        em = _em(tmp_path)
        em.save(0, net)
        os.replace(tmp_path / "step_0", tmp_path / "step_0.old")
        (tmp_path / "step_0").mkdir()
        (tmp_path / "step_0" / MANIFEST_NAME).write_text("{}")
        real_replace = os.replace

        def flaky_replace(src, dst, **kw):
            if str(src).endswith("step_0.old"):
                raise OSError("Directory not empty")
            return real_replace(src, dst, **kw)

        monkeypatch.setattr(os, "replace", flaky_replace)
        assert em.resume(_net(seed=9)) == 0      # degraded, no raise
        assert (tmp_path / "step_0.old").exists()  # kept for later
        monkeypatch.undo()
        assert em.resume(_net(seed=9)) == 1      # next restart recovers

    def test_verify_cli(self, tmp_path):
        from paddle_tpu.distributed.checkpoint.__main__ import main
        em = _em(tmp_path)
        net = _net()
        em.save(0, net)
        em.save(1, net)
        assert main(["verify", str(tmp_path), "--rehash"]) == 0
        assert main(["verify", str(tmp_path / "step_1")]) == 0
        _flip_shards(str(tmp_path / "step_1"))
        assert main(["verify", str(tmp_path), "--rehash"]) == 1
        assert main(["verify", str(tmp_path / "empty-root")]) == 1


class TestLightVerifyTier:
    """verify_on_resume='light' / verify_checkpoint(rehash=False): the
    documented cheap tier for multi-GB checkpoints — reads checkpoint
    metadata only, never array bytes."""

    def test_light_checks_structure_against_metadata(self, tmp_path):
        em = _em(tmp_path)
        em.save(0, _net())
        step = str(tmp_path / "step_0")
        res = verify_checkpoint(step)            # rehash=False
        assert res.ok and res.arrays_checked == 2 and not res.rehashed
        # manifest drift is caught from metadata alone: shape, dtype,
        # and the shape*itemsize-derived nbytes
        mpath = tmp_path / "step_0" / MANIFEST_NAME
        m = json.loads(mpath.read_text())
        m["groups"]["model"]["weight"]["shape"] = [999]
        m["groups"]["model"]["weight"]["nbytes"] = 1
        m["groups"]["model"]["bias"]["dtype"] = "int8"
        mpath.write_text(json.dumps(m))
        res = verify_checkpoint(step)
        assert not res.ok
        assert any("shape" in e for e in res.errors)
        assert any("nbytes" in e for e in res.errors)
        assert any("dtype" in e for e in res.errors)

    def test_checksums_are_the_rehash_tiers_job(self, tmp_path):
        # the tier boundary: light never reads array bytes, so a wrong
        # stored checksum (standing in for silent content damage the
        # storage layer can't see) sails through; rehash catches it
        em = _em(tmp_path)
        em.save(0, _net())
        step = str(tmp_path / "step_0")
        mpath = tmp_path / "step_0" / MANIFEST_NAME
        m = json.loads(mpath.read_text())
        m["groups"]["model"]["weight"]["checksum"] = "sha256:" + "0" * 64
        mpath.write_text(json.dumps(m))
        assert verify_checkpoint(step).ok
        res = verify_checkpoint(step, rehash=True)
        assert not res.ok and any("checksum" in e for e in res.errors)

    @pytest.mark.chaos
    def test_light_resume_still_quarantines_torn_storage(self, tmp_path):
        # flipped OCDBT files damage the format's own structure nodes,
        # so even the metadata-only read reports the group unrestorable:
        # light mode still quarantines at the verify stage
        net = _net()
        em = _em(tmp_path, verify_on_resume="light")
        em.save(0, net)
        em.save(1, net)
        _flip_shards(str(tmp_path / "step_1"))
        assert em.resume(_net(seed=9)) == 1
        assert (tmp_path / "step_1.corrupt").exists()
        assert telemetry.value("pdt_checkpoint_corrupt_total",
                               reason="verify") == 1


@pytest.mark.chaos
class TestCorruptionTolerantResume:
    def test_flipped_shard_quarantined_falls_back(self, tmp_path):
        net = _net()
        em = _em(tmp_path)
        em.save(0, net)
        w0 = net.weight.numpy().copy()
        net.weight._value = paddle.to_tensor(w0 + 1.0)._value
        em.save(1, net)
        _flip_shards(str(tmp_path / "step_1"))
        net2 = _net(seed=9)
        assert em.resume(net2) == 1          # fell back to step_0 + 1
        np.testing.assert_array_equal(net2.weight.numpy(), w0)
        assert (tmp_path / "step_1.corrupt").exists()
        assert not (tmp_path / "step_1").exists()
        assert telemetry.value("pdt_checkpoint_corrupt_total",
                               reason="verify") == 1
        assert telemetry.value(
            "pdt_checkpoint_resume_fallbacks_total") == 1
        assert telemetry.value(
            "pdt_checkpoint_resume_fallback_depth") == 1

    def test_load_failure_quarantines_when_verify_off(self, tmp_path):
        net = _net()
        em = _em(tmp_path, verify_on_resume="off")
        em.save(0, net)
        em.save(1, net)
        _flip_shards(str(tmp_path / "step_1"))
        assert em.resume(_net(seed=9)) == 1
        assert (tmp_path / "step_1.corrupt").exists()
        assert telemetry.value("pdt_checkpoint_corrupt_total",
                               reason="load") == 1

    def test_truncated_manifest_quarantined(self, tmp_path):
        net = _net()
        em = _em(tmp_path)
        em.save(0, net)
        em.save(1, net)
        m = tmp_path / "step_1" / MANIFEST_NAME
        m.write_text(m.read_text()[: m.stat().st_size // 2])
        assert em.resume(_net(seed=9)) == 1
        assert (tmp_path / "step_1.corrupt").exists()

    def test_legacy_checkpoint_without_manifest_loads(self, tmp_path):
        # pre-manifest format: data + bare-float .done, no MANIFEST.json
        net = _net()
        save_state_dict(net.state_dict(), str(tmp_path / "step_0" /
                                              "model"))
        (tmp_path / "step_0" / ".done").write_text("1234.5")
        net2 = _net(seed=9)
        assert _em(tmp_path).resume(net2) == 1       # no quarantine
        np.testing.assert_array_equal(net2.weight.numpy(),
                                      net.weight.numpy())
        assert (tmp_path / "step_0").exists()
        assert telemetry.value("pdt_checkpoint_corrupt_total",
                               reason="verify") == 0

    def test_partial_load_then_exhaustion_raises_not_fresh(
            self, tmp_path):
        """A quarantined attempt that already assigned the model's
        weights must not fall through to a silent "train fresh" return:
        the model is tainted, so resume() raises instead of returning
        0 (verify_on_resume='off' is the only path that can get that
        far with a half-bad checkpoint)."""
        import paddle_tpu.optimizer as opt_mod
        net = _net()
        opt = opt_mod.Adam(learning_rate=1e-2,
                           parameters=net.parameters())
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        loss = (net(x) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        em = _em(tmp_path, verify_on_resume="off")
        em.save(0, net, opt)
        _flip_shards(tmp_path / "step_0", group="opt")  # model intact
        with pytest.raises(RuntimeError, match="tainted|reinitialize"):
            em.resume(_net(seed=9), opt)
        assert (tmp_path / "step_0.corrupt").exists()

    def test_all_corrupt_resumes_fresh(self, tmp_path):
        net = _net()
        em = _em(tmp_path)
        em.save(0, net)
        em.save(1, net)
        _flip_shards(str(tmp_path / "step_0"))
        _flip_shards(str(tmp_path / "step_1"))
        assert em.resume(_net(seed=9)) == 0
        assert telemetry.value(
            "pdt_checkpoint_resume_fallback_depth") == 2
        assert {p.name for p in tmp_path.iterdir()} == \
            {"step_0.corrupt", "step_1.corrupt"}


class TestDurableGc:
    def test_incomplete_dirs_do_not_count_toward_keep_last(self,
                                                           tmp_path):
        em = _em(tmp_path, keep_last=2)
        net = _net()
        em.save(0, net)
        em.save(1, net)
        # newer-numbered but UNcommitted droppings a crash left behind
        (tmp_path / "step_5").mkdir()
        (tmp_path / "step_6.tmp").mkdir()
        (tmp_path / "step_2.corrupt").mkdir()
        em.save(2, net)
        names = {p.name for p in tmp_path.iterdir()}
        # keep_last=2 COMPLETE checkpoints survive; the fresh (within
        # stale_grace) incomplete dirs are not swept, and never consumed
        # a keep_last slot
        assert names == {"step_1", "step_2", "step_5", "step_6.tmp",
                         "step_2.corrupt"}

    def test_newest_complete_never_deleted(self, tmp_path):
        em = _em(tmp_path, keep_last=0)      # pathological config
        net = _net()
        em.save(0, net)
        em.save(1, net)
        assert latest_checkpoint(str(tmp_path)).endswith("step_1")

    def test_stale_incomplete_dirs_swept_fresh_kept(self, tmp_path):
        clk = {"t": 1_000_000.0}
        em = _em(tmp_path, stale_grace=100.0, clock=lambda: clk["t"])
        net = _net()
        em.save(0, net)
        for name in ("step_5", "step_6.tmp", "step_3.corrupt"):
            (tmp_path / name).mkdir()
            os.utime(tmp_path / name, (clk["t"] - 200, clk["t"] - 200))
        (tmp_path / "step_7.tmp").mkdir()    # a LIVE writer's tmp
        os.utime(tmp_path / "step_7.tmp", (clk["t"] - 5, clk["t"] - 5))
        # complete checkpoints are NEVER age-swept
        os.utime(tmp_path / "step_0", (clk["t"] - 900, clk["t"] - 900))
        em._gc()
        assert {p.name for p in tmp_path.iterdir()} == \
            {"step_0", "step_7.tmp"}

    def test_gc_removes_done_before_rmtree(self, tmp_path, monkeypatch):
        """rmtree is not atomic: a kill mid-delete must not leave a
        half-deleted dir that discovery still trusts. Deletion drops the
        commit marker first, so a deletion that stops right there
        already untrusts the directory."""
        em = _em(tmp_path, keep_last=2)
        net = _net()
        em.save(0, net)
        em.save(1, net)
        em.keep_last = 1                     # step_0 now expired
        monkeypatch.setattr(
            "paddle_tpu.distributed.fleet.elastic.shutil.rmtree",
            lambda *a, **k: None)            # the kill: no file removed
        em._gc()
        assert (tmp_path / "step_0").exists()          # half-deleted...
        assert not (tmp_path / "step_0" / ".done").exists()
        # ...but no longer a complete checkpoint
        assert [s for s, _ in complete_checkpoints(str(tmp_path))] == [1]

    def test_quarantined_dir_survives_stale_gc(self, tmp_path):
        """os.replace keeps old data mtimes: without the quarantine-time
        touch, a checkpoint older than stale_grace would be quarantined
        by resume() and destroyed by the very next save's _gc — losing
        the post-mortem evidence quarantine exists to preserve."""
        clk = {"t": 1_000_000.0}
        em = _em(tmp_path, stale_grace=100.0, clock=lambda: clk["t"])
        net = _net()
        em.save(0, net)
        em.save(1, net)
        _flip_shards(str(tmp_path / "step_1"))
        # the data was written long "ago": age every mtime past grace
        for root, dirs, files in os.walk(tmp_path):
            for name in dirs + files:
                os.utime(os.path.join(root, name),
                         (clk["t"] - 900, clk["t"] - 900))
        assert em.resume(_net(seed=9)) == 1
        assert (tmp_path / "step_1.corrupt").exists()
        em._gc()                   # what the very next save would run
        assert (tmp_path / "step_1.corrupt").exists()  # evidence kept
        clk["t"] += 200            # ...until it genuinely goes stale
        em._gc()
        assert not (tmp_path / "step_1.corrupt").exists()

    @pytest.mark.chaos
    def test_gc_fault_does_not_lose_the_committed_save(self, tmp_path):
        em = _em(tmp_path)
        net = _net()
        with FaultInjector() as fi:
            fi.arm("elastic.gc", always=True)
            em.save(0, net)                  # must NOT raise
        assert fi.trips("elastic.gc") == 1
        assert latest_checkpoint(str(tmp_path)).endswith("step_0")
        assert verify_checkpoint(str(tmp_path / "step_0")).ok


class TestWaitForPeersClock:
    def test_deadline_runs_on_injected_clock(self, tmp_path):
        clk = {"t": 1000.0}
        hb = HeartbeatMembership(str(tmp_path), timeout=5.0,
                                 interval=1.0, clock=lambda: clk["t"])
        sleeps = []

        def fake_sleep(dt):
            sleeps.append(dt)
            clk["t"] += dt                   # time passes only here

        with pytest.raises(TimeoutError):
            hb.wait_for_peers(1, timeout=10.0, sleep=fake_sleep)
        # deterministic: exactly timeout / (interval/2) sleeps, and the
        # fake clock is all that advanced — no wall-clock dependence
        assert sleeps == [0.5] * 20
        assert clk["t"] == 1010.0

    def test_returns_once_peers_register(self, tmp_path):
        clk = {"t": 1000.0}
        hb = HeartbeatMembership(str(tmp_path), timeout=5.0,
                                 interval=1.0, clock=lambda: clk["t"])

        def beat_then_advance(dt):
            clk["t"] += dt
            if len(os.listdir(str(tmp_path))) == 0:
                HeartbeatMembership(str(tmp_path), rank=3).heartbeat()
                path = os.path.join(str(tmp_path), "worker_3.hb")
                os.utime(path, (clk["t"], clk["t"]))

        assert hb.wait_for_peers(1, timeout=10.0,
                                 sleep=beat_then_advance) == {3}

    def test_zero_timeout_still_checks_once(self, tmp_path):
        hb = HeartbeatMembership(str(tmp_path), timeout=5.0,
                                 clock=lambda: 1000.0)
        HeartbeatMembership(str(tmp_path), rank=0).heartbeat()
        os.utime(os.path.join(str(tmp_path), "worker_0.hb"),
                 (1000.0, 1000.0))
        assert hb.wait_for_peers(1, timeout=0.0,
                                 sleep=lambda _: None) == {0}
