"""Elastic-infra robustness (fast tier): launch restart backoff with a
fake clock, HeartbeatMembership corrupt-beat tolerance, stale-beat
eviction, and scale_up/scale_down classification edge cases (including
a beat exactly at the timeout boundary)."""
import os
import random
import types

import pytest

from paddle_tpu.distributed.launch import launch, restart_backoff
from paddle_tpu.distributed.fleet.elastic import HeartbeatMembership


class TestRestartBackoff:
    def test_exponential_envelope_jitter_and_cap(self):
        rng = random.Random(0)
        delays = [restart_backoff(a, 1.0, 60.0, rng)
                  for a in range(1, 9)]
        for k, d in enumerate(delays, start=1):
            # +/-50% multiplicative jitter around the exponential,
            # clamped to the cap as a HARD ceiling
            assert min(0.5 * 2.0 ** (k - 1), 60.0) <= d <= 60.0, (k, d)
            assert d <= 1.5 * 2.0 ** (k - 1)
        assert delays[7] == 60.0          # 0.5 * 2^7 = 64 > cap: pinned
        # deterministic given the rng
        rng2 = random.Random(0)
        assert delays == [restart_backoff(a, 1.0, 60.0, rng2)
                          for a in range(1, 9)]
        assert restart_backoff(3, 0.0, 60.0, rng) == 0.0   # disabled

    def test_launch_backs_off_and_caps_restarts(self, tmp_path):
        script = tmp_path / "fail.py"
        script.write_text("import sys; sys.exit(7)\n")
        args = types.SimpleNamespace(
            master=None, nnodes=1, rank=0, job_id="bo", log_dir=None,
            elastic_level=1, max_restart=3, restart_backoff=2.0,
            restart_backoff_max=5.0, script=str(script), script_args=[])
        slept = []
        rc = launch(args, sleep=slept.append, rng=random.Random(42))
        assert rc == 7                    # max_restarts cap: rc propagated
        assert len(slept) == 3            # one backoff per restart
        rng = random.Random(42)
        assert slept == [restart_backoff(a, 2.0, 5.0, rng)
                         for a in (1, 2, 3)]
        assert all(d <= 5.0 for d in slept)    # hard cap

    def test_launch_args_without_backoff_fields_still_work(self, tmp_path):
        # duck-typed args objects predating the backoff knobs
        script = tmp_path / "ok.py"
        script.write_text("print('ok')\n")
        args = types.SimpleNamespace(
            master=None, nnodes=1, rank=0, job_id="t", log_dir=None,
            elastic_level=0, max_restart=1, script=str(script),
            script_args=[])
        assert launch(args, sleep=lambda _: None) == 0


class TestHeartbeatRobustness:
    def test_corrupt_beat_is_stale_not_fatal(self, tmp_path):
        hb = HeartbeatMembership(str(tmp_path), rank=0, timeout=5.0)
        hb.heartbeat()
        assert hb.alive() == {0}
        # a non-atomic writer observed mid-write: truncated / garbage
        with open(hb._beat_path(1), "w"):
            pass                              # empty file, fresh mtime
        with open(hb._beat_path(2), "w") as f:
            f.write("not-a-timestamp\x00")
        assert hb.alive() == {0}              # corrupt = stale, no raise
        assert hb.poll()["alive"] == {0}
        # the corrupt worker recovers on its next good beat
        HeartbeatMembership(str(tmp_path), rank=1).heartbeat()
        assert hb.alive() == {0, 1}

    def test_exactly_at_timeout_beat_is_alive(self, tmp_path):
        t0 = 1000.0
        hb = HeartbeatMembership(str(tmp_path), rank=0, timeout=5.0,
                                 clock=lambda: t0 + 5.0)
        hb.heartbeat()
        os.utime(hb._beat_path(0), (t0, t0))  # beat exactly timeout old
        assert hb.alive() == {0}              # boundary is inclusive
        hb._clock = lambda: t0 + 5.0 + 1e-3
        assert hb.alive() == set()            # a hair past: dead

    def test_stale_eviction_and_scale_classification(self, tmp_path):
        clk = {"t": 1000.0}
        watch = HeartbeatMembership(str(tmp_path), timeout=5.0,
                                    clock=lambda: clk["t"])

        def beat(rank):
            HeartbeatMembership(str(tmp_path), rank=rank).heartbeat()
            path = os.path.join(str(tmp_path), f"worker_{rank}.hb")
            os.utime(path, (clk["t"], clk["t"]))

        beat(0)
        beat(1)
        d = watch.poll()
        assert d["alive"] == {0, 1}
        assert d["event"] is None             # first sighting: no event
        beat(2)                               # join -> scale_up
        d = watch.poll()
        assert d["joined"] == {2} and d["event"] == "scale_up"
        # worker 0 goes silent past the timeout -> evicted, scale_down
        os.utime(os.path.join(str(tmp_path), "worker_0.hb"),
                 (clk["t"] - 6.0, clk["t"] - 6.0))
        d = watch.poll()
        assert d["dead"] == {0} and d["event"] == "scale_down"
        assert d["alive"] == {1, 2}
        # death + join in the same poll: scale_down wins (relaunch must
        # not be masked by a simultaneous join)
        os.utime(os.path.join(str(tmp_path), "worker_1.hb"),
                 (clk["t"] - 6.0, clk["t"] - 6.0))
        beat(3)
        d = watch.poll()
        assert d["dead"] == {1} and d["joined"] == {3}
        assert d["event"] == "scale_down"
        # everyone silent
        clk["t"] += 100.0
        d = watch.poll()
        assert d["alive"] == set() and d["event"] == "scale_down"
