"""Observability PR 5 unit tests (fast tier, `telemetry` marker):
quantile math (reservoir path golden-checked against numpy.percentile,
le-bucket interpolation golden-checked by hand), rolling-window expiry,
SLO burn-rate grading (pass/warn/breach ladder), the one-clock trace
model + request-tree reconstruction + Chrome export schema, the
operator CLI round trips, the bench regression gate, and two
lint-style drift guards: fault sites documented in `utils/faults.py`
must equal the `fault_point()` call sites in the source, and the
metric catalog in docs/observability.md must equal the instruments
actually registered. conftest enables PDT_TELEMETRY=1 and zeroes the
registry/ring for every test in this file."""
import json
import math
import os
import re

import numpy as np
import pytest

import paddle_tpu.observability as telemetry
from paddle_tpu.observability import slo as slo_mod
from paddle_tpu.observability import trace as trace_mod
from paddle_tpu.observability.__main__ import main as cli_main
from paddle_tpu.observability.slo import (Reservoir, SloMonitor,
                                          SloObjective,
                                          fraction_over_threshold,
                                          objectives_from_spec,
                                          quantile_from_buckets)

pytestmark = pytest.mark.telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


# -- quantile math -----------------------------------------------------
class TestQuantileMath:
    def test_reservoir_quantile_matches_numpy_percentile(self):
        """Golden contract of the exact path: linear interpolation,
        bit-for-bit numpy.percentile."""
        rng = np.random.default_rng(0)
        vals = rng.uniform(0.0, 2.0, 37).tolist()
        r = Reservoir(window_s=1e9, clock=FakeClock())
        for v in vals:
            r.observe(v)
        for q in (0.0, 0.25, 0.5, 0.9, 0.95, 0.99, 1.0):
            want = float(np.percentile(vals, q * 100))
            assert r.quantile(q) == pytest.approx(want, abs=1e-12), q

    def test_bucket_interpolation_golden_values(self):
        buckets = {"0.1": 5, "1": 10, "+Inf": 10}
        assert quantile_from_buckets(buckets, 0.5) \
            == pytest.approx(0.1)           # rank 5 = first boundary
        assert quantile_from_buckets(buckets, 0.75) \
            == pytest.approx(0.55)          # halfway into (0.1, 1]
        assert quantile_from_buckets(buckets, 1.0) == pytest.approx(1.0)
        assert quantile_from_buckets(buckets, 0.25) \
            == pytest.approx(0.05)          # halfway into [0, 0.1]

    def test_quantile_in_inf_bucket_clamps_to_highest_finite(self):
        buckets = {"0.1": 5, "+Inf": 10}
        assert quantile_from_buckets(buckets, 0.9) == pytest.approx(0.1)

    def test_empty_and_invalid(self):
        assert quantile_from_buckets({}, 0.5) is None
        assert quantile_from_buckets({"+Inf": 0}, 0.5) is None
        with pytest.raises(ValueError):
            quantile_from_buckets({"+Inf": 1}, 1.5)
        assert Reservoir(clock=FakeClock()).quantile(0.5) is None

    def test_fraction_over_threshold_interpolates(self):
        buckets = {"0.1": 9, "1": 10, "+Inf": 10}
        # cumulative at 0.5 = 9 + (0.5-0.1)/0.9 -> over = (10-at)/10
        want = (10 - (9 + (0.5 - 0.1) / 0.9)) / 10
        assert fraction_over_threshold(buckets, 0.5) \
            == pytest.approx(want)
        assert fraction_over_threshold(buckets, 2.0) == 0.0
        assert fraction_over_threshold({}, 0.5) is None

    def test_unresolvable_threshold_counts_inf_mass_as_over(self):
        """A threshold beyond the highest finite boundary cannot be
        placed against the +Inf mass — that mass must count as OVER
        (conservative), never as a confident pass."""
        buckets = {"0.1": 9, "+Inf": 10}       # 1 sample is ">0.1s"
        assert fraction_over_threshold(buckets, 5.0) \
            == pytest.approx(0.1)
        # and through the monitor's histogram path: every sample in
        # +Inf with a threshold twice the top boundary -> breach
        h = telemetry.histogram("t_slo_inf_seconds", buckets=(0.1,))
        for _ in range(10):
            h.observe(300.0)
        mon = SloMonitor(
            [SloObjective("p95", "lat", "latency", 0.2, quantile=0.95,
                          metric="t_slo_inf_seconds")],
            clock=FakeClock())
        st = mon.evaluate()["p95"]
        assert st.source == "histogram" and st.state == "breach"
        assert st.burn_rate == pytest.approx(20.0)


class TestReservoirWindow:
    def test_window_expiry_drops_old_samples(self):
        clk = FakeClock()
        r = Reservoir(window_s=10.0, clock=clk)
        for v in (1.0, 2.0, 3.0):
            r.observe(v)
        clk.advance(5.0)
        r.observe(100.0)
        assert sorted(r.values()) == [1.0, 2.0, 3.0, 100.0]
        clk.advance(6.0)                     # t=11: the t=0 batch ages out
        assert r.values() == [100.0]
        assert r.quantile(0.5) == 100.0
        clk.advance(10.0)                    # t=21: everything gone
        assert r.quantile(0.5) is None

    def test_sample_cap_bounds_memory(self):
        r = Reservoir(window_s=1e9, max_samples=3, clock=FakeClock())
        for v in range(10):
            r.observe(float(v))
        assert r.values() == [7.0, 8.0, 9.0]


# -- SLO grading -------------------------------------------------------
def _latency_obj(**kw):
    kw.setdefault("window_s", 60.0)
    return SloObjective("lat_p90", "lat", "latency", 0.1,
                        quantile=0.9, **kw)


class TestSloMonitor:
    def test_burn_rate_ladder_pass_warn_breach(self):
        clk = FakeClock()
        for n_over, want_state, want_burn in ((0, "pass", 0.0),
                                              (1, "warn", 0.5),
                                              (4, "breach", 2.0)):
            mon = SloMonitor([_latency_obj()], clock=clk, warn_burn=0.5)
            for i in range(20):
                mon.observe("lat", 0.5 if i < n_over else 0.01)
            st = mon.evaluate()["lat_p90"]
            # budget = 1 - 0.9 = 10% of samples allowed past 0.1s
            assert st.state == want_state, (n_over, st)
            assert st.burn_rate == pytest.approx(want_burn)
            assert st.source == "reservoir" and st.samples == 20
            assert st.value == pytest.approx(float(np.percentile(
                [0.5 if i < n_over else 0.01 for i in range(20)], 90)))

    def test_window_expiry_clears_breach(self):
        clk = FakeClock()
        mon = SloMonitor([_latency_obj()], clock=clk)
        for _ in range(10):
            mon.observe("lat", 1.0)
        assert mon.evaluate()["lat_p90"].state == "breach"
        clk.advance(61.0)
        mon.observe("lat", 0.01)
        st = mon.evaluate()["lat_p90"]
        assert st.state == "pass" and st.samples == 1

    def test_ratio_objectives_error_rate_and_availability(self):
        clk = FakeClock()
        mon = SloMonitor(
            [SloObjective("err", "outcome", "error_rate", 0.2),
             SloObjective("avail", "outcome", "availability", 0.95)],
            clock=clk, warn_burn=0.5)
        for i in range(10):
            mon.observe_outcome("outcome", ok=i != 0)
        rep = mon.evaluate()
        # 1 bad / 10: error budget 0.2 -> burn 0.5 (warn);
        # availability budget 1-0.95 -> burn 2.0 (breach)
        assert rep["err"].state == "warn"
        assert rep["err"].value == pytest.approx(0.1)
        assert rep["err"].burn_rate == pytest.approx(0.5)
        assert rep["avail"].state == "breach"
        assert rep["avail"].value == pytest.approx(0.9)
        assert rep["avail"].burn_rate == pytest.approx(2.0)

    def test_no_data_grades_pass(self):
        mon = SloMonitor([_latency_obj()], clock=FakeClock())
        st = mon.evaluate()["lat_p90"]
        assert st.state == "pass" and st.value is None \
            and st.source == "none"

    def test_histogram_fallback_when_reservoir_empty(self):
        h = telemetry.histogram("t_slo_fb_seconds", buckets=(0.1, 1.0))
        for _ in range(9):
            h.observe(0.05)
        h.observe(0.9)
        mon = SloMonitor(
            [SloObjective("p90", "lat", "latency", 0.5, quantile=0.9,
                          metric="t_slo_fb_seconds")],
            clock=FakeClock(), warn_burn=0.5)
        st = mon.evaluate()["p90"]
        assert st.source == "histogram" and st.samples == 10
        # ~0.056 of mass interpolates past 0.5 on a 0.1 budget -> warn
        assert st.state == "warn"
        assert st.burn_rate == pytest.approx(0.5556, abs=1e-3)

    def test_gauges_exported(self):
        mon = SloMonitor([_latency_obj()], clock=FakeClock())
        for _ in range(10):
            mon.observe("lat", 1.0)
        mon.evaluate()
        assert telemetry.value("pdt_slo_state", objective="lat_p90") \
            == slo_mod.STATE_CODE["breach"]
        assert telemetry.value("pdt_slo_burn_rate",
                               objective="lat_p90") \
            == pytest.approx(10.0)
        assert telemetry.value("pdt_slo_value",
                               objective="lat_p90") == 1.0

    def test_zero_budget_burn_exports_finite_cap(self):
        """An infinite burn (zero-tolerance objective violated) must
        export as a huge FINITE gauge value: a `burn > 1` alert rule
        has to fire, and the text exposition must stay renderable."""
        mon = SloMonitor(
            [SloObjective("zero_err", "outcome", "error_rate", 0.0)],
            clock=FakeClock())
        mon.observe_outcome("outcome", ok=False)
        st = mon.evaluate()["zero_err"]
        assert st.state == "breach" and math.isinf(st.burn_rate)
        assert telemetry.value("pdt_slo_burn_rate",
                               objective="zero_err") == 1e9
        assert "inf" in mon.report()
        telemetry.parse_prometheus(telemetry.to_prometheus())

    def test_replica_state_grades_each_slice(self):
        clk = FakeClock()
        mon = SloMonitor([_latency_obj()], clock=clk)
        for _ in range(5):
            mon.observe("lat", 0.01, replica="0")
            mon.observe("lat", 1.0, replica="1")
        assert mon.replica_state("0") == "pass"
        assert mon.replica_state("1") == "breach"
        assert mon.replica_state("2") is None    # never contributed

    def test_spec_round_trip_and_validation(self, tmp_path):
        spec = [{"name": "a", "signal": "ttft", "kind": "latency",
                 "threshold": 0.25, "quantile": 0.5, "window_s": 30.0}]
        objs = objectives_from_spec(spec)
        assert objs[0] == SloObjective("a", "ttft", "latency", 0.25,
                                       quantile=0.5, window_s=30.0)
        p = tmp_path / "spec.json"
        p.write_text(json.dumps(spec))
        assert objectives_from_spec(str(p)) == objs
        with pytest.raises(ValueError, match="unknown keys"):
            objectives_from_spec([{"name": "x", "signal": "s",
                                   "kind": "latency", "threshold": 1,
                                   "typo": 1}])
        with pytest.raises(ValueError, match="unknown kind"):
            SloObjective("x", "s", "meanness", 1.0)
        with pytest.raises(ValueError, match="already added"):
            SloMonitor([_latency_obj(), _latency_obj()])


# -- trace model -------------------------------------------------------
class TestTraceClock:
    def test_events_share_one_monotonic_base(self):
        """The satellite fix: a child event's timestamps must be
        directly comparable with its parent span's — same clock, same
        base — so durations reconstruct from the JSONL alone."""
        with telemetry.span("outer"):
            telemetry.event("mid")
        mid, outer = telemetry.events()
        assert outer["name"] == "outer" and mid["name"] == "mid"
        assert outer["ts_mono"] <= mid["ts_mono"] \
            <= outer["ts_mono"] + outer["dur_s"]
        # wall ts is DERIVED from ts_mono via one base pair: deltas agree
        assert (mid["ts"] - outer["ts"]) == pytest.approx(
            mid["ts_mono"] - outer["ts_mono"], abs=1e-6)

    def test_file_sink_carries_ts_mono(self, tmp_path):
        sink = tmp_path / "t.jsonl"
        telemetry.set_trace_file(str(sink))
        try:
            with telemetry.span("sunk"):
                pass
        finally:
            telemetry.set_trace_file(None)
        line = json.loads(sink.read_text().strip())
        assert {"ts", "ts_mono", "dur_s", "seq", "parent",
                "trace"} <= set(line)


class TestRequestTrace:
    def test_request_id_attr_joins_trace_automatically(self):
        tid = telemetry.start_trace("r-1", name="router.submit")
        with telemetry.span("router.dispatch", request_id="r-1",
                            replica=0):
            pass
        with telemetry.span("router.replica_step", replica=0):
            with telemetry.span("serving.prefill", request_id="r-1"):
                pass
        telemetry.event("router.terminal", request_id="r-1",
                        status="finished")
        evs = {e["name"]: e for e in telemetry.events()}
        root = evs["router.submit"]
        assert root["trace"] == tid and root["parent"] is None
        assert evs["router.dispatch"]["trace"] == tid
        assert evs["router.dispatch"]["parent"] == root["seq"]
        # nested under the replica span: LOCAL parent wins, trace joins
        prefill = evs["serving.prefill"]
        assert prefill["trace"] == tid
        assert prefill["parent"] == evs["router.replica_step"]["seq"]
        assert evs["router.replica_step"]["trace"] is None
        assert evs["router.terminal"]["parent"] == root["seq"]

    def test_attach_and_end_trace(self):
        telemetry.start_trace("r-2")
        with telemetry.trace_attach("r-2"):
            with telemetry.span("inner"):
                pass
        telemetry.end_trace("r-2")
        with telemetry.span("after", request_id="r-2"):
            pass
        evs = {e["name"]: e for e in telemetry.events()}
        assert evs["inner"]["trace"] == telemetry.events()[0]["trace"]
        assert evs["inner"]["parent"] == telemetry.events()[0]["seq"]
        assert evs["after"]["trace"] is None   # carrier dropped

    def test_tree_reconstruction_with_decode_fanin(self):
        telemetry.start_trace("r-3", name="router.submit")
        with telemetry.span("router.dispatch", request_id="r-3",
                            replica=1):
            pass
        with telemetry.span("serving.decode_step", slots=2,
                            rids=["r-3", "r-other"]):
            pass
        tree = telemetry.request_tree("r-3")
        assert tree["event"]["name"] == "router.submit"
        kids = [c["event"]["name"] for c in tree["children"]]
        assert kids == ["router.dispatch", "serving.decode_step"]
        assert telemetry.request_tree("nobody") is None
        text = trace_mod.format_tree(tree)
        assert "router.submit" in text and "replica=1" in text

    def test_retried_submit_reconstructs_the_newest_trace(self):
        """A refused submit leaves its root event behind; the retry
        that actually served must win request_tree reconstruction."""
        telemetry.start_trace("r-4", name="router.submit")  # refused
        telemetry.end_trace("r-4")
        tid = telemetry.start_trace("r-4", name="router.submit")
        with telemetry.span("router.dispatch", request_id="r-4",
                            replica=0):
            pass
        tree = telemetry.request_tree("r-4")
        assert tree["event"]["trace"] == tid
        assert [c["event"]["name"] for c in tree["children"]] \
            == ["router.dispatch"]

    def test_disabled_mode_true_noop(self, monkeypatch):
        monkeypatch.setenv("PDT_TELEMETRY", "0")
        assert telemetry.start_trace("r-x") is None
        with telemetry.trace_attach("r-x"):
            with telemetry.span("s", request_id="r-x"):
                telemetry.event("e", request_id="r-x")
        assert telemetry.events() == []
        assert telemetry.trace_of("r-x") is None


class TestChromeExport:
    def _validate(self, doc):
        assert set(doc) == {"traceEvents", "displayTimeUnit"}
        for e in doc["traceEvents"]:
            assert isinstance(e["name"], str)
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
            assert e["ph"] in ("X", "i", "M"), e
            if e["ph"] == "M":
                assert e["name"] in ("process_name", "thread_name")
                assert isinstance(e["args"]["name"], str)
            else:
                assert isinstance(e["ts"], float) and e["ts"] >= 0.0
            if e["ph"] == "X":
                assert isinstance(e["dur"], float) and e["dur"] >= 0.0
            if e["ph"] == "i":
                assert e["s"] in ("t", "p", "g")
        json.dumps(doc)                      # must be JSON-serializable

    def test_schema_pid_replica_tid_request(self, tmp_path):
        telemetry.start_trace("req-a", name="router.submit")
        with telemetry.span("router.dispatch", request_id="req-a",
                            replica=2):
            pass
        with telemetry.span("router.replica_step", replica=2):
            with telemetry.span("serving.prefill", request_id="req-a"):
                pass
        with telemetry.span("serving.decode_step", slots=2,
                            rids=["req-a", "req-b"]):
            pass
        out = tmp_path / "chrome.json"
        doc = telemetry.export_chrome_trace(path=str(out))
        self._validate(doc)
        assert json.loads(out.read_text()) == doc
        procs = {e["args"]["name"]: e["pid"] for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"}
        threads = {e["args"]["name"] for e in doc["traceEvents"]
                   if e["ph"] == "M" and e["name"] == "thread_name"}
        assert "replica 2" in procs
        assert {"req-a", "req-b"} <= threads
        # pid=replica INHERITS down the span tree: the engine prefill
        # has no replica attr but sits under the replica_step span
        prefill = [e for e in doc["traceEvents"]
                   if e["name"] == "serving.prefill"]
        assert prefill and prefill[0]["pid"] == procs["replica 2"]
        # the batched decode step fans out into BOTH request rows
        decode = [e for e in doc["traceEvents"]
                  if e["name"] == "serving.decode_step"]
        assert len(decode) == 2
        assert {d["tid"] for d in decode} == {
            e["tid"] for e in doc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"
            and e["args"]["name"] in ("req-a", "req-b")
            and e["pid"] == decode[0]["pid"]}


# -- operator CLI ------------------------------------------------------
class TestCLI:
    def _populate(self):
        telemetry.counter("t_cli_total", "", ("k",)).inc(2, k="x")
        telemetry.gauge("t_cli_depth").set(3)
        telemetry.histogram("t_cli_seconds",
                            buckets=(0.5, 2.5)).observe(0.25)
        return telemetry.snapshot()

    def test_snapshot_json_prom_round_trip(self, tmp_path):
        snap = self._populate()
        src = tmp_path / "snap.json"
        telemetry.write_json(str(src))
        prom = tmp_path / "snap.prom"
        assert cli_main(["snapshot", "--from", str(src),
                         "--out", str(prom)]) == 0
        parsed = telemetry.parse_prometheus(prom.read_text())
        want = {k: snap[k] for k in ("counters", "gauges",
                                     "histograms")}
        assert parsed == want
        # and back: prom text -> JSON snapshot
        back = tmp_path / "back.json"
        assert cli_main(["snapshot", "--from", str(prom), "--format",
                         "json", "--out", str(back)]) == 0
        got = json.loads(back.read_text())
        assert {k: got[k] for k in want} == want

    def _slo_snap(self, breach: bool):
        ttft = {"count": 10, "sum": 1.0,
                "buckets": ({"0.1": 9, "1": 10, "+Inf": 10} if breach
                            else {"0.1": 10, "1": 10, "+Inf": 10})}
        term = {'status="finished"': 9.0, 'status="failed"': 1.0} \
            if breach else {'status="finished"': 10.0}
        return {"counters":
                {"pdt_serving_requests_terminal_total": term},
                "gauges": {},
                "histograms": {"pdt_serving_ttft_seconds": {"": ttft}}}

    def test_slo_command_exit_codes_and_report(self, tmp_path, capsys):
        good = tmp_path / "good.json"
        good.write_text(json.dumps(self._slo_snap(breach=False)))
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(self._slo_snap(breach=True)))
        assert cli_main(["slo", "--from", str(good)]) == 0
        out = capsys.readouterr().out
        assert "PASS" in out and "BREACH" not in out
        assert cli_main(["slo", "--from", str(bad)]) == 1
        out = capsys.readouterr().out
        assert "BREACH" in out          # error_rate 0.1 > 0.01
        assert "ttft_p95" in out and "availability" in out

    def test_slo_command_custom_spec(self, tmp_path):
        snap = tmp_path / "s.json"
        snap.write_text(json.dumps(self._slo_snap(breach=True)))
        spec = tmp_path / "spec.json"
        # generous objectives: the same snapshot passes under them
        spec.write_text(json.dumps(
            [{"name": "ttft_p50", "signal": "ttft", "kind": "latency",
              "threshold": 5.0, "quantile": 0.5,
              "metric": "pdt_serving_ttft_seconds"},
             {"name": "err", "signal": "outcome", "kind": "error_rate",
              "threshold": 0.5,
              "metric": "pdt_serving_requests_terminal_total"}]))
        assert cli_main(["slo", "--from", str(snap), "--spec",
                         str(spec)]) == 0

    def test_trace_export_and_tree_round_trip(self, tmp_path, capsys):
        sink = tmp_path / "trace.jsonl"
        telemetry.set_trace_file(str(sink))
        try:
            telemetry.start_trace("cli-req", name="router.submit")
            with telemetry.span("router.dispatch",
                                request_id="cli-req", replica=0):
                pass
        finally:
            telemetry.set_trace_file(None)
        chrome = tmp_path / "chrome.json"
        assert cli_main(["trace", "export", str(sink), "--chrome",
                         str(chrome)]) == 0
        doc = json.loads(chrome.read_text())
        names = {e["name"] for e in doc["traceEvents"]}
        assert "router.dispatch" in names and "router.submit" in names
        assert cli_main(["trace", "tree", str(sink), "--request",
                         "cli-req"]) == 0
        out = capsys.readouterr().out
        assert "router.submit" in out and "router.dispatch" in out
        assert cli_main(["trace", "tree", str(sink), "--request",
                         "absent"]) == 1


# -- drift guards ------------------------------------------------------
# Since ISSUE 9 these are thin wrappers over the pdt-lint checkers
# (paddle_tpu.analysis, PDT003/PDT004) — ONE source of truth for what
# counts as drift; the word-boundary regex scans that used to live
# here are now AST passes shared with the `paddle-tpu-lint` CLI. The
# wrappers run with suppressions ignored: catalog drift cannot be
# opted out of inline.
class TestDocsAndSiteConsistency:
    def _project(self):
        from paddle_tpu.analysis import Project
        return Project(REPO, [os.path.join(REPO, "paddle_tpu")])

    def _documented_sites(self):
        from paddle_tpu.analysis.checkers.faultsites import (
            FaultSiteDriftChecker, collect_doc_sites)
        return collect_doc_sites(
            self._project(), FaultSiteDriftChecker.DEFAULT_FAULTS_FILE)

    def test_fault_site_docstring_matches_source(self):
        """Every site in the faults.py docstring exists as a
        fault_point() call in the source, and vice versa — the PDT003
        checker, which also rejects non-literal fault_point() sites
        the old regex could not see."""
        from paddle_tpu.analysis import run_checkers
        from paddle_tpu.analysis.checkers import FaultSiteDriftChecker
        res = run_checkers(self._project(), [FaultSiteDriftChecker()],
                           respect_suppressions=False)
        assert res.new == [], ("fault-site drift: "
                               + "; ".join(f.render() for f in res.new))

    def test_every_documented_site_fires_with_site_label(self):
        """Arming + visiting each documented site must produce the
        `pdt_faults_fired_total{site=...}` series chaos tests assert
        on — the docstring and the counter labels cannot drift."""
        from paddle_tpu.utils.faults import (FaultError, FaultInjector,
                                             fault_point)
        sites = self._documented_sites()
        assert sites                          # the regex found the list
        for site in sites:
            with FaultInjector() as fi:
                fi.arm(site, always=True)
                with pytest.raises(FaultError):
                    fault_point(site)
        snap = telemetry.snapshot()
        labels = set(snap["counters"]["pdt_faults_fired_total"])
        assert labels == {f'site="{s}"' for s in sites}

    def test_metric_catalog_matches_registered_instruments(self):
        """docs/observability.md's catalog rows must equal the pdt_*
        instruments the code registers — drift fails in BOTH
        directions (the PDT004 checker; being AST-based it needs no
        import list, so modules the old test forgot to import are
        covered too, and span/event names are checked alongside the
        metric table)."""
        from paddle_tpu.analysis import run_checkers
        from paddle_tpu.analysis.checkers import CatalogDriftChecker
        res = run_checkers(self._project(), [CatalogDriftChecker()],
                           respect_suppressions=False)
        assert res.new == [], ("catalog drift: "
                               + "; ".join(f.render() for f in res.new))
        # the static view must agree with the live registry: every
        # dynamically registered pdt_* instrument is one the AST
        # collector sees (guards against registration forms the
        # checker cannot parse creeping in)
        import paddle_tpu.distributed.checkpoint      # noqa: F401
        import paddle_tpu.distributed.fleet.elastic   # noqa: F401
        import paddle_tpu.distributed.launch          # noqa: F401
        import paddle_tpu.loadgen                     # noqa: F401
        import paddle_tpu.models.serving              # noqa: F401
        import paddle_tpu.observability.slo           # noqa: F401
        import paddle_tpu.serving                     # noqa: F401
        import paddle_tpu.utils.faults                # noqa: F401
        from paddle_tpu.analysis.checkers.catalog import (
            collect_instruments)
        static = set(collect_instruments(
            self._project(), CatalogDriftChecker.DEFAULT_SCOPE,
            CatalogDriftChecker.DEFAULT_EXCLUDE))
        registered = {n for n in telemetry.REGISTRY.instruments()
                      if n.startswith("pdt_")}
        assert registered == static, (
            "static/live registry drift: AST-collector-only (a "
            "registration the runtime never executes) "
            f"{sorted(static - registered)}, live-only (a form the "
            f"collector cannot parse) {sorted(registered - static)}")

    def test_every_pallas_kernel_has_interpret_oracle_test(self):
        """Every `ops/` module containing a Pallas kernel must be
        referenced by a test file that also names an oracle. CI runs on
        the CPU mesh, so those references exercise the interpret /
        XLA-oracle paths — a kernel module without one ships unproven
        math (ISSUE 6 drift guard)."""
        ops_dir = os.path.join(REPO, "paddle_tpu", "ops")
        kernels = []
        for fn in sorted(os.listdir(ops_dir)):
            if not fn.endswith(".py") or fn == "__init__.py":
                continue
            with open(os.path.join(ops_dir, fn)) as f:
                if "pallas_call(" in f.read():
                    kernels.append(fn[:-3])
        assert kernels                       # the scan found the set
        tests_dir = os.path.join(REPO, "tests")
        srcs = []
        for fn in sorted(os.listdir(tests_dir)):
            if fn.startswith("test_") and fn.endswith(".py"):
                with open(os.path.join(tests_dir, fn)) as f:
                    srcs.append(f.read())
        missing = []
        for mod in kernels:
            # word-ish boundary: "paged_attention" must not take credit
            # from "ragged_paged_attention" references
            pat = re.compile(rf"(?<![a-z_]){mod}")
            if not any(pat.search(src) and re.search("oracle", src, re.I)
                       for src in srcs):
                missing.append(mod)
        assert not missing, ("Pallas kernel modules without an "
                             f"interpret-mode oracle test: {missing}")


class TestBenchProbeCache:
    """ISSUE 6 satellite: the TPU probe verdict is cached in a TTL'd
    file so repeat bench runs stop burning minutes re-probing a dead
    tunnel, and an expired FAILURE re-probes with a shrunk attempt
    ladder. All probing is stubbed — no subprocess ever runs here."""

    def _bench(self, tmp_path, monkeypatch, rc=1):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "_bench_probe_under_test", os.path.join(REPO, "bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        monkeypatch.setattr(mod, "PROBE_CACHE_PATH",
                            str(tmp_path / "probe.json"))
        calls = []

        class R:
            returncode = rc
            stdout = "ok" if rc == 0 else ""
            stderr = "stubbed"

        def fake_run(*a, **kw):
            calls.append(a)
            return R()
        monkeypatch.setattr(mod.subprocess, "run", fake_run)
        monkeypatch.setattr(mod.time, "sleep", lambda *_: None)
        return mod, calls

    def test_fresh_cache_short_circuits_probe(self, tmp_path,
                                              monkeypatch):
        import time as _time
        mod, calls = self._bench(tmp_path, monkeypatch)
        with open(mod.PROBE_CACHE_PATH, "w") as f:
            json.dump({"verdict": False, "ts": _time.time()}, f)
        assert mod.probe_tpu() is False
        assert calls == []                    # no subprocess at all
        assert mod.PROBE_INFO["cached"] is True
        assert mod.PROBE_INFO["attempts"] == 0

    def test_expired_failure_shrinks_attempt_ladder(self, tmp_path,
                                                    monkeypatch):
        import time as _time
        mod, calls = self._bench(tmp_path, monkeypatch)
        stale = _time.time() - mod.PROBE_CACHE_TTL_S - 10
        with open(mod.PROBE_CACHE_PATH, "w") as f:
            json.dump({"verdict": False, "ts": stale}, f)
        assert mod.probe_tpu() is False
        # PROBE_ATTEMPTS (default 5) dropped to PROBE_ATTEMPTS_RETRY
        assert len(calls) == mod.PROBE_ATTEMPTS_RETRY

    def test_probe_writes_cache_and_records_cost(self, tmp_path,
                                                 monkeypatch):
        mod, calls = self._bench(tmp_path, monkeypatch, rc=0)
        assert mod.probe_tpu() is True
        assert len(calls) == 1
        info = mod.PROBE_INFO
        assert info["verdict"] is True and info["cached"] is False
        assert info["attempts"] == 1 and info["wall_s"] >= 0
        with open(mod.PROBE_CACHE_PATH) as f:
            entry = json.load(f)
        assert entry["verdict"] is True and entry["attempts"] == 1
        # a cached SUCCESS is never trusted blindly (the tunnel dies
        # between runs): the next call probes again, but with the
        # shrunk one-attempt ladder — so a now-dead tunnel is caught
        # by the cheap subprocess, not by the parent's backend init
        calls.clear()
        assert mod.probe_tpu() is True
        assert len(calls) == 1 and mod.PROBE_INFO["cached"] is False

    def test_cached_success_dead_tunnel_degrades_cheaply(self, tmp_path,
                                                         monkeypatch):
        import time as _time
        mod, calls = self._bench(tmp_path, monkeypatch, rc=1)
        with open(mod.PROBE_CACHE_PATH, "w") as f:
            json.dump({"verdict": True, "ts": _time.time()}, f)
        assert mod.probe_tpu() is False       # tunnel died post-cache
        assert len(calls) == mod.PROBE_ATTEMPTS_RETRY  # cheap recheck
        with open(mod.PROBE_CACHE_PATH) as f:
            assert json.load(f)["verdict"] is False  # cache corrected

    def test_corrupt_cache_is_ignored(self, tmp_path, monkeypatch):
        mod, calls = self._bench(tmp_path, monkeypatch)
        with open(mod.PROBE_CACHE_PATH, "w") as f:
            f.write("{not json")
        monkeypatch.setattr(mod, "PROBE_ATTEMPTS", 2)
        assert mod.probe_tpu() is False
        assert len(calls) == 2                # full ladder, no crash


class TestBenchRegressionGate:
    def _bench(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "_bench_under_test", os.path.join(REPO, "bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_check_regression_detects_drop(self):
        bench = self._bench()
        prev = {"detail": {"tokens_per_sec_per_chip": 100.0,
                           "decode_tokens_per_sec": 50.0}}
        ok = {"detail": {"tokens_per_sec_per_chip": 95.0,
                         "decode_tokens_per_sec": 49.0}}
        bad = {"detail": {"tokens_per_sec_per_chip": 80.0,
                          "decode_tokens_per_sec": 50.0}}
        regs, n = bench.check_regression(prev, ok, 10.0)
        assert regs == [] and n == 2
        regs, n = bench.check_regression(prev, bad, 10.0)
        assert n == 2 and len(regs) == 1 \
            and "tokens_per_sec_per_chip" in regs[0]
        # a tighter threshold flags the small drop too
        regs, _ = bench.check_regression(prev, ok, 1.0)
        assert len(regs) == 2
        # nothing comparable is reported, not silently passed
        assert bench.check_regression({}, {}, 10.0) == ([], 0)

    def test_hist_diff_removes_warm_phase_from_quantiles(self):
        """Steady-state quantiles must exclude warm-up (compile)
        observations — count, sum, AND the cumulative buckets diff."""
        bench = self._bench()
        warm = {"count": 2, "sum": 8.0,
                "buckets": {"0.01": 0, "10": 2, "+Inf": 2}}
        final = {"count": 12, "sum": 8.05,
                 "buckets": {"0.01": 10, "10": 12, "+Inf": 12}}
        steady = bench._hist_diff(final, warm)
        assert steady == {"count": 10, "sum": pytest.approx(0.05),
                          "buckets": {"0.01": 10, "10": 10,
                                      "+Inf": 10}}
        # raw p99 sits in the compile bucket; steady-state does not
        raw_p99 = bench._hist_quantiles(final)["p99"]
        steady_p99 = bench._hist_quantiles(steady)["p99"]
        assert raw_p99 > 1.0 and steady_p99 <= 0.01
        assert bench._hist_diff({}, warm) == {}
        assert bench._hist_diff(None, None) is None

    def test_cli_compare_mode_exit_codes(self, tmp_path):
        bench = self._bench()
        prev = tmp_path / "prev.json"
        prev.write_text(json.dumps(
            {"detail": {"tokens_per_sec_per_chip": 100.0}}))
        good = tmp_path / "good.json"
        good.write_text(json.dumps(
            {"detail": {"tokens_per_sec_per_chip": 99.0}}))
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(
            {"detail": {"tokens_per_sec_per_chip": 50.0}}))
        base = ["--check-regression", str(prev), "--current"]
        assert bench.main(base + [str(good)]) == 0
        assert bench.main(base + [str(bad)]) == 1
        empty = tmp_path / "empty.json"
        empty.write_text("{}")
        assert bench.main(base + [str(empty)]) == 2
        assert bench.main(["--current", str(good)]) == 2
