"""Tensor-parallel serving replicas (ISSUE 12, serving/submesh.py).

One replica = one GSPMD submesh on the 8-simulated-device harness:
submesh carving, sharded-allocator invariants, per-shard migration
payload round-trips, tp=1-vs-tp=2 BIT-IDENTICAL greedy outputs through
SIGKILL failover and prefill->decode migration, spec-decode-on-TP, the
sharded kernel's shard_map parity, and the mesh-axis drift guard
(docs/serving.md "Tensor parallelism" axis table == the specs
serving/submesh.py actually builds).
"""
import ast
import os
import re

import numpy as np
import pytest
import jax

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.serving import (ContinuousBatchingEngine,
                                       SpecConfig, assemble_payload_kv)
from paddle_tpu.serving import (ServingRouter, TP_AXIS, TpConfig,
                                carve_submeshes, transfer)
from paddle_tpu.serving.submesh import SubMesh

pytestmark = pytest.mark.chaos  # fast tier, runs in tier-1

NEW_TOKENS = 10
MAX_SEQ = 96


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def jobs(model):
    rng = np.random.default_rng(7)
    v = model.config.vocab_size
    return [rng.integers(1, v, int(rng.integers(6, 18))).tolist()
            for _ in range(6)]


@pytest.fixture(scope="module")
def oracle(model, jobs):
    """Greedy outputs of a plain single-chip engine — the tp=1 truth
    every TP drill below must reproduce bit-identically."""
    eng = ContinuousBatchingEngine(model, max_batch_size=3,
                                   max_seq_len=MAX_SEQ,
                                   enable_prefix_caching=True)
    rids = [eng.add_request(p, NEW_TOKENS) for p in jobs]
    out = eng.run()
    return [out[r] for r in rids]


def _tp_engine(model, sm, **kw):
    return ContinuousBatchingEngine(model, max_batch_size=3,
                                    max_seq_len=MAX_SEQ, submesh=sm,
                                    **kw)


# -- carving + validation ----------------------------------------------
class TestCarving:
    def test_disjoint_slices(self):
        meshes = carve_submeshes(4, TpConfig(tp=2))
        ids = [m.device_ids for m in meshes]
        flat = [d for t in ids for d in t]
        assert len(flat) == len(set(flat)) == 8
        assert all(len(t) == 2 for t in ids)
        d = meshes[1].describe()
        assert d["tp"] == 2 and d["mode"] == "exact" \
            and len(d["devices"]) == 2

    def test_fleet_must_fit(self):
        with pytest.raises(ValueError, match="needs 16 devices"):
            carve_submeshes(4, TpConfig(tp=4))

    def test_tpconfig_validation(self):
        with pytest.raises(ValueError, match="tp must be >= 1"):
            TpConfig(tp=0)
        with pytest.raises(ValueError, match="exact|fast"):
            TpConfig(tp=2, mode="turbo")

    def test_model_must_split(self, model):
        # tiny(): 4 q heads / 2 kv heads — tp=4 cannot shard the pages
        sm = SubMesh(jax.devices()[:4], TpConfig(tp=4))
        with pytest.raises(ValueError, match="num_key_value_heads"):
            _tp_engine(model, sm)

    def test_engine_requires_paged_ragged(self, model):
        sm = carve_submeshes(1, TpConfig(tp=2))[0]
        with pytest.raises(ValueError, match="kv_layout='paged'"):
            _tp_engine(model, sm, kv_layout="dense")
        with pytest.raises(ValueError, match="ragged"):
            _tp_engine(model, sm, attention_impl="legacy")


# -- engine-level parity + sharded allocator ---------------------------
class TestTpEngine:
    def test_bit_identical_greedy(self, model, jobs, oracle):
        sm = carve_submeshes(1, TpConfig(tp=2))[0]
        eng = _tp_engine(model, sm, enable_prefix_caching=True)
        rids = [eng.add_request(p, NEW_TOKENS) for p in jobs]
        out = eng.run()
        assert [out[r] for r in rids] == oracle
        assert telemetry.value("pdt_tp_dispatches_total") >= 1
        assert telemetry.value("pdt_tp_shards") == 2

    def test_sharded_allocator_invariants(self, model, jobs):
        sm = carve_submeshes(1, TpConfig(tp=2))[0]
        eng = _tp_engine(model, sm)
        eng.add_request(jobs[0], NEW_TOKENS)
        eng.step()
        eng.check_invariants()       # pools on-submesh, spec declared
        hk = model.config.num_key_value_heads
        kp = eng._kv[0][0]
        assert set(kp.sharding.device_set) == set(sm.devices)
        # one logical page = tp local shards: each shard holds hk/tp
        # heads of the WHOLE pool
        shard_shapes = {s.data.shape for s in kp.addressable_shards}
        assert shard_shapes == {(hk // 2,) + kp.shape[1:]}
        # a resharded pool must be caught by the invariant checker
        from paddle_tpu.models.serving import EngineInvariantError
        good = eng._kv[0]
        eng._kv[0] = (jax.device_put(np.asarray(kp), jax.devices()[7]),
                      good[1])
        with pytest.raises(EngineInvariantError, match="submesh"):
            eng.check_invariants()
        eng._kv[0] = good
        eng.check_invariants()

    def test_exact_mode_fences_are_scoped(self, model, jobs, oracle):
        # a plain engine built AFTER a TP engine must stay unaffected
        # (the trace context is scoped to TP dispatches only)
        sm = carve_submeshes(1, TpConfig(tp=2))[0]
        _tp_engine(model, sm).add_request(jobs[0], 2)
        from paddle_tpu.distributed.mesh import serving_tp
        assert serving_tp() is None
        eng = ContinuousBatchingEngine(model, max_batch_size=3,
                                       max_seq_len=MAX_SEQ,
                                       enable_prefix_caching=True)
        rids = [eng.add_request(p, NEW_TOKENS) for p in jobs]
        out = eng.run()
        assert [out[r] for r in rids] == oracle


# -- per-shard migration payloads --------------------------------------
class TestPerShardTransfer:
    def test_export_import_roundtrip(self, model, jobs, oracle):
        sms = carve_submeshes(2, TpConfig(tp=2))
        src = _tp_engine(model, sms[0])
        dst = _tp_engine(model, sms[1])
        rid = src.add_request(jobs[0], NEW_TOKENS)
        for _ in range(3):
            src.step()
        payload = transfer.serialize_request(src, rid)
        # the wire format is one fragment per shard; nbytes counts the
        # fragments (sum == the logical bytes, no double count)
        assert payload["kv"] is None and payload["tp"] == 2
        assert len(payload["kv_shards"]) == 2
        frag_bytes = sum(k.nbytes + v.nbytes
                         for sh in payload["kv_shards"] for k, v in sh)
        assert transfer.payload_nbytes(payload) == frag_bytes
        logical = assemble_payload_kv(payload)
        hk = model.config.num_key_value_heads
        assert logical[0][0].shape[0] == hk
        assert frag_bytes == sum(k.nbytes + v.nbytes
                                 for k, v in logical)
        # shard-bytes metering: one series per shard, equal halves
        b0 = telemetry.value("pdt_tp_migration_shard_bytes_total",
                             shard="0")
        b1 = telemetry.value("pdt_tp_migration_shard_bytes_total",
                             shard="1")
        assert b0 == b1 and b0 > 0
        new_req, _ = transfer.migrate_request(src, dst, rid)
        while not new_req.done:
            dst.step()
        assert new_req.output == oracle[0]
        src.check_invariants()
        dst.check_invariants()

    def test_spill_store_handles_fragment_payloads(self, model, jobs):
        from paddle_tpu.serving.prefix_store import FleetPrefixStore
        sm = carve_submeshes(1, TpConfig(tp=2))[0]
        eng = _tp_engine(model, sm, page_size=8,
                         enable_prefix_caching=True)
        rid = eng.add_request(jobs[1][:3] * 8, NEW_TOKENS)
        eng.step()
        payload = transfer.serialize_request(eng, rid)
        store = FleetPrefixStore(page_size=8)
        spilled = store.spill_payload(payload)
        assert spilled >= 1
        entry = store.fetch(payload["prompt"])
        assert entry is not None
        hk = model.config.num_key_value_heads
        assert entry[1][0][0].shape[0] == hk     # logical rows stored


# -- fleet drills -------------------------------------------------------
class TestTpFleet:
    def _factory(self, model):
        def make(i, sm):
            return _tp_engine(model, sm, enable_prefix_caching=True)
        return make

    def test_kill_a_submesh_bit_identical(self, model, jobs, oracle):
        router = ServingRouter(self._factory(model), num_replicas=2,
                               tp=2)
        ids = [router.submit(p, NEW_TOKENS) for p in jobs]
        router.step()
        router.step()                       # mid-decode
        victim = router.requests[ids[0]].replica
        router.kill_replica(victim)         # SIGKILL one whole submesh
        out = router.run()
        assert [out[i] for i in ids] == oracle
        info = router.fleet_info()
        assert info["failovers"] >= 1
        assert info["tp"]["tp"] == 2
        subs = [r["submesh"] for r in info["replicas"]]
        assert all(s and len(s["devices"]) == 2 for s in subs)
        assert len({tuple(s["devices"]) for s in subs}) == 2
        # replica identity is (submesh, generation): the restarted
        # victim reports the SAME device slice
        assert router.replicas[victim].submesh.device_ids \
            == tuple(subs[victim]["devices"])
        from paddle_tpu.observability.status import render_fleet_status
        text = render_fleet_status(info)
        assert "submesh" in text and "tp=2@[" in text

    def test_roles_migration_bit_identical(self, model, jobs, oracle):
        router = ServingRouter(self._factory(model),
                               roles="prefill:1,decode:1", tp=2,
                               policy="prefix_affinity", page_size=16)
        ids = [router.submit(p, NEW_TOKENS) for p in jobs]
        out = router.run()
        assert [out[i] for i in ids] == oracle
        info = router.fleet_info()
        assert info["migrations"] >= 1
        assert telemetry.value("pdt_tp_migration_shard_bytes_total",
                               shard="0") > 0


# -- speculative decoding on TP ----------------------------------------
class TestSpecOnTp:
    def test_self_draft_smoke(self, model, jobs, oracle):
        # target == draft: acceptance must be total and the stream
        # bit-identical to the plain tp=1 engine — the draft scan,
        # backfill, and verify all ran on the submesh
        sm = carve_submeshes(1, TpConfig(tp=2))[0]
        eng = _tp_engine(model, sm,
                         spec_decode=SpecConfig(model, k=3))
        rids = [eng.add_request(p, NEW_TOKENS) for p in jobs[:3]]
        out = eng.run()
        assert [out[r] for r in rids] == oracle[:3]
        assert eng.num_spec_rounds >= 1
        assert eng.num_spec_accepted == eng.num_spec_proposed > 0

    def test_draft_pool_invariants(self, model, jobs):
        # the draft pools feed the same per-shard kernel path as the
        # target pools — a relocated draft pool must be caught by the
        # same invariant checker, not surface later as wrong proposals
        sm = carve_submeshes(1, TpConfig(tp=2))[0]
        eng = _tp_engine(model, sm, spec_decode=SpecConfig(model, k=3))
        eng.add_request(jobs[0], NEW_TOKENS)
        eng.step()
        eng.check_invariants()
        from paddle_tpu.models.serving import EngineInvariantError
        good = eng._d_kv[0]
        eng._d_kv[0] = (jax.device_put(np.asarray(good[0]),
                                       jax.devices()[7]), good[1])
        with pytest.raises(EngineInvariantError, match="draft-k-pool"):
            eng.check_invariants()
        eng._d_kv[0] = good
        eng.check_invariants()


# -- sharded kernel path ------------------------------------------------
class TestShardMapKernel:
    def test_interpret_parity_under_tp(self):
        """The Pallas kernel via shard_map over `tp` (the on-TPU path,
        forced in interpret mode) == the XLA oracle on head-sharded
        pools with replicated descriptors."""
        from jax.sharding import NamedSharding, PartitionSpec
        from paddle_tpu.ops.ragged_paged_attention import (
            pack_ragged_starts, ragged_paged_attention_values,
            token_arrays)
        rng = np.random.default_rng(3)
        hk, g, d, ps, pps, n = 2, 2, 8, 4, 4, 3
        h = hk * g
        sm = carve_submeshes(1, TpConfig(tp=2))[0]
        qlens = [3, 1, 5]
        ctx = np.asarray([7, 9, 5], np.int32)
        qstart, t = pack_ragged_starts(qlens, block_q=4)
        q = rng.standard_normal((t, h, d)).astype(np.float32)
        kp = rng.standard_normal((hk, 16, ps, d)).astype(np.float32)
        vp = rng.standard_normal((hk, 16, ps, d)).astype(np.float32)
        bt = rng.integers(1, 16, (n, pps)).astype(np.int32)
        qlen = np.asarray(qlens, np.int32)
        want = np.asarray(ragged_paged_attention_values(
            q, kp, vp, qstart, qlen, ctx, bt, use_kernel=False))
        shard = NamedSharding(sm.jax_mesh,
                              PartitionSpec(TP_AXIS, None, None, None))
        got = np.asarray(ragged_paged_attention_values(
            jax.device_put(q, NamedSharding(
                sm.jax_mesh, PartitionSpec(None, TP_AXIS, None))),
            jax.device_put(kp, shard), jax.device_put(vp, shard),
            qstart, qlen, ctx, bt, use_kernel=True, block_q=4,
            tp=(sm.jax_mesh, TP_AXIS)))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


# -- drift guard: mesh-axis names vs the documented axis table ----------
class TestAxisTableDrift:
    ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _doc_axes(self):
        doc = open(os.path.join(self.ROOT, "docs/serving.md")).read()
        section = doc.split("### Tensor parallelism", 1)[1]
        table = section.split("| axis | meaning |", 1)[1]
        axes = set()
        for line in table.splitlines():
            m = re.match(r"\|\s*`(\w+)`\s*\|", line)
            if m:
                axes.add(m.group(1))
            elif axes and not line.startswith("|"):
                break                        # table ended
        return axes

    def _spec_axes(self):
        """Every string literal an explicit PartitionSpec(...) in
        serving/submesh.py names, plus the TP_AXIS constant — the
        axes serving shardings can possibly use."""
        src = open(os.path.join(
            self.ROOT, "paddle_tpu/serving/submesh.py")).read()
        tree = ast.parse(src)
        axes, consts = set(), {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and node.targets \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                consts[node.targets[0].id] = node.value.value
            if isinstance(node, ast.Call) \
                    and getattr(node.func, "id",
                                getattr(node.func, "attr", "")) \
                    == "PartitionSpec":
                for a in ast.walk(node):
                    if isinstance(a, ast.Constant) \
                            and isinstance(a.value, str):
                        axes.add(a.value)
                    if isinstance(a, ast.Name) and a.id in consts:
                        axes.add(consts[a.id])
        axes.add(consts["TP_AXIS"])
        return axes

    def test_axes_match_doc_table(self):
        doc, spec = self._doc_axes(), self._spec_axes()
        assert doc == spec == {TP_AXIS}, (
            f"mesh-axis drift: docs/serving.md table {sorted(doc)} vs "
            f"serving/submesh.py specs {sorted(spec)} — axis names are "
            "stringly-typed; update both sides together")
