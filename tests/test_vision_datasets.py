"""Vision datasets (offline file-format parsers + synthetic).
≙ SURVEY.md §2.2 vision row («python/paddle/vision/datasets/»)."""
import gzip
import os
import pickle
import struct

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision.datasets import (Cifar10, DatasetFolder, FakeData,
                                        ImageFolder, MNIST)


def _write_idx_images(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x00000803))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.astype(np.uint8).tobytes())


def _write_idx_labels(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x00000801))
        f.write(struct.pack(">I", len(arr)))
        f.write(arr.astype(np.uint8).tobytes())


class TestMNIST:
    def test_parses_idx_files(self, tmp_path):
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (10, 28, 28), dtype=np.uint8)
        labs = rng.integers(0, 10, 10, dtype=np.uint8)
        ip = str(tmp_path / "train-images-idx3-ubyte")
        lp = str(tmp_path / "train-labels-idx1-ubyte")
        _write_idx_images(ip, imgs)
        _write_idx_labels(lp, labs)
        ds = MNIST(image_path=ip, label_path=lp)
        assert len(ds) == 10
        x, y = ds[3]
        np.testing.assert_array_equal(x, imgs[3])
        assert y == labs[3]
        # root-directory resolution + gz transparency
        gz = str(tmp_path / "gz")
        os.makedirs(gz)
        with open(ip, "rb") as f, gzip.open(
                os.path.join(gz, "train-images-idx3-ubyte.gz"), "wb") as g:
            g.write(f.read())
        with open(lp, "rb") as f, gzip.open(
                os.path.join(gz, "train-labels-idx1-ubyte.gz"), "wb") as g:
            g.write(f.read())
        ds2 = MNIST(root=gz)
        np.testing.assert_array_equal(ds2[3][0], imgs[3])

    def test_download_raises_offline(self):
        with pytest.raises(RuntimeError):
            MNIST(download=True)


class TestCifar:
    def test_parses_pickle_batches(self, tmp_path):
        rng = np.random.default_rng(1)
        data = rng.integers(0, 256, (8, 3072), dtype=np.uint8)
        labels = list(rng.integers(0, 10, 8))
        fp = tmp_path / "data_batch_1"
        with open(fp, "wb") as f:
            pickle.dump({b"data": data, b"labels": labels}, f)
        ds = Cifar10(data_file=str(fp))
        assert len(ds) == 8
        x, y = ds[2]
        assert x.shape == (3, 32, 32)
        np.testing.assert_array_equal(x.ravel(), data[2])
        assert y == labels[2]


class TestFakeData:
    def test_deterministic_and_transforms(self):
        ds = FakeData(size=5, image_shape=(3, 8, 8), num_classes=4)
        x1, y1 = ds[2]
        x2, y2 = ds[2]
        np.testing.assert_array_equal(x1, x2)
        assert y1 == y2 and 0 <= y1 < 4
        ds_t = FakeData(size=5, image_shape=(3, 8, 8),
                        transform=lambda im: im * 2)
        np.testing.assert_allclose(ds_t[2][0], x1 * 2)

    @pytest.mark.slow
    def test_trains_resnet_smoke(self):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.vision.models import resnet18
        paddle.seed(0)
        model = resnet18(num_classes=4)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        ds = FakeData(size=8, image_shape=(3, 32, 32), num_classes=4)
        loader = DataLoader(ds, batch_size=4)
        from paddle_tpu.nn import functional as F
        for x, y in loader:
            loss = F.cross_entropy(model(paddle.to_tensor(np.asarray(x))),
                                   paddle.to_tensor(np.asarray(y)))
            loss.backward()
            opt.step()
            opt.clear_grad()
            break
        assert np.isfinite(float(loss))


class TestFolders:
    def _tree(self, tmp_path):
        from PIL import Image
        for cls in ("cat", "dog"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(2):
                arr = np.full((6, 6, 3), 40 * i, np.uint8)
                Image.fromarray(arr).save(d / f"{i}.png")
        return tmp_path

    def test_dataset_folder(self, tmp_path):
        root = self._tree(tmp_path)
        ds = DatasetFolder(str(root))
        assert len(ds) == 4
        assert ds.class_to_idx == {"cat": 0, "dog": 1}
        img, y = ds[0]
        assert img.shape == (6, 6, 3)
        assert y in (0, 1)

    def test_image_folder(self, tmp_path):
        root = self._tree(tmp_path)
        ds = ImageFolder(str(root))
        assert len(ds) == 4
        assert ds[0].shape == (6, 6, 3)


class TestTransformBreadth:
    """Round-3 transform additions (≙ «python/paddle/vision/transforms»)."""

    def _img(self, h=16, w=16, c=3):
        return np.random.default_rng(0).integers(
            0, 255, (h, w, c)).astype(np.uint8)

    def test_flips_pad_grayscale(self):
        from paddle_tpu.vision import transforms as T
        img = self._img()
        np.testing.assert_array_equal(T.hflip(img), img[:, ::-1])
        np.testing.assert_array_equal(T.vflip(img), img[::-1])
        padded = T.Pad(2)(img)
        assert padded.shape == (20, 20, 3)
        g = T.Grayscale()(img)
        assert g.shape == (16, 16, 1)
        ref = img.astype(np.float32) @ np.array([0.299, 0.587, 0.114])
        np.testing.assert_allclose(g[..., 0], ref, rtol=1e-5)

    def test_color_jitter_runs(self):
        from paddle_tpu.vision import transforms as T
        img = self._img()
        out = T.ColorJitter(0.4, 0.4, 0.4, 0.1)(img)
        assert out.shape == img.shape and out.dtype == np.uint8

    def test_adjust_functions(self):
        from paddle_tpu.vision import transforms as T
        img = self._img()
        b = T.adjust_brightness(img, 2.0)
        assert b.mean() >= img.mean()
        c = T.adjust_contrast(img, 0.0)
        assert np.ptp(c.astype(np.float32)) <= 1.5  # collapses to mean
        h = T.adjust_hue(img, 0.25)
        assert h.shape == img.shape

    def test_random_resized_crop_and_erasing(self):
        from paddle_tpu.vision import transforms as T
        img = self._img(32, 32)
        out = T.RandomResizedCrop(16)(img)
        assert np.asarray(out).shape[:2] == (16, 16)
        er = T.RandomErasing(prob=1.0, value=0)(img)
        assert (np.asarray(er) == 0).any()

    def test_rotation_and_transpose(self):
        from paddle_tpu.vision import transforms as T
        img = self._img()
        r = T.rotate(img, 90)
        assert r.shape == img.shape
        t = T.Transpose()(img)
        assert t.shape == (3, 16, 16)

    def test_callbacks_namespace(self):
        import paddle_tpu as paddle
        assert hasattr(paddle.callbacks, "EarlyStopping")
        assert hasattr(paddle.callbacks, "ModelCheckpoint")
