"""Autograd tape tests. ≙ reference eager backward tests [U]."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer


class TestBackward:
    def test_simple_chain(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = x * x * x  # y = x^3, dy/dx = 3x^2 = 12
        y.backward()
        assert abs(float(x.grad) - 12.0) < 1e-5

    def test_grad_accumulation(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])

    def test_shared_subexpression(self):
        x = paddle.to_tensor(3.0, stop_gradient=False)
        y = x * x          # used twice
        z = y + y
        z.backward()
        assert abs(float(x.grad) - 12.0) < 1e-5  # d(2x^2)/dx = 4x

    def test_stop_gradient(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = paddle.to_tensor([2.0])  # stop_gradient=True
        z = (x * y).sum()
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])
        assert y.grad is None

    def test_detach(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = (x * 2).detach()
        assert y.stop_gradient
        z = (x * y).sum()
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0])

    def test_no_grad(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y.stop_gradient
        assert y._node is None

    def test_non_scalar_backward_with_grad(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * 3
        y.backward(paddle.to_tensor([1.0, 0.5]))
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 1.5])

    def test_retain_graph(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        assert abs(float(x.grad) - 8.0) < 1e-5
        with pytest.raises(RuntimeError):
            y.backward()  # graph freed now

    def test_multi_output_op(self):
        x = paddle.to_tensor(np.arange(6, np.float32).reshape(2, 3)
                             if False else
                             np.arange(6, dtype=np.float32).reshape(2, 3),
                             stop_gradient=False)
        a, b, c = paddle.split(x, 3, axis=1)
        (a.sum() * 2 + c.sum()).backward()
        want = np.array([[2, 0, 1], [2, 0, 1]], np.float32)
        np.testing.assert_allclose(x.grad.numpy(), want)

    def test_hook(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        h = x.register_hook(lambda g: g * 10)
        (x * 2).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [20.0])
        h.remove()
        x.clear_grad()
        (x * 2).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])

    def test_inplace_iadd_tracks_grad(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * 2
        y += 1.0
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


class TestGradAPI:
    def test_paddle_grad(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = x * x
        (g,) = paddle.grad(y, x)
        assert abs(float(g) - 4.0) < 1e-5
        assert x.grad is None  # .grad untouched

    def test_grad_unused(self):
        x = paddle.to_tensor(1.0, stop_gradient=False)
        z = paddle.to_tensor(1.0, stop_gradient=False)
        y = x * 2
        gx, gz = paddle.grad(y, [x, z], allow_unused=True)
        assert gz is None


class TestPyLayer:
    def test_custom_op(self):
        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, grad):
                return grad * 2

        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = Double.apply(x)
        np.testing.assert_allclose(y.numpy(), [2.0, 4.0])
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])

    def test_custom_op_chained(self):
        class Square(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x

            @staticmethod
            def backward(ctx, grad):
                (x,) = ctx.saved_tensor
                return grad * 2.0 * x

        x = paddle.to_tensor(3.0, stop_gradient=False)
        y = Square.apply(x) * 2  # y = 2x^2, dy/dx = 4x = 12
        y.backward()
        assert abs(float(x.grad) - 12.0) < 1e-5
