"""pdt-lint (paddle_tpu.analysis) — the AST-based invariant analyzer
(ISSUE 9). Three layers of coverage:

* **fixtures** — every checker PDT001–PDT007 against minimal positive
  AND negative synthetic trees, so each rule's trigger is pinned
  independently of the real repo's state;
* **policy** — suppression parsing (reason mandatory, unused reported),
  baseline matching (shrink-only: stale entries fail, --update-baseline
  removes but never adds), CLI exit codes and the JSON schema;
* **the tier-1 gate** — the real repo is clean against the committed
  baseline, and every committed suppression/baseline entry still masks
  a live finding (so removing any one reproduces it).
"""
import json
import os
import textwrap

import pytest

from paddle_tpu.analysis import (Baseline, Project, by_code,
                                 default_checkers, lint_repo,
                                 run_checkers)
from paddle_tpu.analysis.__main__ import BASELINE_NAME
from paddle_tpu.analysis.__main__ import main as cli_main
from paddle_tpu.analysis.checkers import (CatalogDriftChecker,
                                          CompileSeamChecker,
                                          DurableWriteChecker,
                                          FaultCoverageChecker,
                                          FaultSiteDriftChecker,
                                          HarvestSeamChecker,
                                          InjectableClockChecker,
                                          ModelKeyChecker,
                                          PinPairingChecker,
                                          ResizeIntentChecker,
                                          SwallowedErrorChecker,
                                          TracedHostSyncChecker)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_project(tmp_path, files):
    """A synthetic repo: {relpath: source}. Returns its Project."""
    tmp_path.mkdir(parents=True, exist_ok=True)
    (tmp_path / "pyproject.toml").write_text("[project]\n")
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Project(str(tmp_path), [str(tmp_path / "paddle_tpu")])


def run_one(tmp_path, checker, files, **kw):
    res = run_checkers(make_project(tmp_path, files), [checker], **kw)
    return res


def codes(res):
    return [f.code for f in res.new]


# -- PDT001 injectable-clock -------------------------------------------
class TestInjectableClock:
    def test_direct_calls_flagged_references_not(self, tmp_path):
        res = run_one(tmp_path, InjectableClockChecker(), {
            "paddle_tpu/serving/x.py": """\
                import time
                from time import perf_counter

                DEFAULT = time.monotonic      # reference: fine

                def f(clock=time.monotonic):  # default ref: fine
                    t0 = time.time()          # finding
                    t1 = perf_counter()       # finding (from-import)
                    time.sleep(0.1)           # sleep is not a clock
                    return t0, t1
            """})
        assert codes(res) == ["PDT001", "PDT001"]
        assert {f.detail for f in res.new} == {"time.time",
                                               "time.perf_counter"}
        assert res.new[0].symbol == "f"

    def test_scope_and_allowlist(self, tmp_path):
        res = run_one(tmp_path, InjectableClockChecker(), {
            # out of scope: the training stack may read wall clocks
            "paddle_tpu/optimizer.py":
                "import time\nT = time.time()\n",
            # allowlisted clock owner
            "paddle_tpu/observability/registry.py":
                "import time\nT = time.perf_counter()\n",
            # in scope via the models/serving.py entry
            "paddle_tpu/models/serving.py":
                "import time\nT = time.monotonic()\n"})
        assert codes(res) == ["PDT001"]
        assert res.new[0].path == "paddle_tpu/models/serving.py"


# -- PDT002 traced-host-sync -------------------------------------------
class TestTracedHostSync:
    def test_jit_wrapped_and_decorated(self, tmp_path):
        res = run_one(tmp_path, TracedHostSyncChecker(), {
            "paddle_tpu/ops/k.py": """\
                import jax
                import numpy as np

                def kern(x):
                    return np.asarray(x)          # finding (jitted below)

                run = jax.jit(kern)

                @jax.jit
                def deco(x):
                    return x.item()               # finding

                def host(x):
                    return np.asarray(x)          # NOT traced: fine
            """})
        assert codes(res) == ["PDT002", "PDT002"]
        assert res.new[0].detail == "kern:numpy.asarray"
        assert res.new[1].detail == "deco:.item()"

    def test_pallas_kernel_and_float_of_operand(self, tmp_path):
        res = run_one(tmp_path, TracedHostSyncChecker(), {
            "paddle_tpu/ops/p.py": """\
                import jax
                from jax.experimental import pallas as pl

                def kernel(x_ref, o_ref):
                    s = float(x_ref)              # finding: operand
                    n = float(1.5)                # literal: fine
                    k = int(x_ref.shape[0])       # not a bare param: fine
                    o_ref[...] = s * n * k

                def call(x):
                    return pl.pallas_call(kernel, out_shape=x)(x)
            """})
        assert codes(res) == ["PDT002"]
        assert res.new[0].detail == "kernel:float()"

    def test_device_get_and_partial_jit(self, tmp_path):
        res = run_one(tmp_path, TracedHostSyncChecker(), {
            "paddle_tpu/models/m.py": """\
                import jax
                from functools import partial

                @partial(jax.jit, static_argnums=0)
                def step(n, x):
                    return jax.device_get(x)      # finding
            """})
        assert codes(res) == ["PDT002"]
        assert res.new[0].detail == "step:jax.device_get"


# -- PDT003 fault-site drift -------------------------------------------
class TestFaultSiteDrift:
    FAULTS = '''\
        """Fault sites: ``eng.alpha`` and ``eng.beta``."""
        def fault_point(site):
            pass
    '''

    def test_in_sync_is_clean(self, tmp_path):
        res = run_one(tmp_path, FaultSiteDriftChecker(), {
            "paddle_tpu/utils/faults.py": self.FAULTS,
            "paddle_tpu/eng.py": """\
                from .utils.faults import fault_point
                fault_point("eng.alpha")
                fault_point("eng.beta")
            """})
        assert res.new == []

    def test_both_drift_directions_and_non_literal(self, tmp_path):
        res = run_one(tmp_path, FaultSiteDriftChecker(), {
            "paddle_tpu/utils/faults.py": self.FAULTS,
            "paddle_tpu/eng.py": """\
                from .utils.faults import fault_point
                SITE = "eng.alpha"
                fault_point(SITE)                 # non-literal
                fault_point("eng.gamma")          # undocumented
            """})
        got = {(f.code, f.detail) for f in res.new}
        # eng.alpha + eng.beta are documented but never called with a
        # literal; eng.gamma is called but undocumented
        assert got == {("PDT003", "non-literal"),
                       ("PDT003", "eng.gamma"),
                       ("PDT003", "eng.alpha"),
                       ("PDT003", "eng.beta")}
        doc_only = [f for f in res.new if f.detail == "eng.alpha"]
        assert doc_only[0].path == "paddle_tpu/utils/faults.py"
        assert doc_only[0].line > 0      # anchored at the docstring row


# -- PDT008 fault-site coverage ----------------------------------------
class TestFaultCoverage:
    FAULTS = '''\
        """Fault sites: ``eng.alpha``, ``eng.beta`` and ``eng.gamma``."""
        def fault_point(site):
            pass
    '''

    def _run(self, tmp_path, tests):
        files = {"paddle_tpu/utils/faults.py": self.FAULTS}
        files.update(tests)
        project = make_project(tmp_path, files)
        # fixture projects scan paddle_tpu/ only, like the CLI — the
        # checker must find the tests tree from the repo root itself
        return run_checkers(project, [FaultCoverageChecker()])

    def test_all_sites_armed_is_clean(self, tmp_path):
        res = self._run(tmp_path, {"tests/test_x.py": """\
            def test_a(fi):
                fi.arm("eng.alpha", nth=1)
                fi.arm_corrupt("eng.beta", always=True)
            def test_b(run):
                run(fault=("eng.gamma", dict(nth=2)))
                arm = True    # a helper file still needs a real armer
                fi.arm("eng.alpha", always=True)
            """})
        assert res.new == []

    def test_unarmed_site_is_a_finding(self, tmp_path):
        res = self._run(tmp_path, {"tests/test_x.py": """\
            def test_a(fi):
                fi.arm("eng.alpha", nth=1)
            """})
        got = {(f.code, f.detail) for f in res.new}
        assert got == {("PDT008", "eng.beta"), ("PDT008", "eng.gamma")}
        f = res.new[0]
        assert f.path == "paddle_tpu/utils/faults.py"
        assert f.line > 0            # anchored at the docstring row

    def test_docstring_mention_does_not_count(self, tmp_path):
        """A site named only in a test DOCSTRING is description, not a
        drill — and a bare literal in a file with no armer at all
        counts for nothing either."""
        res = self._run(tmp_path, {
            "tests/test_doc.py": '''\
                """This file talks about ``eng.beta`` at length."""
                def test_a(fi):
                    fi.arm("eng.alpha", nth=1)
                def test_b():
                    """eng.gamma is mentioned here too."""
            ''',
            "tests/helpers.py": """\
                SITE = "eng.gamma"    # no arm() anywhere in this file
            """})
        got = {f.detail for f in res.new}
        assert got == {"eng.beta", "eng.gamma"}

    def test_literal_in_armer_file_counts(self, tmp_path):
        """The tuple-indirection idiom test_chaos.py actually uses:
        the site literal rides a helper argument, the arm() call sits
        in the helper — same file, both present, covered."""
        res = self._run(tmp_path, {"tests/test_spec.py": """\
            def _run(fi, fault):
                fi.arm(fault[0], **fault[1])
            def test_a(fi):
                _run(fi, ("eng.alpha", dict(nth=2)))
                _run(fi, ("eng.beta", dict(always=True)))
                _run(fi, ("eng.gamma", dict(nth=1)))
            """})
        assert res.new == []

    def test_teeth_real_registry_fails_with_empty_test_tree(
            self, tmp_path):
        """Teeth: the REAL faults.py docstring against an empty test
        tree — every documented site must fire, proving the checker
        actually reads the live registry (a broken collector would
        silently pass everything)."""
        real = open(os.path.join(
            REPO, "paddle_tpu", "utils", "faults.py")).read()
        project = make_project(tmp_path, {
            "paddle_tpu/utils/faults.py": real,
            "tests/test_empty.py": "def test_nothing():\n    pass\n"})
        res = run_checkers(project, [FaultCoverageChecker()])
        from paddle_tpu.analysis.checkers.faultsites import (
            collect_doc_sites)
        sites = collect_doc_sites(
            project, FaultCoverageChecker.DEFAULT_FAULTS_FILE)
        assert sites and {f.detail for f in res.new} == sites


# -- PDT004 catalog drift ----------------------------------------------
class TestCatalogDrift:
    DOC = """\
        # Observability
        | Metric | Meaning |
        |---|---|
        | `pdt_x_total` | documented |
        | `pdt_ghost_total` | registered nowhere |

        Spans: `eng.work` and the documented-only `eng.phantom`.
    """

    def test_all_four_drift_directions(self, tmp_path):
        project = make_project(tmp_path, {
            "docs/observability.md": self.DOC,
            "paddle_tpu/eng.py": """\
                import paddle_tpu.observability as telemetry
                A = telemetry.counter("pdt_x_total", "doc'd")
                B = telemetry.gauge("pdt_unlisted", "undocumented")
                def f():
                    with telemetry.span("eng.work"):
                        pass
                    telemetry.event("eng.secret")   # not in the doc
            """})
        res = run_checkers(project, [CatalogDriftChecker()])
        got = {(f.code, f.detail) for f in res.new}
        assert got == {("PDT004", "pdt_unlisted"),
                       ("PDT004", "pdt_ghost_total"),
                       ("PDT004", "eng.secret"),
                       ("PDT004", "eng.phantom")}
        doc_anchored = {f.detail: f.path for f in res.new}
        assert doc_anchored["pdt_ghost_total"] == "docs/observability.md"
        assert doc_anchored["pdt_unlisted"] == "paddle_tpu/eng.py"

    def test_missing_doc_is_a_finding(self, tmp_path):
        project = make_project(tmp_path, {
            "paddle_tpu/eng.py": "X = 1\n"})
        res = run_checkers(project, [CatalogDriftChecker()])
        assert [f.detail for f in res.new] == ["missing-doc"]


# -- PDT005 pin/decref pairing -----------------------------------------
class TestPinPairing:
    def test_unguarded_pin_across_reserve(self, tmp_path):
        res = run_one(tmp_path, PinPairingChecker(), {
            "paddle_tpu/serving/eng.py": """\
                class E:
                    def bad(self, req, shared):
                        for p in shared:
                            self._incref(p)
                        return self._reserve_ok(req)     # finding

                    def good(self, req, shared):
                        for p in shared:
                            self._incref(p)
                        try:
                            return self._reserve_ok(req)
                        except BaseException:
                            for p in shared:
                                self._decref(p)
                            raise

                    def pin_after(self, req, shared):
                        ok = self._reserve_ok(req)       # pin AFTER:
                        self._incref(shared[0])          # fine
                        return ok
            """})
        assert codes(res) == ["PDT005"]
        assert res.new[0].symbol == "E.bad"
        assert res.new[0].detail == "pin-across:_reserve_ok"

    def test_claim_caller_needs_finally_decref(self, tmp_path):
        res = run_one(tmp_path, PinPairingChecker(), {
            "paddle_tpu/models/serving.py": """\
                class E:
                    def bad_caller(self, free):
                        claim = self._claim_candidate(free)  # finding
                        self.dispatch(claim)

                    def good_caller(self, free):
                        slot, req, prompt, shared = \\
                            self._claim_candidate(free)
                        try:
                            self.dispatch(slot)
                        finally:
                            for p in shared or ():
                                self._decref(p)
            """})
        assert codes(res) == ["PDT005"]
        assert res.new[0].symbol == "E.bad_caller"
        assert res.new[0].detail == "claim:_claim_candidate"

    def test_unrelated_earlier_finally_does_not_cover(self, tmp_path):
        res = run_one(tmp_path, PinPairingChecker(), {
            "paddle_tpu/models/serving.py": """\
                class E:
                    def sneaky(self, free):
                        try:
                            self.warmup()
                        finally:
                            self._decref(0)     # unrelated, BEFORE
                        claim = self._claim_candidate(free)  # finding
                        self.dispatch(claim)
            """})
        assert codes(res) == ["PDT005"]
        assert res.new[0].symbol == "E.sneaky"


# -- PDT006 swallowed supervision errors -------------------------------
class TestSwallowedErrors:
    def test_swallows_and_bare_except(self, tmp_path):
        res = run_one(tmp_path, SwallowedErrorChecker(), {
            "paddle_tpu/serving/router.py": """\
                class R:
                    def a(self):
                        try:
                            self.step()
                        except Exception:
                            return 0              # finding: swallow

                    def b(self):
                        try:
                            self.step()
                        except:                   # finding: bare
                            self.note_failure()

                    def c(self):
                        try:
                            self.step()
                        except Exception as e:
                            self.note_failure(e)  # charged: fine

                    def d(self):
                        try:
                            self.step()
                        except ValueError:
                            pass                  # typed: fine

                    def e(self):
                        try:
                            self.step()
                        except BaseException:
                            raise                 # re-raise: fine
            """})
        assert [(f.code, f.detail) for f in res.new] == [
            ("PDT006", "swallow"), ("PDT006", "bare-except")]
        assert res.new[0].symbol == "R.a"


# -- PDT007 durable-write discipline -----------------------------------
class TestDurableWrite:
    def test_write_opens_flagged_reads_not(self, tmp_path):
        res = run_one(tmp_path, DurableWriteChecker(), {
            "paddle_tpu/serving/state_store.py": """\
                import io
                import os
                import json

                def bad_w(path, doc):
                    with open(path, "w") as f:       # finding
                        json.dump(doc, f)

                def bad_append(path, line):
                    io.open(path, mode="ab").write(line)  # finding

                def bad_fd(path):
                    return os.open(path, os.O_WRONLY)     # finding

                def bad_pathlib(p, doc):
                    p.write_text(doc)                # finding

                def bad_opaque(path, mode):
                    return open(path, mode)          # finding: opaque

                def good_read(path):
                    with open(path) as f:            # read: fine
                        return f.read()

                def good_read_mode(path):
                    return open(path, "rb").read()   # read: fine
            """})
        assert [(f.code, f.detail) for f in res.new] == [
            ("PDT007", "open:w"), ("PDT007", "open:ab"),
            ("PDT007", "os.open"), ("PDT007", "write_text"),
            ("PDT007", "non-literal-mode")]

    def test_journal_is_allowlisted_other_files_are_not(self, tmp_path):
        files = {
            "paddle_tpu/serving/journal.py": """\
                def appender(path, blob):
                    with open(path, "ab") as f:      # the appender
                        f.write(blob)
            """,
            "paddle_tpu/serving/prefix_store.py": """\
                def spill(path, blob):
                    with open(path, "wb") as f:      # finding
                        f.write(blob)
            """,
        }
        res = run_one(tmp_path, DurableWriteChecker(), files)
        assert [(f.code, f.path) for f in res.new] == [
            ("PDT007", "paddle_tpu/serving/prefix_store.py")]

    def test_scope_is_serving_only(self, tmp_path):
        res = run_one(tmp_path, DurableWriteChecker(), {
            "paddle_tpu/distributed/checkpoint/manifest.py": """\
                def write(path, doc):
                    with open(path, "w") as f:   # not serving/: fine
                        f.write(doc)
            """})
        assert res.new == []


# -- PDT009 resize-intent ----------------------------------------------
class TestResizeIntent:
    def test_undominated_mutation_flagged(self, tmp_path):
        res = run_one(tmp_path, ResizeIntentChecker(), {
            "paddle_tpu/serving/router.py": """\
                def hot_scale(self, n):
                    self._topology_grow(n, [])       # finding: no intent
                    self._note_resize(n)
            """})
        assert [(f.code, f.detail) for f in res.new] == [
            ("PDT009", "hot_scale:_topology_grow")]

    def test_intent_dominated_mutation_passes(self, tmp_path):
        res = run_one(tmp_path, ResizeIntentChecker(), {
            "paddle_tpu/serving/router.py": """\
                def resize(self, n):
                    self.journal.append_resize_intent(1, {})
                    self._apply_topology(n, [], None, False)
                    self.journal.append_resize_commit(1)

                def _rehydrate(self):
                    replay = self.journal.replay()
                    self._topology_recover(replay.topology)
            """})
        assert res.new == []

    def test_mutator_internals_and_late_intent_split(self, tmp_path):
        res = run_one(tmp_path, ResizeIntentChecker(), {
            "paddle_tpu/serving/router.py": """\
                def _apply_topology(self, n):
                    self._topology_shrink(n)     # inside the family: ok
                    self._topology_set_roles([])

                def backwards(self, n):
                    self._topology_recarve(n, [], None)  # finding:
                    self.journal.append_resize_intent(1, {})  # too late
            """})
        assert [(f.code, f.detail) for f in res.new] == [
            ("PDT009", "backwards:_topology_recarve")]

    def test_scope_is_serving_only(self, tmp_path):
        res = run_one(tmp_path, ResizeIntentChecker(), {
            "paddle_tpu/loadgen/driver.py": """\
                def helper(router, n):
                    router._topology_grow(n, [])     # not serving/: fine
            """})
        assert res.new == []


# -- PDT010 model-key ---------------------------------------------------
class TestModelKey:
    def test_adhoc_join_concat_split_flagged(self, tmp_path):
        res = run_one(tmp_path, ModelKeyChecker(), {
            "paddle_tpu/serving/router.py": """\
                def golden_key(self, base, adapter):
                    return f"{base}+{adapter}"       # finding: join

                def budget(self, tenant, model):
                    return tenant + "@" + model      # finding: concat

                def adapter_of(self, mid):
                    return mid.split("+")[1]         # finding: split
            """})
        assert [(f.code, f.detail) for f in res.new] == [
            ("PDT010", "golden_key:join+"),
            ("PDT010", "budget:concat@"),
            ("PDT010", "adapter_of:split+")]

    def test_canonical_helpers_and_constants_pass(self, tmp_path):
        res = run_one(tmp_path, ModelKeyChecker(), {
            "paddle_tpu/serving/router.py": """\
                from .model_store import model_id, split_model_id
                from .admission import budget_key

                DEFAULT = "base+a1"                # constant: not a
                                                   # derivation

                def golden_key(self, base, adapter):
                    return model_id(base, adapter)

                def budget(self, tenant, model):
                    return budget_key(tenant, model)

                def adapter_of(self, mid):
                    return split_model_id(mid)[1]

                def unrelated(self, a, b):
                    return a + b                   # no separator lit
            """})
        assert res.new == []

    def test_helper_homes_exempt_scope_is_serving(self, tmp_path):
        res = run_one(tmp_path, ModelKeyChecker(), {
            # the modules that DEFINE the spelling may spell it
            "paddle_tpu/serving/model_store.py": """\
                def model_id(base, adapter):
                    return f"{base}+{adapter}"
            """,
            "paddle_tpu/serving/admission.py": """\
                def budget_key(tenant, model):
                    return f"{tenant}@{model}"
            """,
            # outside serving/: not this rule's scope
            "paddle_tpu/loadgen/trace.py": """\
                def pick(self, base, adapter):
                    return f"{base}+{adapter}"
            """})
        assert res.new == []


# -- PDT011 harvest-seam ------------------------------------------------
class TestHarvestSeam:
    def test_host_sync_in_decode_path_flagged(self, tmp_path):
        res = run_one(tmp_path, HarvestSeamChecker(), {
            "paddle_tpu/models/serving.py": """\
                import numpy as np
                import jax

                def _decode(self, finished):
                    nxt = self._decode_jit(self._tok)
                    toks = np.asarray(nxt)            # finding: D2H
                    return toks

                def step(self):
                    v = jax.device_get(self._flags)   # finding
                    s = self._count.item()            # finding
                    return v, s
            """})
        assert [(f.code, f.detail) for f in res.new] == [
            ("PDT011", "_decode:numpy.asarray"),
            ("PDT011", "step:jax.device_get"),
            ("PDT011", "step:.item()")]

    def test_seam_functions_and_uploads_pass(self, tmp_path):
        res = run_one(tmp_path, HarvestSeamChecker(), {
            "paddle_tpu/models/serving.py": """\
                import numpy as np
                import jax.numpy as jnp

                def _harvest_pending(self, finished):
                    stacked = np.asarray(self._ring)  # seam: legal

                def quiesce(self):
                    return np.asarray(self._ring)     # seam: legal

                def _decode(self, finished):
                    tok_in = jnp.asarray(self._tok)   # H2D: legal
                    nxt = self._decode_jit(tok_in)
                    i = int(self._tok[0])             # Subscript: legal
                    return nxt, i
            """})
        assert res.new == []

    def test_nested_seam_def_inherits_exemption(self, tmp_path):
        res = run_one(tmp_path, HarvestSeamChecker(), {
            "paddle_tpu/serving/router.py": """\
                import numpy as np

                def step(self):
                    def _harvest_local(h):
                        return np.asarray(h.nxt)      # nested seam: ok
                    out = _harvest_local(self._h)
                    bad = np.asarray(self._dev)       # finding
                    return out, bad
            """})
        assert [(f.code, f.detail) for f in res.new] == [
            ("PDT011", "step:numpy.asarray")]

    def test_scope_is_the_two_hot_loop_files(self, tmp_path):
        res = run_one(tmp_path, HarvestSeamChecker(), {
            # same sync, not a hot-loop file: not this rule's scope
            "paddle_tpu/serving/journal.py": """\
                import numpy as np

                def step(self):
                    return np.asarray(self._dev)
            """,
            # hot-loop file, but not a decode-path function
            "paddle_tpu/models/serving.py": """\
                import numpy as np

                def export_pages(self, rid):
                    return np.asarray(self._kv)
            """})
        assert res.new == []


# -- PDT012 compile-seam ------------------------------------------------
class TestCompileSeam:
    def test_jit_outside_builder_flagged(self, tmp_path):
        res = run_one(tmp_path, CompileSeamChecker(), {
            "paddle_tpu/models/serving.py": """\
                import jax
                from jax.experimental import pallas as pl

                def _decode(self):
                    fn = jax.jit(self._step)          # finding
                    return fn(self._tok)

                def _admit(self, req):
                    k = pl.pallas_call(self._kern)    # finding
                    return k
            """})
        assert [(f.code, f.detail) for f in res.new] == [
            ("PDT012", "_decode:jax.jit"),
            ("PDT012", "_admit:pallas_call")]

    def test_builders_and_seam_pass(self, tmp_path):
        res = run_one(tmp_path, CompileSeamChecker(), {
            "paddle_tpu/models/serving.py": """\
                import jax

                def _build_decode(self):
                    return jax.jit(self._fwd)         # builder: legal

                def _build_ragged_step(self, k):
                    def run(*a):
                        return a
                    return jax.jit(run)               # builder: legal

                def _jit_lru(self, cache, key, build, family="misc"):
                    jit = build()
                    cache[key] = jit                  # the seam: legal
                    return jit

                def _decode_jit_getter(self):
                    self._decode_jit = None           # reset: legal
                    self._decode_jit = \\
                        self._jit_singleton("decode", self._build_decode)
                    return self._decode_jit
            """})
        assert res.new == []

    def test_cache_store_and_raw_slot_flagged(self, tmp_path):
        res = run_one(tmp_path, CompileSeamChecker(), {
            "paddle_tpu/models/serving.py": """\
                import jax

                def _build_prefill(self):
                    return jax.jit(self._fwd)

                def _get_prefill(self, bucket):
                    jit = self._build_prefill()
                    self._prefill_jits[bucket] = jit  # finding: bypass
                    return jit

                def _get_decode(self):
                    self._decode_jit = self._build_decode()  # finding
                    return self._decode_jit
            """})
        assert [(f.code, f.detail) for f in res.new] == [
            ("PDT012", "_get_prefill:_prefill_jits[]"),
            ("PDT012", "_get_decode:_decode_jit")]

    def test_scope_is_the_engine_file(self, tmp_path):
        res = run_one(tmp_path, CompileSeamChecker(), {
            # jit outside the engine file: not this rule's scope
            "paddle_tpu/models/llama.py": """\
                import jax

                def generate(self, ids):
                    return jax.jit(self._fwd)(ids)
            """})
        assert res.new == []


# -- suppressions -------------------------------------------------------
class TestSuppressions:
    FILES = {
        "paddle_tpu/serving/x.py": """\
            import time

            def f():
                return time.time()  # pdt-lint: disable=PDT001 demo why
        """}

    def test_suppression_with_reason_masks(self, tmp_path):
        res = run_one(tmp_path, InjectableClockChecker(), self.FILES)
        assert res.new == [] and res.meta == []
        assert len(res.suppressed) == 1
        f, s = res.suppressed[0]
        assert f.code == "PDT001" and s.reason == "demo why"

    def test_comment_above_covers_next_code_line(self, tmp_path):
        res = run_one(tmp_path, InjectableClockChecker(), {
            "paddle_tpu/serving/x.py": """\
                import time

                def f():
                    # pdt-lint: disable=PDT001 measured wall time on
                    # purpose (continuation comments are fine)
                    return time.time()
            """})
        assert res.new == [] and res.meta == []
        assert len(res.suppressed) == 1

    def test_reason_is_mandatory(self, tmp_path):
        res = run_one(tmp_path, InjectableClockChecker(), {
            "paddle_tpu/serving/x.py": """\
                import time

                def f():
                    return time.time()  # pdt-lint: disable=PDT001
            """})
        # the finding survives AND the reasonless comment is reported
        assert codes(res) == ["PDT001"]
        assert [(m.code, m.detail) for m in res.meta] == [
            ("PDT000", "malformed-suppression")]
        assert res.failed

    def test_unparseable_directive_reported(self, tmp_path):
        res = run_one(tmp_path, InjectableClockChecker(), {
            "paddle_tpu/serving/x.py": """\
                import time

                def f():
                    return time.time()  # pdt-lint: disable=pdt001 x
            """})
        # lowercase code: the disable ATTEMPT parses as nothing — it
        # must not rot silently NOR suppress
        assert codes(res) == ["PDT001"]
        assert [(m.code, m.detail) for m in res.meta] == [
            ("PDT000", "malformed-suppression")]

    def test_docstring_mention_is_inert(self, tmp_path):
        res = run_one(tmp_path, InjectableClockChecker(), {
            "paddle_tpu/serving/x.py": '''\
                """Docs may quote a directive verbatim:

                    # pdt-lint: disable=PDT001 quoted example

                without suppressing anything or reading as stale."""
                X = 1
            '''})
        assert res.new == [] and res.meta == [] and not res.suppressed

    def test_unused_suppression_reported(self, tmp_path):
        res = run_one(tmp_path, InjectableClockChecker(), {
            "paddle_tpu/serving/x.py": """\
                X = 1  # pdt-lint: disable=PDT001 nothing here anymore
            """})
        assert [(m.code, m.detail) for m in res.meta] == [
            ("PDT000", "unused-suppression")]
        assert res.failed

    def test_wrong_code_does_not_mask(self, tmp_path):
        res = run_one(tmp_path, InjectableClockChecker(), {
            "paddle_tpu/serving/x.py": """\
                import time

                def f():
                    return time.time()  # pdt-lint: disable=PDT006 nope
            """})
        assert codes(res) == ["PDT001"]
        # and the PDT006 suppression is unused on top
        assert [m.detail for m in res.meta] == ["unused-suppression"]

    def test_ignore_suppressions_mode(self, tmp_path):
        res = run_one(tmp_path, InjectableClockChecker(), self.FILES,
                      respect_suppressions=False)
        assert codes(res) == ["PDT001"] and res.suppressed == []


# -- baseline -----------------------------------------------------------
class TestBaseline:
    FILES = {
        "paddle_tpu/serving/x.py": """\
            import time

            def f():
                return time.time()
        """}
    FP = "PDT001:paddle_tpu/serving/x.py:f:time.time"

    def test_baselined_finding_passes(self, tmp_path):
        bl = Baseline({self.FP: {"count": 1, "reason": "legacy"}})
        res = run_one(tmp_path, InjectableClockChecker(), self.FILES,
                      baseline=bl)
        assert res.new == [] and len(res.baselined) == 1
        assert not res.failed

    def test_second_occurrence_is_new(self, tmp_path):
        files = {"paddle_tpu/serving/x.py": """\
            import time

            def f():
                a = time.time()
                b = time.time()
                return a, b
        """}
        bl = Baseline({self.FP: {"count": 1, "reason": "legacy"}})
        res = run_one(tmp_path, InjectableClockChecker(), files,
                      baseline=bl)
        assert len(res.baselined) == 1 and codes(res) == ["PDT001"]
        assert res.failed

    def test_stale_entry_fails_shrink_only(self, tmp_path):
        bl = Baseline({self.FP: {"count": 1, "reason": "legacy"},
                       "PDT006:paddle_tpu/serving/gone.py:R.f:swallow":
                           {"count": 1, "reason": "stale"}})
        res = run_one(tmp_path, InjectableClockChecker(), self.FILES,
                      baseline=bl)
        assert res.new == []
        assert res.stale_baseline == [
            "PDT006:paddle_tpu/serving/gone.py:R.f:swallow"]
        assert res.failed

    def test_fingerprints_survive_line_shifts(self, tmp_path):
        shifted = {"paddle_tpu/serving/x.py": """\
            import time

            # a new comment block pushed every line number down
            # by a few lines — the fingerprint must not care

            def f():
                return time.time()
        """}
        bl = Baseline({self.FP: {"count": 1, "reason": "legacy"}})
        res = run_one(tmp_path, InjectableClockChecker(), shifted,
                      baseline=bl)
        assert not res.failed and len(res.baselined) == 1


# -- CLI ----------------------------------------------------------------
class TestCli:
    def _tree(self, tmp_path, dirty=True, baseline=None):
        files = {"paddle_tpu/serving/x.py": (
            "import time\n\ndef f():\n    return time.time()\n"
            if dirty else "def f():\n    return 0\n"),
            # the fixture registers no instruments, so the minimal
            # catalog of record is an empty one (its absence would be
            # a PDT004 finding by design)
            "docs/observability.md": "# Observability\n"}
        make_project(tmp_path, files)
        if baseline is not None:
            (tmp_path / BASELINE_NAME).write_text(json.dumps(baseline))
        return tmp_path

    def test_exit_codes(self, tmp_path, capsys):
        root = self._tree(tmp_path, dirty=True)
        assert cli_main([str(root / "paddle_tpu"),
                         "--root", str(root)]) == 1
        assert "PDT001" in capsys.readouterr().out
        clean = self._tree(tmp_path / "clean", dirty=False)
        assert cli_main([str(clean / "paddle_tpu"),
                         "--root", str(clean)]) == 0
        assert cli_main(["/no/such/path"]) == 2
        assert cli_main([str(root / "paddle_tpu"), "--root", str(root),
                         "--checker", "PDT999"]) == 2

    def test_json_schema(self, tmp_path, capsys):
        root = self._tree(tmp_path, dirty=True)
        rc = cli_main([str(root / "paddle_tpu"), "--root", str(root),
                       "--format", "json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 1 and doc["version"] == 1
        assert set(doc) == {"version", "findings", "baselined",
                            "suppressed", "stale_baseline", "summary"}
        (f,) = [x for x in doc["findings"] if x["code"] == "PDT001"]
        assert set(f) == {"code", "path", "line", "col", "symbol",
                          "message", "detail", "checker", "fingerprint"}
        assert f["path"] == "paddle_tpu/serving/x.py"
        assert doc["summary"]["failed"] is True
        assert doc["summary"]["new"] == 1

    def test_baseline_makes_dirty_tree_pass(self, tmp_path):
        fp = "PDT001:paddle_tpu/serving/x.py:f:time.time"
        root = self._tree(tmp_path, dirty=True, baseline={
            "version": 1,
            "findings": {fp: {"count": 1, "reason": "legacy"}}})
        assert cli_main([str(root / "paddle_tpu"),
                         "--root", str(root)]) == 0
        # --no-baseline shows the raw finding again
        assert cli_main([str(root / "paddle_tpu"), "--root", str(root),
                         "--no-baseline"]) == 1

    def test_update_baseline_shrinks_never_adds(self, tmp_path,
                                                capsys):
        fp_live = "PDT001:paddle_tpu/serving/x.py:f:time.time"
        fp_gone = "PDT006:paddle_tpu/serving/gone.py:R.f:swallow"
        root = self._tree(tmp_path, dirty=True, baseline={
            "version": 1,
            "findings": {fp_live: {"count": 1, "reason": "keep"},
                         fp_gone: {"count": 1, "reason": "stale"}}})
        # stale entry fails the plain run (shrink-only enforcement)
        assert cli_main([str(root / "paddle_tpu"),
                         "--root", str(root)]) == 1
        assert cli_main([str(root / "paddle_tpu"), "--root", str(root),
                         "--update-baseline"]) == 0
        doc = json.loads((root / BASELINE_NAME).read_text())
        assert list(doc["findings"]) == [fp_live]       # shrunk
        assert doc["findings"][fp_live]["reason"] == "keep"
        # a NEW finding is never absorbed: growing the tree fails even
        # with --update-baseline
        (root / "paddle_tpu" / "serving" / "y.py").write_text(
            "import time\nT = time.monotonic()\n")
        assert cli_main([str(root / "paddle_tpu"), "--root", str(root),
                         "--update-baseline"]) == 1
        doc2 = json.loads((root / BASELINE_NAME).read_text())
        assert list(doc2["findings"]) == [fp_live]      # not grown

    def test_update_baseline_json_stdout_stays_machine_pure(
            self, tmp_path, capsys):
        fp = "PDT001:paddle_tpu/serving/x.py:f:time.time"
        root = self._tree(tmp_path, dirty=True, baseline={
            "version": 1,
            "findings": {fp: {"count": 1, "reason": "keep"}}})
        rc = cli_main([str(root / "paddle_tpu"), "--root", str(root),
                       "--update-baseline", "--format", "json"])
        out = capsys.readouterr().out
        doc = json.loads(out)       # status lines go to stderr only
        assert rc == 0 and doc["summary"]["baselined"] == 1

    def test_list_checkers(self, capsys):
        assert cli_main(["--list-checkers"]) == 0
        out = capsys.readouterr().out
        for code in ("PDT001", "PDT002", "PDT003", "PDT004", "PDT005",
                     "PDT006", "PDT007"):
            assert code in out

    def test_unparseable_file_is_a_finding(self, tmp_path, capsys):
        root = self._tree(tmp_path, dirty=False)
        (root / "paddle_tpu" / "serving" / "broken.py").write_text(
            "def f(:\n")
        assert cli_main([str(root / "paddle_tpu"),
                         "--root", str(root)]) == 1
        assert "unparseable" in capsys.readouterr().out


# -- the tier-1 repo gate ----------------------------------------------
class TestRepoGate:
    def test_repo_is_clean_vs_baseline(self):
        """THE drift gate: the tree must be clean against the
        committed baseline — new findings, suppression-hygiene
        violations, and stale baseline entries all fail tier-1."""
        res = lint_repo(REPO)
        assert not res.failed, (
            "pdt-lint gate: "
            + "; ".join([f.render() for f in res.new + res.meta]
                        + [f"stale baseline: {fp}"
                           for fp in res.stale_baseline]))

    def test_every_opt_out_masks_a_live_finding(self):
        """Removing ANY committed suppression or baseline entry must
        reproduce its finding: every opt-out corresponds to a finding
        the raw (no-policy) run still produces."""
        policy = lint_repo(REPO)
        raw = lint_repo(REPO, respect_suppressions=False,
                        use_baseline=False)
        raw_fps = [f.fingerprint for f in raw.new]
        for f, s in policy.suppressed:
            assert f.fingerprint in raw_fps, (
                f"suppression at {s.path}:{s.line} masks nothing")
        bl = Baseline.load(os.path.join(REPO, BASELINE_NAME))
        assert bl.entries, "committed baseline unexpectedly empty"
        for fp, ent in bl.entries.items():
            assert ent["reason"], f"baseline entry {fp} needs a reason"
            assert raw_fps.count(fp) >= ent["count"], (
                f"stale baseline entry {fp}")
        # and the policy run accounts for every raw finding
        assert len(raw.new) == (len(policy.suppressed)
                                + len(policy.baselined)
                                + len(policy.new))

    def test_known_defect_classes_are_guarded(self):
        """The rules that found this PR's live defects keep their
        teeth: strip the fix from a COPY of the source and the checker
        must fire again (regression-proof for the checker itself)."""
        import re as _re
        src = open(os.path.join(
            REPO, "paddle_tpu", "serving", "transfer.py")).read()
        broken = src.replace("t0 = clock()", "t0 = time.perf_counter()")
        assert broken != src
        res = self._lint_snippet(
            "paddle_tpu/serving/transfer.py", broken,
            InjectableClockChecker())
        assert "PDT001" in [f.code for f in res.new]
        rsrc = open(os.path.join(
            REPO, "paddle_tpu", "serving", "router.py")).read()
        rbroken = _re.sub(
            r"except Exception as e:\n(\s+)# best-effort[\s\S]*?"
            r"return 0",
            "except Exception:\n\\1return 0", rsrc, count=1)
        assert rbroken != rsrc
        res = self._lint_snippet("paddle_tpu/serving/router.py",
                                 rbroken, SwallowedErrorChecker())
        assert "PDT006" in [f.code for f in res.new]
        # PDT007 teeth: the journal's OWN writes are legal only via
        # the allowlist — the identical source at any other serving/
        # path fires, so the appender cannot be cargo-culted
        jsrc = open(os.path.join(
            REPO, "paddle_tpu", "serving", "journal.py")).read()
        res = self._lint_snippet("paddle_tpu/serving/journal2.py",
                                 jsrc, DurableWriteChecker())
        assert "PDT007" in [f.code for f in res.new]

    def _lint_snippet(self, relpath, source, checker, tmp=None):
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, relpath)
            os.makedirs(os.path.dirname(p), exist_ok=True)
            with open(p, "w") as f:
                f.write(source)
            with open(os.path.join(td, "pyproject.toml"), "w") as f:
                f.write("[project]\n")
            project = Project(td, [os.path.join(td, "paddle_tpu")])
            return run_checkers(project, [checker])

    def test_registry_is_complete(self):
        assert sorted(by_code()) == ["PDT001", "PDT002", "PDT003",
                                     "PDT004", "PDT005", "PDT006",
                                     "PDT007", "PDT008", "PDT009",
                                     "PDT010", "PDT011", "PDT012"]
        assert len(default_checkers(["PDT003", "PDT004"])) == 2
        with pytest.raises(ValueError):
            default_checkers(["PDT777"])
