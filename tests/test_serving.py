"""Serving path (L10): KV-cache generation, masked_multihead_attention,
paged attention. ≙ SURVEY.md §1 L10 + §7 step 6; VERDICT r2 item 3."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nn import functional as F
from paddle_tpu.ops.paged_attention import (PagedKVCache,
                                            paged_attention_values)


def _mha_oracle(q, k, v, seq_len):
    """NumPy decode attention oracle: q (B,1,H,D), cache (B,T,HK,D)."""
    b, s, h, d = q.shape
    hk = k.shape[2]
    g = h // hk
    q = q.astype(np.float32).reshape(b, s, hk, g, d)
    k = k.astype(np.float32)
    v = v.astype(np.float32)
    logits = np.einsum("bskgd,btkd->bkgst", q, k) / np.sqrt(d)
    t = k.shape[1]
    mask = np.arange(t)[None, :] <= (seq_len - s + np.arange(s))[:, None]
    logits = np.where(mask[None, None, None], logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bkgst,btkd->bskgd", p, v).reshape(b, s, h, d)


class TestMaskedMHA:
    @pytest.mark.parametrize("hk", [4, 2])
    def test_matches_oracle(self, hk):
        rng = np.random.default_rng(0)
        b, t, h, d = 2, 32, 4, 16
        q = rng.standard_normal((b, 1, h, d)).astype(np.float32)
        k = rng.standard_normal((b, t, hk, d)).astype(np.float32)
        v = rng.standard_normal((b, t, hk, d)).astype(np.float32)
        seq_len = 20
        out = F.masked_multihead_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            seq_len=seq_len)
        ref = _mha_oracle(q, k, v, seq_len)
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=1e-4, atol=1e-5)

    def test_traced_seq_len(self):
        rng = np.random.default_rng(1)
        b, t, h, d = 1, 16, 2, 8
        q = rng.standard_normal((b, 1, h, d)).astype(np.float32)
        k = rng.standard_normal((b, t, h, d)).astype(np.float32)
        v = rng.standard_normal((b, t, h, d)).astype(np.float32)

        def fn(sl):
            return F.masked_multihead_attention(
                paddle.to_tensor(q), paddle.to_tensor(k),
                paddle.to_tensor(v), seq_len=paddle.Tensor(sl))._value
        out = jax.jit(fn)(jnp.int32(10))
        ref = _mha_oracle(q, k, v, 10)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)


class TestPagedAttention:
    def _setup(self, b=3, h=4, hk=2, d=16, page=8, pps=4, seed=0):
        rng = np.random.default_rng(seed)
        n_pages = b * pps + 2
        q = rng.standard_normal((b, h, d)).astype(np.float32)
        k_pages = rng.standard_normal((hk, n_pages, page, d)).astype(
            np.float32)
        v_pages = rng.standard_normal((hk, n_pages, page, d)).astype(
            np.float32)
        # distinct non-contiguous pages per sequence
        perm = rng.permutation(n_pages)[:b * pps]
        block_tables = perm.reshape(b, pps).astype(np.int32)
        context_lens = rng.integers(1, page * pps + 1, (b,)).astype(
            np.int32)
        return q, k_pages, v_pages, context_lens, block_tables

    def _oracle(self, q, k_pages, v_pages, context_lens, block_tables):
        b, h, d = q.shape
        hk, _, page, _ = k_pages.shape
        pps = block_tables.shape[1]
        outs = []
        for i in range(b):
            kc = k_pages[:, block_tables[i]].reshape(hk, pps * page, d)
            vc = v_pages[:, block_tables[i]].reshape(hk, pps * page, d)
            kc = np.swapaxes(kc, 0, 1)[None]   # (1, T, HK, D)
            vc = np.swapaxes(vc, 0, 1)[None]
            o = _mha_oracle(q[i][None, None], kc, vc,
                            int(context_lens[i]))
            outs.append(o[0, 0])
        return np.stack(outs)

    def test_matches_oracle(self):
        args = self._setup()
        out = paged_attention_values(*[jnp.asarray(a) for a in args])
        ref = self._oracle(*args)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_gqa_and_min_context(self):
        args = self._setup(b=2, h=8, hk=2, d=32, page=16, pps=2, seed=3)
        q, kp, vp, cl, bt = args
        cl = np.array([1, 32], np.int32)  # one-token and full contexts
        out = paged_attention_values(jnp.asarray(q), jnp.asarray(kp),
                                     jnp.asarray(vp), jnp.asarray(cl),
                                     jnp.asarray(bt))
        ref = self._oracle(q, kp, vp, cl, bt)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_sliding_window_matches_truncated_oracle(self):
        """window=w must equal full attention over only the last w keys
        (band semantics of the kernel / XLA gather path)."""
        args = self._setup(b=3, h=4, hk=2, d=16, page=8, pps=4, seed=6)
        q, kp, vp, cl, bt = args
        cl = np.array([5, 20, 32], np.int32)
        w = 12
        out = paged_attention_values(jnp.asarray(q), jnp.asarray(kp),
                                     jnp.asarray(vp), jnp.asarray(cl),
                                     jnp.asarray(bt), window=w)
        # oracle: re-gather each sequence keeping only [ctx-w, ctx)
        b_, h, d = q.shape
        hk, _, page, _ = kp.shape
        pps = bt.shape[1]
        outs = []
        for i in range(b_):
            kc = kp[:, bt[i]].reshape(hk, pps * page, d)
            vc = vp[:, bt[i]].reshape(hk, pps * page, d)
            lo = max(0, int(cl[i]) - w)
            kc = np.swapaxes(kc[:, lo:cl[i]], 0, 1)[None]
            vc = np.swapaxes(vc[:, lo:cl[i]], 0, 1)[None]
            o = _mha_oracle(q[i][None, None], kc, vc, int(cl[i]) - lo)
            outs.append(o[0, 0])
        np.testing.assert_allclose(np.asarray(out), np.stack(outs),
                                   rtol=1e-4, atol=1e-5)

    def test_cache_append(self):
        b, hk, d, page = 2, 2, 8, 4
        cache = PagedKVCache(hk, d, num_pages=8, page_size=page,
                             dtype=jnp.float32)
        bt = jnp.asarray([[0, 1], [2, 3]], jnp.int32)
        k = jnp.ones((b, hk, d))
        v = jnp.full((b, hk, d), 2.0)
        cache = cache.append(k, v, bt, jnp.asarray([0, 5], jnp.int32))
        # seq 0 pos 0 -> page 0 slot 0; seq 1 pos 5 -> page 3 slot 1
        assert float(cache.k_pages[0, 0, 0, 0]) == 1.0
        assert float(cache.v_pages[0, 3, 1, 0]) == 2.0
        assert float(cache.k_pages[0, 0, 1, 0]) == 0.0


class TestGenerate:
    def _model(self, seed=0):
        cfg = LlamaConfig.tiny()
        paddle.seed(seed)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return cfg, m

    @pytest.mark.slow
    def test_greedy_matches_eager_refeed(self):
        """Greedy KV-cache decode == argmax over full re-forward each
        step (the VERDICT 'greedy-decode parity test vs eager forward')."""
        cfg, model = self._model()
        ids = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 12)).astype(np.int32)
        toks, scores = model.generate(paddle.to_tensor(ids),
                                      max_new_tokens=6)
        cur = ids.copy()
        for _ in range(6):
            logits = model(paddle.to_tensor(cur))
            nxt = np.asarray(jnp.argmax(logits._value[:, -1], -1),
                             np.int32)
            cur = np.concatenate([cur, nxt[:, None]], 1)
        np.testing.assert_array_equal(np.asarray(toks._value),
                                      cur[:, 12:])
        assert scores.shape == [2, 6]

    def test_eos_padding(self):
        cfg, model = self._model()
        ids = np.random.default_rng(1).integers(
            0, cfg.vocab_size, (1, 8)).astype(np.int32)
        # find the first greedy token, use it as eos => all later = eos
        toks, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=5)
        first = int(np.asarray(toks._value)[0, 0])
        toks2, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=5,
                                  eos_token_id=first)
        got = np.asarray(toks2._value)[0]
        assert got[0] == first
        assert all(t == first for t in got[1:])

    def test_sampling_reproducible_with_seed(self):
        cfg, model = self._model()
        ids = np.random.default_rng(2).integers(
            0, cfg.vocab_size, (2, 8)).astype(np.int32)
        paddle.seed(42)
        a, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=6,
                              decode_strategy="sampling", top_k=20,
                              temperature=0.9)
        paddle.seed(42)
        b, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=6,
                              decode_strategy="sampling", top_k=20,
                              temperature=0.9)
        np.testing.assert_array_equal(np.asarray(a._value),
                                      np.asarray(b._value))

    def test_top_p_keeps_top_token(self):
        cfg, model = self._model()
        ids = np.random.default_rng(3).integers(
            0, cfg.vocab_size, (1, 8)).astype(np.int32)
        # top_p -> 0 degenerates to greedy
        greedy, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=4)
        samp, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                                 decode_strategy="sampling", top_p=1e-9)
        np.testing.assert_array_equal(np.asarray(greedy._value),
                                      np.asarray(samp._value))

    def test_cache_overflow_raises(self):
        cfg, model = self._model()
        ids = np.zeros((1, 8), np.int32)
        with pytest.raises(ValueError):
            model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                           max_cache_len=10)

    def test_chunked_prefill_matches_full(self):
        """Two-chunk prefill through the cache == one-shot prefill
        (exercises the end-aligned causal convention with offset > 0)."""
        cfg, model = self._model()
        rng = np.random.default_rng(4)
        ids = rng.integers(0, cfg.vocab_size, (1, 16)).astype(np.int32)
        hk, hd = cfg.num_key_value_heads, cfg.head_dim
        n_l = cfg.num_hidden_layers
        caches = [(paddle.to_tensor(np.zeros((1, 32, hk, hd), np.float32)),
                   paddle.to_tensor(np.zeros((1, 32, hk, hd), np.float32)))
                  for _ in range(n_l)]
        with paddle.no_grad():
            l1, caches = model(paddle.to_tensor(ids[:, :8]),
                               past_key_values=caches, position_offset=0,
                               use_cache=True)
            l2, caches = model(paddle.to_tensor(ids[:, 8:]),
                               past_key_values=caches, position_offset=8,
                               use_cache=True)
            full = model(paddle.to_tensor(ids))
        np.testing.assert_allclose(
            np.asarray(l2._value[:, -1]),
            np.asarray(full._value[:, -1]), rtol=2e-3, atol=2e-3)


class TestAttentionMaskWithCache:
    def test_padding_mask_excludes_cached_positions(self):
        """Left-padding written into the cache must get zero weight."""
        rng = np.random.default_rng(9)
        b, t, h, d = 2, 16, 2, 8
        q = rng.standard_normal((b, 1, h, d)).astype(np.float32)
        k = rng.standard_normal((b, t, h, d)).astype(np.float32)
        v = rng.standard_normal((b, t, h, d)).astype(np.float32)
        pad = np.ones((b, t), bool)
        pad[0, :4] = False                       # seq 0: first 4 are pad
        out_m = F.masked_multihead_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            seq_len=12, attn_mask=paddle.to_tensor(pad))
        # reference: zero out padded keys by giving them -inf manually
        k2 = k.copy()
        ref = _mha_oracle(q, np.where(pad[:, :, None, None], k, -1e4),
                          v, 12)
        # cheaper check: masked positions have no influence — perturb them
        k_pert = k.copy()
        k_pert[0, :4] += 100.0
        v_pert = v.copy()
        v_pert[0, :4] += 100.0
        out_p = F.masked_multihead_attention(
            paddle.to_tensor(q), paddle.to_tensor(k_pert),
            paddle.to_tensor(v_pert), seq_len=12,
            attn_mask=paddle.to_tensor(pad))
        np.testing.assert_allclose(np.asarray(out_m._value),
                                   np.asarray(out_p._value), atol=1e-6)
        # and unmasked output differs from masked (mask has an effect)
        out_nomask = F.masked_multihead_attention(
            paddle.to_tensor(q), paddle.to_tensor(k_pert),
            paddle.to_tensor(v_pert), seq_len=12)
        assert not np.allclose(np.asarray(out_m._value),
                               np.asarray(out_nomask._value))


class TestGPTGenerate:
    @pytest.mark.slow
    def test_greedy_matches_eager_refeed(self):
        """GPT decode with learned position embeddings + KV cache matches
        argmax over full re-forward each step."""
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        cfg = GPTConfig.tiny() if hasattr(GPTConfig, "tiny") else GPTConfig(
            vocab_size=512, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=128,
            max_position_embeddings=64)
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        model.eval()
        ids = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 10)).astype(np.int32)
        toks, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=5)
        cur = ids.copy()
        for _ in range(5):
            logits = model(paddle.to_tensor(cur))
            nxt = np.asarray(jnp.argmax(logits._value[:, -1], -1),
                             np.int32)
            cur = np.concatenate([cur, nxt[:, None]], 1)
        np.testing.assert_array_equal(np.asarray(toks._value), cur[:, 10:])

    def test_beam_search_runs_on_gpt(self):
        """GenerationMixin strategies are model-family-generic: beam
        search drives GPT (learned position embeddings) unchanged."""
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32,
                        num_hidden_layers=1, num_attention_heads=2,
                        intermediate_size=64, max_position_embeddings=32)
        paddle.seed(4)
        model = GPTForCausalLM(cfg)
        model.eval()
        ids = np.array([[3, 5, 7]], np.int32)
        toks, score = model.generate(paddle.to_tensor(ids),
                                     max_new_tokens=6,
                                     decode_strategy="beam_search",
                                     num_beams=3)
        assert np.asarray(toks._value).shape == (1, 6)
        assert np.isfinite(float(score[0]))


@pytest.mark.slow
class TestContinuousBatching:
    """In-flight batching (VERDICT r3 next #3): slots at different
    positions decode in ONE compiled step; admission reuses freed slots.
    Oracle: per-request generate() greedy outputs."""

    def _model(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        paddle.seed(7)
        cfg = LlamaConfig.tiny()
        m = LlamaForCausalLM(cfg)
        m.eval()
        return m, cfg

    def _ref_greedy(self, m, prompt, n):
        out = m.generate(paddle.to_tensor(
            np.asarray(prompt, np.int32)[None]), max_new_tokens=n,
            decode_strategy="greedy_search")
        t = out[0] if isinstance(out, (tuple, list)) else out
        return [int(x) for x in np.asarray(t._value).ravel()[:n]]

    def test_matches_per_request_greedy(self):
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        m, cfg = self._model()
        rng_ = np.random.default_rng(3)
        prompts = [list(rng_.integers(1, cfg.vocab_size,
                                      rng_.integers(3, 12)))
                   for _ in range(5)]
        lens = [6, 9, 4, 7, 5]
        # max_batch_size 2 < 5 requests: slots MUST be reused in flight
        eng = ContinuousBatchingEngine(m, max_batch_size=2,
                                       max_seq_len=64)
        rids = [eng.add_request(p, n) for p, n in zip(prompts, lens)]
        results = eng.run()
        assert set(results) == set(rids)
        for rid, p, n in zip(rids, prompts, lens):
            ref = self._ref_greedy(m, p, n)
            assert results[rid] == ref, (rid, results[rid], ref)

    def test_mid_flight_admission(self):
        """A request added while others are mid-decode joins without
        disturbing them."""
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        m, cfg = self._model()
        eng = ContinuousBatchingEngine(m, max_batch_size=4,
                                       max_seq_len=64)
        a = eng.add_request([5, 42, 7], 8)
        b = eng.add_request([9, 1, 2, 3, 4], 8)
        done = {}
        for _ in range(3):
            for r in eng.step():
                done[r.rid] = r.output
        c = eng.add_request([11, 13], 6)     # mid-flight
        while len(done) < 3:
            for r in eng.step():
                done[r.rid] = r.output
        assert done[a] == self._ref_greedy(m, [5, 42, 7], 8)
        assert done[b] == self._ref_greedy(m, [9, 1, 2, 3, 4], 8)
        assert done[c] == self._ref_greedy(m, [11, 13], 6)

    def test_eos_frees_slot(self):
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        m, cfg = self._model()
        # discover the greedy continuation, then declare its 2nd token
        # as EOS: the engine must stop that request early
        ref = self._ref_greedy(m, [5, 42, 7], 6)
        eos = ref[1]
        eng = ContinuousBatchingEngine(m, max_batch_size=2,
                                       max_seq_len=64, eos_token_id=eos)
        rid = eng.add_request([5, 42, 7], 6)
        out = eng.run()[rid]
        assert out == ref[:2], (out, ref)

    def test_single_compiled_decode_program(self):
        """The decode step compiles once regardless of slot positions."""
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        m, cfg = self._model()
        eng = ContinuousBatchingEngine(m, max_batch_size=3,
                                       max_seq_len=64)
        for p, n in [([5, 4], 4), ([1, 2, 3, 4, 5, 6, 7], 6),
                     ([9], 5)]:
            eng.add_request(p, n)
        eng.run()
        assert eng._decode_jit is not None
        # jax caches by signature; the step signature never changed
        if not hasattr(eng._decode_jit, "_cache_size"):
            pytest.skip("jax private _cache_size API unavailable — "
                        "single-compilation guarantee unverifiable here")
        assert eng._decode_jit._cache_size() == 1

    def test_prompt_length_validation(self):
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        m, cfg = self._model()
        eng = ContinuousBatchingEngine(m, max_batch_size=2,
                                       max_seq_len=32)
        with pytest.raises(ValueError, match="max_seq_len"):
            eng.add_request(list(range(40)), 4)
        # near-limit prompt: bucket must clamp to the cache, not crash
        rid = eng.add_request(list(np.arange(1, 30) % cfg.vocab_size), 2)
        out = eng.run()[rid]
        assert len(out) == 2


@pytest.mark.slow
class TestPagedEngine:
    """Paged-KV serving engine (VERDICT r4 item 2): block-table cache
    wired into the decode step, occupancy-proportional HBM accounting,
    sampling exposure, page-pool admission control."""

    def _model(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        paddle.seed(7)
        cfg = LlamaConfig.tiny()
        m = LlamaForCausalLM(cfg)
        m.eval()
        return m, cfg

    def test_paged_matches_dense_engine(self):
        """The paged engine's outputs equal the dense engine's (same
        model, same prompts) — the engine-level paged == dense oracle."""
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        m, cfg = self._model()
        rng_ = np.random.default_rng(5)
        prompts = [list(rng_.integers(1, cfg.vocab_size,
                                      rng_.integers(3, 14)))
                   for _ in range(4)]
        lens = [6, 8, 5, 7]
        outs = {}
        for layout in ("paged", "dense"):
            eng = ContinuousBatchingEngine(m, max_batch_size=2,
                                           max_seq_len=64,
                                           kv_layout=layout)
            rids = [eng.add_request(p, n) for p, n in zip(prompts, lens)]
            res = eng.run()
            outs[layout] = [res[r] for r in rids]
        assert outs["paged"] == outs["dense"]

    def test_memory_occupancy_proportional(self):
        """bytes_in_use tracks pages actually allocated, not B*S_max;
        finished requests return their pages."""
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        m, cfg = self._model()
        eng = ContinuousBatchingEngine(m, max_batch_size=4,
                                       max_seq_len=64, kv_layout="paged",
                                       page_size=16)
        info0 = eng.cache_memory_info()
        assert info0["pages_in_use"] == 0 and info0["bytes_in_use"] == 0
        rid = eng.add_request([3, 5, 7], 4)       # 3 tokens -> 1 page
        eng.step()
        info1 = eng.cache_memory_info()
        assert info1["pages_in_use"] >= 1
        assert info1["bytes_in_use"] < info1["bytes_pool"] / 2
        eng.run()
        info2 = eng.cache_memory_info()
        assert info2["pages_in_use"] == 0         # pages reclaimed

    def test_pool_exhaustion_defers_admission(self):
        """A pool too small for two concurrent requests serves them
        SEQUENTIALLY (FIFO), not incorrectly."""
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        m, cfg = self._model()
        # each request worst-cases at ceil((3+6)/16)=1 page; pool of 1
        # usable page forces one-at-a-time admission
        eng = ContinuousBatchingEngine(m, max_batch_size=2,
                                       max_seq_len=64, kv_layout="paged",
                                       page_size=16, num_pages=2)
        a = eng.add_request([5, 42, 7], 6)
        b = eng.add_request([9, 1, 2], 6)
        # after the first step only one request may hold pages
        eng.step()
        active = [r for r in eng._slot_req if r is not None]
        assert len(active) == 1
        res = eng.run()
        ref_a = self._ref(m, [5, 42, 7], 6)
        ref_b = self._ref(m, [9, 1, 2], 6)
        assert res[a] == ref_a and res[b] == ref_b

    def _ref(self, m, prompt, n):
        out = m.generate(paddle.to_tensor(
            np.asarray(prompt, np.int32)[None]), max_new_tokens=n)
        t = out[0] if isinstance(out, (tuple, list)) else out
        return [int(x) for x in np.asarray(t._value).ravel()[:n]]

    def test_sampling_seeded_reproducible(self):
        """do_sample engines with the same seed emit identical streams;
        top_p -> 0 degenerates to greedy."""
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        m, cfg = self._model()
        p = [5, 42, 7, 11]

        def run_once(seed, **kw):
            eng = ContinuousBatchingEngine(m, max_batch_size=2,
                                           max_seq_len=64, seed=seed,
                                           **kw)
            rid = eng.add_request(p, 8)
            return eng.run()[rid]

        s1 = run_once(3, do_sample=True, temperature=0.8, top_k=20)
        s2 = run_once(3, do_sample=True, temperature=0.8, top_k=20)
        s3 = run_once(4, do_sample=True, temperature=0.8, top_k=20)
        assert s1 == s2
        greedy = run_once(0)
        tiny_p = run_once(9, do_sample=True, top_p=1e-9)
        assert tiny_p == greedy
        assert len(s3) == 8

    def test_sliding_window_paged_matches_dense(self):
        """r5: sliding-window models serve on the PAGED layout (window
        band in the paged kernel) — outputs equal the dense-layout
        oracle, and pages that slide out of the window are reclaimed so
        resident KV is bounded by the window, not the sequence."""
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig.tiny()
        cfg.sliding_window = 16
        paddle.seed(0)
        m = LlamaForCausalLM(cfg)
        m.eval()
        rng_ = np.random.default_rng(4)
        prompts = [list(rng_.integers(1, cfg.vocab_size, 9 + 4 * j))
                   for j in range(3)]
        outs = {}
        for layout in ("dense", "paged"):
            eng = ContinuousBatchingEngine(m, max_batch_size=2,
                                           max_seq_len=64, page_size=8,
                                           kv_layout=layout)
            rids = [eng.add_request(p, 30) for p in prompts]
            res = eng.run()
            outs[layout] = [res[r] for r in rids]
        assert outs["paged"] == outs["dense"]
        assert eng.layout == "paged"

    def test_sliding_window_reclaims_pages(self):
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig.tiny()
        cfg.sliding_window = 16
        paddle.seed(0)
        m = LlamaForCausalLM(cfg)
        m.eval()
        eng = ContinuousBatchingEngine(m, max_batch_size=1,
                                       max_seq_len=64, page_size=8)
        eng.add_request(list(range(1, 10)), 40)   # runs to position ~49
        max_in_use = 0
        while eng._queue or any(r is not None for r in eng._slot_req):
            eng.step()
            max_in_use = max(max_in_use,
                             eng.cache_memory_info()["pages_in_use"])
        # window 16 at page 8 -> at most ceil(16/8)+1 = 3 live pages
        # (+1 partial write page) ever resident after reclamation
        assert max_in_use <= 4, max_in_use
        assert all(eng._page_rc[1:] == 0)         # all reclaimed at end

    def test_prefill_program_cache_capped(self):
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        m, cfg = self._model()
        eng = ContinuousBatchingEngine(m, max_batch_size=1,
                                       max_seq_len=64, prompt_pad=4,
                                       max_prefill_programs=2)
        for n_len in (3, 7, 11, 15):
            eng.add_request(list(range(1, n_len + 1)), 2)
        eng.run()
        assert len(eng._prefill_jits) <= 2


class TestPrefixCaching:
    """Automatic prefix caching (VERDICT r4 weak #4: no cross-request
    prefix sharing): a finished request's full-page prompt KV is reused
    read-only by later requests with the same token prefix; only the
    suffix is prefilled. Oracle: an identical engine with caching off."""

    def _model(self):
        paddle.seed(11)
        cfg = LlamaConfig(vocab_size=512, hidden_size=64,
                          intermediate_size=128, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=128)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return m, cfg

    def _run(self, m, prompts, lens, **kw):
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        # batch 1: sequential admission, so earlier requests register
        # their prefixes before later ones are admitted
        eng = ContinuousBatchingEngine(m, max_batch_size=1,
                                       max_seq_len=96, page_size=8,
                                       prompt_pad=8, **kw)
        rids = [eng.add_request(p, n) for p, n in zip(prompts, lens)]
        res = eng.run()
        return [res[r] for r in rids], eng

    @pytest.mark.slow
    def test_hit_outputs_match_uncached(self):
        m, cfg = self._model()
        rng = np.random.default_rng(5)
        base = list(rng.integers(1, cfg.vocab_size, 24))
        prompts = [base + [7, 8, 9],        # registers prefix
                   base + [100, 101],       # hits it (24 = 3 pages)
                   base[:16] + [55, 56, 57, 58],  # shorter-prefix hit
                   list(rng.integers(1, cfg.vocab_size, 10))]  # miss
        lens = [6, 6, 5, 4]
        out_ref, _ = self._run(m, prompts, lens)
        out_cached, eng = self._run(m, prompts, lens,
                                    enable_prefix_caching=True)
        assert out_cached == out_ref
        assert eng.prefix_hits >= 2
        # shared length is power-of-two-page quantized: the 3-page (24
        # token) match attaches 2 pages, the 2-page match attaches both
        assert eng.prefix_tokens_reused >= 16 + 16
        info = eng.cache_memory_info()
        assert info["prefix_entries"] >= 2 and info["prefix_pages"] >= 2

    @pytest.mark.slow
    def test_whole_prompt_cached_still_decodes(self):
        """Prompt == cached prefix: sharing must cap at one page less so
        the suffix prefill still produces first-token logits."""
        m, cfg = self._model()
        base = list(range(1, 17))           # exactly 2 pages of 8
        out_ref, _ = self._run(m, [base, base], [5, 5])
        out_cached, eng = self._run(m, [base, base], [5, 5],
                                    enable_prefix_caching=True)
        assert out_cached == out_ref
        assert eng.prefix_hits == 1
        assert eng.prefix_tokens_reused == 8   # capped below p_len

    @pytest.mark.slow
    def test_eviction_under_pool_pressure(self):
        """Tiny pool: cached pages must be reclaimed (LRU) so new
        requests still admit; outputs stay correct."""
        m, cfg = self._model()
        rng = np.random.default_rng(9)
        prompts = [list(rng.integers(1, cfg.vocab_size, 16))
                   for _ in range(4)]
        lens = [6] * 4
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        eng = ContinuousBatchingEngine(m, max_batch_size=1,
                                       max_seq_len=96, page_size=8,
                                       prompt_pad=8, num_pages=8,
                                       enable_prefix_caching=True)
        rids = [eng.add_request(p, n) for p, n in zip(prompts, lens)]
        res = eng.run()
        ref, _ = self._run(m, prompts, lens)
        assert [res[r] for r in rids] == ref
        # pool accounting sane: every page is free, cached, or trash
        rc = eng._page_rc
        cached = {n["page"] for n in eng._prefix_nodes.values()}
        assert set(eng._free).isdisjoint(cached)
        assert all(rc[p] >= 1 for p in cached)
        assert all(rc[p] == 0 for p in eng._free)

    @pytest.mark.slow
    def test_refcounts_zero_after_cache_clear(self):
        m, cfg = self._model()
        base = list(range(1, 25))
        _, eng = self._run(m, [base, base + [3]], [4, 4],
                           enable_prefix_caching=True)
        while eng._evict_one():
            pass
        assert all(eng._page_rc[1:] == 0)
        assert sorted(eng._free) == list(range(1, eng.num_pages))

    @pytest.mark.slow
    def test_eviction_cannot_reclaim_matched_pages(self):
        """r5 review: _reserve_ok may evict the just-matched entry under
        pool pressure; the matched pages must be pinned so they never
        transit the free list while a slot attaches them."""
        m, cfg = self._model()
        rng = np.random.default_rng(13)
        base = list(rng.integers(1, cfg.vocab_size, 16))  # 2 pages
        others = [list(rng.integers(1, cfg.vocab_size, 16))
                  for _ in range(3)]
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        # pool of 9 usable pages: each 16+6-token request needs 3; the
        # cache fills fast and hit-admissions must evict under pressure
        eng = ContinuousBatchingEngine(m, max_batch_size=1,
                                       max_seq_len=96, page_size=8,
                                       prompt_pad=8, num_pages=10,
                                       enable_prefix_caching=True)
        prompts = [base, others[0], base + [3], others[1],
                   base + [4], others[2], base + [5]]
        rids = [eng.add_request(p, 6) for p in prompts]
        res = eng.run()
        ref, _ = self._run(m, prompts, [6] * len(prompts))
        assert [res[r] for r in rids] == ref
        rc = eng._page_rc
        assert all(rc[p] == 0 for p in eng._free)
        assert len(set(eng._free)) == len(eng._free)   # no double-free

    def test_dense_layout_warns_and_disables(self):
        m, cfg = self._model()
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        with pytest.warns(UserWarning, match="prefix caching is DISABLED"):
            eng = ContinuousBatchingEngine(m, max_batch_size=1,
                                           kv_layout="dense",
                                           max_seq_len=96,
                                           enable_prefix_caching=True)
        rid = eng.add_request([5, 4, 3], 4)
        assert len(eng.run()[rid]) == 4 and eng.prefix_hits == 0


class TestBeamSearch:
    """Scan-native beam search (≙ PaddleNLP decode_strategy='beam_search').
    Exactness oracle: with K >= V^(n_new-1) beams the search is
    exhaustive, so its best score must equal the brute-force maximum
    total log-prob over ALL V^n_new continuations computed by eager full
    re-forwards — this exercises the cache reorder/gather machinery
    end-to-end."""

    def _model(self, vocab=8):
        cfg = LlamaConfig(vocab_size=vocab, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=1,
                          max_position_embeddings=32)
        paddle.seed(23)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return cfg, m

    @pytest.mark.slow
    def test_full_width_beam_finds_global_max(self):
        import itertools
        cfg, m = self._model(vocab=8)
        v, n_new = cfg.vocab_size, 3
        ids = np.array([[3, 1, 4, 1, 5]], np.int32)
        toks, score = m.generate(paddle.to_tensor(ids),
                                 max_new_tokens=n_new,
                                 decode_strategy="beam_search",
                                 num_beams=v * v)    # >= V^(n-1): exhaustive
        # brute force: total logprob of every continuation by re-forward
        best = -np.inf
        best_seq = None
        for seq in itertools.product(range(v), repeat=n_new):
            total, cur = 0.0, ids[0].tolist()
            for tk in seq:
                logits = m(paddle.to_tensor(
                    np.asarray(cur, np.int32)[None]))
                lgp = jax.nn.log_softmax(
                    logits._value[0, -1].astype(jnp.float32))
                total += float(lgp[tk])
                cur.append(tk)
            if total > best:
                best, best_seq = total, seq
        assert abs(float(score[0]) - best) < 1e-3, (float(score[0]), best)
        assert tuple(int(t) for t in np.asarray(toks._value)[0]) == best_seq

    def test_eos_freezes_beams(self):
        cfg, m = self._model(vocab=16)
        eos = 5
        ids = np.array([[2, 7, 9]], np.int32)
        toks, score = m.generate(paddle.to_tensor(ids), max_new_tokens=10,
                                 decode_strategy="beam_search",
                                 num_beams=4, eos_token_id=eos,
                                 length_penalty=0.6)
        seq = [int(t) for t in np.asarray(toks._value)[0]]
        if eos in seq:
            i = seq.index(eos)
            assert all(t == eos for t in seq[i:])
        assert np.isfinite(float(score[0]))

    @pytest.mark.slow
    def test_reported_score_matches_eager_recompute(self):
        """Self-consistency: the returned score (length_penalty=0) must
        equal the returned sequence's actual total log-prob, recomputed
        by eager full re-forwards — catches any cache-reorder or score-
        bookkeeping drift. (Beam width monotonicity is NOT asserted:
        greedy pruning does not guarantee it.)"""
        cfg, m = self._model(vocab=12)
        ids = np.array([[1, 2, 3], [4, 5, 6]], np.int32)
        toks, scores = m.generate(paddle.to_tensor(ids), max_new_tokens=5,
                                  decode_strategy="beam_search",
                                  num_beams=4)
        toks = np.asarray(toks._value)
        for i in range(ids.shape[0]):
            total, cur = 0.0, ids[i].tolist()
            for tk in toks[i]:
                lg = m(paddle.to_tensor(np.asarray(cur, np.int32)[None]))
                total += float(jax.nn.log_softmax(
                    lg._value[0, -1].astype(jnp.float32))[int(tk)])
                cur.append(int(tk))
            assert abs(float(scores._value[i]) - total) < 1e-3, \
                (i, float(scores._value[i]), total)

    def test_rejects_single_beam(self):
        cfg, m = self._model()
        with pytest.raises(ValueError, match="num_beams"):
            m.generate(paddle.to_tensor(np.array([[1]], np.int32)),
                       decode_strategy="beam_search", num_beams=1)


class TestLogitsProcessors:
    """repetition_penalty + min_new_tokens (≙ the reference's
    LogitsProcessor stack in generate). Oracle: an eager re-forward loop
    applying the identical rule."""

    def _model(self):
        cfg = LlamaConfig(vocab_size=32, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=1,
                          max_position_embeddings=32)
        paddle.seed(31)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return cfg, m

    @pytest.mark.slow
    def test_repetition_penalty_matches_eager_rule(self):
        cfg, m = self._model()
        rp, n = 1.8, 6
        ids = np.array([[3, 9, 3]], np.int32)
        toks, _ = m.generate(paddle.to_tensor(ids), max_new_tokens=n,
                             decode_strategy="greedy_search",
                             repetition_penalty=rp)
        got = [int(t) for t in np.asarray(toks._value)[0]]
        # eager oracle
        seen = set(ids[0].tolist())
        cur, want = ids[0].tolist(), []
        for _ in range(n):
            lg = np.array(m(paddle.to_tensor(
                np.asarray(cur, np.int32)[None]))._value[0, -1],
                np.float32)
            for tk in seen:
                lg[tk] = lg[tk] / rp if lg[tk] > 0 else lg[tk] * rp
            nxt = int(np.argmax(lg))
            want.append(nxt)
            seen.add(nxt)
            cur.append(nxt)
        assert got == want, (got, want)
        # and the penalty actually changes the output for this model
        plain, _ = m.generate(paddle.to_tensor(ids), max_new_tokens=n)
        assert got != [int(t) for t in np.asarray(plain._value)[0]]

    def test_min_new_tokens_suppresses_eos(self):
        cfg, m = self._model()
        ids = np.array([[5, 6]], np.int32)
        # pick eos = the unconstrained first greedy token, so generation
        # would otherwise stop immediately
        t0, _ = m.generate(paddle.to_tensor(ids), max_new_tokens=1)
        eos = int(np.asarray(t0._value)[0, 0])
        toks, _ = m.generate(paddle.to_tensor(ids), max_new_tokens=8,
                             eos_token_id=eos, min_new_tokens=4)
        seq = [int(t) for t in np.asarray(toks._value)[0]]
        assert all(t != eos for t in seq[:4]), seq

    def test_beam_repetition_penalty_runs(self):
        cfg, m = self._model()
        ids = np.array([[1, 2]], np.int32)
        toks, score = m.generate(paddle.to_tensor(ids), max_new_tokens=5,
                                 decode_strategy="beam_search",
                                 num_beams=3, repetition_penalty=1.5,
                                 min_new_tokens=2, eos_token_id=7)
        seq = [int(t) for t in np.asarray(toks._value)[0]]
        assert len(seq) == 5 and np.isfinite(float(score[0]))
        assert all(t != 7 for t in seq[:2])


class TestSpeculativeDecoding:
    """Greedy speculative decoding is LOSSLESS: the emitted stream must
    equal target-only greedy exactly, for ANY draft — a random unrelated
    draft (worst case, low acceptance) and the target itself (best case,
    full acceptance)."""

    def _models(self):
        cfg_t = LlamaConfig(vocab_size=64, hidden_size=64,
                            intermediate_size=128, num_hidden_layers=2,
                            num_attention_heads=4, num_key_value_heads=2,
                            max_position_embeddings=64)
        cfg_d = LlamaConfig(vocab_size=64, hidden_size=32,
                            intermediate_size=64, num_hidden_layers=1,
                            num_attention_heads=2, num_key_value_heads=1,
                            max_position_embeddings=64)
        paddle.seed(17)
        t = LlamaForCausalLM(cfg_t)
        paddle.seed(18)
        d = LlamaForCausalLM(cfg_d)
        t.eval(); d.eval()
        return t, d

    @pytest.mark.slow
    def test_lossless_vs_target_greedy_random_draft(self):
        from paddle_tpu.models.speculative import speculative_generate
        t, d = self._models()
        rng = np.random.default_rng(2)
        ids = rng.integers(1, 64, (2, 7)).astype(np.int32)
        n = 12
        want, _ = t.generate(paddle.to_tensor(ids), max_new_tokens=n)
        got, acc = speculative_generate(t, d, paddle.to_tensor(ids),
                                        max_new_tokens=n,
                                        num_draft_tokens=3)
        np.testing.assert_array_equal(np.asarray(got._value),
                                      np.asarray(want._value))
        assert 0.0 <= float(acc) <= 1.0

    @pytest.mark.slow
    def test_self_draft_full_acceptance(self):
        from paddle_tpu.models.speculative import speculative_generate
        t, _ = self._models()
        ids = np.array([[5, 9, 13]], np.int32)
        n = 10
        want, _ = t.generate(paddle.to_tensor(ids), max_new_tokens=n)
        got, acc = speculative_generate(t, t, paddle.to_tensor(ids),
                                        max_new_tokens=n,
                                        num_draft_tokens=4)
        np.testing.assert_array_equal(np.asarray(got._value),
                                      np.asarray(want._value))
        assert float(acc) > 0.95, float(acc)   # target drafts for itself

    def test_eos_stops_early(self):
        from paddle_tpu.models.speculative import speculative_generate
        t, d = self._models()
        ids = np.array([[3, 4]], np.int32)
        w, _ = t.generate(paddle.to_tensor(ids), max_new_tokens=1)
        eos = int(np.asarray(w._value)[0, 0])
        got, _ = speculative_generate(t, d, paddle.to_tensor(ids),
                                      max_new_tokens=8,
                                      num_draft_tokens=3,
                                      eos_token_id=eos)
        seq = [int(x) for x in np.asarray(got._value)[0]]
        assert seq[0] == eos
        assert all(x == 0 for x in seq[1:]), seq   # PAD after EOS

    def test_rejection_sampling_first_token_distribution(self):
        """The Leviathan guarantee, tested directly on _spec_accept:
        whatever the draft q, the first emitted token's marginal must
        equal the target p. 200k vectorized draws vs closed form."""
        from paddle_tpu.models.speculative import _spec_accept
        rng = np.random.default_rng(0)
        V, K, N = 8, 2, 200_000
        p = rng.dirichlet(np.ones(V), size=K + 1)    # target rows
        q = rng.dirichlet(np.ones(V) * 0.4, size=K)  # skewed draft rows
        p_logp = jnp.log(jnp.asarray(p, jnp.float32))[None]
        q_logp = jnp.log(jnp.asarray(q, jnp.float32))[None]

        def one(key):
            kq, ka = jax.random.split(key)
            props = jax.random.categorical(
                kq, q_logp[0], axis=-1).astype(jnp.int32)[None]  # (1, K)
            j, repl = _spec_accept(p_logp, q_logp, props, ka)
            return jnp.where(j[0] >= 1, props[0, 0], repl[0])

        keys = jax.random.split(jax.random.PRNGKey(7), N)
        toks = np.asarray(jax.jit(jax.vmap(one))(keys))
        freq = np.bincount(toks, minlength=V) / N
        np.testing.assert_allclose(freq, p[0], atol=0.006)

    def test_sampling_near_zero_temperature_equals_greedy(self):
        from paddle_tpu.models.speculative import speculative_generate
        t, d = self._models()
        ids = np.array([[4, 8, 15]], np.int32)
        n = 10
        want, _ = t.generate(paddle.to_tensor(ids), max_new_tokens=n)
        got, _ = speculative_generate(t, d, paddle.to_tensor(ids),
                                      max_new_tokens=n,
                                      num_draft_tokens=3, do_sample=True,
                                      temperature=1e-4)
        np.testing.assert_array_equal(np.asarray(got._value),
                                      np.asarray(want._value))

    def test_vocab_mismatch_raises(self):
        from paddle_tpu.models.speculative import speculative_generate
        t, _ = self._models()
        cfg_bad = LlamaConfig(vocab_size=32, hidden_size=32,
                              intermediate_size=64, num_hidden_layers=1,
                              num_attention_heads=2,
                              num_key_value_heads=1,
                              max_position_embeddings=64)
        bad = LlamaForCausalLM(cfg_bad)
        with pytest.raises(ValueError, match="vocab"):
            speculative_generate(t, bad, paddle.to_tensor(
                np.array([[1]], np.int32)))


class TestNoRepeatNgram:
    def _model(self):
        cfg = LlamaConfig(vocab_size=32, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=1,
                          max_position_embeddings=32)
        paddle.seed(31)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return cfg, m

    def test_no_repeat_ngram_matches_eager_rule(self):
        cfg, m = self._model()
        n_gram, n = 2, 8
        ids = np.array([[3, 9, 3]], np.int32)
        toks, _ = m.generate(paddle.to_tensor(ids), max_new_tokens=n,
                             no_repeat_ngram_size=n_gram)
        got = [int(t) for t in np.asarray(toks._value)[0]]
        cur, want = ids[0].tolist(), []
        for _ in range(n):
            lg = np.array(m(paddle.to_tensor(
                np.asarray(cur, np.int32)[None]))._value[0, -1],
                np.float32)
            suffix = tuple(cur[-(n_gram - 1):])
            for i in range(len(cur) - n_gram + 1):
                if tuple(cur[i:i + n_gram - 1]) == suffix:
                    lg[cur[i + n_gram - 1]] = -1e30
            nxt = int(np.argmax(lg))
            want.append(nxt)
            cur.append(nxt)
        assert got == want, (got, want)
        # the constraint binds: no repeated bigram in prompt+output
        grams = set()
        for a, bb in zip(cur, cur[1:]):
            assert (a, bb) not in grams, (a, bb, cur)
            grams.add((a, bb))

    def test_no_repeat_ngram_beam_runs(self):
        cfg, m = self._model()
        ids = np.array([[1, 2, 1]], np.int32)
        toks, score = m.generate(paddle.to_tensor(ids), max_new_tokens=6,
                                 decode_strategy="beam_search",
                                 num_beams=3, no_repeat_ngram_size=2)
        seq = ids[0].tolist() + [int(t) for t in
                                 np.asarray(toks._value)[0]]
        grams = list(zip(seq, seq[1:]))
        assert len(grams) == len(set(grams)), seq
        assert np.isfinite(float(score[0]))


class TestChunkedPrefill:
    """Chunked prefill (≙ vLLM chunked prefill): long prompts run
    through ONE fixed-size chunk program with traced offsets instead of
    minting per-bucket programs. Oracle: the default bucketed engine."""

    def _model(self):
        cfg = LlamaConfig(vocab_size=256, hidden_size=64,
                          intermediate_size=128, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=128)
        paddle.seed(13)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return cfg, m

    def test_matches_bucketed_engine(self):
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        cfg, m = self._model()
        rng = np.random.default_rng(7)
        # short (bucket path), exact multiple, ragged, long
        prompts = [list(rng.integers(1, cfg.vocab_size, p))
                   for p in (5, 16, 23, 40)]
        outs = {}
        for chunk in (None, 16):
            eng = ContinuousBatchingEngine(
                m, max_batch_size=2, max_seq_len=96, page_size=8,
                prompt_pad=8, prefill_chunk=chunk)
            rids = [eng.add_request(p, 6) for p in prompts]
            res = eng.run()
            outs[chunk] = [res[r] for r in rids]
            if chunk:
                # long prompts minted no per-bucket programs: only the
                # short prompt (5 <= chunk) used the bucket path
                assert len(eng._prefill_jits) <= 1
        assert outs[16] == outs[None]

    def test_chunk_must_align_to_pages(self):
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        cfg, m = self._model()
        with pytest.raises(ValueError, match="multiple of page_size"):
            ContinuousBatchingEngine(m, page_size=8, prefill_chunk=12)


class TestPageAccounting:
    """Robustness PR satellite: after ANY engine.run() — plain,
    prefix-cache-sharing, sliding-window-reclamation — every page is
    back on the free list, all refcounts are zero, and
    `cache_memory_info()` matches the fresh-engine baseline. conftest
    enables PDT_CHECK_INVARIANTS=1 for this file, so every intermediate
    step is also re-proved by `check_invariants()`."""

    def _tiny(self, **cfg_kw):
        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=1,
                          max_position_embeddings=64, **cfg_kw)
        paddle.seed(3)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return m

    @staticmethod
    def _occupancy(info):
        # occupancy-only view: hit counters legitimately differ after
        # a run, occupancy must not
        return {k: v for k, v in info.items()
                if k in ("pages_in_use", "bytes_in_use", "utilization",
                         "prefix_entries", "prefix_pages")}

    def _assert_pool_restored(self, eng, baseline):
        assert self._occupancy(eng.cache_memory_info()) == baseline
        assert all(eng._page_rc[1:] == 0)
        assert sorted(eng._free) == list(range(1, eng.num_pages))
        eng.check_invariants()

    def test_plain_run_returns_every_page(self):
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        m = self._tiny()
        eng = ContinuousBatchingEngine(m, max_batch_size=2,
                                       max_seq_len=64, page_size=4)
        baseline = self._occupancy(eng.cache_memory_info())
        rids = [eng.add_request([5, 4, 3, 2, 6, 7], 8),
                eng.add_request([9, 1, 2], 6)]
        res = eng.run()
        assert [len(res[r]) for r in rids] == [8, 6]
        self._assert_pool_restored(eng, baseline)

    def test_prefix_sharing_run_returns_every_page(self):
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        m = self._tiny()
        eng = ContinuousBatchingEngine(m, max_batch_size=2,
                                       max_seq_len=64, page_size=4,
                                       enable_prefix_caching=True)
        baseline = self._occupancy(eng.cache_memory_info())
        base = list(range(1, 13))
        rids = [eng.add_request(base + [t], 5) for t in (20, 21, 22)]
        res = eng.run()
        assert all(len(res[r]) == 5 for r in rids)
        assert eng.prefix_hits >= 1
        # cached pages are retained BY DESIGN; after draining the cache
        # the pool must be byte-identical to the fresh-engine baseline
        while eng._evict_one():
            pass
        self._assert_pool_restored(eng, baseline)

    def test_sliding_window_run_returns_every_page(self):
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=1,
                          max_position_embeddings=64)
        cfg.sliding_window = 8
        paddle.seed(3)
        m = LlamaForCausalLM(cfg)
        m.eval()
        eng = ContinuousBatchingEngine(m, max_batch_size=2,
                                       max_seq_len=64, page_size=4)
        baseline = self._occupancy(eng.cache_memory_info())
        rids = [eng.add_request(list(range(1, 10)), 16),
                eng.add_request(list(range(3, 9)), 12)]
        res = eng.run()
        assert [len(res[r]) for r in rids] == [16, 12]
        self._assert_pool_restored(eng, baseline)


class TestPinSafety:
    """ISSUE 9 (pdt-lint PDT005): admission pins matched prefix pages
    BEFORE the worst-case reservation — so the reservation's ERROR
    path must unpin, or the refcounts leak and a later
    `check_invariants()` dies far from the cause. Both pin-across-
    reserve sites (`_claim_candidate`, `import_pages`) were unguarded
    until the checker flagged them; these tests pin the guard."""

    def _tiny(self):
        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=1,
                          max_position_embeddings=64)
        paddle.seed(3)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return m

    @staticmethod
    def _raising_reserve():
        def boom(req, shared_pages=0):
            raise RuntimeError("reservation accounting exploded")
        return boom

    def test_claim_candidate_unpins_when_reserve_raises(self):
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        eng = ContinuousBatchingEngine(self._tiny(), max_batch_size=1,
                                       max_seq_len=64, page_size=4,
                                       enable_prefix_caching=True)
        base = list(range(1, 13))
        rid0 = eng.add_request(base, 4)
        res = eng.run()
        assert len(res[rid0]) == 4          # chain now registered
        rc_before = eng._page_rc.copy()
        orig = eng._reserve_ok
        eng._reserve_ok = self._raising_reserve()
        try:
            eng.add_request(base + [40, 41], 4)   # prefix match pins
            with pytest.raises(RuntimeError, match="accounting"):
                eng.step()
        finally:
            eng._reserve_ok = orig
        # the pins taken for the matched prefix were released on the
        # error path: refcounts identical, invariants hold
        assert (eng._page_rc == rc_before).all()
        eng.check_invariants()
        res = eng.run()                     # and the engine still serves
        assert len(res[rid0 + 1]) == 4

    def test_import_pages_unpins_when_reserve_raises(self):
        from paddle_tpu.models.serving import ContinuousBatchingEngine
        m = self._tiny()
        prompt = list(range(1, 11))
        src = ContinuousBatchingEngine(m, max_batch_size=1,
                                       max_seq_len=64, page_size=4)
        rid = src.add_request(prompt, 6)
        src.step()                          # prefilled + first token
        payload = src.export_pages(rid)
        dst = ContinuousBatchingEngine(m, max_batch_size=2,
                                       max_seq_len=64, page_size=4,
                                       enable_prefix_caching=True)
        warm = dst.add_request(prompt, 3)
        dst.run()                           # dst trie holds the chain
        rc_before = dst._page_rc.copy()
        orig = dst._reserve_ok
        dst._reserve_ok = self._raising_reserve()
        try:
            with pytest.raises(RuntimeError, match="accounting"):
                dst.import_pages(payload)
        finally:
            dst._reserve_ok = orig
        assert (dst._page_rc == rc_before).all()
        dst.check_invariants()
        req = dst.import_pages(payload)     # and the import still works
        assert req.request_id == payload["request_id"]
        dst.check_invariants()
        assert warm is not None
