"""Serving path (L10): KV-cache generation, masked_multihead_attention,
paged attention. ≙ SURVEY.md §1 L10 + §7 step 6; VERDICT r2 item 3."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nn import functional as F
from paddle_tpu.ops.paged_attention import (PagedKVCache,
                                            paged_attention_values)


def _mha_oracle(q, k, v, seq_len):
    """NumPy decode attention oracle: q (B,1,H,D), cache (B,T,HK,D)."""
    b, s, h, d = q.shape
    hk = k.shape[2]
    g = h // hk
    q = q.astype(np.float32).reshape(b, s, hk, g, d)
    k = k.astype(np.float32)
    v = v.astype(np.float32)
    logits = np.einsum("bskgd,btkd->bkgst", q, k) / np.sqrt(d)
    t = k.shape[1]
    mask = np.arange(t)[None, :] <= (seq_len - s + np.arange(s))[:, None]
    logits = np.where(mask[None, None, None], logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bkgst,btkd->bskgd", p, v).reshape(b, s, h, d)


class TestMaskedMHA:
    @pytest.mark.parametrize("hk", [4, 2])
    def test_matches_oracle(self, hk):
        rng = np.random.default_rng(0)
        b, t, h, d = 2, 32, 4, 16
        q = rng.standard_normal((b, 1, h, d)).astype(np.float32)
        k = rng.standard_normal((b, t, hk, d)).astype(np.float32)
        v = rng.standard_normal((b, t, hk, d)).astype(np.float32)
        seq_len = 20
        out = F.masked_multihead_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            seq_len=seq_len)
        ref = _mha_oracle(q, k, v, seq_len)
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=1e-4, atol=1e-5)

    def test_traced_seq_len(self):
        rng = np.random.default_rng(1)
        b, t, h, d = 1, 16, 2, 8
        q = rng.standard_normal((b, 1, h, d)).astype(np.float32)
        k = rng.standard_normal((b, t, h, d)).astype(np.float32)
        v = rng.standard_normal((b, t, h, d)).astype(np.float32)

        def fn(sl):
            return F.masked_multihead_attention(
                paddle.to_tensor(q), paddle.to_tensor(k),
                paddle.to_tensor(v), seq_len=paddle.Tensor(sl))._value
        out = jax.jit(fn)(jnp.int32(10))
        ref = _mha_oracle(q, k, v, 10)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)


class TestPagedAttention:
    def _setup(self, b=3, h=4, hk=2, d=16, page=8, pps=4, seed=0):
        rng = np.random.default_rng(seed)
        n_pages = b * pps + 2
        q = rng.standard_normal((b, h, d)).astype(np.float32)
        k_pages = rng.standard_normal((hk, n_pages, page, d)).astype(
            np.float32)
        v_pages = rng.standard_normal((hk, n_pages, page, d)).astype(
            np.float32)
        # distinct non-contiguous pages per sequence
        perm = rng.permutation(n_pages)[:b * pps]
        block_tables = perm.reshape(b, pps).astype(np.int32)
        context_lens = rng.integers(1, page * pps + 1, (b,)).astype(
            np.int32)
        return q, k_pages, v_pages, context_lens, block_tables

    def _oracle(self, q, k_pages, v_pages, context_lens, block_tables):
        b, h, d = q.shape
        hk, _, page, _ = k_pages.shape
        pps = block_tables.shape[1]
        outs = []
        for i in range(b):
            kc = k_pages[:, block_tables[i]].reshape(hk, pps * page, d)
            vc = v_pages[:, block_tables[i]].reshape(hk, pps * page, d)
            kc = np.swapaxes(kc, 0, 1)[None]   # (1, T, HK, D)
            vc = np.swapaxes(vc, 0, 1)[None]
            o = _mha_oracle(q[i][None, None], kc, vc,
                            int(context_lens[i]))
            outs.append(o[0, 0])
        return np.stack(outs)

    def test_matches_oracle(self):
        args = self._setup()
        out = paged_attention_values(*[jnp.asarray(a) for a in args])
        ref = self._oracle(*args)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_gqa_and_min_context(self):
        args = self._setup(b=2, h=8, hk=2, d=32, page=16, pps=2, seed=3)
        q, kp, vp, cl, bt = args
        cl = np.array([1, 32], np.int32)  # one-token and full contexts
        out = paged_attention_values(jnp.asarray(q), jnp.asarray(kp),
                                     jnp.asarray(vp), jnp.asarray(cl),
                                     jnp.asarray(bt))
        ref = self._oracle(q, kp, vp, cl, bt)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_cache_append(self):
        b, hk, d, page = 2, 2, 8, 4
        cache = PagedKVCache(hk, d, num_pages=8, page_size=page,
                             dtype=jnp.float32)
        bt = jnp.asarray([[0, 1], [2, 3]], jnp.int32)
        k = jnp.ones((b, hk, d))
        v = jnp.full((b, hk, d), 2.0)
        cache = cache.append(k, v, bt, jnp.asarray([0, 5], jnp.int32))
        # seq 0 pos 0 -> page 0 slot 0; seq 1 pos 5 -> page 3 slot 1
        assert float(cache.k_pages[0, 0, 0, 0]) == 1.0
        assert float(cache.v_pages[0, 3, 1, 0]) == 2.0
        assert float(cache.k_pages[0, 0, 1, 0]) == 0.0


class TestGenerate:
    def _model(self, seed=0):
        cfg = LlamaConfig.tiny()
        paddle.seed(seed)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return cfg, m

    def test_greedy_matches_eager_refeed(self):
        """Greedy KV-cache decode == argmax over full re-forward each
        step (the VERDICT 'greedy-decode parity test vs eager forward')."""
        cfg, model = self._model()
        ids = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 12)).astype(np.int32)
        toks, scores = model.generate(paddle.to_tensor(ids),
                                      max_new_tokens=6)
        cur = ids.copy()
        for _ in range(6):
            logits = model(paddle.to_tensor(cur))
            nxt = np.asarray(jnp.argmax(logits._value[:, -1], -1),
                             np.int32)
            cur = np.concatenate([cur, nxt[:, None]], 1)
        np.testing.assert_array_equal(np.asarray(toks._value),
                                      cur[:, 12:])
        assert scores.shape == [2, 6]

    def test_eos_padding(self):
        cfg, model = self._model()
        ids = np.random.default_rng(1).integers(
            0, cfg.vocab_size, (1, 8)).astype(np.int32)
        # find the first greedy token, use it as eos => all later = eos
        toks, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=5)
        first = int(np.asarray(toks._value)[0, 0])
        toks2, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=5,
                                  eos_token_id=first)
        got = np.asarray(toks2._value)[0]
        assert got[0] == first
        assert all(t == first for t in got[1:])

    def test_sampling_reproducible_with_seed(self):
        cfg, model = self._model()
        ids = np.random.default_rng(2).integers(
            0, cfg.vocab_size, (2, 8)).astype(np.int32)
        paddle.seed(42)
        a, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=6,
                              decode_strategy="sampling", top_k=20,
                              temperature=0.9)
        paddle.seed(42)
        b, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=6,
                              decode_strategy="sampling", top_k=20,
                              temperature=0.9)
        np.testing.assert_array_equal(np.asarray(a._value),
                                      np.asarray(b._value))

    def test_top_p_keeps_top_token(self):
        cfg, model = self._model()
        ids = np.random.default_rng(3).integers(
            0, cfg.vocab_size, (1, 8)).astype(np.int32)
        # top_p -> 0 degenerates to greedy
        greedy, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=4)
        samp, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                                 decode_strategy="sampling", top_p=1e-9)
        np.testing.assert_array_equal(np.asarray(greedy._value),
                                      np.asarray(samp._value))

    def test_cache_overflow_raises(self):
        cfg, model = self._model()
        ids = np.zeros((1, 8), np.int32)
        with pytest.raises(ValueError):
            model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                           max_cache_len=10)

    def test_chunked_prefill_matches_full(self):
        """Two-chunk prefill through the cache == one-shot prefill
        (exercises the end-aligned causal convention with offset > 0)."""
        cfg, model = self._model()
        rng = np.random.default_rng(4)
        ids = rng.integers(0, cfg.vocab_size, (1, 16)).astype(np.int32)
        hk, hd = cfg.num_key_value_heads, cfg.head_dim
        n_l = cfg.num_hidden_layers
        caches = [(paddle.to_tensor(np.zeros((1, 32, hk, hd), np.float32)),
                   paddle.to_tensor(np.zeros((1, 32, hk, hd), np.float32)))
                  for _ in range(n_l)]
        with paddle.no_grad():
            l1, caches = model(paddle.to_tensor(ids[:, :8]),
                               past_key_values=caches, position_offset=0,
                               use_cache=True)
            l2, caches = model(paddle.to_tensor(ids[:, 8:]),
                               past_key_values=caches, position_offset=8,
                               use_cache=True)
            full = model(paddle.to_tensor(ids))
        np.testing.assert_allclose(
            np.asarray(l2._value[:, -1]),
            np.asarray(full._value[:, -1]), rtol=2e-3, atol=2e-3)


class TestAttentionMaskWithCache:
    def test_padding_mask_excludes_cached_positions(self):
        """Left-padding written into the cache must get zero weight."""
        rng = np.random.default_rng(9)
        b, t, h, d = 2, 16, 2, 8
        q = rng.standard_normal((b, 1, h, d)).astype(np.float32)
        k = rng.standard_normal((b, t, h, d)).astype(np.float32)
        v = rng.standard_normal((b, t, h, d)).astype(np.float32)
        pad = np.ones((b, t), bool)
        pad[0, :4] = False                       # seq 0: first 4 are pad
        out_m = F.masked_multihead_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            seq_len=12, attn_mask=paddle.to_tensor(pad))
        # reference: zero out padded keys by giving them -inf manually
        k2 = k.copy()
        ref = _mha_oracle(q, np.where(pad[:, :, None, None], k, -1e4),
                          v, 12)
        # cheaper check: masked positions have no influence — perturb them
        k_pert = k.copy()
        k_pert[0, :4] += 100.0
        v_pert = v.copy()
        v_pert[0, :4] += 100.0
        out_p = F.masked_multihead_attention(
            paddle.to_tensor(q), paddle.to_tensor(k_pert),
            paddle.to_tensor(v_pert), seq_len=12,
            attn_mask=paddle.to_tensor(pad))
        np.testing.assert_allclose(np.asarray(out_m._value),
                                   np.asarray(out_p._value), atol=1e-6)
        # and unmasked output differs from masked (mask has an effect)
        out_nomask = F.masked_multihead_attention(
            paddle.to_tensor(q), paddle.to_tensor(k_pert),
            paddle.to_tensor(v_pert), seq_len=12)
        assert not np.allclose(np.asarray(out_m._value),
                               np.asarray(out_nomask._value))


class TestGPTGenerate:
    def test_greedy_matches_eager_refeed(self):
        """GPT decode with learned position embeddings + KV cache matches
        argmax over full re-forward each step."""
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        cfg = GPTConfig.tiny() if hasattr(GPTConfig, "tiny") else GPTConfig(
            vocab_size=512, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=128,
            max_position_embeddings=64)
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        model.eval()
        ids = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 10)).astype(np.int32)
        toks, _ = model.generate(paddle.to_tensor(ids), max_new_tokens=5)
        cur = ids.copy()
        for _ in range(5):
            logits = model(paddle.to_tensor(cur))
            nxt = np.asarray(jnp.argmax(logits._value[:, -1], -1),
                             np.int32)
            cur = np.concatenate([cur, nxt[:, None]], 1)
        np.testing.assert_array_equal(np.asarray(toks._value), cur[:, 10:])
