"""Native runtime tests: codec, shm ring, multiprocess DataLoader.
≙ reference C++ unit tests for the shm channel + save/load codec
(SURVEY.md §2.1 rows 'Memory/allocators'/'JIT saved-model layer' analogs)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import _native as N
from paddle_tpu.io import DataLoader, Dataset

rng = np.random.default_rng(31)

needs_native = pytest.mark.skipif(N._load() is None,
                                  reason="g++/native lib unavailable")


@needs_native
class TestCodec:
    @pytest.mark.parametrize("dtype", ["float32", "int32", "float64",
                                       "uint8", "bool"])
    def test_roundtrip(self, dtype):
        a = (rng.random((3, 4, 5)) * 100).astype(dtype)
        b = N.encode_tensor(a)
        np.testing.assert_array_equal(N.decode_tensor(b), a)

    @pytest.mark.parametrize("dtype", ["bfloat16", "complex64",
                                       "complex128", "float16"])
    def test_roundtrip_long_dtype_names(self, dtype):
        # round-1 regression: the v1 header truncated dtype names to 7
        # chars — 'complex64' silently decoded as complex128, 'bfloat16'
        # (the default training dtype) failed outright
        import ml_dtypes
        dt = np.dtype(getattr(ml_dtypes, dtype, dtype))
        a = rng.random((4, 3)).astype(dt)
        b = N.encode_tensor(a)
        got = N.decode_tensor(b)
        assert got.dtype == dt
        np.testing.assert_array_equal(got, a)

    def test_unencodable_dtype_falls_back_to_npy(self):
        # dtype whose name exceeds the 15-char header field
        a = np.array([1, 2], dtype="datetime64[100ns]")
        b = N.encode_tensor(a)
        assert b[:4] == b"NPYF"
        np.testing.assert_array_equal(N.decode_tensor(b), a)

    def test_datetime_roundtrip(self):
        a = np.array(["2024-01-01", "2024-01-02"], dtype="datetime64[ns]")
        np.testing.assert_array_equal(
            N.decode_tensor(N.encode_tensor(a)), a)

    def test_scalar_and_empty(self):
        for a in (np.float32(3.5), np.zeros((0, 4), np.int32)):
            got = N.decode_tensor(N.encode_tensor(np.asarray(a)))
            np.testing.assert_array_equal(got, np.asarray(a))

    def test_crc_detects_corruption(self):
        b = bytearray(N.encode_tensor(np.arange(10, dtype=np.float32)))
        b[-2] ^= 0x40
        with pytest.raises(ValueError, match="crc32"):
            N.decode_tensor(bytes(b))

    def test_bad_magic(self):
        with pytest.raises(ValueError, match="magic"):
            N.decode_tensor(b"\x00" * 64)


@needs_native
class TestShmRing:
    def test_push_pop_order(self):
        ring = N.ShmRing(f"/pdt_t1_{os.getpid()}", capacity=1 << 16)
        try:
            for i in range(10):
                assert ring.push(bytes([i]) * (i + 1))
            for i in range(10):
                msg = ring.pop(timeout_ms=1000)
                assert msg == bytes([i]) * (i + 1)
        finally:
            ring.close()

    def test_wraparound(self):
        ring = N.ShmRing(f"/pdt_t2_{os.getpid()}", capacity=1 << 12)
        try:
            payload = bytes(1000)
            for _ in range(20):  # > capacity total: forces wraparound
                assert ring.push(payload, timeout_ms=1000)
                assert ring.pop(timeout_ms=1000) == payload
        finally:
            ring.close()

    def test_timeout_on_empty(self):
        ring = N.ShmRing(f"/pdt_t3_{os.getpid()}", capacity=1 << 12)
        try:
            assert ring.pop(timeout_ms=50) is None
        finally:
            ring.close()

    def test_too_large_record(self):
        ring = N.ShmRing(f"/pdt_t4_{os.getpid()}", capacity=1 << 10)
        try:
            with pytest.raises(ValueError, match="capacity"):
                ring.push(bytes(2048))
        finally:
            ring.close()

    def test_cross_process(self):
        name = f"/pdt_t5_{os.getpid()}"
        ring = N.ShmRing(name, capacity=1 << 20)
        try:
            pid = os.fork()
            if pid == 0:
                try:
                    w = N.ShmRing(name, create=False)
                    for i in range(20):
                        w.push(N.encode_tensor(
                            np.full((8,), i, np.int32)))
                finally:
                    os._exit(0)
            for i in range(20):
                arr = N.decode_tensor(ring.pop(timeout_ms=10000))
                assert (arr == i).all()
            os.waitpid(pid, 0)
        finally:
            ring.close()


class _ArrayDataset(Dataset):
    def __init__(self, n=40):
        self.x = rng.normal(size=(n, 6)).astype(np.float32)
        self.y = np.arange(n, dtype=np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


@needs_native
class TestMultiprocessDataLoader:
    def test_worker_batches_match_inline(self):
        ds = _ArrayDataset(40)
        dl0 = DataLoader(ds, batch_size=8, num_workers=0)
        dl2 = DataLoader(ds, batch_size=8, num_workers=2)
        batches0 = [(x.numpy(), y.numpy()) for x, y in dl0]
        batches2 = [(x.numpy(), y.numpy()) for x, y in dl2]
        assert len(batches0) == len(batches2) == 5
        for (x0, y0), (x2, y2) in zip(batches0, batches2):
            np.testing.assert_array_equal(x0, x2)
            np.testing.assert_array_equal(y0, y2)

    def test_shuffle_with_workers_covers_all(self):
        ds = _ArrayDataset(32)
        dl = DataLoader(ds, batch_size=4, shuffle=True, num_workers=3)
        seen = np.concatenate([y.numpy() for _, y in dl])
        assert sorted(seen.tolist()) == list(range(32))


class TestSaveIntegrity:
    def test_save_load_crc(self, tmp_path):
        t = paddle.to_tensor(rng.normal(size=(4, 4)).astype(np.float32))
        p = str(tmp_path / "x.pdparams")
        paddle.save({"w": t}, p)
        out = paddle.load(p)
        np.testing.assert_array_equal(out["w"].numpy(), t.numpy())


def test_packaged_native_source_in_sync():
    """The wheel ships paddle_tpu/_native/csrc/native.cc; it must stay
    byte-identical to the development copy at the repo root."""
    import paddle_tpu._native as N
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "csrc", "native.cc")
    pkg = os.path.join(os.path.dirname(os.path.abspath(N.__file__)),
                       "csrc", "native.cc")
    with open(root, "rb") as a, open(pkg, "rb") as b:
        assert a.read() == b.read(), (
            "csrc/native.cc and paddle_tpu/_native/csrc/native.cc have "
            "drifted — copy the root file over the package copy")


class TestBPE:
    """Byte-level BPE: train/encode/decode + C++-vs-Python parity
    (≙ reference faster-tokenizer native core [U])."""

    def _tok(self):
        from paddle_tpu.text import BPETokenizer
        corpus = ("the quick brown fox jumps over the lazy dog " * 40
                  + "tokenization is compression " * 20)
        return BPETokenizer.train(corpus, vocab_size=320)

    def test_roundtrip_and_compression(self):
        tok = self._tok()
        s = "the quick brown fox likes tokenization"
        ids = tok.encode(s)
        assert tok.decode(ids) == s
        assert len(ids) < len(s.encode())

    def test_unicode_bytes_roundtrip(self):
        tok = self._tok()
        s = "héllo wörld — 你好 🙂"
        assert tok.decode(tok.encode(s)) == s

    def test_native_matches_python(self):
        from paddle_tpu import _native
        tok = self._tok()
        texts = ["the dog", "zzzzz unseen bytes \x00\x01",
                 "tokenization of the lazy fox " * 7]
        for t in texts:
            py = tok._encode_py(t.encode())
            full = tok.encode(t)
            np.testing.assert_array_equal(py, full)
        if _native._load() is not None:
            # ensure the native path actually ran (not the fallback)
            out = _native.bpe_encode_native(
                b"the dog", tok._ml, tok._mr)
            assert out is not None

    def test_save_load(self, tmp_path):
        from paddle_tpu.text import BPETokenizer
        tok = self._tok()
        p = str(tmp_path / "bpe.json")
        tok.save(p)
        tok2 = BPETokenizer.load(p)
        s = "the quick dog"
        np.testing.assert_array_equal(tok.encode(s), tok2.encode(s))
