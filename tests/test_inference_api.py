"""Predictor API + paddle.base shim + elastic heartbeat (round 4:
closing the L10 'no predictor-style load-and-serve API', base-glue, and
elastic-thinness partials from VERDICT r3)."""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class TestPredictorAPI:
    def _save_model(self, tmp_path):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                              nn.Linear(16, 4))
        model.eval()
        prefix = str(tmp_path / "served")
        paddle.jit.save(model, prefix,
                        input_spec=[paddle.static.InputSpec([2, 8],
                                                            "float32")])
        return model, prefix

    def test_reference_style_serving_script(self, tmp_path):
        """The canonical paddle_infer script shape runs verbatim."""
        model, prefix = self._save_model(tmp_path)
        from paddle_tpu.inference import Config, create_predictor
        config = Config(prefix + ".pdmodel")
        config.enable_use_gpu(100, 0)       # accepted no-op toggles
        config.switch_ir_optim(True)
        predictor = create_predictor(config)

        x = np.random.default_rng(1).normal(size=(2, 8)) \
            .astype(np.float32)
        in_names = predictor.get_input_names()
        h = predictor.get_input_handle(in_names[0])
        h.reshape([2, 8])
        h.copy_from_cpu(x)
        predictor.run()
        out_names = predictor.get_output_names()
        got = predictor.get_output_handle(out_names[0]).copy_to_cpu()

        want = np.asarray(model(paddle.to_tensor(x))._value)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_run_with_direct_inputs(self, tmp_path):
        model, prefix = self._save_model(tmp_path)
        from paddle_tpu.inference import Config, create_predictor
        p = create_predictor(Config(prefix))
        x = np.ones((2, 8), np.float32)
        (out,) = p.run([x])
        np.testing.assert_allclose(
            out, np.asarray(model(paddle.to_tensor(x))._value),
            rtol=1e-5)

    def test_missing_program_errors_clearly(self, tmp_path):
        paddle.seed(0)
        model = nn.Linear(4, 2)
        prefix = str(tmp_path / "noprog")
        paddle.jit.save(model, prefix)      # no input_spec -> no .pdmodel
        from paddle_tpu.inference import Config, create_predictor
        with pytest.raises(RuntimeError, match="input_spec"):
            create_predictor(Config(prefix))


class TestBaseShim:
    def test_core_probes_and_places(self):
        from paddle_tpu import base
        assert base.core.is_compiled_with_cuda() is False
        assert base.core.get_cuda_device_count() == 0
        base.core.CPUPlace()
        base.core.CUDAPlace(0)

    def test_framework_and_dygraph_guard(self):
        from paddle_tpu import base
        assert base.framework.in_dygraph_mode()
        paddle.enable_static()
        try:
            assert not base.framework.in_dygraph_mode()
            with base.dygraph.guard():
                assert base.framework.in_dygraph_mode()
                t = base.dygraph.to_variable(np.ones(3, np.float32))
                assert float(t.sum()) == 3.0
            assert not base.framework.in_dygraph_mode()
        finally:
            paddle.disable_static()

    def test_executor_and_program_reexports(self):
        from paddle_tpu import base
        assert base.Program is paddle.static.Program
        assert base.executor.Executor is paddle.static.Executor
        assert base.ParamAttr is not None


class TestHeartbeatMembership:
    def test_register_watch_and_scale_events(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import \
            HeartbeatMembership
        d = str(tmp_path / "hb")
        watcher = HeartbeatMembership(d, interval=0.1, timeout=0.6)

        w0 = HeartbeatMembership(d, rank=0, interval=0.1,
                                 timeout=0.6).start()
        w1 = HeartbeatMembership(d, rank=1, interval=0.1,
                                 timeout=0.6).start()
        alive = watcher.wait_for_peers(2, timeout=5)
        assert alive == {0, 1}
        assert watcher.poll()["event"] is None      # steady state

        # scale-up: a third worker joins
        w2 = HeartbeatMembership(d, rank=2, interval=0.1,
                                 timeout=0.6).start()
        time.sleep(0.2)
        ev = watcher.poll()
        assert ev["event"] == "scale_up" and 2 in ev["joined"]

        # scale-down: worker 1 dies (stops beating, file removed)
        w1.stop()
        deadline = time.time() + 3
        ev = watcher.poll()
        while ev["event"] != "scale_down" and time.time() < deadline:
            time.sleep(0.2)
            ev = watcher.poll()
        assert ev["event"] == "scale_down" and 1 in ev["dead"], ev
        assert watcher.alive() == {0, 2}
        w0.stop()
        w2.stop()

    def test_stale_heartbeat_counts_as_dead(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import \
            HeartbeatMembership
        d = str(tmp_path / "hb2")
        watcher = HeartbeatMembership(d, timeout=0.3)
        w = HeartbeatMembership(d, rank=5, timeout=0.3)
        w.heartbeat()                      # one manual beat, no thread
        assert watcher.alive() == {5}
        time.sleep(0.5)                    # goes stale (no daemon)
        assert watcher.alive() == set()

    def test_wait_for_peers_times_out(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import \
            HeartbeatMembership
        watcher = HeartbeatMembership(str(tmp_path / "hb3"),
                                      interval=0.05)
        with pytest.raises(TimeoutError, match="0/2"):
            watcher.wait_for_peers(2, timeout=0.4)


class TestReviewRegressions:
    def test_buffered_model_roundtrips_through_predictor(self, tmp_path):
        """Non-persistable buffers (rope-table style) must not skew the
        export arity (round-4 review finding #1)."""
        from paddle_tpu.core.tensor import Tensor
        import jax.numpy as jnp

        class WithBuffers(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(6, 3)
                self.register_buffer("scale_p",
                                     paddle.to_tensor(
                                         np.full(3, 2.0, np.float32)))
                self.register_buffer("table_np",
                                     paddle.to_tensor(
                                         np.full(3, 5.0, np.float32)),
                                     persistable=False)

            def forward(self, x):
                return self.lin(x) * self.scale_p + self.table_np

        paddle.seed(0)
        m = WithBuffers()
        m.eval()
        prefix = str(tmp_path / "buf")
        paddle.jit.save(m, prefix,
                        input_spec=[paddle.static.InputSpec([2, 6],
                                                            "float32")])
        from paddle_tpu.inference import Config, create_predictor
        p = create_predictor(Config(prefix))
        assert len(p.get_input_names()) == 1
        x = np.random.default_rng(3).normal(size=(2, 6)) \
            .astype(np.float32)
        (out,) = p.run([x])
        np.testing.assert_allclose(
            out, np.asarray(m(paddle.to_tensor(x))._value), rtol=1e-5,
            atol=1e-6)

    def test_params_file_override(self, tmp_path):
        paddle.seed(0)
        m = nn.Linear(4, 2)
        m.eval()
        prefix = str(tmp_path / "a" / "model")
        os.makedirs(str(tmp_path / "a"))
        paddle.jit.save(m, prefix,
                        input_spec=[paddle.static.InputSpec([1, 4],
                                                            "float32")])
        # move params elsewhere (reference-style split layout)
        os.makedirs(str(tmp_path / "w"))
        wpath = str(tmp_path / "w" / "net.pdiparams")
        os.replace(prefix + ".pdiparams", wpath)
        from paddle_tpu.inference import Config, create_predictor
        p = create_predictor(Config(prefix + ".pdmodel", wpath))
        (out,) = p.run([np.ones((1, 4), np.float32)])
        np.testing.assert_allclose(
            out, np.asarray(m(paddle.to_tensor(
                np.ones((1, 4), np.float32)))._value), rtol=1e-5)

    def test_membership_restartable(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import \
            HeartbeatMembership
        d = str(tmp_path / "hb4")
        w = HeartbeatMembership(d, rank=0, interval=0.05, timeout=0.5)
        w.start()
        w.stop()
        w.start()                         # must beat again, not go stale
        watcher = HeartbeatMembership(d, timeout=0.5)
        time.sleep(0.7)                   # past one timeout window
        assert watcher.alive() == {0}, "restarted worker went stale"
        w.stop()

    def test_output_handles_stable_and_prefetchable(self, tmp_path):
        """Reference scripts fetch output handles BEFORE the run loop
        and reuse them across runs (round-4 review finding)."""
        paddle.seed(0)
        m = nn.Linear(4, 2)
        m.eval()
        prefix = str(tmp_path / "h")
        paddle.jit.save(m, prefix,
                        input_spec=[paddle.static.InputSpec([1, 4],
                                                            "float32")])
        from paddle_tpu.inference import Config, create_predictor
        p = create_predictor(Config(prefix))
        out_h = p.get_output_handle(p.get_output_names()[0])  # pre-run
        in_h = p.get_input_handle(p.get_input_names()[0])
        for scale in (1.0, 2.0):
            in_h.copy_from_cpu(np.full((1, 4), scale, np.float32))
            p.run()
            fresh = out_h.copy_to_cpu()          # same handle object
            want = np.asarray(m(paddle.to_tensor(
                np.full((1, 4), scale, np.float32)))._value)
            np.testing.assert_allclose(fresh, want, rtol=1e-5)
