"""Crash-durable serving control plane (ISSUE 13): the router
write-ahead journal (`serving/journal.py`) and zero-loss router
restart (`ServingRouter.recover`).

The acceptance property threaded through this file: a router SIGKILL
at ANY phase — post-submit pre-dispatch, mid-decode, pre-terminal-
flush — followed by `recover()` on the journal yields greedy outputs
BIT-IDENTICAL to an uninterrupted fleet, finished requests are never
re-executed (idempotent-per-request_id dedupe, proven by exact
`pdt_journal_*`-vs-terminal counter reconciliation), and a torn
journal tail (fuzzed at every byte offset of the final record) is
dropped and counted, never fatal. conftest runs this file with
PDT_TELEMETRY=1 and PDT_CHECK_INVARIANTS=1."""
import json
import os
import shutil
import struct

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.serving import (ContinuousBatchingEngine,
                                       RequestStatus)
from paddle_tpu.serving import (FleetOverloaded, RouterJournal,
                                QosAdmission, ServingRouter)
from paddle_tpu.serving.journal import _HEADER, commit_bytes
from paddle_tpu.utils.faults import FaultError, FaultInjector

pytestmark = pytest.mark.chaos


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=1, max_position_embeddings=64)
    paddle.seed(7)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _factory(model, clock=None, **kw):
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", 4)

    def make(index):
        return ContinuousBatchingEngine(model, clock=clock, **kw)

    return make


def _jobs(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 64, int(rng.integers(4, 8))).tolist()
            for _ in range(n)]


JOBS = _jobs()
N_TOK = 8
# staggered budgets so fleet runs finish at DIFFERENT steps — the
# mid-decode SIGKILL drill needs finished-and-live requests to coexist
N_TOKS = [4, 10, 8, 14]


def _submit_jobs(router):
    return [router.submit(p, n) for p, n in zip(JOBS, N_TOKS)]


@pytest.fixture(scope="module")
def oracle(model):
    """The uninterrupted fleet's outputs for JOBS — every drill below
    must reproduce these streams exactly."""
    clock = FakeClock()
    router = ServingRouter(_factory(model, clock), num_replicas=2,
                           clock=clock, sleep=clock.advance)
    ids = _submit_jobs(router)
    out = router.run()
    return [out[i] for i in ids]


def _segment_files(path):
    return sorted(fn for fn in os.listdir(path)
                  if fn.startswith("seg-") and fn.endswith(".wal"))


def _record_spans(blob):
    """(start, end) byte spans of each record in a segment blob."""
    spans, off = [], 0
    while off < len(blob):
        length, _ = _HEADER.unpack_from(blob, off)
        end = off + _HEADER.size + length
        spans.append((off, end))
        off = end
    return spans


# -- the record format + replay ----------------------------------------
class TestRecordFormat:
    def test_roundtrip_submit_progress_terminal(self, tmp_path):
        # both instances share one frozen clock: replay re-anchors
        # deadlines to remaining-time-at-last-stamp (satellite drill
        # below), so only zero elapsed time keeps them literal
        clock = FakeClock()
        with RouterJournal(tmp_path / "wal", fsync="off",
                           clock=clock) as jr:
            jr.append_submit(request_id="a", prompt=[1, 2, 3],
                             max_new_tokens=8, lane="batch",
                             tenant="acme", priority=1,
                             deadline_abs=9.5, max_queue_time=2.0)
            jr.append_submit(request_id="b", prompt=[4], max_new_tokens=4)
            assert jr.step_mirror({"a": [7, 8], "b": [9]}) == 2
            assert jr.step_mirror({"a": [7, 8, 10], "b": [9]}) == 1
            jr.append_terminal("b", RequestStatus.FINISHED,
                               [9, 11, 12, 13])
        rep = RouterJournal(tmp_path / "wal", fsync="off",
                            clock=clock).replay()
        assert set(rep.live) == {"a"} and set(rep.finished) == {"b"}
        a = rep.live["a"]
        assert (a.prompt, a.tokens, a.lane, a.tenant, a.priority,
                a.deadline_abs, a.max_queue_time) \
            == ([1, 2, 3], [7, 8, 10], "batch", "acme", 1, 9.5, 2.0)
        b = rep.finished["b"]
        assert b.status == RequestStatus.FINISHED
        assert b.tokens == [9, 11, 12, 13]
        assert rep.corrupt_dropped == 0

    def test_rejected_submit_never_resurrects(self, tmp_path):
        with RouterJournal(tmp_path / "wal", fsync="off") as jr:
            jr.append_submit(request_id="a", prompt=[1], max_new_tokens=4)
            jr.append_rejected("a")
        rep = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert not rep.live and not rep.finished
        assert rep.rejected == 1

    def test_release_lets_replay_drop_the_terminal(self, tmp_path):
        with RouterJournal(tmp_path / "wal", fsync="off") as jr:
            jr.append_submit(request_id="a", prompt=[1], max_new_tokens=4)
            jr.append_terminal("a", RequestStatus.FINISHED, [5])
            jr.append_release("a")
        rep = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert not rep.live and not rep.finished

    def test_rewind_truncates_live_stream_and_realigns(self, tmp_path):
        """ISSUE 14: a gray-failure quarantine drops a request's
        tainted token suffix — `rewind()` makes the journal forget it
        too, so a replay before the terminal recovers the VERIFIED
        prefix only and the regenerated suffix journals at the right
        offsets (not misaligned past ghost tokens)."""
        with RouterJournal(tmp_path / "wal", fsync="off") as jr:
            jr.append_submit(request_id="a", prompt=[1, 2],
                             max_new_tokens=8)
            jr.step_mirror({"a": [5, 6, 7, 8]})      # 6,7,8 tainted
            jr.rewind("a", 1)
            # the healthy replica regenerates a DIFFERENT suffix —
            # the diff must run against the truncated stream
            assert jr.step_mirror({"a": [5, 9, 10]}) == 1
        rep = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert rep.live["a"].tokens == [5, 9, 10]
        assert telemetry.value("pdt_journal_records_total",
                               kind="rewind") == 1

    def test_rewind_replays_without_later_progress(self, tmp_path):
        """The crash window the record exists for: router dies right
        after the quarantine's rewind, before any regeneration —
        replay must hand recovery the verified prefix, not the
        tainted stream the earlier progress records committed."""
        with RouterJournal(tmp_path / "wal", fsync="off") as jr:
            jr.append_submit(request_id="a", prompt=[1],
                             max_new_tokens=8)
            jr.step_mirror({"a": [5, 6, 7]})
            jr.rewind("a", 0)
        rep = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert rep.live["a"].tokens == []
        # a rewind for a FINISHED request is inert at replay (the
        # terminal's complete stream is authoritative)
        with RouterJournal(tmp_path / "w2", fsync="off") as jr:
            jr.append_submit(request_id="b", prompt=[1],
                             max_new_tokens=4)
            jr.append_terminal("b", RequestStatus.FINISHED, [5, 6])
            jr.rewind("b", 0)
        rep = RouterJournal(tmp_path / "w2", fsync="off").replay()
        assert rep.finished["b"].tokens == [5, 6]

    def test_mirror_with_no_growth_appends_nothing(self, tmp_path):
        with RouterJournal(tmp_path / "wal", fsync="off") as jr:
            jr.append_submit(request_id="a", prompt=[1], max_new_tokens=4)
            assert jr.step_mirror({"a": [5]}) == 1
            before = telemetry.value("pdt_journal_records_total",
                                     kind="progress")
            assert jr.step_mirror({"a": [5]}) == 0
            assert telemetry.value("pdt_journal_records_total",
                                   kind="progress") == before

    def test_every_open_starts_a_fresh_segment(self, tmp_path):
        j1 = RouterJournal(tmp_path / "wal", fsync="off")
        j1.append_submit(request_id="a", prompt=[1], max_new_tokens=4)
        j2 = RouterJournal(tmp_path / "wal", fsync="off")
        j2.append_submit(request_id="b", prompt=[2], max_new_tokens=4)
        # never append after a possibly-torn tail: two opens, two
        # (or more) segments, and replay merges them in order
        assert len(_segment_files(j1.path)) >= 2
        rep = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert set(rep.live) == {"a", "b"}

    def test_segment_rotation_replays_across_segments(self, tmp_path):
        with RouterJournal(tmp_path / "wal", fsync="off",
                           segment_bytes=128) as jr:
            for i in range(10):
                jr.append_submit(request_id=f"r{i}", prompt=[i],
                                 max_new_tokens=4)
        assert len(_segment_files(jr.path)) > 2
        rep = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert set(rep.live) == {f"r{i}" for i in range(10)}

    def test_fsync_policy(self, tmp_path):
        def fsyncs():
            return telemetry.value("pdt_journal_fsyncs_total")

        with RouterJournal(tmp_path / "w1", fsync="off") as jr:
            jr.append_submit(request_id="a", prompt=[1], max_new_tokens=4)
            jr.step_mirror({"a": [5]})
        assert fsyncs() == 0
        with RouterJournal(tmp_path / "w2", fsync="terminal") as jr:
            jr.append_submit(request_id="a", prompt=[1], max_new_tokens=4)
            jr.step_mirror({"a": [5]})          # progress: no fsync
            jr.append_terminal("a", RequestStatus.FINISHED, [5])
        assert fsyncs() == 2                     # submit + terminal
        with RouterJournal(tmp_path / "w3", fsync="step") as jr:
            jr.append_submit(request_id="a", prompt=[1], max_new_tokens=4)
            jr.step_mirror({"a": [5]})
        # step mode also fsyncs the segment-open record
        assert fsyncs() == 2 + 3
        with pytest.raises(ValueError):
            RouterJournal(tmp_path / "w4", fsync="sometimes")

    def test_unknown_version_raises(self, tmp_path):
        jr = RouterJournal(tmp_path / "wal", fsync="off")
        jr.close()
        from paddle_tpu.serving.journal import _encode
        blob = _encode({"kind": "open", "v": 99, "segment": 9})
        commit_bytes(os.path.join(jr.path, "seg-00000009.wal"), blob,
                     fsync=False)
        with pytest.raises(ValueError, match="version"):
            RouterJournal(tmp_path / "wal", fsync="off").replay()

    def test_journal_constructor_validation(self, tmp_path):
        with pytest.raises(ValueError):
            RouterJournal(tmp_path / "a", segment_bytes=0)
        with pytest.raises(ValueError):
            RouterJournal(tmp_path / "b", compact_finalized=0)


# -- two-phase resize records (ISSUE 16) --------------------------------
class TestResizeRecords:
    TOPO = {"num_replicas": 3, "roles": ["colocated"] * 3, "tp": None}

    def test_intent_without_commit_rolls_forward(self, tmp_path):
        with RouterJournal(tmp_path / "wal", fsync="off") as jr:
            jr.append_resize_intent(1, self.TOPO)
        replay = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert replay.topology == self.TOPO
        assert replay.resize_seq == 1
        assert replay.resize_rolled_forward is True

    def test_commit_settles_the_transaction(self, tmp_path):
        with RouterJournal(tmp_path / "wal", fsync="off") as jr:
            jr.append_resize_intent(1, self.TOPO)
            jr.append_resize_commit(1)
        replay = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert replay.topology == self.TOPO
        assert replay.resize_seq == 1
        assert replay.resize_rolled_forward is False

    def test_latest_resize_wins(self, tmp_path):
        smaller = {"num_replicas": 1, "roles": ["colocated"], "tp": None}
        with RouterJournal(tmp_path / "wal", fsync="off") as jr:
            jr.append_resize_intent(1, self.TOPO)
            jr.append_resize_commit(1)
            jr.append_resize_intent(2, smaller)    # open: rolls forward
        replay = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert replay.topology == smaller
        assert replay.resize_seq == 2
        assert replay.resize_rolled_forward is True

    def test_topology_survives_compaction(self, tmp_path):
        with RouterJournal(tmp_path / "wal", fsync="off") as jr:
            jr.append_resize_intent(1, self.TOPO)
            jr.append_resize_commit(1)
            jr.compact()                # supersedes the resize segment
        replay = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert replay.topology == self.TOPO
        assert replay.resize_seq == 1
        assert replay.resize_rolled_forward is False
        assert telemetry.value("pdt_journal_records_total",
                               kind="topology") >= 1


# -- torn-tail tolerance (the parse_done tradition) --------------------
class TestTornTail:
    def _build(self, path):
        with RouterJournal(path, fsync="off") as jr:
            jr.append_submit(request_id="a", prompt=[1, 2],
                             max_new_tokens=8)
            jr.step_mirror({"a": [5, 6]})
            jr.append_submit(request_id="b", prompt=[3], max_new_tokens=8)
        return jr.path

    def test_truncation_fuzz_every_offset(self, tmp_path):
        """Truncate the journal at EVERY byte offset inside the final
        record: replay never raises, always recovers the committed
        prefix, and counts exactly one corrupt-tail drop."""
        src = self._build(tmp_path / "wal")
        seg = _segment_files(src)[-1]
        blob = open(os.path.join(src, seg), "rb").read()
        spans = _record_spans(blob)
        last_start, last_end = spans[-1]
        assert last_end == len(blob)
        for cut in range(last_start + 1, last_end):
            trial = tmp_path / f"trial-{cut}"
            shutil.copytree(src, trial)
            with open(os.path.join(trial, seg), "r+b") as f:
                f.truncate(cut)
            rep = RouterJournal(trial, fsync="off").replay()
            assert rep.corrupt_dropped == 1, cut
            # the committed prefix: "a" + its progress always survive
            # (they precede the final record); "b" is the drop
            assert set(rep.live) == {"a"}, cut
            assert rep.live["a"].tokens == [5, 6], cut

    def test_checksum_flip_drops_the_tail(self, tmp_path):
        src = self._build(tmp_path / "wal")
        seg = _segment_files(src)[-1]
        p = os.path.join(src, seg)
        blob = bytearray(open(p, "rb").read())
        start, end = _record_spans(bytes(blob))[-1]
        blob[(start + _HEADER.size + end) // 2] ^= 0xFF
        open(p, "wb").write(bytes(blob))
        before = telemetry.value("pdt_journal_corrupt_tail_total")
        rep = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert rep.corrupt_dropped == 1
        assert set(rep.live) == {"a"}
        assert telemetry.value("pdt_journal_corrupt_tail_total") \
            == before + 1

    def test_garbage_length_prefix_is_a_tear_not_an_oom(self, tmp_path):
        src = self._build(tmp_path / "wal")
        seg = _segment_files(src)[-1]
        with open(os.path.join(src, seg), "ab") as f:
            f.write(struct.pack("<II", 0x7FFFFFFF, 0) + b"xx")
        rep = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert rep.corrupt_dropped == 1
        assert set(rep.live) == {"a", "b"}   # committed prefix intact

    def test_stray_tmp_and_foreign_files_ignored(self, tmp_path):
        src = self._build(tmp_path / "wal")
        open(os.path.join(src, "seg-00000042.wal.tmp"), "wb").write(
            b"garbage from a compaction that never committed")
        open(os.path.join(src, "NOTES.txt"), "w").write("hi")
        rep = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert rep.corrupt_dropped == 0
        assert set(rep.live) == {"a", "b"}


# -- compaction --------------------------------------------------------
class TestCompaction:
    def test_compact_condenses_and_preserves_state(self, tmp_path):
        jr = RouterJournal(tmp_path / "wal", fsync="off",
                           segment_bytes=128)
        for i in range(6):
            jr.append_submit(request_id=f"r{i}", prompt=[i],
                             max_new_tokens=8)
            jr.step_mirror({f"r{i}": [100 + i]})
        jr.append_terminal("r0", RequestStatus.FINISHED, [100, 200])
        jr.append_terminal("r1", RequestStatus.TIMEOUT, [101],
                           "deadline")
        jr.append_release("r0")              # delivered: droppable
        n_seg_before = len(_segment_files(jr.path))
        retained = jr.compact()
        assert retained == 5                 # r0 dropped, r1..r5 kept
        # one snapshot segment + one fresh active segment
        assert len(_segment_files(jr.path)) == 2 < n_seg_before
        rep = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert set(rep.live) == {f"r{i}" for i in range(2, 6)}
        assert rep.live["r3"].tokens == [103]
        assert set(rep.finished) == {"r1"}
        assert rep.finished["r1"].status == RequestStatus.TIMEOUT
        assert rep.finished["r1"].error == "deadline"

    def test_auto_compaction_after_finalized_threshold(self, tmp_path):
        before = telemetry.value("pdt_journal_compactions_total")
        jr = RouterJournal(tmp_path / "wal", fsync="off",
                           compact_finalized=2)
        for i in range(4):
            jr.append_submit(request_id=f"r{i}", prompt=[i],
                             max_new_tokens=8)
            jr.append_terminal(f"r{i}", RequestStatus.FINISHED, [i])
        assert telemetry.value("pdt_journal_compactions_total") \
            == before + 2
        rep = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert set(rep.finished) == {f"r{i}" for i in range(4)}

    def test_compact_crash_before_segment_deletes(self, tmp_path,
                                                  monkeypatch):
        """A crash between the snapshot commit and the old-segment
        deletes replays consistently: snap records override."""
        jr = RouterJournal(tmp_path / "wal", fsync="off")
        jr.append_submit(request_id="a", prompt=[1], max_new_tokens=8)
        jr.step_mirror({"a": [5]})
        jr.append_terminal("a", RequestStatus.FINISHED, [5, 6])
        monkeypatch.setattr(os, "remove", lambda p: None)
        jr.compact()
        monkeypatch.undo()
        assert len(_segment_files(jr.path)) >= 3   # old ones linger
        rep = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert set(rep.finished) == {"a"}
        assert rep.finished["a"].tokens == [5, 6]
        assert not rep.live


# -- fault sites -------------------------------------------------------
class TestFaultSites:
    def test_append_fault_fires(self, tmp_path):
        jr = RouterJournal(tmp_path / "wal", fsync="off")
        with FaultInjector(seed=0) as fi:
            fi.arm("journal.append", nth=1)
            with pytest.raises(FaultError):
                jr.append_submit(request_id="a", prompt=[1],
                                 max_new_tokens=4)
            # the failed submit never landed
        rep = RouterJournal(tmp_path / "wal", fsync="off").replay()
        assert not rep.live

    def test_replay_fault_fires(self, tmp_path):
        jr = RouterJournal(tmp_path / "wal", fsync="off")
        with FaultInjector(seed=0) as fi:
            fi.arm("journal.replay", nth=1)
            with pytest.raises(FaultError):
                jr.replay()


# -- router integration ------------------------------------------------
def _journaled_router(model, tmp_path, clock=None, name="wal", **kw):
    clock = clock if clock is not None else FakeClock()
    jr = RouterJournal(os.path.join(str(tmp_path), name), fsync="off",
                       clock=clock)
    router = ServingRouter(_factory(model, clock), num_replicas=2,
                           clock=clock, sleep=clock.advance,
                           journal=jr, **kw)
    return router, jr, clock


class TestRouterJournalIntegration:
    def test_submit_lands_in_journal_before_any_step(self, model,
                                                     tmp_path):
        router, jr, clock = _journaled_router(model, tmp_path)
        rid = router.submit(JOBS[0], N_TOK, deadline=50.0,
                            lane="batch", tenant="acme")
        rep = RouterJournal(jr.path, fsync="off", clock=clock).replay()
        assert set(rep.live) == {rid}
        st = rep.live[rid]
        assert st.prompt == [int(t) for t in JOBS[0]]
        assert (st.lane, st.tenant, st.priority) == ("batch", "acme", 1)
        assert st.deadline_abs == pytest.approx(clock() + 50.0)

    def test_refused_submit_journals_rejected(self, model, tmp_path):
        router, jr, clock = _journaled_router(
            model, tmp_path, max_replica_outstanding=1)
        for p in JOBS[:2]:
            router.submit(p, N_TOK)
        with pytest.raises(FleetOverloaded):
            router.submit(JOBS[2], N_TOK)
        rep = RouterJournal(jr.path, fsync="off", clock=clock).replay()
        assert len(rep.live) == 2 and rep.rejected == 1
        assert router.run()                   # accepted work completes

    def test_submit_append_fault_refuses_the_submit(self, model,
                                                    tmp_path):
        router, jr, clock = _journaled_router(model, tmp_path)
        with FaultInjector(seed=0) as fi:
            fi.arm("journal.append", nth=1)
            with pytest.raises(FaultError):
                router.submit(JOBS[0], N_TOK)
        assert not router.requests            # nothing was accepted
        assert all(h.outstanding() == 0 for h in router.replicas)

    def test_terminal_records_reconcile_with_router_counters(
            self, model, tmp_path, oracle):
        router, jr, clock = _journaled_router(model, tmp_path)
        ids = _submit_jobs(router)
        out = router.run()
        assert [out[i] for i in ids] == oracle   # journaling is inert
        snap = telemetry.snapshot()["counters"]
        terminals = sum(
            snap["pdt_router_requests_terminal_total"].values())
        assert telemetry.value("pdt_journal_records_total",
                               kind="terminal") == terminals == len(JOBS)

    def test_progress_append_fault_counted_not_fatal(self, model,
                                                     tmp_path, oracle):
        router, jr, clock = _journaled_router(model, tmp_path)
        ids = _submit_jobs(router)
        with FaultInjector(seed=0) as fi:
            # nth=1 from here lands on the next journal append — a
            # progress mirror (submits already journaled)
            fi.arm("journal.append", nth=1)
            router.step()
        out = router.run()
        assert [out[i] for i in ids] == oracle
        assert telemetry.value("pdt_journal_append_failures_total") >= 1

    def test_release_request_journals_release(self, model, tmp_path):
        router, jr, clock = _journaled_router(model, tmp_path)
        rid = router.submit(JOBS[0], N_TOK)
        router.run()
        router.release_request(rid)
        jr.compact()
        rep = RouterJournal(jr.path, fsync="off", clock=clock).replay()
        assert not rep.live and not rep.finished

    def test_fleet_info_journal_section(self, model, tmp_path):
        router, jr, clock = _journaled_router(model, tmp_path)
        router.submit(JOBS[0], N_TOK)
        info = router.fleet_info()
        assert info["journal"]["segments"] >= 1
        assert info["journal"]["tracked_live"] == 1
        assert info["journal"]["fsync"] == "off"


# -- the chaos drill: SIGKILL the router at every phase ----------------
class TestRouterRecovery:
    def _recover(self, model, tmp_path, clock, name="wal", **kw):
        """A fresh incarnation: new journal object on the same path
        (SIGKILL semantics — nothing of the old process survives but
        the directory)."""
        jr2 = RouterJournal(os.path.join(str(tmp_path), name),
                            fsync="off", clock=clock)
        return ServingRouter.recover(
            jr2, _factory(model, clock), num_replicas=2, clock=clock,
            sleep=clock.advance, **kw), jr2

    def test_phase1_post_submit_pre_dispatch(self, model, tmp_path,
                                             oracle):
        """The durability point: the submit record alone (no dispatch
        ever happened) recovers to the full bit-identical stream."""
        clock = FakeClock()
        jr = RouterJournal(tmp_path / "wal", fsync="off", clock=clock)
        for i, p in enumerate(JOBS):
            jr.append_submit(request_id=f"fleet-{i}", prompt=p,
                             max_new_tokens=N_TOKS[i])
        jr.close()
        router, _ = self._recover(model, tmp_path, clock)
        out = router.run()
        assert [out[f"fleet-{i}"] for i in range(len(JOBS))] == oracle
        assert telemetry.value("pdt_journal_replay_recovered_total") \
            == len(JOBS)

    def test_phase2_mid_decode_with_dedupe_reconciliation(
            self, model, tmp_path, oracle):
        """SIGKILL mid-decode with some requests already finished:
        live ones re-prefill from the journaled mirror, finished ones
        restore WITHOUT re-execution, and the journal/terminal
        counters reconcile exactly."""
        router, jr, clock = _journaled_router(model, tmp_path)
        ids = _submit_jobs(router)
        finished_before = []
        while len(finished_before) < 1:       # run until someone ends
            finished_before += [r.request_id for r in router.step()]
        assert any(not router.requests[i].done for i in ids)
        del router                            # SIGKILL-shaped
        recovered, jr2 = self._recover(model, tmp_path, clock)
        # dedupe: the finished ones came back terminal, un-dispatched
        for rid in finished_before:
            rec = recovered.requests[rid]
            assert rec.done and rec.dispatches == 0
        assert telemetry.value("pdt_journal_replay_deduped_total") \
            == len(finished_before)
        assert telemetry.value("pdt_journal_replay_recovered_total") \
            == len(JOBS) - len(finished_before)
        out = recovered.run()
        assert [out[i] for i in ids] == oracle
        # exact reconciliation across BOTH incarnations: every fleet
        # terminal wrote exactly one journal terminal record — the
        # restored (deduped) ones did NOT write or count a second one
        snap = telemetry.snapshot()["counters"]
        terminals = sum(
            snap["pdt_router_requests_terminal_total"].values())
        assert terminals == len(JOBS)
        assert telemetry.value("pdt_journal_records_total",
                               kind="terminal") == terminals

    def test_phase3_pre_terminal_flush(self, model, tmp_path, oracle):
        """SIGKILL in the window where a request finished on the
        engine but its terminal record never flushed: recovery re-runs
        it (it is live per the journal) and greedy determinism makes
        the re-execution bit-identical."""
        router, jr, clock = _journaled_router(model, tmp_path)
        ids = _submit_jobs(router)
        lost_terminals = []
        while not lost_terminals:
            with FaultInjector(seed=0) as fi:
                # every journal append in this tick fails — when the
                # tick finalizes a request, its terminal record is
                # exactly the write a pre-flush SIGKILL would lose
                fi.arm("journal.append", always=True)
                lost_terminals += [r.request_id for r in router.step()]
        del router
        recovered, jr2 = self._recover(model, tmp_path, clock)
        # the lost-terminal request replays as LIVE: re-executed, not
        # deduped
        assert telemetry.value("pdt_journal_replay_deduped_total") == 0
        out = recovered.run()
        assert [out[i] for i in ids] == oracle

    def test_torn_progress_tail_still_bit_identical(self, model,
                                                    tmp_path, oracle):
        """Truncate the journal mid-record before recovery: the lost
        mirror suffix re-generates bit-identically from the shorter
        folded re-prefill (why fsync="terminal" stays zero-loss)."""
        router, jr, clock = _journaled_router(model, tmp_path)
        ids = _submit_jobs(router)
        router.step()
        router.step()
        del router
        # the buffered progress mirrors reached the OS page cache ...
        jr.flush()
        seg = _segment_files(jr.path)[-1]
        p = os.path.join(jr.path, seg)
        blob = open(p, "rb").read()
        start, end = _record_spans(blob)[-1]
        with open(p, "r+b") as f:
            f.truncate((start + end) // 2)    # ... then the OS tore it
        recovered, _ = self._recover(model, tmp_path, clock)
        out = recovered.run()
        assert [out[i] for i in ids] == oracle
        assert telemetry.value("pdt_journal_corrupt_tail_total") == 1

    def test_recover_finalizes_expired_deadlines_honestly(
            self, model, tmp_path):
        """A deadline that expired while the router was ALIVE (proved
        by journaled clock stamps past it) finalizes as an honest
        TIMEOUT at recovery — dead time after the kill doesn't matter
        (the re-anchoring drill below proves it burns no budget)."""
        router, jr, clock = _journaled_router(model, tmp_path)
        rid = router.submit(JOBS[0], N_TOK, deadline=5.0)
        clock.advance(6.0)                    # alive past the deadline
        # a second submit stamps the journal's clock at t=6 WITHOUT
        # stepping (the live router never got to finalize the expiry)
        rid2 = router.submit(JOBS[1], N_TOK)
        del router
        clock.advance(60.0)                   # the router was dead
        recovered, jr2 = self._recover(model, tmp_path, clock)
        rec = recovered.requests[rid]
        assert rec.status == RequestStatus.TIMEOUT
        delivered = recovered.step()          # backlog delivery
        assert [r.request_id for r in delivered] == [rid]
        out = recovered.run()                 # the fresh one completes
        assert len(out[rid2]) == N_TOK
        # the honest timeout was journaled: a SECOND recovery dedupes
        recovered2, _ = self._recover(model, tmp_path, clock,
                                      name="wal")
        assert recovered2.requests[rid].status == RequestStatus.TIMEOUT
        assert recovered2.requests[rid].dispatches == 0

    def test_recover_reanchors_deadlines_as_remaining_time(
            self, model, tmp_path):
        """The satellite-1 regression drill: a SLOW RESTART must not
        mass-expire live requests. Deadlines replay as remaining-time-
        at-last-journaled-stamp, so only time the router provably
        spent alive burns budget — the 300s dead gap here would have
        expired every request under absolute-deadline replay."""
        router, jr, clock = _journaled_router(model, tmp_path)
        rids = [router.submit(p, n, deadline=50.0)
                for p, n in zip(JOBS, N_TOKS)]
        router.step()                         # stamped progress at ~t0
        alive_t = clock()
        del router
        clock.advance(300.0)                  # crash + slow restart
        recovered, jr2 = self._recover(model, tmp_path, clock)
        for rid in rids:
            rec = recovered.requests[rid]
            assert rec.status != RequestStatus.TIMEOUT
            # the full ~50s budget survived, minus only alive time
            assert rec.deadline_abs == pytest.approx(
                clock() + 50.0 - alive_t, abs=1.0)
        # the re-anchored deadlines survive a COMPACTION (snapshots
        # re-stamp in the compacting incarnation's epoch) + a SECOND
        # 300s dead gap: budgets never double-burn, work completes
        jr2.compact()
        del recovered
        clock.advance(300.0)
        recovered2, _ = self._recover(model, tmp_path, clock)
        for rid in rids:
            assert recovered2.requests[rid].status \
                != RequestStatus.TIMEOUT
        out = recovered2.run()
        assert sorted(len(out[r]) for r in rids) == sorted(N_TOKS)

    def test_recover_restores_qos_budget_context(self, model, tmp_path):
        clock = FakeClock()
        jr = RouterJournal(tmp_path / "wal", fsync="off", clock=clock)
        admission = QosAdmission(budgets={"acme": 1000},
                                 tenant_window_s=300.0, clock=clock)
        router = ServingRouter(_factory(model, clock), num_replicas=2,
                               clock=clock, sleep=clock.advance,
                               journal=jr, admission=admission)
        rid = router.submit(JOBS[0], N_TOK, lane="batch", tenant="acme")
        cost = len(JOBS[0]) + N_TOK
        assert admission.stats()["tenants"]["acme"]["used_tokens"] \
            == cost
        router.step()
        del router
        adm2 = QosAdmission(budgets={"acme": 1000},
                            tenant_window_s=300.0, clock=clock)
        jr2 = RouterJournal(tmp_path / "wal", fsync="off", clock=clock)
        recovered = ServingRouter.recover(
            jr2, _factory(model, clock), num_replicas=2, clock=clock,
            sleep=clock.advance, admission=adm2)
        # the live request re-charged its TENANT BUDGET in the new
        # incarnation, but not the admit ledger (the old incarnation
        # counted that admission): the cross-incarnation identity is
        # terminals == committed admits + replay-recovered
        assert adm2.stats()["tenants"]["acme"]["used_tokens"] == cost
        assert recovered.requests[rid].lane == "batch"
        recovered.run()
        snap = telemetry.snapshot()["counters"]
        admits = sum(
            v for k, v in snap["pdt_admission_decisions_total"].items()
            if 'decision="admit"' in k)
        terminals = sum(
            snap["pdt_router_requests_terminal_total"].values())
        recovered_n = telemetry.value(
            "pdt_journal_replay_recovered_total")
        assert admits == 1 and recovered_n == 1
        assert terminals == admits == recovered_n

    def test_recovered_ids_stay_idempotent(self, model, tmp_path,
                                           oracle):
        router, jr, clock = _journaled_router(model, tmp_path)
        ids = _submit_jobs(router)
        router.step()
        del router
        recovered, _ = self._recover(model, tmp_path, clock)
        # a client re-submitting after the crash (it never saw the
        # response) gets the SAME id back, no double-generation
        assert recovered.submit(JOBS[0], N_TOKS[0],
                                request_id=ids[0]) == ids[0]
        out = recovered.run()
        assert [out[i] for i in ids] == oracle

    def test_replay_fault_propagates_to_recover(self, model, tmp_path):
        clock = FakeClock()
        jr = RouterJournal(tmp_path / "wal", fsync="off", clock=clock)
        jr.append_submit(request_id="a", prompt=[1], max_new_tokens=4)
        with FaultInjector(seed=0) as fi:
            fi.arm("journal.replay", nth=1)
            with pytest.raises(FaultError):
                ServingRouter.recover(jr, _factory(model, clock),
                                      num_replicas=2, clock=clock,
                                      sleep=clock.advance)

    def test_recovery_emits_span_and_histogram(self, model, tmp_path):
        router, jr, clock = _journaled_router(model, tmp_path)
        router.submit(JOBS[0], N_TOK)
        router.step()
        del router
        recovered, _ = self._recover(model, tmp_path, clock)
        names = [e["name"] for e in telemetry.events()]
        assert "journal.replay" in names
        assert "journal.recovered" in names
        snap = telemetry.snapshot()["histograms"]
        assert snap["pdt_journal_recovery_seconds"][""]["count"] == 1
        recovered.run()
