"""Distributed checkpoint tests: sharded save + cross-mesh reshard restore.
≙ reference «test/auto_parallel/» reshard/ckpt tests (SURVEY.md §4/§5)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # heavy tier (VERDICT r3 #9)

import jax

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                               save_state_dict)

rng = np.random.default_rng(9)


class TestDistCheckpoint:
    def test_cross_mesh_reshard_restore(self, tmp_path):
        """Save sharded on mesh (dp=4, mp=2); restore onto (dp=2, mp=4)."""
        mesh_a = dist.create_mesh(dp=4, mp=2)
        mesh_b = dist.create_mesh(dp=2, mp=4)
        w = rng.normal(size=(16, 8)).astype(np.float32)
        b = rng.normal(size=(8,)).astype(np.float32)

        ta = dist.shard_tensor(paddle.to_tensor(w), mesh_a,
                               [dist.Shard(0), dist.Shard(1)])
        tb = dist.shard_tensor(paddle.to_tensor(b), mesh_a,
                               [dist.Replicate(), dist.Shard(0)])
        sd = {"linear": {"weight": ta, "bias": tb}}
        save_state_dict(sd, str(tmp_path / "ckpt"))

        wa2 = dist.shard_tensor(paddle.to_tensor(np.zeros_like(w)), mesh_b,
                                [dist.Shard(1), dist.Shard(0)])
        tb2 = dist.shard_tensor(paddle.to_tensor(np.zeros_like(b)), mesh_b,
                                [dist.Shard(0), dist.Replicate()])
        sd2 = {"linear": {"weight": wa2, "bias": tb2}}
        load_state_dict(sd2, str(tmp_path / "ckpt"))

        np.testing.assert_array_equal(
            np.asarray(sd2["linear"]["weight"]._value), w)
        np.testing.assert_array_equal(
            np.asarray(sd2["linear"]["bias"]._value), b)
        # restored with mesh_b's sharding
        spec = sd2["linear"]["weight"]._value.sharding.spec
        assert tuple(spec) == ("mp", "dp"), spec

    def test_model_state_roundtrip(self, tmp_path):
        from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                             shard_llama)
        mesh = dist.create_mesh(dp=2, sharding=2, mp=2)
        paddle.seed(0)
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        with dist.use_mesh(mesh):
            shard_llama(model, mesh)
            save_state_dict(model.state_dict(), str(tmp_path / "m"))

            paddle.seed(1)
            model2 = LlamaForCausalLM(cfg)
            shard_llama(model2, mesh)
            load_state_dict(model2.state_dict(), str(tmp_path / "m"))
        for (n1, p1), (n2, p2) in zip(model.named_parameters(),
                                      model2.named_parameters()):
            np.testing.assert_array_equal(np.asarray(p1._value),
                                          np.asarray(p2._value), err_msg=n1)

    def test_async_save(self, tmp_path):
        t = paddle.to_tensor(rng.normal(size=(4, 4)).astype(np.float32))
        ck = save_state_dict({"t": t}, str(tmp_path / "a"), async_save=True)
        ck.wait_until_finished()
        t2 = paddle.to_tensor(np.zeros((4, 4), np.float32))
        load_state_dict({"t": t2}, str(tmp_path / "a"))
        np.testing.assert_array_equal(t2.numpy(), t.numpy())


class TestDurableShardedCheckpoints:
    """Commit protocol + integrity manifests over GSPMD-sharded saves
    (docs/checkpointing.md): many tensorstore files per checkpoint, so
    torn/corrupt state is the common failure — resume must reshard the
    fallback checkpoint onto the NEW mesh, not crash-loop."""

    def test_sharded_verify_and_cross_mesh_fallback(self, tmp_path):
        import os

        from paddle_tpu.distributed.checkpoint import verify_checkpoint
        from paddle_tpu.distributed.fleet.elastic import ElasticManager
        from paddle_tpu.models.llama import (LlamaConfig,
                                             LlamaForCausalLM,
                                             shard_llama)

        mesh_a = dist.create_mesh(dp=4, mp=2)
        paddle.seed(0)
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        em = ElasticManager(str(tmp_path), save_interval_steps=1,
                            sleep=lambda _: None)
        with dist.use_mesh(mesh_a):
            shard_llama(model, mesh_a)
            em.save(0, model)
            em.save(1, model)
        for step in (0, 1):
            res = verify_checkpoint(str(tmp_path / f"step_{step}"),
                                    rehash=True)
            assert res.ok, res.errors
            assert res.arrays_checked == len(
                list(model.named_parameters()))
        # manifest records the sharding layout it was written under
        import json
        manifest = json.loads(
            (tmp_path / "step_1" / "MANIFEST.json").read_text())
        assert manifest["mesh"]["device_count"] == 8
        assert any("sharding" in e
                   for e in manifest["groups"]["model"].values())

        # flip bytes in the newest checkpoint's shards, then resume a
        # DIFFERENTLY-meshed job: quarantine + fallback + reshard
        from paddle_tpu.utils.faults import flip_ocdbt_shards
        flip_ocdbt_shards(tmp_path / "step_1")
        mesh_b = dist.create_mesh(dp=2, mp=4)
        paddle.seed(1)
        model2 = LlamaForCausalLM(cfg)
        with dist.use_mesh(mesh_b):
            shard_llama(model2, mesh_b)
            start = em.resume(model2)
        assert start == 1
        assert (tmp_path / "step_1.corrupt").exists()
        for (n1, p1), (n2, p2) in zip(model.named_parameters(),
                                      model2.named_parameters()):
            np.testing.assert_array_equal(np.asarray(p1._value),
                                          np.asarray(p2._value),
                                          err_msg=n1)
