"""Elastic autoscaling control plane (ISSUE 16):
`ServingRouter.resize()` two-phase crash-durable fleet resizing and
the `FleetAutoscaler` control loop (`serving/autoscaler.py`).

The acceptance drill threaded through this file: a router SIGKILL at
EVERY journal record boundary inside a scale-up AND a scale-down
(before/after INTENT, mid-mutation, before/after COMMIT — the
``autoscale.resize`` fault site), at tp=1 and tp=2, followed by
`recover()`, yields the fleet in exactly the old topology (killed
before the intent reached disk) or the new one (any later instant),
with zero lost or duplicated requests and greedy streams BIT-IDENTICAL
to an undisturbed fleet. conftest runs this file with PDT_TELEMETRY=1
and PDT_CHECK_INVARIANTS=1."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.serving import (ContinuousBatchingEngine,
                                       RequestStatus)
from paddle_tpu.serving import (AutoscalePolicy, FleetAutoscaler,
                                ReplicaRole, ReplicaState,
                                RouterJournal, ServingRouter)
from paddle_tpu.utils.faults import FaultError, FaultInjector

pytestmark = pytest.mark.chaos


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def model():
    # head counts divisible by the tp=2 carve the drills use
    cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64)
    paddle.seed(7)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _factory(model, clock=None, **kw):
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", 4)

    def make(index, submesh=None):
        return ContinuousBatchingEngine(model, clock=clock,
                                        submesh=submesh, **kw)

    return make


def _jobs(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 64, int(rng.integers(4, 8))).tolist()
            for _ in range(n)]


JOBS = _jobs()
N_TOKS = [4, 10, 8, 14]      # staggered so finished + live coexist


def _fleet(model, num_replicas=2, clock=None, **kw):
    clock = clock if clock is not None else FakeClock()
    kw.setdefault("page_size", 4)   # match the engines' page size so
    #                                 prefix spill stays live
    router = ServingRouter(_factory(model, clock),
                           num_replicas=num_replicas, clock=clock,
                           sleep=clock.advance, **kw)
    return router, clock


def _submit_jobs(router):
    return [router.submit(p, n) for p, n in zip(JOBS, N_TOKS)]


@pytest.fixture(scope="module")
def oracle(model):
    """The undisturbed fleet's streams for JOBS — greedy decoding is
    batching-invariant AND tp-invariant (exact mode), so every resize
    drill below must reproduce these exactly."""
    router, _ = _fleet(model)
    ids = _submit_jobs(router)
    out = router.run()
    return [out[i] for i in ids]


# -- resize(): the operator surface ------------------------------------
class TestResize:
    def test_noop_resize_reports_unchanged(self, model):
        router, _ = _fleet(model, num_replicas=2)
        res = router.resize(num_replicas=2)
        assert res == {"changed": False,
                       "topology": {"num_replicas": 2,
                                    "roles": ["colocated"] * 2,
                                    "tp": None}}
        assert router.num_resizes == 0

    def test_grow_and_shrink_mid_flight_bit_identical(self, model,
                                                      oracle):
        router, _ = _fleet(model, num_replicas=2)
        ids = _submit_jobs(router)
        router.step()
        grew = router.resize(num_replicas=4)
        assert grew["kind"] == "grow" and len(router.replicas) == 4
        router.step()
        shrunk = router.resize(num_replicas=1)
        assert shrunk["kind"] == "shrink"
        assert len(router.replicas) == 1
        out = router.run()
        assert [out[i] for i in ids] == oracle
        assert router.num_resizes == 2
        info = router.fleet_info()
        assert info["resizes"] == 2 and info["resize_seq"] == 2

    def test_shrink_drains_via_migration_and_spills_prefixes(
            self, model):
        """Scale-down is a DRAIN, not a kill: running requests with
        output move warm through the transfer plane, and on
        role-managed fleets their prefix payloads spill into the
        fleet store."""
        router, _ = _fleet(model, num_replicas=2,
                           roles="prefill:1,decode:1")
        assert router.prefix_store is not None
        ids = _submit_jobs(router)
        # run until decode work actually lives on replica 1 (the
        # doomed top slot of the shrink below)
        for _ in range(40):
            router.step()
            if any(not rec.done and rec.replica == 1
                   and rec.engine_req is not None
                   and rec.engine_req.output
                   for rec in router._live.values()):
                break
        else:
            pytest.fail("no running request landed on replica 1")
        res = router.resize(roles="colocated:1")
        assert res["kind"] == "shrink"
        # num_migrations increments ONLY in the scale-down drain (the
        # disagg prefill->decode handoff has its own counter)
        assert router.num_migrations >= 1
        store = router.prefix_store.stats()
        assert store["spilled_chains"] >= 1 \
            and store["spilled_bytes"] > 0
        assert int(telemetry.value(
            "pdt_prefix_store_spilled_bytes")) > 0
        out = router.run()
        assert all(len(out[i]) == n for i, n in zip(ids, N_TOKS))

    def test_recarve_tp_mid_flight_bit_identical(self, model, oracle):
        """A tp change rebuilds every slot on the new carve; live
        requests re-enter through the failover fold-in and the greedy
        streams never fork."""
        router, _ = _fleet(model, num_replicas=2)
        ids = _submit_jobs(router)
        router.step()
        res = router.resize(tp=2)
        assert res["kind"] == "recarve"
        assert router._tp_cfg is not None and router._tp_cfg.tp == 2
        assert all(h.submesh is not None for h in router.replicas)
        out = router.run()
        assert [out[i] for i in ids] == oracle

    def test_roles_only_resize_relabels(self, model):
        router, _ = _fleet(model, num_replicas=2)
        res = router.resize(roles="prefill:1,decode:1")
        assert res["kind"] == "roles" and res["changed"]
        assert [h.role for h in router.replicas] \
            == [ReplicaRole.PREFILL, ReplicaRole.DECODE]
        assert router.roles_enabled and router.prefix_store is not None

    def test_impossible_targets_refuse_before_intent(self, model,
                                                     tmp_path):
        jr = RouterJournal(tmp_path / "wal", fsync="off")
        router, _ = _fleet(model, num_replicas=2, journal=jr)
        with pytest.raises(ValueError):
            router.resize(num_replicas=0)
        with pytest.raises(ValueError):
            router.resize(roles="decode:2")     # nothing can prefill
        with pytest.raises(ValueError):
            router.resize(num_replicas=8, tp=2)  # 16 devices > 8
        # none of the refusals journaled an intent
        assert telemetry.value("pdt_journal_records_total",
                               kind="resize_intent") == 0

    def test_grow_lands_in_probation_on_canary_fleets(self, model):
        from paddle_tpu.serving import CanaryConfig
        router, _ = _fleet(model, num_replicas=1,
                           canary=CanaryConfig(interval=1000.0,
                                               max_new_tokens=4))
        router.resize(num_replicas=2)
        assert router.replicas[1].state == ReplicaState.PROBATION
        assert router.replicas[0].state == ReplicaState.HEALTHY
        # probation clears through the ordinary canary machinery
        ids = _submit_jobs(router)
        out = router.run()
        assert all(len(out[i]) == n for i, n in zip(ids, N_TOKS))


# -- the acceptance chaos drill ----------------------------------------
# the 5 sequential autoscale.resize fault boundaries inside resize():
#   1 before INTENT | 2 after INTENT | 3 mid-mutation (fleet reshaped,
#   stranded work not yet re-routed) | 4 mutated, before COMMIT |
#   5 after COMMIT
_BOUNDARIES = (1, 2, 3, 4, 5)


class TestResizeCrashMatrix:
    def _journaled(self, model, tmp_path, n, tp=None):
        clock = FakeClock()
        jr = RouterJournal(os.path.join(str(tmp_path), "wal"),
                           fsync="off", clock=clock)
        router = ServingRouter(_factory(model, clock),
                               num_replicas=n, tp=tp, clock=clock,
                               sleep=clock.advance, journal=jr)
        return router, jr, clock

    @pytest.mark.parametrize("tp", [None, 2])
    @pytest.mark.parametrize("direction", ["up", "down"])
    @pytest.mark.parametrize("boundary", _BOUNDARIES)
    def test_sigkill_at_every_resize_boundary(self, model, tmp_path,
                                              oracle, boundary,
                                              direction, tp):
        """SIGKILL the router at each journal record boundary inside a
        scale-up and a scale-down, tp=1 and tp=2: recover() lands on
        the OLD topology iff the kill preceded the durable INTENT
        (boundary 1) and the NEW topology anywhere later (roll
        forward), with no lost or duplicated requests and streams
        bit-identical to the undisturbed fleet."""
        n_old = 1 if direction == "up" else 2
        n_new = 2 if direction == "up" else 1
        router, jr, clock = self._journaled(model, tmp_path, n_old,
                                            tp=tp)
        ids = _submit_jobs(router)
        router.step()                      # mid-flight: tokens mirrored
        router.step()
        with FaultInjector(seed=0) as fi:
            fi.arm("autoscale.resize", nth=boundary)
            with pytest.raises(FaultError):
                router.resize(num_replicas=n_new, reason="drill")
        del router                         # SIGKILL-shaped teardown
        del jr                             # flush the dead buffers
        jr2 = RouterJournal(os.path.join(str(tmp_path), "wal"),
                            fsync="off", clock=clock)
        recovered = ServingRouter.recover(
            jr2, _factory(model, clock), num_replicas=n_old, tp=tp,
            clock=clock, sleep=clock.advance)
        expect = n_old if boundary == 1 else n_new
        assert len(recovered.replicas) == expect, \
            f"boundary {boundary}: recovered into {direction} " \
            f"topology of {len(recovered.replicas)} != {expect}"
        if tp is not None:
            assert recovered._tp_cfg.tp == tp
            assert all(h.submesh is not None
                       for h in recovered.replicas)
        out = recovered.run()
        # zero lost, zero duplicated: exactly the submitted ids are
        # terminal, each FINISHED exactly once, bit-identical
        assert sorted(out) == sorted(ids)
        assert [out[i] for i in ids] == oracle
        assert all(recovered.requests[i].status
                   == RequestStatus.FINISHED for i in ids)
        # an interrupted transaction (boundaries 2-4) rolled FORWARD:
        # recovery appended the closing commit itself
        replay_again = RouterJournal(
            os.path.join(str(tmp_path), "wal"), fsync="off",
            clock=clock).replay()
        assert replay_again.resize_rolled_forward is False
        if boundary == 1:
            assert replay_again.topology is None
        else:
            assert replay_again.topology["num_replicas"] == n_new

    def test_second_recovery_is_stable(self, model, tmp_path, oracle):
        """Recover, kill again WITHOUT completing the work, recover
        again: the rolled-forward topology and the streams hold."""
        router, jr, clock = self._journaled(model, tmp_path, 1)
        ids = _submit_jobs(router)
        router.step()
        with FaultInjector(seed=0) as fi:
            fi.arm("autoscale.resize", nth=3)
            with pytest.raises(FaultError):
                router.resize(num_replicas=2, reason="drill")
        del router
        del jr
        jr2 = RouterJournal(os.path.join(str(tmp_path), "wal"),
                            fsync="off", clock=clock)
        rec1 = ServingRouter.recover(jr2, _factory(model, clock),
                                     num_replicas=1, clock=clock,
                                     sleep=clock.advance)
        assert len(rec1.replicas) == 2
        rec1.step()                        # partial progress only
        del rec1
        del jr2
        jr3 = RouterJournal(os.path.join(str(tmp_path), "wal"),
                            fsync="off", clock=clock)
        rec2 = ServingRouter.recover(jr3, _factory(model, clock),
                                     num_replicas=1, clock=clock,
                                     sleep=clock.advance)
        assert len(rec2.replicas) == 2
        out = rec2.run()
        assert [out[i] for i in ids] == oracle


# -- the control loop --------------------------------------------------
class TestAutoscalePolicy:
    def test_policy_validation(self):
        with pytest.raises(ValueError):
            AutoscalePolicy(min_replicas=0)
        with pytest.raises(ValueError):
            AutoscalePolicy(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError):
            AutoscalePolicy(max_step=0)
        with pytest.raises(ValueError):
            AutoscalePolicy(scale_up_depth=1.0, scale_down_depth=2.0)
        with pytest.raises(ValueError):
            AutoscalePolicy(up_ticks=0)


class TestFleetAutoscaler:
    def _scaler(self, model, *, policy=None, n=1, interval=1.0,
                **fleet_kw):
        router, clock = _fleet(model, num_replicas=n, **fleet_kw)
        policy = policy or AutoscalePolicy(
            min_replicas=1, max_replicas=3, scale_up_depth=2.0,
            scale_down_depth=0.5, up_ticks=2, down_ticks=3,
            cooldown_s=2.0, max_step=1)
        return FleetAutoscaler(router, policy, interval_s=interval,
                               clock=clock), router, clock

    def _tick(self, scaler, router, clock, n, step=True):
        out = []
        for _ in range(n):
            if step:
                router.step()
            clock.advance(1.0)
            res = scaler.tick()
            if res is not None:
                out.append(res)
        return out

    def test_hysteresis_needs_consecutive_pressure(self, model):
        scaler, router, clock = self._scaler(model)
        _submit_jobs(router)               # 4 outstanding on 1 replica
        clock.advance(1.0)
        first = scaler.tick()              # first high observation
        assert first["action"] == "hold" and len(router.replicas) == 1
        clock.advance(1.0)
        second = scaler.tick()             # streak reaches up_ticks
        assert second["action"] == "grow"
        assert len(router.replicas) == 2
        assert second["reaction_s"] == pytest.approx(1.0)
        router.run()

    def test_scale_down_at_sustained_idle_and_floor(self, model):
        scaler, router, clock = self._scaler(model, n=3)
        acts = [r["action"] for r in
                self._tick(scaler, router, clock, 30, step=False)]
        assert acts.count("shrink") == 2   # 3 -> 2 -> 1, then floored
        assert len(router.replicas) == 1
        assert {"action": "hold", "reason": "at_min_replicas"} in [
            {k: r[k] for k in ("action", "reason")}
            for r in self._tick(scaler, router, clock, 5, step=False)]

    def test_cooldown_blocks_flapping(self, model):
        policy = AutoscalePolicy(min_replicas=1, max_replicas=4,
                                 scale_up_depth=2.0,
                                 scale_down_depth=0.5, up_ticks=1,
                                 down_ticks=1, cooldown_s=30.0,
                                 max_step=1)
        scaler, router, clock = self._scaler(model, policy=policy)
        _submit_jobs(router)
        self._tick(scaler, router, clock, 1)
        assert len(router.replicas) == 2   # grew once...
        held = self._tick(scaler, router, clock, 5)
        assert len(router.replicas) == 2   # ...then cooldown holds
        assert not any(r["action"] in ("grow", "shrink") for r in held)
        assert any(r == {"action": "hold", "reason": "cooldown",
                         "until": r.get("until")} and r["until"] >= 30.0
                   for r in held)
        router.run()

    def test_max_step_and_max_replicas_clamp(self, model):
        policy = AutoscalePolicy(min_replicas=1, max_replicas=2,
                                 scale_up_depth=1.0,
                                 scale_down_depth=0.0, up_ticks=1,
                                 down_ticks=99, cooldown_s=0.0,
                                 max_step=1)
        scaler, router, clock = self._scaler(model, policy=policy)
        _submit_jobs(router)
        acts = self._tick(scaler, router, clock, 6)
        assert len(router.replicas) == 2   # one step at a time, capped
        assert [a["action"] for a in acts].count("grow") == 1
        assert any(a.get("reason") == "at_max_replicas" for a in acts)
        router.run()

    def test_degraded_mode_refuses_scale_up_while_quarantined(
            self, model):
        scaler, router, clock = self._scaler(model, n=2)
        router.replicas[1].state = ReplicaState.QUARANTINED
        _submit_jobs(router)
        # step=False: the queue must stay deep through the drill so
        # the only thing standing between pressure and a grow is the
        # quarantined replica
        refusals = [r for r in
                    self._tick(scaler, router, clock, 4, step=False)
                    if r["action"] == "refused"]
        assert refusals and all(r["reason"] == "quarantined"
                                for r in refusals)
        assert len(router.replicas) == 2   # the fleet did NOT grow
        assert scaler.num_refusals == len(refusals)
        assert telemetry.value("pdt_autoscaler_refusals_total",
                               reason="quarantined") \
            == len(refusals)
        # the fleet heals -> the pent-up streak acts immediately
        router.replicas[1].state = ReplicaState.HEALTHY
        clock.advance(1.0)
        assert scaler.tick()["action"] == "grow"
        router.run()

    def test_degraded_mode_refuses_scale_up_on_journal_failures(
            self, model):
        scaler, router, clock = self._scaler(model)
        _submit_jobs(router)
        clock.advance(1.0)
        scaler.tick()                          # high streak = 1
        router.journal_append_failures += 1    # fsync trouble tick
        clock.advance(1.0)
        res = scaler.tick()                    # streak due -> refused
        assert res == {"action": "refused", "reason": "journal_failing"}
        assert len(router.replicas) == 1
        # failures stopped advancing -> the next due tick proceeds
        clock.advance(1.0)
        assert scaler.tick()["action"] == "grow"
        router.run()

    def test_roles_mix_policy_applies_on_resize(self, model):
        policy = AutoscalePolicy(min_replicas=1, max_replicas=4,
                                 scale_up_depth=1.0,
                                 scale_down_depth=0.0, up_ticks=1,
                                 down_ticks=99, cooldown_s=0.0,
                                 max_step=3, prefill_fraction=0.5)
        scaler, router, clock = self._scaler(model, policy=policy)
        _submit_jobs(router)
        clock.advance(1.0)
        router.step()
        res = scaler.tick()
        assert res["action"] == "grow"
        assert [h.role for h in router.replicas] \
            == [ReplicaRole.PREFILL, ReplicaRole.PREFILL,
                ReplicaRole.DECODE, ReplicaRole.DECODE]
        router.run()

    def test_wide_tp_recarve_at_idle_and_back_under_pressure(
            self, model):
        policy = AutoscalePolicy(min_replicas=1, max_replicas=2,
                                 scale_up_depth=2.0,
                                 scale_down_depth=0.5, up_ticks=2,
                                 down_ticks=2, cooldown_s=0.0,
                                 max_step=1, wide_tp=2)
        router, clock = _fleet(model, num_replicas=1, tp=1)
        scaler = FleetAutoscaler(router, policy, interval_s=1.0,
                                 clock=clock)
        # sustained idle at the floor: trade the narrow carve for the
        # wide latency-optimized one
        acts = []
        for _ in range(4):
            clock.advance(1.0)
            r = scaler.tick()
            if r:
                acts.append(r)
        assert any(a["action"] == "recarve" for a in acts)
        assert router._tp_cfg.tp == 2
        # pressure: recarve BACK to the base tp before count-growth
        _submit_jobs(router)
        back = []
        for _ in range(4):
            router.step()
            clock.advance(1.0)
            r = scaler.tick()
            if r:
                back.append(r)
        kinds = [a["action"] for a in back]
        assert "recarve" in kinds
        assert router._tp_cfg.tp == 1
        assert "grow" in kinds[kinds.index("recarve"):] \
            or len(router.replicas) == 2
        router.run()

    def test_journaled_autoscaler_actions_are_resize_transactions(
            self, model, tmp_path):
        clock = FakeClock()
        jr = RouterJournal(tmp_path / "wal", fsync="off", clock=clock)
        router = ServingRouter(_factory(model, clock), num_replicas=1,
                               clock=clock, sleep=clock.advance,
                               journal=jr)
        scaler = FleetAutoscaler(
            router, AutoscalePolicy(min_replicas=1, max_replicas=2,
                                    scale_up_depth=2.0,
                                    scale_down_depth=0.5, up_ticks=1,
                                    down_ticks=99, cooldown_s=0.0),
            interval_s=1.0, clock=clock)
        ids = _submit_jobs(router)
        clock.advance(1.0)
        router.step()
        assert scaler.tick()["action"] == "grow"
        assert telemetry.value("pdt_journal_records_total",
                               kind="resize_intent") == 1
        assert telemetry.value("pdt_journal_records_total",
                               kind="resize_commit") == 1
        out = router.run()
        assert all(len(out[i]) == n for i, n in zip(ids, N_TOKS))

    def test_resize_failure_is_a_visible_refusal_not_a_crash(
            self, model, tmp_path):
        """A journal that cannot append the INTENT fails the resize;
        the control loop records a degraded-mode refusal and keeps
        running instead of dying."""
        clock = FakeClock()
        jr = RouterJournal(tmp_path / "wal", fsync="off", clock=clock)
        router = ServingRouter(_factory(model, clock), num_replicas=1,
                               clock=clock, sleep=clock.advance,
                               journal=jr)
        scaler = FleetAutoscaler(
            router, AutoscalePolicy(min_replicas=1, max_replicas=2,
                                    scale_up_depth=2.0,
                                    scale_down_depth=0.5, up_ticks=1,
                                    down_ticks=99, cooldown_s=0.0),
            interval_s=1.0, clock=clock)
        _submit_jobs(router)
        clock.advance(1.0)
        with FaultInjector(seed=0) as fi:
            fi.arm("journal.append", nth=1)
            res = scaler.tick()
        assert res["action"] == "refused" \
            and res["reason"] == "resize_failed"
        assert len(router.replicas) == 1
        # next tick, healthy journal: the grow goes through
        clock.advance(1.0)
        assert scaler.tick()["action"] == "grow"
        router.run()
