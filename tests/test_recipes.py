"""Recipe CI smoke + text/data tier. ≙ SURVEY.md §6 north-star configs,
§7 steps 4/9; VERDICT r2 item 8."""
import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # heavy tier (VERDICT r3 #9)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.text import (ByteTokenizer, FileTokens, LMBlockDataset,
                             MLMBlockDataset, SyntheticTokens,
                             WordTokenizer, encode_file)  # noqa: E402


class TestTokenizers:
    def test_byte_roundtrip(self):
        tok = ByteTokenizer()
        s = "Hello, TPU! ünïcode 世界"
        assert tok.decode(tok.encode(s)) == s
        assert tok.vocab_size == 261

    def test_byte_specials(self):
        tok = ByteTokenizer()
        ids = tok.encode("hi", add_bos=True, add_eos=True)
        assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id

    def test_word_tokenizer_build(self):
        tok = WordTokenizer.build(["the cat sat", "the dog sat"])
        ids = tok.encode("the cat")
        assert len(ids) == 2
        assert tok.decode(ids) == "the cat"
        # oov -> unk
        assert tok.encode("zebra")[0] == tok.vocab.unk_id


class TestDatasets:
    def test_lm_blocks_shift(self):
        src = SyntheticTokens(100, 1001, seed=1)
        ds = LMBlockDataset(src, 50)
        assert len(ds) == 20
        x, y = ds[0]
        np.testing.assert_array_equal(x[1:], y[:-1])

    def test_mlm_masking_rule(self):
        src = SyntheticTokens(200, 4000, seed=2)
        tok = ByteTokenizer()
        ds = MLMBlockDataset(src, 128, mask_id=tok.mask_id,
                             vocab_size=261, seed=3)
        x, y = ds[0]
        masked = y != -100
        assert masked.any()
        # labels hold the ORIGINAL ids at masked positions
        orig = src.ids[:128]
        np.testing.assert_array_equal(y[masked], orig[masked])
        # unmasked inputs unchanged
        np.testing.assert_array_equal(x[~masked], orig[~masked])
        # deterministic per index
        x2, y2 = ds[0]
        np.testing.assert_array_equal(x, x2)

    def test_file_tokens_txt_and_bin(self, tmp_path):
        txt = tmp_path / "c.txt"
        txt.write_text("hello tpu world")
        src = FileTokens(str(txt))
        assert ByteTokenizer().decode(src.ids) == "hello tpu world"
        binp = tmp_path / "c.bin"
        n = encode_file(str(txt), str(binp))
        src2 = FileTokens(str(binp))
        assert len(src2.ids) == n
        np.testing.assert_array_equal(np.asarray(src2.ids, np.int32),
                                      src.ids)


class TestRecipeSmoke:
    """Each north-star recipe runs end-to-end in one command (tiny
    synthetic shapes on the CI mesh)."""

    def test_bert_mlm(self):
        from recipes.bert_mlm import main
        r = main(["--size", "tiny", "--steps", "3", "--batch-size", "2",
                  "--seq-len", "64", "--log-every", "0"])
        assert np.isfinite(r.final_loss)

    def test_llama_pretrain(self):
        from recipes.llama_pretrain import main
        r = main(["--size", "tiny", "--steps", "3", "--batch-size", "2",
                  "--seq-len", "64", "--log-every", "0"])
        assert np.isfinite(r.final_loss)

    def test_llama_serve(self):
        """The serving demo (generate strategies + paged engine with
        prefix caching + speculative decoding) runs end-to-end."""
        from recipes.llama_serve import main
        assert main(["--max-new-tokens", "8", "--num-beams", "2"]) == 0

    def test_llama_pretrain_accumulate_recompute(self):
        from recipes.llama_pretrain import main
        r = main(["--size", "tiny", "--steps", "2", "--batch-size", "4",
                  "--seq-len", "32", "--accumulate-steps", "2",
                  "--recompute", "--log-every", "0"])
        assert np.isfinite(r.final_loss)

    def test_llama_pretrain_mesh(self):
        from recipes.llama_pretrain import main
        r = main(["--size", "tiny", "--steps", "2", "--batch-size", "4",
                  "--seq-len", "32", "--mesh", "dp=2,mp=2",
                  "--log-every", "0"])
        assert np.isfinite(r.final_loss)

    def test_moe_train_ep(self):
        from recipes.moe_train import main
        r = main(["--steps", "2", "--batch-size", "4", "--seq-len", "32",
                  "--mesh", "dp=2,ep=4", "--dropless",
                  "--log-every", "0"])
        assert np.isfinite(r.final_loss)

    def test_recipe_with_file_data_and_save(self, tmp_path):
        from recipes.llama_pretrain import main
        data = tmp_path / "corpus.txt"
        data.write_text("the quick brown fox " * 2000)
        ckpt = tmp_path / "model.pd"
        r = main(["--size", "tiny", "--steps", "2", "--batch-size", "2",
                  "--seq-len", "64", "--data", str(data),
                  "--save", str(ckpt), "--log-every", "0"])
        assert np.isfinite(r.final_loss)
        state = paddle.load(str(ckpt))
        assert len(state) > 0


class TestErnie4D:
    """North-star config #3 (ERNIE 4D hybrid). ≙ BASELINE.md configs."""

    def test_ernie_model_forward_and_loss(self):
        import paddle_tpu as paddle
        from paddle_tpu.models.ernie import (ErnieConfig,
                                             ErnieForPretraining,
                                             ErnieForSequenceClassification,
                                             synthetic_ernie_batch)
        paddle.seed(0)
        cfg = ErnieConfig.tiny()
        m = ErnieForPretraining(cfg)
        ids, labels, sop = synthetic_ernie_batch(2, 32, cfg.vocab_size)
        loss, logits = m(ids, labels=labels, sop_labels=sop)
        assert np.isfinite(float(loss))
        assert tuple(logits.shape) == (2, 32, cfg.vocab_size)

        clf = ErnieForSequenceClassification(cfg, num_classes=3)
        out = clf(ids)
        assert tuple(out.shape) == (2, 3)

    def test_recipe_single_device(self):
        from recipes.ernie_4d import main
        res = main(["--steps", "3", "--batch-size", "2", "--seq-len", "32",
                    "--log-every", "0"])
        assert np.isfinite(res.final_loss)

    def test_recipe_4d_mesh(self):
        from recipes.ernie_4d import main
        res = main(["--steps", "3", "--batch-size", "4", "--seq-len", "32",
                    "--mesh", "dp=2,mp=2,sharding=2", "--log-every", "0"])
        assert np.isfinite(res.final_loss)

    def test_4d_loss_matches_single_device(self):
        """Convergence-parity oracle (SURVEY.md §4 TestDistBase port):
        same seed, same data -> mesh loss == single-device loss."""
        from recipes.ernie_4d import main
        r1 = main(["--steps", "2", "--batch-size", "4", "--seq-len", "32",
                   "--log-every", "0"])
        r2 = main(["--steps", "2", "--batch-size", "4", "--seq-len", "32",
                   "--mesh", "dp=2,mp=2,sharding=2", "--log-every", "0"])
        assert abs(r1.final_loss - r2.final_loss) < 0.05, (r1, r2)


class TestDiTRecipe:
    """North-star config #4 (DiT diffusion)."""

    def test_single_device_with_sampling(self):
        from recipes.dit_train import main
        res = main(["--steps", "3", "--batch-size", "2",
                    "--log-every", "0", "--sample-after"])
        assert np.isfinite(res.final_loss)

    def test_dp_mp_mesh(self):
        from recipes.dit_train import main
        res = main(["--steps", "2", "--batch-size", "4",
                    "--mesh", "dp=4,mp=2", "--log-every", "0"])
        assert np.isfinite(res.final_loss)
