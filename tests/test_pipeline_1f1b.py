"""True-1F1B pipeline schedule tests (8-virtual-device CPU mesh).

≙ reference `PipelineParallel.train_batch` 1F1B
(«.../fleet/meta_parallel/pipeline_parallel.py», SURVEY.md §7 hard part
#1). Oracles: sequential execution + jax.grad, and the GPipe
(grad-of-scan) path. The memory test inspects compiled-HLO temp
allocation to verify the S-bounded (M-independent) activation residency
claim — the defining property of 1F1B.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet.pipeline import (pipeline_1f1b,
                                                   pipeline_forward,
                                                   stack_stage_params)

rng = np.random.default_rng(11)


def _mlp_stage(params, x, *extra):
    w1, w2 = params
    return x + jnp.tanh(x @ w1) @ w2


def _stages(s, h=16, hid=32):
    return [(jnp.asarray(rng.normal(size=(h, hid)).astype(np.float32)
                         * 0.3),
             jnp.asarray(rng.normal(size=(hid, h)).astype(np.float32)
                         * 0.3)) for _ in range(s)]


@pytest.fixture(scope="module")
def pp_mesh():
    return dist.create_mesh(pp=4)


def _seq_losses(per_stage, x, m):
    """Oracle: per-microbatch sum-of-squares through the stage chain."""
    mb = x.shape[0] // m
    out = []
    for i in range(m):
        y = x[i * mb:(i + 1) * mb]
        for p in per_stage:
            y = _mlp_stage(p, y)
        out.append(jnp.sum(y.astype(jnp.float32) ** 2))
    return jnp.stack(out)


class TestOneFOneB:
    @pytest.mark.parametrize("micro", [
        2, 4, pytest.param(8, marks=pytest.mark.slow)])
    def test_losses_match_sequential(self, pp_mesh, micro):
        per_stage = _stages(4)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rng.normal(size=(8, 5, 16)).astype(np.float32))

        def reduce_fn(y, idx):
            return jnp.sum(y.astype(jnp.float32) ** 2)

        def call(sp):
            return pipeline_1f1b(_mlp_stage, sp, x, pp_mesh, micro,
                                 reduce_fn=reduce_fn)

        want = _seq_losses(per_stage, x, micro)
        # fused-scan primal (via vjp -> run_fwd) AND forward-only eval
        # primal (undifferentiated call) must both match
        got_fused, _ = jax.vjp(call, stacked)
        np.testing.assert_allclose(np.asarray(got_fused),
                                   np.asarray(want),
                                   rtol=1e-4, atol=1e-4)
        got_eval = call(stacked)
        np.testing.assert_allclose(np.asarray(got_eval),
                                   np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_grads_match_sequential(self, pp_mesh):
        per_stage = _stages(4)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rng.normal(size=(8, 3, 16)).astype(np.float32))

        def reduce_fn(y, idx):
            return jnp.sum(y.astype(jnp.float32) ** 2)

        def loss_1f1b(sp, xx):
            return jnp.mean(pipeline_1f1b(
                _mlp_stage, sp, xx, pp_mesh, 4, reduce_fn=reduce_fn))

        def loss_seq(sp, xx):
            return jnp.mean(_seq_losses(
                [jax.tree_util.tree_map(lambda l: l[i], sp)
                 for i in range(4)], xx, 4))

        g1 = jax.grad(loss_1f1b, (0, 1))(stacked, x)
        g2 = jax.grad(loss_seq, (0, 1))(stacked, x)
        for a, b in zip(jax.tree_util.tree_leaves(g1),
                        jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_grads_match_gpipe_path(self, pp_mesh):
        """1F1B and grad-of-scan GPipe are the same math."""
        per_stage = _stages(4)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rng.normal(size=(4, 3, 16)).astype(np.float32))

        def reduce_fn(y, idx):
            return jnp.sum(y.astype(jnp.float32) ** 2)

        def l_new(sp):
            return jnp.mean(pipeline_1f1b(
                _mlp_stage, sp, x, pp_mesh, 4, reduce_fn=reduce_fn,
                need_input_grad=False))

        def l_old(sp):
            return jnp.mean(pipeline_forward(
                _mlp_stage, sp, x, pp_mesh, 4, reduce_fn=reduce_fn))

        g1 = jax.grad(l_new)(stacked)
        g2 = jax.grad(l_old)(stacked)
        for a, b in zip(jax.tree_util.tree_leaves(g1),
                        jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_reduce_vector_and_args_grads(self, pp_mesh):
        """(sum, count) reductions: component 0 carries gradient, the
        reduce_args (a trained head weight) receive cotangents, and an
        integer reduce_arg (labels) rides through without one."""
        per_stage = _stages(4)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rng.normal(size=(4, 3, 16)).astype(np.float32))
        w = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
        labels = jnp.asarray(
            rng.integers(0, 2, size=(4, 3)).astype(np.int32))

        def reduce_fn(y, idx, wv, lab):
            li = jax.lax.dynamic_index_in_dim(lab, idx, 0,
                                              keepdims=False)
            per = (y @ wv) * li.astype(jnp.float32)[..., None][..., 0]
            return jnp.stack([jnp.sum(per),
                              jnp.sum(li).astype(jnp.float32)])

        lab_r = labels.reshape(4, 1, 3)

        def loss_new(sp, wv):
            st = pipeline_1f1b(
                _mlp_stage, sp, x, pp_mesh, 4, reduce_fn=reduce_fn,
                reduce_args=(wv, lab_r), reduce_shape=(2,),
                need_input_grad=False)
            return jnp.sum(st[:, 0]) / jnp.maximum(jnp.sum(st[:, 1]), 1.0)

        def loss_old(sp, wv):
            st = pipeline_forward(
                _mlp_stage, sp, x, pp_mesh, 4, reduce_fn=reduce_fn,
                reduce_args=(wv, lab_r), reduce_shape=(2,))
            return jnp.sum(st[:, 0]) / jnp.maximum(jnp.sum(st[:, 1]), 1.0)

        v1, g1 = jax.value_and_grad(loss_new, (0, 1))(stacked, w)
        v2, g2 = jax.value_and_grad(loss_old, (0, 1))(stacked, w)
        np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g1),
                        jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_dp_mesh_grads_match_gpipe(self):
        """dp x pp mesh with reduce_mean_axes=('dp',): the 1F1B manual
        backward must NOT overcount grads by the dp degree (round-4
        code-review finding: psum'd grads + pmean'd losses double-counted
        the mean factor)."""
        mesh = dist.create_mesh(dp=2, pp=4)
        per_stage = _stages(4)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rng.normal(size=(8, 3, 16)).astype(np.float32))

        def reduce_fn(y, idx):
            return jnp.sum(y.astype(jnp.float32) ** 2)

        kw = dict(x_spec=P("dp", None, None),
                  reduce_mean_axes=("dp",))

        def l_new(sp, xx):
            return jnp.mean(pipeline_1f1b(
                _mlp_stage, sp, xx, mesh, 4, reduce_fn=reduce_fn, **kw))

        def l_old(sp, xx):
            return jnp.mean(pipeline_forward(
                _mlp_stage, sp, xx, mesh, 4, reduce_fn=reduce_fn, **kw))

        v1, g1 = jax.value_and_grad(l_new, (0, 1))(stacked, x)
        v2, g2 = jax.value_and_grad(l_old, (0, 1))(stacked, x)
        np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g1),
                        jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_input_grad_flows(self, pp_mesh):
        per_stage = _stages(4)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rng.normal(size=(4, 3, 16)).astype(np.float32))

        def reduce_fn(y, idx):
            return jnp.sum(y.astype(jnp.float32) ** 2)

        def loss_new(xx):
            return jnp.mean(pipeline_1f1b(
                _mlp_stage, stacked, xx, pp_mesh, 4,
                reduce_fn=reduce_fn))

        def loss_seq(xx):
            return jnp.mean(_seq_losses(per_stage, xx, 4))

        g1 = jax.grad(loss_new)(x)
        g2 = jax.grad(loss_seq)(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-5)


class TestMemoryProfile:
    """The defining 1F1B property: activation residency ∝ S, not M
    (VERDICT r3 missing #1 'done' criterion)."""

    def _temp_bytes(self, schedule, mesh, m, mb=4, h=64, hid=128):
        per_stage = [(jnp.asarray(
            rng.normal(size=(h, hid)).astype(np.float32) * 0.2),
            jnp.asarray(rng.normal(size=(hid, h)).astype(np.float32)
                        * 0.2)) for _ in range(4)]
        stacked = stack_stage_params(per_stage)
        x = jnp.zeros((m * mb, 8, h), jnp.float32)

        def reduce_fn(y, idx):
            return jnp.sum(y.astype(jnp.float32) ** 2)

        if schedule == "1f1b":
            def loss(sp, xx):
                return jnp.mean(pipeline_1f1b(
                    _mlp_stage, sp, xx, mesh, m, reduce_fn=reduce_fn,
                    need_input_grad=False))
        else:
            def loss(sp, xx):
                return jnp.mean(pipeline_forward(
                    _mlp_stage, sp, xx, mesh, m, reduce_fn=reduce_fn))

        c = jax.jit(jax.grad(loss)).lower(stacked, x).compile()
        ma = c.memory_analysis()
        return getattr(ma, "temp_size_in_bytes", None)

    def test_residency_independent_of_microbatches(self, pp_mesh):
        vals = {}
        for sched in ("1f1b", "gpipe"):
            lo = self._temp_bytes(sched, pp_mesh, m=4)
            hi = self._temp_bytes(sched, pp_mesh, m=16)
            vals[sched] = (lo, hi)
        if any(v is None for pair in vals.values() for v in pair):
            pytest.skip("memory_analysis unavailable on this backend")
        lo1, hi1 = vals["1f1b"]
        lo2, hi2 = vals["gpipe"]
        print(f"\ncompiled temp bytes (fixed microbatch size, M=4 -> 16):"
              f" 1f1b {lo1} -> {hi1}; gpipe {lo2} -> {hi2}")
        # GPipe residuals grow ~linearly in M; 1F1B's stash must not.
        # 4x the microbatches: allow modest growth (per-microbatch loss
        # buffers etc.) but nothing near the GPipe slope.
        assert hi2 > 2.0 * lo2, (lo2, hi2)          # sanity: oracle grows
        assert hi1 < 1.6 * lo1, (lo1, hi1)          # 1f1b must not
        assert hi1 < hi2 / 2, (hi1, hi2)


class TestInterleavedMultiRound:
    """M > S interleave via sequential rounds (VERDICT r3 missing #1:
    'lift the M <= S interleave constraint')."""

    def _chunks(self, n, h=16, hid=32):
        return [(jnp.asarray(rng.normal(size=(h, hid)).astype(np.float32)
                             * 0.3),
                 jnp.asarray(rng.normal(size=(hid, h)).astype(np.float32)
                             * 0.3)) for _ in range(n)]

    def _stack_interleaved(self, chunks, s, v):
        def leaf(i):
            return jnp.stack(
                [jnp.stack([chunks[vv * s + ss][i] for vv in range(v)])
                 for ss in range(s)])
        return (leaf(0), leaf(1))

    @pytest.mark.parametrize("micro", [8, 12])
    def test_matches_sequential(self, pp_mesh, micro):
        s, v = 4, 2
        chunks = self._chunks(s * v)
        stacked = self._stack_interleaved(chunks, s, v)
        x = jnp.asarray(rng.normal(size=(micro, 5, 16))
                        .astype(np.float32))
        y = pipeline_forward(_mlp_stage, stacked, x, pp_mesh, micro,
                             virtual_chunks=v)
        ref = x
        for c in chunks:
            ref = _mlp_stage(c, ref)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_non_divisible_raises(self, pp_mesh):
        chunks = self._chunks(8)
        stacked = self._stack_interleaved(chunks, 4, 2)
        x = jnp.asarray(rng.normal(size=(6, 5, 16)).astype(np.float32))
        with pytest.raises(ValueError, match="divisible"):
            pipeline_forward(_mlp_stage, stacked, x, pp_mesh, 6,
                             virtual_chunks=2)

    @pytest.mark.slow
    def test_multi_round_grads(self, pp_mesh):
        s, v = 4, 2
        chunks = self._chunks(s * v)
        stacked = self._stack_interleaved(chunks, s, v)
        x = jnp.asarray(rng.normal(size=(8, 5, 16)).astype(np.float32))

        def loss_pipe(st):
            return jnp.sum(pipeline_forward(
                _mlp_stage, st, x, pp_mesh, 8,
                virtual_chunks=v).astype(jnp.float32) ** 2)

        def loss_seq(cs):
            ref = x
            for c in cs:
                ref = _mlp_stage(c, ref)
            return jnp.sum(ref.astype(jnp.float32) ** 2)

        g_pipe = jax.grad(loss_pipe)(stacked)
        g_seq = jax.grad(loss_seq)(chunks)
        for i in range(2):
            got = np.asarray(g_pipe[i])
            for ss in range(s):
                for vv in range(v):
                    np.testing.assert_allclose(
                        got[ss, vv], np.asarray(g_seq[vv * s + ss][i]),
                        rtol=3e-4, atol=3e-4)


class TestInterleaved1F1B:
    """Interleaved (V>1) TRUE 1F1B — table-driven schedule (VERDICT r4
    missing #2): losses/grads vs the sequential oracle, no M % S
    constraint, and the defining flat-in-M activation residency."""

    def _chunks(self, n, h=16, hid=32):
        return [(jnp.asarray(rng.normal(size=(h, hid)).astype(np.float32)
                             * 0.3),
                 jnp.asarray(rng.normal(size=(hid, h)).astype(np.float32)
                             * 0.3)) for _ in range(n)]

    def _stack(self, chunks, s, v):
        def leaf(i):
            return jnp.stack(
                [jnp.stack([chunks[vv * s + ss][i] for vv in range(v)])
                 for ss in range(s)])
        return (leaf(0), leaf(1))

    @staticmethod
    def _reduce(y, idx):
        return jnp.sum(y.astype(jnp.float32) ** 2)

    @pytest.mark.parametrize("micro", [
        pytest.param(4, marks=pytest.mark.slow), 6])
    def test_losses_match_sequential(self, pp_mesh, micro):
        """micro=6 is NOT divisible by S=4 — the schedule's partial last
        group lifts the old GPipe-interleave M % S == 0 constraint."""
        s, v = 4, 2
        chunks = self._chunks(s * v)
        stacked = self._stack(chunks, s, v)
        x = jnp.asarray(rng.normal(size=(micro, 5, 16))
                        .astype(np.float32))

        def call(sp):
            return pipeline_1f1b(_mlp_stage, sp, x, pp_mesh, micro,
                                 reduce_fn=self._reduce,
                                 virtual_chunks=v)

        want = _seq_losses(chunks, x, micro)
        # jax.vjp routes the primal through run_fwd = the fused
        # interleaved scan's loss_buf (the schedule under test) ...
        got_fused, _ = jax.vjp(call, stacked)
        np.testing.assert_allclose(np.asarray(got_fused),
                                   np.asarray(want),
                                   rtol=1e-4, atol=1e-4)
        # ... while the undifferentiated call exercises the
        # forward-only eval primal (falls back to the fused scan when
        # M % S != 0 defeats the GPipe interleave)
        got_eval = call(stacked)
        np.testing.assert_allclose(np.asarray(got_eval),
                                   np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_grads_match_sequential(self, pp_mesh):
        s, v, m = 4, 2, 4
        chunks = self._chunks(s * v)
        stacked = self._stack(chunks, s, v)
        x = jnp.asarray(rng.normal(size=(m, 3, 16)).astype(np.float32))

        def loss_pipe(sp, xx):
            return jnp.mean(pipeline_1f1b(
                _mlp_stage, sp, xx, pp_mesh, m,
                reduce_fn=self._reduce, virtual_chunks=v))

        def loss_seq(cs, xx):
            return jnp.mean(_seq_losses(cs, xx, m))

        g1 = jax.grad(loss_pipe, (0, 1))(stacked, x)
        g2 = jax.grad(loss_seq, (0, 1))(chunks, x)
        for li in range(2):
            got = np.asarray(g1[0][li])
            for ss in range(4):
                for vv in range(v):
                    np.testing.assert_allclose(
                        got[ss, vv], np.asarray(g2[0][vv * 4 + ss][li]),
                        rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(g1[1]), np.asarray(g2[1]),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.slow
    def test_v1_loss_parity(self, pp_mesh):
        """The same 8-layer model, partitioned V=1 (fat stages of 2) vs
        V=2 (single-layer chunks), produces identical losses."""
        s, m = 4, 4
        chunks = self._chunks(8)
        x = jnp.asarray(rng.normal(size=(m, 5, 16)).astype(np.float32))

        def fat_stage(params, xx, *extra):
            for li in range(2):
                xx = _mlp_stage(
                    jax.tree_util.tree_map(lambda l: l[li], params), xx)
            return xx

        fat = stack_stage_params(
            [jax.tree_util.tree_map(
                lambda *ls: jnp.stack(ls), chunks[2 * ss],
                chunks[2 * ss + 1]) for ss in range(s)])
        # interleaved layout runs chunks in virtual order v*S+s = layer
        ilv = self._stack(chunks, s, 2)
        # jax.vjp so the primal is the fused scan (the schedule under
        # test), not the forward-only eval fast path
        l1, _ = jax.vjp(lambda sp: pipeline_1f1b(
            fat_stage, sp, x, pp_mesh, m, reduce_fn=self._reduce), fat)
        l2, _ = jax.vjp(lambda sp: pipeline_1f1b(
            _mlp_stage, sp, x, pp_mesh, m, reduce_fn=self._reduce,
            virtual_chunks=2), ilv)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.slow
    def test_residency_flat_in_m_at_v2(self, pp_mesh):
        s, v = 4, 2
        h, hid = 64, 128

        def temp_bytes(m):
            stacked = tuple(jnp.asarray(
                rng.normal(size=(s, v, *shape)).astype(np.float32) * 0.2)
                for shape in [(h, hid), (hid, h)])
            x = jnp.zeros((m * 4, 8, h), jnp.float32)

            def loss(sp, xx):
                return jnp.mean(pipeline_1f1b(
                    _mlp_stage, sp, xx, pp_mesh, m,
                    reduce_fn=self._reduce, virtual_chunks=v,
                    need_input_grad=False))
            c = jax.jit(jax.grad(loss)).lower(stacked, x).compile()
            return getattr(c.memory_analysis(), "temp_size_in_bytes",
                           None)

        lo, hi = temp_bytes(4), temp_bytes(16)
        if lo is None or hi is None:
            pytest.skip("memory_analysis unavailable on this backend")
        print(f"\nV=2 1F1B compiled temp bytes M=4 -> 16: {lo} -> {hi}")
        assert hi < 1.6 * lo, (lo, hi)


class TestCotangentUniformity:
    """The 1F1B uniform-cotangent assumption is CHECKED (VERDICT r4 weak
    #3): a non-uniform microbatch combiner raises in eager backward
    instead of silently mis-training."""

    def _setup(self, pp_mesh):
        stacked = tuple(jnp.asarray(
            rng.normal(size=(4, *sh)).astype(np.float32) * 0.3)
            for sh in [(16, 32), (32, 16)])
        x = jnp.asarray(rng.normal(size=(4, 3, 16)).astype(np.float32))

        def reduce_fn(y, idx):
            return jnp.sum(y.astype(jnp.float32) ** 2)

        return jax.vjp(
            lambda sp: pipeline_1f1b(_mlp_stage, sp, x, pp_mesh, 4,
                                     reduce_fn=reduce_fn,
                                     need_input_grad=False), stacked)

    def test_nonuniform_combiner_raises(self, pp_mesh):
        _, vjp_fn = self._setup(pp_mesh)
        bad = jnp.asarray([1.0, 2.0, 3.0, 4.0], jnp.float32)
        with pytest.raises(ValueError, match="not uniform"):
            vjp_fn(bad)

    def test_uniform_combiner_clean(self, pp_mesh):
        _, vjp_fn = self._setup(pp_mesh)
        g = vjp_fn(jnp.full((4,), 0.25, jnp.float32))
        for leaf in jax.tree_util.tree_leaves(g):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_nonuniform_under_jit_poisons_nan(self, pp_mesh):
        """Inside jit the check cannot raise; it poisons the grads with
        NaN so FLAGS_check_nan_inf / loss monitoring surfaces it."""
        stacked = tuple(jnp.asarray(
            rng.normal(size=(4, *sh)).astype(np.float32) * 0.3)
            for sh in [(16, 32), (32, 16)])
        x = jnp.asarray(rng.normal(size=(4, 3, 16)).astype(np.float32))

        def reduce_fn(y, idx):
            return jnp.sum(y.astype(jnp.float32) ** 2)

        w = jnp.asarray([1.0, 2.0, 3.0, 4.0], jnp.float32)

        @jax.jit
        def g(sp):
            return jax.grad(lambda sp_: jnp.sum(w * pipeline_1f1b(
                _mlp_stage, sp_, x, pp_mesh, 4, reduce_fn=reduce_fn,
                need_input_grad=False)))(sp)

        leaves = jax.tree_util.tree_leaves(g(stacked))
        assert any(np.isnan(np.asarray(l)).any() for l in leaves)
