"""Multi-model serving plane under chaos (ISSUE 17).

Coverage for the fleet weight store + model-keyed routing + batched
multi-LoRA decode:

* the `ops/lora_epilogue.py` Pallas kernel (interpret mode) held
  against an independent NumPy oracle, plus the two exactness
  arguments the whole plane leans on (row 0 = exact-zero delta,
  rank padding = exact-zero columns);
* mixed-adapter batches bit-identical to serving each adapter alone,
  span-asserted to ride ONE ragged dispatch;
* store install/evict transactionality, byte-budget LRU, pin
  discipline, and cold-install liveness when the budget cannot be
  met;
* cross-model import refusal (`ModelMismatch`, typed + counted);
* per-hosted-model canary goldens: no false quarantine on a healthy
  swapped replica, and a corrupted swapped replica quarantines with
  its streams re-served bit-identically;
* SIGKILL-the-router recovery restoring model assignments with exact
  per-model terminal reconciliation.

conftest enables PDT_TELEMETRY=1 and PDT_CHECK_INVARIANTS=1 for this
file."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.serving import (ContinuousBatchingEngine,
                                       ModelMismatch)
from paddle_tpu.ops.lora_epilogue import lora_epilogue_values
from paddle_tpu.serving import (CanaryConfig, FleetModelStore,
                                ReplicaState, RouterJournal,
                                SentryConfig, ServingRouter, model_id)
from paddle_tpu.utils.faults import FaultInjector

pytestmark = pytest.mark.chaos


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=2, num_key_value_heads=1,
                      max_position_embeddings=64)
    paddle.seed(7)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def v2_values(model):
    """A second FULL checkpoint (different seed, same config) for the
    hot-swap / per-model-canary drills."""
    paddle.seed(11)
    m2 = LlamaForCausalLM(model.config)
    m2.eval()
    return {k: np.asarray(v) for k, v in m2.state_dict().items()}


TARGETS = ("model.layers.0.self_attn.q_proj.weight",
           "model.layers.1.mlp.gate_proj.weight")


def _deltas(model, seed, rank=4, scale=0.5):
    """Rank-`rank` LoRA deltas over TARGETS, big enough to actually
    change greedy streams (the bit-identity drills must compare
    DIFFERENT per-model streams, not six copies of the base's)."""
    sd = model.state_dict()
    rng = np.random.default_rng(seed)
    out = {}
    for nm in TARGETS:
        k, n = np.asarray(sd[nm]).shape
        out[nm] = (rng.normal(size=(k, rank)).astype(np.float32)
                   * scale,
                   rng.normal(size=(rank, n)).astype(np.float32)
                   * scale)
    return out


def _store(model, budget=None, adapters=("a1", "a2")):
    """A fresh fleet store hosting base + rank-4 adapters (padded to
    max_rank 8 by registration). Re-calling builds IDENTICAL
    artifacts — every fleet in a drill hosts the same weights."""
    store = FleetModelStore(base_model="base",
                            byte_budget_per_replica=budget, max_rank=8)
    mids = [store.register_adapter(a, _deltas(model, seed=i + 1))
            for i, a in enumerate(adapters)]
    return store, mids


JOBS = [([5, 4, 3, 2, 6, 7], 10), ([9, 1, 2], 10), ([7, 7, 1, 2], 10),
        ([3, 3, 9], 10)]


def _fleet(model, n=2, clock=None, engine_kw=None, **kw):
    clock = clock if clock is not None else FakeClock()
    ekw = dict(max_batch_size=3, max_seq_len=64, page_size=4)
    ekw.update(engine_kw or {})
    kw.setdefault("policy", "model_affinity")
    kw.setdefault("sleep", clock.advance)
    router = ServingRouter(
        lambda i: ContinuousBatchingEngine(model, clock=clock, **ekw),
        num_replicas=n, clock=clock, **kw)
    return router, clock


def _dedicated_streams(model, jobs_by_model, n=2):
    """Oracle: each model's jobs on its own single-model fleet (fresh
    unbudgeted store, same replica count). The multi-model plane's
    acceptance bar is bit-identity against THESE streams."""
    out = {}
    for mid, jobs in jobs_by_model.items():
        store, _ = _store(model)
        router, _ = _fleet(model, n=n, model_store=store)
        ids = [router.submit(p, m, model=mid) for p, m in jobs]
        res = router.run()
        out[mid] = [res[i] for i in ids]
    return out


# ---------------------------------------------------------------------
class TestLoraEpilogueKernelOracle:
    """ops/lora_epilogue.py parity: the Pallas BGMV kernel (interpret
    mode on CPU) against an independent NumPy oracle, plus the two
    exactness properties the bit-identity argument rests on."""

    def _operands(self, t=16, k=128, n=128, r=8, stacks=4, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.normal(size=(t, k)).astype(np.float32)
        a = rng.normal(size=(stacks, k, r)).astype(np.float32) * 0.2
        b = rng.normal(size=(stacks, r, n)).astype(np.float32) * 0.2
        a[0] = 0.0
        b[0] = 0.0
        scale = np.linspace(0.0, 1.5, stacks).astype(np.float32)
        ids = rng.integers(0, stacks, t).astype(np.int32)
        return x, a, b, scale, ids

    @staticmethod
    def _numpy_oracle(x, a, b, scale, ids):
        out = np.zeros((x.shape[0], b.shape[2]), np.float64)
        for t in range(x.shape[0]):
            i = int(ids[t])
            h = x[t].astype(np.float64) @ a[i].astype(np.float64)
            out[t] = (h @ b[i].astype(np.float64)) * float(scale[i])
        return out

    def test_interpret_kernel_matches_numpy_oracle(self):
        # K/N on the 128-lane grid, rank on the 8-grid: the Pallas
        # path is taken (use_kernel=True -> interpret mode off-TPU)
        x, a, b, scale, ids = self._operands()
        oracle = self._numpy_oracle(x, a, b, scale, ids)
        got = np.asarray(lora_epilogue_values(x, a, b, scale, ids,
                                              use_kernel=True))
        assert got.shape == oracle.shape
        np.testing.assert_allclose(got, oracle, rtol=1e-4, atol=1e-4)
        # and the XLA fallback (the CPU serving path) agrees with the
        # kernel — both reduce in f32
        xla = np.asarray(lora_epilogue_values(x, a, b, scale, ids,
                                              use_kernel=False))
        np.testing.assert_allclose(got, xla, rtol=1e-5, atol=1e-5)

    def test_off_grid_shapes_route_to_xla_and_match_oracle(self):
        # K=32/N=64 (the test model's real shapes) are off the MXU
        # lane grid: use_kernel=True must still be correct (the
        # routing guard falls back rather than miscompiling)
        x, a, b, scale, ids = self._operands(t=9, k=32, n=64, r=8)
        oracle = self._numpy_oracle(x, a, b, scale, ids)
        got = np.asarray(lora_epilogue_values(x, a, b, scale, ids,
                                              use_kernel=True))
        np.testing.assert_allclose(got, oracle, rtol=1e-4, atol=1e-4)

    def test_row_zero_is_exact_zero_delta(self):
        """Base-model tokens ride the mixed dispatch through row 0:
        their delta must be EXACTLY zero (bitwise), not merely small —
        that is the whole mixed==dedicated bit-identity argument."""
        x, a, b, scale, ids = self._operands()
        zeros = np.zeros_like(ids)
        for kernel in (False, True):
            d = np.asarray(lora_epilogue_values(x, a, b, scale, zeros,
                                                use_kernel=kernel))
            assert np.all(d == 0.0)

    def test_rank_padding_columns_are_exact(self):
        """Registration pads rank r -> max_rank with zero columns;
        the padded stack must produce the BIT-IDENTICAL delta, so
        fleets hosting different adapter subsets still agree."""
        x, a, b, scale, ids = self._operands(r=4)
        pad_a = np.concatenate(
            [a, np.zeros(a.shape[:2] + (4,), np.float32)], axis=2)
        pad_b = np.concatenate(
            [b, np.zeros((b.shape[0], 4, b.shape[2]), np.float32)],
            axis=1)
        d0 = np.asarray(lora_epilogue_values(x, a, b, scale, ids,
                                             use_kernel=False))
        d1 = np.asarray(lora_epilogue_values(x, pad_a, pad_b, scale,
                                             ids, use_kernel=False))
        assert np.array_equal(d0, d1)


# ---------------------------------------------------------------------
class TestMixedBatchBitIdentity:
    """The tentpole acceptance bar at the engine seam: one engine
    serving base + two adapters in ONE ragged dispatch produces
    streams bit-identical to three dedicated engines."""

    def _engine(self, model, slots=6):
        return ContinuousBatchingEngine(model, max_batch_size=slots,
                                        max_seq_len=64, page_size=4)

    def test_mixed_batch_bit_identical_single_dispatch(self, model):
        prompts = {"base": [[5, 4, 3, 2], [9, 1, 2]],
                   "a1": [[7, 7, 1, 2], [3, 3, 9]],
                   "a2": [[2, 8, 8], [6, 1, 4, 4]]}
        mixed = self._engine(model)
        mixed.install_adapter("a1", _deltas(model, seed=1))
        mixed.install_adapter("a2", _deltas(model, seed=2))
        telemetry.clear_events()
        rids = {}
        for tag, ps in prompts.items():
            for i, p in enumerate(ps):
                rids[f"{tag}-{i}"] = mixed.add_request(
                    p, 8, request_id=f"{tag}-{i}",
                    adapter=None if tag == "base" else tag)
        out = mixed.run()
        mixed_streams = {key: out[rid] for key, rid in rids.items()}

        # span-asserted single dispatch: at least one decode step's
        # span carries live requests of all THREE models at once
        spans = [e for e in telemetry.events()
                 if e["name"] == "serving.decode_step"]
        assert spans
        tags = [{str(r).split("-")[0] for r in e["attrs"]["rids"]}
                for e in spans]
        assert any(t >= {"base", "a1", "a2"} for t in tags), \
            "no decode step batched all three models together"

        # dedicated engines: base-only (no adapter machinery AT ALL),
        # and one engine per adapter — note a2 sits in stack row 1
        # there vs row 2 in the mixed engine
        for tag in prompts:
            eng = self._engine(model)
            if tag != "base":
                eng.install_adapter(tag, _deltas(
                    model, seed=1 if tag == "a1" else 2))
            ids = [eng.add_request(p, 8, request_id=f"{tag}-{i}",
                                   adapter=None if tag == "base"
                                   else tag)
                   for i, p in enumerate(prompts[tag])]
            res = eng.run()
            for i, rid in enumerate(ids):
                assert res[rid] == mixed_streams[f"{tag}-{i}"], \
                    f"{tag}-{i} diverged between mixed and dedicated"

        # and the adapters genuinely steer the stream (the identity
        # above must compare three DIFFERENT streams, not one)
        assert mixed_streams["a1-0"] != mixed_streams["base-0"] \
            or mixed_streams["a1-1"] != mixed_streams["base-1"]


# ---------------------------------------------------------------------
class TestStoreInstallEvict:
    """FleetModelStore transactionality: failed installs leave no
    residue, the LRU honors pins, and refused evictions never strand
    in-flight work."""

    def _engine(self, model):
        return ContinuousBatchingEngine(model, max_batch_size=3,
                                        max_seq_len=64, page_size=4)

    def test_failed_install_leaves_no_residue(self, model):
        """An adapter whose deltas target an unknown parameter passes
        registration (the store cannot see the model) but the ENGINE
        install raises — ensure() must propagate with the store's
        accounting untouched, and the replica must keep serving."""
        bad = FleetModelStore(base_model="base", max_rank=8)
        mid_bad = bad.register_adapter(
            "bad", {"nope.weight": (np.zeros((8, 4), np.float32),
                                    np.zeros((4, 8), np.float32))})
        eng = self._engine(model)
        with pytest.raises(ValueError, match="unknown parameter"):
            bad.ensure(0, eng, mid_bad)
        assert not bad.is_resident(0, mid_bad)
        assert bad.installs == 0
        assert bad.resident(0) == ("base",)
        # a good store still installs onto the SAME engine afterwards
        good, (m1, _) = _store(model)
        assert good.ensure(0, eng, m1) is True
        rid = eng.add_request([5, 4, 3], 4, adapter="a1")
        assert len(eng.run()[rid]) == 4

    def test_byte_budget_lru_evicts_cold_adapter(self, model):
        store, (m1, m2) = _store(model, budget=6_000)
        eng = self._engine(model)
        assert store.ensure("r0", eng, m1) is True
        assert store.ensure("r0", eng, m1) is False     # warm hit
        assert store.ensure("r0", eng, m2) is True      # evicts a1
        assert store.is_resident("r0", m2)
        assert not store.is_resident("r0", m1)
        assert store.evictions == 1
        assert store.resident_bytes("r0") \
            <= store.byte_budget_per_replica
        # the engine agrees: a1's row is gone
        with pytest.raises(ModelMismatch):
            eng.add_request([5, 4], 4, adapter="a1")

    def test_pinned_adapter_survives_make_room(self, model):
        """Pins outrank the budget: with a1 pinned, installing a2
        refuses the eviction and legally runs over budget."""
        store, (m1, m2) = _store(model, budget=6_000)
        eng = self._engine(model)
        store.ensure("r0", eng, m1)
        store.pin("r0", m1)
        store.ensure("r0", eng, m2)
        assert store.is_resident("r0", m1)      # pinned: not evicted
        assert store.is_resident("r0", m2)
        assert store.evict_refusals >= 1
        assert store.resident_bytes("r0") \
            > store.byte_budget_per_replica     # over budget is legal
        store.unpin("r0", m1)

    def test_engine_refuses_evicting_inflight_adapter(self, model):
        """The engine's own backstop under the store's refusal path:
        evict_adapter refuses while a request decodes under it."""
        eng = self._engine(model)
        eng.install_adapter("a1", _deltas(model, seed=1))
        rid = eng.add_request([5, 4, 3], 6, adapter="a1")
        with pytest.raises(ValueError, match="in flight|in-flight"):
            eng.evict_adapter("a1")
        assert len(eng.run()[rid]) == 6
        eng.evict_adapter("a1")                 # drained: now fine

    def test_budget_below_one_adapter_still_installs(self, model):
        """Cold-install liveness under pressure: a budget smaller
        than a single adapter has nothing evictable — the install
        must proceed (advisory budget), not deadlock."""
        store, (m1, _) = _store(model, budget=1_000)
        eng = self._engine(model)
        assert store.ensure("r0", eng, m1) is True
        assert store.is_resident("r0", m1)
        rid = eng.add_request([5, 4, 3], 4, adapter="a1")
        assert len(eng.run()[rid]) == 4

    def test_full_checkpoint_swap_drops_adapters(self, model,
                                                 v2_values):
        store, (m1, _) = _store(model)
        mid_v2 = store.register_model("v2", v2_values)
        eng = self._engine(model)
        store.ensure("r0", eng, m1)
        store.ensure("r0", eng, mid_v2)
        assert store.replica_base("r0") == "v2"
        assert not store.is_resident("r0", m1)  # died with its base
        assert eng.model_tag == "v2"
        with pytest.raises(ModelMismatch):
            eng.add_request([5, 4], 4, adapter="a1")


# ---------------------------------------------------------------------
class TestRouterMultiModel:
    """Model-keyed routing: typed refusals, cold-install accounting,
    eviction churn under a tight budget, and bit-identity of every
    model's streams against dedicated single-model fleets."""

    def test_unknown_model_refused_typed(self, model):
        store, _ = _store(model)
        router, _ = _fleet(model, model_store=store)
        with pytest.raises(ValueError, match="base\\+nope"):
            router.submit([5, 4, 3], 4, model="base+nope")
        assert not router.requests      # refused before any state

    def test_submit_model_needs_a_store(self, model):
        router, _ = _fleet(model, policy="round_robin")
        with pytest.raises(ValueError, match="model_store"):
            router.submit([5, 4, 3], 4, model="base+a1")

    def test_mixed_fleet_bit_identical_to_dedicated(self, model):
        jobs_by_model = {"base": JOBS[:2], "base+a1": JOBS[2:],
                         "base+a2": JOBS[:2]}
        want = _dedicated_streams(model, jobs_by_model)
        store, _ = _store(model)
        router, _ = _fleet(model, model_store=store)
        ids = {mid: [router.submit(p, m, model=mid) for p, m in jobs]
               for mid, jobs in jobs_by_model.items()}
        out = router.run()
        for mid, rids in ids.items():
            assert [out[r] for r in rids] == want[mid], \
                f"{mid} streams diverged from its dedicated fleet"
        # accounting: every submit and terminal is model-keyed
        info = router.fleet_info()
        for mid, jobs in jobs_by_model.items():
            assert info["models"][mid]["submitted"] == len(jobs)
            assert sum(info["models"][mid]["terminal"].values()) \
                == len(jobs)
            assert info["models"][mid]["pending"] == 0
        assert sum(router.num_cold_installs_by_model.values()) >= 2
        assert telemetry.value("pdt_router_model_cold_installs_total",
                               model="base+a1") >= 1
        spans = [e for e in telemetry.events()
                 if e["name"] == "router.model_install"]
        assert spans and all("model" in e["attrs"] for e in spans)

    def test_budget_churn_evicts_and_stays_bit_identical(self, model):
        """Serial single-adapter phases under a one-adapter budget:
        each phase must evict the previous adapter, reinstall, and
        still reproduce the dedicated fleet's streams exactly."""
        phases = [("base+a1", JOBS[:2]), ("base+a2", JOBS[2:]),
                  ("base+a1", JOBS[2:])]
        want = _dedicated_streams(
            model, {"base+a1": JOBS[:2] + JOBS[2:],
                    "base+a2": JOBS[2:]}, n=1)
        store, _ = _store(model, budget=6_000)
        router, _ = _fleet(model, n=1, model_store=store)
        got = {"base+a1": [], "base+a2": []}
        for mid, jobs in phases:
            rids = [router.submit(p, m, model=mid) for p, m in jobs]
            out = router.run()
            got[mid] += [out[r] for r in rids]
        assert got == want
        assert store.evictions >= 2             # a1 out, then a2 out
        assert router.num_cold_installs_by_model["base+a1"] == 2
        assert telemetry.value("pdt_model_store_evictions_total",
                               kind="adapter") >= 2


# ---------------------------------------------------------------------
class TestModelKeyedMigration:
    """Scale-down evacuation on a multi-model fleet: the survivor
    must cold-install the victim's model BEFORE the pages move (a
    cross-model import is a typed refusal), and the migrated streams
    stay bit-identical to dedicated single-model fleets."""

    def test_shrink_migrates_adapter_requests_bit_identical(
            self, model):
        jobs_by_model = {"base+a1": JOBS[:2], "base+a2": JOBS[2:]}
        want = _dedicated_streams(model, jobs_by_model)
        store, (m1, m2) = _store(model)
        router, _ = _fleet(model, model_store=store,
                           engine_kw=dict(max_batch_size=4))
        ids = {mid: [router.submit(p, m, model=mid) for p, m in jobs]
               for mid, jobs in jobs_by_model.items()}
        for _ in range(3):
            router.step()   # prefilled + decoding: pages are warm
        victim_models = {router.requests[r].model
                         for rids in ids.values() for r in rids
                         if router.requests[r].replica == 1}
        assert victim_models, "affinity left replica 1 empty"
        router.resize(num_replicas=1, reason="evacuation drill")
        # the warm hand-off happened, and the survivor cold-installed
        # the victim's model first (import_pages would have refused)
        assert router.num_migrations >= 1
        for mid in victim_models:
            assert store.is_resident(0, mid)
        out = router.run()
        for mid, rids in ids.items():
            assert [out[r] for r in rids] == want[mid], \
                f"{mid} streams diverged through the shrink"


# ---------------------------------------------------------------------
class TestCrossModelImport:
    """Migration payloads carry the hosted model's identity: KV pages
    produced under one checkpoint must refuse to land under another
    (silent cross-model KV corruption is the failure mode)."""

    def _engine(self, model):
        return ContinuousBatchingEngine(model, max_batch_size=3,
                                        max_seq_len=64, page_size=4)

    def test_import_pages_refuses_cross_model(self, model, v2_values):
        src = self._engine(model)
        src.install_weights(v2_values, tag="v2")
        rid = src.add_request([5, 4, 3, 2], 8)
        src.step()                      # running: pages resident
        payload = src.export_pages(rid)
        dst = self._engine(model)       # hosts the build-time base
        before = telemetry.value("pdt_model_mismatch_total",
                                 kind="import")
        with pytest.raises(ModelMismatch, match="v2"):
            dst.import_pages(payload)
        assert telemetry.value("pdt_model_mismatch_total",
                               kind="import") == before + 1
        # the source is untouched (export is read-only): it finishes
        assert len(src.run()[rid]) == 8

    def test_nonresident_adapter_refused_before_enqueue(self, model):
        eng = self._engine(model)
        before = telemetry.value("pdt_model_mismatch_total",
                                 kind="adapter")
        with pytest.raises(ModelMismatch, match="ghost"):
            eng.add_request([5, 4], 4, adapter="ghost")
        assert telemetry.value("pdt_model_mismatch_total",
                               kind="adapter") == before + 1


# ---------------------------------------------------------------------
class TestPerModelCanary:
    """Canary probes on multi-model fleets grade each replica against
    the golden of the checkpoint it HOSTS — one shared golden would
    false-quarantine every healthy swapped replica."""

    def _mm_sentried(self, model, v2_values, n=2):
        store, _ = _store(model)
        mid_v2 = store.register_model("v2", v2_values)
        router, clock = _fleet(
            model, n=n, model_store=store,
            sentry=SentryConfig(scan_every=2),
            canary=CanaryConfig(interval=5.0, max_new_tokens=6),
            restart_backoff_base=3.0, restart_backoff_max=3.0)
        return router, clock, store, mid_v2

    def test_swapped_replica_canary_passes_on_its_own_golden(
            self, model, v2_values):
        """The false-quarantine regression: a healthy replica hosting
        the v2 checkpoint runs its canary and must PASS — graded
        against v2's golden stream, not base's."""
        router, clock, store, mid_v2 = self._mm_sentried(
            model, v2_values)
        ids = [router.submit(p, m, model=mid) for (p, m), mid
               in zip(JOBS, ["base", mid_v2, "base", mid_v2])]
        clock.advance(6.0)              # canary schedule due
        router.run()
        for _ in range(60):             # let in-flight canaries land
            if all(h.canary is None and h.canary_runs >= 1
                   for h in router.replicas):
                break
            router.step()
        bases = {store.replica_base(h.index) for h in router.replicas}
        assert "v2" in bases            # a replica really swapped
        assert router.num_quarantines == 0
        assert all(h.state == ReplicaState.HEALTHY
                   for h in router.replicas)
        # per-model goldens: lazily computed for v2, distinct streams
        assert set(router._canary_goldens) >= {"base", "v2"}
        assert router._canary_goldens["base"] \
            != router._canary_goldens["v2"]
        assert telemetry.value("pdt_sentry_canary_runs_total",
                               result="pass") >= 2

    def test_corrupt_swapped_replica_quarantines_and_reserves(
            self, model, v2_values):
        """A persistently NaN-poisoned v2 replica must quarantine —
        graded against v2's golden — and its streams re-serve
        bit-identically on the surviving replica (which cold-installs
        v2 to take the work)."""
        jobs = JOBS
        # the uncorrupted oracle: same fleet shape, same submits
        oracle_rt, _, _, mid_v2 = self._mm_sentried(model, v2_values)
        oids = [oracle_rt.submit(p, m, model=mid_v2) for p, m in jobs]
        oout = oracle_rt.run()
        want = [oout[i] for i in oids]

        router, clock, store, mid_v2 = self._mm_sentried(
            model, v2_values)
        ids = [router.submit(p, m, model=mid_v2) for p, m in jobs]
        vidx = None
        for _ in range(40):             # find the swapped replica
            router.step()
            hosts = [h.index for h in router.replicas
                     if store.replica_base(h.index) == "v2"]
            if hosts:
                vidx = hosts[0]
                break
        assert vidx is not None, "v2 never installed"
        with FaultInjector(seed=0) as fi:
            fi.arm_corrupt("serving.logits", mode="nan", always=True,
                           tag=str(vidx))
            quarantined = False
            for _ in range(120):
                router.step()
                if router.replicas[vidx].state \
                        == ReplicaState.QUARANTINED:
                    quarantined = True
                    break
            assert quarantined, "corrupt v2 replica never quarantined"
            clock.advance(4.0)
            out = router.run()
        assert [out[i] for i in ids] == want
        assert router.num_quarantines >= 1
        assert "v2" in router._canary_goldens
        ev = [e for e in telemetry.events()
              if e["name"] == "replica.quarantine"]
        assert ev and ev[0]["attrs"]["replica"] == vidx


# ---------------------------------------------------------------------
class TestJournalRecoveryModelAssignments:
    """SIGKILL the router mid-decode on a multi-model fleet: recovery
    must restore every request's MODEL assignment from the journal
    (re-dispatch under the wrong weights would be silent corruption),
    finish bit-identically, and reconcile per-model terminals."""

    # staggered budgets: finished-and-live requests must coexist at
    # the kill point
    N_TOKS = [4, 10, 8, 14]

    def _submits(self, router, mids):
        return [router.submit(p, n, model=mid)
                for (p, _), n, mid in zip(JOBS, self.N_TOKS, mids)]

    def test_sigkill_recovery_restores_models_bit_identical(
            self, model, tmp_path):
        mids = ["base", "base+a1", "base+a2", "base+a1"]
        # the uninterrupted oracle
        store0, _ = _store(model)
        oracle_rt, _ = _fleet(model, model_store=store0)
        oids = self._submits(oracle_rt, mids)
        oout = oracle_rt.run()
        want = [oout[i] for i in oids]

        clock = FakeClock()
        store1, _ = _store(model)
        jr = RouterJournal(os.path.join(str(tmp_path), "wal"),
                           fsync="off", clock=clock)
        router, _ = _fleet(model, clock=clock, model_store=store1,
                           journal=jr)
        ids = self._submits(router, mids)
        finished = []
        while not finished:
            finished += [r.request_id for r in router.step()]
        assert any(not router.requests[i].done for i in ids)
        del router                      # SIGKILL-shaped: only the
        #                                 journal directory survives
        jr2 = RouterJournal(os.path.join(str(tmp_path), "wal"),
                            fsync="off", clock=clock)
        store2, _ = _store(model)       # artifacts re-registered at
        #                                 boot; residency died with
        #                                 the old process's engines
        recovered = ServingRouter.recover(
            jr2,
            lambda i: ContinuousBatchingEngine(
                model, clock=clock, max_batch_size=3, max_seq_len=64,
                page_size=4),
            num_replicas=2, clock=clock, sleep=clock.advance,
            policy="model_affinity", model_store=store2)
        # the journal restored every request's model assignment
        for rid, mid in zip(ids, mids):
            assert recovered.requests[rid].model == mid
        out = recovered.run()
        assert [out[i] for i in ids] == want
        # exact per-model terminal reconciliation across BOTH
        # incarnations (deduped restores count too)
        for mid in set(mids):
            n = sum(1 for m in mids if m == mid)
            row = recovered.num_terminal_by_model[mid]
            assert row.get("finished", 0) == n, (mid, row)
        info = recovered.fleet_info()
        for mid in set(mids):
            n = sum(1 for m in mids if m == mid)
            assert sum(info["models"][mid]["terminal"].values()) == n
