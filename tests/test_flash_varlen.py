"""Varlen/packed flash attention vs NumPy oracle over random packings.
≙ SURVEY.md §2.1 FlashAttention row (varlen variants); VERDICT r2 item 5."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.flash_varlen import (flash_attention_varlen,
                                         flash_attention_varlen_values,
                                         segments_from_cu_seqlens)


def _random_packing(rng, b, s, max_segs=4):
    """Random segment ids per batch row: contiguous runs, tail padding."""
    seg = np.full((b, s), -1, np.int32)
    for i in range(b):
        n = rng.integers(1, max_segs + 1)
        cuts = np.sort(rng.choice(np.arange(1, s), n - 1, replace=False)) \
            if n > 1 else np.array([], np.int64)
        bounds = np.concatenate([[0], cuts, [rng.integers(s // 2, s + 1)]])
        bounds = np.sort(bounds)
        for j in range(len(bounds) - 1):
            seg[i, bounds[j]:bounds[j + 1]] = j
    return seg


def _oracle(q, k, v, seg_q, seg_k, causal):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    g = h // hk
    if g > 1:
        k = np.repeat(k, g, axis=2)
        v = np.repeat(v, g, axis=2)
    out = np.zeros_like(q, dtype=np.float32)
    for bi in range(b):
        for hi in range(h):
            logits = (q[bi, :, hi].astype(np.float32)
                      @ k[bi, :, hi].astype(np.float32).T) / np.sqrt(d)
            mask = (seg_q[bi][:, None] == seg_k[bi][None, :]) & \
                (seg_q[bi][:, None] >= 0)
            if causal:
                pos_q = np.arange(sq)[:, None] + (sk - sq)
                mask &= pos_q >= np.arange(sk)[None, :]
            logits = np.where(mask, logits, -1e30)
            valid = mask.any(-1)
            e = np.exp(logits - logits.max(-1, keepdims=True))
            p = e / np.maximum(e.sum(-1, keepdims=True), 1e-30)
            p = np.where(valid[:, None], p, 0.0)
            out[bi, :, hi] = p @ v[bi, :, hi].astype(np.float32)
    return out


class TestVarlenParity:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_packings(self, causal, seed):
        rng = np.random.default_rng(seed)
        b, s, h, hk, d = 2, 256, 4, 2, 32
        q = rng.standard_normal((b, s, h, d)).astype(np.float32)
        k = rng.standard_normal((b, s, hk, d)).astype(np.float32)
        v = rng.standard_normal((b, s, hk, d)).astype(np.float32)
        seg = _random_packing(rng, b, s)
        out = flash_attention_varlen_values(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(seg), jnp.asarray(seg), causal=causal)
        ref = _oracle(q, k, v, seg, seg, causal)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4)

    def test_unaligned_falls_back(self):
        rng = np.random.default_rng(3)
        b, s, h, d = 1, 100, 2, 16   # s not a block multiple
        q = rng.standard_normal((b, s, h, d)).astype(np.float32)
        seg = _random_packing(rng, b, s)
        out = flash_attention_varlen_values(
            jnp.asarray(q), jnp.asarray(q), jnp.asarray(q),
            jnp.asarray(seg), jnp.asarray(seg), causal=True)
        ref = _oracle(q, q, q, seg, seg, True)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4)

    def test_grads_confined_to_segments(self):
        """dk for keys in segment A must be unaffected by queries in
        segment B: cross-segment leakage would show up here."""
        rng = np.random.default_rng(4)
        b, s, h, d = 1, 256, 2, 32
        seg = np.zeros((b, s), np.int32)
        seg[:, 128:] = 1
        q = rng.standard_normal((b, s, h, d)).astype(np.float32)
        k = rng.standard_normal((b, s, h, d)).astype(np.float32)
        v = rng.standard_normal((b, s, h, d)).astype(np.float32)

        def loss_fn(qq, kk, vv, w):
            # weight only segment-0 outputs
            out = flash_attention_varlen_values(
                qq, kk, vv, jnp.asarray(seg), jnp.asarray(seg),
                causal=True)
            return jnp.sum(out[:, :128] * w)

        w = rng.standard_normal((b, 128, h, d)).astype(np.float32)
        dq, dk, dv = jax.grad(loss_fn, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(w))
        # segment-1 keys/values got zero gradient
        np.testing.assert_allclose(np.asarray(dk[:, 128:]), 0.0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(dv[:, 128:]), 0.0, atol=1e-6)
        assert float(jnp.abs(dk[:, :128]).max()) > 0

    def test_grad_matches_xla_reference(self):
        rng = np.random.default_rng(5)
        b, s, h, d = 1, 256, 2, 32
        seg = _random_packing(rng, b, s)
        q = rng.standard_normal((b, s, h, d)).astype(np.float32)
        k = rng.standard_normal((b, s, h, d)).astype(np.float32)
        v = rng.standard_normal((b, s, h, d)).astype(np.float32)
        from paddle_tpu.ops.flash_varlen import _varlen_xla

        def f_kernel(qq, kk, vv):
            return flash_attention_varlen_values(
                qq, kk, vv, jnp.asarray(seg), jnp.asarray(seg),
                causal=True).astype(jnp.float32).sum()

        def f_ref(qq, kk, vv):
            return _varlen_xla(qq, kk, vv, jnp.asarray(seg),
                               jnp.asarray(seg), 1.0 / np.sqrt(d),
                               True).astype(jnp.float32).sum()

        g1 = jax.grad(f_kernel, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        for a, bb in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                       rtol=2e-4, atol=2e-4)


class TestCuSeqlens:
    def test_segments_from_cu_seqlens(self):
        seg = segments_from_cu_seqlens(jnp.asarray([0, 3, 5, 8]), 10)
        np.testing.assert_array_equal(
            np.asarray(seg), [0, 0, 0, 1, 1, 2, 2, 2, -1, -1])

    def test_flash_attn_unpadded_routes_kernel(self):
        from paddle_tpu.nn import functional as F
        rng = np.random.default_rng(6)
        total, h, d = 256, 2, 32
        cu = np.array([0, 100, 256], np.int32)
        q = rng.standard_normal((total, h, d)).astype(np.float32)
        out, _ = F.flash_attn_unpadded(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            paddle.to_tensor(cu), paddle.to_tensor(cu), 156, 156,
            causal=True)
        seg = np.asarray(segments_from_cu_seqlens(jnp.asarray(cu), total))
        ref = _oracle(q[None], q[None], q[None], seg[None], seg[None],
                      True)[0]
        np.testing.assert_allclose(np.asarray(out._value), ref, rtol=2e-4,
                                   atol=2e-4)


@pytest.mark.slow
class TestPackedTraining:
    def test_packed_batch_train_step(self):
        """Packed two-documents-per-row batch trains through the varlen
        kernel: loss decreases and grads flow."""
        from paddle_tpu import nn

        class PackedAttn(nn.Layer):
            def __init__(self, h=32, heads=2):
                super().__init__()
                self.qkv = nn.Linear(h, 3 * h)
                self.out = nn.Linear(h, h)
                self.heads = heads

            def forward(self, x, seg):
                b, s, hdim = x.shape
                qkv = self.qkv(x).reshape([b, s, 3, self.heads,
                                           hdim // self.heads])
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
                o = flash_attention_varlen(q, k, v, seg, seg, causal=True)
                return self.out(o.reshape([b, s, hdim]))

        paddle.seed(0)
        rng = np.random.default_rng(7)
        model = PackedAttn()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        x = paddle.to_tensor(
            rng.standard_normal((2, 128, 32)).astype(np.float32))
        y = paddle.to_tensor(
            rng.standard_normal((2, 128, 32)).astype(np.float32))
        seg = np.zeros((2, 128), np.int32)
        seg[:, 64:] = 1
        seg_t = paddle.to_tensor(seg)
        losses = []
        for _ in range(5):
            loss = ((model(x, seg_t) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]


rng = np.random.default_rng(21)


class TestQKVPacked:
    def test_qkvpacked_matches_split(self):
        import paddle_tpu.nn.functional as F
        qkv = paddle.to_tensor(rng.normal(size=(2, 32, 3, 2, 16))
                               .astype(np.float32))
        out, _ = F.flash_attn_qkvpacked(qkv, causal=True)
        ref, _ = F.flash_attention(qkv[:, :, 0], qkv[:, :, 1],
                                   qkv[:, :, 2], causal=True)
        np.testing.assert_allclose(np.asarray(out._value),
                                   np.asarray(ref._value), rtol=1e-6)

    def test_varlen_qkvpacked_matches_unpadded(self):
        import paddle_tpu.nn.functional as F
        total = 48
        cu = np.array([0, 16, 48], np.int32)
        qkv = paddle.to_tensor(rng.normal(size=(total, 3, 2, 16))
                               .astype(np.float32))
        out = F.flash_attn_varlen_qkvpacked(qkv, paddle.to_tensor(cu),
                                            paddle.to_tensor(cu), 32, 32,
                                            causal=True)
        ref = F.flash_attn_unpadded(qkv[:, 0], qkv[:, 1], qkv[:, 2],
                                    paddle.to_tensor(cu),
                                    paddle.to_tensor(cu), 32, 32,
                                    causal=True)
        o = out[0] if isinstance(out, tuple) else out
        r = ref[0] if isinstance(ref, tuple) else ref
        np.testing.assert_allclose(np.asarray(o._value),
                                   np.asarray(r._value), rtol=1e-6)
