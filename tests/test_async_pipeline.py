"""Pipelined decode with a bounded-staleness durability window
(ISSUE 18): `ContinuousBatchingEngine(harvest_every=k)` keeps the
greedy-sampled token vector ON DEVICE between dispatches and batches
the D2H harvest every k steps.

The acceptance property threaded through this file: greedy streams are
BIT-IDENTICAL to the k=1 (synchronous) oracle through every drill —
plain runs, EOS overshoot, deadline expiry mid-window, quiesce seams,
replica SIGKILL at every intra-window offset, router SIGKILL at every
intra-window offset followed by `recover()`, and sentry quarantine —
while the staleness contract `durable_len <= len(tokens) <=
device_len` holds at every observable instant and the sentry's
detection latency stays bounded at k steps. conftest runs this file
with PDT_TELEMETRY=1 and PDT_CHECK_INVARIANTS=1."""
import json
import os
import shutil

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.serving import (ContinuousBatchingEngine,
                                       RequestStatus, SpecConfig)
from paddle_tpu.serving import (CanaryConfig, ReplicaState,
                                RouterJournal, SentryConfig,
                                ServingRouter)
from paddle_tpu.serving.journal import _HEADER
from paddle_tpu.serving.sentry import NumericSentry
from paddle_tpu.utils.faults import FaultInjector

pytestmark = pytest.mark.chaos


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=1, max_position_embeddings=64)
    paddle.seed(7)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _engine(model, k=1, **kw):
    kw.setdefault("max_batch_size", 3)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", 4)
    return ContinuousBatchingEngine(model, harvest_every=k, **kw)


# more jobs than slots, staggered budgets: queue pressure forces
# early harvests (admission trigger) AND full windows coexist
JOBS = [([1, 2, 3], 9), ([4, 5], 7), ([6, 7, 8, 9], 5),
        ([2, 2], 12), ([9, 1], 3)]


def _run_engine(model, k, jobs=JOBS, **kw):
    eng = _engine(model, k, **kw)
    rids = [eng.add_request(p, n) for p, n in jobs]
    res = eng.run()
    return [res[r] for r in rids]


def _segment_files(path):
    return sorted(fn for fn in os.listdir(path)
                  if fn.startswith("seg-") and fn.endswith(".wal"))


def _record_spans(blob):
    spans, off = [], 0
    while off < len(blob):
        length, _ = _HEADER.unpack_from(blob, off)
        end = off + _HEADER.size + length
        spans.append((off, end))
        off = end
    return spans


def _journal_records(path):
    out = []
    for seg in _segment_files(path):
        blob = open(os.path.join(path, seg), "rb").read()
        for start, end in _record_spans(blob):
            out.append(json.loads(
                blob[start + _HEADER.size:end].decode()))
    return out


# ---------------------------------------------------------------------
class TestEnginePipeline:
    """Engine-level k-identity + the staleness contract's seams."""

    @pytest.mark.parametrize("k", [2, 4, 8])
    def test_bit_identical_streams(self, model, k):
        ref = _run_engine(model, 1)
        assert _run_engine(model, k) == ref

    def test_eos_overshoot_discarded(self, model):
        """The device can't see EOS mid-window, so a pipelined engine
        dispatches up to k-1 steps past it — the harvest must discard
        the overshoot, leaving the stream identical to the
        synchronous engine's EOS cut."""
        plain = _run_engine(model, 1)
        # an eos that fires mid-stream for at least one request
        eos = plain[3][4]
        ref = _run_engine(model, 1, eos_token_id=eos)
        assert any(len(r) < n for r, (_, n) in zip(ref, JOBS))
        for k in (4, 8):
            assert _run_engine(model, k, eos_token_id=eos) == ref

    def test_k1_keeps_the_synchronous_loop(self, model):
        eng = _engine(model, 1)
        rids = [eng.add_request(p, n) for p, n in JOBS[:2]]
        eng.step()
        eng.step()
        assert eng._pending == [] and eng._tok_dev is None
        assert eng.quiesce() == 0          # no-op on the sync loop
        out = eng.run()
        assert [out[r] for r in rids] == _run_engine(model, 1, JOBS[:2])

    def test_constructor_validation(self, model):
        with pytest.raises(ValueError, match="harvest_every"):
            _engine(model, 0)
        with pytest.raises(ValueError, match="greedy-only"):
            _engine(model, 4, do_sample=True)
        with pytest.raises(ValueError, match="spec_decode"):
            _engine(model, 4,
                    spec_decode=SpecConfig(draft_model=model, k=2))
        with pytest.raises(ValueError, match="ragged"):
            _engine(model, 4, kv_layout="dense",
                    attention_impl="legacy")

    def test_quiesce_drains_the_window(self, model):
        eng = _engine(model, 4)
        rids = [eng.add_request(p, n) for p, n in JOBS[:2]]
        eng.step()                          # prefill + first dispatch
        eng.step()                          # deferred dispatch
        assert len(eng._pending) >= 1
        drained = eng.quiesce()
        assert drained >= 1
        assert eng._pending == [] and eng._tok_dev is None
        out = eng.run()
        assert [out[r] for r in rids] == _run_engine(model, 1, JOBS[:2])

    def test_device_len_runs_ahead_then_resyncs(self, model):
        eng = _engine(model, 4)
        rid = eng.add_request([1, 2, 3], 9)
        eng.step()                          # prefill (+1 output token)
        eng.step()                          # deferred dispatch
        eng.step()                          # deferred dispatch
        req = eng.get_request(rid)
        depth = len(eng._pending)
        assert depth >= 1
        assert req.device_len == len(req.output) + depth
        eng.quiesce()
        assert req.device_len == len(req.output)

    def test_export_pages_quiesces_first(self, model):
        """Migration's export must hand off COMMITTED state only: a
        mid-window export sees every deferred token harvested."""
        eng = _engine(model, 4)
        rid = eng.add_request([1, 2, 3], 9)
        eng.step()
        eng.step()
        assert len(eng._pending) >= 1
        payload = eng.export_pages(rid)
        assert eng._pending == []
        req = eng.get_request(rid)
        assert len(payload["output"]) == len(req.output)

    def test_deadline_expiry_mid_window(self, model):
        """A deadline elapsing inside the deferred window finalizes at
        the same token count as the synchronous engine: the running-
        deadline harvest trigger closes the window before expiry
        acts."""
        def script(k):
            clock = FakeClock()
            eng = _engine(model, k, clock=clock)
            doomed = eng.add_request([1, 2, 3], 30, deadline=4.0)
            safe = eng.add_request([4, 5], 6)
            outs = {}
            for i in range(40):
                for r in eng.step():
                    outs[r.rid] = (r.status, list(r.output))
                clock.advance(1.0)
                if doomed in outs and safe in outs:
                    break
            return outs[doomed], outs[safe]

        ref = script(1)
        assert ref[0][0] == RequestStatus.TIMEOUT
        for k in (4, 8):
            assert script(k) == ref

    def test_sentry_stream_identical_and_lag_bounded(self, model):
        """The sentry on the pipelined loop: checks defer to harvest
        (lag metered, bounded at k-1) but the stream never moves."""
        ref = _run_engine(model, 1)
        k = 4
        eng = _engine(model, k)
        s = NumericSentry(SentryConfig(scan_every=1), vocab_size=64)
        eng.attach_sentry(s)
        rids = [eng.add_request(p, n) for p, n in JOBS]
        out = eng.run()
        assert [out[r] for r in rids] == ref
        assert s.scans >= 2 and s.trips == 0
        from paddle_tpu.serving.sentry import _M_DETECTION_LAG
        lag = _M_DETECTION_LAG.get()
        assert lag["count"] > 0
        assert lag["sum"] <= (k - 1) * lag["count"]

    def test_nan_poison_detected_within_k_steps(self, model):
        """Detection latency bound: with the scan every step, a NaN
        poisoning armed before the run trips at the FIRST harvest —
        within k dispatches of the poisoned one."""
        k = 4
        eng = _engine(model, k)
        s = NumericSentry(SentryConfig(scan_every=1), vocab_size=64)
        eng.attach_sentry(s)
        eng.add_request([1, 2, 3], 9)
        with FaultInjector(seed=0) as fi:
            fi.arm_corrupt("serving.logits", mode="nan", always=True)
            for _ in range(k + 1):          # prefill + one full window
                eng.step()
            assert s.trips >= 1
        assert s.last_trip["kind"] == "logit_nonfinite"


# ---------------------------------------------------------------------
class TestRouterPipelineChaos:
    """Fleet drills with pipelined engines: the kill offset sweeps
    EVERY position inside a k=4 window, so a dropped in-flight window
    of every depth 0..k-1 is re-generated bit-identically."""

    def _fleet(self, model, k, n=2, clock=None, **kw):
        clock = clock if clock is not None else FakeClock()
        kw.setdefault("page_size", 4)
        kw.setdefault("sleep", clock.advance)
        router = ServingRouter(
            lambda i: ContinuousBatchingEngine(
                model, clock=clock, max_batch_size=3, max_seq_len=64,
                page_size=4, harvest_every=k),
            num_replicas=n, policy="round_robin", clock=clock, **kw)
        return router, clock

    def _ref(self, model, jobs):
        eng = _engine(model, 1)
        rids = [eng.add_request(p, m) for p, m in jobs]
        res = eng.run()
        return [res[r] for r in rids]

    @pytest.mark.parametrize("offset", [0, 1, 2, 3])
    def test_replica_kill_every_window_offset(self, model, offset):
        """SIGKILL replica 0 at every intra-window offset: the unseen
        window dies with the engine, the mirrored prefix folds into a
        survivor's re-prefill, and the stream re-generates
        bit-identically (zero loss, up to k-1 tokens re-decoded)."""
        ref = self._ref(model, JOBS)
        router, clock = self._fleet(model, k=4, n=2,
                                    restart_backoff_base=3.0,
                                    restart_backoff_max=3.0)
        ids = [router.submit(p, m) for p, m in JOBS]
        for _ in range(2 + offset):
            router.step()
        assert any(not router.requests[i].done for i in ids)
        router.kill_replica(0)
        clock.advance(4.0)
        out = router.run()
        assert [out[i] for i in ids] == ref

    def test_quarantine_reserve_with_pipelined_engines(self, model):
        """Gray-failure response at k=4: persistent NaN poisoning of
        one replica's logit harvest quarantines it via dirty canaries
        and every stream re-serves bit-identically — canary verdicts
        quantize to harvest boundaries without weakening the drill."""
        ref = self._ref(model, JOBS)
        router, clock = self._fleet(
            model, k=4, n=2, restart_backoff_base=3.0,
            restart_backoff_max=3.0,
            sentry=SentryConfig(scan_every=1),
            canary=CanaryConfig(interval=1000.0, max_new_tokens=6))
        ids = [router.submit(p, m) for p, m in JOBS]
        with FaultInjector(seed=0) as fi:
            fi.arm_corrupt("serving.logits", mode="nan", always=True,
                           tag="1")
            for _ in range(120):
                router.step()
                if router.replicas[1].state \
                        == ReplicaState.QUARANTINED:
                    break
            assert router.replicas[1].state \
                == ReplicaState.QUARANTINED
            clock.advance(4.0)
            out = router.run()
        assert [out[i] for i in ids] == ref

    def test_fleet_info_reports_pending_harvest(self, model):
        router, _ = self._fleet(model, k=4, n=1)
        router.submit([1, 2, 3], 9)
        router.step()
        router.step()
        info = router.fleet_info()
        assert info["replicas"][0]["pending_harvest"] >= 1
        router.run()
        info = router.fleet_info()
        assert info["replicas"][0]["pending_harvest"] == 0


# ---------------------------------------------------------------------
class TestJournalWindow:
    """Group-commit + crash durability of the deferred window."""

    def _journaled(self, model, tmp_path, k, clock=None, name="wal",
                   fsync="off"):
        clock = clock if clock is not None else FakeClock()
        jr = RouterJournal(os.path.join(str(tmp_path), name),
                           fsync=fsync, clock=clock)
        router = ServingRouter(
            lambda i: ContinuousBatchingEngine(
                model, clock=clock, max_batch_size=3, max_seq_len=64,
                page_size=4, harvest_every=k),
            num_replicas=2, policy="round_robin", clock=clock,
            sleep=clock.advance, journal=jr, page_size=4)
        return router, jr, clock

    def test_group_commit_one_progress_record_per_window(
            self, model, tmp_path):
        """Mirrors only move at harvest ticks, so the journal writes
        ONE batched progress record per window — the record count
        shrinks ~k-fold vs the synchronous loop while the journaled
        token payload stays identical."""
        counts, tokens = {}, {}
        for k in (1, 4, 8):
            router, jr, _ = self._journaled(model, tmp_path, k,
                                            name=f"wal{k}")
            ids = [router.submit(p, m) for p, m in JOBS]
            out = router.run()
            tokens[k] = [out[i] for i in ids]
            jr.close()
            recs = _journal_records(jr.path)
            counts[k] = sum(1 for r in recs if r["kind"] == "progress")
        assert tokens[4] == tokens[1] and tokens[8] == tokens[1]
        assert counts[4] * 2 <= counts[1]
        assert counts[8] <= counts[4]

    @pytest.mark.parametrize("offset", [0, 1, 2, 3])
    def test_router_sigkill_every_window_offset(self, model, tmp_path,
                                                offset):
        """SIGKILL the ROUTER at every intra-window offset, then
        recover(): durable_len is monotone while alive, at most k
        undurable suffix tokens die with the process, replay
        re-generates them bit-identically, and no token is ever
        duplicated (the streams equal the oracle EXACTLY)."""
        ref = TestRouterPipelineChaos()._ref(model, JOBS)
        # fsync="step" — one fsync per GROUP-COMMIT record, i.e. per
        # harvest window: the policy whose cost this PR amortizes
        # k-fold, and the one under which durable_len means DISK
        router, jr, clock = self._journaled(model, tmp_path, 4,
                                            name=f"wal{offset}",
                                            fsync="step")
        ids = [router.submit(p, m) for p, m in JOBS]
        floor = {i: 0 for i in ids}
        for _ in range(2 + offset):
            router.step()
            for i in ids:
                rec = router.requests[i]
                # the staleness contract, at every observable instant
                assert rec.durable_len >= floor[i]       # monotone
                assert rec.durable_len <= len(rec.tokens)
                assert len(rec.tokens) <= rec.device_len
                floor[i] = rec.durable_len
        assert any(not router.requests[i].done for i in ids)
        del router                                   # SIGKILL-shaped
        jr2 = RouterJournal(os.path.join(str(tmp_path),
                                         f"wal{offset}"),
                            fsync="off", clock=clock)
        recovered = ServingRouter.recover(
            jr2, lambda i: ContinuousBatchingEngine(
                model, clock=clock, max_batch_size=3, max_seq_len=64,
                page_size=4, harvest_every=4),
            num_replicas=2, policy="round_robin", clock=clock,
            sleep=clock.advance, page_size=4)
        for i in ids:
            rec = recovered.requests[i]
            assert rec.durable_len == len(rec.tokens)
            assert rec.durable_len >= floor[i]
        out = recovered.run()
        assert [out[i] for i in ids] == ref   # bit-identical, no dups

    def test_torn_window_tail_fuzz_every_offset(self, tmp_path):
        """Truncate the journal at EVERY byte offset inside a final
        WINDOW-SIZED progress record (the group-commit shape): replay
        never raises, recovers the committed prefix, and counts
        exactly one corrupt-tail drop — a torn window is
        indistinguishable from a window that never committed."""
        src = os.path.join(str(tmp_path), "wal")
        with RouterJournal(src, fsync="off") as jr:
            jr.append_submit(request_id="a", prompt=[1, 2],
                             max_new_tokens=16)
            jr.step_mirror({"a": [5, 6, 7, 8]})      # window 1 commits
            jr.step_mirror({"a": [5, 6, 7, 8, 9, 10, 11, 12]})  # torn
        seg = _segment_files(src)[-1]
        blob = open(os.path.join(src, seg), "rb").read()
        last_start, last_end = _record_spans(blob)[-1]
        assert last_end == len(blob)
        for cut in range(last_start + 1, last_end):
            trial = os.path.join(str(tmp_path), f"trial-{cut}")
            shutil.copytree(src, trial)
            with open(os.path.join(trial, seg), "r+b") as f:
                f.truncate(cut)
            rep = RouterJournal(trial, fsync="off").replay()
            assert rep.corrupt_dropped == 1, cut
            # the committed window survives whole; the torn one is
            # dropped whole — never a partial window
            assert rep.live["a"].tokens == [5, 6, 7, 8], cut
