"""paddle.sparse parity tier (VERDICT r3 missing #3 / next #5).

Oracles: scipy.sparse for structure/matmul/binary ops, dense numpy for
softmax/attention/conv, and jax.grad-free eager backward for the
gradients-throughout requirement (sp.values().grad must populate).
"""
import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import sparse

rng = np.random.default_rng(23)


def _rand_coo(m=6, n=5, density=0.4, seed=0):
    r = np.random.default_rng(seed)
    mat = sp.random(m, n, density=density, random_state=r.integers(1e6),
                    dtype=np.float32)
    coo = mat.tocoo()
    idx = np.stack([coo.row, coo.col])
    t = sparse.sparse_coo_tensor(idx, coo.data.astype(np.float32),
                                 [m, n], stop_gradient=False)
    return t, mat.toarray().astype(np.float32)


class TestStructure:
    def test_coalesce_sums_duplicates(self):
        idx = np.array([[0, 0, 1], [1, 1, 2]])
        vals = np.array([1.0, 2.0, 3.0], np.float32)
        s = sparse.sparse_coo_tensor(idx, vals, [3, 3])
        c = s.coalesce()
        assert c.nnz() == 2 and c.is_coalesced()
        d = np.asarray(c.to_dense()._value)
        assert d[0, 1] == 3.0 and d[1, 2] == 3.0

    def test_coo_csr_roundtrip_vs_scipy(self):
        t, dense = _rand_coo(seed=1)
        csr = t.to_sparse_csr()
        ref = sp.csr_matrix(dense)
        np.testing.assert_array_equal(np.asarray(csr.crows()._value),
                                      ref.indptr)
        np.testing.assert_array_equal(np.asarray(csr.cols()._value),
                                      ref.indices)
        np.testing.assert_allclose(np.asarray(csr.values()._value),
                                   ref.data, rtol=1e-6)
        back = np.asarray(csr.to_sparse_coo().to_dense()._value)
        np.testing.assert_allclose(back, dense, rtol=1e-6)

    def test_transpose(self):
        t, dense = _rand_coo(seed=2)
        tt = sparse.transpose(t, [1, 0])
        np.testing.assert_allclose(np.asarray(tt.to_dense()._value),
                                   dense.T, rtol=1e-6)


class TestBinaryOps:
    @pytest.mark.parametrize("op,npop", [
        ("add", np.add), ("subtract", np.subtract),
        ("multiply", np.multiply)])
    def test_vs_scipy_dense(self, op, npop):
        a, da = _rand_coo(seed=3)
        b, db = _rand_coo(seed=4)
        out = getattr(sparse, op)(a, b)
        np.testing.assert_allclose(np.asarray(out.to_dense()._value),
                                   npop(da, db), rtol=1e-5, atol=1e-6)

    def test_multiply_pattern_is_intersection(self):
        a, da = _rand_coo(seed=5)
        b, db = _rand_coo(seed=6)
        out = sparse.multiply(a, b)
        n_both = int(((da != 0) & (db != 0)).sum())
        assert out.nnz() == n_both

    def test_binary_grads_flow(self):
        a, da = _rand_coo(seed=7)
        b, db = _rand_coo(seed=8)
        out = sparse.add(a, b)
        out.values().sum().backward()
        ga = a.values().grad
        gb = b.values().grad
        assert ga is not None and gb is not None
        np.testing.assert_allclose(np.asarray(ga._value), 1.0)
        np.testing.assert_allclose(np.asarray(gb._value), 1.0)


class TestMatmulFamily:
    def test_spmm_vs_scipy(self):
        t, dense = _rand_coo(seed=9)
        y = rng.normal(size=(5, 4)).astype(np.float32)
        out = sparse.matmul(t, paddle.to_tensor(y))
        np.testing.assert_allclose(np.asarray(out._value), dense @ y,
                                   rtol=1e-5, atol=1e-6)

    def test_spmm_grads_to_values_and_dense(self):
        t, dense = _rand_coo(seed=10)
        y = paddle.to_tensor(rng.normal(size=(5, 4)).astype(np.float32),
                             stop_gradient=False)
        out = sparse.matmul(t, y)
        (out ** 2).sum().backward()
        gv = t.values().grad
        gy = y.grad
        assert gv is not None and gy is not None
        # dense oracle: d/dA sum((A@Y)^2) = 2 (A@Y) Y^T at the pattern
        gd = 2 * (dense @ np.asarray(y._value)) @ np.asarray(y._value).T
        ii = np.asarray(t.indices()._value)
        np.testing.assert_allclose(np.asarray(gv._value),
                                   gd[ii[0], ii[1]], rtol=1e-4,
                                   atol=1e-5)

    def test_mv_and_addmm(self):
        t, dense = _rand_coo(seed=11)
        v = rng.normal(size=(5,)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(sparse.mv(t, paddle.to_tensor(v))._value),
            dense @ v, rtol=1e-5, atol=1e-6)
        inp = rng.normal(size=(6, 4)).astype(np.float32)
        y = rng.normal(size=(5, 4)).astype(np.float32)
        got = sparse.addmm(paddle.to_tensor(inp), t,
                           paddle.to_tensor(y), beta=0.5, alpha=2.0)
        np.testing.assert_allclose(np.asarray(got._value),
                                   0.5 * inp + 2.0 * (dense @ y),
                                   rtol=1e-5, atol=1e-5)

    def test_sddmm_vs_dense(self):
        mask, dmask = _rand_coo(m=6, n=6, seed=12)
        x = rng.normal(size=(6, 8)).astype(np.float32)
        y = rng.normal(size=(8, 6)).astype(np.float32)
        out = sparse.masked_matmul(paddle.to_tensor(x),
                                   paddle.to_tensor(y), mask)
        ref = (x @ y) * (dmask != 0)
        np.testing.assert_allclose(np.asarray(out.to_dense()._value),
                                   ref, rtol=1e-4, atol=1e-5)

    def test_sddmm_grads(self):
        mask, dmask = _rand_coo(m=4, n=4, seed=13)
        x = paddle.to_tensor(rng.normal(size=(4, 3)).astype(np.float32),
                             stop_gradient=False)
        y = paddle.to_tensor(rng.normal(size=(3, 4)).astype(np.float32),
                             stop_gradient=False)
        out = sparse.masked_matmul(x, y, mask)
        out.values().sum().backward()
        assert x.grad is not None and y.grad is not None
        # oracle: d sum(M*(XY)) / dX = M Y^T (M the 0/1 pattern)
        pat = (dmask != 0).astype(np.float32)
        np.testing.assert_allclose(np.asarray(x.grad._value),
                                   pat @ np.asarray(y._value).T,
                                   rtol=1e-4, atol=1e-5)


class TestEdgeCases:
    """Round-4 review regressions."""

    def test_empty_operand_binary(self):
        a, da = _rand_coo(seed=30)
        # disjoint multiply -> empty result; adding it back must work
        empty = sparse.sparse_coo_tensor(
            np.zeros((2, 0), np.int64), np.zeros((0,), np.float32),
            [6, 5])
        out = sparse.add(a, empty)
        np.testing.assert_allclose(np.asarray(out.to_dense()._value),
                                   da, rtol=1e-6)
        out2 = sparse.multiply(a, empty)
        assert out2.nnz() == 0

    def test_shape_mismatch_raises_valueerror(self):
        a, _ = _rand_coo(m=4, n=4, seed=31)
        b, _ = _rand_coo(m=2, n=8, seed=32)
        with pytest.raises(ValueError, match="shape mismatch"):
            sparse.add(a, b)

    def test_matmul_with_vector_routes_to_mv(self):
        t, dense = _rand_coo(seed=33)
        v = rng.normal(size=(5,)).astype(np.float32)
        out = sparse.matmul(t, paddle.to_tensor(v))
        assert tuple(out.shape) == (6,)
        np.testing.assert_allclose(np.asarray(out._value), dense @ v,
                                   rtol=1e-5, atol=1e-6)

    def test_unary_accepts_name_kwarg(self):
        t, _ = _rand_coo(seed=34)
        out = sparse.tanh(t, name="t")
        assert out.nnz() == t.nnz()

    def test_maxpool_keeps_negative_sites(self):
        from paddle_tpu.sparse.nn import MaxPool3D
        # two sites (0,0,0,0,0) and (0,4,4,4,0) in (ndim, nnz) layout
        idx = np.array([[0, 0], [0, 4], [0, 4], [0, 4], [0, 0]])
        vals = np.array([-2.0, -3.0], np.float32)   # all-negative
        t = sparse.sparse_coo_tensor(idx, vals, [1, 8, 8, 8, 1])
        out = MaxPool3D(kernel_size=2, stride=2)(t)
        assert out.nnz() >= 2, "negative-valued sites were dropped"
        v = np.asarray(out.values()._value)
        assert (v < 0).all()


class TestUnaryZoo:
    @pytest.mark.parametrize("name,npf", [
        ("sin", np.sin), ("tanh", np.tanh), ("sqrt", np.sqrt),
        ("square", np.square), ("abs", np.abs), ("log1p", np.log1p),
        ("expm1", np.expm1), ("neg", np.negative)])
    def test_value_ops(self, name, npf):
        idx = np.array([[0, 1, 2], [1, 2, 0]])
        vals = np.array([0.5, 0.25, 0.75], np.float32)
        s = sparse.sparse_coo_tensor(idx, vals, [3, 3])
        out = getattr(sparse, name)(s)
        np.testing.assert_allclose(np.asarray(out.values()._value),
                                   npf(vals), rtol=1e-5)
        # pattern unchanged
        np.testing.assert_array_equal(
            np.asarray(out.indices()._value), idx)

    def test_cast(self):
        t, _ = _rand_coo(seed=14)
        out = sparse.cast(t, value_dtype="bfloat16")
        assert "bfloat16" in str(out.values()._value.dtype)


class TestSoftmaxAttention:
    def test_csr_softmax_vs_dense(self):
        t, dense = _rand_coo(m=5, n=7, density=0.5, seed=15)
        out = sparse.nn.functional.softmax(t)
        # dense oracle: -inf outside the pattern
        masked = np.where(dense != 0, dense, -np.inf)
        ref = np.exp(masked - masked.max(-1, keepdims=True))
        ref = np.nan_to_num(ref / ref.sum(-1, keepdims=True))
        np.testing.assert_allclose(np.asarray(out.to_dense()._value),
                                   ref, rtol=1e-5, atol=1e-6)

    def test_softmax_grads(self):
        t, _ = _rand_coo(seed=16)
        out = sparse.nn.functional.softmax(t)
        (out.values() ** 2).sum().backward()
        assert t.values().grad is not None

    def test_sparse_attention_vs_dense(self):
        B, H, S, D = 2, 3, 8, 4
        q = rng.normal(size=(B, H, S, D)).astype(np.float32)
        k = rng.normal(size=(B, H, S, D)).astype(np.float32)
        v = rng.normal(size=(B, H, S, D)).astype(np.float32)
        # causal band pattern as the sparse mask
        pat = np.tril(np.ones((S, S), np.float32))
        idx = np.stack(np.nonzero(pat))
        mask = sparse.sparse_coo_tensor(idx, pat[pat != 0], [S, S])
        out = sparse.nn.functional.attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), mask)
        # dense oracle
        scores = q @ k.transpose(0, 1, 3, 2) / np.sqrt(D)
        scores = np.where(pat[None, None] != 0, scores, -np.inf)
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        ref = p @ v
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=1e-4, atol=1e-5)

    def test_sparse_attention_grads(self):
        B, H, S, D = 1, 2, 6, 4
        q = paddle.to_tensor(rng.normal(size=(B, H, S, D))
                             .astype(np.float32), stop_gradient=False)
        k = paddle.to_tensor(rng.normal(size=(B, H, S, D))
                             .astype(np.float32), stop_gradient=False)
        v = paddle.to_tensor(rng.normal(size=(B, H, S, D))
                             .astype(np.float32), stop_gradient=False)
        pat = np.tril(np.ones((S, S), np.float32))
        idx = np.stack(np.nonzero(pat))
        mask = sparse.sparse_coo_tensor(idx, pat[pat != 0], [S, S])
        out = sparse.nn.functional.attention(q, k, v, mask)
        (out ** 2).sum().backward()
        for t in (q, k, v):
            assert t.grad is not None
            assert np.isfinite(np.asarray(t.grad._value)).all()
            assert np.abs(np.asarray(t.grad._value)).max() > 0


class TestSparseConv:
    def _point_cloud(self, n_pts=20, size=8, cin=3, seed=20):
        r = np.random.default_rng(seed)
        sites = np.unique(r.integers(0, size, (n_pts, 3)), axis=0)
        n = len(sites)
        feats = r.normal(size=(n, cin)).astype(np.float32)
        # COO over (N=1, D, H, W, C): one entry per (site, channel)
        idx_rows = []
        vals = []
        for i, s_ in enumerate(sites):
            for c in range(cin):
                idx_rows.append([0, s_[0], s_[1], s_[2], c])
                vals.append(feats[i, c])
        idx = np.asarray(idx_rows).T
        t = sparse.sparse_coo_tensor(
            idx, np.asarray(vals, np.float32), [1, size, size, size, cin],
            stop_gradient=False)
        dense = np.asarray(t.to_dense()._value)
        return t, dense, sites

    def test_subm_conv_output_sites_match_input(self):
        from paddle_tpu.sparse.nn import SubmConv3D
        paddle.seed(0)
        t, dense, sites = self._point_cloud()
        conv = SubmConv3D(3, 5, kernel_size=3, padding=1)
        out = conv(t)
        assert out.shape == [1, 8, 8, 8, 5]
        od = np.asarray(out.to_dense()._value)
        # the submanifold property: non-active sites stay EXACTLY zero
        site_mask = np.zeros((8, 8, 8), bool)
        site_mask[sites[:, 0], sites[:, 1], sites[:, 2]] = True
        assert np.all(od[0][~site_mask] == 0)
        # active sites match a dense conv at those positions
        import jax
        ref = np.asarray(jax.lax.conv_general_dilated(
            jnp.asarray(dense), conv.weight._value.astype(jnp.float32),
            (1, 1, 1), [(1, 1)] * 3,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC")))
        ref = ref + np.asarray(conv.bias._value)
        np.testing.assert_allclose(od[0][site_mask],
                                   ref[0][site_mask], rtol=1e-4,
                                   atol=1e-4)

    def test_conv3d_matches_dense_conv(self):
        from paddle_tpu.sparse.nn import Conv3D
        paddle.seed(1)
        t, dense, _ = self._point_cloud(seed=21)
        conv = Conv3D(3, 4, kernel_size=3, padding=1, bias_attr=False)
        out = conv(t)
        import jax
        ref = np.asarray(jax.lax.conv_general_dilated(
            jnp.asarray(dense), conv.weight._value.astype(jnp.float32),
            (1, 1, 1), [(1, 1)] * 3,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC")))
        np.testing.assert_allclose(np.asarray(out.to_dense()._value),
                                   ref * (np.abs(ref) > 0), rtol=1e-4,
                                   atol=1e-4)

    def test_subm_conv_grads_flow(self):
        from paddle_tpu.sparse.nn import SubmConv3D
        paddle.seed(2)
        t, _, _ = self._point_cloud(seed=22)
        conv = SubmConv3D(3, 4, kernel_size=3, padding=1)
        out = conv(t)
        (out.values() ** 2).sum().backward()
        assert conv.weight.grad is not None
        assert t.values().grad is not None
        assert np.abs(np.asarray(conv.weight.grad._value)).max() > 0

    def test_relu_batchnorm_pipeline(self):
        from paddle_tpu.sparse.nn import BatchNorm, ReLU, SubmConv3D
        paddle.seed(3)
        t, _, _ = self._point_cloud(seed=23)
        net_out = ReLU()(BatchNorm(4)(SubmConv3D(3, 4, 3, padding=1)(t)))
        v = np.asarray(net_out.values()._value)
        assert (v >= 0).all() and np.isfinite(v).all()
