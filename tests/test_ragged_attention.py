"""Ragged paged attention (ISSUE 6): the fused mixed prefill+decode
kernel (`ops/ragged_paged_attention.py`) proved in INTERPRET mode
against an independent NumPy oracle — mixed batches, prefix-shared
pages at nonzero position offsets, sliding windows, GQA group sizes,
and empty/degenerate sequences — plus the scatter/packing helpers, the
bounded-gather static trim, and the ENGINE-level contract: greedy
streams bit-identical between `attention_impl="ragged"` and `"legacy"`
through a forced preemption and a SIGKILL replica failover.

conftest runs this file with PDT_TELEMETRY=1 and
PDT_CHECK_INVARIANTS=1, so every engine step here re-proves page
accounting on the ragged path."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.serving import (ContinuousBatchingEngine,
                                       PoolExhausted, RequestStatus)
from paddle_tpu.ops.paged_attention import paged_attention_values
from paddle_tpu.ops.ragged_paged_attention import (
    gather_pages, pack_ragged_starts, ragged_paged_attention_values,
    ragged_scatter_values, token_arrays)
from paddle_tpu.utils.faults import FaultInjector


def np_ragged_oracle(q, kp, vp, qs, ql, cl, bt, window=None):
    """Independent NumPy reference: per-token loop over the page table,
    full-precision softmax. Padding rows output zero."""
    hk, _, ps, d = kp.shape
    h = q.shape[1]
    g = h // hk
    out = np.zeros_like(q, dtype=np.float32)
    scale = 1.0 / np.sqrt(d)
    for s in range(len(ql)):
        for j in range(int(ql[s])):
            row = int(qs[s]) + j
            pos = int(cl[s]) - int(ql[s]) + j
            lo = 0 if window is None else max(0, pos - window + 1)
            keys, vals = [], []
            for kpos in range(lo, pos + 1):
                pg = bt[s, kpos // ps]
                keys.append(kp[:, pg, kpos % ps])
                vals.append(vp[:, pg, kpos % ps])
            if not keys:
                continue
            K = np.stack(keys, 0)                    # (L, HK, D)
            V = np.stack(vals, 0)
            for head in range(h):
                kh = head // g
                logits = (K[:, kh] @ q[row, head]) * scale
                p = np.exp(logits - logits.max())
                p /= p.sum()
                out[row, head] = p @ V[:, kh]
    return out


def _case(rng, hk=2, g=2, d=16, ps=4, n_pages=12, pps=4,
          ql=(1, 7, 5), cl=(9, 7, 13), block_q=4, tail_pad=4,
          bt=None):
    """Build one ragged batch: packed q, page pools, block tables,
    descriptors. Defaults mix a decode step, a full prefill, and a
    suffix continuation (context > query: nonzero position offset)."""
    h = hk * g
    ql = np.asarray(ql, np.int32)
    cl = np.asarray(cl, np.int32)
    qs, total = pack_ragged_starts(ql, block_q=block_q)
    t = total + tail_pad
    q = rng.standard_normal((t, h, d)).astype(np.float32)
    kp = rng.standard_normal((hk, n_pages, ps, d)).astype(np.float32)
    vp = rng.standard_normal((hk, n_pages, ps, d)).astype(np.float32)
    if bt is None:
        bt = np.zeros((len(ql), pps), np.int32)
        nxt = 1
        for s in range(len(ql)):
            need = -(-int(cl[s]) // ps) if cl[s] else 0
            for j in range(need):
                bt[s, j] = nxt
                nxt += 1
            assert nxt <= n_pages
    return q, kp, vp, qs, ql, cl, np.asarray(bt, np.int32)


def _both_paths(q, kp, vp, qs, ql, cl, bt, window=None, block_q=4):
    """(interpret-mode Pallas kernel, XLA gather oracle) outputs."""
    args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            qs, ql, cl, bt)
    kern = np.asarray(ragged_paged_attention_values(
        *args, window=window, block_q=block_q, use_kernel=True))
    xla = np.asarray(ragged_paged_attention_values(
        *args, window=window, block_q=block_q, use_kernel=False))
    return kern, xla


class TestRaggedKernelParity:
    """Interpret-mode kernel AND the XLA oracle vs NumPy — the parity
    ladder every ops/ kernel carries."""

    def test_mixed_decode_prefill_batch(self):
        rng = np.random.default_rng(0)
        q, kp, vp, qs, ql, cl, bt = _case(rng)
        ref = np_ragged_oracle(q, kp, vp, qs, ql, cl, bt)
        kern, xla = _both_paths(q, kp, vp, qs, ql, cl, bt)
        np.testing.assert_allclose(kern, ref, atol=2e-5)
        np.testing.assert_allclose(xla, ref, atol=2e-5)
        # padding rows (owned by no sequence) are exactly zero
        seq_t, _ = token_arrays(qs, ql, cl, q.shape[0])
        assert np.all(kern[seq_t < 0] == 0)
        assert np.all(xla[seq_t < 0] == 0)

    def test_prefix_shared_pages_nonzero_offset(self):
        """Two sequences attach the SAME physical pages for their first
        two page slots (a prefix-cache hit); the second prefills only a
        suffix at position_offset = 8."""
        rng = np.random.default_rng(1)
        bt = np.zeros((2, 4), np.int32)
        bt[0] = [1, 2, 3, 0]       # full owner: ctx 12, decode q=1
        bt[1] = [1, 2, 4, 5]       # shares pages 1-2, suffix q=5 @ off 8
        q, kp, vp, qs, ql, cl, bt = _case(
            rng, ql=(1, 5), cl=(12, 13), bt=bt)
        ref = np_ragged_oracle(q, kp, vp, qs, ql, cl, bt)
        kern, xla = _both_paths(q, kp, vp, qs, ql, cl, bt)
        np.testing.assert_allclose(kern, ref, atol=2e-5)
        np.testing.assert_allclose(xla, ref, atol=2e-5)

    @pytest.mark.parametrize("window", [3, 6, 64])
    def test_sliding_window(self, window):
        rng = np.random.default_rng(2)
        q, kp, vp, qs, ql, cl, bt = _case(rng)
        ref = np_ragged_oracle(q, kp, vp, qs, ql, cl, bt, window=window)
        kern, xla = _both_paths(q, kp, vp, qs, ql, cl, bt, window=window)
        np.testing.assert_allclose(kern, ref, atol=2e-5)
        np.testing.assert_allclose(xla, ref, atol=2e-5)

    @pytest.mark.parametrize("g", [1, 2, 4])
    def test_gqa_group_sizes(self, g):
        rng = np.random.default_rng(3)
        q, kp, vp, qs, ql, cl, bt = _case(rng, g=g)
        ref = np_ragged_oracle(q, kp, vp, qs, ql, cl, bt)
        kern, xla = _both_paths(q, kp, vp, qs, ql, cl, bt)
        np.testing.assert_allclose(kern, ref, atol=2e-5)
        np.testing.assert_allclose(xla, ref, atol=2e-5)

    def test_empty_and_degenerate_sequences(self):
        """query_len 0 (nothing to do) and context_len == query_len == 1
        (a sequence's very first token) are both well-defined; outputs
        stay finite and match NumPy."""
        rng = np.random.default_rng(4)
        q, kp, vp, qs, ql, cl, bt = _case(
            rng, ql=(0, 1, 3), cl=(0, 1, 3), block_q=1, tail_pad=0)
        ref = np_ragged_oracle(q, kp, vp, qs, ql, cl, bt)
        kern, xla = _both_paths(q, kp, vp, qs, ql, cl, bt, block_q=1)
        assert np.isfinite(kern).all() and np.isfinite(xla).all()
        np.testing.assert_allclose(kern, ref, atol=2e-5)
        np.testing.assert_allclose(xla, ref, atol=2e-5)

    def test_decode_batch_matches_legacy_kernel(self):
        """A pure decode batch (block_q=1, one query per sequence) is
        exactly the legacy kernel's domain: both kernels, both in
        interpret mode, must agree — the ragged kernel subsumes the
        q=1 one."""
        rng = np.random.default_rng(5)
        b = 3
        q, kp, vp, qs, ql, cl, bt = _case(
            rng, ql=(1,) * b, cl=(9, 6, 2), block_q=1, tail_pad=0)
        ragged = np.asarray(ragged_paged_attention_values(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            qs, ql, cl, bt, block_q=1, use_kernel=True))
        legacy = np.asarray(paged_attention_values(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(cl), jnp.asarray(bt), use_kernel=True))
        np.testing.assert_allclose(ragged, legacy, atol=2e-5)
        # and the legacy interpret kernel agrees with ITS oracle
        legacy_xla = np.asarray(paged_attention_values(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(cl), jnp.asarray(bt)))
        np.testing.assert_allclose(legacy, legacy_xla, atol=2e-5)

    def test_unaligned_packed_length_rejected(self):
        rng = np.random.default_rng(6)
        q, kp, vp, qs, ql, cl, bt = _case(rng, tail_pad=3)  # t % 4 != 0
        with pytest.raises(ValueError, match="block_q"):
            ragged_paged_attention_values(
                jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                qs, ql, cl, bt, block_q=4, use_kernel=True)


class TestScatterAndPacking:
    def test_scatter_roundtrip_and_trash_routing(self):
        rng = np.random.default_rng(7)
        hk, d, ps, n_pages = 2, 8, 4, 6
        ql = np.array([3, 2], np.int32)
        cl = np.array([7, 2], np.int32)
        qs, total = pack_ragged_starts(ql, block_q=4)
        t = total
        seq_t, pos_t = token_arrays(qs, ql, cl, t)
        k_rows = rng.standard_normal((t, hk, d)).astype(np.float32)
        v_rows = rng.standard_normal((t, hk, d)).astype(np.float32)
        bt = np.array([[1, 2, 0], [3, 0, 0]], np.int32)
        kp0 = np.zeros((hk, n_pages, ps, d), np.float32)
        kp, vp = ragged_scatter_values(
            jnp.asarray(kp0), jnp.asarray(kp0.copy()),
            jnp.asarray(k_rows), jnp.asarray(v_rows),
            jnp.asarray(bt), jnp.asarray(seq_t), jnp.asarray(pos_t))
        kp = np.asarray(kp)
        for row in range(t):
            s, pos = int(seq_t[row]), int(pos_t[row])
            if s < 0:
                continue
            pg = bt[s, pos // ps]
            np.testing.assert_array_equal(kp[:, pg, pos % ps],
                                          k_rows[row])
        # live pages hold ONLY live rows; everything else (incl. every
        # padding row) landed in trash page 0
        live = {(int(bt[int(seq_t[r])][int(pos_t[r]) // ps]),
                 int(pos_t[r]) % ps)
                for r in range(t) if seq_t[r] >= 0}
        for pg in range(1, n_pages):
            for sl in range(ps):
                if (pg, sl) not in live:
                    assert np.all(kp[:, pg, sl] == 0), (pg, sl)

    def test_pack_starts_aligned_and_token_arrays(self):
        ql = [1, 7, 5, 0]
        qs, total = pack_ragged_starts(ql, block_q=8)
        assert list(qs) == [0, 8, 16, 24]
        assert total == 24
        seq_t, pos_t = token_arrays(qs, np.asarray(ql),
                                    np.asarray([4, 7, 9, 0]), 24)
        assert seq_t[0] == 0 and pos_t[0] == 3          # decode @ ctx-1
        assert list(seq_t[8:15]) == [1] * 7
        assert list(pos_t[16:21]) == [4, 5, 6, 7, 8]    # offset 4 suffix
        assert np.all(seq_t[np.r_[1:8, 15:16, 21:24]] == -1)


class TestGatherTrim:
    """The `_paged_xla` satellite: the gather is bounded to the
    block-table prefix actually referenced when context lengths are
    concrete, and the trim never changes results."""

    def test_gather_bounded_to_referenced_prefix(self):
        kp = jnp.zeros((2, 33, 4, 8))
        bt = jnp.asarray(np.zeros((3, 8), np.int32))
        ctx = np.array([5, 9, 2], np.int32)               # 3 pages max
        kc, _ = gather_pages(kp, kp, bt, context_lens=ctx)
        assert kc.shape[1] == 3 * 4                       # trimmed
        kc_full, _ = gather_pages(kp, kp, bt, pages_bound=8)
        assert kc_full.shape[1] == 8 * 4                  # full on demand
        # traced context lengths cannot trim (shape must be static)
        shape = jax.eval_shape(
            lambda c: gather_pages(kp, kp, bt, context_lens=c)[0],
            jax.ShapeDtypeStruct((3,), jnp.int32)).shape
        assert shape[1] == 8 * 4

    def test_trim_matches_full_gather_attention(self):
        rng = np.random.default_rng(8)
        q, kp, vp, qs, ql, cl, bt = _case(rng, pps=8, n_pages=40)
        trimmed = np.asarray(ragged_paged_attention_values(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            qs, ql, cl, bt, use_kernel=False))
        ref = np_ragged_oracle(q, kp, vp, qs, ql, cl, bt)
        np.testing.assert_allclose(trimmed, ref, atol=2e-5)


# -- engine integration ------------------------------------------------
@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=1, max_position_embeddings=64)
    paddle.seed(7)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _engine(model, **kw):
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", 4)
    return ContinuousBatchingEngine(model, **kw)


JOBS = [([5, 4, 3, 2, 6, 7], 8), ([9, 1, 2], 6), ([7, 7, 1, 2], 5)]


def _drain(eng):
    reqs = {}
    while eng._queue or any(r is not None for r in eng._slot_req):
        for r in eng.step():
            reqs[r.rid] = r
    return reqs


class TestRaggedEngineParity:
    """The ISSUE 6 acceptance contract: `attention_impl="ragged"` and
    `"legacy"` produce IDENTICAL greedy streams — in the clean run,
    through a forced preemption, and through a SIGKILL replica
    failover (the PR-4/5 chaos drills as the kernel's regression
    harness)."""

    def _run(self, model, impl, jobs=JOBS, fault=None, **kw):
        eng = _engine(model, attention_impl=impl, **kw)
        rids = [eng.add_request(p, n) for p, n in jobs]
        if fault is None:
            reqs = _drain(eng)
        else:
            with FaultInjector() as fi:
                fi.arm(*fault[:1], **fault[1])
                reqs = _drain(eng)
        return eng, rids, reqs

    def test_streams_identical_clean(self, model):
        outs = {}
        for impl in ("legacy", "ragged"):
            _, rids, reqs = self._run(model, impl)
            outs[impl] = [reqs[r].output for r in rids]
            assert all(reqs[r].status == RequestStatus.FINISHED
                       for r in rids)
        assert outs["ragged"] == outs["legacy"]

    def test_streams_identical_through_preemption(self, model):
        """Forced pool exhaustion mid-decode: the victim requeues and
        re-prefills through the ragged path — final streams equal the
        legacy run under the SAME fault."""
        outs = {}
        for impl in ("legacy", "ragged"):
            eng, rids, reqs = self._run(
                model, impl, jobs=JOBS[:2],
                fault=("serving.alloc_page",
                       dict(nth=4, exc=PoolExhausted)))
            assert eng.num_preemptions == 1, impl
            assert all(reqs[r].status == RequestStatus.FINISHED
                       for r in rids), impl
            outs[impl] = [reqs[r].output for r in rids]
        assert outs["ragged"] == outs["legacy"]

    def test_streams_identical_through_sigkill_failover(self, model):
        """A replica SIGKILL mid-decode with zero-loss failover: fleet
        outputs are identical between the two impls (and equal the
        single-engine reference)."""
        from paddle_tpu.serving import ServingRouter

        class Clock:
            def __init__(self):
                self.t = 0.0

            def advance(self, dt):
                self.t += dt

            def __call__(self):
                return self.t

        outs = {}
        for impl in ("legacy", "ragged"):
            clock = Clock()
            router = ServingRouter(
                lambda i: _engine(model, attention_impl=impl,
                                  clock=clock),
                num_replicas=3, policy="round_robin", clock=clock,
                sleep=clock.advance, page_size=4)
            ids = [router.submit(p, n) for p, n in JOBS]
            router.step()
            router.step()                            # mid-decode
            router.kill_replica(1)
            out = router.run()
            assert router.num_failovers == 1, impl
            outs[impl] = [out[i] for i in ids]
        assert outs["ragged"] == outs["legacy"]
        _, rids, reqs = self._run(model, "ragged")
        assert outs["ragged"] == [reqs[r].output for r in rids]

    def test_one_dispatch_per_admission_round(self, model):
        """Admitting N ragged prompts costs ONE dispatch: the first
        step's admission produces a single serving.ragged_prefill span
        carrying every admitted request_id, and no legacy per-bucket
        prefill/suffix/chunk programs are ever minted."""
        eng = _engine(model, max_batch_size=3)
        rids = [eng.add_request(p, n) for p, n in JOBS]
        eng.step()
        spans = [e for e in telemetry.events()
                 if e["name"] == "serving.ragged_prefill"]
        assert len(spans) == 1            # N admissions, ONE dispatch
        batch_rids = set(spans[0]["attrs"]["rids"])
        assert batch_rids == {str(r) for r in rids}
        eng.run()
        assert len(eng._prefill_jits) == 0
        assert len(eng._suffix_jits) == 0
        assert len(eng._ragged_jits) >= 1

    def test_prefix_cache_rides_ragged_admission(self, model):
        """A prefix-cache hit admits through the packed suffix path:
        hits are counted and outputs equal the cache-off engine."""
        sys_prompt = [3, 9, 2, 7, 5, 1, 4, 8]          # 2 full pages
        jobs = [(sys_prompt + [11], 6), (sys_prompt + [13, 14], 6)]
        outs = {}
        for caching in (False, True):
            eng = _engine(model, max_batch_size=1,
                          enable_prefix_caching=caching)
            rids = [eng.add_request(p, n) for p, n in jobs]
            reqs = _drain(eng)
            outs[caching] = [reqs[r].output for r in rids]
        assert outs[True] == outs[False]
        assert eng.prefix_hits >= 1
        assert eng.prefix_tokens_reused >= 4

    def test_chunked_prefill_spills_across_dispatches(self, model):
        """prefill_chunk bounds the ragged dispatch: a long prompt
        spills into chunk-continuation pieces, and the stream equals
        the unchunked engine's."""
        prompt = list(np.arange(1, 30) % 60 + 1)
        ref_eng, _, ref_reqs = self._run(model, "ragged",
                                         jobs=[(prompt, 6)])
        eng = _engine(model, attention_impl="ragged", prefill_chunk=8)
        rid = eng.add_request(prompt, 6)
        reqs = _drain(eng)
        assert reqs[rid].output == list(ref_reqs.values())[0].output
        # the admission really split: > 1 ragged_prefill span for one
        # admitted request
        spans = {e["seq"] for e in telemetry.events()
                 if e["name"] == "serving.ragged_prefill"}
        assert len(spans) >= 2

    def test_admission_program_gather_is_bounded(self, model):
        """The traced admission program cannot use the concrete trim
        (context lengths are tracers), so the engine threads a STATIC
        pages_bound — short prompts must compile a program whose
        gather is O(their pages), not O(pps)."""
        eng = _engine(model, max_batch_size=2)      # pps = 64/4 = 16
        eng.add_request([1, 2, 3], 2)
        eng.step()
        keys = list(eng._ragged_jits)
        assert keys, "no ragged admission program was built"
        t_pad, bound = keys[0]
        assert bound == 1                            # ceil(3/4) -> pow2
        assert bound < eng.pps

    def test_attention_impl_validation_and_dense_fallback(self, model):
        with pytest.raises(ValueError, match="attention_impl"):
            _engine(model, attention_impl="fused")
        eng = _engine(model, kv_layout="dense", attention_impl="ragged")
        assert eng.attn_impl == "legacy"   # dense has no page table
        eng2 = _engine(model)
        assert eng2.attn_impl == "ragged"  # the default

    def test_sampling_seeded_reproducible_on_ragged(self, model):
        def run(seed, **kw):
            eng = _engine(model, seed=seed, **kw)
            rid = eng.add_request([5, 42, 7, 11], 8)
            return _drain(eng)[rid].output

        s1 = run(3, do_sample=True, temperature=0.8, top_k=20)
        s2 = run(3, do_sample=True, temperature=0.8, top_k=20)
        assert s1 == s2 and len(s1) == 8
        tiny_p = run(9, do_sample=True, top_p=1e-9)
        greedy = run(0)
        assert tiny_p == greedy
