"""Real-TPU Mosaic compile gate for every Pallas kernel (VERDICT r2 item 2).

≙ SURVEY.md §4 two-platform rule: every kernel must not only pass math
checks in interpret mode but COMPILE for the attached chip. Round 2
shipped a norm kernel whose BlockSpec Mosaic rejected — interpret-mode CI
could not see it and the bench went to 0.0. This suite jits and EXECUTES
each kernel (fwd AND bwd) at the bench shapes so any Mosaic layout error
fails the suite, not the bench.

Runs only under PDT_TEST_PLATFORM=tpu with a real chip attached (Mosaic
compilation needs the TPU target); skips cleanly on the CPU CI mesh.
Driver smoke: `PDT_TEST_PLATFORM=tpu python -m pytest tests/test_tpu_compile.py -q`.

Gate mechanism: jit + EXECUTE + device_get, not AOT .lower().compile() —
the axon remote-AOT helper is unreliable (HTTP 500 on kernels that run
fine through the normal execution path, verified live this round), and
execution exercises exactly the Mosaic compile that the bench path hits.
device_get (a D2H transfer) is the sync: on the axon platform
jax.block_until_ready returns immediately for in-flight work, so it
would let a runtime failure escape the test.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    jax.devices()[0].platform != "tpu",
    reason="Mosaic compile gate needs the real TPU chip",
)

# bench.py's Llama config: hidden 1024, 16 q heads / 8 kv heads, d=64,
# batch 8, seq 2048 -> norm rows 16384 (the exact shape that failed r2)
BENCH_B, BENCH_S, BENCH_H, BENCH_HK, BENCH_D = 8, 2048, 16, 8, 64
BENCH_HIDDEN = 1024
BENCH_ROWS = BENCH_B * BENCH_S


def _compile(fn, *args):
    """jit + run + D2H: any Mosaic rejection (trace-time or chip compile)
    raises here."""
    return jax.device_get(jax.jit(fn)(*args))


class TestNormKernelsCompile:
    def test_rms_norm_fwd_bwd_bench_shape(self):
        from paddle_tpu.ops.norm_kernels import rms_norm_values

        x = jnp.zeros((BENCH_ROWS, BENCH_HIDDEN), jnp.bfloat16)
        w = jnp.ones((BENCH_HIDDEN,), jnp.bfloat16)
        _compile(rms_norm_values, x, w)

        def loss(x, w):
            return rms_norm_values(x, w).astype(jnp.float32).sum()

        _compile(jax.grad(loss, argnums=(0, 1)), x, w)

    def test_layer_norm_fwd_bwd_bench_shape(self):
        from paddle_tpu.ops.norm_kernels import layer_norm_values

        x = jnp.zeros((BENCH_ROWS, BENCH_HIDDEN), jnp.bfloat16)
        w = jnp.ones((BENCH_HIDDEN,), jnp.bfloat16)
        b = jnp.zeros((BENCH_HIDDEN,), jnp.bfloat16)
        _compile(layer_norm_values, x, w, b)

        def loss(x, w, b):
            return layer_norm_values(x, w, b).astype(jnp.float32).sum()

        _compile(jax.grad(loss, argnums=(0, 1, 2)), x, w, b)

    def test_rms_norm_runs_and_matches_xla(self):
        from paddle_tpu.ops.norm_kernels import rms_norm_values

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((512, BENCH_HIDDEN)),
                        jnp.bfloat16)
        w = jnp.asarray(rng.standard_normal(BENCH_HIDDEN), jnp.bfloat16)
        out = _compile(rms_norm_values, x, w)
        xf = x.astype(jnp.float32)
        ref = (xf * jax.lax.rsqrt(
            jnp.mean(jnp.square(xf), -1, keepdims=True) + 1e-6)
            * w.astype(jnp.float32))
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), atol=0.05)


class TestFlashAttentionCompile:
    def _qkv(self, sq=BENCH_S, sk=BENCH_S):
        q = jnp.zeros((BENCH_B, sq, BENCH_H, BENCH_D), jnp.bfloat16)
        k = jnp.zeros((BENCH_B, sk, BENCH_HK, BENCH_D), jnp.bfloat16)
        v = jnp.zeros((BENCH_B, sk, BENCH_HK, BENCH_D), jnp.bfloat16)
        return q, k, v

    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_bwd_gqa_bench_shape(self, causal):
        from paddle_tpu.ops.flash_attention import flash_attention_values

        q, k, v = self._qkv()
        _compile(lambda q, k, v: flash_attention_values(
            q, k, v, causal=causal), q, k, v)

        def loss(q, k, v):
            return flash_attention_values(
                q, k, v, causal=causal).astype(jnp.float32).sum()

        _compile(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)

    def test_sliding_window_fwd_bwd_bench_shape(self):
        from paddle_tpu.ops.flash_attention import flash_attention_values

        q, k, v = self._qkv()
        _compile(lambda q, k, v: flash_attention_values(
            q, k, v, causal=True, window_size=512), q, k, v)

        def loss(q, k, v):
            return flash_attention_values(
                q, k, v, causal=True,
                window_size=512).astype(jnp.float32).sum()

        _compile(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


class TestRopeCompile:
    def test_fwd_bwd_bench_shape(self):
        from paddle_tpu.ops.rope import rope_values

        x = jnp.zeros((BENCH_B, BENCH_S, BENCH_H, BENCH_D), jnp.bfloat16)
        # trig tables are (max_len, D/2) — the kernel's pair convention
        # (rope_values docstring; models/llama.py precompute_rope)
        cos = jnp.zeros((BENCH_S, BENCH_D // 2), jnp.float32)
        sin = jnp.zeros((BENCH_S, BENCH_D // 2), jnp.float32)
        _compile(rope_values, x, cos, sin)

        def loss(x):
            return rope_values(x, cos, sin).astype(jnp.float32).sum()

        _compile(jax.grad(loss), x)


class TestVarlenFlashCompile:
    def test_fwd_bwd_packed_bench_shape(self):
        from paddle_tpu.ops.flash_varlen import flash_attention_varlen_values

        q = jnp.zeros((BENCH_B, BENCH_S, BENCH_H, BENCH_D), jnp.bfloat16)
        k = jnp.zeros((BENCH_B, BENCH_S, BENCH_HK, BENCH_D), jnp.bfloat16)
        seg = jnp.zeros((BENCH_B, BENCH_S), jnp.int32)

        def loss(q, k, v):
            return flash_attention_varlen_values(
                q, k, v, seg, seg, causal=True).astype(jnp.float32).sum()

        _compile(lambda q, k, v: flash_attention_varlen_values(
            q, k, v, seg, seg, causal=True), q, k, k)
        _compile(jax.grad(loss, argnums=(0, 1, 2)), q, k, k)


class TestPagedAttentionCompile:
    def test_decode_shape(self):
        from paddle_tpu.ops.paged_attention import paged_attention_values

        b, pages_per_seq, page = 8, 128, 16   # 2048-token contexts
        q = jnp.zeros((b, BENCH_H, BENCH_D), jnp.bfloat16)
        kp = jnp.zeros((BENCH_HK, b * pages_per_seq, page, BENCH_D),
                       jnp.bfloat16)
        bt = jnp.arange(b * pages_per_seq, dtype=jnp.int32).reshape(
            b, pages_per_seq)
        cl = jnp.full((b,), 2000, jnp.int32)
        _compile(lambda q, kp, vp: paged_attention_values(
            q, kp, vp, cl, bt), q, kp, kp)
        # sliding-window band variant (serving window models on paged)
        _compile(lambda q, kp, vp: paged_attention_values(
            q, kp, vp, cl, bt, window=512), q, kp, kp)


class TestRaggedPagedAttentionCompile:
    """ISSUE 6: the mixed prefill+decode ragged kernel must compile AND
    execute on the chip — q tiles are (block_q*G, D), descriptors ride
    scalar prefetch, dead pages route their index_map to the trash
    page. Numerics vs the XLA oracle stay the interpret tier's job
    (tests/test_ragged_attention.py); this is the Mosaic gate."""

    def test_mixed_batch_and_decode_shapes(self):
        from paddle_tpu.ops.ragged_paged_attention import (
            pack_ragged_starts, ragged_paged_attention_values)

        pages_per_seq, page = 128, 16
        ql = np.array([512, 512, 1, 1, 1, 1], np.int32)
        cl = np.array([512, 512, 1800, 1500, 900, 600], np.int32)
        qs, total = pack_ragged_starts(ql, block_q=8)
        q = jnp.zeros((total, BENCH_H, BENCH_D), jnp.bfloat16)
        kp = jnp.zeros((BENCH_HK, len(ql) * pages_per_seq, page,
                        BENCH_D), jnp.bfloat16)
        bt = jnp.arange(len(ql) * pages_per_seq,
                        dtype=jnp.int32).reshape(len(ql), pages_per_seq)
        _compile(lambda q, kp, vp: ragged_paged_attention_values(
            q, kp, vp, qs, ql, cl, bt, block_q=8), q, kp, kp)
        _compile(lambda q, kp, vp: ragged_paged_attention_values(
            q, kp, vp, qs, ql, cl, bt, window=512, block_q=8),
            q, kp, kp)
        # decode form: block_q=1, one query per sequence
        b = 8
        qs1 = np.arange(b, dtype=np.int32)
        ql1 = np.ones(b, np.int32)
        cl1 = np.full(b, 2000, np.int32)
        q1 = jnp.zeros((b, BENCH_H, BENCH_D), jnp.bfloat16)
        kp1 = jnp.zeros((BENCH_HK, b * pages_per_seq, page, BENCH_D),
                        jnp.bfloat16)
        bt1 = jnp.arange(b * pages_per_seq, dtype=jnp.int32).reshape(
            b, pages_per_seq)
        _compile(lambda q, kp, vp: ragged_paged_attention_values(
            q, kp, vp, qs1, ql1, cl1, bt1, block_q=1), q1, kp1, kp1)


class TestGroupedMatmulCompile:
    def test_gmm_bench_shape(self):
        from paddle_tpu.ops.grouped_matmul import gmm_pallas

        # MoE-ish: 8 experts, 4096 tokens, 1024 -> 2816
        lhs = jnp.zeros((4096, BENCH_HIDDEN), jnp.bfloat16)
        rhs = jnp.zeros((8, BENCH_HIDDEN, 2816), jnp.bfloat16)
        sizes = jnp.full((8,), 512, jnp.int32)
        _compile(gmm_pallas, lhs, rhs, sizes)


class TestInt8MXUCompile:
    """Round-4: the W8A8 path must hit the MXU's native int8 mode on
    the real chip (VERDICT r3 #4), and be FASTER than bf16 at a
    serving-ish shape."""

    def test_int8_dot_compiles_and_runs(self):
        from paddle_tpu.nn.quant import (int8_dot_values,
                                         quantize_activation_dynamic_values)

        x = jnp.zeros((BENCH_ROWS // 4, BENCH_HIDDEN), jnp.bfloat16)
        w8 = jnp.zeros((BENCH_HIDDEN, 4 * BENCH_HIDDEN), jnp.int8)
        ws = jnp.ones((4 * BENCH_HIDDEN,), jnp.float32)

        def f(xv):
            xq, xs = quantize_activation_dynamic_values(xv)
            return int8_dot_values(xq, w8, xs, ws)
        _compile(f, x)

    def test_weight_only_int8_decode_shape(self):
        from paddle_tpu.nn.quant import (weight_only_linear_values,
                                         weight_quantize_values)

        w = jnp.ones((BENCH_HIDDEN, 4 * BENCH_HIDDEN), jnp.float32)
        qw, sc = weight_quantize_values(w)
        x = jnp.zeros((BENCH_B, 1, BENCH_HIDDEN), jnp.bfloat16)  # decode
        _compile(lambda xv: weight_only_linear_values(
            xv.reshape(-1, BENCH_HIDDEN), qw, sc), x)

    def test_int8_faster_than_bf16_at_large_shape(self):
        """Measured on-chip speedup, slope method (r5 chip-gate finding:
        the axon tunnel adds ~64ms per synchronous roundtrip, so ANY
        single-dispatch timing — device_get of the result, fused-reduce
        scalar, block_until_ready — measures transport, not the MXU.
        bench.bench_int8 times N dependent matmuls inside ONE executable
        at two values of N; the slope cancels every fixed cost: measured
        bf16 0.646 ms = 213 TF/s ≈ nominal v5e peak, int8 0.528 ms = 260
        TOP/s → a real but modest 1.22x, NOT the 2x of the 394-TOPs
        marketing peak)."""
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "bench", os.path.join(os.path.dirname(__file__), "..",
                                  "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        res = bench.bench_int8(on_tpu=True)
        print(f"\n{res}")
        assert "int8_timing_error" not in res, res
        assert res["int8_speedup_vs_bf16"] > 1.05, res


class TestRaggedEPCompile:
    """Round-5: the ragged exact-EP exchange (count all-gather +
    lax.ragged_all_to_all) has no XLA:CPU thunk, so the chip is the only
    place it can EXECUTE. ep=1 on the single chip still runs the real
    ragged-all-to-all op (self-exchange) through the full dispatch/
    compute/return pipeline."""

    def test_ragged_ep_matches_single_shard_dropless(self):
        from jax.sharding import Mesh, PartitionSpec as P
        from jax import shard_map
        from paddle_tpu.incubate.moe import (moe_ffn_dropless_ep_values,
                                             moe_ffn_dropless_values)

        e, h, i, k, t = 8, 256, 512, 2, 512
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((t, h)), jnp.float32)
        gw = jnp.asarray(rng.standard_normal((h, e)) * 0.1, jnp.float32)
        wg = jnp.asarray(rng.standard_normal((e, h, i)) * 0.05,
                         jnp.float32)
        wu = jnp.asarray(rng.standard_normal((e, h, i)) * 0.05,
                         jnp.float32)
        wd = jnp.asarray(rng.standard_normal((e, i, h)) * 0.05,
                         jnp.float32)

        mesh = Mesh(np.array(jax.devices()[:1]), ("ep",))

        def body(x_l, gw_, wg_l, wu_l, wd_l):
            return moe_ffn_dropless_ep_values(
                x_l, gw_, wg_l, wu_l, wd_l, k, 1, "ep", ["ep"], t * k,
                ragged=True)

        from paddle_tpu.distributed.collective import _SM_KW
        mapped = shard_map(
            body, mesh=mesh,
            in_specs=(P("ep", None), P(None, None), P("ep", None, None),
                      P("ep", None, None), P("ep", None, None)),
            out_specs=(P("ep", None), P(), P()), **_SM_KW)
        out, aux, drops = jax.device_get(jax.jit(mapped)(x, gw, wg, wu,
                                                         wd))
        ref, aux_ref = jax.device_get(
            jax.jit(lambda *a: moe_ffn_dropless_values(*a, k))(
                x, gw, wg, wu, wd))
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)
        assert abs(float(aux) - float(aux_ref)) < 1e-3
        assert int(drops) == 0


class TestPagedEngineDecodeCompile:
    """Round-5: the serving engine's paged decode path on the chip.

    Chip-gate r5 finding: asserting exact greedy-token equality between
    the paged and dense ENGINES is unsound on silicon — the Pallas paged
    kernel and the XLA dense attention are both correct but accumulate in
    different orders (measured max |Δ| = one bf16 ulp), and greedy argmax
    amplifies a near-tie into a different trajectory after ~10 tokens
    (interpret mode can't see this: both layouts run the same XLA math
    there). So the chip test asserts (a) single-step LOGIT parity between
    a paged and a dense decode step on identical cache state, and (b)
    both engines run end-to-end producing well-formed outputs."""

    def _tiny(self):
        import paddle_tpu as paddle
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        paddle.seed(0)
        cfg = LlamaConfig(vocab_size=2048, hidden_size=256,
                          intermediate_size=512, num_hidden_layers=2,
                          num_attention_heads=8, num_key_value_heads=4,
                          max_position_embeddings=512)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return cfg, m

    def test_paged_decode_step_logits_match_dense_on_chip(self):
        import paddle_tpu as paddle
        from paddle_tpu.core.tensor import Tensor, no_grad
        from paddle_tpu.models.llama import PagedKVCacheView
        from paddle_tpu.ops.paged_attention import paged_prefill_scatter

        cfg, m = self._tiny()
        hk, hd = cfg.num_key_value_heads, cfg.head_dim
        page_size, b, s_max = 16, 2, 256
        pps = s_max // page_size
        rng = np.random.default_rng(1)
        p_lens = [27, 41]                      # straddle page boundaries
        p_max = max(p_lens)
        ids = np.zeros((b, p_max), np.int64)
        for i, pl_ in enumerate(p_lens):
            ids[i, :pl_] = rng.integers(1, cfg.vocab_size, pl_)

        with no_grad():
            # dense prefill (zero caches + validity mask, the engine's
            # own prefill contract) -> per-layer (B, S, HK, D) caches
            zero = [(Tensor(jnp.zeros((b, p_max, hk, hd), jnp.float32)),
                     Tensor(jnp.zeros((b, p_max, hk, hd), jnp.float32)))
                    for _ in range(cfg.num_hidden_layers)]
            am = jnp.arange(p_max)[None, :] < jnp.asarray(p_lens)[:, None]
            _, caches = m.forward(Tensor(jnp.asarray(ids)),
                                  attention_mask=Tensor(am),
                                  past_key_values=zero,
                                  position_offset=0, use_cache=True)
            dense, paged = [], []
            n_pages = 1 + b * pps              # page 0 = trash page
            for (k, v) in caches:
                kd = jnp.zeros((b, s_max, hk, hd), k._value.dtype)
                vd = jnp.zeros_like(kd)
                kd = kd.at[:, :k.shape[1]].set(k._value)
                vd = vd.at[:, :v.shape[1]].set(v._value)
                dense.append((Tensor(kd), Tensor(vd)))
                kp = jnp.zeros((hk, n_pages, page_size, hd),
                               k._value.dtype)
                vp = jnp.zeros_like(kp)
                for i in range(b):
                    bt_row = jnp.arange(1 + i * pps, 1 + (i + 1) * pps)
                    kp, vp = paged_prefill_scatter(
                        kp, vp, k._value[i, :p_lens[i]].astype(kp.dtype),
                        v._value[i, :p_lens[i]].astype(kp.dtype),
                        bt_row, p_lens[i])
                paged.append((kp, vp))
            bt = jnp.arange(1, 1 + b * pps, dtype=jnp.int32).reshape(
                b, pps)
            tok = jnp.asarray([[7], [11]], jnp.int64)
            pos = jnp.asarray(p_lens, jnp.int32)

            lg_dense, _ = m.forward(Tensor(tok), past_key_values=dense,
                                    position_offset=Tensor(pos),
                                    use_cache=True)
            pkv = [PagedKVCacheView(kp, vp, bt) for kp, vp in paged]
            lg_paged, _ = m.forward(Tensor(tok), past_key_values=pkv,
                                    position_offset=Tensor(pos),
                                    use_cache=True)
        np.testing.assert_allclose(
            np.asarray(lg_paged._value, np.float32),
            np.asarray(lg_dense._value, np.float32), rtol=2e-2, atol=2e-2)

    def test_both_engine_layouts_run_on_chip(self):
        from paddle_tpu.models.serving import ContinuousBatchingEngine

        cfg, m = self._tiny()
        rng = np.random.default_rng(1)
        prompts = [list(rng.integers(1, cfg.vocab_size, 12 + 5 * j))
                   for j in range(3)]
        for layout in ("paged", "dense"):
            eng = ContinuousBatchingEngine(m, max_batch_size=2,
                                           max_seq_len=256,
                                           kv_layout=layout)
            rids = [eng.add_request(p, 16) for p in prompts]
            res = eng.run()
            assert sorted(res) == sorted(rids)
            for r in rids:
                assert len(res[r]) == 16
                assert all(0 <= t < cfg.vocab_size for t in res[r])

    def test_speculative_decode_on_chip(self):
        """Draft-propose + one-forward verify (vector-offset rope, s>1
        vector cache writes, in-graph verify mask) compiles and runs on
        silicon; output must stay lossless vs target greedy."""
        from paddle_tpu.models.speculative import speculative_generate
        import paddle_tpu as paddle
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        cfg, t = self._tiny()
        paddle.seed(1)
        d = LlamaForCausalLM(LlamaConfig(
            vocab_size=cfg.vocab_size, hidden_size=128,
            intermediate_size=256, num_hidden_layers=1,
            num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=512))
        d.eval()
        ids = np.random.default_rng(3).integers(
            1, cfg.vocab_size, (2, 9)).astype(np.int32)
        want, _ = t.generate(paddle.to_tensor(ids), max_new_tokens=12)
        got, acc = speculative_generate(t, d, paddle.to_tensor(ids),
                                        max_new_tokens=12,
                                        num_draft_tokens=4)
        np.testing.assert_array_equal(np.asarray(got._value),
                                      np.asarray(want._value))

    def test_prefix_caching_suffix_prefill_on_chip(self):
        """The prefix-hit admission path (page gather + chunked suffix
        prefill + rebased scatter) must compile and run on silicon."""
        from paddle_tpu.models.serving import ContinuousBatchingEngine

        cfg, m = self._tiny()
        rng = np.random.default_rng(2)
        base = list(rng.integers(1, cfg.vocab_size, 32))
        eng = ContinuousBatchingEngine(m, max_batch_size=1,
                                       max_seq_len=256,
                                       enable_prefix_caching=True)
        rids = [eng.add_request(base + [5, 6], 8),
                eng.add_request(base + [9], 8)]
        res = eng.run()
        assert eng.prefix_hits == 1 and eng.prefix_tokens_reused == 32
        for r in rids:
            assert len(res[r]) == 8
            assert all(0 <= t < cfg.vocab_size for t in res[r])
