"""Real-TPU Mosaic compile gate for every Pallas kernel (VERDICT r2 item 2).

≙ SURVEY.md §4 two-platform rule: every kernel must not only pass math
checks in interpret mode but COMPILE for the attached chip. Round 2
shipped a norm kernel whose BlockSpec Mosaic rejected — interpret-mode CI
could not see it and the bench went to 0.0. This suite jits and EXECUTES
each kernel (fwd AND bwd) at the bench shapes so any Mosaic layout error
fails the suite, not the bench.

Runs only under PDT_TEST_PLATFORM=tpu with a real chip attached (Mosaic
compilation needs the TPU target); skips cleanly on the CPU CI mesh.
Driver smoke: `PDT_TEST_PLATFORM=tpu python -m pytest tests/test_tpu_compile.py -q`.

Gate mechanism: jit + EXECUTE + device_get, not AOT .lower().compile() —
the axon remote-AOT helper is unreliable (HTTP 500 on kernels that run
fine through the normal execution path, verified live this round), and
execution exercises exactly the Mosaic compile that the bench path hits.
device_get (a D2H transfer) is the sync: on the axon platform
jax.block_until_ready returns immediately for in-flight work, so it
would let a runtime failure escape the test.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    jax.devices()[0].platform != "tpu",
    reason="Mosaic compile gate needs the real TPU chip",
)

# bench.py's Llama config: hidden 1024, 16 q heads / 8 kv heads, d=64,
# batch 8, seq 2048 -> norm rows 16384 (the exact shape that failed r2)
BENCH_B, BENCH_S, BENCH_H, BENCH_HK, BENCH_D = 8, 2048, 16, 8, 64
BENCH_HIDDEN = 1024
BENCH_ROWS = BENCH_B * BENCH_S


def _compile(fn, *args):
    """jit + run + D2H: any Mosaic rejection (trace-time or chip compile)
    raises here."""
    return jax.device_get(jax.jit(fn)(*args))


class TestNormKernelsCompile:
    def test_rms_norm_fwd_bwd_bench_shape(self):
        from paddle_tpu.ops.norm_kernels import rms_norm_values

        x = jnp.zeros((BENCH_ROWS, BENCH_HIDDEN), jnp.bfloat16)
        w = jnp.ones((BENCH_HIDDEN,), jnp.bfloat16)
        _compile(rms_norm_values, x, w)

        def loss(x, w):
            return rms_norm_values(x, w).astype(jnp.float32).sum()

        _compile(jax.grad(loss, argnums=(0, 1)), x, w)

    def test_layer_norm_fwd_bwd_bench_shape(self):
        from paddle_tpu.ops.norm_kernels import layer_norm_values

        x = jnp.zeros((BENCH_ROWS, BENCH_HIDDEN), jnp.bfloat16)
        w = jnp.ones((BENCH_HIDDEN,), jnp.bfloat16)
        b = jnp.zeros((BENCH_HIDDEN,), jnp.bfloat16)
        _compile(layer_norm_values, x, w, b)

        def loss(x, w, b):
            return layer_norm_values(x, w, b).astype(jnp.float32).sum()

        _compile(jax.grad(loss, argnums=(0, 1, 2)), x, w, b)

    def test_rms_norm_runs_and_matches_xla(self):
        from paddle_tpu.ops.norm_kernels import rms_norm_values

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((512, BENCH_HIDDEN)),
                        jnp.bfloat16)
        w = jnp.asarray(rng.standard_normal(BENCH_HIDDEN), jnp.bfloat16)
        out = _compile(rms_norm_values, x, w)
        xf = x.astype(jnp.float32)
        ref = (xf * jax.lax.rsqrt(
            jnp.mean(jnp.square(xf), -1, keepdims=True) + 1e-6)
            * w.astype(jnp.float32))
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), atol=0.05)


class TestFlashAttentionCompile:
    def _qkv(self, sq=BENCH_S, sk=BENCH_S):
        q = jnp.zeros((BENCH_B, sq, BENCH_H, BENCH_D), jnp.bfloat16)
        k = jnp.zeros((BENCH_B, sk, BENCH_HK, BENCH_D), jnp.bfloat16)
        v = jnp.zeros((BENCH_B, sk, BENCH_HK, BENCH_D), jnp.bfloat16)
        return q, k, v

    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_bwd_gqa_bench_shape(self, causal):
        from paddle_tpu.ops.flash_attention import flash_attention_values

        q, k, v = self._qkv()
        _compile(lambda q, k, v: flash_attention_values(
            q, k, v, causal=causal), q, k, v)

        def loss(q, k, v):
            return flash_attention_values(
                q, k, v, causal=causal).astype(jnp.float32).sum()

        _compile(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)

    def test_sliding_window_fwd_bwd_bench_shape(self):
        from paddle_tpu.ops.flash_attention import flash_attention_values

        q, k, v = self._qkv()
        _compile(lambda q, k, v: flash_attention_values(
            q, k, v, causal=True, window_size=512), q, k, v)

        def loss(q, k, v):
            return flash_attention_values(
                q, k, v, causal=True,
                window_size=512).astype(jnp.float32).sum()

        _compile(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


class TestRopeCompile:
    def test_fwd_bwd_bench_shape(self):
        from paddle_tpu.ops.rope import rope_values

        x = jnp.zeros((BENCH_B, BENCH_S, BENCH_H, BENCH_D), jnp.bfloat16)
        cos = jnp.zeros((BENCH_S, BENCH_D), jnp.float32)
        sin = jnp.zeros((BENCH_S, BENCH_D), jnp.float32)
        _compile(rope_values, x, cos, sin)

        def loss(x):
            return rope_values(x, cos, sin).astype(jnp.float32).sum()

        _compile(jax.grad(loss), x)


class TestVarlenFlashCompile:
    def test_fwd_bwd_packed_bench_shape(self):
        from paddle_tpu.ops.flash_varlen import flash_attention_varlen_values

        q = jnp.zeros((BENCH_B, BENCH_S, BENCH_H, BENCH_D), jnp.bfloat16)
        k = jnp.zeros((BENCH_B, BENCH_S, BENCH_HK, BENCH_D), jnp.bfloat16)
        seg = jnp.zeros((BENCH_B, BENCH_S), jnp.int32)

        def loss(q, k, v):
            return flash_attention_varlen_values(
                q, k, v, seg, seg, causal=True).astype(jnp.float32).sum()

        _compile(lambda q, k, v: flash_attention_varlen_values(
            q, k, v, seg, seg, causal=True), q, k, k)
        _compile(jax.grad(loss, argnums=(0, 1, 2)), q, k, k)


class TestPagedAttentionCompile:
    def test_decode_shape(self):
        from paddle_tpu.ops.paged_attention import paged_attention_values

        b, pages_per_seq, page = 8, 128, 16   # 2048-token contexts
        q = jnp.zeros((b, BENCH_H, BENCH_D), jnp.bfloat16)
        kp = jnp.zeros((BENCH_HK, b * pages_per_seq, page, BENCH_D),
                       jnp.bfloat16)
        bt = jnp.arange(b * pages_per_seq, dtype=jnp.int32).reshape(
            b, pages_per_seq)
        cl = jnp.full((b,), 2000, jnp.int32)
        _compile(lambda q, kp, vp: paged_attention_values(
            q, kp, vp, cl, bt), q, kp, kp)


class TestGroupedMatmulCompile:
    def test_gmm_bench_shape(self):
        from paddle_tpu.ops.grouped_matmul import gmm_pallas

        # MoE-ish: 8 experts, 4096 tokens, 1024 -> 2816
        lhs = jnp.zeros((4096, BENCH_HIDDEN), jnp.bfloat16)
        rhs = jnp.zeros((8, BENCH_HIDDEN, 2816), jnp.bfloat16)
        sizes = jnp.full((8,), 512, jnp.int32)
        _compile(gmm_pallas, lhs, rhs, sizes)


class TestInt8MXUCompile:
    """Round-4: the W8A8 path must hit the MXU's native int8 mode on
    the real chip (VERDICT r3 #4), and be FASTER than bf16 at a
    serving-ish shape."""

    def test_int8_dot_compiles_and_runs(self):
        from paddle_tpu.nn.quant import (int8_dot_values,
                                         quantize_activation_dynamic_values)

        x = jnp.zeros((BENCH_ROWS // 4, BENCH_HIDDEN), jnp.bfloat16)
        w8 = jnp.zeros((BENCH_HIDDEN, 4 * BENCH_HIDDEN), jnp.int8)
        ws = jnp.ones((4 * BENCH_HIDDEN,), jnp.float32)

        def f(xv):
            xq, xs = quantize_activation_dynamic_values(xv)
            return int8_dot_values(xq, w8, xs, ws)
        _compile(f, x)

    def test_weight_only_int8_decode_shape(self):
        from paddle_tpu.nn.quant import (weight_only_linear_values,
                                         weight_quantize_values)

        w = jnp.ones((BENCH_HIDDEN, 4 * BENCH_HIDDEN), jnp.float32)
        qw, sc = weight_quantize_values(w)
        x = jnp.zeros((BENCH_B, 1, BENCH_HIDDEN), jnp.bfloat16)  # decode
        _compile(lambda xv: weight_only_linear_values(
            xv.reshape(-1, BENCH_HIDDEN), qw, sc), x)

    def test_int8_faster_than_bf16_at_large_shape(self):
        """Measured on-chip speedup check (soft: asserts not slower than
        0.9x; records the ratio in the output for the round notes)."""
        import time

        m, k, n = 4096, 4096, 4096
        xb = jnp.ones((m, k), jnp.bfloat16)
        wb = jnp.ones((k, n), jnp.bfloat16)
        x8 = jnp.ones((m, k), jnp.int8)
        w8 = jnp.ones((k, n), jnp.int8)

        f_bf = jax.jit(lambda a, b: a @ b)
        f_i8 = jax.jit(lambda a, b: jax.lax.dot_general(
            a, b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32))

        def timeit(f, a, b):
            jax.device_get(f(a, b))          # compile + warm
            t0 = time.perf_counter()
            for _ in range(10):
                r = f(a, b)
            jax.device_get(r)
            return (time.perf_counter() - t0) / 10

        t_bf = timeit(f_bf, xb, wb)
        t_i8 = timeit(f_i8, x8, w8)
        print(f"\nint8 vs bf16 matmul {m}x{k}x{n}: bf16 {t_bf*1e3:.3f} "
              f"ms, int8 {t_i8*1e3:.3f} ms ({t_bf/t_i8:.2f}x)")
        assert t_i8 < t_bf / 0.9, (t_i8, t_bf)


class TestRaggedEPCompile:
    """Round-5: the ragged exact-EP exchange (count all-gather +
    lax.ragged_all_to_all) has no XLA:CPU thunk, so the chip is the only
    place it can EXECUTE. ep=1 on the single chip still runs the real
    ragged-all-to-all op (self-exchange) through the full dispatch/
    compute/return pipeline."""

    def test_ragged_ep_matches_single_shard_dropless(self):
        from jax.sharding import Mesh, PartitionSpec as P
        from jax import shard_map
        from paddle_tpu.incubate.moe import (moe_ffn_dropless_ep_values,
                                             moe_ffn_dropless_values)

        e, h, i, k, t = 8, 256, 512, 2, 512
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((t, h)), jnp.float32)
        gw = jnp.asarray(rng.standard_normal((h, e)) * 0.1, jnp.float32)
        wg = jnp.asarray(rng.standard_normal((e, h, i)) * 0.05,
                         jnp.float32)
        wu = jnp.asarray(rng.standard_normal((e, h, i)) * 0.05,
                         jnp.float32)
        wd = jnp.asarray(rng.standard_normal((e, i, h)) * 0.05,
                         jnp.float32)

        mesh = Mesh(np.array(jax.devices()[:1]), ("ep",))

        def body(x_l, gw_, wg_l, wu_l, wd_l):
            return moe_ffn_dropless_ep_values(
                x_l, gw_, wg_l, wu_l, wd_l, k, 1, "ep", ["ep"], t * k,
                ragged=True)

        mapped = shard_map(
            body, mesh=mesh,
            in_specs=(P("ep", None), P(None, None), P("ep", None, None),
                      P("ep", None, None), P("ep", None, None)),
            out_specs=(P("ep", None), P(), P()))
        out, aux, drops = jax.device_get(jax.jit(mapped)(x, gw, wg, wu,
                                                         wd))
        ref, aux_ref = jax.device_get(
            jax.jit(lambda *a: moe_ffn_dropless_values(*a, k))(
                x, gw, wg, wu, wd))
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)
        assert abs(float(aux) - float(aux_ref)) < 1e-3
        assert int(drops) == 0


class TestPagedEngineDecodeCompile:
    """Round-5: the serving engine's paged decode step (vector-position
    rope + paged append + paged attention + sampling) at engine shapes,
    end-to-end on the chip, with outputs checked against the dense
    engine."""

    def test_paged_engine_step_matches_dense_on_chip(self):
        import paddle_tpu as paddle
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.models.serving import ContinuousBatchingEngine

        paddle.seed(0)
        cfg = LlamaConfig(vocab_size=2048, hidden_size=256,
                          intermediate_size=512, num_hidden_layers=2,
                          num_attention_heads=8, num_key_value_heads=4,
                          max_position_embeddings=512)
        m = LlamaForCausalLM(cfg)
        m.eval()
        rng = np.random.default_rng(1)
        prompts = [list(rng.integers(1, cfg.vocab_size, 12 + 5 * j))
                   for j in range(3)]
        outs = {}
        for layout in ("paged", "dense"):
            eng = ContinuousBatchingEngine(m, max_batch_size=2,
                                           max_seq_len=256,
                                           kv_layout=layout)
            rids = [eng.add_request(p, 16) for p in prompts]
            res = eng.run()
            outs[layout] = [res[r] for r in rids]
        assert outs["paged"] == outs["dense"]
