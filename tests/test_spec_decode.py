"""Speculative decoding as a first-class ENGINE mode (ISSUE 10):
`ContinuousBatchingEngine(spec_decode=SpecConfig(draft, k))` drafts k
greedy tokens per slot over the draft's own paged cache (one fused
scan dispatch), verifies every slot in ONE batched ragged target pass,
and commits the longest matching prefix + bonus token.

The contract under test is LOSSLESSNESS: engine-speculative greedy
streams are BIT-IDENTICAL to the engine-plain streams — in the clean
run, at tiny token budgets, through eos, through a forced preemption
(token-folding re-prefill drops draft state), through a SIGKILL router
failover, and through a prefill→decode migration under `roles=` (the
draft cache is dropped at the source and rebuilt on the target —
never torn). conftest runs this file with PDT_TELEMETRY=1 and
PDT_CHECK_INVARIANTS=1, so the DRAFT pool's page accounting
(`_check_invariants_draft`) is re-proved after every engine step of
every test here."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.serving import (ContinuousBatchingEngine,
                                       PoolExhausted, RequestStatus,
                                       SpecConfig)
from paddle_tpu.serving import ServingRouter
from paddle_tpu.utils.faults import FaultInjector


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=1, max_position_embeddings=64)
    paddle.seed(7)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def draft():
    cfg = LlamaConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                      num_hidden_layers=1, num_attention_heads=2,
                      num_key_value_heads=1, max_position_embeddings=64)
    paddle.seed(8)
    d = LlamaForCausalLM(cfg)
    d.eval()
    return d


JOBS = [([5, 4, 3, 2, 6, 7], 8), ([9, 1, 2], 6), ([7, 7, 1, 2], 5)]


def _engine(model, **kw):
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", 4)
    return ContinuousBatchingEngine(model, **kw)


def _drain(eng):
    reqs = {}
    while eng._queue or any(r is not None for r in eng._slot_req):
        for r in eng.step():
            reqs[r.rid] = r
    return reqs


def _run(model, jobs=JOBS, fault=None, **kw):
    eng = _engine(model, **kw)
    rids = [eng.add_request(p, n) for p, n in jobs]
    if fault is None:
        reqs = _drain(eng)
    else:
        with FaultInjector() as fi:
            fi.arm(fault[0], **fault[1])
            reqs = _drain(eng)
    return eng, [reqs[r].output for r in rids], \
        [reqs[r].status for r in rids]


@pytest.fixture(scope="module")
def plain(model):
    """The engine-plain greedy reference streams for JOBS — computed
    once; every lossless assertion in this module compares to it."""
    _, outs, statuses = _run(model)
    assert all(s == RequestStatus.FINISHED for s in statuses)
    return outs


class TestSpecConfigValidation:
    def test_requires_paged_ragged(self, model, draft):
        with pytest.raises(ValueError, match="ragged"):
            _engine(model, kv_layout="dense",
                    spec_decode=SpecConfig(draft))
        with pytest.raises(ValueError, match="ragged"):
            _engine(model, attention_impl="legacy",
                    spec_decode=SpecConfig(draft))

    def test_greedy_only(self, model, draft):
        with pytest.raises(ValueError, match="greedy"):
            _engine(model, do_sample=True, temperature=0.8,
                    spec_decode=SpecConfig(draft))

    def test_vocab_and_rope_coverage(self, model, draft):
        bad = LlamaForCausalLM(LlamaConfig(
            vocab_size=32, hidden_size=16, intermediate_size=32,
            num_hidden_layers=1, num_attention_heads=2,
            num_key_value_heads=1, max_position_embeddings=64))
        with pytest.raises(ValueError, match="vocab"):
            _engine(model, spec_decode=SpecConfig(bad))
        short = LlamaForCausalLM(LlamaConfig(
            vocab_size=64, hidden_size=16, intermediate_size=32,
            num_hidden_layers=1, num_attention_heads=2,
            num_key_value_heads=1, max_position_embeddings=16))
        with pytest.raises(ValueError, match="rope"):
            _engine(model, spec_decode=SpecConfig(short))

    def test_k_validation(self, model, draft):
        with pytest.raises(ValueError, match="k must be"):
            _engine(model, spec_decode=SpecConfig(draft, k=0))

    def test_tiny_draft_pairs_with_tiny(self):
        """The ready-made tiny()/tiny_draft() pair passes every
        spec_decode compatibility check (shared vocab, rope coverage)
        — the config a demo reaches for first must actually work."""
        t_cfg, d_cfg = LlamaConfig.tiny(), LlamaConfig.tiny_draft()
        assert d_cfg.vocab_size == t_cfg.vocab_size
        assert d_cfg.max_position_embeddings \
            == t_cfg.max_position_embeddings
        paddle.seed(0)
        target = LlamaForCausalLM(t_cfg)
        d = LlamaForCausalLM(d_cfg)
        eng = ContinuousBatchingEngine(target, max_batch_size=1,
                                       max_seq_len=64,
                                       spec_decode=SpecConfig(d, k=4))
        assert eng.spec_enabled

    def test_sliding_window_rejected(self, draft):
        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=1,
                          num_attention_heads=2, num_key_value_heads=1,
                          max_position_embeddings=64, sliding_window=8)
        paddle.seed(9)
        win = LlamaForCausalLM(cfg)
        win.eval()
        with pytest.raises(ValueError, match="sliding_window"):
            _engine(win, spec_decode=SpecConfig(draft))


class TestAcceptanceCore:
    """`spec_accept_greedy` is the ONE copy of the acceptance math —
    shared by `speculative_generate`'s compiled loop and the engine's
    verify commit (sentinel-padded for ragged per-slot budgets)."""

    def test_prefix_match_and_bonus(self):
        from paddle_tpu.models.speculative import spec_accept_greedy
        g = np.array([[1, 2, 3], [4, 9, 9], [5, 6, 7]], np.int32)
        p = np.array([[1, 2], [4, 5], [9, 9]], np.int32)
        j, bonus = (np.asarray(x) for x in spec_accept_greedy(g, p))
        # full accept -> bonus is the free extra token
        # partial -> bonus corrects the first mismatch
        # zero accept -> bonus is the plain greedy token
        np.testing.assert_array_equal(j, [2, 1, 0])
        np.testing.assert_array_equal(bonus, [3, 9, 5])

    def test_sentinel_padding_caps_accept_count(self):
        from paddle_tpu.models.speculative import spec_accept_greedy
        # row budget k_i=1 padded with -1 proposals / -2 greedy: j can
        # never run past the real proposal count
        g = np.array([[1, 2, -2, -2]], np.int32)
        p = np.array([[1, -1, -1]], np.int32)
        j, bonus = (np.asarray(x) for x in spec_accept_greedy(g, p))
        assert int(j[0]) == 1 and int(bonus[0]) == 2


class TestSpecEngineLossless:
    def test_streams_identical_clean(self, model, draft, plain):
        for k in (2, 4):
            eng, outs, statuses = _run(
                model, spec_decode=SpecConfig(draft, k=k))
            assert outs == plain, f"k={k}"
            assert all(s == RequestStatus.FINISHED for s in statuses)
            assert eng.num_spec_rounds > 0

    def test_self_draft_accepts_everything(self, model, plain):
        """target==draft: the draft's greedy picks ARE the target's,
        so every proposal is accepted and each round commits k+1
        tokens — the multiplicative-throughput configuration bench.py
        measures."""
        eng, outs, _ = _run(model, spec_decode=SpecConfig(model, k=4))
        assert outs == plain
        info = eng.spec_info()
        assert info["proposed"] > 0
        assert info["accepted"] == info["proposed"]
        assert info["acceptance_rate"] == 1.0

    def test_eos_stops_identically(self, model, draft, plain):
        eos = plain[0][3]            # a token plain emits mid-stream
        _, want, p_st = _run(model, eos_token_id=eos)
        eng, got, s_st = _run(model, eos_token_id=eos,
                              spec_decode=SpecConfig(draft, k=4))
        assert got == want and p_st == s_st
        assert got[0][-1] == eos and len(got[0]) == 4

    def test_tiny_budgets_never_overshoot(self, model, draft):
        """k > remaining budget: the verify budget caps at
        remaining-1, so a round can never emit past max_new_tokens —
        incl. the k_i=0 degenerate where the slot rides the round as
        a plain qlen=1 row."""
        jobs = [([5, 4, 3], 1), ([9, 1, 2], 2), ([8, 8], 3)]
        _, want, _ = _run(model, jobs=jobs)
        _, got, statuses = _run(model, jobs=jobs,
                                spec_decode=SpecConfig(draft, k=8))
        assert got == want
        assert [len(o) for o in got] == [1, 2, 3]
        assert all(s == RequestStatus.FINISHED for s in statuses)

    def test_streams_identical_through_preemption(self, model, draft,
                                                  plain):
        """Forced pool exhaustion mid-round: the victim's slot release
        DROPS its draft cache with it; the token-folding re-prefill
        readmits, and the next spec round backfills the draft from the
        folded stream — the final streams still equal plain greedy."""
        eng, outs, statuses = _run(
            model, jobs=JOBS[:2],
            fault=("serving.alloc_page", dict(nth=4, exc=PoolExhausted)),
            spec_decode=SpecConfig(draft, k=4))
        assert eng.num_preemptions >= 1
        assert outs == plain[:2]
        assert all(s == RequestStatus.FINISHED for s in statuses)

    def test_draft_pool_exhaustion_degrades_that_slot(self, model,
                                                      draft, plain):
        """An undersized draft pool (explicit SpecConfig.num_pages)
        starves the draft cache: affected slots ride rounds as plain
        qlen=1 rows — streams stay bit-identical, nothing fails."""
        eng, outs, statuses = _run(
            model, spec_decode=SpecConfig(draft, k=4, num_pages=3))
        assert outs == plain
        assert all(s == RequestStatus.FINISHED for s in statuses)


class TestSpecTelemetry:
    def test_spans_metrics_and_acceptance_gauge(self, model, draft,
                                                plain):
        telemetry.reset()
        telemetry.clear_events()
        eng, outs, _ = _run(model, spec_decode=SpecConfig(draft, k=4))
        assert outs == plain
        names = [e["name"] for e in telemetry.events()]
        drafts = [e for e in telemetry.events()
                  if e["name"] == "serving.draft"]
        verifies = [e for e in telemetry.events()
                    if e["name"] == "serving.verify"]
        assert len(drafts) == eng.num_spec_rounds == len(verifies)
        assert drafts[0]["attrs"]["k"] == 4
        assert verifies[0]["attrs"]["rids"]      # trace fan-out handle
        assert "serving.decode_step" not in names   # no plain rounds
        snap = telemetry.snapshot()["counters"]
        assert snap["pdt_spec_rounds_total"][""] == eng.num_spec_rounds
        assert snap["pdt_spec_proposed_total"][""] \
            == eng.num_spec_proposed
        assert snap["pdt_spec_accepted_total"][""] \
            == eng.num_spec_accepted
        rate = telemetry.value("pdt_spec_acceptance_rate")
        assert rate == pytest.approx(eng.spec_info()["acceptance_rate"])
        # emitted spec tokens ride the decode-token counter: effective
        # decode throughput stays one metric, speculative or not
        emitted = sum(len(o) for o in outs)
        first_tokens = len(outs)
        assert telemetry.value("pdt_serving_decode_tokens_total") \
            == emitted - first_tokens
        hists = telemetry.snapshot()["histograms"]
        assert hists["pdt_spec_draft_seconds"][""]["count"] \
            == eng.num_spec_rounds
        assert hists["pdt_spec_verify_seconds"][""]["count"] \
            == eng.num_spec_rounds


class TestSpecFleet:
    def _factory(self, model, draft, k):
        def f(i):
            return _engine(model, enable_prefix_caching=True,
                           spec_decode=None if k is None
                           else SpecConfig(draft, k=k))
        return f

    def test_streams_identical_through_sigkill_failover(self, model,
                                                        draft):
        """SIGKILL a spec replica mid-decode: failover re-prefills on
        a survivor from the router's token mirror (draft cache died
        with the engine — rebuilt lazily on the survivor), and fleet
        outputs equal an UNKILLED PLAIN fleet's."""
        clock = FakeClock()
        ref = ServingRouter(self._factory(model, draft, None),
                            num_replicas=3, policy="round_robin",
                            clock=clock, sleep=clock.advance,
                            page_size=4)
        ids0 = [ref.submit(p, n) for p, n in JOBS]
        want = ref.run()

        clock = FakeClock()
        router = ServingRouter(self._factory(model, draft, 4),
                               num_replicas=3, policy="round_robin",
                               clock=clock, sleep=clock.advance,
                               page_size=4)
        ids = [router.submit(p, n) for p, n in JOBS]
        router.step()
        router.step()                            # mid-decode
        router.kill_replica(1)
        got = router.run()
        assert router.num_failovers >= 1
        assert [got[i] for i in ids] == [want[i] for i in ids0]
        info = router.fleet_info()
        assert info["speculation"]["rounds"] > 0
        # the killed replica's acceptance history survived the discard
        assert info["speculation"]["proposed"] >= \
            sum(h.spec_info()["proposed"] for h in router.replicas
                if h.engine is not None)

    def test_migration_rebuilds_draft_on_decode_replica(self, model,
                                                        draft):
        """Disaggregated roles with speculation: prefill→decode
        migration moves TARGET pages only; the decode replica rebuilds
        the draft cache from the migrated stream on its first spec
        round. Outputs equal a plain colocated fleet's, and the
        invariant checker (draft section included) holds on both
        engines through every transfer."""
        clock = FakeClock()
        ref = ServingRouter(self._factory(model, draft, None),
                            num_replicas=2, policy="round_robin",
                            clock=clock, sleep=clock.advance,
                            page_size=4)
        ids0 = [ref.submit(p, n) for p, n in JOBS]
        want = ref.run()

        clock = FakeClock()
        router = ServingRouter(self._factory(model, draft, 4),
                               policy="prefix_affinity",
                               roles="prefill:1,decode:1",
                               clock=clock, sleep=clock.advance,
                               page_size=4)
        ids = [router.submit(p, n) for p, n in JOBS]
        got = router.run()
        info = router.fleet_info()
        assert info["migrations"] >= 1
        assert [got[i] for i in ids] == [want[i] for i in ids0]
        decode_replica = router.replicas[1]
        assert decode_replica.role == "decode"
        assert decode_replica.spec_info()["rounds"] > 0

    def test_fleet_info_omits_speculation_when_off(self, model, draft):
        clock = FakeClock()
        router = ServingRouter(self._factory(model, draft, None),
                               num_replicas=1, clock=clock,
                               sleep=clock.advance, page_size=4)
        assert "speculation" not in router.fleet_info()
