"""hapi Model trainer tests. ≙ reference «test/legacy_test/test_model.py»
family (Model.fit/evaluate/predict, callbacks) [U]."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.hapi import EarlyStopping, Model
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy
from paddle_tpu.nn import functional as F
from paddle_tpu.optimizer import Adam


class _ToyDataset(Dataset):
    """Linearly separable 2-class problem."""

    def __init__(self, n=128, seed=0):
        rng = np.random.default_rng(seed)
        self.x = rng.normal(size=(n, 8)).astype(np.float32)
        # ground-truth weights shared across train/eval splits
        w = np.random.default_rng(42).normal(size=(8,))
        self.y = (self.x @ w > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _mlp():
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))


class TestModelFit:
    def test_fit_improves_accuracy(self, tmp_path):
        paddle.seed(0)
        net = _mlp()
        model = Model(net)
        model.prepare(Adam(learning_rate=0.01,
                           parameters=net.parameters()),
                      loss=nn.CrossEntropyLoss(),
                      metrics=Accuracy())
        train = _ToyDataset(128)
        model.fit(train, epochs=8, batch_size=32, verbose=0)
        logs = model.evaluate(_ToyDataset(64, seed=1), batch_size=32,
                              verbose=0)
        assert logs["acc"] > 0.8, logs

    def test_save_load_roundtrip(self, tmp_path):
        paddle.seed(0)
        net = _mlp()
        model = Model(net)
        model.prepare(Adam(learning_rate=0.01,
                           parameters=net.parameters()),
                      loss=nn.CrossEntropyLoss())
        model.fit(_ToyDataset(64), epochs=1, batch_size=32, verbose=0)
        path = str(tmp_path / "ckpt" / "model")
        model.save(path)

        net2 = _mlp()
        model2 = Model(net2)
        model2.prepare(Adam(learning_rate=0.01,
                            parameters=net2.parameters()),
                       loss=nn.CrossEntropyLoss())
        model2.load(path)
        x = paddle.to_tensor(np.ones((2, 8), np.float32))
        np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(),
                                   rtol=1e-6)

    def test_early_stopping_stops(self):
        paddle.seed(0)
        net = _mlp()
        model = Model(net)
        model.prepare(Adam(learning_rate=0.0,  # frozen -> no improvement
                           parameters=net.parameters()),
                      loss=nn.CrossEntropyLoss())
        es = EarlyStopping(monitor="loss", patience=1, verbose=0)
        model.fit(_ToyDataset(32), eval_data=_ToyDataset(32, seed=2),
                  epochs=10, batch_size=16, verbose=0, callbacks=[es])
        assert model.stop_training

    def test_predict_and_summary(self, capsys):
        net = _mlp()
        model = Model(net)
        model.prepare(loss=nn.CrossEntropyLoss())
        outs = model.predict(_ToyDataset(16), batch_size=8)
        assert len(outs) == 2
        info = model.summary()
        assert info["total_params"] == 8 * 32 + 32 + 32 * 2 + 2


class TestAccumulateGradBatches:
    def test_fit_with_accumulation(self):
        """accumulate_grad_batches = Paddle gradient-merge: N loader
        batches merge into ONE optimizer step (was silently ignored
        before round 3). Ragged datasets must not crash (tail drops)."""
        from paddle_tpu.io import Dataset

        class DS(Dataset):
            def __init__(self):
                r = np.random.default_rng(0)
                self.x = r.normal(size=(32, 8)).astype(np.float32)
                w = r.normal(size=(8, 1)).astype(np.float32)
                self.y = self.x @ w

            def __getitem__(self, i):
                return self.x[i], self.y[i]

            def __len__(self):
                return 32

        paddle.seed(0)
        net = nn.Linear(8, 1)
        model = Model(net)
        model.prepare(optimizer=paddle.optimizer.Adam(
            learning_rate=0.05, parameters=net.parameters()),
            loss=nn.MSELoss())
        model.fit(DS(), batch_size=8, epochs=25, verbose=0,
                  accumulate_grad_batches=2)
        assert model._train_step.accumulate_steps == 2
        # one optimizer step per 2 loader batches => 32/8/2 = 2 steps/epoch
        assert model._optimizer._step_count == 25 * 2
        res = model.evaluate(DS(), batch_size=8, verbose=0)
        assert res["loss"] < 1.0

    def test_fit_accumulation_ragged_dataset(self):
        """30 samples, batch 8, accum 2: ragged tail dropped, no crash."""
        from paddle_tpu.io import Dataset

        class DS(Dataset):
            def __init__(self):
                r = np.random.default_rng(1)
                self.x = r.normal(size=(30, 8)).astype(np.float32)
                self.y = r.normal(size=(30, 1)).astype(np.float32)

            def __getitem__(self, i):
                return self.x[i], self.y[i]

            def __len__(self):
                return 30

        paddle.seed(0)
        net = nn.Linear(8, 1)
        model = Model(net)
        model.prepare(optimizer=paddle.optimizer.Adam(
            learning_rate=0.01, parameters=net.parameters()),
            loss=nn.MSELoss())
        model.fit(DS(), batch_size=8, epochs=2, verbose=0,
                  accumulate_grad_batches=2)
        # 30 // 8 = 3 full batches -> 1 merged step per epoch
        assert model._optimizer._step_count == 2


class TestFlops:
    @pytest.mark.slow
    def test_lenet_flops_counts_conv_and_linear(self):
        import paddle_tpu as paddle
        m = paddle.vision.LeNet()
        total = paddle.flops(m, [1, 1, 28, 28])
        # conv1: 28*28*6*(1*3*3+1); conv2: 12*12*16*(6*5*5+1); fc stack
        assert total > 3e5 and total < 1e7
        # batch scales activation-dependent terms linearly
        total2 = paddle.flops(m, [2, 1, 28, 28])
        assert total2 == 2 * total
