"""Trace-driven loadgen (paddle_tpu/loadgen/, ISSUE 11): seeded trace
determinism (same seed => identical arrival sequence AND identical
soak metrics) and the virtual-clock open-loop soak smoke against a
2-replica fleet. The REAL soaks (recipe drill, thousands of
sessions) are slow-tier; the fast tier keeps a seconds-scale smoke.
conftest runs this file with PDT_TELEMETRY=1 and
PDT_CHECK_INVARIANTS=1."""
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.loadgen import (SoakDriver, TraceConfig, VirtualClock,
                                binary_search_qps, generate_trace)
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.serving import ContinuousBatchingEngine
from paddle_tpu.observability.slo import SloMonitor, SloObjective
from paddle_tpu.serving import Lane, QosAdmission, ServingRouter

pytestmark = pytest.mark.telemetry


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=1, max_position_embeddings=64)
    paddle.seed(7)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _cfg(**kw):
    kw.setdefault("seed", 0)
    kw.setdefault("duration_s", 5.0)
    kw.setdefault("base_qps", 4.0)
    kw.setdefault("prompt_len_max", 16)
    kw.setdefault("output_len_max", 8)
    kw.setdefault("vocab_size", 64)
    return TraceConfig(**kw)


class TestTraceDeterminism:
    def test_same_seed_identical_sequence(self):
        cfg = _cfg(duration_s=20.0, diurnal_amplitude=0.3,
                   burst_start_prob=0.05, num_system_prompts=2,
                   system_prompt_len=8, shared_prefix_prob=0.5)
        assert generate_trace(cfg) == generate_trace(cfg)

    def test_different_seed_differs(self):
        a = generate_trace(_cfg(seed=0, duration_s=10.0))
        b = generate_trace(_cfg(seed=1, duration_s=10.0))
        assert a != b

    def test_times_ordered_and_bounded(self):
        evts = generate_trace(_cfg(duration_s=10.0))
        ts = [e.t for e in evts]
        assert ts == sorted(ts)
        assert all(0 <= t < 10.0 for t in ts)
        assert [e.request_id for e in evts] == \
            [f"soak-{i}" for i in range(len(evts))]

    def test_lengths_clamped_heavy_tail(self):
        cfg = _cfg(duration_s=60.0, base_qps=10.0,
                   prompt_len_median=6.0, prompt_len_sigma=1.2,
                   prompt_len_min=2, prompt_len_max=20,
                   output_len_min=1, output_len_max=10)
        evts = generate_trace(cfg)
        assert all(2 <= len(e.prompt) <= 20 for e in evts)
        assert all(1 <= e.max_new_tokens <= 10 for e in evts)
        # heavy tail: the clamp ceiling is actually reached
        assert any(len(e.prompt) == 20 for e in evts)

    def test_tenant_and_lane_mix(self):
        cfg = _cfg(duration_s=60.0, base_qps=10.0,
                   tenants=(("a", 5.0), ("b", 1.0)),
                   interactive_fraction=0.5)
        evts = generate_trace(cfg)
        tenants = {e.tenant for e in evts}
        lanes = {e.lane for e in evts}
        assert tenants == {"a", "b"}
        assert lanes == {Lane.INTERACTIVE, Lane.BATCH}
        # weighted mix: 'a' dominates 5:1
        n_a = sum(1 for e in evts if e.tenant == "a")
        assert n_a > len(evts) // 2

    def test_burst_episodes_add_arrivals(self):
        calm = generate_trace(_cfg(duration_s=120.0))
        bursty = generate_trace(_cfg(duration_s=120.0,
                                     burst_start_prob=0.1,
                                     burst_mean_s=3.0,
                                     burst_multiplier=5.0))
        assert len(bursty) > len(calm)

    def test_shared_prefixes_repeat(self):
        cfg = _cfg(duration_s=30.0, base_qps=8.0,
                   num_system_prompts=2, system_prompt_len=8,
                   shared_prefix_prob=1.0)
        evts = generate_trace(cfg)
        heads = {e.prompt[:8] for e in evts}
        assert len(heads) <= 2

    def test_config_validation(self):
        with pytest.raises(ValueError):
            _cfg(base_qps=0.0)
        with pytest.raises(ValueError):
            _cfg(diurnal_amplitude=1.5)
        with pytest.raises(ValueError):
            _cfg(tenants=())


def _soak(model, *, qps=4.0, duration=3.5, seed=0, with_qos=True,
          slots=2, step_dt=0.05, budgets=None):
    clock = VirtualClock()
    mon = SloMonitor(
        [SloObjective("interactive_ttft_p95", "ttft.interactive",
                      "latency", 0.4, quantile=0.95, window_s=5.0)],
        clock=clock)
    qos = None
    if with_qos:
        qos = QosAdmission(slo_monitor=mon,
                           shed_objective="interactive_ttft_p95",
                           shed_burn=0.5, budgets=budgets or {},
                           tenant_window_s=5.0, clock=clock)
    router = ServingRouter(
        lambda i: ContinuousBatchingEngine(
            model, max_batch_size=slots, max_seq_len=64, page_size=4,
            clock=clock),
        num_replicas=2, policy="least_outstanding", page_size=4,
        max_replica_outstanding=3 * slots, clock=clock,
        sleep=clock.advance, slo_monitor=mon, admission=qos)
    trace = generate_trace(_cfg(seed=seed, duration_s=duration,
                                base_qps=qps))
    driver = SoakDriver(router, trace, clock=clock, step_dt=step_dt,
                        max_wall_s=300)
    return driver.run(), router


class TestSoakSmoke:
    def test_virtual_clock_soak_accounts_every_session(self, model):
        result, router = _soak(model)
        summary = result.summary()
        assert summary["sessions"] == len(result.sessions) > 0
        assert sum(summary["outcomes"].values()) == \
            summary["sessions"]
        # the offered-load rate is measured over the ARRIVAL window,
        # not the drain-inclusive duration
        assert 0 < result.trace_span_s <= 3.5
        assert result.duration_s >= result.trace_span_s
        assert summary["arrival_qps"] == pytest.approx(
            summary["sessions"] / result.trace_span_s, abs=1e-4)
        # drained: nothing pending, nothing open
        assert router.fleet_info()["pending"] == 0
        refusals = {"shed", "overloaded", "invalid"}
        served = [s for s in result.sessions
                  if s.outcome not in refusals]
        assert all(s.tokens > 0 for s in served
                   if s.outcome == "finished")
        # TTFT is a virtual-time quantity: multiples of step_dt
        for s in served:
            if s.ttft_s is not None:
                assert s.ttft_s >= 0.05 - 1e-9

    def test_same_seed_identical_soak_metrics(self, model):
        a, _ = _soak(model)
        telemetry.reset()
        b, _ = _soak(model)
        assert a.summary() == b.summary()

    def test_admission_counters_reconcile_exactly(self, model):
        result, router = _soak(model, qps=8.0, duration=3.0)
        snap = telemetry.snapshot()["counters"]

        def total(name, **labels):
            want = [f'{k}="{v}"' for k, v in labels.items()]
            return int(sum(v for key, v in snap.get(name, {}).items()
                           if all(w in key for w in want)))

        # admissions count at COMMIT: the identity is exact, with
        # fleet_full backpressure booked separately
        admits = total("pdt_admission_decisions_total",
                       decision="admit")
        terminals = total("pdt_router_requests_terminal_total")
        assert admits == terminals
        sheds = sum(1 for s in result.sessions
                    if s.outcome == "shed")
        assert total("pdt_admission_shed_total") == sheds == \
            total("pdt_router_rejections_total", reason="qos_shed")
        arrivals = total("pdt_loadgen_arrivals_total")
        assert arrivals == len(result.sessions) == \
            total("pdt_loadgen_outcomes_total")

    def test_overload_sheds_confine_to_batch_or_over_budget(self,
                                                           model):
        result, _ = _soak(model, qps=14.0, duration=3.0, slots=1,
                          budgets={"free": 50})
        sheds = [s for s in result.sessions if s.outcome == "shed"]
        assert sheds, "overload smoke produced no sheds"
        for s in sheds:
            assert s.lane == Lane.BATCH \
                or s.shed_reason == "tenant_budget"
            assert s.retry_after and s.retry_after > 0

    def test_binary_search_qps_brackets(self):
        # pure search logic: sustainable iff qps <= 7.3
        probe = lambda q: q <= 7.3             # noqa: E731
        got = binary_search_qps(probe, 1.0, 4.0, iters=8)
        assert got == pytest.approx(7.3, abs=0.1)
        assert probe(got)
        # everything sustainable: returns the grown ceiling
        assert binary_search_qps(lambda q: True, 1.0, 2.0,
                                 iters=3, max_grow_steps=2) == 8.0


@pytest.mark.slow
class TestRealSoak:
    """The real soaks: thousands of sessions / the graded recipe
    drill. Slow tier (ISSUE 11 wall-time audit: the fast tier keeps
    only the seconds-scale smoke above)."""

    def test_fleet_soak_recipe_drill_passes(self):
        from recipes.fleet_soak import main
        assert main(["--duration", "30", "--overload", "2"]) == 0

    def test_large_soak_replays_identically(self, model):
        a, _ = _soak(model, qps=20.0, duration=30.0, seed=3)
        telemetry.reset()
        b, _ = _soak(model, qps=20.0, duration=30.0, seed=3)
        assert a.summary() == b.summary()
        assert a.summary()["sessions"] > 400
