"""TPU-native int8 quantization (VERDICT r3 missing #4 / next #4).

Covers: MXU-native W8A8 (int8 lax.dot_general + fp rescale), weight-only
int8/int4 with group-wise scales and nibble packing, the reference
paddle.nn.quant API surface, and the Llama serving conversion with
logits-parity and greedy-decode checks.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn import quant as Q

rng = np.random.default_rng(17)


class TestInt8Dot:
    def test_w8a8_matches_fp_within_quant_error(self):
        x = rng.normal(size=(16, 64)).astype(np.float32)
        w = rng.normal(size=(64, 32)).astype(np.float32)
        xq, xs = Q.quantize_activation_dynamic_values(jnp.asarray(x))
        wq, ws = Q.weight_quantize_values(jnp.asarray(w))
        out = Q.int8_dot_values(xq, wq, xs, ws)
        ref = x @ w
        err = np.abs(np.asarray(out) - ref).max() / np.abs(ref).max()
        assert err < 0.02, err

    def test_int32_accumulation_no_overflow(self):
        # K=4096 of worst-case ±127 products: |acc| <= 4096*127*127
        # = 6.6e7 << 2^31 — the int32 accumulator must not saturate
        xq = jnp.full((2, 4096), 127, jnp.int8)
        wq = jnp.full((4096, 3), 127, jnp.int8)
        out = Q.int8_dot_values(xq, wq, jnp.float32(127.0),
                                jnp.full((3,), 127.0, jnp.float32))
        # scales of 127 make the dequant factor 1: out == raw int32 acc
        np.testing.assert_allclose(np.asarray(out),
                                   4096.0 * 127 * 127, rtol=1e-6)

    def test_llm_int8_linear_api(self):
        x = rng.normal(size=(4, 32)).astype(np.float32)
        w = rng.normal(size=(32, 8)).astype(np.float32)
        b = rng.normal(size=(8,)).astype(np.float32)
        wq, ws = Q.weight_quantize(paddle.to_tensor(w))
        out = Q.llm_int8_linear(paddle.to_tensor(x), wq,
                                bias=paddle.to_tensor(b),
                                weight_scale=ws)
        ref = x @ w + b
        assert np.abs(np.asarray(out._value) - ref).max() \
            < 0.05 * np.abs(ref).max() + 0.05


class TestWeightOnly:
    def test_int8_roundtrip_close(self):
        w = rng.normal(size=(64, 48)).astype(np.float32)
        qw, sc = Q.weight_quantize_values(jnp.asarray(w))
        assert qw.dtype == jnp.int8 and qw.shape == (64, 48)
        back = Q.weight_dequantize_values(qw, sc)
        assert np.abs(np.asarray(back) - w).max() < np.abs(w).max() / 100

    def test_int4_pack_unpack_exact(self):
        w = rng.normal(size=(32, 16)).astype(np.float32)
        qw, sc = Q.weight_quantize_values(jnp.asarray(w),
                                          "weight_only_int4")
        assert qw.shape == (16, 16)          # two nibbles per byte
        back = Q.weight_dequantize_values(qw, sc, "weight_only_int4")
        # unpacked values must be EXACTLY representable int4 * scale / 7
        q_ref = np.clip(np.round(np.asarray(w) / np.maximum(
            np.abs(w).max(0), 1e-9) * 7), -8, 7)
        np.testing.assert_allclose(
            np.asarray(back),
            q_ref * np.maximum(np.abs(w).max(0), 1e-9) / 7, rtol=1e-6)

    def test_groupwise_scales_beat_per_channel_on_outliers(self):
        w = rng.normal(size=(128, 8)).astype(np.float32)
        w[0, :] *= 50                        # one outlier row
        qw_pc, sc_pc = Q.weight_quantize_values(jnp.asarray(w))
        qw_gw, sc_gw = Q.weight_quantize_values(jnp.asarray(w),
                                                group_size=32)
        assert sc_gw.shape == (4, 8)
        # judge error OUTSIDE the outlier's group (rows 32+): group-wise
        # scales contain the damage to group 0, per-channel ones don't
        e_pc = np.abs(np.asarray(Q.weight_dequantize_values(
            qw_pc, sc_pc)) - w)[32:].max()
        e_gw = np.abs(np.asarray(Q.weight_dequantize_values(
            qw_gw, sc_gw, group_size=32)) - w)[32:].max()
        assert e_gw < e_pc / 4, (e_gw, e_pc)

    def test_weight_only_linear_api(self):
        x = rng.normal(size=(4, 64)).astype(np.float32)
        w = rng.normal(size=(64, 16)).astype(np.float32)
        for dtype in ("int8", "int4"):
            qw, sc = Q.weight_quantize(paddle.to_tensor(w),
                                       f"weight_only_{dtype}")
            out = Q.weight_only_linear(paddle.to_tensor(x), qw,
                                       weight_scale=sc,
                                       weight_dtype=dtype)
            ref = x @ w
            tol = 0.03 if dtype == "int8" else 0.2
            assert np.abs(np.asarray(out._value) - ref).max() \
                < tol * np.abs(ref).max(), dtype


class TestQuantedLinearW8A8:
    def test_w8a8_convert_close_to_fp(self):
        from paddle_tpu import nn
        from paddle_tpu.quantization import QuantedLinear
        paddle.seed(3)
        lin = nn.Linear(32, 16)
        x = paddle.to_tensor(rng.normal(size=(8, 32)).astype(np.float32))
        ref = np.asarray(lin(x)._value)
        ql = QuantedLinear(lin).convert(mode="w8a8")
        got = np.asarray(ql(x)._value)
        assert np.abs(got - ref).max() < 0.05 * np.abs(ref).max() + 0.02

    def test_w8a8_uses_int8_dot(self):
        """The compiled HLO must contain a convert to s8 and an s32-
        accumulating dot — proof the MXU int8 path is exercised."""
        from paddle_tpu import nn
        from paddle_tpu.quantization import QuantedLinear
        paddle.seed(3)
        ql = QuantedLinear(nn.Linear(128, 128)).convert(mode="w8a8")
        iw, ws, b = ql._int_weight, ql._w_scale, ql.linear.bias

        def f(xv):
            from paddle_tpu.nn.quant import (
                int8_dot_values, quantize_activation_dynamic_values)
            xq, xs = quantize_activation_dynamic_values(xv)
            return int8_dot_values(xq, iw._value, xs, ws._value)

        txt = jax.jit(f).lower(
            jnp.zeros((8, 128), jnp.float32)).as_text()
        # StableHLO spells the types xi8 / xi32: the dot must consume
        # int8 operands and accumulate int32
        assert "xi8>" in txt and "xi32>" in txt and "dot" in txt, \
            txt[:500]


class TestLlamaWeightOnlyServing:
    @pytest.mark.slow
    def test_quantized_llama_logits_parity_and_decode(self):
        from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                             synthetic_lm_batch)
        from paddle_tpu.quantization import quantize_model_weight_only
        paddle.seed(5)
        cfg = LlamaConfig.tiny()
        m = LlamaForCausalLM(cfg)
        m.eval()
        ids, _ = synthetic_lm_batch(1, 32, cfg.vocab_size, seed=9)
        ref = np.asarray(m(ids)._value)

        quantize_model_weight_only(m, "int8")
        # every Linear replaced: q/k/v/o + mlp x3 per layer + lm_head
        from paddle_tpu.quantization import WeightOnlyLinear
        n_wol = sum(isinstance(s, WeightOnlyLinear)
                    for s in m.sublayers())
        assert n_wol == cfg.num_hidden_layers * 7 + 1, n_wol

        got = np.asarray(m(ids)._value)
        # the quantization must actually ENGAGE (round-4 review: a stale
        # __dict__ sublayer made this comparison vacuously exact)
        assert not np.array_equal(got, ref), \
            "quantized forward identical to fp — swap did not take"
        # logits parity: int8 weight-only keeps the distribution
        cos = (ref.ravel() @ got.ravel()) / (
            np.linalg.norm(ref) * np.linalg.norm(got))
        assert cos > 0.999, cos
        top1 = (ref.argmax(-1) == got.argmax(-1)).mean()
        assert top1 > 0.9, top1

        # cached greedy decode end-to-end on the quantized model
        out = m.generate(paddle.to_tensor(
            np.array([[5, 42, 7]], np.int32)), max_new_tokens=8,
            decode_strategy="greedy_search")
        toks = out[0] if isinstance(out, (tuple, list)) else out
        t = np.asarray(toks._value)
        assert t.shape[-1] == 8 and (t >= 0).all()

    def test_unquantizable_layers_reported_not_crashed(self):
        import warnings
        from paddle_tpu import nn
        from paddle_tpu.quantization import quantize_model_weight_only
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(100, 64),   # 100 % 64 != 0
                              nn.Linear(64, 64))
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            quantize_model_weight_only(model, "int8", group_size=64)
        assert any("left in fp" in str(r.message) for r in rec)
        assert any(sh == (100, 64)
                   for _, sh, _ in model._weight_only_skipped)
        from paddle_tpu.quantization import WeightOnlyLinear
        kinds = [type(s).__name__ for s in model.sublayers()]
        assert "WeightOnlyLinear" in kinds and "Linear" in kinds

    def test_weight_bytes_shrink(self):
        from paddle_tpu import nn
        from paddle_tpu.quantization import (WeightOnlyLinear,
                                             quantize_model_weight_only)
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(256, 256), nn.ReLU(),
                              nn.Linear(256, 256))
        fp_bytes = sum(p._value.nbytes for p in model.parameters())
        quantize_model_weight_only(model, "int4", group_size=64)
        q_bytes = sum(b._value.nbytes for s in model.sublayers()
                      if isinstance(s, WeightOnlyLinear)
                      for b in (s.quant_weight, s.weight_scale)) \
            + sum(p._value.nbytes for p in model.parameters())
        assert q_bytes < fp_bytes / 3, (q_bytes, fp_bytes)
