"""Optimizer + LR scheduler + AMP tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.optimizer import (SGD, Adam, AdamW, Momentum, Adagrad,
                                  RMSProp, Lamb, lr as lr_mod)

rng = np.random.default_rng(0)


def _quadratic_converges(opt_cls, lr=0.1, steps=60, tol=0.1, **kw):
    """All optimizers must minimize ||x - c||^2."""
    target = np.float32([1.0, -2.0, 3.0])
    x = paddle.framework.Parameter(np.zeros(3, np.float32))
    opt = opt_cls(learning_rate=lr, parameters=[x], **kw)
    for _ in range(steps):
        loss = ((x - paddle.to_tensor(target)) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(((x - paddle.to_tensor(target)) ** 2).sum()) < tol, \
        f"{opt_cls.__name__} failed to converge: x={x.numpy()}"


class TestOptimizers:
    def test_sgd(self):
        _quadratic_converges(SGD, lr=0.1)

    def test_momentum(self):
        _quadratic_converges(Momentum, lr=0.05)

    def test_adam(self):
        _quadratic_converges(Adam, lr=0.3)

    def test_adamw(self):
        _quadratic_converges(AdamW, lr=0.3, weight_decay=0.0)

    def test_adagrad(self):
        _quadratic_converges(Adagrad, lr=1.0, steps=120, tol=0.5)

    def test_rmsprop(self):
        _quadratic_converges(RMSProp, lr=0.3, tol=0.3)

    def test_lamb(self):
        # decay off: LAMB's fixed point with weight decay is biased away
        # from the quadratic minimum, which is what this oracle checks
        _quadratic_converges(Lamb, lr=0.15, steps=150, tol=0.3,
                             lamb_weight_decay=0.0)

    def test_adamw_decoupled_decay(self):
        # with huge decay and zero grad-producing loss, params shrink
        p = paddle.framework.Parameter(np.ones(2, np.float32))
        opt = AdamW(learning_rate=0.1, parameters=[p], weight_decay=0.5)
        for _ in range(5):
            (p * 0.0).sum().backward()
            opt.step()
            opt.clear_grad()
        assert np.all(p.numpy() < 1.0)

    def test_master_weights_bf16(self):
        p = paddle.framework.Parameter(
            np.ones(4, np.float32)).astype("bfloat16")
        p = paddle.framework.Parameter(p.numpy())
        p._value = p._value.astype("bfloat16")
        opt = AdamW(learning_rate=1e-3, parameters=[p], multi_precision=True)
        (p.astype("float32") ** 2).sum().backward()
        opt.step()
        assert id(p) in opt._master_weights
        assert opt._master_weights[id(p)].dtype == np.float32

    def test_grad_clip_global_norm(self):
        p = paddle.framework.Parameter(np.zeros(2, np.float32))
        clip = nn.ClipGradByGlobalNorm(1.0)
        opt = SGD(learning_rate=1.0, parameters=[p], grad_clip=clip)
        (p * 100.0).sum().backward()  # grad = [100, 100], norm ~141
        opt.step()
        # clipped to norm 1 -> update magnitude ~0.707 each
        np.testing.assert_allclose(-p.numpy(),
                                   [100 / np.sqrt(2 * 100 ** 2)] * 2,
                                   rtol=1e-4)

    def test_state_dict_roundtrip(self):
        p = paddle.framework.Parameter(np.ones(2, np.float32))
        opt = Adam(learning_rate=0.1, parameters=[p])
        (p ** 2).sum().backward()
        opt.step()
        sd = opt.state_dict()
        p2 = paddle.framework.Parameter(np.ones(2, np.float32))
        opt2 = Adam(learning_rate=0.1, parameters=[p2])
        (p2 ** 2).sum().backward()
        opt2.step()  # create accumulators
        opt2.set_state_dict(sd)
        assert opt2._step_count == opt._step_count


class TestLRSchedulers:
    def test_step_decay(self):
        s = lr_mod.StepDecay(0.1, step_size=2, gamma=0.5)
        lrs = []
        for _ in range(5):
            lrs.append(s())
            s.step()
        assert lrs[0] == pytest.approx(0.1)
        assert lrs[2] == pytest.approx(0.05)
        assert lrs[4] == pytest.approx(0.025)

    def test_cosine(self):
        s = lr_mod.CosineAnnealingDecay(1.0, T_max=10)
        assert s() == pytest.approx(1.0)
        for _ in range(10):
            s.step()
        assert s() == pytest.approx(0.0, abs=1e-6)

    def test_linear_warmup(self):
        s = lr_mod.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0,
                                end_lr=0.1)
        assert s() == pytest.approx(0.0)
        for _ in range(10):
            s.step()
        assert s() == pytest.approx(0.1)

    def test_scheduler_with_optimizer(self):
        p = paddle.framework.Parameter(np.ones(1, np.float32))
        sched = lr_mod.StepDecay(0.1, step_size=1, gamma=0.1)
        opt = SGD(learning_rate=sched, parameters=[p])
        assert opt.get_lr() == pytest.approx(0.1)
        sched.step()
        assert opt.get_lr() == pytest.approx(0.01)

    def test_noam_and_poly(self):
        s = lr_mod.NoamDecay(d_model=512, warmup_steps=10, learning_rate=1.0)
        v1 = s()
        s.step()
        s.step()
        assert s() > v1  # rising during warmup
        p = lr_mod.PolynomialDecay(0.1, decay_steps=10, end_lr=0.0)
        for _ in range(10):
            p.step()
        assert p() == pytest.approx(0.0, abs=1e-6)


class TestAMP:
    def test_autocast_o1_matmul_bf16(self):
        a = paddle.randn([4, 4])
        b = paddle.randn([4, 4])
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            out = paddle.matmul(a, b)
        assert out.dtype == paddle.bfloat16
        out2 = paddle.matmul(a, b)
        assert out2.dtype == np.float32

    def test_autocast_black_list_kept_fp32(self):
        a = paddle.randn([4, 4])
        with paddle.amp.auto_cast(level="O1"):
            out = F.softmax(a)
        assert out.dtype == np.float32

    def test_grad_scaler_noop_path(self):
        p = paddle.framework.Parameter(np.ones(2, np.float32))
        opt = SGD(learning_rate=0.1, parameters=[p])
        scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
        loss = (p ** 2).sum()
        scaler.scale(loss).backward()
        scaler.step(opt)
        # grad was 2*p*scale=4, unscaled to 2, update = 0.1*2
        np.testing.assert_allclose(p.numpy(), 0.8, rtol=1e-5)

    def test_decorate_o2(self):
        m = nn.Linear(4, 4)
        opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
        m, opt = paddle.amp.decorate(m, opt, level="O2", dtype="bfloat16")
        assert m.weight.dtype == paddle.bfloat16
        assert opt._multi_precision


class TestTrainStep:
    def test_compiled_matches_eager(self):
        paddle.seed(0)
        x = paddle.randn([16, 8])
        y = paddle.randint(0, 3, [16])

        def build():
            paddle.seed(42)
            m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 3))
            o = AdamW(learning_rate=1e-2, parameters=m.parameters())
            return m, o

        # eager
        m1, o1 = build()
        losses_eager = []
        for _ in range(4):
            loss = F.cross_entropy(m1(x), y)
            loss.backward()
            o1.step()
            o1.clear_grad()
            losses_eager.append(float(loss))
        # compiled
        m2, o2 = build()
        step = paddle.jit.TrainStep(
            m2, o2, loss_fn=lambda m, a, b: F.cross_entropy(m(a), b))
        losses_jit = [float(step(x, y)) for _ in range(4)]
        np.testing.assert_allclose(losses_eager, losses_jit, rtol=2e-4,
                                   atol=1e-5)

    def test_to_static_function(self):
        @paddle.jit.to_static
        def f(a, b):
            return a * b + a.exp()

        x = paddle.randn([3, 3])
        y = paddle.randn([3, 3])
        want = x.numpy() * y.numpy() + np.exp(x.numpy())
        np.testing.assert_allclose(f(x, y).numpy(), want, rtol=1e-5)

    def test_to_static_layer(self):
        m = nn.Linear(4, 2)
        x = paddle.randn([3, 4])
        want = m(x).numpy()
        paddle.jit.to_static(m)
        got = m(x).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)


class TestRound3Optimizers:
    """NAdam/RAdam/Rprop/ASGD/LBFGS (round-3 additions). Oracles: torch
    (CPU) where the update rule matches, else convergence checks."""

    def _quad_problem(self, opt_cls, **kw):
        paddle.seed(0)
        w = paddle.to_tensor(np.array([5.0, -3.0], np.float32),
                             stop_gradient=False)
        w = paddle_tpu.Parameter(w._value)
        opt = opt_cls(parameters=[w], **kw)
        for _ in range(60):
            loss = ((w - paddle.to_tensor(
                np.array([1.0, 2.0], np.float32))) ** 2).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        return np.asarray(w._value), float(loss)

    def test_nadam_converges(self):
        from paddle_tpu.optimizer import NAdam
        w, loss = self._quad_problem(NAdam, learning_rate=0.3)
        np.testing.assert_allclose(w, [1.0, 2.0], atol=0.3)

    def test_nadam_matches_torch(self):
        torch = pytest.importorskip("torch")
        from paddle_tpu.optimizer import NAdam
        x0 = np.array([1.5, -2.0, 0.5], np.float32)
        w = paddle_tpu.Parameter(paddle.to_tensor(x0)._value)
        opt = NAdam(learning_rate=0.05, parameters=[w])
        tw = torch.tensor(x0, requires_grad=True)
        topt = torch.optim.NAdam([tw], lr=0.05)
        for _ in range(10):
            loss = (w ** 2).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
            tl = (tw ** 2).sum()
            topt.zero_grad()
            tl.backward()
            topt.step()
        np.testing.assert_allclose(np.asarray(w._value),
                                   tw.detach().numpy(), rtol=2e-4,
                                   atol=2e-5)

    def test_radam_matches_torch(self):
        torch = pytest.importorskip("torch")
        from paddle_tpu.optimizer import RAdam
        x0 = np.array([1.5, -2.0, 0.5], np.float32)
        w = paddle_tpu.Parameter(paddle.to_tensor(x0)._value)
        opt = RAdam(learning_rate=0.05, parameters=[w])
        tw = torch.tensor(x0, requires_grad=True)
        topt = torch.optim.RAdam([tw], lr=0.05)
        for _ in range(12):
            loss = (w ** 2).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
            tl = (tw ** 2).sum()
            topt.zero_grad()
            tl.backward()
            topt.step()
        np.testing.assert_allclose(np.asarray(w._value),
                                   tw.detach().numpy(), rtol=2e-4,
                                   atol=2e-5)

    def test_rprop_converges(self):
        from paddle_tpu.optimizer import Rprop
        w, loss = self._quad_problem(Rprop, learning_rate=0.01)
        np.testing.assert_allclose(w, [1.0, 2.0], atol=0.1)

    def test_asgd_converges(self):
        from paddle_tpu.optimizer import ASGD
        w, loss = self._quad_problem(ASGD, learning_rate=0.1)
        np.testing.assert_allclose(w, [1.0, 2.0], atol=0.3)

    def test_lbfgs_rosenbrock(self):
        from paddle_tpu.optimizer import LBFGS
        paddle.seed(0)
        w = paddle_tpu.Parameter(paddle.to_tensor(
            np.array([-1.0, 1.5], np.float32))._value)
        opt = LBFGS(learning_rate=1.0, max_iter=30,
                    line_search_fn="strong_wolfe", parameters=[w])

        def closure():
            x, y = w[0], w[1]
            loss = (1 - x) ** 2 + 100 * (y - x ** 2) ** 2
            loss.backward()
            return loss

        for _ in range(10):
            loss = opt.step(closure)
        np.testing.assert_allclose(np.asarray(w._value), [1.0, 1.0],
                                   atol=1e-2)

    def test_lbfgs_no_line_search_matches_torch(self):
        # ADVICE r3: line_search_fn=None must take a single t=lr step per
        # inner iteration (reference default), not run backtracking
        import torch
        from paddle_tpu.optimizer import LBFGS
        x0 = np.array([-0.7, 1.3], np.float32)

        w = paddle_tpu.Parameter(paddle.to_tensor(x0)._value)
        opt = LBFGS(learning_rate=0.05, max_iter=4, parameters=[w])

        def closure():
            loss = ((w - paddle.to_tensor(
                np.array([1.0, 2.0], np.float32))) ** 2).sum() \
                + 0.5 * (w[0] * w[1])
            loss.backward()
            return loss

        tw = torch.tensor(x0.copy(), requires_grad=True)
        topt = torch.optim.LBFGS([tw], lr=0.05, max_iter=4)

        def tclosure():
            topt.zero_grad()
            tl = ((tw - torch.tensor([1.0, 2.0])) ** 2).sum() \
                + 0.5 * (tw[0] * tw[1])
            tl.backward()
            return tl

        for _ in range(3):
            opt.step(closure)
            topt.step(tclosure)
        np.testing.assert_allclose(np.asarray(w._value),
                                   tw.detach().numpy(), rtol=1e-4,
                                   atol=1e-5)
