"""paddle.geometric + paddle.vision.ops parity tests.
≙ reference «test/legacy_test/test_segment_ops.py», «test_nms_op.py»,
«test_roi_align_op.py», «test_deformable_conv_op.py» [U]; oracles are
NumPy references (and torchvision-free torch ops are avoided — torch is
CPU-only here and only used where it ships the exact op)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import geometric as G
from paddle_tpu.vision import ops as V

rng = np.random.default_rng(3)


class TestSegmentOps:
    def _data(self):
        x = rng.normal(size=(10, 4)).astype(np.float32)
        ids = np.sort(rng.integers(0, 5, 10)).astype(np.int32)
        return x, ids

    def test_segment_sum_mean(self):
        x, ids = self._data()
        out = G.segment_sum(paddle.to_tensor(x), paddle.to_tensor(ids))
        ref = np.zeros((ids.max() + 1, 4), np.float32)
        np.add.at(ref, ids, x)
        np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-6)

        outm = G.segment_mean(paddle.to_tensor(x), paddle.to_tensor(ids))
        cnt = np.bincount(ids, minlength=ids.max() + 1)[:, None]
        np.testing.assert_allclose(np.asarray(outm._value),
                                   ref / np.maximum(cnt, 1), rtol=1e-6)

    def test_segment_min_max_empty_segment(self):
        x = np.array([[1.0], [3.0], [-2.0]], np.float32)
        ids = np.array([0, 0, 2], np.int32)  # segment 1 empty
        mx = np.asarray(G.segment_max(paddle.to_tensor(x),
                                      paddle.to_tensor(ids))._value)
        mn = np.asarray(G.segment_min(paddle.to_tensor(x),
                                      paddle.to_tensor(ids))._value)
        np.testing.assert_allclose(mx.ravel(), [3.0, 0.0, -2.0])
        np.testing.assert_allclose(mn.ravel(), [1.0, 0.0, -2.0])

    def test_send_u_recv(self):
        x = rng.normal(size=(6, 3)).astype(np.float32)
        src = np.array([0, 1, 2, 3], np.int32)
        dst = np.array([1, 2, 1, 5], np.int32)
        out = G.send_u_recv(paddle.to_tensor(x), paddle.to_tensor(src),
                            paddle.to_tensor(dst), reduce_op="sum")
        ref = np.zeros_like(x)
        np.add.at(ref, dst, x[src])
        np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-6)

    def test_send_u_recv_grad(self):
        x = paddle.to_tensor(rng.normal(size=(4, 2)).astype(np.float32),
                             stop_gradient=False)
        src = paddle.to_tensor(np.array([0, 1, 1], np.int32))
        dst = paddle.to_tensor(np.array([2, 3, 2], np.int32))
        out = G.send_u_recv(x, src, dst, reduce_op="sum")
        out.sum().backward()
        # node 1 feeds two edges -> grad 2; nodes 2,3 feed none -> grad 0
        np.testing.assert_allclose(np.asarray(x.grad)[:, 0], [1, 2, 0, 0])

    def test_send_ue_recv_and_uv(self):
        x = rng.normal(size=(5, 2)).astype(np.float32)
        y = rng.normal(size=(3, 2)).astype(np.float32)
        src = np.array([0, 2, 4], np.int32)
        dst = np.array([1, 1, 0], np.int32)
        out = G.send_ue_recv(paddle.to_tensor(x), paddle.to_tensor(y),
                             paddle.to_tensor(src), paddle.to_tensor(dst),
                             message_op="mul", reduce_op="max")
        msg = x[src] * y
        ref = np.zeros((5, 2), np.float32)
        for i, d in enumerate(dst):
            ref[d] = np.maximum(ref[d], msg[i]) if i and d in dst[:i] \
                else msg[i]
        # simpler oracle
        ref = np.zeros((5, 2), np.float32)
        filled = np.zeros(5, bool)
        for i, d in enumerate(dst):
            ref[d] = msg[i] if not filled[d] else np.maximum(ref[d], msg[i])
            filled[d] = True
        np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-6)

        uv = G.send_uv(paddle.to_tensor(x), paddle.to_tensor(x),
                       paddle.to_tensor(src), paddle.to_tensor(dst),
                       message_op="add")
        np.testing.assert_allclose(np.asarray(uv._value), x[src] + x[dst],
                                   rtol=1e-6)


class TestNMS:
    def test_nms_matches_torch(self):
        torch = pytest.importorskip("torch")
        try:
            import torchvision  # noqa: F401
            have_tv = True
        except ImportError:
            have_tv = False
        boxes = rng.uniform(0, 90, (30, 2)).astype(np.float32)
        boxes = np.concatenate(
            [boxes, boxes + rng.uniform(5, 30, (30, 2)).astype(np.float32)],
            axis=1)
        scores = rng.uniform(size=30).astype(np.float32)
        idx = np.asarray(V.nms(paddle.to_tensor(boxes), 0.5,
                               paddle.to_tensor(scores))._value)
        if have_tv:
            from torchvision.ops import nms as tv_nms
            ref = tv_nms(torch.tensor(boxes), torch.tensor(scores),
                         0.5).numpy()
            np.testing.assert_array_equal(idx, ref)
        else:
            # greedy numpy reference
            order = np.argsort(-scores)
            keep = []
            sup = np.zeros(30, bool)
            for i in order:
                if sup[i]:
                    continue
                keep.append(i)
                iou = np.asarray(V.box_iou(
                    paddle.to_tensor(boxes[i:i + 1]),
                    paddle.to_tensor(boxes))._value)[0]
                sup |= iou > 0.5
                sup[i] = True
            np.testing.assert_array_equal(idx, np.array(keep))

    def test_box_iou_area(self):
        a = np.array([[0, 0, 10, 10]], np.float32)
        b = np.array([[5, 5, 15, 15], [20, 20, 30, 30]], np.float32)
        iou = np.asarray(V.box_iou(paddle.to_tensor(a),
                                   paddle.to_tensor(b))._value)
        np.testing.assert_allclose(iou[0, 0], 25.0 / 175.0, rtol=1e-6)
        assert iou[0, 1] == 0.0
        ar = np.asarray(V.box_area(paddle.to_tensor(b))._value)
        np.testing.assert_allclose(ar, [100.0, 100.0])


class TestRoIAlign:
    def test_matches_torchvision(self):
        torch = pytest.importorskip("torch")
        tv = pytest.importorskip("torchvision")
        x = rng.normal(size=(2, 3, 16, 16)).astype(np.float32)
        boxes = np.array([[1.0, 1.0, 9.0, 9.0], [2.0, 3.0, 14.0, 12.0],
                          [0.0, 0.0, 15.0, 15.0]], np.float32)
        boxes_num = np.array([2, 1], np.int32)
        out = V.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                          paddle.to_tensor(boxes_num), output_size=4,
                          spatial_scale=0.5, sampling_ratio=2,
                          aligned=True)
        tb = torch.tensor(
            np.concatenate([[[0.0], [0.0], [1.0]], boxes], axis=1))
        ref = tv.ops.roi_align(torch.tensor(x), tb, output_size=4,
                               spatial_scale=0.5, sampling_ratio=2,
                               aligned=True).numpy()
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=1e-4, atol=1e-4)

    def test_roi_pool_shape_and_values(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        boxes = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)
        out = V.roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                         paddle.to_tensor(np.array([1], np.int32)),
                         output_size=2)
        ref = np.array([[[[5.0, 7.0], [13.0, 15.0]]]], np.float32)
        np.testing.assert_allclose(np.asarray(out._value), ref)


class TestDeformConv:
    def test_zero_offset_equals_conv(self):
        tch = pytest.importorskip("torch")
        x = rng.normal(size=(1, 4, 8, 8)).astype(np.float32)
        w = rng.normal(size=(6, 4, 3, 3)).astype(np.float32) * 0.2
        offset = np.zeros((1, 2 * 9, 8, 8), np.float32)
        out = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(offset),
                              paddle.to_tensor(w), padding=1)
        ref = tch.nn.functional.conv2d(
            tch.tensor(x), tch.tensor(w), padding=1).numpy()
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=1e-4, atol=1e-4)

    def test_matches_torchvision_with_offsets(self):
        tch = pytest.importorskip("torch")
        tv = pytest.importorskip("torchvision")
        x = rng.normal(size=(2, 4, 6, 6)).astype(np.float32)
        w = rng.normal(size=(5, 4, 3, 3)).astype(np.float32) * 0.2
        off = (rng.normal(size=(2, 18, 6, 6)) * 0.7).astype(np.float32)
        m = rng.uniform(0.2, 1.0, (2, 9, 6, 6)).astype(np.float32)
        b = rng.normal(size=(5,)).astype(np.float32)
        out = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                              paddle.to_tensor(w), paddle.to_tensor(b),
                              padding=1, mask=paddle.to_tensor(m))
        ref = tv.ops.deform_conv2d(
            tch.tensor(x), tch.tensor(off), tch.tensor(w), tch.tensor(b),
            padding=1, mask=tch.tensor(m)).numpy()
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=1e-3, atol=1e-3)

    def test_layer_and_grad(self):
        layer = V.DeformConv2D(3, 4, 3, padding=1)
        x = paddle.to_tensor(rng.normal(size=(1, 3, 5, 5))
                             .astype(np.float32), stop_gradient=False)
        off = paddle.to_tensor(
            np.zeros((1, 18, 5, 5), np.float32), stop_gradient=False)
        out = layer(x, off)
        assert tuple(out.shape) == (1, 4, 5, 5)
        out.sum().backward()
        assert x.grad is not None and np.isfinite(np.asarray(x.grad)).all()
        assert off.grad is not None


@pytest.mark.slow
class TestVisionZoo:
    """Forward-shape + grad smoke for the round-3 model-zoo additions.
    ≙ reference «test/legacy_test/test_vision_models.py» [U]."""

    @pytest.mark.parametrize("build,shape,nclass", [
        (lambda: paddle.vision.LeNet(num_classes=10), (2, 1, 28, 28), 10),
        (lambda: paddle.vision.alexnet(num_classes=7), (2, 3, 63, 63), 7),
        (lambda: paddle.vision.vgg11(num_classes=5), (1, 3, 32, 32), 5),
        (lambda: paddle.vision.mobilenet_v1(
            scale=0.25, num_classes=6), (2, 3, 32, 32), 6),
        (lambda: paddle.vision.mobilenet_v2(
            scale=0.35, num_classes=6), (2, 3, 32, 32), 6),
        (lambda: paddle.vision.squeezenet1_1(num_classes=4),
         (2, 3, 64, 64), 4),
        (lambda: paddle.vision.densenet121(num_classes=3),
         (1, 3, 32, 32), 3),
    ])
    def test_forward_shapes(self, build, shape, nclass):
        paddle.seed(0)
        m = build()
        m.eval()
        x = paddle.to_tensor(rng.normal(size=shape).astype(np.float32))
        out = m(x)
        assert tuple(out.shape) == (shape[0], nclass)
        assert np.isfinite(np.asarray(out._value)).all()

    def test_train_step_decreases_loss(self):
        paddle.seed(0)
        m = paddle.vision.LeNet(num_classes=10)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=m.parameters())
        x = paddle.to_tensor(rng.normal(size=(8, 1, 28, 28))
                             .astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 10, (8,)).astype(np.int64))
        import paddle_tpu.nn.functional as F
        losses = []
        for _ in range(5):
            loss = F.cross_entropy(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestGraphSampling:
    def test_sample_neighbors_and_reindex(self):
        # CSC graph: 4 nodes; col j's neighbors = row[colptr[j]:colptr[j+1]]
        row = np.array([1, 2, 3, 0, 2, 0], np.int64)
        colptr = np.array([0, 3, 5, 6, 6], np.int64)
        nodes = np.array([0, 1], np.int64)
        n, cnt = G.sample_neighbors(paddle.to_tensor(row),
                                    paddle.to_tensor(colptr),
                                    paddle.to_tensor(nodes))
        np.testing.assert_array_equal(np.asarray(cnt._value), [3, 2])
        np.testing.assert_array_equal(np.asarray(n._value),
                                      [1, 2, 3, 0, 2])
        # bounded sampling
        n2, cnt2 = G.sample_neighbors(paddle.to_tensor(row),
                                      paddle.to_tensor(colptr),
                                      paddle.to_tensor(nodes),
                                      sample_size=2)
        np.testing.assert_array_equal(np.asarray(cnt2._value), [2, 2])

        re, dst, uniq = G.reindex_graph(paddle.to_tensor(nodes), n,
                                        count=cnt)
        u = np.asarray(uniq._value)
        assert u[0] == 0 and u[1] == 1          # seeds first
        # reindexed neighbors map back to the originals
        np.testing.assert_array_equal(u[np.asarray(re._value)],
                                      np.asarray(n._value))
        np.testing.assert_array_equal(np.asarray(dst._value),
                                      [0, 0, 0, 1, 1])


@pytest.mark.slow
class TestVisionZooRound3b:
    @pytest.mark.parametrize("build,shape,nclass", [
        (lambda: paddle.vision.shufflenet_v2_x0_5(num_classes=5),
         (1, 3, 64, 64), 5),
        (lambda: paddle.vision.googlenet(num_classes=4), (1, 3, 64, 64), 4),
    ])
    def test_forward_shapes(self, build, shape, nclass):
        paddle.seed(0)
        m = build()
        m.eval()
        x = paddle.to_tensor(rng.normal(size=shape).astype(np.float32))
        out = m(x)
        assert tuple(out.shape) == (shape[0], nclass)
        assert np.isfinite(np.asarray(out._value)).all()
