"""Disaggregated prefill/decode serving (ISSUE 8): role-aware routing,
the KV page transfer plane (`serving/transfer.py` over the engine's
`export_pages` / `import_pages` / `evict_request` hooks), and the
fleet-wide prefix store with host-RAM spill (`serving/prefix_store.py`).

The acceptance property threaded through this file: greedy outputs are
BIT-IDENTICAL between a colocated fleet (== a single engine, pinned by
tests/test_router.py) and a role-split fleet, including through
mid-transfer faults and a SIGKILL of either transfer endpoint. conftest
runs this file with PDT_TELEMETRY=1 and PDT_CHECK_INVARIANTS=1, so
every engine step of every migration re-proves page accounting."""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.serving import (ContinuousBatchingEngine,
                                       EngineOverloaded, RequestStatus)
from paddle_tpu.serving import (FleetPrefixStore, PrefixAffinityPolicy,
                                ReplicaRole, ReplicaState, ServingRouter,
                                chain_hashes, parse_roles)
from paddle_tpu.serving import transfer
from paddle_tpu.utils.faults import FaultError, FaultInjector

pytestmark = pytest.mark.chaos


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=1, max_position_embeddings=64)
    paddle.seed(7)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _engine(model, clock=None, **kw):
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", 4)
    kw.setdefault("enable_prefix_caching", True)
    return ContinuousBatchingEngine(model, clock=clock, **kw)


def _fleet(model, roles, policy="prefix_affinity", clock=None,
           engine_kw=None, **kw):
    clock = clock if clock is not None else FakeClock()
    kw.setdefault("page_size", 4)
    kw.setdefault("sleep", clock.advance)
    ekw = dict(engine_kw or {})
    router = ServingRouter(
        lambda i: _engine(model, clock=clock, **ekw),
        roles=roles, policy=policy, clock=clock, **kw)
    return router, clock


def _reference(model, jobs, **kw):
    """Single-engine greedy outputs — the colocated oracle (a colocated
    fleet equals one engine, pinned by tests/test_router.py)."""
    eng = _engine(model, **kw)
    rids = [eng.add_request(p, n) for p, n in jobs]
    res = eng.run()
    return [res[r] for r in rids]


# two full 4-token pages of shared system prompt + distinct tails: the
# workload disaggregation + the prefix store exist for
SYS = [11, 7, 23, 42, 9, 30, 5, 17]
JOBS = [(SYS + [3, 1, 4], 6), (SYS + [55, 2], 5), (SYS + [8, 8, 61], 6),
        (SYS + [19, 44], 5), (SYS + [31, 6, 12], 6), (SYS + [27], 5)]


@pytest.fixture(scope="module")
def oracle(model):
    """Greedy outputs for every JOB from ONE engine run — per-request
    outputs are independent of co-batching (the engine's bit-identity
    guarantee), so each test slices what it needs."""
    return _reference(model, JOBS)


class TestRoleSpec:
    def test_parse_roles_forms(self):
        assert parse_roles("prefill:2,decode:1") \
            == ["prefill", "prefill", "decode"]
        assert parse_roles({"decode": 1, "prefill": 1}) \
            == ["prefill", "decode"]
        assert parse_roles(["decode", "colocated"]) \
            == ["decode", "colocated"]
        assert parse_roles(None) is None
        with pytest.raises(ValueError, match="unknown replica role"):
            parse_roles("turbo:2")
        with pytest.raises(ValueError, match="count"):
            parse_roles("prefill:0,decode:2")

    def test_decode_only_fleet_rejected(self, model):
        with pytest.raises(ValueError, match="prefill-capable"):
            _fleet(model, roles="decode:2")

    def test_fresh_submits_avoid_decode_replicas(self, model):
        router, _ = _fleet(model, roles="prefill:1,decode:2")
        assert [h.role for h in router.replicas] \
            == [ReplicaRole.PREFILL, ReplicaRole.DECODE,
                ReplicaRole.DECODE]
        ids = [router.submit(p, n) for p, n in JOBS[:3]]
        assert all(router.requests[i].replica == 0 for i in ids)
        snap = telemetry.snapshot()["counters"]
        dispatched_to = {lbl for lbl in
                         snap.get("pdt_router_dispatch_total", {})}
        assert not any('replica="1"' in s or 'replica="2"' in s
                       for s in dispatched_to)


class TestTransferPlane:
    def test_migrate_mid_stream_bit_identical(self, model, oracle):
        ref = [oracle[0]]
        src, dst = _engine(model), _engine(model)
        rid = src.add_request(*JOBS[0])
        src.step()
        src.step()                              # mid-decode: 3 tokens
        req, payload = transfer.migrate_request(src, dst, rid)
        src.check_invariants()
        dst.check_invariants()
        assert src.get_request(rid) is None     # evicted, not terminal
        assert src.lifecycle_info()["running"] == 0
        assert req.output == ref[0][:len(req.output)]
        done = {}
        while src._queue or dst._queue \
                or any(r is not None for r in dst._slot_req):
            for r in dst.step():
                done[r.request_id] = r
            src.step()                          # source keeps serving
        assert done[req.request_id].status == RequestStatus.FINISHED
        assert done[req.request_id].output == ref[0]
        assert telemetry.value("pdt_transfer_migrations_total") == 1
        assert telemetry.value("pdt_transfer_bytes_total") > 0
        assert payload["request_id"] == req.request_id

    def test_export_validations(self, model):
        src = _engine(model)
        with pytest.raises(ValueError, match="no resident request"):
            src.export_pages(99)
        # a queued (never admitted) request has no pages to export
        src2 = _engine(model, max_batch_size=1)
        src2.add_request(*JOBS[0])
        waiting = src2.add_request(*JOBS[1])
        src2.step()
        with pytest.raises(ValueError, match="no resident request"):
            src2.export_pages(waiting)
        dense = ContinuousBatchingEngine(model, max_batch_size=1,
                                         max_seq_len=64,
                                         kv_layout="dense")
        r = dense.add_request(*JOBS[0])
        dense.step()
        with pytest.raises(ValueError, match="paged"):
            dense.export_pages(r)

    def test_import_validations_and_capacity(self, model):
        src = _engine(model)
        rid = src.add_request(*JOBS[0])
        src.step()
        payload = transfer.serialize_request(src, rid)
        geom = _engine(model, page_size=8)
        with pytest.raises(ValueError, match="page_size"):
            geom.import_pages(payload)
        full = _engine(model, max_batch_size=1)
        full.add_request(*JOBS[1])
        full.step()
        with pytest.raises(EngineOverloaded, match="no free slot"):
            full.import_pages(payload)
        # source was never touched: the request is still live there
        assert src.get_request(rid) is not None
        src.check_invariants()

    def test_import_attaches_target_warm_prefix(self, model, oracle):
        # warm the target's trie with the shared system prompt first
        dst = _engine(model)
        warm_rid = dst.add_request(SYS + [50, 12], 4)
        dst.run()
        assert dst._prefix_nodes                 # SYS pages registered
        src = _engine(model)
        rid = src.add_request(*JOBS[0])
        src.step()
        req, _ = transfer.migrate_request(src, dst, rid)
        dst.check_invariants()
        slot = dst._slot_req.index(req)
        # the two full SYS pages attached read-only instead of copying
        assert len(dst._slot_shared_pages[slot]) == 2
        res = dst.run()
        assert res[req.rid] == oracle[0]
        assert warm_rid is not None

    def test_evict_keeps_source_chain_warm(self, model):
        src, dst = _engine(model), _engine(model)
        rid = src.add_request(*JOBS[0])
        src.step()
        transfer.migrate_request(src, dst, rid)
        assert src._prefix_nodes                # chain registered at evict
        rid2 = src.add_request(*JOBS[1])        # same SYS prefix
        src.run()
        assert src.prefix_hits == 1 and src.prefix_tokens_reused == 8
        assert rid2 is not None

    def test_transfer_fault_sites_fire_and_isolate(self, model, oracle):
        ref = [oracle[0]]
        src, dst = _engine(model), _engine(model)
        rid = src.add_request(*JOBS[0])
        src.step()
        with FaultInjector() as fi:
            fi.arm("transfer.serialize", nth=1)
            with pytest.raises(FaultError):
                transfer.migrate_request(src, dst, rid)
        with FaultInjector() as fi:
            fi.arm("transfer.install", nth=1)
            with pytest.raises(FaultError):
                transfer.migrate_request(src, dst, rid)
        src.check_invariants()
        dst.check_invariants()
        assert dst.lifecycle_info()["running"] == 0     # backed out
        assert telemetry.value("pdt_transfer_failures_total",
                               stage="serialize") == 1
        assert telemetry.value("pdt_transfer_failures_total",
                               stage="install") == 1
        # both engines stayed consistent: the migration then succeeds
        req, _ = transfer.migrate_request(src, dst, rid)
        res = dst.run()
        assert res[req.rid] == ref[0]


class TestPrefixStore:
    def test_chain_hash_shared_with_policy(self):
        pol = PrefixAffinityPolicy(page_size=4)
        prompt = SYS + [3, 1, 4]
        assert pol._chain_hashes(prompt) == chain_hashes(prompt, 4)

    def test_record_lookup_forget(self):
        store = FleetPrefixStore(page_size=4)
        store.record(0, SYS + [1])
        hashes = chain_hashes(SYS + [9, 9], 4)
        assert store.longest_warm(0, hashes) == 2
        assert store.longest_warm(1, hashes) == 0
        store.forget_replica(0)
        assert store.longest_warm(0, hashes) == 0
        assert store.stats()["chains"] == 2

    def test_spill_fetch_import_prefix_roundtrip(self, model, oracle):
        src = _engine(model)
        rid = src.add_request(*JOBS[0])
        src.step()
        payload = transfer.serialize_request(src, rid)
        store = FleetPrefixStore(page_size=4)
        assert store.spill_payload(payload) == 2        # both SYS pages
        entry = store.fetch(SYS + [77, 78])
        assert entry is not None
        tokens, kv_rows = entry
        assert [len(t) for t in tokens] == [4, 4]
        fresh = _engine(model)
        assert fresh.import_prefix(tokens, kv_rows) == 2
        fresh.check_invariants()
        rid2 = fresh.add_request(*JOBS[1])
        res = fresh.run()
        assert fresh.prefix_hits == 1                   # spill revived
        assert res[rid2] == oracle[1]
        assert store.fetch([1, 2, 3, 4, 5]) is None

    def test_import_prefix_respects_free_pool(self, model):
        """Restoring a spilled chain draws only on genuinely FREE
        pages — it must not evict resident chains, and (the review
        repro) a mid-build eviction must never corrupt the trie: a
        3-page chain into a 2-usable-page pool installs exactly what
        fits and the engine keeps serving."""
        src = _engine(model)
        long_prompt = SYS + [3, 1, 4, 1, 5]     # 3 full chain pages
        rid = src.add_request(long_prompt, 4)
        src.step()
        payload = transfer.serialize_request(src, rid)
        store = FleetPrefixStore(page_size=4)
        assert store.spill_payload(payload) == 3
        tokens, kv_rows = store.fetch(long_prompt)
        tiny = _engine(model, max_batch_size=1, num_pages=3)
        assert tiny.import_prefix(tokens, kv_rows) == 2
        tiny.check_invariants()
        # the partially-restored chain is ordinary cache content:
        # admission can evict it under pressure and serve normally
        r2 = tiny.add_request([1, 2, 3, 4], 4)
        res = tiny.run()
        assert len(res[r2]) == 4
        tiny.check_invariants()

    def test_spill_budget_evicts_lru_content(self, model):
        src = _engine(model)
        rid = src.add_request(*JOBS[0])
        src.step()
        payload = transfer.serialize_request(src, rid)
        page_bytes = sum(k[:, 0].nbytes + v[:, 0].nbytes
                         for k, v in payload["kv"])
        store = FleetPrefixStore(page_size=4,
                                 spill_budget_bytes=page_bytes)
        store.spill_payload(payload)            # 2 pages > 1-page budget
        assert store.spilled_bytes <= page_bytes
        assert store.evictions >= 1
        stats = store.stats()
        assert stats["spilled_chains"] < 2
        assert stats["chains"] == 2             # warmth records survive


class TestDisaggFleet:
    def test_disagg_fleet_matches_colocated_engine(self, model, oracle):
        """The acceptance drill: a prefill:2,decode:2 fleet on the
        shared-prefix workload produces greedy outputs bit-identical to
        a colocated run, every request migrates exactly once, decode
        replicas take no fresh submits, and fleet-vs-engine terminal
        counters reconcile exactly under roles."""
        ref = oracle
        # an earlier test's engines ticked the global pdt_serving_* counters;
        # baseline them so reconciliation measures the fleet run alone
        eng_base = telemetry.value("pdt_serving_requests_terminal_total",
                                   status="finished")
        router, _ = _fleet(model, roles="prefill:2,decode:2")
        ids = [router.submit(p, n) for p, n in JOBS]
        out = router.run()
        assert [out[i] for i in ids] == ref
        assert router.num_migrations == len(JOBS)
        assert telemetry.value("pdt_transfer_migrations_total") \
            == len(JOBS)
        # terminal counters reconcile exactly under roles
        assert telemetry.value("pdt_router_requests_terminal_total",
                               status="finished") == len(JOBS)
        assert telemetry.value("pdt_serving_requests_terminal_total",
                               status="finished") - eng_base \
            == len(JOBS)
        # decode replicas never saw a fresh dispatch, only migrations
        snap = telemetry.snapshot()["counters"]
        for lbl in snap.get("pdt_router_dispatch_total", {}):
            assert 'replica="2"' not in lbl and 'replica="3"' not in lbl
        # decode dispatch balanced outstanding slots across both
        info = router.fleet_info()
        roles = info["roles"]
        assert roles["prefill"]["replicas"] == 2
        assert roles["decode"]["replicas"] == 2
        assert roles["prefill"]["migrations"] == len(JOBS)
        assert roles["decode"]["migrations"] == len(JOBS)
        assert min(h.migrations_in for h in router.replicas[2:]) >= 1
        assert info["migrations"] == len(JOBS)
        assert info["prefix_store"]["chains"] >= 2
        rendered = telemetry.render_fleet_status(info)
        assert "prefill" in rendered and "roles" in rendered

    def test_no_decode_capacity_serves_colocated_style(self, model,
                                                       oracle):
        """Liveness: with every decode replica permanently dead, prefill
        replicas keep decoding their own work — migration is an
        optimization, never a dependency."""
        ref = oracle[:2]
        router, _ = _fleet(model, roles="prefill:1,decode:1",
                           max_restarts=0)
        router.kill_replica(1)
        ids = [router.submit(p, n) for p, n in JOBS[:2]]
        out = router.run()
        assert [out[i] for i in ids] == ref
        assert router.num_migrations == 0

    def test_kill_prefill_endpoint_mid_migration_zero_loss(self, model,
                                                           oracle):
        """SIGKILL of the SOURCE endpoint mid-transfer: the serialize
        fault marks the transfer dead, the replica is killed, and the
        failover machinery re-prefills on a survivor with streamed
        tokens folded in — greedy outputs bit-identical."""
        ref = oracle[:3]
        router, clock = _fleet(model, roles="prefill:1,decode:1",
                               restart_backoff_base=2.0,
                               restart_backoff_max=2.0)
        ids = [router.submit(p, n) for p, n in JOBS[:3]]
        with FaultInjector() as fi:
            fi.arm("transfer.serialize", always=True)
            router.step()               # prefills land; migrations die
            assert fi.trips("transfer.serialize") >= 1
        assert telemetry.value("pdt_transfer_failures_total",
                               stage="serialize") >= 1
        router.kill_replica(0)          # SIGKILL the source endpoint
        clock.advance(2.5)
        out = router.run()
        assert [out[i] for i in ids] == ref
        assert router.num_failovers >= 1

    def test_kill_decode_endpoint_after_install_zero_loss(self, model,
                                                          oracle):
        """SIGKILL of the TARGET endpoint just after pages installed:
        the migrated request dies with the decode replica and fails
        over (re-prefill, tokens folded) — still bit-identical."""
        ref = oracle[:2]
        router, clock = _fleet(model, roles="prefill:1,decode:1",
                               restart_backoff_base=2.0,
                               restart_backoff_max=2.0)
        ids = [router.submit(p, n) for p, n in JOBS[:2]]
        router.step()                   # prefill + migrate to replica 1
        migrated = [i for i in ids
                    if router.requests[i].replica == 1]
        assert migrated                 # at least one landed on decode
        router.kill_replica(1)
        clock.advance(2.5)
        out = router.run()
        assert [out[i] for i in ids] == ref
        assert router.num_failovers >= 1

    def test_migration_respects_replica_outstanding_bound(self, model,
                                                          oracle):
        """The bounded per-replica queue holds for MIGRATED work too
        (review repro): one tick finishing more prefills than the
        decode tier has headroom must not pile them past
        max_replica_outstanding — the surplus keeps decoding on its
        prefill replica until slots free."""
        router, _ = _fleet(model, roles="prefill:4,decode:1",
                           max_replica_outstanding=1)
        ids = [router.submit(p, n) for p, n in JOBS[:4]]
        router.step()               # up to 4 prefills finish this tick
        assert router.replicas[4].outstanding() <= 1
        out = router.run()
        assert [out[i] for i in ids] == oracle[:4]

    def test_install_fault_defers_and_retries(self, model, oracle):
        ref = oracle[:1]
        router, _ = _fleet(model, roles="prefill:1,decode:1")
        rid = router.submit(*JOBS[0])
        with FaultInjector() as fi:
            fi.arm("transfer.install", nth=1)
            router.step()               # first migration attempt fails
        assert router.requests[rid].replica == 0    # still on source
        out = router.run()              # next step retries and succeeds
        assert out[rid] == ref[0]
        assert router.num_migrations == 1
        assert telemetry.value("pdt_transfer_failures_total",
                               stage="install") == 1

    def test_spill_revives_prefix_after_replica_death(self, model,
                                                      oracle):
        """The fleet-wide story: a chain warm only on a dead replica is
        re-installed from the host-RAM spill into the next prefill
        replica — the prefix outlives every engine that computed it."""
        router, clock = _fleet(model, roles="prefill:2,decode:1",
                               restart_backoff_base=2.0,
                               restart_backoff_max=2.0)
        a = router.submit(*JOBS[0])
        router.run()                    # migrated: prompt chain spilled
        assert router.prefix_store.stats()["spilled_chains"] == 2
        victim = 0 if telemetry.value(
            "pdt_router_dispatch_total", policy="prefix_affinity",
            replica="0") else 1
        router.kill_replica(victim)     # the only warm replica dies
        b = router.submit(*JOBS[1])     # same SYS prefix, cold fleet
        out = router.run()
        assert out[b] == oracle[1]
        stats = router.prefix_store.stats()
        assert stats["spill_hits"] >= 1
        assert router.fleet_info()["prefix_hits"] >= 1  # engine-level hit
        assert telemetry.value("pdt_prefix_store_hits_total",
                               source="spill") >= 1
        assert a is not None

    def test_obs_cli_status_renders_roles(self, model, tmp_path,
                                          capsys):
        from paddle_tpu.observability.__main__ import main as obs_main
        router, _ = _fleet(model, roles="prefill:1,decode:1")
        path = tmp_path / "fleet.json"
        path.write_text(json.dumps(router.fleet_info()))
        assert obs_main(["status", "--from", str(path)]) == 0
        rendered = capsys.readouterr().out
        assert "roles" in rendered and "prefill" in rendered \
            and "decode" in rendered
        assert "prefix store" in rendered


class TestMigrationTiming:
    """ISSUE 9 (pdt-lint PDT001, the live hit that motivated the
    rule): `migrate_request` timed migrations on
    `time.perf_counter()`, so the `pdt_transfer_seconds` quantiles the
    bench reports could never be driven by the tests' fake clocks.
    The clock is now injectable and the router threads ITS clock
    through every hand-off."""

    def test_fake_clock_drives_transfer_histogram(self, model):
        src, dst = _engine(model), _engine(model)
        rid = src.add_request([5, 4, 3, 2, 6, 7], 6)
        src.step()                      # prefill -> RUNNING w/ output
        ticks = iter([10.0, 11.5])
        transfer.migrate_request(src, dst, rid,
                                 clock=lambda: next(ticks))
        h = telemetry.snapshot()["histograms"]["pdt_transfer_seconds"]
        assert h[""]["count"] == 1
        assert h[""]["sum"] == pytest.approx(1.5)

    def test_router_migrations_run_on_the_router_clock(self, model):
        router, clock = _fleet(model, "prefill:1,decode:1")
        rids = [router.submit(p, n) for p, n in
                [([5, 4, 3, 2, 6, 7], 8), ([9, 1, 2], 6)]]
        out = router.run()
        assert router.num_migrations >= 1
        assert all(len(out[r]) > 0 for r in rids)
        h = telemetry.snapshot()["histograms"]["pdt_transfer_seconds"]
        # the fake clock does not advance inside one step tick, so a
        # migration timed on the ROUTER clock observes exactly 0.0 —
        # any perf_counter leak would observe real (nonzero) wall time
        assert h[""]["count"] == router.num_migrations
        assert h[""]["sum"] == 0.0


class TestPayloadIntegrity:
    """Migration payload integrity (ISSUE 13): `export_pages` attaches
    a sha256 per KV shard fragment (the manifest.py hashing
    discipline) and `import_pages` verifies BEFORE install — a flipped
    byte in flight is a counted `stage="verify"` transfer failure that
    leaves both engines consistent."""

    @staticmethod
    def _flip(payload, which=0):
        """Corrupt one byte of a KV fragment (the exported arrays are
        read-only views of device memory — corrupting a copy is
        exactly what in-flight damage looks like)."""
        pair = list(payload["kv"][0])
        arr = pair[which].copy()
        arr.flat[arr.size // 2] += 1.0
        pair[which] = arr
        payload["kv"][0] = tuple(pair)

    def _running_payload(self, model):
        src = _engine(model)
        rid = src.add_request([5, 4, 3, 2, 6, 7], 6)
        src.step()
        src.step()
        return src, rid, src.export_pages(rid)

    def test_export_attaches_sha256_manifest(self, model):
        src, rid, payload = self._running_payload(model)
        want_layers = len(payload["kv"])
        assert len(payload["kv_sha256"]) == 1          # one shard
        assert len(payload["kv_sha256"][0]) == want_layers
        for k_sha, v_sha in payload["kv_sha256"][0]:
            assert k_sha.startswith("sha256:")
            assert v_sha.startswith("sha256:")
        # the manifest covers the actual bytes: recompute == attached
        from paddle_tpu.models.serving import payload_checksums
        assert payload_checksums(payload) == payload["kv_sha256"]

    def test_corrupt_payload_refused_before_any_mutation(self, model):
        from paddle_tpu.models.serving import PayloadCorruption
        src, rid, payload = self._running_payload(model)
        dst = _engine(model)
        self._flip(payload)
        before = dst.cache_memory_info()["pages_in_use"]
        with pytest.raises(PayloadCorruption):
            transfer.install_request(dst, payload)
        src.check_invariants()
        dst.check_invariants()
        assert dst.cache_memory_info()["pages_in_use"] == before
        assert src.get_request(rid) is not None   # source still owns it
        # a clean payload still installs afterwards: the refusal left
        # the target fully serviceable
        req = transfer.install_request(dst, src.export_pages(rid))
        assert req.request_id == payload["request_id"]

    def test_migrate_books_stage_verify(self, model):
        from paddle_tpu.models.serving import PayloadCorruption
        src, rid, _ = self._running_payload(model)
        dst = _engine(model)
        flip = self._flip

        class CorruptingWire:
            """A source whose exported payloads are damaged in flight."""

            def get_request(self, r):
                return src.get_request(r)

            def export_pages(self, r):
                p = src.export_pages(r)
                flip(p, which=1)
                return p

        with pytest.raises(PayloadCorruption):
            transfer.migrate_request(CorruptingWire(), dst, rid)
        assert telemetry.value("pdt_transfer_failures_total",
                               stage="verify") == 1
        events = [e for e in telemetry.events()
                  if e["name"] == "transfer.failed"]
        assert events and events[-1]["attrs"]["stage"] == "verify"
        src.check_invariants()
        dst.check_invariants()
        assert src.get_request(rid) is not None   # never evicted

    def test_router_falls_back_to_source_on_corrupt_wire(
            self, model, oracle, monkeypatch):
        """A corrupt payload at the router's migration pass: the
        request keeps decoding on its consistent source and the
        outputs stay bit-identical to the colocated oracle."""
        flip = self._flip
        real_serialize = transfer.serialize_request
        corrupted = {"n": 0}

        def bad_serialize(engine, rid):
            p = real_serialize(engine, rid)
            if corrupted["n"] == 0:
                corrupted["n"] += 1
                flip(p)
            return p

        monkeypatch.setattr(transfer, "serialize_request",
                            bad_serialize)
        router, clock = _fleet(model, "prefill:1,decode:1")
        rids = [router.submit(p, n) for p, n in JOBS[:2]]
        out = router.run()
        assert corrupted["n"] == 1
        assert [out[r] for r in rids] == oracle[:2]
        assert router.fleet_info()["pending"] == 0
        assert telemetry.value("pdt_transfer_failures_total",
                               stage="verify") == 1
