"""Telemetry subsystem tests (fast tier, `telemetry` marker):
instrument semantics (counter/gauge/histogram, labels, disabled-mode
true no-op), span tracing (nesting, ring bound, JSONL sink), the
Prometheus text-exposition golden format + parse-back round trip, and
the integration contract from ISSUE 2's acceptance criteria — a
chaos-injected serving run whose terminal-status counters reconcile
EXACTLY with per-request statuses and whose text export parses back to
the same values. conftest enables PDT_TELEMETRY=1 and zeroes the
registry/ring for every test in this file."""
import json
import random
import types
from collections import deque

import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as telemetry
from paddle_tpu.observability import trace as _trace

pytestmark = pytest.mark.telemetry


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


class TestCounter:
    def test_inc_labels_and_value(self):
        c = telemetry.counter("t_reqs_total", "requests", ("kind",))
        c.inc(kind="a")
        c.inc(2.5, kind="a")
        c.inc(kind="b")
        assert c.get(kind="a") == 3.5
        assert telemetry.value("t_reqs_total", kind="b") == 1.0
        assert telemetry.value("t_reqs_total", kind="absent") == 0.0

    def test_negative_inc_rejected(self):
        c = telemetry.counter("t_mono_total")
        with pytest.raises(ValueError, match="< 0"):
            c.inc(-1)

    def test_label_mismatch_rejected(self):
        c = telemetry.counter("t_lab_total", "", ("site",))
        with pytest.raises(ValueError, match="expected labels"):
            c.inc()
        with pytest.raises(ValueError, match="expected labels"):
            c.inc(site="x", extra="y")

    def test_redeclare_idempotent_conflict_raises(self):
        a = telemetry.counter("t_same_total", "h", ("x",))
        assert telemetry.counter("t_same_total", "h", ("x",)) is a
        with pytest.raises(ValueError, match="already registered"):
            telemetry.gauge("t_same_total")
        with pytest.raises(ValueError, match="labels"):
            telemetry.counter("t_same_total", "h", ("y",))


class TestGauge:
    def test_set_inc_dec(self):
        g = telemetry.gauge("t_depth")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.get() == 6.0


class TestHistogram:
    def test_bucket_boundaries_cumulative(self):
        h = telemetry.histogram("t_lat_seconds", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.1, 0.5, 5.0, 50.0):
            h.observe(v)
        snap = telemetry.snapshot()["histograms"]["t_lat_seconds"][""]
        # le-boundaries are INCLUSIVE and counts cumulative
        assert snap["buckets"] == {"0.1": 2, "1": 3, "10": 4, "+Inf": 5}
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(55.65)

    def test_timer_monotonic(self):
        h = telemetry.histogram("t_timer_seconds")
        with h.time():
            pass
        got = h.get()
        assert got["count"] == 1 and got["sum"] >= 0.0

    def test_value_rejects_histogram(self):
        telemetry.histogram("t_hist_seconds").observe(1.0)
        with pytest.raises(TypeError, match="histogram"):
            telemetry.value("t_hist_seconds")


class TestDisabledMode:
    def test_true_noop_when_disabled(self, monkeypatch):
        monkeypatch.setenv("PDT_TELEMETRY", "0")
        assert not telemetry.enabled()
        c = telemetry.counter("t_off_total", "", ("k",))
        g = telemetry.gauge("t_off_gauge")
        h = telemetry.histogram("t_off_seconds")
        c.inc(k="x")
        g.set(3)
        h.observe(1.0)
        with telemetry.span("t.off", a=1):
            telemetry.event("t.off.point")
        snap = telemetry.snapshot()
        assert snap["enabled"] is False
        assert snap["counters"] == snap["gauges"] \
            == snap["histograms"] == {}
        assert telemetry.events() == []
        assert telemetry.to_prometheus() == ""

    def test_enable_overrides_env(self, monkeypatch):
        monkeypatch.setenv("PDT_TELEMETRY", "0")
        telemetry.enable()
        try:
            assert telemetry.enabled()
            telemetry.counter("t_ovr_total").inc()
            assert telemetry.value("t_ovr_total") == 1.0
            telemetry.disable()
            assert not telemetry.enabled()
        finally:
            telemetry.disable(clear_override=True)  # back to env-driven

    def test_reset_keeps_instruments_clears_series(self):
        c = telemetry.counter("t_reset_total")
        c.inc()
        telemetry.reset()
        assert telemetry.counter("t_reset_total") is c
        assert c.get() == 0.0
        assert "t_reset_total" not in telemetry.snapshot()["counters"]


class TestTrace:
    def test_nesting_depth_parent_and_attrs(self):
        with telemetry.span("outer", rid=1):
            with telemetry.span("inner"):
                pass
            telemetry.event("point", site="s")
        evs = telemetry.events()
        names = [e["name"] for e in evs]
        assert names == ["inner", "point", "outer"]  # completion order
        inner, point, outer = evs
        assert inner["depth"] == 1 and inner["parent"] == outer["seq"]
        assert point["depth"] == 1 and point["parent"] == outer["seq"]
        assert outer["depth"] == 0 and outer["parent"] is None
        assert outer["attrs"] == {"rid": 1}
        assert outer["dur_s"] >= inner["dur_s"] >= 0.0
        assert inner["seq"] > outer["seq"]  # outer entered first

    def test_exception_lands_in_attrs(self):
        with pytest.raises(RuntimeError):
            with telemetry.span("boom", rid=2):
                raise RuntimeError("kaput")
        ev = telemetry.events()[-1]
        assert ev["attrs"]["rid"] == 2
        assert "RuntimeError: kaput" in ev["attrs"]["error"]

    def test_ring_buffer_is_bounded(self, monkeypatch):
        monkeypatch.setattr(_trace, "_RING", deque(maxlen=8))
        for i in range(20):
            telemetry.event("e", i=i)
        evs = telemetry.events()
        assert len(evs) == 8
        assert [e["attrs"]["i"] for e in evs] == list(range(12, 20))

    def test_file_sink_writes_jsonl(self, tmp_path):
        sink = tmp_path / "trace.jsonl"
        telemetry.set_trace_file(str(sink))
        try:
            with telemetry.span("sunk", k="v"):
                pass
            telemetry.event("pt")
        finally:
            telemetry.set_trace_file(None)
        lines = [json.loads(ln) for ln in
                 sink.read_text().strip().splitlines()]
        assert [ln["name"] for ln in lines] == ["sunk", "pt"]
        assert lines[0]["attrs"] == {"k": "v"}

    def test_set_trace_file_none_sticks_over_env(self, tmp_path,
                                                 monkeypatch):
        """set_trace_file(None) must close the sink FOR GOOD — the env
        var is not re-consulted on the next emit."""
        sink = tmp_path / "env_trace.jsonl"
        monkeypatch.setenv("PDT_TELEMETRY_TRACE_FILE", str(sink))
        monkeypatch.setattr(_trace, "_SINK_RESOLVED", False)
        monkeypatch.setattr(_trace, "_SINK_PATH", None)
        telemetry.event("before")
        telemetry.set_trace_file(None)
        telemetry.event("after")
        names = [json.loads(ln)["name"]
                 for ln in sink.read_text().strip().splitlines()]
        assert names == ["before"]


class TestPrometheusExport:
    def test_golden_text_format(self):
        reg = telemetry.Registry()
        c = reg.counter("g_req_total", "Requests served.", ("code",))
        c.inc(3, code="200")
        c.inc(code="500")
        reg.gauge("g_depth", "Queue depth.").set(2)
        h = reg.histogram("g_lat_seconds", "Latency.",
                          buckets=(0.5, 2.5))
        h.observe(0.25)
        h.observe(1.0)
        h.observe(9.0)
        assert telemetry.to_prometheus(reg) == """\
# HELP g_req_total Requests served.
# TYPE g_req_total counter
g_req_total{code="200"} 3
g_req_total{code="500"} 1
# HELP g_depth Queue depth.
# TYPE g_depth gauge
g_depth 2
# HELP g_lat_seconds Latency.
# TYPE g_lat_seconds histogram
g_lat_seconds_bucket{le="0.5"} 1
g_lat_seconds_bucket{le="2.5"} 2
g_lat_seconds_bucket{le="+Inf"} 3
g_lat_seconds_sum 10.25
g_lat_seconds_count 3
"""

    def test_parse_roundtrip_matches_snapshot(self):
        telemetry.counter("r_a_total", "", ("x", "y")).inc(
            2, x="1", y="two words")
        telemetry.gauge("r_g").set(0.125)
        telemetry.histogram("r_h_seconds", "", ("op",),
                            buckets=(0.01, 0.1)).observe(0.05, op="save")
        snap = telemetry.snapshot()
        parsed = telemetry.parse_prometheus(telemetry.to_prometheus())
        assert parsed == {k: snap[k]
                          for k in ("counters", "gauges", "histograms")}

    def test_label_values_escaped_and_roundtrip(self):
        """Quotes/backslashes/newlines in label values (e.g. a hostile
        --job_id) must not corrupt the exposition or the round trip."""
        c = telemetry.counter("r_esc_total", "", ("job",))
        c.inc(job='a"b')
        c.inc(2, job="back\\slash")
        c.inc(3, job="new\nline")
        txt = telemetry.to_prometheus()
        assert r'job="a\"b"' in txt
        assert r'job="back\\slash"' in txt
        assert r'job="new\nline"' in txt and "new\nline" not in txt
        snap = telemetry.snapshot()
        parsed = telemetry.parse_prometheus(txt)
        assert parsed["counters"]["r_esc_total"] \
            == snap["counters"]["r_esc_total"]
        assert c.get(job='a"b') == 1.0    # raw value still the key


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=1, max_position_embeddings=64)
    paddle.seed(7)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _drain(eng):
    reqs = {}
    while eng._queue or any(r is not None for r in eng._slot_req):
        for r in eng.step():
            reqs[r.rid] = r
    return reqs


class TestEngineIntegration:
    """ISSUE 2 acceptance: under fault injection, telemetry counters
    reconcile exactly with request terminal statuses, and the Prometheus
    export round-trips; with telemetry disabled the engine records
    nothing and still serves."""

    def _chaos_run(self, model, clock=None):
        from paddle_tpu.models.serving import (ContinuousBatchingEngine,
                                               PoolExhausted)
        from paddle_tpu.utils.faults import FaultInjector
        eng = ContinuousBatchingEngine(
            model, max_batch_size=2, max_seq_len=64, page_size=4,
            max_preemptions=0, clock=clock)
        # one request per fate: the injected decode-time exhaustion
        # preempts the youngest (starved terminal at max_preemptions=0),
        # the 3rd prefill (the waiting request's admission into the
        # freed slot) faults -> failed, the first finishes; with a fake
        # clock a 4th expires -> timeout
        eng.add_request([5, 4, 3, 2, 6, 7], 8)
        eng.add_request([9, 1, 2], 6)
        eng.add_request([1, 2, 3], 4)
        with FaultInjector() as fi:
            # prompts of 6+3 tokens at page_size 4 = alloc visits 1-3;
            # visit 4 is the first decode-time growth
            fi.arm("serving.alloc_page", nth=4, exc=PoolExhausted)
            fi.arm("serving.prefill", nth=3)
            reqs = _drain(eng)
        if clock is not None:
            eng.add_request([7, 7, 7], 30, deadline=5.0)
            eng.step()
            clock.advance(6.0)
            reqs.update(_drain(eng))
        return eng, reqs

    def test_terminal_counters_reconcile_and_roundtrip(self, model):
        clk = FakeClock()
        eng, reqs = self._chaos_run(model, clock=clk)
        statuses = [r.status for r in reqs.values()]
        snap = telemetry.snapshot()
        term = snap["counters"]["pdt_serving_requests_terminal_total"]
        # every terminal status the run produced is counted EXACTLY
        for status in ("finished", "timeout", "failed", "preempted"):
            want = statuses.count(status)
            got = term.get(f'status="{status}"', 0)
            assert got == want, (status, got, want, statuses)
        assert sum(term.values()) == len(reqs)
        assert {"finished", "failed", "preempted", "timeout"} \
            <= set(statuses)          # the run exercised all four fates
        # engine's own counters agree with telemetry
        li = eng.lifecycle_info()
        assert telemetry.value("pdt_serving_preemptions_total") \
            == li["preemptions"]
        assert telemetry.value("pdt_serving_requests_terminal_total",
                               status="timeout") == li["timeouts"]
        assert telemetry.value("pdt_serving_requests_terminal_total",
                               status="failed") == li["failures"]
        # fault fires carry the site label
        faults = snap["counters"]["pdt_faults_fired_total"]
        assert faults['site="serving.alloc_page"'] == 1
        assert faults['site="serving.prefill"'] == 1
        # TTFT observed once per request that produced a first token
        first_tok = sum(1 for r in reqs.values() if r.output)
        assert snap["histograms"]["pdt_serving_ttft_seconds"][""][
            "count"] == first_tok
        # Prometheus text export parses back to the same values
        parsed = telemetry.parse_prometheus(telemetry.to_prometheus())
        assert parsed == {k: snap[k]
                          for k in ("counters", "gauges", "histograms")}

    def test_spans_cover_prefill_and_decode(self, model):
        self._chaos_run(model)
        names = [e["name"] for e in telemetry.events()]
        for expected in ("serving.prefill", "serving.decode_step",
                         "serving.terminal", "serving.preempt",
                         "fault.fire"):
            assert expected in names, (expected, set(names))

    def test_disabled_engine_records_nothing(self, model, monkeypatch):
        monkeypatch.setenv("PDT_TELEMETRY", "0")
        eng, reqs = self._chaos_run(model)
        assert all(r.done for r in reqs.values())
        snap = telemetry.snapshot()
        assert snap["counters"] == snap["gauges"] \
            == snap["histograms"] == {}
        assert telemetry.events() == []


class TestInfraIntegration:
    def test_launch_restart_counter_and_backoff(self, tmp_path):
        from paddle_tpu.distributed.launch import launch
        script = tmp_path / "fail.py"
        script.write_text("import sys; sys.exit(3)\n")
        args = types.SimpleNamespace(
            master=None, nnodes=1, rank=0, job_id="tm", log_dir=None,
            elastic_level=1, max_restart=1, restart_backoff=2.0,
            restart_backoff_max=5.0, script=str(script), script_args=[])
        rc = launch(args, sleep=lambda _: None, rng=random.Random(0))
        assert rc == 3
        assert telemetry.value("pdt_launch_restarts_total", job="tm") == 1
        bo = telemetry.histogram(
            "pdt_launch_restart_backoff_seconds").get()
        assert bo["count"] == 1 and 1.0 <= bo["sum"] <= 5.0
        assert any(e["name"] == "launch.restart"
                   for e in telemetry.events())

    def test_heartbeat_staleness_and_membership_events(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import \
            HeartbeatMembership
        import os
        clk = {"t": 1000.0}
        watch = HeartbeatMembership(str(tmp_path), timeout=5.0,
                                    clock=lambda: clk["t"])

        def beat(rank, age=0.0):
            HeartbeatMembership(str(tmp_path), rank=rank).heartbeat()
            path = os.path.join(str(tmp_path), f"worker_{rank}.hb")
            os.utime(path, (clk["t"] - age, clk["t"] - age))

        beat(0)
        beat(1, age=2.0)
        watch.poll()
        assert telemetry.value("pdt_elastic_heartbeat_staleness_seconds",
                               rank="0") == pytest.approx(0.0)
        assert telemetry.value("pdt_elastic_heartbeat_staleness_seconds",
                               rank="1") == pytest.approx(2.0)
        beat(0, age=10.0)                    # silent past the timeout
        d = watch.poll()
        assert d["event"] == "scale_down"
        assert telemetry.value("pdt_elastic_membership_events_total",
                               event="scale_down") == 1
        # a departed worker (beat file gone, as stop() leaves it) must
        # not keep exporting a frozen staleness value
        os.remove(os.path.join(str(tmp_path), "worker_1.hb"))
        watch.poll()
        series = telemetry.snapshot()["gauges"].get(
            "pdt_elastic_heartbeat_staleness_seconds", {})
        assert 'rank="1"' not in series and 'rank="0"' in series

    def test_checkpoint_save_load_bytes_and_spans(self, tmp_path):
        from paddle_tpu import nn
        from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                       save_state_dict)
        paddle.seed(0)
        net = nn.Linear(4, 4)
        nbytes = sum(p._value.nbytes for p in net.parameters())
        save_state_dict(net.state_dict(), str(tmp_path / "ck"))
        load_state_dict(net.state_dict(), str(tmp_path / "ck"))
        assert telemetry.value("pdt_checkpoint_ops_total", op="save") == 1
        assert telemetry.value("pdt_checkpoint_ops_total", op="load") == 1
        assert telemetry.value("pdt_checkpoint_bytes_total",
                               op="save") == nbytes
        assert telemetry.value("pdt_checkpoint_bytes_total",
                               op="load") == nbytes
        names = [e["name"] for e in telemetry.events()]
        assert "checkpoint.save" in names and "checkpoint.load" in names

    def test_async_checkpoint_counts_on_completion(self, tmp_path):
        """An async save is only DISPATCHED by save_state_dict — the op
        must not count as completed until wait_until_finished()."""
        from paddle_tpu import nn
        from paddle_tpu.distributed.checkpoint import save_state_dict
        paddle.seed(0)
        net = nn.Linear(4, 4)
        nbytes = sum(p._value.nbytes for p in net.parameters())
        ckptr = save_state_dict(net.state_dict(), str(tmp_path / "ck"),
                                async_save=True)
        assert telemetry.value("pdt_checkpoint_ops_total",
                               op="save") == 0
        ckptr.wait_until_finished()
        ckptr.wait_until_finished()          # idempotent: counts once
        assert telemetry.value("pdt_checkpoint_ops_total",
                               op="save") == 1
        assert telemetry.value("pdt_checkpoint_bytes_total",
                               op="save") == nbytes
