"""Offline Mosaic lowering tier (VERDICT r4 #8).

Runs the Pallas→Mosaic TPU lowering WITHOUT a chip: `jax.export` with
platforms=["tpu"] executes the full Mosaic pass (BlockSpec/layout/shape
validation — the class of error that broke BENCH_r02 and, verified live
in round 5, the rope trig-table and varlen segment-id BlockSpecs) at
trace time on the CPU CI mesh. Execution still needs silicon — this tier
catches *compile-time* rejections only; tests/test_tpu_compile.py remains
the execute gate.

PDT_FORCE_MOSAIC=1 flips every kernel's `on_tpu()` gate so the
non-interpret Pallas path is traced while the process runs on CPU.

Shapes mirror tests/test_tpu_compile.py (bench.py's Llama config).
"""
import os

import jax
import jax.export  # noqa: F401  (registers jax.export for _lower —
#                   standalone runs must not depend on another test
#                   file having imported it first)
import jax.numpy as jnp
import numpy as np
import pytest

BENCH_B, BENCH_S, BENCH_H, BENCH_HK, BENCH_D = 8, 2048, 16, 8, 64
BENCH_HIDDEN = 1024
BENCH_ROWS = BENCH_B * BENCH_S


@pytest.fixture(autouse=True)
def _force_mosaic(monkeypatch):
    monkeypatch.setenv("PDT_FORCE_MOSAIC", "1")


def _lower(fn, *args):
    """Trace + Mosaic-lower for the TPU target; any BlockSpec/layout
    rejection raises here. Does NOT execute."""
    return jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


class TestNormLowering:
    def test_rms_norm_fwd_bwd(self):
        from paddle_tpu.ops.norm_kernels import rms_norm_values

        x = jnp.zeros((BENCH_ROWS, BENCH_HIDDEN), jnp.bfloat16)
        w = jnp.ones((BENCH_HIDDEN,), jnp.bfloat16)
        _lower(rms_norm_values, x, w)

        def loss(x, w):
            return rms_norm_values(x, w).astype(jnp.float32).sum()

        _lower(jax.grad(loss, argnums=(0, 1)), x, w)

    def test_layer_norm_fwd_bwd(self):
        from paddle_tpu.ops.norm_kernels import layer_norm_values

        x = jnp.zeros((BENCH_ROWS, BENCH_HIDDEN), jnp.bfloat16)
        w = jnp.ones((BENCH_HIDDEN,), jnp.bfloat16)
        b = jnp.zeros((BENCH_HIDDEN,), jnp.bfloat16)

        def loss(x, w, b):
            return layer_norm_values(x, w, b).astype(jnp.float32).sum()

        _lower(layer_norm_values, x, w, b)
        _lower(jax.grad(loss, argnums=(0, 1, 2)), x, w, b)


class TestFlashLowering:
    def _qkv(self):
        q = jnp.zeros((BENCH_B, BENCH_S, BENCH_H, BENCH_D), jnp.bfloat16)
        k = jnp.zeros((BENCH_B, BENCH_S, BENCH_HK, BENCH_D), jnp.bfloat16)
        return q, k, k

    @pytest.mark.parametrize("kw", [dict(causal=False), dict(causal=True),
                                    dict(causal=True, window_size=512)])
    def test_fwd_bwd(self, kw):
        from paddle_tpu.ops.flash_attention import flash_attention_values

        q, k, v = self._qkv()
        _lower(lambda q, k, v: flash_attention_values(q, k, v, **kw),
               q, k, v)

        def loss(q, k, v):
            return flash_attention_values(
                q, k, v, **kw).astype(jnp.float32).sum()

        _lower(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


class TestVarlenLowering:
    def test_fwd_bwd_packed(self):
        from paddle_tpu.ops.flash_varlen import (
            flash_attention_varlen_values)

        q = jnp.zeros((BENCH_B, BENCH_S, BENCH_H, BENCH_D), jnp.bfloat16)
        k = jnp.zeros((BENCH_B, BENCH_S, BENCH_HK, BENCH_D), jnp.bfloat16)
        seg = jnp.zeros((BENCH_B, BENCH_S), jnp.int32)

        def loss(q, k, v):
            return flash_attention_varlen_values(
                q, k, v, seg, seg, causal=True).astype(jnp.float32).sum()

        _lower(lambda q, k, v: flash_attention_varlen_values(
            q, k, v, seg, seg, causal=True), q, k, k)
        _lower(jax.grad(loss, argnums=(0, 1, 2)), q, k, k)


class TestRopeLowering:
    def test_fwd_bwd(self):
        from paddle_tpu.ops.rope import rope_values

        x = jnp.zeros((BENCH_B, BENCH_S, BENCH_H, BENCH_D), jnp.bfloat16)
        cos = jnp.zeros((BENCH_S, BENCH_D // 2), jnp.float32)
        sin = jnp.zeros((BENCH_S, BENCH_D // 2), jnp.float32)
        _lower(rope_values, x, cos, sin)

        def loss(x):
            return rope_values(x, cos, sin).astype(jnp.float32).sum()

        _lower(jax.grad(loss), x)


class TestPagedAttentionLowering:
    @pytest.mark.parametrize("window", [None, 64])
    def test_decode(self, window):
        from paddle_tpu.ops.paged_attention import paged_attention_values

        b, pages, page_size = 8, 64, 16
        q = jnp.zeros((b, BENCH_H, BENCH_D), jnp.bfloat16)
        kp = jnp.zeros((BENCH_HK, pages, page_size, BENCH_D), jnp.bfloat16)
        ctx = jnp.full((b,), 100, jnp.int32)
        bt = jnp.zeros((b, 8), jnp.int32)
        _lower(lambda q, kp, vp: paged_attention_values(
            q, kp, vp, ctx, bt, window=window), q, kp, kp)


class TestRaggedPagedAttentionLowering:
    """ISSUE 6: the mixed prefill+decode grid — (block_q*G, D) q tiles,
    scalar-prefetched descriptors, trash-page index_map routing — must
    survive the Mosaic pass at bench shapes, windowed and not, and at
    the decode form (block_q=1)."""

    @pytest.mark.parametrize("window", [None, 256])
    def test_mixed_batch(self, window):
        from paddle_tpu.ops.ragged_paged_attention import (
            pack_ragged_starts, ragged_paged_attention_values)

        pages, page_size = 512, 16
        ql = np.array([512, 512, 1, 1, 1, 1], np.int32)
        cl = np.array([512, 512, 900, 800, 700, 600], np.int32)
        qs, total = pack_ragged_starts(ql, block_q=8)
        q = jnp.zeros((total, BENCH_H, BENCH_D), jnp.bfloat16)
        kp = jnp.zeros((BENCH_HK, pages, page_size, BENCH_D),
                       jnp.bfloat16)
        bt = jnp.zeros((len(ql), 64), jnp.int32)
        _lower(lambda q, kp, vp: ragged_paged_attention_values(
            q, kp, vp, qs, ql, cl, bt, window=window, block_q=8),
            q, kp, kp)

    def test_decode_block_q1(self):
        from paddle_tpu.ops.ragged_paged_attention import \
            ragged_paged_attention_values

        b, pages, page_size = 8, 64, 16
        qs = np.arange(b, dtype=np.int32)
        ql = np.ones(b, np.int32)
        cl = np.full(b, 100, np.int32)
        q = jnp.zeros((b, BENCH_H, BENCH_D), jnp.bfloat16)
        kp = jnp.zeros((BENCH_HK, pages, page_size, BENCH_D),
                       jnp.bfloat16)
        bt = jnp.zeros((b, 8), jnp.int32)
        _lower(lambda q, kp, vp: ragged_paged_attention_values(
            q, kp, vp, qs, ql, cl, bt, block_q=1), q, kp, kp)

    @pytest.mark.parametrize("block_q", [8, 1])
    def test_quantized_pages(self, block_q):
        """ISSUE 15: int8 page pools + (P, 1, page_size) scale blocks
        (the dequant-in-flight inputs) must survive the Mosaic pass at
        bench shapes, mixed and decode forms."""
        from paddle_tpu.ops.ragged_paged_attention import (
            pack_ragged_starts, ragged_paged_attention_values)

        pages, page_size = 256, 16
        if block_q == 8:
            ql = np.array([512, 512, 1, 1], np.int32)
            cl = np.array([512, 512, 900, 800], np.int32)
        else:
            ql = np.ones(4, np.int32)
            cl = np.array([100, 90, 80, 70], np.int32)
        qs, total = pack_ragged_starts(ql, block_q=block_q)
        q = jnp.zeros((total, BENCH_H, BENCH_D), jnp.bfloat16)
        kp = jnp.zeros((BENCH_HK, pages, page_size, BENCH_D), jnp.int8)
        ks = jnp.zeros((pages, page_size), jnp.float32)
        bt = jnp.zeros((len(ql), 64), jnp.int32)
        _lower(lambda q, kp, vp, ks, vs: ragged_paged_attention_values(
            q, kp, vp, qs, ql, cl, bt, block_q=block_q,
            k_scale=ks, v_scale=vs), q, kp, kp, ks, ks)


class TestQuantMatmulLowering:
    """ISSUE 15: the fused dequant-matmul epilogue — int8 weight tiles
    widened in VMEM, per-out-channel scale applied to the f32
    accumulator on the last K step — at decode (M=8) and prefill
    (M=1024) shapes."""

    @pytest.mark.parametrize("m", [8, 1024])
    def test_int8_epilogue(self, m):
        from paddle_tpu.ops.quant_matmul import (dequant_matmul_values,
                                                 quantize_weight_values)
        k, n = 1024, 4096
        qw, sc = quantize_weight_values(jnp.zeros((k, n)), "int8")
        x = jnp.zeros((m, k), jnp.bfloat16)
        _lower(lambda x, qw, sc: dequant_matmul_values(x, qw, sc),
               x, qw, sc)


class TestGroupedMatmulLowering:
    def test_grouped(self):
        from paddle_tpu.ops.grouped_matmul import grouped_matmul_values

        e, n = 8, 2048
        x = jnp.zeros((n, BENCH_HIDDEN), jnp.bfloat16)
        w = jnp.zeros((e, BENCH_HIDDEN, BENCH_HIDDEN), jnp.bfloat16)
        sizes = jnp.full((e,), n // e, jnp.int32)
        _lower(lambda x, w: grouped_matmul_values(x, w, sizes), x, w)
