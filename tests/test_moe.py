"""MoE + expert parallelism tests (8-virtual-device CPU mesh).
≙ reference incubate MoE tests + collective EP tests (SURVEY.md §4)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.incubate.moe import (MoELayer, moe_ffn_values,
                                     moe_gating_values, shard_moe)

rng = np.random.default_rng(3)


class TestGating:
    def test_topk_dispatch_within_capacity(self):
        # 4 tokens, 4 experts, each token strongly prefers its own expert
        logits = jnp.asarray(np.eye(4, dtype=np.float32) * 10)
        d, c, aux = moe_gating_values(logits, top_k=1, capacity=1)
        d = np.asarray(d)
        for t in range(4):
            assert d[t, t, 0] == 1.0
        # combine weights are the softmax gate values
        cw = np.asarray(c)
        assert (cw[np.arange(4), np.arange(4), 0] > 0.9).all()

    def test_capacity_drops_overflow(self):
        # all 4 tokens want expert 0, capacity 2 -> 2 dropped
        logits = jnp.asarray(np.tile([10.0, 0, 0, 0], (4, 1))
                             .astype(np.float32))
        d, c, aux = moe_gating_values(logits, top_k=1, capacity=2)
        d = np.asarray(d)
        assert d[:, 0].sum() == 2.0         # only 2 tokens placed
        assert d[:2, 0].sum() == 2.0        # priority order: first tokens

    def test_top2_second_choice_lower_priority(self):
        logits = jnp.asarray(np.array(
            [[10.0, 5.0, 0, 0], [10.0, 5.0, 0, 0]], np.float32))
        d, c, aux = moe_gating_values(logits, top_k=2, capacity=2)
        d = np.asarray(d)
        # both tokens land in expert 0 (1st choice) and expert 1 (2nd)
        assert d[:, 0].sum() == 2.0 and d[:, 1].sum() == 2.0

    def test_aux_loss_uniform_is_one(self):
        # uniform router -> aux == 1 (its minimum for balanced routing)
        t, e = 64, 8
        logits = jnp.zeros((t, e), jnp.float32)
        _, _, aux = moe_gating_values(logits, top_k=2, capacity=16)
        assert float(aux) == pytest.approx(1.0, rel=1e-5)


class TestMoELayer:
    def test_forward_backward(self):
        paddle.seed(0)
        layer = MoELayer(32, 64, num_experts=4, top_k=2,
                         shared_intermediate_size=16)
        x = paddle.to_tensor(rng.normal(size=(2, 8, 32)).astype(np.float32),
                             stop_gradient=False)
        out, aux = layer(x)
        assert out.shape == [2, 8, 32]
        loss = (out.astype("float32") ** 2).sum() + aux * 0.01
        loss.backward()
        for p in layer.parameters():
            assert p.grad is not None, p.name
            assert np.isfinite(p.grad.numpy()).all()

    def test_single_expert_matches_dense_ffn(self):
        """E=1, top_k=1, ample capacity: MoE == plain SwiGLU FFN."""
        paddle.seed(1)
        h, i = 16, 32
        layer = MoELayer(h, i, num_experts=1, top_k=1, capacity_factor=2.0)
        x = rng.normal(size=(12, h)).astype(np.float32)
        out, _ = layer(paddle.to_tensor(x))
        wg = layer.w_gate.numpy()[0]
        wu = layer.w_up.numpy()[0]
        wd = layer.w_down.numpy()[0]
        silu = lambda v: v / (1 + np.exp(-v))
        want = (silu(x @ wg) * (x @ wu)) @ wd
        np.testing.assert_allclose(out.numpy(), want, rtol=2e-4, atol=2e-4)


class TestExpertParallel:
    def test_ep_sharded_training_step(self):
        """MoE model trains on a dp×ep mesh; loss decreases."""
        from paddle_tpu.models.moe import (MoEConfig, MoEForCausalLM,
                                           shard_moe_model,
                                           synthetic_lm_batch)
        from paddle_tpu.optimizer import AdamW

        mesh = dist.create_mesh(dp=2, ep=4)
        paddle.seed(0)
        cfg = MoEConfig.tiny()
        model = MoEForCausalLM(cfg)
        with dist.use_mesh(mesh):
            shard_moe_model(model, mesh)
            opt = AdamW(learning_rate=1e-3,
                        parameters=model.parameters())
            ids, labels = synthetic_lm_batch(4, 32, cfg.vocab_size)
            pl = [dist.Shard(0), dist.Replicate()]
            ids = dist.shard_tensor(ids, mesh, pl)
            labels = dist.shard_tensor(labels, mesh, pl)
            step = paddle.jit.TrainStep(
                model, opt, loss_fn=lambda m, x, y: m(x, labels=y)[0])
            losses = [float(step(ids, labels)) for _ in range(4)]
        assert losses[-1] < losses[0], losses
        assert np.isfinite(losses).all()

    def test_expert_params_sharded(self):
        mesh = dist.create_mesh(ep=4)
        paddle.seed(0)
        layer = MoELayer(16, 32, num_experts=8, top_k=2)
        shard_moe(layer, mesh)
        sh = layer.w_gate._value.sharding
        spec = sh.spec
        assert spec[0] == "ep", spec
